"""Block-paged KV cache + radix prefix sharing + chunked prefill
(ISSUE 11): greedy bit-equivalence against the slot-cache engine AND
sequential ``models.generate``, page-pool accounting, copy-on-write,
victim-only exhaustion (real and injected), mid-prefill deadline shedding,
and the page-watermark admission gate — all on CPU.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import generate
from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
from paddle_tpu.resilience.inject import FaultSchedule
from paddle_tpu.serving import (
    AdmissionRejected,
    ContinuousBatchingEngine,
    PagePool,
    PagesExhaustedError,
    RadixCache,
    Request,
)
from paddle_tpu.serving.admission import DEADLINE_ERROR_TYPE
from paddle_tpu.serving.paged import TRASH_PAGE

VOCAB = 64


def _tiny_model():
    paddle.seed(0)
    cfg = gpt_config("gpt2-small", vocab_size=VOCAB, hidden_size=32,
                     num_layers=2, num_attention_heads=4,
                     max_position_embeddings=64, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    m = GPTForPretraining(cfg)
    m.eval()
    return m


def _sequential(model, prompt, n, eos=None):
    out = generate(model, paddle.to_tensor(np.asarray(prompt)[None]),
                   max_new_tokens=n, eos_token_id=eos)
    return np.asarray(out._data)[0]


@pytest.fixture(scope="module")
def model():
    return _tiny_model()


# =====================================================================
# host-side pool + radix tree
# =====================================================================
class TestPagePool:
    def test_trash_page_reserved(self):
        pool = PagePool(4)
        pages = pool.alloc(3)
        assert TRASH_PAGE not in pages
        assert sorted(pages) == [1, 2, 3]
        assert pool.free_count() == 0

    def test_refcount_lifecycle(self):
        pool = PagePool(4)
        (p,) = pool.alloc(1)
        pool.retain([p])
        pool.release([p])
        assert pool.used_count() == 1  # second ref still held
        pool.release([p])
        assert pool.used_count() == 0
        with pytest.raises(ValueError):
            pool.release([p])

    def test_shared_count_and_state(self):
        pool = PagePool(5, page_bytes=128)
        a, b = pool.alloc(2)
        pool.retain([a])
        st = pool.state()
        assert st == {"capacity": 4, "free": 2, "used": 2, "shared": 1,
                      "page_bytes": 128}

    def test_exhaustion_raises_typed(self):
        pool = PagePool(3)
        pool.alloc(2)
        with pytest.raises(PagesExhaustedError, match="exhausted"):
            pool.alloc(1)

    def test_alloc_calls_evictor_then_retries(self):
        pool = PagePool(3)
        held = pool.alloc(2)

        def evict(n):
            pool.release(held[:n])

        got = pool.alloc(1, evict=evict)
        assert len(got) == 1

    def test_fifo_reuse_is_deterministic(self):
        pool = PagePool(4)
        a = pool.alloc(3)
        pool.release(a)
        assert pool.alloc(3) == a  # FIFO: same order back


class TestRadixCache:
    def _tree(self, n_pages=16, ps=4):
        pool = PagePool(n_pages)
        return pool, RadixCache(pool, ps)

    def test_match_full_pages_only(self):
        pool, tree = self._tree()
        toks = np.arange(10)  # 2 full pages + 2 remainder @ ps=4
        pages = pool.alloc(2)
        tree.insert(toks, pages)
        got = tree.match(toks)
        assert got == pages            # remainder page never shared
        assert tree.peek(toks[:9]) == 2
        assert tree.peek(toks[:7]) == 1
        # divergence INSIDE a page keeps that page private
        div = np.array(list(toks[:7]) + [63])
        assert tree.peek(div) == 1

    def test_match_retains_insert_holds_tree_ref(self):
        pool, tree = self._tree()
        pages = pool.alloc(1)
        tree.insert(np.arange(4), pages)      # tree ref: refs == 2
        assert pool.refcount(pages[0]) == 2
        got = tree.match(np.arange(4))
        assert got == pages and pool.refcount(pages[0]) == 3
        pool.release(got)                      # request done
        pool.release(pages)                    # prefiller done
        assert pool.refcount(pages[0]) == 1    # the tree keeps it resident

    def test_evict_lru_leaves_only_unpinned(self):
        pool, tree = self._tree(n_pages=8)
        a = pool.alloc(1)
        b = pool.alloc(1)
        tree.insert(np.arange(4), a)
        tree.insert(np.arange(4, 8), b)
        pool.release(a)
        pool.release(b)                # only tree refs remain
        tree.match(np.arange(4))       # touch a: b becomes LRU (and pins a)
        freed = tree.evict(1)
        assert freed == 1
        assert pool.refcount(b[0]) == 0
        assert tree.peek(np.arange(4, 8)) == 0
        assert tree.peek(np.arange(4)) == 1

    def test_hit_counters(self):
        pool, tree = self._tree()
        pages = pool.alloc(1)
        tree.insert(np.arange(4), pages)
        tree.match(np.arange(4))
        tree.match(np.arange(32, 36))  # miss
        assert tree.queries == 2 and tree.hits == 1
        assert tree.hit_tokens == 4
        assert tree.hit_rate() == 0.5


# =====================================================================
# bit-equivalence: paged == slot == sequential generate (acceptance)
# =====================================================================
class TestPagedBitEquivalence:
    def test_paged_vs_slot_vs_sequential(self, model):
        """Staggered mixed-length greedy requests through the CHUNKED
        paged engine == the slot-cache engine == sequential generate,
        token for token — including a request that joins via a shared
        prefix and one that exhausts its pages mid-generation (victim
        fails typed; every survivor stays exact)."""
        rng = np.random.default_rng(0)
        base = rng.integers(0, VOCAB, (8,)).astype(np.int32)  # 2 pages @4
        lens = [3, 5, 7, 4, 9, 6]
        prompts = [rng.integers(0, VOCAB, (l,)).astype(np.int32)
                   for l in lens]
        prompts.append(np.concatenate(
            [base, rng.integers(0, VOCAB, (3,)).astype(np.int32)]))
        prompts.append(base.copy())  # joins fully via the shared prefix
        news = [6, 4, 8, 5, 3, 7, 6, 5]
        want = [_sequential(model, p, n) for p, n in zip(prompts, news)]

        def drive(eng):
            first = [eng.submit(Request(p, max_new_tokens=n))
                     for p, n in zip(prompts[:5], news[:5])]
            for _ in range(3):
                eng.step_once()
            second = [eng.submit(Request(p, max_new_tokens=n))
                      for p, n in zip(prompts[5:], news[5:])]
            eng.run_until_idle(timeout=300)
            return first + second

        buckets = [4, 8, 16]
        slot_eng = ContinuousBatchingEngine(
            model, max_seq_len=32, n_slots=4, prefill_buckets=buckets,
            kv_layout="slot")
        paged_eng = ContinuousBatchingEngine(
            model, max_seq_len=32, n_slots=4, prefill_buckets=buckets,
            page_size=4, prefill_chunk=8)
        for eng in (slot_eng, paged_eng):
            got = drive(eng)
            for req, w in zip(got, want):
                assert req.state == Request.DONE, (req.state, req.error)
                np.testing.assert_array_equal(req.result(), w)
        # compile cache: <= len(chunk_buckets) prefill programs + 1 step,
        # counted by the in-trace counter (acceptance criterion)
        assert paged_eng.trace_count <= len(paged_eng.chunk_buckets) + 1
        assert paged_eng.trace_counts["step"] == 1
        # prefix sharing engaged for the shared-prefix joiners
        st = paged_eng.page_state()
        assert st["prefix_hits"] >= 1
        assert st["prefix_hit_tokens"] >= 8

    def test_exhaustion_mid_generation_fails_only_victim(self, model):
        """A pool too small for every stream's decode growth: the starved
        slot fails typed (pages released), survivors decode on and stay
        exact vs sequential generate."""
        rng = np.random.default_rng(2)
        prompts = [rng.integers(0, VOCAB, (6,)).astype(np.int32)
                   for _ in range(3)]
        want = [_sequential(model, p, 14) for p in prompts]
        eng = ContinuousBatchingEngine(
            model, max_seq_len=32, n_slots=3, prefill_buckets=[8],
            page_size=4, n_pages=1 + 9, prefix_sharing=False)
        reqs = [eng.submit(Request(p, max_new_tokens=14)) for p in prompts]
        eng.run_until_idle(timeout=300)
        done = [i for i, r in enumerate(reqs) if r.state == Request.DONE]
        failed = [r for r in reqs if r.state == Request.FAILED]
        assert done and failed  # over-committed: someone starved
        for r in failed:
            assert r.error_type == PagesExhaustedError.error_type
            assert "page pool exhausted" in r.error
        for i in done:
            np.testing.assert_array_equal(reqs[i].result(), want[i])
        # every victim's refcounted pages came back
        assert eng.page_state()["used"] == 0

    def test_cow_whole_prompt_match_exact(self, model):
        """A prompt fully resident in the radix tree recomputes only its
        final token into a copy-on-write page — and still decodes
        exactly."""
        rng = np.random.default_rng(3)
        prompt = rng.integers(0, VOCAB, (8,)).astype(np.int32)  # 2 pages
        want = _sequential(model, prompt, 5)
        eng = ContinuousBatchingEngine(
            model, max_seq_len=32, n_slots=2, prefill_buckets=[4, 8],
            page_size=4)
        a = eng.submit(Request(prompt, max_new_tokens=5))
        eng.run_until_idle(timeout=300)
        b = eng.submit(Request(prompt, max_new_tokens=5))
        eng.run_until_idle(timeout=300)
        np.testing.assert_array_equal(a.result(), want)
        np.testing.assert_array_equal(b.result(), want)
        assert eng.cow_pages == 1
        assert eng.page_state()["cow_pages"] == 1
        snap = eng.metrics.snapshot()
        assert snap["kv_pages"]["cow_pages"] == 1
        assert snap["kv_pages"]["prefix_hit_rate"] == 0.5

    @pytest.mark.pallas
    def test_pallas_arm_staggered_cow_matches_gather_and_sequential(
            self, model):
        """ISSUE 16 acceptance: the paged flash-decode kernel arm
        (attn_impl='pallas', interpret mode on CPU) greedy output is
        token-for-token equal to the XLA-gather arm AND sequential
        generate over staggered mixed-length requests, including the
        shared-prefix COW joiners — same gauntlet as the gather-arm
        test above, with the kernel handling both chunked prefill
        (T > 1) and decode (T = 1) blocks."""
        rng = np.random.default_rng(0)
        base = rng.integers(0, VOCAB, (8,)).astype(np.int32)
        lens = [3, 5, 7, 4, 9, 6]
        prompts = [rng.integers(0, VOCAB, (l,)).astype(np.int32)
                   for l in lens]
        prompts.append(np.concatenate(
            [base, rng.integers(0, VOCAB, (3,)).astype(np.int32)]))
        prompts.append(base.copy())  # joins fully via the shared prefix
        news = [6, 4, 8, 5, 3, 7, 6, 5]
        want = [_sequential(model, p, n) for p, n in zip(prompts, news)]

        def drive(eng):
            first = [eng.submit(Request(p, max_new_tokens=n))
                     for p, n in zip(prompts[:5], news[:5])]
            for _ in range(3):
                eng.step_once()
            second = [eng.submit(Request(p, max_new_tokens=n))
                      for p, n in zip(prompts[5:], news[5:])]
            eng.run_until_idle(timeout=300)
            return first + second

        results = {}
        for impl in ("xla", "pallas"):
            eng = ContinuousBatchingEngine(
                model, max_seq_len=32, n_slots=4,
                prefill_buckets=[4, 8, 16], page_size=4, prefill_chunk=8,
                attn_impl=impl)
            got = drive(eng)
            for req, w in zip(got, want):
                assert req.state == Request.DONE, \
                    (impl, req.state, req.error)
                np.testing.assert_array_equal(req.result(), w)
            results[impl] = [req.result() for req in got]
            if impl == "pallas":  # COW joiners engaged under the kernel
                st = eng.page_state()
                assert st["prefix_hits"] >= 1
                assert st["prefix_hit_tokens"] >= 8
        for a, b in zip(results["xla"], results["pallas"]):
            np.testing.assert_array_equal(a, b)

    @pytest.mark.pallas
    def test_pallas_arm_exhaustion_fails_only_victim(self, model):
        """Mid-generation page exhaustion under the kernel arm: victim
        fails typed, survivors stay exact vs sequential generate."""
        rng = np.random.default_rng(2)
        prompts = [rng.integers(0, VOCAB, (6,)).astype(np.int32)
                   for _ in range(3)]
        want = [_sequential(model, p, 14) for p in prompts]
        eng = ContinuousBatchingEngine(
            model, max_seq_len=32, n_slots=3, prefill_buckets=[8],
            page_size=4, n_pages=1 + 9, prefix_sharing=False,
            attn_impl="pallas")
        reqs = [eng.submit(Request(p, max_new_tokens=14)) for p in prompts]
        eng.run_until_idle(timeout=300)
        done = [i for i, r in enumerate(reqs) if r.state == Request.DONE]
        failed = [r for r in reqs if r.state == Request.FAILED]
        assert done and failed
        for r in failed:
            assert r.error_type == PagesExhaustedError.error_type
        for i in done:
            np.testing.assert_array_equal(reqs[i].result(), want[i])
        assert eng.page_state()["used"] == 0

    def test_pallas_requires_paged_layout(self, model):
        with pytest.raises(ValueError, match="paged"):
            ContinuousBatchingEngine(model, max_seq_len=32, n_slots=2,
                                     kv_layout="slot", attn_impl="pallas")
        with pytest.raises(ValueError, match="attn_impl"):
            ContinuousBatchingEngine(model, max_seq_len=32, n_slots=2,
                                     attn_impl="cuda")

    def test_slot_flag_still_available(self, model):
        """The old slot cache stays reachable behind kv_layout='slot' (the
        bit-comparison fallback)."""
        eng = ContinuousBatchingEngine(model, max_seq_len=16, n_slots=1,
                                       prefill_buckets=[8],
                                       kv_layout="slot")
        assert eng.kv_layout == "slot"
        assert eng.page_state() == {}
        assert eng.kv_bytes_per_stream() is None
        p = np.arange(1, 5, dtype=np.int32)
        req = eng.submit(Request(p, max_new_tokens=3))
        eng.run_until_idle(timeout=120)
        np.testing.assert_array_equal(req.result(), _sequential(model, p, 3))


# =====================================================================
# chunked prefill: interleaving + mid-prefill deadline (satellites)
# =====================================================================
class TestChunkedPrefill:
    def test_long_prompt_exceeding_largest_bucket(self, model):
        """Chunked prefill admits prompts LONGER than the largest prefill
        bucket (the whole point of chunking) and stays exact."""
        rng = np.random.default_rng(4)
        prompt = rng.integers(0, VOCAB, (24,)).astype(np.int32)
        want = _sequential(model, prompt, 4)
        eng = ContinuousBatchingEngine(
            model, max_seq_len=40, n_slots=2, prefill_buckets=[4, 8],
            page_size=4, prefill_chunk=8)
        req = eng.submit(Request(prompt, max_new_tokens=4))
        eng.run_until_idle(timeout=300)
        np.testing.assert_array_equal(req.result(), want)
        assert eng.trace_count <= len(eng.chunk_buckets) + 1

    def test_decode_interleaves_with_chunks(self, model):
        """A long prompt's prefill no longer stalls in-flight streams:
        between its chunks, active slots keep emitting one token per tick
        (tick-deterministic — the head-of-line TTFT fix)."""
        rng = np.random.default_rng(5)
        short = rng.integers(0, VOCAB, (3,)).astype(np.int32)
        long_p = rng.integers(0, VOCAB, (16,)).astype(np.int32)
        eng = ContinuousBatchingEngine(
            model, max_seq_len=32, n_slots=2, prefill_buckets=[4],
            page_size=4, prefill_chunk=4, max_prefills_per_tick=1)
        a = eng.submit(Request(short, max_new_tokens=10))
        eng.step_once()  # admit + prefill + first decode
        assert len(a.tokens) >= 1
        b = eng.submit(Request(long_p, max_new_tokens=3))
        grew = []
        for _ in range(3):  # 3 of long's 4 chunks: b must not be done
            before = len(a.tokens)
            eng.step_once()
            grew.append(len(a.tokens) - before)
        assert all(g == 1 for g in grew), grew  # one token per tick
        assert b.tokens == [] and eng._prefill_slots  # still prefilling
        eng.run_until_idle(timeout=300)
        np.testing.assert_array_equal(
            b.result(), _sequential(model, long_p, 3))
        np.testing.assert_array_equal(
            a.result(), _sequential(model, short, 10))

    def test_deadline_expiry_mid_prefill_sheds_typed(self, model):
        """A request admitted pre-chunking can expire mid-prefill: the
        engine re-checks the deadline before each next chunk and sheds
        with the typed 503, pages released, no further prefill burned
        (satellite: scheduler admission re-checks deadline expiry after
        chunked-prefill waits)."""
        rng = np.random.default_rng(6)
        long_p = rng.integers(0, VOCAB, (16,)).astype(np.int32)
        eng = ContinuousBatchingEngine(
            model, max_seq_len=32, n_slots=1, prefill_buckets=[4],
            page_size=4, prefill_chunk=4, prefix_sharing=False)
        req = eng.submit(Request(long_p, max_new_tokens=3, deadline_s=0.05))
        eng.step_once()  # first chunk runs (deadline still valid)
        assert req.state != Request.FAILED
        prefills = eng.metrics.prefill_calls
        shed_before = eng.metrics.requests_shed
        time.sleep(0.08)  # the deadline lapses BETWEEN chunks
        eng.step_once()
        assert req.state == Request.FAILED
        assert req.error_type == DEADLINE_ERROR_TYPE
        assert "mid-prefill" in req.error
        assert eng.metrics.prefill_calls == prefills  # no next chunk
        assert eng.metrics.requests_shed == shed_before + 1
        assert eng.page_state()["used"] == 0          # pages released
        assert not eng._prefill_slots
        # the freed slot is immediately usable
        ok = eng.submit(Request(long_p[:3], max_new_tokens=2))
        eng.run_until_idle(timeout=120)
        assert ok.state == Request.DONE


# =====================================================================
# injected exhaustion twin (r13 inject plane satellite)
# =====================================================================
class TestInjectedExhaustion:
    def _run(self, model, prompts):
        eng = ContinuousBatchingEngine(
            model, max_seq_len=32, n_slots=3, prefill_buckets=[8],
            page_size=4, prefix_sharing=False)
        sched = FaultSchedule(seed=7).add(
            "serving.pages.exhausted", "raise", at=5,
            exception=PagesExhaustedError)
        with sched:
            reqs = [eng.submit(Request(p, max_new_tokens=14))
                    for p in prompts]
            eng.run_until_idle(timeout=300)
        return ([(r.state, tuple(r.tokens)) for r in reqs],
                sched.fired_log(), eng.page_state()["used"])

    def test_victim_only_and_bit_identical_replay(self, model):
        """A seeded fault at page-allocation exhaustion fails ONLY the
        victim request, releases its refcounted pages, and the whole run
        replays bit-identically (transcripts AND fired logs equal)."""
        rng = np.random.default_rng(7)
        prompts = [rng.integers(0, VOCAB, (6,)).astype(np.int32)
                   for _ in range(3)]
        a, fired_a, used_a = self._run(model, prompts)
        b, fired_b, used_b = self._run(model, prompts)
        assert a == b
        assert fired_a == fired_b
        assert fired_a[0]["point"] == "serving.pages.exhausted"
        states = [s for s, _ in a]
        assert states.count(Request.FAILED) == 1  # ONLY the victim
        assert states.count(Request.DONE) == 2
        assert used_a == used_b == 0              # victim pages released


# =====================================================================
# page-watermark admission gate (tentpole: AdmissionGate over pages)
# =====================================================================
class TestPageWatermarkGate:
    def test_refusal_cites_pages(self, model):
        """The 429 body cites the predicted page-pool watermark
        (predicted/free/budget) — pages are the allocation unit, so
        predicted-resident tracks true occupancy (acceptance)."""
        eng = ContinuousBatchingEngine(
            model, max_seq_len=32, n_slots=2, prefill_buckets=[8],
            page_size=4, n_pages=1 + 4, prefix_sharing=False,
            hbm_budget_bytes=1 << 30)
        # needs ceil((6+6)/4) = 3 pages; budget is 4: first fits,
        # second's predicted watermark 3+3=6 > 4 while still queued
        p = np.arange(1, 7, dtype=np.int32)
        eng.submit(Request(p, max_new_tokens=6))
        with pytest.raises(AdmissionRejected) as ei:
            eng.submit(Request(p, max_new_tokens=6))
        pages = ei.value.estimate["pages"]
        assert pages["predicted"] == 6
        assert pages["budget"] == 4
        assert pages["needed"] == 3
        assert pages["committed_queued"] == 3
        assert "page-pool watermark" in str(ei.value)
        assert "free" in pages and pages["page_bytes"] > 0

    def test_commit_settles_at_allocation(self, model):
        eng = ContinuousBatchingEngine(
            model, max_seq_len=32, n_slots=2, prefill_buckets=[8],
            page_size=4, n_pages=1 + 8, hbm_budget_bytes=1 << 30)
        gate = eng.admission_gate
        p = np.arange(1, 7, dtype=np.int32)
        req = eng.submit(Request(p, max_new_tokens=6))
        assert gate._committed_pages == 3
        eng.step_once()  # allocates real pages; the reservation settles
        assert gate._committed_pages == 0
        wm = gate.page_watermark()
        assert wm["used"] >= 1 and wm["committed_queued"] == 0
        eng.run_until_idle(timeout=120)
        assert req.state == Request.DONE

    def test_shed_and_failed_requests_settle(self, model):
        eng = ContinuousBatchingEngine(
            model, max_seq_len=32, n_slots=1, prefill_buckets=[8],
            page_size=4, hbm_budget_bytes=1 << 30)
        gate = eng.admission_gate
        blocker = eng.submit(Request(np.arange(1, 5, dtype=np.int32),
                                     max_new_tokens=8))
        doomed = eng.submit(Request(np.arange(1, 5, dtype=np.int32),
                                    max_new_tokens=4, deadline_s=0.01))
        assert gate._committed_pages > 0
        time.sleep(0.03)
        while not doomed.done:
            eng.step_once()
        assert doomed.error_type == DEADLINE_ERROR_TYPE
        eng.run_until_idle(timeout=120)
        assert blocker.state == Request.DONE
        assert gate._committed_pages == 0

    def test_watermark_admits_after_sharing(self, model):
        """pages_needed is net of resident shared prefixes: a request the
        pool could never fit cold IS admissible once its prefix is
        resident — predicted-resident tracks true occupancy."""
        rng = np.random.default_rng(8)
        prompt = rng.integers(0, VOCAB, (12,)).astype(np.int32)  # 3 pages
        eng = ContinuousBatchingEngine(
            model, max_seq_len=32, n_slots=2, prefill_buckets=[4, 8, 16],
            page_size=4)
        cold = eng.pages_needed(Request(prompt, max_new_tokens=4))
        a = eng.submit(Request(prompt, max_new_tokens=4))
        eng.run_until_idle(timeout=120)
        assert a.state == Request.DONE
        warm = eng.pages_needed(Request(prompt, max_new_tokens=4))
        assert warm < cold  # the radix-resident prefix is free


# =====================================================================
# gauges + per-stream HBM accounting
# =====================================================================
class TestPagedMetrics:
    def test_page_gauges_and_prometheus_series(self, model):
        eng = ContinuousBatchingEngine(
            model, max_seq_len=32, n_slots=2, prefill_buckets=[8],
            page_size=4)
        reqs = [eng.submit(Request(np.arange(1, 6, dtype=np.int32),
                                   max_new_tokens=4)) for _ in range(2)]
        eng.run_until_idle(timeout=120)
        assert all(r.state == Request.DONE for r in reqs)
        snap = eng.metrics.snapshot()
        kv = snap["kv_pages"]
        assert kv["capacity"] == eng.n_pages - 1
        assert kv["free"] + kv["used"] == kv["capacity"]
        assert kv["page_bytes"] == eng.page_bytes
        text = eng.metrics.prometheus_text()
        for series in ("serving_kv_pages_free", "serving_kv_pages_used",
                       "serving_kv_pages_shared",
                       "serving_prefix_hits_total",
                       "serving_cow_pages_total"):
            assert series in text

    def test_kv_hbm_per_stream_bounded_by_live_pages(self, model):
        """Acceptance: per-stream KV HBM <= (live pages x page bytes) +
        one page of slack — the paged win over the slot layout's fixed
        2·L·H·S·D per stream."""
        eng = ContinuousBatchingEngine(
            model, max_seq_len=32, n_slots=2, prefill_buckets=[8],
            page_size=4, prefix_sharing=False)
        reqs = [eng.submit(Request(np.arange(1, 6, dtype=np.int32),
                                   max_new_tokens=8)) for _ in range(2)]
        eng.step_once()
        assert eng.active_slots() == 2
        per_stream = eng.kv_bytes_per_stream()
        live_pages_per_stream = max(
            len(getattr(r, "_pages", [])) for r in reqs)
        bound = live_pages_per_stream * eng.page_bytes + eng.page_bytes
        assert per_stream is not None and per_stream <= bound
        # and strictly below the slot layout's worst-case share
        slot_share = eng.max_pages_per_slot * eng.page_bytes
        assert per_stream < slot_share
        eng.run_until_idle(timeout=120)
