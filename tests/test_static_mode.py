"""Static-graph paradigm tests.

Parity model: reference unittests (test_executor_and_use_program_cache,
book/test_fit_a_line, test_program_guard, interpreter/ standalone-executor
equivalence — here static-vs-dygraph equivalence plays that role).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static


@pytest.fixture(autouse=True)
def _static_mode_guard():
    static.program._reset_default_programs() if hasattr(static.program, "_reset_default_programs") else None
    yield
    paddle.disable_static()


def _fresh_program():
    return static.Program(), static.Program()


def test_forward_only():
    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        y = x * 2.0 + 1.0
    exe = static.Executor()
    x_np = np.random.rand(3, 4).astype("float32")
    (out,) = exe.run(main, feed={"x": x_np}, fetch_list=[y])
    np.testing.assert_allclose(out, x_np * 2 + 1, rtol=1e-6)


def test_linear_regression_training_converges():
    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 2], "float32")
        t = static.data("t", [None, 1], "float32")
        lin = paddle.nn.Linear(2, 1)
        pred = lin(x)
        loss = ((pred - t) ** 2).mean()
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
        opt.minimize(loss)

    exe = static.Executor()
    rng = np.random.RandomState(0)
    w_true = np.array([[2.0], [-3.0]], "float32")
    losses = []
    for _ in range(500):
        x_np = rng.rand(16, 2).astype("float32")
        t_np = x_np @ w_true + 0.5
        (l,) = exe.run(main, feed={"x": x_np, "t": t_np}, fetch_list=[loss])
        losses.append(float(l))
    assert losses[-1] < 5e-3, f"did not converge: {losses[-1]}"
    np.testing.assert_allclose(lin.weight.numpy(), w_true, atol=0.05)


def test_static_matches_dygraph_forward():
    # same parameters, same input -> identical result in both paradigms
    x_np = np.random.rand(4, 8).astype("float32")
    lin = paddle.nn.Linear(8, 3)
    eager_out = lin(paddle.to_tensor(x_np)).numpy()

    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 8], "float32")
        out = lin(x)
    exe = static.Executor()
    (static_out,) = exe.run(main, feed={"x": x_np}, fetch_list=[out])
    np.testing.assert_allclose(static_out, eager_out, rtol=1e-5)


def test_append_backward_grad_fetch():
    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 3], "float32")
        lin = paddle.nn.Linear(3, 1, bias_attr=False)
        loss = lin(x).sum()
        pairs = static.append_backward(loss)
    assert len(pairs) == 1
    exe = static.Executor()
    x_np = np.ones((5, 3), "float32")
    (g,) = exe.run(main, feed={"x": x_np}, fetch_list=[pairs[0][1]])
    # dloss/dW = sum over batch of x -> 5.0 each
    np.testing.assert_allclose(g, np.full((3, 1), 5.0), rtol=1e-6)


def test_gradients_wrt_feed():
    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 3], "float32")
        loss = (x * x).sum()
        (gx,) = static.gradients(loss, [x])
    exe = static.Executor()
    x_np = np.array([[1.0, 2.0, 3.0]], "float32")
    (g,) = exe.run(main, feed={"x": x_np}, fetch_list=[gx])
    np.testing.assert_allclose(g, 2 * x_np, rtol=1e-6)


def test_static_dropout_varies_per_run():
    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 64], "float32")
        y = paddle.nn.functional.dropout(x, p=0.5, training=True)
    exe = static.Executor()
    x_np = np.ones((2, 64), "float32")
    (a,) = exe.run(main, feed={"x": x_np}, fetch_list=[y])
    (b,) = exe.run(main, feed={"x": x_np}, fetch_list=[y])
    assert not np.allclose(a, b), "dropout mask must differ between runs"
    # upscale_in_train preserves expectation
    assert 0.5 < a.mean() < 1.5


def test_batchnorm_running_stats_update_in_static():
    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        bn = paddle.nn.BatchNorm1D(4)
        y = bn(x)
    exe = static.Executor()
    before = bn._mean.numpy().copy()
    x_np = np.random.rand(8, 4).astype("float32") + 5.0
    exe.run(main, feed={"x": x_np}, fetch_list=[y])
    after = bn._mean.numpy()
    assert not np.allclose(before, after), "running mean must update"
    expected = 0.9 * before + 0.1 * x_np.mean(0)
    np.testing.assert_allclose(after, expected, rtol=1e-4)


def test_program_guard_isolation():
    paddle.enable_static()
    p1, s1 = _fresh_program()
    p2, s2 = _fresh_program()
    with static.program_guard(p1, s1):
        x1 = static.data("x", [None, 2], "float32")
        _ = x1 + 1.0
    with static.program_guard(p2, s2):
        x2 = static.data("x", [None, 2], "float32")
        _ = x2 * 3.0
    assert len(p1.ops) == 1 and len(p2.ops) == 1


def test_batch_size_change_recompiles_transparently():
    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        y = x.sum()
    exe = static.Executor()
    (a,) = exe.run(main, feed={"x": np.ones((2, 4), "float32")}, fetch_list=[y])
    (b,) = exe.run(main, feed={"x": np.ones((7, 4), "float32")}, fetch_list=[y])
    assert float(a) == 8.0 and float(b) == 28.0


def test_save_load_inference_model(tmp_path):
    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 6], "float32")
        lin = paddle.nn.Linear(6, 2)
        out = lin(x)
    exe = static.Executor()
    x_np = np.random.rand(3, 6).astype("float32")
    (ref,) = exe.run(main, feed={"x": x_np}, fetch_list=[out])

    prefix = str(tmp_path / "infer")
    static.save_inference_model(prefix, [x], [out], exe)
    prog, feed_names, fetch_names = static.load_inference_model(prefix, exe)
    assert feed_names == ["x"]
    (loaded,) = prog.run({"x": x_np})
    np.testing.assert_allclose(loaded, ref, rtol=1e-5)
    # different batch size through the symbolic dim
    (l2,) = prog.run({"x": np.random.rand(5, 6).astype("float32")})
    assert l2.shape == (5, 2)


def test_adam_static_training_mnistish():
    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 16], "float32")
        label = static.data("label", [None], "int64")
        net = paddle.nn.Sequential(
            paddle.nn.Linear(16, 32), paddle.nn.ReLU(), paddle.nn.Linear(32, 4)
        )
        logits = net(x)
        loss = paddle.nn.functional.cross_entropy(logits, label)
        opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
        opt.minimize(loss)
    exe = static.Executor()
    rng = np.random.RandomState(1)
    # learnable toy task: class = argmax of 4 chunks' sums
    losses = []
    for _ in range(150):
        x_np = rng.rand(32, 16).astype("float32")
        y_np = x_np.reshape(32, 4, 4).sum(-1).argmax(-1).astype("int64")
        (l,) = exe.run(main, feed={"x": x_np, "label": y_np}, fetch_list=[loss])
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.5, f"{losses[0]} -> {losses[-1]}"


def test_minimize_without_parameter_list():
    # the standard static idiom: optimizer constructed with no parameters
    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 3], "float32")
        lin = paddle.nn.Linear(3, 1)
        loss = (lin(x) ** 2).mean()
        opt = paddle.optimizer.SGD(learning_rate=0.5)
        opt.minimize(loss)
    exe = static.Executor()
    w0 = lin.weight.numpy().copy()
    exe.run(main, feed={"x": np.ones((4, 3), "float32")}, fetch_list=[loss])
    assert not np.allclose(lin.weight.numpy(), w0), "weights must update"


def test_minimize_with_program_all_parameters():
    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 3], "float32")
        lin = paddle.nn.Linear(3, 1)
        loss = (lin(x) ** 2).mean()
        opt = paddle.optimizer.SGD(learning_rate=0.5)
        opt.minimize(loss, parameters=main.all_parameters())
    exe = static.Executor()
    (l,) = exe.run(main, feed={"x": np.ones((4, 3), "float32")}, fetch_list=[loss])
    assert np.isfinite(l)


def test_clone_for_test_disables_dropout_and_bn_updates():
    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 8], "float32")
        bn = paddle.nn.BatchNorm1D(8)
        h = bn(x)
        y = paddle.nn.functional.dropout(h, p=0.5, training=True)
    test_prog = main.clone(for_test=True)
    exe = static.Executor()
    x_np = np.random.rand(16, 8).astype("float32") + 3.0
    mean_before = bn._mean.numpy().copy()
    (out,) = exe.run(test_prog, feed={"x": x_np}, fetch_list=[y])
    # dropout off: nothing zeroed; bn in inference mode: stats untouched
    assert (out != 0).all()
    np.testing.assert_allclose(bn._mean.numpy(), mean_before)
    # inference bn uses running stats (zeros mean, ones var at init)
    expected = (x_np - mean_before) / np.sqrt(bn._variance.numpy() + 1e-5)
    np.testing.assert_allclose(out, expected, rtol=1e-4)
    # the training program still updates stats
    exe.run(main, feed={"x": x_np}, fetch_list=[y])
    assert not np.allclose(bn._mean.numpy(), mean_before)


def test_clone_isolated_from_later_recording():
    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        h = x * 2.0
    test_prog = main.clone(for_test=True)
    n_ops = len(test_prog.ops)
    with static.program_guard(main, startup):
        label = static.data("label", [None, 4], "float32")
        _ = ((h - label) ** 2).mean()
    assert len(test_prog.ops) == n_ops
    assert "label" not in test_prog.feed_vars
    exe = static.Executor()
    (out,) = exe.run(test_prog, feed={"x": np.ones((2, 4), "float32")}, fetch_list=[h])
    np.testing.assert_allclose(out, 2.0)


def test_save_inference_model_middle_symbolic_dim(tmp_path):
    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, None, 6], "float32")
        lin = paddle.nn.Linear(6, 2)
        out = lin(x)
    exe = static.Executor()
    prefix = str(tmp_path / "seq")
    static.save_inference_model(prefix, [x], [out], exe)
    prog, _, _ = static.load_inference_model(prefix, exe)
    for T in (3, 11):
        (o,) = prog.run({"x": np.random.rand(2, T, 6).astype("float32")})
        assert o.shape == (2, T, 2)


def test_input_grad_fetch_during_optimized_training():
    # adversarial-training pattern: fetch d(loss)/d(input) while minimizing
    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 3], "float32")
        lin = paddle.nn.Linear(3, 1)
        loss = (lin(x) ** 2).mean()
        (gx,) = static.gradients(loss, [x])
        opt = paddle.optimizer.SGD(learning_rate=0.01)
        opt.minimize(loss)
    exe = static.Executor()
    x_np = np.random.rand(4, 3).astype("float32")
    w0 = lin.weight.numpy().copy()
    g, l = exe.run(main, feed={"x": x_np}, fetch_list=[gx, loss])
    assert g.shape == x_np.shape and np.isfinite(l)
    assert not np.allclose(lin.weight.numpy(), w0), "params must still update"


def test_exe_run_accepts_loaded_program(tmp_path):
    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        out = x * 3.0
    exe = static.Executor()
    prefix = str(tmp_path / "m")
    static.save_inference_model(prefix, [x], [out], exe)
    prog, feeds, fetches = static.load_inference_model(prefix, exe)
    x_np = np.ones((2, 4), "float32")
    (o,) = exe.run(prog, feed={"x": x_np}, fetch_list=fetches)
    np.testing.assert_allclose(o, 3.0)


def test_clone_for_test_downscale_dropout_scales():
    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 16], "float32")
        y = paddle.nn.functional.dropout(x, p=0.5, training=True,
                                         mode="downscale_in_infer")
    test_prog = main.clone(for_test=True)
    exe = static.Executor()
    (out,) = exe.run(test_prog, feed={"x": np.ones((2, 16), "float32")},
                     fetch_list=[y])
    np.testing.assert_allclose(out, 0.5)


def test_static_nn_fc_batch_gt_one():
    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 3, 4], "float32")
        out = static.nn.fc(x, 5)
    exe = static.Executor()
    (o,) = exe.run(main, feed={"x": np.ones((8, 3, 4), "float32")},
                   fetch_list=[out])
    assert o.shape == (8, 5)


def test_static_amp_autocast_records_bf16_and_trains():
    import jax.numpy as jnp

    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 8], "float32")
        t = static.data("t", [None, 1], "float32")
        lin = paddle.nn.Linear(8, 1)
        with static.amp.amp_guard(level="O2", dtype="bfloat16"):
            pred = lin(x)
        # matmul recorded under O2 produces bf16 activations
        assert pred.value.dtype == jnp.bfloat16
        loss = ((pred.astype("float32") - t) ** 2).mean()
        opt = static.amp.decorate(
            paddle.optimizer.SGD(learning_rate=0.05), init_loss_scaling=8.0)
        opt.minimize(loss)
    exe = static.Executor()
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(100):
        xb = rng.rand(16, 8).astype("float32")
        tb = (xb.sum(1, keepdims=True) * 0.5).astype("float32")
        (l,) = exe.run(main, feed={"x": xb, "t": tb}, fetch_list=[loss])
        losses.append(float(l))
    # loss fetch is scaled by 8; training must still converge
    assert losses[-1] < losses[0] * 0.2, f"{losses[0]} -> {losses[-1]}"


def test_fused_dropout_add_ln_fresh_mask_per_run():
    """Static-mode fused_dropout_add_ln must sample its mask per run (not
    bake it at trace time): two runs differ, p=0 path is deterministic."""
    from paddle_tpu.incubate.operators import fused_dropout_add_ln

    paddle.enable_static()
    main, startup = _fresh_program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 8], "float32")
        r = static.data("r", [4, 8], "float32")
        g = paddle.to_tensor(np.ones(8, "float32"))
        b = paddle.to_tensor(np.zeros(8, "float32"))
        out, new_res = fused_dropout_add_ln(x, r, g, b, p=0.5, training=True)
    exe = static.Executor()
    x_np = np.random.RandomState(0).rand(4, 8).astype("float32")
    r_np = np.zeros((4, 8), "float32")
    (a1,) = exe.run(main, feed={"x": x_np, "r": r_np}, fetch_list=[new_res])
    (a2,) = exe.run(main, feed={"x": x_np, "r": r_np}, fetch_list=[new_res])
    assert not np.allclose(a1, a2), "dropout mask was baked in at trace time"
