"""Tape autograd tests (parity: reference BasicEngine / imperative tests)."""
import numpy as np

import paddle_tpu as paddle


def test_simple_backward():
    x = paddle.to_tensor(np.array([2.0, 3.0], np.float32), stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_chain():
    x = paddle.to_tensor(np.array(0.4, np.float32), stop_gradient=False)
    y = paddle.tanh(x * 3.0)
    z = y * y
    z.backward()
    t = np.tanh(1.2)
    np.testing.assert_allclose(x.grad.numpy(), 2 * t * (1 - t * t) * 3, rtol=1e-4)


def test_accumulation_and_clear():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    (x * 2).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 2, 2])
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5, 5, 5])  # accumulated
    x.clear_grad()
    assert x.grad is None


def test_fanout():
    x = paddle.to_tensor(np.array(3.0, np.float32), stop_gradient=False)
    a = x * 2
    b = a + a * a
    b.backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 + 2 * 2 * 2 * 3.0)


def test_stop_gradient():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    y = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=True)
    (x * y).sum().backward()
    assert x.grad is not None and y.grad is None


def test_detach():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    y = (x * 2).detach()
    assert y.stop_gradient
    z = (x * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 2])


def test_no_grad():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient and y._node is None


def test_multi_output_op():
    x = paddle.to_tensor(np.array([[3.0, 1.0, 2.0]], np.float32), stop_gradient=False)
    parts = paddle.split(x, 3, axis=1)
    (parts[0] * 5 + parts[2] * 2).backward(paddle.to_tensor(np.array([[1.0]], np.float32)))
    np.testing.assert_allclose(x.grad.numpy(), [[5.0, 0.0, 2.0]])


def test_paddle_grad_api():
    x = paddle.to_tensor(np.array(2.0, np.float32), stop_gradient=False)
    y = x * x
    (gx,) = paddle.grad(y, x)
    np.testing.assert_allclose(gx.numpy(), 4.0)
    assert x.grad is None  # grad() must not pollute .grad


def test_register_hook():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    seen = []

    def hook(g):
        seen.append(g.numpy().copy())
        return g * 2

    x.register_hook(hook)
    (x * 3).sum().backward()
    assert len(seen) == 1
    np.testing.assert_allclose(x.grad.numpy(), [6, 6])


def test_retain_graph():
    x = paddle.to_tensor(np.array(2.0, np.float32), stop_gradient=False)
    y = x * x
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 8.0)


def test_non_scalar_backward_requires_grad_tensor():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = x * 2
    try:
        y.backward()
        raise AssertionError("expected RuntimeError")
    except RuntimeError:
        pass


class TestDoubleGrad:
    """create_graph=True: the backward lands on the tape (reference:
    PartialGradEngine partial_grad_engine.cc:1088 + matmul_v2_grad_grad)."""

    def test_elementwise_double_grad(self):
        import paddle_tpu as paddle
        from paddle_tpu.autograd import tape

        x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
        y = (x * x * x).sum()
        (g1,) = tape.grad(y, [x], create_graph=True)
        np.testing.assert_allclose(np.asarray(g1._data), [12.0, 27.0])
        assert g1._node is not None  # backward was taped
        (g2,) = tape.grad(g1.sum(), [x])
        np.testing.assert_allclose(np.asarray(g2._data), [12.0, 18.0])  # 6x

    def test_matmul_double_grad(self):
        import paddle_tpu as paddle
        from paddle_tpu.autograd import tape

        rng = np.random.default_rng(0)
        a_np = rng.normal(size=(3, 4)).astype("float32")
        b_np = rng.normal(size=(4, 2)).astype("float32")
        a = paddle.to_tensor(a_np, stop_gradient=False)
        b = paddle.to_tensor(b_np, stop_gradient=False)
        z = paddle.matmul(a, b).sum()
        (ga,) = tape.grad(z, [a], create_graph=True)
        # dz/da = 1 @ b^T
        np.testing.assert_allclose(
            np.asarray(ga._data), np.ones((3, 2)) @ b_np.T, rtol=1e-5)
        # d/d b of sum(ga * a) = d/db sum((1 @ b^T) * a) -> ones^T-weighted a
        (gb,) = tape.grad((ga * a).sum(), [b])
        want = (a_np.T @ np.ones((3, 2))).astype("float32")
        np.testing.assert_allclose(np.asarray(gb._data), want, rtol=1e-5)

    def test_activation_double_grad(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        from paddle_tpu.autograd import tape

        x = paddle.to_tensor([0.5, -0.3, 1.2], stop_gradient=False)
        y = F.tanh(x).sum()
        (g1,) = tape.grad(y, [x], create_graph=True)
        (g2,) = tape.grad(g1.sum(), [x])
        t = np.tanh(np.asarray([0.5, -0.3, 1.2]))
        np.testing.assert_allclose(
            np.asarray(g2._data), -2 * t * (1 - t * t), rtol=1e-5)

    def test_double_backward_via_backward(self):
        import paddle_tpu as paddle
        from paddle_tpu.autograd import tape

        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        y = (x ** 2).sum()
        (g1,) = tape.grad(y, [x], create_graph=True)
        s = g1.sum()
        s.backward()
        np.testing.assert_allclose(np.asarray(x.grad._data), [2.0, 2.0])
