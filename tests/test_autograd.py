"""Tape autograd tests (parity: reference BasicEngine / imperative tests)."""
import numpy as np

import paddle_tpu as paddle


def test_simple_backward():
    x = paddle.to_tensor(np.array([2.0, 3.0], np.float32), stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_chain():
    x = paddle.to_tensor(np.array(0.4, np.float32), stop_gradient=False)
    y = paddle.tanh(x * 3.0)
    z = y * y
    z.backward()
    t = np.tanh(1.2)
    np.testing.assert_allclose(x.grad.numpy(), 2 * t * (1 - t * t) * 3, rtol=1e-4)


def test_accumulation_and_clear():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    (x * 2).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 2, 2])
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5, 5, 5])  # accumulated
    x.clear_grad()
    assert x.grad is None


def test_fanout():
    x = paddle.to_tensor(np.array(3.0, np.float32), stop_gradient=False)
    a = x * 2
    b = a + a * a
    b.backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 + 2 * 2 * 2 * 3.0)


def test_stop_gradient():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    y = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=True)
    (x * y).sum().backward()
    assert x.grad is not None and y.grad is None


def test_detach():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    y = (x * 2).detach()
    assert y.stop_gradient
    z = (x * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 2])


def test_no_grad():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient and y._node is None


def test_multi_output_op():
    x = paddle.to_tensor(np.array([[3.0, 1.0, 2.0]], np.float32), stop_gradient=False)
    parts = paddle.split(x, 3, axis=1)
    (parts[0] * 5 + parts[2] * 2).backward(paddle.to_tensor(np.array([[1.0]], np.float32)))
    np.testing.assert_allclose(x.grad.numpy(), [[5.0, 0.0, 2.0]])


def test_paddle_grad_api():
    x = paddle.to_tensor(np.array(2.0, np.float32), stop_gradient=False)
    y = x * x
    (gx,) = paddle.grad(y, x)
    np.testing.assert_allclose(gx.numpy(), 4.0)
    assert x.grad is None  # grad() must not pollute .grad


def test_register_hook():
    x = paddle.to_tensor(np.ones(2, np.float32), stop_gradient=False)
    seen = []

    def hook(g):
        seen.append(g.numpy().copy())
        return g * 2

    x.register_hook(hook)
    (x * 3).sum().backward()
    assert len(seen) == 1
    np.testing.assert_allclose(x.grad.numpy(), [6, 6])


def test_retain_graph():
    x = paddle.to_tensor(np.array(2.0, np.float32), stop_gradient=False)
    y = x * x
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 8.0)


def test_non_scalar_backward_requires_grad_tensor():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = x * 2
    try:
        y.backward()
        raise AssertionError("expected RuntimeError")
    except RuntimeError:
        pass
