"""Test harness config.

Forces an 8-virtual-device CPU platform (parity with the reference's
single-host multi-device test strategy, SURVEY.md §4.3) so every sharding /
collective / pipeline test runs without TPU hardware.

Note: jax is already imported by a pytest plugin before this file runs, so we
use jax.config.update (honored until backend init) rather than env vars.
"""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# ---------------------------------------------------------------------------
# fast / full split (≙ reference CI sharding, tools/parallel_UT_rule.py):
# `pytest -m fast` is the ~4.5-minute tier (measured 4:25 by the r4 judge
# run on this box); the files below are the heavy
# integration/parity suites (measured full run: ~42 min wall, r4) and only
# run in the full tier. Everything else is auto-marked fast.
# ---------------------------------------------------------------------------
_SLOW_FILES = {
    "test_pipeline_schedule.py",   # ~10 min: dense-parity hybrid meshes
    "test_vision_models.py",       # ~7 min: 13 model families forward
    "test_gpt_model.py",           # ~6.5 min: model-parallel parity
    "test_moe.py",
    "test_bert_model.py",
    "test_sequence_parallel.py",
    "test_hapi.py",
    "test_mnist_e2e.py",
    "test_launch_multiproc.py",    # forks subprocesses
    "test_pallas_flash_attention.py",
    "test_pallas_kernels.py",
    "test_quantization.py",
    "test_vision_ops.py",
    "test_offload.py",
    "test_distributed.py",
    "test_checkpoint_elastic.py",
    "test_book_e2e.py",
    "test_eager_layer_jit.py",
    "test_text_utils_inference.py",
    "test_text_ops.py",
    "test_nn_layers.py",
    "test_fft_signal.py",
    "test_inference_generation.py",  # StableHLO export round-trips
}


def pytest_configure(config):
    config.addinivalue_line("markers", "fast: quick tier (<3 min total)")
    config.addinivalue_line("markers", "full: heavy integration/parity tier")
    config.addinivalue_line(
        "markers",
        "slow: multi-process chaos/e2e tests (>10s), excluded from the "
        "tier-1 `-m 'not slow'` run")
    config.addinivalue_line(
        "markers",
        "chaos: SIGTERM/SIGKILL process-kill tests (test_resilience / "
        "test_elastic_dp / test_router_failover) — timing-sensitive under "
        "concurrent load; rerun in isolation with `pytest -m chaos` "
        "before calling a failure a regression")
    config.addinivalue_line(
        "markers",
        "pallas: interpret-mode Pallas kernel suites (CPU tier-1 runs "
        "them; TPU-only shape/tiling parametrizations can be targeted or "
        "excluded with one `-m pallas` expression)")


def pytest_collection_modifyitems(config, items):
    for item in items:
        name = os.path.basename(str(item.fspath))
        if name in _SLOW_FILES:
            item.add_marker(pytest.mark.full)
        else:
            item.add_marker(pytest.mark.fast)


@pytest.fixture(autouse=True)
def _seed_everything():
    import paddle_tpu as paddle

    paddle.seed(2024)
    np.random.seed(2024)
    yield


# ---------------------------------------------------------------------------
# Runtime lock-order journal (concurrency doctor, ISSUE 14): the suites
# that exercise the threaded control plane hardest run with instrumented
# locks; at session end the observed held->acquired edges are merged into
# the STATIC lock model and the union must be acyclic. Set
# HOSTRACE_JOURNAL_OUT=<path> to also persist the journal (that is how
# benchmarks/hostrace_journal.json is regenerated).
# ---------------------------------------------------------------------------
_HOSTRACE_SUITES = {
    "test_serving.py",
    "test_router_failover.py",
    "test_replicated_store.py",
}
_hostrace_recorder = None


def _get_hostrace_recorder():
    global _hostrace_recorder
    if _hostrace_recorder is None:
        from paddle_tpu.analysis.lockmodel import LockOrderRecorder

        _hostrace_recorder = LockOrderRecorder()
    return _hostrace_recorder


@pytest.fixture(autouse=True)
def _hostrace_arm(request):
    if os.environ.get("HOSTRACE_ARM", "1") == "0":  # escape hatch
        yield
        return
    if os.path.basename(str(request.node.fspath)) not in _HOSTRACE_SUITES:
        yield
        return
    from paddle_tpu.analysis import lockmodel

    rec = _get_hostrace_recorder()
    try:
        lockmodel.arm(rec)
    except RuntimeError:  # already armed (nested/re-entrant collection)
        yield
        return
    try:
        yield
    finally:
        lockmodel.disarm()


@pytest.fixture(autouse=True, scope="session")
def _hostrace_journal_check():
    yield
    rec = _hostrace_recorder
    if rec is None or not rec.edges:
        return
    from paddle_tpu.analysis import lockmodel

    out = os.environ.get("HOSTRACE_JOURNAL_OUT")
    if out:
        lockmodel.write_journal(rec, out, meta={"source": "pytest-tier1"})
    model = lockmodel.scan_modules(lockmodel.default_host_paths())
    graph = lockmodel.build_order_graph(model, rec.edge_list())
    cycles = graph.cycles()
    assert not cycles, (
        f"runtime lock-order journal introduced cycles into the static "
        f"lock graph (potential deadlocks observed live): {cycles}")
