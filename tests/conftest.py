"""Test harness config.

Forces an 8-virtual-device CPU platform (parity with the reference's
single-host multi-device test strategy, SURVEY.md §4.3) so every sharding /
collective / pipeline test runs without TPU hardware.

Note: jax is already imported by a pytest plugin before this file runs, so we
use jax.config.update (honored until backend init) rather than env vars.
"""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_everything():
    import paddle_tpu as paddle

    paddle.seed(2024)
    np.random.seed(2024)
    yield
