"""KV-cache generation on exported artifacts + int8 PTQ artifacts through
the Predictor (VERDICT r3 do#8; reference analysis_predictor.h:86,:173)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import (
    Config,
    GenerationPredictor,
    create_predictor,
    save_for_generation,
)
from paddle_tpu.models import generate
from paddle_tpu.models.gpt import GPTForPretraining, gpt_config


def _tiny_model():
    paddle.seed(0)
    cfg = gpt_config("gpt2-small", vocab_size=64, hidden_size=32, num_layers=2,
                     num_attention_heads=4, max_position_embeddings=64,
                     hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    m = GPTForPretraining(cfg)
    m.eval()
    return m


def test_exported_generation_matches_eager(tmp_path):
    """Predictor-driven incremental decoding == eager KV-cache generate,
    token for token."""
    m = _tiny_model()
    prompt = np.random.default_rng(0).integers(0, 64, (2, 5)).astype("int32")
    want = np.asarray(generate(m, paddle.to_tensor(prompt),
                               max_new_tokens=8)._data)
    p = os.path.join(tmp_path, "gpt")
    save_for_generation(m, p, max_seq_len=32, batch_size=2, prompt_len=5)
    got = GenerationPredictor(p).generate(prompt, max_new_tokens=8)
    np.testing.assert_array_equal(got, want)


def test_exported_generation_eos_and_capacity(tmp_path):
    m = _tiny_model()
    prompt = np.random.default_rng(1).integers(0, 64, (1, 4)).astype("int32")
    p = os.path.join(tmp_path, "gpt")
    save_for_generation(m, p, max_seq_len=16, batch_size=1, prompt_len=4)
    pred = GenerationPredictor(p)
    out = pred.generate(prompt, max_new_tokens=6)
    assert out.shape == (1, 10)
    # eos early-stop mirrors eager semantics
    eager = np.asarray(generate(m, paddle.to_tensor(prompt), max_new_tokens=6,
                                eos_token_id=int(out[0, 4]))._data)
    got = pred.generate(prompt, max_new_tokens=6, eos_token_id=int(out[0, 4]))
    np.testing.assert_array_equal(got, eager)


def test_int8_ptq_generation_artifact(tmp_path):
    """precision='int8' weight-only PTQ artifacts drive the same decode
    loop end-to-end (quantized weights → dequant at load → generation)."""
    m = _tiny_model()
    prompt = np.random.default_rng(2).integers(0, 64, (2, 5)).astype("int32")
    p = os.path.join(tmp_path, "gpt8")
    save_for_generation(m, p, max_seq_len=24, batch_size=2, prompt_len=5,
                        precision="int8")
    got = GenerationPredictor(p).generate(prompt, max_new_tokens=6)
    assert got.shape == (2, 11)
    assert got.dtype == np.int64
    np.testing.assert_array_equal(got[:, :5], prompt)
    assert (got >= 0).all() and (got < 64).all()
    # int8 artifact files exist and carry scales
    assert os.path.exists(p + ".step.pdiparams")
    meta_blob = open(p + ".step.pdmeta").read()
    assert "int8_scales" in meta_blob


def test_int8_ptq_predictor_close_to_float(tmp_path):
    """The plain Predictor accepts an int8 artifact; outputs stay close to
    the float export (weight-only PTQ error bound)."""
    from paddle_tpu.jit import InputSpec, save as jit_save

    m = _tiny_model()
    x = np.random.default_rng(3).integers(0, 64, (2, 6)).astype("int32")
    pf = os.path.join(tmp_path, "f32")
    p8 = os.path.join(tmp_path, "i8")
    jit_save(m, pf, input_spec=[InputSpec([2, 6], "int32")])
    jit_save(m, p8, input_spec=[InputSpec([2, 6], "int32")], precision="int8")
    out_f = create_predictor(Config(pf)).run([x])[0]
    out_8 = create_predictor(Config(p8)).run([x])[0]
    assert out_f.shape == out_8.shape
    # per-channel symmetric int8: logits track the float artifact closely
    denom = np.abs(out_f).mean() + 1e-6
    assert np.abs(out_f - out_8).mean() / denom < 0.1


def test_capacity_overflow_raises(tmp_path):
    m = _tiny_model()
    prompt = np.random.default_rng(4).integers(0, 64, (1, 10)).astype("int32")
    p = os.path.join(tmp_path, "gpt")
    save_for_generation(m, p, max_seq_len=16, batch_size=1, prompt_len=10)
    with pytest.raises(ValueError, match="KV capacity"):
        GenerationPredictor(p).generate(prompt, max_new_tokens=32)


def test_jit_artifact_output_names_before_run(tmp_path):
    """Fetch names resolve at load time (reference pattern: bind output
    handles before the first ZeroCopyRun)."""
    from paddle_tpu.jit import InputSpec, save as jit_save

    m = _tiny_model()
    p = os.path.join(tmp_path, "m")
    jit_save(m, p, input_spec=[InputSpec([2, 6], "int32")])
    pred = create_predictor(Config(p))
    assert pred.get_output_names() == ["out0"]


def test_export_then_eager_generate_with_layer_jit(tmp_path):
    """Regression (review r4): the Step export calls layers with a
    position_ids TENSOR while eager prefill passes None — the layer-jit
    cache key must distinguish the two (a Tensor used to hash as None,
    poisoning the cache: later eager generates crashed or emitted wrong
    tokens on the TPU platform)."""
    from paddle_tpu.framework.flags import set_flags

    m = _tiny_model()
    prompt = np.random.default_rng(5).integers(0, 64, (2, 5)).astype("int32")
    set_flags({"FLAGS_eager_layer_jit": "force"})
    try:
        want = np.asarray(generate(m, paddle.to_tensor(prompt),
                                   max_new_tokens=6)._data)
        p = os.path.join(tmp_path, "gpt")
        save_for_generation(m, p, max_seq_len=24, batch_size=2, prompt_len=5)
        # eager generation AFTER export must still match (cache not poisoned)
        again = np.asarray(generate(m, paddle.to_tensor(prompt),
                                    max_new_tokens=6)._data)
        np.testing.assert_array_equal(again, want)
        got = GenerationPredictor(p).generate(prompt, max_new_tokens=6)
        np.testing.assert_array_equal(got, want)
    finally:
        set_flags({"FLAGS_eager_layer_jit": "true"})
