"""OpTest-style harness: numpy-golden correctness + finite-difference grads.

Parity: the reference's keystone op test pattern
(/root/reference/python/paddle/fluid/tests/unittests/op_test.py:277 —
check_output compares op vs numpy; check_grad compares analytic grads against
get_numeric_gradient finite differences :110). TPU translation per SURVEY.md
§4: numpy golden vs eager-XLA, plus an extra eager-vs-jit consistency check
that the reference expresses as dygraph/static consistency.
"""
from __future__ import annotations

import jax
import numpy as np

import paddle_tpu as paddle


def check_output(op_fn, np_fn, inputs, atol=1e-5, rtol=2e-4, kwargs=None):
    """Run op_fn on Tensors and np_fn on numpy arrays; compare all outputs."""
    kwargs = kwargs or {}
    tensors = [paddle.to_tensor(a) for a in inputs]
    got = op_fn(*tensors, **kwargs)
    want = np_fn(*[np.asarray(a) for a in inputs], **kwargs)
    _assert_all_close(got, want, atol, rtol)
    return got


def _flatten_out(out):
    if isinstance(out, (list, tuple)):
        res = []
        for o in out:
            res.extend(_flatten_out(o))
        return res
    return [out]


def _assert_all_close(got, want, atol, rtol):
    got_list = _flatten_out(got)
    want_list = _flatten_out(want)
    assert len(got_list) == len(want_list), f"{len(got_list)} outputs vs {len(want_list)}"
    for g, w in zip(got_list, want_list):
        g_np = g.numpy() if isinstance(g, paddle.Tensor) else np.asarray(g)
        np.testing.assert_allclose(
            np.asarray(g_np, dtype=np.float64) if np.issubdtype(g_np.dtype, np.floating) else g_np,
            np.asarray(w, dtype=np.float64) if np.issubdtype(np.asarray(w).dtype, np.floating) else w,
            atol=atol,
            rtol=rtol,
        )


def get_numeric_gradient(fn, inputs, wrt_idx, delta=1e-3):
    """Central finite differences of sum(fn(*inputs)) w.r.t. inputs[wrt_idx]."""
    inputs = [np.asarray(a, dtype=np.float64) for a in inputs]
    x = inputs[wrt_idx]
    grad = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        orig = x[idx]
        x[idx] = orig + delta
        hi = float(np.sum(fn(*inputs)))
        x[idx] = orig - delta
        lo = float(np.sum(fn(*inputs)))
        x[idx] = orig
        grad[idx] = (hi - lo) / (2 * delta)
        it.iternext()
    return grad


def check_grad(op_fn, np_fn, inputs, wrt=(0,), atol=5e-3, rtol=5e-3, kwargs=None):
    """Compare tape-computed grads against finite differences."""
    kwargs = kwargs or {}
    tensors = [
        paddle.to_tensor(np.asarray(a, dtype=np.float64), stop_gradient=False) for a in inputs
    ]
    out = op_fn(*tensors, **kwargs)
    outs = _flatten_out(out)
    loss = outs[0].sum() if outs[0].size > 1 else outs[0]
    for o in outs[1:]:
        if o.dtype in ("float32", "float64"):
            loss = loss + o.sum()
    loss.backward()
    for i in wrt:
        got = tensors[i].grad.numpy()
        want = get_numeric_gradient(lambda *a: np_fn(*a, **kwargs), inputs, i)
        np.testing.assert_allclose(got, want, atol=atol, rtol=rtol, err_msg=f"grad wrt input {i}")


def check_eager_vs_jit(op_fn, inputs, kwargs=None, atol=1e-6):
    """Eager vs jit consistency (≙ reference dygraph/static equivalence)."""
    kwargs = kwargs or {}
    arrays = [np.asarray(a) for a in inputs]
    eager = op_fn(*[paddle.to_tensor(a) for a in arrays], **kwargs)

    raw = getattr(op_fn, "raw", None)
    if raw is None:
        def raw_call(*arrs):
            with paddle.no_grad():
                out = op_fn(*[paddle.to_tensor(a) for a in arrs], **kwargs)
            outs = _flatten_out(out)
            return [o.value for o in outs]
        jitted = jax.jit(raw_call)
        got = jitted(*arrays)
        _assert_all_close([paddle.Tensor(g) for g in got], [o.numpy() for o in _flatten_out(eager)], atol, atol)
    else:
        jitted = jax.jit(lambda *arrs: raw(*arrs, **kwargs))
        got = jitted(*arrays)
        _assert_all_close(got, [o.numpy() for o in _flatten_out(eager)], atol, atol)
