"""Dataset-channel feeding engine (VERDICT r3 missing #5; reference
framework/data_set.cc + data_feed.cc: file-sharded parsing, channel
shuffle, InMemoryDataset local/global shuffle)."""
import json
import os

import numpy as np

from paddle_tpu.io import (
    DataLoader,
    FileListDataset,
    InMemoryDataset,
    ShuffleChannel,
)


def _write_files(tmp_path, n_files=6, per_file=10):
    files = []
    v = 0
    for i in range(n_files):
        p = tmp_path / f"part-{i:03d}.jsonl"
        with open(p, "w") as f:
            for _ in range(per_file):
                f.write(json.dumps({"v": v}) + "\n")
                v += 1
        files.append(str(p))
    return files


def _parse(path):
    with open(path) as f:
        for line in f:
            yield np.int64(json.loads(line)["v"])


def test_file_list_rank_sharding(tmp_path):
    files = _write_files(tmp_path)
    seen = []
    for rank in (0, 1):
        ds = FileListDataset(files, _parse, rank=rank, world_size=2,
                             shuffle_files=False)
        seen.append({int(x) for x in ds})
    # disjoint file shards covering everything
    assert not (seen[0] & seen[1])
    assert seen[0] | seen[1] == set(range(60))


def test_file_list_epoch_reshuffle(tmp_path):
    files = _write_files(tmp_path)
    ds = FileListDataset(files, _parse, rank=0, world_size=1, seed=3)
    ds.set_epoch(0)
    e0 = [int(x) for x in ds]
    ds.set_epoch(1)
    e1 = [int(x) for x in ds]
    assert sorted(e0) == sorted(e1) == list(range(60))
    assert e0 != e1  # file order reshuffled
    ds.set_epoch(0)
    assert [int(x) for x in ds] == e0  # deterministic


def _parse_tag_pid(path):
    for v in _parse(path):
        yield np.asarray([v, os.getpid()], np.int64)


def test_file_list_under_dataloader_workers(tmp_path):
    """Workers REALLY run in parallel processes, each parsing its own file
    stride (review r4: iterable multiprocess path must engage)."""
    files = _write_files(tmp_path)
    ds = FileListDataset(files, _parse_tag_pid, rank=0, world_size=1,
                         shuffle_files=False)
    loader = DataLoader(ds, batch_size=5, num_workers=2)
    rows = [np.asarray(b) for batch in loader
            for b in np.asarray(batch[0] if isinstance(batch, (list, tuple))
                                else batch).reshape(-1, 2)]
    vals = sorted(int(r[0]) for r in rows)
    pids = {int(r[1]) for r in rows}
    assert vals == list(range(60))
    assert os.getpid() not in pids, "parsing must happen in worker procs"
    assert len(pids) == 2, "both workers must contribute"


def test_world_size_exceeding_files_raises(tmp_path):
    files = _write_files(tmp_path, n_files=2)
    import pytest
    with pytest.raises(ValueError, match="exceeds the file count"):
        FileListDataset(files, _parse, rank=0, world_size=3)


def test_shuffle_channel_streaming(tmp_path):
    files = _write_files(tmp_path)
    base = FileListDataset(files, _parse, rank=0, world_size=1,
                           shuffle_files=False)
    ch = ShuffleChannel(base, capacity=16, seed=1)
    out = [int(x) for x in ch]
    assert sorted(out) == list(range(60))
    assert out != list(range(60))  # actually shuffled
    # bounded displacement beyond the reservoir is not required, but
    # determinism per (seed, epoch) is
    assert [int(x) for x in ShuffleChannel(base, capacity=16, seed=1)] == out
    ch.set_epoch(1)
    assert [int(x) for x in ch] != out


def test_in_memory_dataset_local_and_global(tmp_path):
    files = _write_files(tmp_path)
    # two ranks load disjoint shards
    sizes = []
    rank_data = []
    for rank in (0, 1):
        ds = InMemoryDataset(rank=rank, world_size=2, seed=5)
        ds.set_filelist(files)
        ds.set_parser(_parse)
        n = ds.load_into_memory()
        sizes.append(n)
        rank_data.append({int(x) for x in ds})
    assert sum(sizes) == 60 and not (rank_data[0] & rank_data[1])

    # local shuffle permutes in place
    ds = InMemoryDataset(rank=0, world_size=1, seed=5)
    ds.set_filelist(files)
    ds.set_parser(_parse)
    ds.load_into_memory()
    before = [int(x) for x in ds]
    ds.local_shuffle(epoch=0)
    after = [int(x) for x in ds]
    assert sorted(after) == sorted(before) and after != before

    # global shuffle: both ranks draw ONE shared permutation, strided
    g = []
    for rank in (0, 1):
        ds = InMemoryDataset(rank=rank, world_size=2, seed=9)
        ds.set_filelist(files)
        ds.set_parser(_parse)
        ds.global_shuffle(epoch=2)
        g.append([int(x) for x in ds])
    assert not (set(g[0]) & set(g[1]))
    assert set(g[0]) | set(g[1]) == set(range(60))
    # shard sizes even to within one
    assert abs(len(g[0]) - len(g[1])) <= 1


def test_channel_pipeline_feeds_training(tmp_path):
    """End-to-end: file shards -> shuffle channel -> DataLoader -> a tiny
    jitted train step consumes batches."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.optimizer.optimizers import Adam

    files = _write_files(tmp_path, n_files=4, per_file=8)

    def parse_xy(path):
        for v in _parse(path):
            x = np.asarray([v % 7, (v * 3) % 5], np.float32)
            yield x, np.float32(x.sum())

    ds = ShuffleChannel(
        FileListDataset(files, parse_xy, rank=0, world_size=1, seed=2),
        capacity=8, seed=2)
    loader = DataLoader(ds, batch_size=8, num_workers=0)
    paddle.seed(0)
    net = nn.Linear(2, 1)
    opt = Adam(learning_rate=0.1, parameters=net.parameters())
    losses = []
    for _epoch in range(6):
        for xb, yb in loader:
            loss = ((net(xb)[:, 0] - yb) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
    assert losses[-1] < losses[0]


def _killer_parse(path):
    # worker 1 dies mid-parse without an EOF sentinel (simulated OOM-kill)
    from paddle_tpu.io import get_worker_info

    info = get_worker_info()
    if info is not None and info.id == 1:
        os._exit(17)
    yield from _parse(path)


def test_dead_worker_raises_instead_of_hanging(tmp_path):
    import pytest

    files = _write_files(tmp_path, n_files=4)
    ds = FileListDataset(files, _killer_parse, rank=0, world_size=1,
                         shuffle_files=False)
    loader = DataLoader(ds, batch_size=5, num_workers=2)
    with pytest.raises(RuntimeError, match="died with exit code 17"):
        list(loader)


def test_rank_without_world_size_raises(tmp_path):
    import pytest

    files = _write_files(tmp_path, n_files=2)
    with pytest.raises(ValueError, match="both rank and world_size"):
        FileListDataset(files, _parse, rank=1)
    with pytest.raises(ValueError, match="both rank and world_size"):
        InMemoryDataset(world_size=2)
