"""GPT flagship model: forward shapes, TP parity, hybrid-mesh training.

Parity strategy follows the reference's dist tests (SURVEY.md §4.3):
assert loss parity between replicated and model-parallel runs of the same
model, and convergence of the jitted hybrid step.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.env import init_mesh, clear_mesh
from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
from paddle_tpu.models.gpt import (
    GPTForPretraining,
    GPTPretrainingCriterion,
    gpt_config,
)
from paddle_tpu.optimizer.optimizers import AdamW


def tiny_cfg(**kw):
    base = dict(vocab_size=128, hidden_size=64, num_layers=2,
                num_attention_heads=4, max_position_embeddings=64,
                hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    base.update(kw)
    return gpt_config("gpt2-small", **base)


@pytest.fixture(autouse=True)
def _clean_mesh():
    yield
    clear_mesh()


def _batch(b=4, t=16, vocab=128, seed=0):
    rng = np.random.default_rng(seed)
    return paddle.to_tensor(rng.integers(0, vocab, (b, t)).astype("int32"))


def test_forward_shapes():
    m = GPTForPretraining(tiny_cfg())
    ids = _batch()
    logits = m(ids)
    assert list(logits.shape) == [4, 16, 128]
    loss = GPTPretrainingCriterion()(logits, ids)
    assert float(loss._data) > 0


def test_loss_parity_replicated_vs_mp():
    """Same seed => same init => identical loss on dp-only vs dp x mp mesh."""
    paddle.seed(7)
    m1 = GPTForPretraining(tiny_cfg())
    crit = GPTPretrainingCriterion()
    ids = _batch(b=8)

    init_mesh({"dp": 8})
    opt1 = AdamW(learning_rate=0.0, parameters=m1.parameters())
    t1 = ParallelTrainer(m1, lambda o, y: crit(o, y), opt1)
    l1 = float(t1.step(ids, ids)._data)
    clear_mesh()

    paddle.seed(7)
    m2 = GPTForPretraining(tiny_cfg())
    init_mesh({"dp": 2, "mp": 4})
    opt2 = AdamW(learning_rate=0.0, parameters=m2.parameters())
    t2 = ParallelTrainer(m2, lambda o, y: crit(o, y), opt2)
    l2 = float(t2.step(ids, ids)._data)

    np.testing.assert_allclose(l1, l2, rtol=2e-5)


def test_hybrid_training_converges():
    paddle.seed(3)
    cfg = tiny_cfg()
    m = GPTForPretraining(cfg)
    crit = GPTPretrainingCriterion()
    init_mesh({"dp": 2, "sharding": 2, "mp": 2})
    opt = AdamW(learning_rate=3e-3, parameters=m.parameters())
    tr = ParallelTrainer(m, lambda o, y: crit(o, y), opt,
                         dp_axis="dp", fsdp_axis="sharding")
    ids = _batch(b=8)
    losses = [float(tr.step(ids, ids)._data) for _ in range(10)]
    assert losses[-1] < losses[0] - 0.5, losses


def test_recompute_matches_baseline():
    paddle.seed(11)
    m1 = GPTForPretraining(tiny_cfg(use_recompute=False))
    paddle.seed(11)
    m2 = GPTForPretraining(tiny_cfg(use_recompute=True))
    crit = GPTPretrainingCriterion()
    ids = _batch()
    init_mesh({"dp": 1})
    o1 = AdamW(learning_rate=1e-3, parameters=m1.parameters())
    o2 = AdamW(learning_rate=1e-3, parameters=m2.parameters())
    t1 = ParallelTrainer(m1, lambda o, y: crit(o, y), o1, dp_axis=None)
    t2 = ParallelTrainer(m2, lambda o, y: crit(o, y), o2, dp_axis=None)
    for _ in range(3):
        l1 = float(t1.step(ids, ids)._data)
        l2 = float(t2.step(ids, ids)._data)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_graft_entry():
    import __graft_entry__ as g
    import jax

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (2, 32, 256)
    g.dryrun_multichip(8)


class TestGeneration:
    def _model(self):
        from paddle_tpu.models.gpt import GPTForPretraining, gpt_config

        paddle.seed(0)
        cfg = gpt_config("gpt2-small", vocab_size=64, hidden_size=32,
                         num_layers=2, num_attention_heads=4,
                         max_position_embeddings=64,
                         hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
        return GPTForPretraining(cfg)

    def test_cached_equals_uncached_greedy(self):
        from paddle_tpu.models import generate

        model = self._model()
        prompt = paddle.to_tensor(
            np.random.default_rng(0).integers(0, 64, (2, 5)).astype("int32"))
        out_cache = generate(model, prompt, max_new_tokens=8, use_cache=True)
        out_plain = generate(model, prompt, max_new_tokens=8, use_cache=False)
        np.testing.assert_array_equal(np.asarray(out_cache._data),
                                      np.asarray(out_plain._data))

    def test_greedy_matches_manual_loop(self):
        from paddle_tpu.models import generate

        model = self._model()
        rng_l = np.random.default_rng(1)
        prompt = rng_l.integers(0, 64, (1, 4)).astype("int32")
        out = np.asarray(generate(model, paddle.to_tensor(prompt),
                                  max_new_tokens=4)._data)
        # manual greedy: full forward each step
        ids = prompt.copy()
        model.eval()
        for _ in range(4):
            logits = np.asarray(model(paddle.to_tensor(ids))._data)
            nxt = logits[:, -1].argmax(-1).astype("int32")
            ids = np.concatenate([ids, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(out, ids)

    def test_eos_stops_early_and_sampling_runs(self):
        from paddle_tpu.models import generate

        model = self._model()
        prompt = paddle.to_tensor(np.array([[1, 2]], "int32"))
        out = generate(model, prompt, max_new_tokens=50, eos_token_id=0)
        assert np.asarray(out._data).shape[1] <= 52
        paddle.seed(3)
        s1 = np.asarray(generate(model, prompt, max_new_tokens=5,
                                 temperature=1.0, top_k=10)._data)
        paddle.seed(3)
        s2 = np.asarray(generate(model, prompt, max_new_tokens=5,
                                 temperature=1.0, top_k=10)._data)
        np.testing.assert_array_equal(s1, s2)  # seeded reproducibility
        out_p = generate(model, prompt, max_new_tokens=5, temperature=0.8,
                         top_p=0.9)
        assert np.asarray(out_p._data).shape == (1, 7)

    def test_cache_cleaned_up(self):
        from paddle_tpu.models import generate
        from paddle_tpu.models.gpt import GPTAttention

        model = self._model()
        generate(model, paddle.to_tensor(np.array([[1]], "int32")), 2)
        for m in model.sublayers():
            if isinstance(m, GPTAttention):
                assert not hasattr(m, "_gen_cache")
        # model still trains after generation (mode restored, no cache)
        model.train()
        out = model(paddle.to_tensor(np.array([[1, 2, 3]], "int32")))
        assert tuple(out.shape) == (1, 3, 64)


def test_qkv_layout_migration():
    """Explicitly old-tagged checkpoints auto-migrate; untagged dicts are
    ambiguous (may already be head-major) so they load as-is with a warning
    unless FLAGS_gpt_qkv_assume_legacy opts in to the permutation."""
    import warnings

    import numpy as np

    from paddle_tpu.framework.flags import set_flags

    m = GPTForPretraining(tiny_cfg())
    ids = _batch()
    ref = np.asarray(m(ids)._data)
    sd = {k: np.asarray(v._data) for k, v in m.state_dict().items()}
    assert "gpt.qkv_layout" in sd

    # simulate an old checkpoint: permute qkv columns [nh,3,hd]->[3,nh,hd]
    old = dict(sd)
    hd = m.gpt.config.head_dim
    for k in list(old):
        if k.endswith("qkv_proj.weight"):
            w = old[k]
            nh = w.shape[1] // (3 * hd)
            old[k] = (w.reshape(w.shape[0], nh, 3, hd)
                      .transpose(0, 2, 1, 3).reshape(w.shape))
        elif k.endswith("qkv_proj.bias"):
            b = old[k]
            nh = b.shape[0] // (3 * hd)
            old[k] = b.reshape(nh, 3, hd).transpose(1, 0, 2).reshape(b.shape)

    # (a) explicit old tag → auto-migrated, no flag needed
    tagged_old = dict(old)
    tagged_old["gpt.qkv_layout"] = np.asarray(1, np.int32)
    m2 = GPTForPretraining(tiny_cfg())
    m2.set_state_dict(tagged_old)
    np.testing.assert_allclose(np.asarray(m2(ids)._data), ref, rtol=1e-5, atol=1e-5)

    # (b) untagged head-major dict (saved between layout change and tag
    # introduction) → warned, loaded verbatim, outputs unchanged
    untagged_new = {k: v for k, v in sd.items() if k != "gpt.qkv_layout"}
    m3 = GPTForPretraining(tiny_cfg())
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        m3.set_state_dict(untagged_new)
    assert any("NOT migrating" in str(x.message) for x in w)
    np.testing.assert_allclose(np.asarray(m3(ids)._data), ref, rtol=1e-5, atol=1e-5)

    # (c) untagged legacy dict + explicit opt-in flag → migrated
    untagged_old = {k: v for k, v in old.items() if k != "gpt.qkv_layout"}
    set_flags({"FLAGS_gpt_qkv_assume_legacy": True})
    try:
        m4 = GPTForPretraining(tiny_cfg())
        m4.set_state_dict(untagged_old)
    finally:
        set_flags({"FLAGS_gpt_qkv_assume_legacy": False})
    np.testing.assert_allclose(np.asarray(m4(ids)._data), ref, rtol=1e-5, atol=1e-5)

    # (d) new-format dict (tag present) must load unpermuted
    m5 = GPTForPretraining(tiny_cfg())
    m5.set_state_dict(sd)
    np.testing.assert_allclose(np.asarray(m5(ids)._data), ref, rtol=1e-5, atol=1e-5)


def test_recompute_interval_marks_every_kth_block():
    """recompute_interval=3: blocks 0,3,6,... remat, the rest run saved
    (reference PipelineLayer recompute_interval semantics)."""
    m = GPTForPretraining(tiny_cfg(num_layers=6, use_recompute=True,
                                   recompute_interval=3))
    flags = [blk._use_recompute for blk in m.gpt.h]
    assert flags == [True, False, False, True, False, False], flags
    # still trains
    ids = _batch()
    loss = GPTPretrainingCriterion()(m(ids), ids)
    loss.backward()
    assert m.gpt.h[1].attn.qkv_proj.weight.grad is not None


def test_recompute_interval_zero_disables():
    """interval 0 = recompute off (reference PipelineLayer default)."""
    m = GPTForPretraining(tiny_cfg(num_layers=4, use_recompute=True,
                                   recompute_interval=0))
    assert all(not blk._use_recompute for blk in m.gpt.h)
