"""Unified telemetry plane (ISSUE 7): distributed tracing, Prometheus
metrics, live MFU/HBM gauges, crash flight recorder.

Acceptance bars exercised here:
* one serving request traced across router and replica produces a single
  trace id with a well-formed span tree (route ⊃ queue ⊃ prefill ⊃ decode
  tokens) and a merged chrome-trace timeline (CLI e2e);
* a Prometheus scrape of a LIVE server parses under a strict text-format
  parser (this file ships one);
* live MFU and HBM-drift gauges populate on a real trainer step;
* flight-recorder dumps on a planted sentinel halt / engine tick failure /
  SIGTERM name the final step and carry the last N spans;
* tracing enabled vs disabled compiles the IDENTICAL jaxpr for trainer and
  pipeline steps (the r6/r7 zero-perturbation bar, extended).
"""
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.observability import flight as obs_flight
from paddle_tpu.observability import trace as obs_trace
from paddle_tpu.observability.metrics import (
    MetricsRegistry,
    log_buckets,
    wants_prometheus,
)
from paddle_tpu.resilience import AnomalyHalt, SentinelConfig, SentinelMonitor


@pytest.fixture(autouse=True)
def _clean_telemetry():
    obs.disable_tracing()
    obs_trace.reset_spans()
    fr = obs_flight.flight_recorder()
    fr.directory = None
    fr.last = fr.last_path = None
    with fr._lock:
        fr._notes.clear()
    yield
    obs.disable_tracing()
    obs_trace.reset_spans()
    fr.directory = None


def _tiny_model():
    from paddle_tpu.models.gpt import GPTForPretraining, gpt_config

    paddle.seed(0)
    cfg = gpt_config("gpt2-small", vocab_size=32, hidden_size=16,
                     num_layers=1, num_attention_heads=2,
                     max_position_embeddings=64, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    m = GPTForPretraining(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.distributed.env import clear_mesh, init_mesh

    clear_mesh()
    init_mesh({"dp": 1})
    return _tiny_model()


def _engine(model, **kw):
    from paddle_tpu.serving import ContinuousBatchingEngine

    kw.setdefault("max_seq_len", 32)
    kw.setdefault("n_slots", 2)
    kw.setdefault("prefill_buckets", [8])
    kw.setdefault("max_queue", 16)
    return ContinuousBatchingEngine(model, **kw)


def _tiny_trainer(sentinel=None, donate=False):
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.optimizer.optimizers import AdamW

    paddle.seed(0)
    clear_mesh()
    init_mesh({"dp": 1})
    net = paddle.nn.Linear(4, 4)
    opt = AdamW(learning_rate=1e-2, parameters=net.parameters())
    return ParallelTrainer(net, lambda o, y: ((o - y) ** 2).mean(), opt,
                           dp_axis=None, sentinel=sentinel, donate=donate)


# =====================================================================
# strict Prometheus text-format parser (the acceptance-bar scrape check)
# =====================================================================
_HELP_RE = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*) (.*)$")
_TYPE_RE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) (counter|gauge|histogram|summary|"
    r"untyped)$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\\\|\\\"|\\n)*\""
    r"(?:,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\\\|\\\"|\\n)*\")*)?)\})?"
    r" (-?(?:[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?|\.[0-9]+)|[+-]Inf|NaN)$")
_LABEL_PAIR_RE = re.compile(
    r"([a-zA-Z_][a-zA-Z0-9_]*)=\"((?:[^\"\\\n]|\\\\|\\\"|\\n)*)\"")


def parse_prometheus_strict(text):
    """Validate text-format 0.0.4 and return {name: [(labels, value)]}.

    Strictness: every non-comment line must be a grammatical sample, every
    sample's base name must carry a preceding ``# TYPE``, histogram
    ``_bucket`` series must be cumulative, end in ``+Inf`` and equal the
    ``_count`` sample, and the exposition must end with a newline."""
    assert text.endswith("\n"), "exposition must end with a newline"
    types, samples = {}, {}
    for line in text.split("\n")[:-1]:
        assert line.strip() == line and line, f"bad line framing: {line!r}"
        if line.startswith("# HELP "):
            assert _HELP_RE.match(line), f"bad HELP: {line!r}"
            continue
        if line.startswith("#"):
            m = _TYPE_RE.match(line)
            assert m, f"bad comment line: {line!r}"
            assert m.group(1) not in types, f"duplicate TYPE {m.group(1)}"
            types[m.group(1)] = m.group(2)
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"bad sample line: {line!r}"
        name, labelstr, value = m.group(1), m.group(2), m.group(3)
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            stripped = name[:-len(suffix)] if name.endswith(suffix) else None
            if stripped and types.get(stripped) in ("histogram", "summary"):
                base = stripped
        assert base in types, f"sample {name!r} before its # TYPE"
        labels = tuple(_LABEL_PAIR_RE.findall(labelstr or ""))
        v = {"+Inf": np.inf, "-Inf": -np.inf, "NaN": np.nan}.get(
            value, None)
        v = float(value) if v is None else v
        samples.setdefault(name, []).append((labels, v))
    # histogram invariants
    for name, kind in types.items():
        if kind != "histogram":
            continue
        series = {}
        for labels, v in samples.get(name + "_bucket", ()):
            rest = tuple(kv for kv in labels if kv[0] != "le")
            le = dict(labels)["le"]
            series.setdefault(rest, []).append((le, v))
        counts = {tuple(kv for kv in labels): v
                  for labels, v in samples.get(name + "_count", ())}
        for rest, buckets in series.items():
            values = [v for _, v in buckets]
            assert values == sorted(values), f"{name}: non-cumulative"
            assert buckets[-1][0] == "+Inf", f"{name}: missing +Inf"
            assert counts[rest] == buckets[-1][1], f"{name}: count mismatch"
    return types, samples


# =====================================================================
# span ring + context propagation
# =====================================================================
class TestSpans:
    def test_disabled_records_nothing(self):
        with obs.span("idle"):
            pass
        obs.event("marker")
        assert obs.snapshot_spans() == []

    def test_ring_bounded_with_drop_count(self):
        obs.enable_tracing(max_spans=8)
        for i in range(20):
            with obs.span(f"s{i}"):
                pass
        spans = obs.snapshot_spans()
        assert len(spans) == 8
        assert [s.name for s in spans] == [f"s{i}" for i in range(12, 20)]
        assert obs_trace.span_ring().dropped == 12

    def test_nesting_and_trace_context(self):
        obs.enable_tracing(max_spans=64)
        tid = obs.new_trace_id()
        with obs.trace_context(tid):
            with obs.span("root") as root:
                with obs.span("child", k=1) as child:
                    pass
        spans = {s.name: s for s in obs.snapshot_spans()}
        assert spans["root"].trace_id == tid
        assert spans["child"].trace_id == tid
        assert spans["child"].parent_id == root.span_id
        assert spans["child"].span_id == child.span_id
        assert spans["child"].attrs == {"k": 1}
        assert spans["root"].dur >= spans["child"].dur >= 0

    def test_zero_footprint_inside_jax_trace(self):
        """Spans are host-only: a jitted fn using span() records nothing
        at trace time and lowers to the identical jaxpr."""
        obs.enable_tracing(max_spans=64)

        def with_span(x):
            with obs.span("in.trace"):
                y = x * 2.0
            obs.event("in.trace.event")
            return y + 1.0

        ja = jax.make_jaxpr(with_span)(1.0)
        jb = jax.make_jaxpr(lambda x: x * 2.0 + 1.0)(1.0)
        assert [e.primitive for e in ja.jaxpr.eqns] == \
            [e.primitive for e in jb.jaxpr.eqns]
        assert obs.snapshot_spans() == []

    def test_chrome_trace_export(self):
        obs.enable_tracing(max_spans=64)
        with obs.span("outer"):
            with obs.span("inner"):
                pass
        doc = obs.to_chrome_trace(obs.snapshot_spans(),
                                  process_names={os.getpid(): "me"})
        events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert [e["name"] for e in events] == ["outer", "inner"]
        assert all(e["ts"] > 1e15 for e in events)  # epoch micros
        names = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        assert any(e["name"] == "process_name" for e in names)

    def test_dump_trace_schema(self, tmp_path):
        obs.enable_tracing(max_spans=16)
        with obs.span("a"):
            pass
        path = str(tmp_path / "trace.json")
        doc = obs.dump_trace(path, process="tester")
        with open(path) as f:
            ondisk = json.load(f)
        assert ondisk["schema_version"] == obs_trace.TRACE_SCHEMA_VERSION
        assert ondisk["process"] == "tester"
        assert ondisk["spans"] == doc["spans"]
        assert len(ondisk["spans"]) == 1


# =====================================================================
# metrics registry + strict exposition
# =====================================================================
class TestMetricsRegistry:
    def test_counter_and_gauge(self):
        r = MetricsRegistry()
        c = r.counter("reqs_total", "requests", ("code",))
        c.inc(code="200")
        c.inc(2, code="500")
        assert c.value(code="200") == 1
        assert c.value(code="500") == 2
        with pytest.raises(ValueError):
            c.inc(-1, code="200")
        g = r.gauge("depth", "queue depth")
        g.set(5)
        g.inc(2)
        assert g.value() == 7

    def test_type_conflict_raises(self):
        r = MetricsRegistry()
        r.counter("x_total", "")
        with pytest.raises(ValueError):
            r.gauge("x_total", "")
        with pytest.raises(ValueError):
            r.counter("x_total", "", ("lbl",))

    def test_histogram_percentiles_log_buckets(self):
        r = MetricsRegistry()
        h = r.histogram("lat_seconds", "lat", buckets=log_buckets(1e-3, 8.0))
        for v in [0.002] * 50 + [0.1] * 45 + [4.0] * 5:
            h.observe(v)
        assert h.count() == 100
        assert 0.001 <= h.percentile(50) <= 0.004
        assert 0.05 <= h.percentile(95) <= 0.21
        assert h.percentile(99) >= 1.0

    def test_strict_parse_full_registry(self):
        r = MetricsRegistry()
        r.counter("a_total", 'with "quotes" and \\slash', ("l",)).inc(
            l='va"l\\ue')
        r.gauge("b", "gauge help").set(-1.5)
        h = r.histogram("c_seconds", "hist", ("op",),
                        buckets=log_buckets(1e-3, 1.0))
        h.observe(0.05, op="read")
        h.observe(2.0, op="read")  # lands in +Inf
        types, samples = parse_prometheus_strict(r.prometheus_text())
        assert types == {"a_total": "counter", "b": "gauge",
                         "c_seconds": "histogram"}
        assert samples["b"] == [((), -1.5)]
        (labels, v), = samples["a_total"]
        assert v == 1 and labels[0][0] == "l"
        count, = samples["c_seconds_count"]
        assert count[1] == 2

    def test_http_exporter_negotiation(self):
        import http.client

        r = MetricsRegistry()
        r.counter("hits_total", "hits").inc(3)
        srv = obs.start_http_exporter(r)
        try:
            host, port = srv.addr.rsplit(":", 1)
            c = http.client.HTTPConnection(host, int(port), timeout=5)
            c.request("GET", "/metrics")  # exporter default: prometheus
            resp = c.getresponse()
            body = resp.read().decode()
            assert "text/plain" in resp.getheader("Content-Type")
            parse_prometheus_strict(body)
            assert "hits_total 3" in body
            c.request("GET", "/metrics",
                      headers={"Accept": "application/json"})
            resp = c.getresponse()
            doc = json.loads(resp.read())
            assert doc["hits_total"]["values"] == 3
            c.close()
        finally:
            srv.stop()


# =====================================================================
# serving /metrics: Accept negotiation, JSON byte-compatibility
# =====================================================================
class TestServingMetricsEndpoint:
    def _scrape(self, addr, accept=None):
        import http.client

        host, port = addr.rsplit(":", 1)
        c = http.client.HTTPConnection(host, int(port), timeout=10)
        headers = {"Accept": accept} if accept else {}
        c.request("GET", "/metrics", headers=headers)
        r = c.getresponse()
        body = r.read()
        ctype = r.getheader("Content-Type")
        c.close()
        return ctype, body

    def test_json_default_stays_byte_compatible(self, model):
        from paddle_tpu.serving import ServingServer

        srv = ServingServer(_engine(model)).start()
        try:
            ctype, body = self._scrape(srv.addr)
            assert ctype == "application/json"
            snap = json.loads(body)
            # the r8/r11 consumer contract: these keys feed ServingClient
            # and the router's routing/drain decisions
            for key in ("requests", "tokens_generated", "queue_depth",
                        "in_admission", "slot_occupancy", "draining",
                        "compile_cache", "ttft_seconds"):
                assert key in snap, key
            # an explicit JSON Accept gets the same body
            _, body2 = self._scrape(srv.addr, accept="application/json")
            assert json.loads(body2).keys() == snap.keys()
        finally:
            srv.stop()

    def test_live_scrape_parses_strict(self, model):
        """Acceptance: Prometheus scrape of a LIVE serving server (mid-
        traffic) parses under the strict parser with live gauges."""
        from paddle_tpu.serving import ServingClient, ServingServer

        srv = ServingServer(_engine(model)).start()
        try:
            client = ServingClient(srv.addr)
            rid = client.submit([1, 2, 3], max_new_tokens=4)
            client.wait(rid, timeout=60)
            ctype, body = self._scrape(srv.addr, accept="text/plain")
            assert "text/plain" in ctype and "0.0.4" in ctype
            types, samples = parse_prometheus_strict(body.decode())
            assert types["serving_requests_submitted_total"] == "counter"
            assert types["serving_ttft_seconds"] == "histogram"
            assert samples["serving_requests_submitted_total"][0][1] == 1
            assert samples["serving_tokens_generated_total"][0][1] == 4
            assert samples["serving_slots_total"][0][1] == 2
            # TTFT histogram observed exactly one request
            assert samples["serving_ttft_seconds_count"][0][1] == 1
        finally:
            srv.stop()

    def test_router_endpoint_negotiates(self, model):
        import http.client

        from paddle_tpu.serving import ServingRouter, ServingServer

        srv = ServingServer(_engine(model)).start()
        router = ServingRouter([srv.addr], health_interval_s=0.1).start()
        try:
            router.check_health()
            addr = router.serve_metrics()
            host, port = addr.rsplit(":", 1)
            c = http.client.HTTPConnection(host, int(port), timeout=5)
            c.request("GET", "/metrics")
            snap = json.loads(c.getresponse().read())
            assert set(snap) == {"replicas", "failovers", "resubmits",
                                 "inflight_failures", "resurrections",
                                 "resurrected_tokens", "migrations",
                                 "migration_fallbacks"}
            c.request("GET", "/metrics", headers={"Accept": "text/plain"})
            types, samples = parse_prometheus_strict(
                c.getresponse().read().decode())
            assert types["router_breaker_state"] == "gauge"
            assert types["router_failovers_total"] == "counter"
            (labels, v), = samples["router_replica_up"]
            assert dict(labels)["replica"] == srv.addr and v == 1
            c.close()
        finally:
            router.stop()
            srv.stop()


# =====================================================================
# e2e trace propagation + merge CLI (acceptance)
# =====================================================================
class TestEndToEndTrace:
    def test_single_trace_id_with_well_formed_span_tree(self, model,
                                                        tmp_path):
        from paddle_tpu.serving import ServingRouter, ServingServer

        obs.enable_tracing(max_spans=4096)
        servers = [ServingServer(_engine(model)).start() for _ in range(2)]
        router = ServingRouter([s.addr for s in servers],
                               health_interval_s=0.1).start()
        try:
            router.check_health()
            rr = router.submit([1, 2, 3, 4], max_new_tokens=5)
            out = router.wait(rr, timeout=60)
            assert out["status"] == "done"
            assert rr.trace_id is not None
            mine = [s for s in obs.snapshot_spans()
                    if s.trace_id == rr.trace_id]
            by_name = {}
            for s in mine:
                by_name.setdefault(s.name, []).append(s)
            # ONE trace id stitches router + replica work
            assert set(by_name) == {"serving.route", "serving.queue_wait",
                                    "serving.prefill",
                                    "serving.decode_token"}
            route, = by_name["serving.route"]
            queue, = by_name["serving.queue_wait"]
            prefill, = by_name["serving.prefill"]
            decodes = by_name["serving.decode_token"]
            # tree: route ⊃ queue ⊃ prefill ⊃ decode tokens
            assert queue.parent_id == route.span_id
            assert prefill.parent_id == queue.span_id
            assert all(d.parent_id == prefill.span_id for d in decodes)
            # prefill samples token 0 in-graph; decode emits the rest
            assert len(decodes) == len(out["tokens"]) - 1
            assert sorted(d.attrs["token_index"] for d in decodes) == \
                list(range(1, len(out["tokens"])))
            assert prefill.attrs["bucket"] == 8
            assert route.attrs["replica"] == rr.replica_addr

            # merge CLI: split the ring into two per-"process" dumps (the
            # in-process harness shares one ring; a real deployment dumps
            # per process) and stitch them back into ONE timeline
            router_doc = obs.dump_trace(process="router")
            router_doc["spans"] = [s.to_dict() for s in mine
                                   if s.name == "serving.route"]
            replica_doc = {
                "schema_version": 1, "process": "replica", "pid":
                    os.getpid() + 1,
                "spans": [dict(s.to_dict(), pid=os.getpid() + 1)
                          for s in mine if s.name != "serving.route"],
            }
            pa, pb = tmp_path / "router.json", tmp_path / "replica.json"
            pa.write_text(json.dumps(router_doc))
            pb.write_text(json.dumps(replica_doc))
            out_path = tmp_path / "merged.json"
            res = subprocess.run(
                [sys.executable, "-m", "paddle_tpu.observability", "merge",
                 "-o", str(out_path), "--trace-id", rr.trace_id,
                 str(pa), str(pb)],
                capture_output=True, text=True,
                env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=120)
            assert res.returncode == 0, res.stderr
            merged = json.loads(out_path.read_text())
            events = [e for e in merged["traceEvents"] if e["ph"] == "X"]
            assert merged["metadata"]["n_spans"] == len(events) == len(mine)
            assert {e["pid"] for e in events} == {os.getpid(),
                                                 os.getpid() + 1}
            # one timeline: sorted by wall-clock ts across processes
            ts = [e["ts"] for e in events]
            assert ts == sorted(ts)
            assert all(e["args"]["trace_id"] == rr.trace_id
                       for e in events)
        finally:
            router.stop()
            for s in servers:
                s.kill()

    def test_direct_submit_mints_trace_locally(self, model):
        """Engine-only runs (no router) still get span trees: the Request
        mints its own id when tracing is armed."""
        from paddle_tpu.serving import Request

        obs.enable_tracing(max_spans=1024)
        eng = _engine(model)
        req = eng.submit(Request([1, 2, 3], max_new_tokens=3))
        assert req.trace_id is not None
        eng.run_until_idle(timeout=60)
        mine = [s for s in obs.snapshot_spans()
                if s.trace_id == req.trace_id]
        assert {"serving.queue_wait", "serving.prefill",
                "serving.decode_token"} <= {s.name for s in mine}


# =====================================================================
# flight recorder (acceptance: dumps name the final step + last spans)
# =====================================================================
class TestFlightRecorder:
    def test_dump_schema_and_file(self, tmp_path):
        obs.enable_tracing(max_spans=32)
        with obs.span("work.unit"):
            pass
        fr = obs_flight.FlightRecorder(directory=str(tmp_path),
                                       process="tester")
        fr.note(step=11, phase="train")
        doc = fr.dump("unit_test", extra={"k": "v"})
        assert doc["schema_version"] == obs.FLIGHT_SCHEMA_VERSION
        assert doc["step"] == 11
        assert doc["extra"] == {"k": "v"}
        assert any(s["name"] == "work.unit" for s in doc["spans"])
        assert fr.last_path and os.path.exists(fr.last_path)
        with open(fr.last_path) as f:
            assert json.load(f)["reason"] == "unit_test"

    def test_planted_sentinel_halt_dumps_last_spans_and_step(self):
        obs.enable_tracing(max_spans=256)
        tr = _tiny_trainer(SentinelConfig(warmup_steps=2, policy="halt",
                                          min_spike_delta=0.1))
        rng = np.random.default_rng(3)
        x = paddle.to_tensor(rng.standard_normal((8, 4)).astype("float32"))
        y = paddle.to_tensor(rng.standard_normal((8, 4)).astype("float32"))
        monitor = SentinelMonitor(tr._sentinel)
        for _ in range(3):
            tr.step(x, y)
            monitor.after_step(tr)
        xnan = paddle.to_tensor(np.full((8, 4), np.nan, "float32"))
        tr.step(xnan, y)  # the planted halt: step index 3
        with pytest.raises(AnomalyHalt):
            monitor.after_step(tr)
        doc = obs_flight.flight_recorder().last
        assert doc is not None and doc["reason"] == "sentinel_halt"
        assert doc["schema_version"] == obs.FLIGHT_SCHEMA_VERSION
        # the offending step is named...
        assert doc["step"] == 3
        assert doc["extra"]["last_code"] == 1  # SENTINEL_NONFINITE
        # ...and the last N spans (every train.step incl. the fatal one)
        steps = [s for s in doc["spans"] if s["name"] == "train.step"]
        assert [s["attrs"]["step"] for s in steps] == [0, 1, 2, 3]

    def test_engine_tick_failure_dumps(self, model, monkeypatch):
        from paddle_tpu.serving import Request

        obs.enable_tracing(max_spans=128)
        eng = _engine(model)
        req = eng.submit(Request([1, 2, 3], max_new_tokens=4))

        def boom():
            raise RuntimeError("planted tick fault")

        monkeypatch.setattr(eng, "step_once", boom)
        stop = threading.Event()
        t = threading.Thread(target=eng.serve_forever, args=(stop,),
                             daemon=True)
        t.start()
        assert req.wait(timeout=10)
        stop.set()
        t.join(10)
        assert req.state == Request.FAILED
        doc = obs_flight.flight_recorder().last
        assert doc is not None and doc["reason"] == "engine_tick_failure"
        assert "planted tick fault" in doc["extra"]["error"]
        # the dump freezes THIS engine's serving series, not just the
        # process registry
        serving_sections = [m for name, m in doc["metrics"].items()
                            if name.startswith("serving-")
                            and "serving_requests_submitted_total" in m]
        assert any(m["serving_requests_submitted_total"]["values"] == 1
                   for m in serving_sections)

    def test_sigterm_leaves_dump_naming_final_step(self, tmp_path):
        """Acceptance: a SIGTERM'd training run leaves a readable flight
        dump naming its final step (lands next to the checkpoints when no
        flight directory is configured)."""
        from paddle_tpu.framework.checkpoint import CheckpointManager
        from paddle_tpu.resilience import PreemptionGuard

        obs.enable_tracing(max_spans=64)
        with obs.span("train.step", step=7):
            pass
        mgr = CheckpointManager(str(tmp_path))
        guard = PreemptionGuard(mgr, exit_code=None,
                                signals=(signal.SIGTERM,))
        guard.install()
        try:
            guard.update(7, {"w": np.zeros(2)})
            os.kill(os.getpid(), signal.SIGTERM)
            assert guard.preempted and guard.saved_step == 7
        finally:
            guard.uninstall()
        dumps = [f for f in os.listdir(tmp_path)
                 if f.startswith("flight_preemption_signal_")]
        assert len(dumps) == 1
        with open(tmp_path / dumps[0]) as f:
            doc = json.load(f)
        assert doc["schema_version"] == obs.FLIGHT_SCHEMA_VERSION
        assert doc["step"] == 7                      # the final step
        assert doc["extra"]["saved_step"] == 7       # and it was saved
        assert any(s["name"] == "train.step" and s["attrs"]["step"] == 7
                   for s in doc["spans"])

    def test_replica_death_dumps_once(self, model):
        from paddle_tpu.serving import Request, ServingRouter, ServingServer

        engines = [_engine(model, max_seq_len=64) for _ in range(2)]
        # throttle decode so the generation is still in flight at the kill
        for eng in engines:
            orig = eng.step_once
            eng.step_once = (lambda o=orig: (time.sleep(0.05), o())[1])
        servers = [ServingServer(e).start() for e in engines]
        # slow health loop: the DEATH CONFIRMATION must come from the
        # request path (poll → transport error → probe), the hook's trigger
        router = ServingRouter([s.addr for s in servers],
                               health_interval_s=5.0,
                               request_timeout=2.0).start()
        try:
            router.check_health()
            # a long generation keeps the request IN FLIGHT when the
            # replica dies — polls then observe the death first-hand
            rr = router.submit([1, 2, 3], max_new_tokens=60)
            deadline = time.monotonic() + 30
            while not rr.tokens and time.monotonic() < deadline:
                router.poll(rr)
                time.sleep(0.01)
            assert rr.tokens, "generation never started"
            victim = rr.replica_addr
            fr = obs_flight.flight_recorder()
            seq_before = fr._seq
            next(s for s in servers if s.addr == victim).kill()
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and not rr.done:
                router.poll(rr)
                time.sleep(0.02)
            # r21: in-flight stream with observed tokens is RESURRECTED
            # on the survivor as a continuation join, not surfaced FAILED
            assert rr.state == Request.DONE
            assert rr.resurrections == 1
            assert rr.replica_addr != victim
            # exactly TWO dumps: one replica_death for the confirmed
            # death (not one per affected observation) and one
            # stream_resurrection for the re-homed stream
            assert fr._seq == seq_before + 2
            assert fr.last is not None
            assert fr.last["reason"] == "stream_resurrection"
            assert fr.last["extra"]["replica"] == victim
            # the router's breaker/failover series are in the dump
            assert any(name.startswith("router-")
                       and "router_breaker_state" in m
                       for name, m in fr.last["metrics"].items())
            seq_after_first = fr.last
            # a second observation of the settled request must NOT dump
            try:
                router.poll(rr)
            except Exception:
                pass
            assert obs_flight.flight_recorder().last is seq_after_first
            assert obs_flight.flight_recorder()._seq == seq_before + 2
        finally:
            router.stop()
            for s in servers:
                s.kill()


# =====================================================================
# live MFU + HBM-drift gauges on a real trainer step (acceptance)
# =====================================================================
class TestTrainerGauges:
    def test_mfu_and_hbm_gauges_populate(self):
        reg = MetricsRegistry()
        tr = _tiny_trainer(donate=False)
        tel = obs.TrainerTelemetry(tr, registry=reg, name="t0")
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.standard_normal((8, 4)).astype("float32"))
        y = paddle.to_tensor(rng.standard_normal((8, 4)).astype("float32"))
        tel.prime(x, y)
        assert tel.flops_per_step and tel.flops_per_step > 0
        assert tel.predicted_peak_bytes and tel.predicted_peak_bytes > 0
        for _ in range(3):
            tel.step(x, y)
        census = tel.refresh_hbm()
        rep = tel.report()
        assert rep["steps"] == 3
        # first gap is compile+dispatch and skipped — 2 observations
        assert reg.get("train_step_seconds").count(trainer="t0") == 2
        assert rep["mfu"] is not None and rep["mfu"] > 0
        assert rep["hbm_live_bytes"] and rep["hbm_live_bytes"] > 0
        assert np.isfinite(rep["hbm_drift_frac"])
        assert census["live_bytes"] > 0
        # the series are scrapeable
        types, samples = parse_prometheus_strict(reg.prometheus_text())
        assert types["train_mfu"] == "gauge"
        assert types["train_hbm_predicted_peak_bytes"] == "gauge"
        mfu, = samples["train_mfu"]
        assert dict(mfu[0])["trainer"] == "t0" and mfu[1] > 0

    def test_observe_step_direct(self):
        reg = MetricsRegistry()
        tr = _tiny_trainer(donate=False)
        tel = obs.TrainerTelemetry(tr, registry=reg, peak_flops=1e12,
                                   name="t1")
        tel.flops_per_step = 2e9
        tel.observe_step(0.01)  # 2e9 / (0.01 * 1e12) = 0.2
        assert reg.get("train_mfu").value(trainer="t1") == \
            pytest.approx(0.2)


# =====================================================================
# exemplars + OpenMetrics negotiation (r14)
# =====================================================================
class TestExemplarsAndOpenMetrics:
    def _two_registries(self):
        """Same observations into an exemplar-enabled and a plain
        registry — the byte-compatibility pair."""
        regs = []
        for ex in (True, False):
            r = MetricsRegistry()
            h = r.histogram("ttft_seconds", "ttft", buckets=[0.01, 0.1, 1.0],
                            exemplars=ex)
            h.observe(0.005, trace_id="trace-a")
            h.observe(0.5, trace_id="trace-b")
            h.observe(0.5, trace_id="trace-c")  # last exemplar wins
            r.counter("reqs_total", "requests").inc(3)
            regs.append(r)
        return regs

    def test_exemplars_bounded_one_per_bucket_last_wins(self):
        reg, _ = self._two_registries()
        ex = reg.get("ttft_seconds").exemplars()
        assert set(ex) == {"0.01", "1"}
        assert ex["0.01"]["trace_id"] == "trace-a"
        assert ex["1"]["trace_id"] == "trace-c"  # last observation kept
        assert ex["1"]["value"] == 0.5
        assert ex["1"]["ts"] > 0

    def test_prometheus_004_byte_identical_with_exemplars_enabled(self):
        with_ex, without_ex = self._two_registries()
        assert with_ex.prometheus_text() == without_ex.prometheus_text()
        # and the 0.0.4 body still parses strict, with no exemplar syntax
        types, _ = parse_prometheus_strict(with_ex.prometheus_text())
        assert "ttft_seconds" in types
        assert "# {" not in with_ex.prometheus_text()

    def test_openmetrics_exposition_carries_exemplars_and_eof(self):
        reg, _ = self._two_registries()
        om = reg.openmetrics_text()
        assert om.endswith("# EOF\n")
        assert '# {trace_id="trace-a"} 0.005' in om
        assert '# {trace_id="trace-c"} 0.5' in om
        # counter family per the OpenMetrics spec: TYPE names the family
        # (no _total), the sample keeps the _total suffix
        assert "# TYPE reqs counter" in om
        assert "\nreqs_total 3" in om
        # histogram series unchanged otherwise
        assert 'ttft_seconds_bucket{le="+Inf"} 3' in om

    def test_registry_json_byte_identical_unless_asked(self):
        """Review fix: to_dict() (the training exporter's JSON body) is
        byte-identical with exemplars on or off; dumps opt in."""
        with_ex, without_ex = self._two_registries()
        assert json.dumps(with_ex.to_dict()) == \
            json.dumps(without_ex.to_dict())
        asked = with_ex.to_dict(include_exemplars=True)
        assert asked["ttft_seconds"]["values"]["exemplars"]["0.01"][
            "trace_id"] == "trace-a"

    def test_ambient_trace_context_feeds_exemplar(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", "l", buckets=[1.0], exemplars=True)
        with obs_trace.trace_context("ctx-trace"):
            h.observe(0.5)
        h.observe(0.7)  # no context, no explicit id -> no exemplar update
        assert h.exemplars()["1"]["trace_id"] == "ctx-trace"
        assert h.exemplars()["1"]["value"] == 0.5

    def test_wants_openmetrics_is_explicit(self):
        from paddle_tpu.observability.metrics import wants_openmetrics

        assert wants_openmetrics("application/openmetrics-text")
        assert wants_openmetrics(
            "application/openmetrics-text; version=1.0.0")
        assert not wants_openmetrics("text/plain")
        assert not wants_openmetrics("*/*")
        assert not wants_openmetrics(None)
        # the pre-r14 wants_prometheus keeps matching openmetrics-ish
        # Accepts, so ordering (openmetrics checked first) is the contract
        assert wants_prometheus("application/openmetrics-text")

    def test_server_endpoint_negotiates_openmetrics(self, model):
        """A request with a trace id lands a TTFT exemplar; the OM scrape
        carries it, the 0.0.4 scrape is byte-identical to before and the
        JSON body is untouched (the ServingClient/router contract)."""
        import http.client

        from paddle_tpu.serving import ServingClient, ServingServer

        srv = ServingServer(_engine(model)).start()
        try:
            client = ServingClient(srv.addr)
            rid = client.submit([1, 2, 3], max_new_tokens=2,
                                trace_id="abcd1234deadbeef")
            client.wait(rid, timeout=60)

            def scrape(accept):
                host, port = srv.addr.rsplit(":", 1)
                c = http.client.HTTPConnection(host, int(port), timeout=10)
                c.request("GET", "/metrics",
                          headers={"Accept": accept} if accept else {})
                r = c.getresponse()
                body, ctype = r.read(), r.getheader("Content-Type")
                c.close()
                return ctype, body.decode()

            ctype, om = scrape("application/openmetrics-text")
            assert "application/openmetrics-text" in ctype
            assert om.endswith("# EOF\n")
            assert 'trace_id="abcd1234deadbeef"' in om
            ctype, prom = scrape("text/plain")
            assert "0.0.4" in ctype
            parse_prometheus_strict(prom)
            assert "# {" not in prom  # exemplars never leak into 0.0.4
            ctype, js = scrape(None)
            assert ctype == "application/json"
            assert "exemplars" not in json.loads(js)
        finally:
            srv.stop()

    def test_router_endpoint_negotiates_openmetrics(self, model):
        import http.client

        from paddle_tpu.serving import ServingRouter, ServingServer

        srv = ServingServer(_engine(model)).start()
        router = ServingRouter([srv.addr], health_interval_s=0.1).start()
        try:
            router.check_health()
            addr = router.serve_metrics()
            host, port = addr.rsplit(":", 1)
            c = http.client.HTTPConnection(host, int(port), timeout=5)
            c.request("GET", "/metrics",
                      headers={"Accept": "application/openmetrics-text"})
            r = c.getresponse()
            assert "application/openmetrics-text" in \
                r.getheader("Content-Type")
            body = r.read().decode()
            assert body.endswith("# EOF\n")
            assert "# TYPE router_replica_up gauge" in body
            c.close()
        finally:
            router.stop()
            srv.stop()


# =====================================================================
# metric dumps through the merge CLI (r14 satellite)
# =====================================================================
class TestMetricDumpMerge:
    def _metric_dump(self, tmp_path, name="metrics.json"):
        from paddle_tpu.observability.metrics import dump_metrics

        reg = MetricsRegistry()
        h = reg.histogram("ttft_seconds", "t", buckets=[0.01, 1.0],
                          exemplars=True)
        h.observe(0.005, trace_id="trace-x")
        h.observe(0.5, trace_id="trace-y")
        path = str(tmp_path / name)
        doc = dump_metrics(reg, path=path, process="replica-0")
        assert doc["schema_version"] == 1
        ex = doc["metrics"]["ttft_seconds"]["values"]["exemplars"]
        assert ex["0.01"]["trace_id"] == "trace-x"
        return path

    def test_merge_renders_exemplars_next_to_spans(self, tmp_path):
        from paddle_tpu.observability.merge import merge_files

        obs.enable_tracing(max_spans=64)
        with obs_trace.span("serving.route", trace_id="trace-x"):
            pass
        span_path = str(tmp_path / "trace.json")
        obs_trace.dump_trace(span_path, process="router")
        metric_path = self._metric_dump(tmp_path)
        doc = merge_files([span_path, metric_path])
        assert doc["metadata"]["n_spans"] == 1
        assert doc["metadata"]["n_exemplars"] == 2
        instants = [e for e in doc["traceEvents"] if e.get("ph") == "i"]
        assert len(instants) == 2
        names = {e["name"] for e in instants}
        assert any("ttft_seconds_bucket[le=" in n for n in names)
        assert {e["args"]["trace_id"] for e in instants} == \
            {"trace-x", "trace-y"}
        # --trace-id filters spans AND exemplars to one request
        doc = merge_files([span_path, metric_path], trace_id="trace-x")
        assert doc["metadata"]["n_spans"] == 1
        assert doc["metadata"]["n_exemplars"] == 1

    def test_merge_accepts_flight_dump_metric_sections(self, tmp_path):
        from paddle_tpu.observability.merge import merge_dumps

        doc = merge_dumps([{
            "pid": 7, "process": "engine", "spans": [],
            "metrics": {"serving-1": {
                "lat_seconds": {"type": "histogram", "help": "",
                                "values": {"count": 1, "sum": 0.5,
                                           "exemplars": {"1": {
                                               "trace_id": "t", "value": 0.5,
                                               "ts": 1.0}}}}}}}])
        assert doc["metadata"]["n_exemplars"] == 1
        (ev,) = [e for e in doc["traceEvents"] if e.get("ph") == "i"]
        assert ev["name"].startswith("serving-1/")

    def test_non_dump_errors_instead_of_silently_ignoring(self, tmp_path):
        from paddle_tpu.observability.__main__ import main as obs_main
        from paddle_tpu.observability.merge import load_dump

        bogus = tmp_path / "bogus.json"
        bogus.write_text(json.dumps({"foo": 1}))
        with pytest.raises(ValueError, match="no 'spans' or 'metrics'"):
            load_dump(str(bogus))
        assert obs_main(["merge", str(bogus)]) == 2


# =====================================================================
# recompile-aware MFU pricing (r14 satellite fix)
# =====================================================================
class TestTelemetryReprice:
    def test_reshaped_batch_reprices_instead_of_stale_flops(self):
        reg = MetricsRegistry()
        tr = _tiny_trainer(donate=False)
        tel = obs.TrainerTelemetry(tr, registry=reg, name="rp")
        rng = np.random.default_rng(0)
        x8 = paddle.to_tensor(rng.standard_normal((8, 4)).astype("float32"))
        x2 = paddle.to_tensor(rng.standard_normal((2, 4)).astype("float32"))
        tel.prime(x8, x8)
        f8 = tel.flops_per_step
        assert f8 and f8 > 0
        tel.step(x8, x8)            # first step: compile, observation skipped
        tel.step(x8, x8)            # steady state: observed, no reprice
        assert tel.reprices == 0
        assert reg.get("train_step_seconds").count(trainer="rp") == 1
        tel.step(x2, x2)            # reshaped batch -> jit cache miss
        assert tel.reprices == 1
        assert tel.reprice_errors == 0
        f2 = tel.flops_per_step
        assert f2 and f2 < f8       # re-priced for the SMALLER batch
        # the recompiled step's wall time (trace+compile) is NOT observed
        assert reg.get("train_step_seconds").count(trainer="rp") == 1
        assert reg.get("train_telemetry_reprices_total").value(
            trainer="rp") == 1
        tel.step(x2, x2)            # steady again: observed at new shape
        assert tel.reprices == 1
        assert reg.get("train_step_seconds").count(trainer="rp") == 2
        assert tel.report()["reprices"] == 1

    def test_reprice_restamps_return_clock(self):
        """Review fix: the reprice (re-trace + liveness estimate) runs
        AFTER the step's return timestamp — the next step's return-to-
        return gap must not absorb the pricing wall time."""
        reg = MetricsRegistry()
        tr = _tiny_trainer(donate=False)
        tel = obs.TrainerTelemetry(tr, registry=reg, name="rpt")
        rng = np.random.default_rng(0)
        x8 = paddle.to_tensor(rng.standard_normal((8, 4)).astype("float32"))
        x2 = paddle.to_tensor(rng.standard_normal((2, 4)).astype("float32"))
        tel.prime(x8, x8)
        tel.step(x8, x8)
        marker = {}
        orig_prime = tel.prime

        def marking_prime(xx, yy):
            out = orig_prime(xx, yy)
            marker["end"] = time.perf_counter()
            return out

        tel.prime = marking_prime
        tel.step(x2, x2)            # reshaped -> reprice fires
        assert "end" in marker
        # the return clock was re-stamped AFTER the pricing finished
        assert tel._last_return >= marker["end"]

    def test_failed_reprice_retries_at_most_once_per_compile(self):
        """Review fix: a rebuilt trainer whose pricing RAISES must not
        re-run the full-trace prime on every subsequent step, and step
        observation must resume (stale-but-live gauges + counted error)."""
        reg = MetricsRegistry()
        tr = _tiny_trainer(donate=False)
        tel = obs.TrainerTelemetry(tr, registry=reg, name="rpf")
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.standard_normal((8, 4)).astype("float32"))
        tel.prime(x, x)
        tel.step(x, x)              # first: compile, skipped
        tel.step(x, x)              # observed
        assert reg.get("train_step_seconds").count(trainer="rpf") == 1
        tr._build()                 # rebuild: wholly new jit identity
        calls = {"n": 0}

        def boom(xx, yy):
            calls["n"] += 1
            raise RuntimeError("pricing broke")

        tel.prime = boom
        tel.step(x, x)              # rebuilt -> reprice attempt fails ONCE
        assert calls["n"] == 1
        assert tel.reprice_errors == 1
        tel.step(x, x)              # no retry storm; observation resumes
        tel.step(x, x)
        assert calls["n"] == 1
        assert tel.reprice_errors == 1
        assert reg.get("train_step_seconds").count(trainer="rpf") == 3

    def test_mfu_uses_repriced_flops(self):
        reg = MetricsRegistry()
        tr = _tiny_trainer(donate=False)
        tel = obs.TrainerTelemetry(tr, registry=reg, peak_flops=1e12,
                                   name="rp2")
        rng = np.random.default_rng(0)
        x8 = paddle.to_tensor(rng.standard_normal((8, 4)).astype("float32"))
        x2 = paddle.to_tensor(rng.standard_normal((2, 4)).astype("float32"))
        tel.prime(x8, x8)
        tel.step(x8, x8)
        tel.step(x2, x2)            # repriced here
        f2 = tel.flops_per_step
        tel.observe_step(0.01)
        assert reg.get("train_mfu").value(trainer="rp2") == \
            pytest.approx(f2 / (0.01 * 1e12))


# =====================================================================
# jaxpr identity: tracing enabled vs disabled (r6 bar, extended)
# =====================================================================
class TestTracingJaxprIdentity:
    def test_trainer_step_jaxpr_identical(self):
        def jaxpr_of():
            tr = _tiny_trainer(donate=False)
            tr._build()
            xb = jnp.zeros((8, 4), jnp.float32)
            key = jax.random.key(0)
            lr = jnp.asarray(0.01, jnp.float32)
            return str(jax.make_jaxpr(tr._jit_step)(
                tr.params, tr.opt_state, tr.buffers, xb, xb, key,
                tr.scale_state, tr.sentinel_state, lr))

        obs.disable_tracing()
        plain = jaxpr_of()
        obs.enable_tracing()
        traced = jaxpr_of()
        assert plain == traced

    def test_pipeline_step_jaxpr_identical(self):
        from paddle_tpu.distributed.env import clear_mesh, init_mesh
        from paddle_tpu.distributed.meta_parallel.pipeline_schedule import (
            build_gpt_pipeline_step,
        )
        from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
        from paddle_tpu.optimizer.optimizers import AdamW

        def jaxpr_of():
            cfg = gpt_config("gpt2-small", vocab_size=64, hidden_size=32,
                             num_layers=2, num_attention_heads=4,
                             max_position_embeddings=32,
                             hidden_dropout_prob=0.0,
                             attention_dropout_prob=0.0)
            paddle.seed(0)
            clear_mesh()
            init_mesh({"pp": 1})
            model = GPTForPretraining(cfg)
            opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
            s = build_gpt_pipeline_step(model, opt, microbatches=2)
            rng = np.random.default_rng(0)
            ids = jnp.asarray(rng.integers(0, 64, (4, 16)).astype("int32"))
            kd = jax.random.key_data(jax.random.key(0))
            lr = jnp.asarray(1e-3, jnp.float32)
            return str(jax.make_jaxpr(s.jitted)(
                s.state["params"], s.state["opt"], ids, ids, kd, lr,
                s.state["sentinel"]))

        obs.disable_tracing()
        plain = jaxpr_of()
        obs.enable_tracing()
        traced = jaxpr_of()
        assert plain == traced

    def test_scope_with_tracing_enabled_keeps_jaxpr(self):
        """The r6 scope/TimerRegistry fix: profiler scopes inside a jit
        trace stay pure HLO metadata even with tracing + timers armed."""
        from paddle_tpu import profiler

        obs.enable_tracing()
        profiler.enable_timers()
        try:
            def with_scopes(x):
                with profiler.scope("a"):
                    return x * 2.0

            ja = jax.make_jaxpr(with_scopes)(1.0)
            jb = jax.make_jaxpr(lambda x: x * 2.0)(1.0)
            assert [e.primitive for e in ja.jaxpr.eqns] == \
                [e.primitive for e in jb.jaxpr.eqns]
            # and no host span leaked out of the trace
            assert obs.snapshot_spans() == []
        finally:
            profiler.disable_timers()

    def test_scope_emits_spans_outside_trace(self):
        from paddle_tpu import profiler

        obs.enable_tracing(max_spans=16)
        with profiler.scope("host.region"):
            time.sleep(0.001)
        spans = obs.snapshot_spans()
        assert [s.name for s in spans] == ["host.region"]
        assert spans[0].dur >= 0.001
