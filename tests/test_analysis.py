"""paddle_tpu.analysis — the jaxpr/HLO static-analysis layer (ISSUE 4).

Per-rule contract: one minimal synthetic program that triggers exactly that
rule, plus a clean program with zero findings.  Runtime half: TraceGuard
recompile attribution.  Integration: the shipped entry points must lint
HIGH-clean (the CI gate the satellite fixes established), and findings must
carry r6 profiler scope names as source attribution.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import analysis as an
from paddle_tpu.analysis import (
    AnalysisTarget,
    AnalysisWarning,
    CollectiveOrderRule,
    ConstantBloatRule,
    DonationRule,
    DtypePromotionRule,
    HostSyncRule,
    ProgramRule,
    RecompileHazardRule,
    Severity,
    ShardingPropagationRule,
    TraceGuard,
)


def _sev(findings, severity):
    return [f for f in findings if f.severity == severity]


def _mesh2x2():
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    return Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("x", "y"))


# ---------------------------------------------------------------------------
# dtype-promotion
# ---------------------------------------------------------------------------
class TestDtypePromotion:
    def test_bf16_upcast_fed_dot_flagged(self):
        def f(x, w):
            h = jnp.dot(x, w)  # legitimate bf16 matmul
            return jnp.dot(h.astype(jnp.float32),
                           w.astype(jnp.float32)).sum()

        t = AnalysisTarget("t", f, (jnp.ones((8, 8), jnp.bfloat16),
                                    jnp.ones((8, 8), jnp.bfloat16)))
        fs = an.run_rules(t, [DtypePromotionRule()])
        assert _sev(fs, Severity.HIGH), fs
        assert "upcast" in _sev(fs, Severity.HIGH)[0].message

    def test_clean_bf16_program(self):
        def f(x, w):
            return jnp.dot(x, w).astype(jnp.float32).sum()  # f32 loss is fine

        t = AnalysisTarget("t", f, (jnp.ones((8, 8), jnp.bfloat16),
                                    jnp.ones((8, 8), jnp.bfloat16)))
        assert an.run_rules(t, [DtypePromotionRule()]) == []

    def test_incidental_half_dot_does_not_flood_f32_program(self):
        """One bf16 matmul in a mostly-f32 program is not an amp program:
        the 'predominantly half-precision' MEDIUM needs a majority."""
        def f(x, w, hx, hw):
            y = jnp.dot(x, w)
            y = jnp.dot(y, w)
            y = jnp.dot(y, w)
            return y.sum() + jnp.dot(hx, hw).sum().astype(jnp.float32)

        t = AnalysisTarget("t", f, (jnp.ones((8, 8), jnp.float32),
                                    jnp.ones((8, 8), jnp.float32),
                                    jnp.ones((8, 8), jnp.bfloat16),
                                    jnp.ones((8, 8), jnp.bfloat16)))
        assert an.run_rules(t, [DtypePromotionRule()]) == []

    def test_scope_attribution_from_profiler(self):
        """Findings carry the r6 profiler scope names (HLO metadata)."""
        from paddle_tpu.profiler.scope import scope

        def f(x, w):
            with scope("model.head"):
                return jnp.dot(x.astype(jnp.float32),
                               w.astype(jnp.float32)).sum() \
                    + jnp.dot(x, w).sum().astype(jnp.float32)

        t = AnalysisTarget("t", f, (jnp.ones((8, 8), jnp.bfloat16),
                                    jnp.ones((8, 8), jnp.bfloat16)))
        highs = _sev(an.run_rules(t, [DtypePromotionRule()]), Severity.HIGH)
        assert highs and "model.head" in highs[0].scope


# ---------------------------------------------------------------------------
# constant-bloat
# ---------------------------------------------------------------------------
class TestConstantBloat:
    def test_closure_captured_weight_flagged(self):
        W = jnp.zeros((256, 256), jnp.float32)  # 256 KiB baked in

        t = AnalysisTarget("t", jax.jit(lambda x: x @ W),
                           (jnp.ones((4, 256), jnp.float32),))
        fs = an.run_rules(t, [ConstantBloatRule()])
        assert _sev(fs, Severity.HIGH), fs
        assert fs[0].details["bytes"] == 256 * 256 * 4

    def test_weight_as_argument_clean(self):
        t = AnalysisTarget("t", jax.jit(lambda x, w: x @ w),
                           (jnp.ones((4, 256), jnp.float32),
                            jnp.zeros((256, 256), jnp.float32)))
        assert an.run_rules(t, [ConstantBloatRule()]) == []


# ---------------------------------------------------------------------------
# donation-miss
# ---------------------------------------------------------------------------
class TestDonation:
    def test_carried_state_not_donated_flagged(self):
        s = jnp.zeros((1024,), jnp.float32)  # 4 KiB carried state
        f = jax.jit(lambda st, x: (st + x, x.sum()))
        fs = an.run_rules(AnalysisTarget("t", f, (s, s)), [DonationRule()])
        highs = _sev(fs, Severity.HIGH)
        assert highs and "args[0]" in highs[0].details["arg"]

    def test_donated_clean(self):
        s = jnp.zeros((1024,), jnp.float32)
        f = jax.jit(lambda st, x: (st + x, x.sum()), donate_argnums=(0,))
        assert an.run_rules(AnalysisTarget("t", f, (s, s)),
                            [DonationRule()]) == []

    def test_donated_but_unmatched_flagged(self):
        s = jnp.zeros((1024,), jnp.float32)
        f = jax.jit(lambda st: st.sum(), donate_argnums=(0,))
        fs = an.run_rules(AnalysisTarget("t", f, (s,)), [DonationRule()])
        assert _sev(fs, Severity.MEDIUM), fs

    def test_intended_donation_override(self):
        """donate_argnums metadata lints the TPU deployment contract even
        when the live jit gated donation off (serving on CPU)."""
        s = jnp.zeros((1024,), jnp.float32)
        f = jax.jit(lambda st, x: (st + x, x.sum()))  # no actual donation
        t = AnalysisTarget("t", f, (s, s), donate_argnums=(0,))
        assert an.run_rules(t, [DonationRule()]) == []


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------
class TestHostSync:
    def test_pure_callback_flagged_high(self):
        def f(x):
            return jax.pure_callback(
                lambda a: np.asarray(a) + 1,
                jax.ShapeDtypeStruct((3,), jnp.float32), x)

        fs = an.run_rules(AnalysisTarget("t", jax.jit(f), (jnp.ones(3),)),
                          [HostSyncRule()])
        assert _sev(fs, Severity.HIGH), fs

    def test_debug_callback_flagged_medium(self):
        def f(x):
            jax.debug.print("x={x}", x=x.sum())
            return x * 2

        fs = an.run_rules(AnalysisTarget("t", jax.jit(f), (jnp.ones(3),)),
                          [HostSyncRule()])
        assert _sev(fs, Severity.MEDIUM), fs

    def test_clean(self):
        fs = an.run_rules(
            AnalysisTarget("t", jax.jit(lambda x: x * 2), (jnp.ones(3),)),
            [HostSyncRule()])
        assert fs == []


# ---------------------------------------------------------------------------
# recompile-hazard (static half)
# ---------------------------------------------------------------------------
class TestRecompileHazard:
    def test_weak_typed_arg_flagged(self):
        t = AnalysisTarget("t", jax.jit(lambda x, s: x * s),
                           (jnp.ones(3), 2.0))
        fs = an.run_rules(t, [RecompileHazardRule()])
        assert _sev(fs, Severity.LOW) and "args[1]" in fs[0].details["arg"]

    def test_explicit_arrays_clean(self):
        t = AnalysisTarget("t", jax.jit(lambda x, s: x * s),
                           (jnp.ones(3), jnp.asarray(2.0, jnp.float32)))
        assert an.run_rules(t, [RecompileHazardRule()]) == []


# ---------------------------------------------------------------------------
# collective-order (static deadlock/divergence detector)
# ---------------------------------------------------------------------------
class TestCollectiveOrder:
    def test_rank_varying_pred_gating_collective_flagged(self):
        from paddle_tpu.distributed.spmd import shard_map

        mesh = _mesh2x2()

        def inner(a):
            r = lax.axis_index("x")
            return lax.cond(r == 0, lambda v: lax.psum(v, "x"),
                            lambda v: v, a)

        sm = shard_map(inner, mesh=mesh, in_specs=P("x"), out_specs=P("x"))
        fs = an.run_rules(AnalysisTarget("t", sm, (jnp.ones(8),)),
                          [CollectiveOrderRule()])
        highs = _sev(fs, Severity.HIGH)
        assert highs and highs[0].details["axes"] == ["x"]

    def test_reduced_pred_proven_uniform(self):
        """A psum'd predicate (the r7 sentinel pattern) is provably uniform
        along the gated collective's axis — no finding."""
        from paddle_tpu.distributed.spmd import shard_map

        mesh = _mesh2x2()

        def inner(a):
            s = lax.psum(a.sum(), "x")
            return lax.cond(s > 0, lambda v: lax.psum(v, "x"),
                            lambda v: v, a)

        sm = shard_map(inner, mesh=mesh, in_specs=P("x"), out_specs=P("x"))
        assert an.run_rules(AnalysisTarget("t", sm, (jnp.ones(8),)),
                            [CollectiveOrderRule()]) == []

    def test_disjoint_axis_pred_safe(self):
        """Pred varying over 'y' gating a psum over 'x': every 'x' peer
        group shares the predicate — safe (the pipeline head pattern:
        stage-index cond gating mp collectives)."""
        from paddle_tpu.distributed.spmd import shard_map

        mesh = _mesh2x2()

        def inner(a):
            r = lax.axis_index("y")
            return lax.cond(r == 0, lambda v: lax.psum(v, "x"),
                            lambda v: v, a)

        sm = shard_map(inner, mesh=mesh, in_specs=P(), out_specs=P(None),
                       )
        assert an.run_rules(AnalysisTarget("t", sm, (jnp.ones(8),)),
                            [CollectiveOrderRule()]) == []

    def test_carry_written_divergence_found_by_fixpoint(self):
        """The body writes axis_index into the carry slot the predicate
        reads: only a taint FIXPOINT over the loop carry sees the
        rank-divergent trip count (single-pass propagation misses it)."""
        from paddle_tpu.distributed.spmd import shard_map

        mesh = _mesh2x2()

        def inner(a):
            def body(c):
                i, v = c
                return (lax.axis_index("x").astype(jnp.int32),
                        lax.psum(v, "x"))

            return lax.while_loop(lambda c: c[0] < 1, body,
                                  (jnp.int32(0), a))[1]

        sm = shard_map(inner, mesh=mesh, in_specs=P(), out_specs=P(None))
        fs = an.run_rules(AnalysisTarget("t", sm, (jnp.ones(8),)),
                          [CollectiveOrderRule()])
        assert _sev(fs, Severity.HIGH), fs

    def test_shard_map_inside_while_body_taints_carry(self):
        """The fixpoint pre-pass must apply shard_map in_names taints: a
        while whose carry is fed by a shard_map over sharded data has a
        rank-divergent trip count around the body's psum."""
        from paddle_tpu.distributed.spmd import shard_map

        mesh = _mesh2x2()
        inner = shard_map(lambda v: (v + lax.axis_index("x"),
                                     lax.psum(v, "x")),
                          mesh=mesh, in_specs=P("x"),
                          out_specs=(P("x"), P("x")))

        def f(a):
            def body(c):
                i, v = inner(c[1])
                return (c[0] + i.sum().astype(jnp.float32), v)

            return lax.while_loop(lambda c: c[0] < 10.0, body,
                                  (jnp.float32(0), a))[1]

        fs = an.run_rules(AnalysisTarget("t", f, (jnp.ones(8),)),
                          [CollectiveOrderRule()])
        assert _sev(fs, Severity.HIGH), fs

    def test_nonuniform_while_trip_count_flagged(self):
        from paddle_tpu.distributed.spmd import shard_map

        mesh = _mesh2x2()

        def inner(a):
            r = lax.axis_index("x")

            def body(c):
                return (c[0] + 1, lax.psum(c[1], "x"))

            return lax.while_loop(lambda c: c[0] < r, body,
                                  (jnp.int32(0), a))[1]

        sm = shard_map(inner, mesh=mesh, in_specs=P("x"), out_specs=P("x"))
        fs = an.run_rules(AnalysisTarget("t", sm, (jnp.ones(8),)),
                          [CollectiveOrderRule()])
        assert _sev(fs, Severity.HIGH), fs


# ---------------------------------------------------------------------------
# sharding-propagation (StableHLO surface)
# ---------------------------------------------------------------------------
class TestShardingPropagation:
    def test_replicated_spmd_entry_flagged(self):
        t = AnalysisTarget("t", jax.jit(lambda x: x * 2), (jnp.ones(8),),
                           tags=("spmd",))
        fs = an.run_rules(t, [ShardingPropagationRule()])
        assert _sev(fs, Severity.MEDIUM), fs

    def test_sharded_entry_clean(self):
        from jax.sharding import NamedSharding

        mesh = _mesh2x2()
        sh = NamedSharding(mesh, P("x"))
        f = jax.jit(lambda x: x * 2, in_shardings=(sh,), out_shardings=sh)
        t = AnalysisTarget("t", f, (jax.device_put(jnp.ones(8), sh),),
                           tags=("spmd",))
        assert an.run_rules(t, [ShardingPropagationRule()]) == []

    def test_untagged_target_skipped(self):
        t = AnalysisTarget("t", jax.jit(lambda x: x * 2), (jnp.ones(8),))
        assert an.run_rules(t, [ShardingPropagationRule()]) == []


# ---------------------------------------------------------------------------
# program-check (static.Program op-record IR)
# ---------------------------------------------------------------------------
class TestProgramRule:
    def _clean(self):
        paddle.disable_static()

    def test_dead_feed_flagged(self):
        from paddle_tpu import static

        paddle.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main, static.Program()):
                x = static.data("x", [None, 4], "float32")
                static.data("unused", [None, 2], "float32")
                y = x * 2.0 + 1.0
            t = an.target_from_program(main, name="p")
            fs = an.run_rules(t, [ProgramRule()])
            lows = _sev(fs, Severity.LOW)
            assert lows and lows[0].details["feed"] == "unused"
        finally:
            self._clean()

    def test_frozen_trainable_capture_flagged(self):
        from paddle_tpu import static
        from paddle_tpu.nn import Linear
        from paddle_tpu.optimizer.optimizers import SGD

        paddle.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main, static.Program()):
                x = static.data("x", [None, 4], "float32")
                a = Linear(4, 4)
                b = Linear(4, 1)
                loss = b(a(x)).mean()
                # only b's params handed to the optimizer: a is frozen by
                # accident
                SGD(learning_rate=0.1,
                    parameters=b.parameters()).minimize(loss)
            t = an.target_from_program(main, name="p")
            fs = an.run_rules(t, [ProgramRule()])
            assert _sev(fs, Severity.MEDIUM), fs
        finally:
            self._clean()

    def test_clean_training_program_and_jaxpr_rules_apply(self):
        from paddle_tpu import static
        from paddle_tpu.nn import Linear
        from paddle_tpu.optimizer.optimizers import SGD

        paddle.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main, static.Program()):
                x = static.data("x", [None, 4], "float32")
                tgt = static.data("t", [None, 1], "float32")
                lin = Linear(4, 1)
                loss = ((lin(x) - tgt) ** 2).mean()
                SGD(learning_rate=0.1,
                    parameters=lin.parameters()).minimize(loss)
            t = an.target_from_program(main, name="p")
            assert an.run_rules(t, [ProgramRule()]) == []
            # the op-record IR flows through the full jaxpr rule set too
            assert t.graph().nodes
            assert an.run_rules(t, [HostSyncRule(),
                                    DtypePromotionRule()]) == []
        finally:
            self._clean()


# ---------------------------------------------------------------------------
# TraceGuard (runtime recompile attribution)
# ---------------------------------------------------------------------------
class TestTraceGuard:
    def test_no_events_on_stable_signature(self):
        g = TraceGuard(jax.jit(lambda x: x * 2))
        for _ in range(3):
            g(jnp.ones(3))
        assert g.events == [] and g.calls == 3

    def test_recompile_attributed_to_component(self):
        g = TraceGuard(jax.jit(lambda d: d["a"] * d["b"]), name="step")
        g({"a": jnp.ones(3), "b": jnp.ones(3)})
        g({"a": jnp.ones(3), "b": jnp.ones(3)})        # cache hit
        g({"a": jnp.ones(4), "b": jnp.ones(4)})        # miss: shape
        assert len(g.events) == 1
        comps = {d["component"] for d in g.events[0].diffs}
        assert comps == {"args[0]['a']", "args[0]['b']"}
        fs = g.findings()
        assert fs and fs[0].rule == "recompile-hazard"
        assert fs[0].severity == Severity.MEDIUM

    def test_repeated_recompiles_escalate_high(self):
        g = TraceGuard(jax.jit(lambda x: x * 2), max_compiles=2)
        for n in (3, 4, 5, 6):
            g(jnp.ones(n))
        assert any(f.severity == Severity.HIGH for f in g.findings())

    def test_weak_type_flip_attributed(self):
        g = TraceGuard(jax.jit(lambda x, s: x * s), name="step")
        g(jnp.ones(3), 2.0)
        g(jnp.ones(3), jnp.asarray(2.0, jnp.float32))  # weak -> strong
        assert len(g.events) == 1
        assert any("args[1]" in d["component"] for d in g.events[0].diffs)


# ---------------------------------------------------------------------------
# dy2static strictness (satellite: AnalysisWarning instead of silent fallback)
# ---------------------------------------------------------------------------
class TestDy2StaticStrictness:
    def test_global_write_warns_and_falls_back(self):
        from paddle_tpu.jit.dy2static import convert_function

        def f(x):
            global _some_counter
            _some_counter = 1
            if x.sum() > 0:
                return x + 1.0
            return x

        with pytest.warns(AnalysisWarning) as rec:
            g = convert_function(f)
        assert g is f  # fell back to tracing
        w = rec[0].message
        assert w.finding.rule == "dy2static-strictness"
        assert "_some_counter" in str(w)

    def test_closure_mutation_in_branch_warns_and_falls_back(self):
        """Mutation INSIDE converted control flow double-applies (probe +
        trace) — refused with a warning."""
        from paddle_tpu.jit.dy2static import convert_function

        seen = []

        def f(x):
            if x.sum() > 0:
                seen.append(x)
                return x + 1.0
            return x

        with pytest.warns(AnalysisWarning) as rec:
            g = convert_function(f)
        assert g is f
        assert "seen" in str(rec[0].message)

    def test_straight_line_closure_mutation_still_converts(self):
        """Top-level closure mutation executes once per trace exactly as
        plain tracing would — conversion must not be refused for it."""
        import warnings as _w

        from paddle_tpu.jit.dy2static import convert_function

        d = {}

        def f(x):
            d["calls"] = 1
            if x.sum() > 0:
                return x + 1.0
            return x - 1.0

        with _w.catch_warnings():
            _w.simplefilter("error", AnalysisWarning)
            g = convert_function(f)
        assert g is not f
        out = g(paddle.to_tensor(np.asarray([2.0], np.float32)))
        np.testing.assert_allclose(np.asarray(out._data), [3.0])
        assert d == {"calls": 1}

    def test_nonlocal_write_warns(self):
        from paddle_tpu.jit.dy2static import convert_function

        def outer():
            state = 0

            def f(x):
                nonlocal state
                state = 1
                if x.sum() > 0:
                    return x + 1.0
                return x

            return f

        with pytest.warns(AnalysisWarning):
            g = convert_function(outer())
        assert g.__name__ == "f"

    def test_internal_nonlocal_still_converts(self):
        """A nonlocal binding a cell INTERNAL to the decorated function is
        safe (the whole function converts together) — no warning, and the
        tensor-dependent control flow still lowers."""
        import warnings as _w

        from paddle_tpu.jit.dy2static import convert_function

        def f(x):
            acc = x * 0.0

            def add(v):
                nonlocal acc
                acc = acc + v

            add(x)
            if x.sum() > 0:
                return acc + 1.0
            return acc - 1.0

        with _w.catch_warnings():
            _w.simplefilter("error", AnalysisWarning)
            g = convert_function(f)
        assert g is not f
        out = g(paddle.to_tensor(np.asarray([2.0], np.float32)))
        np.testing.assert_allclose(np.asarray(out._data), [3.0])

    def test_clean_function_converts_without_warning(self):
        import warnings as _w

        from paddle_tpu.jit.dy2static import convert_function

        def f(x):
            if x.sum() > 0:
                return x + 1.0
            return x - 1.0

        with _w.catch_warnings():
            _w.simplefilter("error", AnalysisWarning)
            g = convert_function(f)
        assert g is not f  # converted
        out = g(paddle.to_tensor(np.asarray([2.0], np.float32)))
        np.testing.assert_allclose(np.asarray(out._data), [3.0])


# ---------------------------------------------------------------------------
# satellite donation fixes: regressions
# ---------------------------------------------------------------------------
class TestTrainerDonationSafety:
    def test_model_buffers_survive_donated_step(self):
        """Donating the buffer carry must not delete the model Layer's own
        arrays: device_put can alias on a 1-device mesh, and the jitted
        step would consume the Tensor's _data (regression for the r9
        donation fix)."""
        import paddle_tpu.distributed as dist
        import paddle_tpu.optimizer as popt
        from paddle_tpu.nn import BatchNorm1D, Linear, ReLU, Sequential

        prev = dist.get_mesh()
        dist.init_mesh({"dp": 1})
        try:
            paddle.seed(0)
            model = Sequential(Linear(8, 16), BatchNorm1D(16), ReLU(),
                               Linear(16, 1))
            tr = dist.ParallelTrainer(
                model, lambda o, y: ((o - y) ** 2).mean(), popt.SGD(0.01),
                dp_axis=None)
            X = np.zeros((4, 8), np.float32)
            Y = np.zeros((4, 1), np.float32)
            tr.step(paddle.to_tensor(X), paddle.to_tensor(Y))
            # the model's own tensors must still be readable (no deleted
            # buffers), and an eager forward must work
            for _, b in model.named_buffers():
                np.asarray(b._data)
            for _, p in model.named_parameters():
                np.asarray(p._data)
            model.eval()
            model(paddle.to_tensor(X))
        finally:
            dist.set_mesh(prev)


# ---------------------------------------------------------------------------
# shipped entry points: the CI gate (tier-1 smoke)
# ---------------------------------------------------------------------------
class TestShippedEntryPoints:
    def test_zero_high_findings_across_entry_points(self):
        """ISSUE 4 acceptance: >= 5 shipped entry points lint HIGH-clean
        after the satellite fixes (trainer/serving donation, CE head)."""
        from paddle_tpu.analysis.entrypoints import shipped_entry_points
        from paddle_tpu.analysis.rules import analyze_targets

        targets, errors = shipped_entry_points()
        assert errors == {}
        assert len(targets) >= 5
        names = {t.name for t in targets}
        assert {"trainer_step", "pipeline_step", "serving_prefill",
                "serving_decode", "exported_infer",
                "static_program"} <= names
        report = analyze_targets(targets)
        highs = report.high()
        assert highs == [], "\n".join(str(f) for f in highs)
        crashed = [f for f in report.findings if "rule crashed" in f.message]
        assert crashed == [], "\n".join(str(f) for f in crashed)

    def test_report_shape_and_json(self, tmp_path):
        from paddle_tpu.analysis.entrypoints import static_program_target
        from paddle_tpu.analysis.rules import analyze_targets

        report = analyze_targets([static_program_target()])
        d = report.to_dict()
        assert set(d) == {"schema_version", "meta", "counts", "findings"}
        assert d["schema_version"] >= 2    # r10: versioned report layout
        assert "static_program" in d["meta"]["timings_s"]
        p = tmp_path / "report.json"
        report.save(str(p))
        import json

        assert json.loads(p.read_text())["counts"]["HIGH"] == 0

    def test_bf16_pipeline_ce_head_dtype_clean(self):
        """Satellite check: the r6 fused-f32-statistics CE head leaves no
        residual f32 matmul in the bf16 pipeline step."""
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.meta_parallel.pipeline_schedule import (
            build_gpt_pipeline_step,
        )
        from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
        from paddle_tpu.optimizer.optimizers import AdamW
        from paddle_tpu.random import split_key

        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        prev = dist.get_mesh()
        dist.init_mesh({"pp": 2})
        try:
            paddle.seed(0)
            cfg = gpt_config(
                "gpt2-small", vocab_size=64, hidden_size=32, num_layers=2,
                num_attention_heads=4, max_position_embeddings=32,
                hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
            model = GPTForPretraining(cfg)
            step = build_gpt_pipeline_step(
                model, AdamW(1e-3, parameters=model.parameters()),
                microbatches=2, compute_dtype=jnp.bfloat16)
            x = jnp.zeros((4, 16), jnp.int32)
            args = (step.state["params"], step.state["opt"], x, x,
                    jax.random.key_data(split_key()),
                    jnp.asarray(1e-3, jnp.float32), step.state["sentinel"])
            t = AnalysisTarget("pipeline_bf16", step.jitted, args)
            fs = an.run_rules(t, [DtypePromotionRule()])
            assert _sev(fs, Severity.HIGH) == [], fs
        finally:
            dist.set_mesh(prev)

    def test_cli_end_to_end(self, tmp_path):
        from paddle_tpu.analysis.cli import main

        out = tmp_path / "r.json"
        rc = main(["--only", "static_program", "--out", str(out)])
        assert rc == 0
        import json

        data = json.loads(out.read_text())
        assert data["meta"]["entry_points"] == ["static_program"]

    def test_unknown_only_is_an_error_not_an_empty_lint(self, tmp_path):
        from paddle_tpu.analysis.cli import main
        from paddle_tpu.analysis.entrypoints import shipped_entry_points

        with pytest.raises(ValueError, match="unknown entry-point"):
            shipped_entry_points(only=("trainer",))  # typo of trainer_step
        with pytest.raises(SystemExit) as e:  # argparse usage error
            main(["--only", "trainer", "--out", str(tmp_path / "r.json")])
        assert e.value.code == 2
