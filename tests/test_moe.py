"""MoE / expert-parallel tests on the 8-virtual-device mesh.

Parity: the reference's global_scatter/global_gather collective ops
(operators/collective/global_scatter_op.cc) and MoE dispatch — here verified
as: all_to_all roundtrip identity, expert-parallel MoE == single-shard MoE
with the same weights, and gating invariants (capacity, combine weights).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import P
from paddle_tpu.distributed.meta_parallel.moe_layer import (
    MoELayer,
    _stacked_ffn,
    top_k_gating,
)
from paddle_tpu.distributed.utils import global_gather, global_scatter


@pytest.fixture
def ep_mesh():
    dist.init_mesh({"ep": 8})
    yield
    dist.clear_mesh()


class TestGlobalScatterGather:
    def test_roundtrip_identity(self, ep_mesh):
        g = dist.new_group(axis_name="ep")

        def fn(x):
            return global_gather(global_scatter(x, group=g), group=g)

        f = dist.run_on_mesh(fn, in_specs=P("ep"), out_specs=P("ep"))
        x = np.random.randn(8 * 16, 4).astype(np.float32)
        np.testing.assert_allclose(np.asarray(f(x)), x, rtol=1e-6)

    def test_scatter_routes_rows(self, ep_mesh):
        # each shard sends row-block i to rank i; after scatter, shard r
        # holds everyone's block r (grouped by source)
        g = dist.new_group(axis_name="ep")
        f = dist.run_on_mesh(
            lambda x: global_scatter(x, group=g), in_specs=P("ep"), out_specs=P("ep")
        )
        # global input: shard r holds rows [r*8, (r+1)*8); value = 100*src + dst_block
        x = np.zeros((64, 1), np.float32)
        for src in range(8):
            for dst in range(8):
                x[src * 8 + dst] = 100 * src + dst
        out = np.asarray(f(x))
        for dst in range(8):
            for src in range(8):
                assert out[dst * 8 + src, 0] == 100 * src + dst

    def test_world1_noop(self):
        x = paddle.to_tensor(np.ones((4, 2), np.float32))
        out = global_scatter(x)
        np.testing.assert_allclose(np.asarray(out._data), 1.0)


class TestGating:
    def test_capacity_respected(self):
        logits = jnp.asarray(np.random.randn(32, 4).astype(np.float32))
        combine, dispatch, l_aux = top_k_gating(logits, 2, 4, 4)
        assert combine.shape == (32, 4, 4)
        # no capacity slot double-booked
        per_slot = jnp.sum(dispatch.astype(jnp.int32), axis=0)
        assert int(per_slot.max()) <= 1
        assert float(l_aux) > 0

    def test_top1_weights_are_gate_probs(self):
        logits = jnp.asarray(np.random.randn(8, 4).astype(np.float32))
        gates = jax.nn.softmax(logits, axis=-1)
        combine, dispatch, _ = top_k_gating(logits, 1, 8, 4)
        w = jnp.sum(combine, axis=(1, 2))
        np.testing.assert_allclose(np.asarray(w), np.asarray(gates.max(axis=-1)), rtol=1e-6)

    def test_top2_weights_normalized(self):
        logits = jnp.asarray(np.random.randn(8, 4).astype(np.float32))
        combine, _, _ = top_k_gating(logits, 2, 8, 4)
        w = jnp.sum(combine, axis=(1, 2))
        np.testing.assert_allclose(np.asarray(w), 1.0, rtol=1e-5)


class TestMoELayer:
    def test_single_shard_forward_backward(self):
        paddle.seed(0)
        layer = MoELayer(16, 32, 4, top_k=2, capacity_factor=2.0)
        x = paddle.to_tensor(np.random.randn(2, 8, 16).astype(np.float32), stop_gradient=False)
        out = layer(x)
        assert tuple(out.shape) == (2, 8, 16)
        loss = (out * out).mean() + layer.l_aux * 0.01
        loss.backward()
        assert layer.gate_weight.grad is not None
        assert layer.experts.w1.grad is not None

    def test_expert_parallel_matches_single_shard(self, ep_mesh):
        """EP-sharded MoE == local MoE with the same weights (tokens replicated)."""
        paddle.seed(0)
        e, m, h, cap_f = 8, 16, 32, 8.0  # capacity ample so nothing drops
        layer = MoELayer(m, h, e, top_k=2, capacity_factor=cap_f)
        x = np.random.randn(8, m).astype(np.float32)  # 8 tokens, 1 per shard

        # reference: single-shard forward on full weights
        ref = np.asarray(layer(paddle.to_tensor(x))._data)

        gw = np.asarray(layer.gate_weight._data)
        w1 = np.asarray(layer.experts.w1._data)
        b1 = np.asarray(layer.experts.b1._data)
        w2 = np.asarray(layer.experts.w2._data)
        b2 = np.asarray(layer.experts.b2._data)

        def fn(x, gw, w1, b1, w2, b2):
            from paddle_tpu.tensor import Tensor

            layer.gate_weight._set_data(gw)
            layer.experts.w1._set_data(w1)
            layer.experts.b1._set_data(b1)
            layer.experts.w2._set_data(w2)
            layer.experts.b2._set_data(b2)
            with paddle.no_grad():
                return layer(Tensor(x))._data

        f = dist.run_on_mesh(
            fn,
            in_specs=(P("ep"), P(), P("ep"), P("ep"), P("ep"), P("ep")),
            out_specs=P("ep"),
        )
        out = np.asarray(f(x, gw, w1, b1, w2, b2))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_gspmd_pjit_path(self, ep_mesh):
        """GSPMD path: jit the layer with ep-sharded expert weights."""
        paddle.seed(0)
        layer = MoELayer(16, 32, 8, top_k=1, capacity_factor=4.0)
        x = np.random.randn(4, 16).astype(np.float32)
        ref = np.asarray(layer(paddle.to_tensor(x))._data)

        mesh = dist.get_mesh()
        from jax.sharding import NamedSharding

        arrs = {
            "gw": layer.gate_weight._data,
            "w1": jax.device_put(layer.experts.w1._data, NamedSharding(mesh, P("ep", None, None))),
            "b1": jax.device_put(layer.experts.b1._data, NamedSharding(mesh, P("ep", None))),
            "w2": jax.device_put(layer.experts.w2._data, NamedSharding(mesh, P("ep", None, None))),
            "b2": jax.device_put(layer.experts.b2._data, NamedSharding(mesh, P("ep", None))),
        }

        @jax.jit
        def f(a, x):
            import paddle_tpu.distributed.meta_parallel.moe_layer as ml

            g = x @ a["gw"]
            combine, dispatch, _ = ml.top_k_gating(g, 1, layer._capacity(x.shape[0]), 8)
            xin = jnp.einsum("gec,gm->ecm", dispatch.astype(x.dtype), x)
            out = _stacked_ffn(xin, a["w1"], a["b1"], a["w2"], a["b2"], jax.nn.gelu)
            return jnp.einsum("gec,ecm->gm", combine.astype(x.dtype), out)

        out = np.asarray(f(arrs, x))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


class TestMoEGPT:
    def test_moe_gpt_trains(self):
        """ERNIE-MoE analog: GPT with MoE FFN blocks converges eagerly."""
        from paddle_tpu.models.gpt import (
            GPTForPretraining,
            GPTPretrainingCriterion,
            gpt_config,
        )
        from paddle_tpu.optimizer.optimizers import AdamW

        paddle.seed(0)
        cfg = gpt_config(
            "ernie-moe-base", vocab_size=128, hidden_size=64, num_layers=2,
            num_attention_heads=4, max_position_embeddings=64,
            hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
            num_experts=4, moe_every=2, moe_capacity_factor=2.0)
        model = GPTForPretraining(cfg)
        assert model.gpt.h[1].is_moe and not model.gpt.h[0].is_moe
        crit = GPTPretrainingCriterion()
        opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
        ids = paddle.to_tensor(
            np.random.default_rng(0).integers(0, 128, (4, 16)).astype("int32"))
        losses = []
        for _ in range(8):
            logits = model(ids)
            loss = crit(logits, ids) + model.aux_loss()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss._data))
        assert losses[-1] < losses[0], losses
