"""hapi Model / metrics / callbacks / summary / flops tests.

Parity strategy: the reference's python/paddle/tests/test_model.py pattern —
fit a tiny model on synthetic data, check metrics move, checkpoint/restore,
early stopping fires.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.hapi.callbacks import EarlyStopping, VisualDL
from paddle_tpu.io.dataset import Dataset
from paddle_tpu.metric import Accuracy, Auc, Precision, Recall
from paddle_tpu.optimizer.optimizers import Adam


class XorDataset(Dataset):
    """Learnable synthetic task (xor-ish blobs)."""

    def __init__(self, n=64, seed=0):
        rng = np.random.default_rng(seed)
        self.x = rng.standard_normal((n, 2)).astype(np.float32)
        self.y = ((self.x[:, 0] * self.x[:, 1]) > 0).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _mlp(classes=2):
    return nn.Sequential(nn.Linear(2, 32), nn.Tanh(), nn.Linear(32, classes))


class TestMetrics:
    def test_accuracy_topk(self):
        m = Accuracy(topk=(1, 2))
        pred = np.asarray([[0.1, 0.7, 0.2], [0.6, 0.3, 0.1]], np.float32)
        label = np.asarray([1, 2], np.int64)
        m.update(m.compute(pred, label))
        top1, top2 = m.accumulate()
        assert top1 == 0.5  # only first sample right at top-1
        assert top2 == 0.5  # second sample's label 2 is ranked 3rd
        assert m.name() == ["acc_top1", "acc_top2"]

    def test_precision_recall(self):
        p, r = Precision(), Recall()
        preds = np.asarray([0.9, 0.8, 0.2, 0.7], np.float32)
        labels = np.asarray([1, 0, 1, 1], np.int64)
        p.update(preds, labels)
        r.update(preds, labels)
        assert abs(p.accumulate() - 2 / 3) < 1e-9  # tp=2 fp=1
        assert abs(r.accumulate() - 2 / 3) < 1e-9  # tp=2 fn=1

    def test_auc(self):
        m = Auc()
        preds = np.stack([1 - np.linspace(0, 1, 100), np.linspace(0, 1, 100)], 1)
        labels = (np.linspace(0, 1, 100) > 0.5).astype(np.int64)
        m.update(preds, labels)
        assert m.accumulate() > 0.99  # perfectly separable


class TestModel:
    def test_fit_evaluate_predict(self, tmp_path, capsys):
        paddle.seed(0)
        model = paddle.Model(_mlp())
        model.prepare(
            Adam(learning_rate=0.05, parameters=model.parameters()),
            nn.CrossEntropyLoss(),
            Accuracy(),
        )
        train = XorDataset(128, seed=0)
        val = XorDataset(64, seed=1)
        # Xavier default init (reference param_attr.py:142) starts this tiny
        # net near-linear; XOR needs ~25 epochs to clear 0.8 val accuracy.
        model.fit(train, val, batch_size=32, epochs=25, verbose=0,
                  save_dir=str(tmp_path / "ckpt"))
        logs = model.evaluate(val, batch_size=32, verbose=0)
        assert logs["acc"] > 0.8, logs
        preds = model.predict(val, batch_size=32, stack_outputs=True)
        assert preds[0].shape == (64, 2)
        # checkpoints written
        import os

        assert os.path.exists(tmp_path / "ckpt" / "final.pdparams")

    def test_save_load_roundtrip(self, tmp_path):
        paddle.seed(0)
        m1 = paddle.Model(_mlp())
        m1.prepare(Adam(learning_rate=0.01, parameters=m1.parameters()),
                   nn.CrossEntropyLoss())
        x = np.random.randn(8, 2).astype(np.float32)
        y = np.zeros(8, np.int64)
        m1.train_batch([x], [y])
        m1.save(str(tmp_path / "m"))
        m2 = paddle.Model(_mlp())
        m2.prepare(Adam(learning_rate=0.01, parameters=m2.parameters()),
                   nn.CrossEntropyLoss())
        m2.load(str(tmp_path / "m"))
        p1 = m1.predict_batch([x])[0]
        p2 = m2.predict_batch([x])[0]
        np.testing.assert_allclose(p1, p2, rtol=1e-6)

    def test_early_stopping(self):
        paddle.seed(0)
        model = paddle.Model(_mlp())
        # lr=0 → no improvement → patience triggers
        model.prepare(Adam(learning_rate=0.0, parameters=model.parameters()),
                      nn.CrossEntropyLoss(), Accuracy())
        es = EarlyStopping(monitor="loss", patience=1, verbose=0, save_best_model=False)
        train = XorDataset(32)
        model.fit(train, train, batch_size=16, epochs=10, verbose=0, callbacks=[es])
        assert model.stop_training

    def test_visualdl_writes_scalars(self, tmp_path):
        paddle.seed(0)
        model = paddle.Model(_mlp())
        model.prepare(Adam(learning_rate=0.01, parameters=model.parameters()),
                      nn.CrossEntropyLoss())
        model.fit(XorDataset(32), batch_size=16, epochs=1, verbose=0,
                  callbacks=[VisualDL(log_dir=str(tmp_path))])
        assert (tmp_path / "scalars.jsonl").exists()
        import json

        lines = [json.loads(l) for l in open(tmp_path / "scalars.jsonl")]
        assert any(r["tag"] == "train/loss" for r in lines)


class TestSummaryFlops:
    def test_summary_counts_params(self, capsys):
        net = _mlp(3)
        info = paddle.summary(net, (1, 2))
        want = 2 * 32 + 32 + 32 * 3 + 3
        assert info["total_params"] == want
        out = capsys.readouterr().out
        assert "Total params" in out

    def test_flops_linear(self, capsys):
        net = nn.Sequential(nn.Linear(4, 8))
        n = paddle.flops(net, (1, 4))
        # out_numel * in_features + bias = 8*4 + 8
        assert n == 8 * 4 + 8

    def test_flops_conv(self, capsys):
        from paddle_tpu.vision.models import LeNet

        n = paddle.flops(LeNet(), (1, 1, 28, 28))
        assert n > 100_000  # sanity: LeNet ≈ 0.4 MFLOPs-scale


class TestModelWidened:
    """Round-2 hapi widening: multi-input/multi-label specs, loss lists,
    amp_configs, inference export (reference model.py fit:1556 surface)."""

    def _mk_two_headed(self):
        import paddle_tpu.nn as nn

        class TwoHead(nn.Layer):
            def __init__(self):
                super().__init__()
                self.shared = nn.Linear(4, 8)
                self.h1 = nn.Linear(8, 3)
                self.h2 = nn.Linear(8, 1)

            def forward(self, x, scale):
                h = paddle.nn.functional.relu(self.shared(x * scale)) \
                    if hasattr(paddle.nn, "functional") else self.shared(x)
                return self.h1(h), self.h2(h)

        return TwoHead()

    def test_multi_input_multi_label_fit(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.hapi import Model
        from paddle_tpu.optimizer.optimizers import Adam

        net = self._mk_two_headed()
        from paddle_tpu.jit.input_spec import InputSpec

        model = Model(net,
                      inputs=[InputSpec([None, 4], "float32", "x"),
                              InputSpec([None, 4], "float32", "scale")],
                      labels=[InputSpec([None], "int64", "y1"),
                              InputSpec([None, 1], "float32", "y2")])
        ce = nn.CrossEntropyLoss()
        mse = nn.MSELoss()
        model.prepare(Adam(learning_rate=1e-2, parameters=net.parameters()),
                      loss=[lambda o, l: ce(o, l), lambda o, l: mse(o, l)])
        rng = np.random.default_rng(0)
        data = [
            (rng.normal(size=(8, 4)).astype("float32"),
             np.ones((8, 4), "float32"),
             rng.integers(0, 3, (8,)).astype("int64"),
             rng.normal(size=(8, 1)).astype("float32"))
            for _ in range(4)
        ]
        model.fit(data, epochs=2, verbose=0)
        res = model.train_batch(list(data[0][:2]), list(data[0][2:]))
        assert np.isfinite(res[0] if not isinstance(res, tuple) else res[0][0])

    def test_amp_configs_accepted(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.hapi import Model
        from paddle_tpu.optimizer.optimizers import Adam

        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        model = Model(net)
        model.prepare(Adam(learning_rate=1e-2, parameters=net.parameters()),
                      loss=nn.CrossEntropyLoss(),
                      amp_configs={"level": "O1"})
        x = np.random.default_rng(0).normal(size=(4, 4)).astype("float32")
        y = np.asarray([0, 1, 0, 1], "int64")
        out = model.train_batch([x], [y])
        assert np.isfinite(out[0] if not isinstance(out, tuple) else out[0][0])

    def test_inference_export(self, tmp_path):
        import paddle_tpu.nn as nn
        from paddle_tpu.hapi import Model
        from paddle_tpu.jit.input_spec import InputSpec
        from paddle_tpu.jit.save_load import load as jit_load

        net = nn.Sequential(nn.Linear(4, 2))
        model = Model(net, inputs=[InputSpec([None, 4], "float32", "x")])
        p = str(tmp_path / "infer" / "m")
        model.save(p, training=False)
        loaded = jit_load(p)
        x = np.random.default_rng(0).normal(size=(3, 4)).astype("float32")
        want = np.asarray(net(paddle.to_tensor(x))._data)
        got = np.asarray(loaded(paddle.to_tensor(x))._data)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


class TestDistributedHapi:
    """Model.prepare(strategy=) routes fit through the jitted multi-device
    ParallelTrainer (VERDICT r2 missing #6; reference dist-hapi,
    hapi/model.py:906)."""

    def _data(self, n=32):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((n, 2)).astype(np.float32)
        y = ((x[:, 0] * x[:, 1]) > 0).astype(np.int64)
        return x, y

    def _fit_losses(self, strategy, seed=0, steps=6):
        from paddle_tpu.hapi.model import Model

        paddle.seed(seed)
        net = _mlp()
        model = Model(net)
        opt = Adam(learning_rate=0.05, parameters=net.parameters())
        model.prepare(
            opt, loss=lambda out, y: nn.functional.cross_entropy(out, y),
            strategy=strategy)
        x, y = self._data()
        losses = []
        for _ in range(steps):
            losses.append(model.train_batch([x], [y])[0])
        return model, losses

    def test_strategy_fit_matches_eager_dp8(self):
        import paddle_tpu.distributed as dist

        dist.init_mesh({"dp": 8})
        try:
            _, dist_losses = self._fit_losses(strategy=True)
            model, eager_losses = self._fit_losses(strategy=None)
            # full-batch loss each step: dp sharding is exact (mean of
            # per-shard means == full mean; grads pmean'd)
            np.testing.assert_allclose(dist_losses, eager_losses,
                                       rtol=2e-4, atol=2e-5)
        finally:
            dist.clear_mesh()

    def test_dist_fit_syncs_weights_for_eval(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.hapi.model import Model

        dist.init_mesh({"dp": 8})
        try:
            model, losses = self._fit_losses(strategy=True, steps=12)
            assert losses[-1] < losses[0]
            x, y = self._data()
            # eval_batch syncs trained shards back into the eager network
            ev = model.eval_batch([x], [y])
            assert ev[0] <= losses[0]
        finally:
            dist.clear_mesh()

    def test_metrics_fall_back_with_warning(self):
        import warnings

        import paddle_tpu.distributed as dist
        from paddle_tpu.hapi.model import Model
        from paddle_tpu.metric import Accuracy

        dist.init_mesh({"dp": 8})
        try:
            paddle.seed(0)
            net = _mlp()
            model = Model(net)
            opt = Adam(learning_rate=0.05, parameters=net.parameters())
            model.prepare(
                opt, loss=lambda out, y: nn.functional.cross_entropy(out, y),
                metrics=Accuracy(), strategy=True)
            x, y = self._data()
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                model.train_batch([x], [y])
            assert any("eager" in str(m.message) for m in w)
            assert model._dist_trainer is None
        finally:
            dist.clear_mesh()
