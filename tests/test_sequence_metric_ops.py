"""Numpy-parity tests for the new sequence_ops tranche and the
chunk_eval / mean_iou metrics (OpTest pattern; reference kernels:
operators/sequence_ops/*, chunk_eval_op.h, mean_iou_op.h)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.metric import chunk_eval, mean_iou
from paddle_tpu.ops import sequence as S
from paddle_tpu.tensor import Tensor


def _np(x):
    return np.asarray(x._data if isinstance(x, Tensor) else x)


def test_sequence_concat():
    x1 = np.arange(10, dtype=np.float32).reshape(5, 2)
    x2 = 100 + np.arange(8, dtype=np.float32).reshape(4, 2)
    l1 = np.array([2, 3])
    l2 = np.array([3, 1])
    out, lens = S.sequence_concat([x1, x2], [l1, l2])
    want = np.concatenate([x1[:2], x2[:3], x1[2:5], x2[3:4]])
    np.testing.assert_allclose(_np(out), want)
    np.testing.assert_array_equal(_np(lens), [5, 4])


def test_sequence_pool_all_types():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((3, 5, 2)).astype(np.float32)
    lens = np.array([3, 5, 1])
    mask = np.arange(5)[None, :] < lens[:, None]
    for pt in ("SUM", "AVERAGE", "SQRT", "MAX", "LAST", "FIRST"):
        got = _np(S.sequence_pool(x, pt, length=lens))
        if pt == "SUM":
            want = (x * mask[..., None]).sum(1)
        elif pt == "AVERAGE":
            want = (x * mask[..., None]).sum(1) / lens[:, None]
        elif pt == "SQRT":
            want = (x * mask[..., None]).sum(1) / np.sqrt(lens)[:, None]
        elif pt == "MAX":
            want = np.where(mask[..., None], x, -np.inf).max(1)
        elif pt == "LAST":
            want = x[np.arange(3), lens - 1]
        else:
            want = x[:, 0]
        np.testing.assert_allclose(got, want, rtol=1e-5, err_msg=pt)


def test_sequence_pool_empty_seq_pad_value():
    x = np.ones((2, 3, 1), np.float32)
    lens = np.array([0, 2])
    got = _np(S.sequence_pool(x, "SUM", length=lens, pad_value=-7.0))
    np.testing.assert_allclose(got[0], -7.0)
    np.testing.assert_allclose(got[1], 2.0)


def test_sequence_conv():
    rng = np.random.default_rng(1)
    B, T, D, O, L = 2, 4, 3, 5, 3
    x = rng.standard_normal((B, T, D)).astype(np.float32)
    w = rng.standard_normal((L * D, O)).astype(np.float32)
    lens = np.array([4, 2])
    start = -1
    got = _np(S.sequence_conv(x, w, length=lens, context_length=L,
                              context_start=start))
    want = np.zeros((B, T, O), np.float32)
    for b in range(B):
        for t in range(int(lens[b])):
            ctx = []
            for j in range(L):
                s = t + start + j
                ctx.append(x[b, s] if 0 <= s < lens[b] else np.zeros(D))
            want[b, t] = np.concatenate(ctx) @ w
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_sequence_enumerate():
    x = np.array([[1, 2, 3, 4], [5, 6, 0, 0]], np.int32)
    lens = np.array([4, 2])
    got = _np(S.sequence_enumerate(x, win_size=2, pad_value=0, length=lens))
    want = np.array([[[1, 2], [2, 3], [3, 4], [4, 0]],
                     [[5, 6], [6, 0], [0, 0], [0, 0]]], np.int32)
    np.testing.assert_array_equal(got, want)


def test_sequence_erase():
    x = np.array([[2, 2, 6, 1, 3, 9], [1, 0, 0, 0, 0, 0]], np.int64)
    lens = np.array([6, 1])
    out, nl = S.sequence_erase(x, [2, 3, 5], length=lens)
    np.testing.assert_array_equal(_np(nl), [3, 1])
    np.testing.assert_array_equal(_np(out)[0, :3], [6, 1, 9])
    np.testing.assert_array_equal(_np(out)[1, :1], [1])


def test_sequence_expand_as():
    x = np.array([[1.0], [2.0], [3.0]], np.float32)
    got = _np(S.sequence_expand_as(x, np.array([2, 0, 3])))
    np.testing.assert_allclose(got[:, 0], [1, 1, 3, 3, 3])


def test_sequence_reshape():
    x = np.arange(12, dtype=np.float32).reshape(6, 2)
    out, lens = S.sequence_reshape(x, new_dim=4, length=np.array([2, 4]))
    np.testing.assert_array_equal(_np(lens), [1, 2])
    np.testing.assert_allclose(_np(out), x.reshape(3, 4))


def test_sequence_scatter():
    x = np.zeros((2, 5), np.float32)
    idx = np.array([1, 3, 0, 2])
    upd = np.array([10.0, 20.0, 30.0, 40.0], np.float32)
    got = _np(S.sequence_scatter(x, idx, upd, index_lengths=np.array([2, 2])))
    want = np.zeros((2, 5), np.float32)
    want[0, 1], want[0, 3] = 10, 20
    want[1, 0], want[1, 2] = 30, 40
    np.testing.assert_allclose(got, want)


def test_sequence_slice():
    x = np.arange(24, dtype=np.float32).reshape(2, 6, 2)
    out, lens = S.sequence_slice(x, offset=np.array([1, 0]),
                                 length=np.array([2, 3]))
    np.testing.assert_array_equal(_np(lens), [2, 3])
    np.testing.assert_allclose(_np(out)[0, :2], x[0, 1:3])
    np.testing.assert_allclose(_np(out)[1, :3], x[1, 0:3])
    np.testing.assert_allclose(_np(out)[0, 2], 0.0)


def test_row_conv():
    rng = np.random.default_rng(2)
    B, T, D, C = 2, 5, 3, 2
    x = rng.standard_normal((B, T, D)).astype(np.float32)
    w = rng.standard_normal((C, D)).astype(np.float32)
    lens = np.array([5, 3])
    got = _np(S.row_conv(x, w, length=lens))
    want = np.zeros_like(x)
    for b in range(B):
        for t in range(int(lens[b])):
            for j in range(C):
                if t + j < lens[b]:
                    want[b, t] += w[j] * x[b, t + j]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_im2sequence():
    rng = np.random.default_rng(3)
    x = rng.standard_normal((1, 2, 4, 4)).astype(np.float32)
    got = _np(S.im2sequence(x, filter_size=2, stride=2))
    assert got.shape == (4, 8)  # 2x2 output grid, 2*2*2 features
    # first patch, channel-major feature order [C, kh, kw]
    want0 = np.concatenate([x[0, 0, :2, :2].reshape(-1),
                            x[0, 1, :2, :2].reshape(-1)])
    np.testing.assert_allclose(got[0], want0, rtol=1e-5)


def test_sequence_grad_flows():
    """sequence_pool/conv are differentiable through the tape."""
    x = paddle.to_tensor(np.ones((2, 3, 2), np.float32), stop_gradient=False)
    out = S.sequence_pool(x, "SUM", length=np.array([2, 3]))
    out.sum().backward()
    g = _np(x.grad)
    assert g[0, :2].sum() == 4 and g[0, 2].sum() == 0


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def test_mean_iou():
    pred = np.array([[0, 1, 2, 2], [1, 1, 0, 0]], np.int32)
    lab = np.array([[0, 1, 2, 1], [2, 1, 0, 0]], np.int32)
    miou, wrong, correct = mean_iou(pred, lab, num_classes=3)
    # correct: c0=3, c1=2, c2=1; wrong: mismatches (2,1) and (1,2):
    # wrong[1] += 2, wrong[2] += 2
    np.testing.assert_array_equal(_np(correct), [3, 2, 1])
    np.testing.assert_array_equal(_np(wrong), [0, 2, 2])
    want = (3 / 3 + 2 / 4 + 1 / 3) / 3
    np.testing.assert_allclose(float(_np(miou)), want, rtol=1e-5)


def test_chunk_eval_iob():
    """IOB with 2 chunk types: labels 0=B-0, 1=I-0, 2=B-1, 3=I-1, 4=O."""
    # label:  [B-0 I-0 O  B-1] → chunks (0,1,t0), (3,3,t1)
    # pred:   [B-0 I-0 O  B-0] → chunks (0,1,t0), (3,3,t0)
    lab = np.array([[0, 1, 4, 2]], np.int64)
    pred = np.array([[0, 1, 4, 0]], np.int64)
    p, r, f1, ni, nl, nc = chunk_eval(pred, lab, "IOB", num_chunk_types=2)
    assert int(_np(ni)) == 2 and int(_np(nl)) == 2 and int(_np(nc)) == 1
    np.testing.assert_allclose(float(_np(p)), 0.5)
    np.testing.assert_allclose(float(_np(r)), 0.5)
    np.testing.assert_allclose(float(_np(f1)), 0.5)


def test_chunk_eval_iobes_exact():
    """IOBES: 4 tags per type (B,I,E,S); 1 type + other=1.
    labels: B=0 I=1 E=2 S=3, O=4."""
    lab = np.array([[0, 1, 2, 4, 3]], np.int64)   # chunk (0,2), chunk (4,4)
    pred = np.array([[0, 1, 2, 4, 4]], np.int64)  # chunk (0,2)
    p, r, f1, ni, nl, nc = chunk_eval(pred, lab, "IOBES", num_chunk_types=1)
    assert int(_np(ni)) == 1 and int(_np(nl)) == 2 and int(_np(nc)) == 1
    np.testing.assert_allclose(float(_np(p)), 1.0)
    np.testing.assert_allclose(float(_np(r)), 0.5)


def test_chunk_eval_seq_length_and_excluded():
    lab = np.array([[0, 1, 4, 0], [0, 4, 4, 4]], np.int64)
    pred = lab.copy()
    p, r, f1, ni, nl, nc = chunk_eval(pred, lab, "IOB", num_chunk_types=2,
                                      seq_length=np.array([2, 1]))
    assert int(_np(nc)) == 2 and float(_np(f1)) == 1.0
    # excluding type 0 removes every chunk
    p2, r2, f2, ni2, nl2, nc2 = chunk_eval(
        pred, lab, "IOB", num_chunk_types=2, seq_length=np.array([2, 1]),
        excluded_chunk_types=[0])
    assert int(_np(ni2)) == 0 and float(_np(f2)) == 0.0


def test_detection_map_integral_and_11point():
    """mAP parity with a hand-computed VOC-style case
    (detection_map_op.h CalcTrueAndFalsePositive + CalcMAP)."""
    from paddle_tpu.metric import DetectionMAP

    # one image, one class (label 1): 2 gt boxes, 3 detections
    gt = np.array([[1, 0.1, 0.1, 0.4, 0.4],
                   [1, 0.6, 0.6, 0.9, 0.9]], np.float32)
    det = np.array([
        [1, 0.9, 0.1, 0.1, 0.4, 0.4],   # TP (matches gt0)
        [1, 0.8, 0.62, 0.62, 0.9, 0.9],  # TP (matches gt1)
        [1, 0.7, 0.0, 0.0, 0.05, 0.05],  # FP
    ], np.float32)
    m = DetectionMAP(overlap_threshold=0.5, ap_type="integral")
    m.update(det, np.array([3]), gt, np.array([2]))
    # precision at ranks: 1/1, 2/2, 2/3; recall: .5, 1.0, 1.0
    # integral AP = 1*0.5 + 1*0.5 = 1.0
    np.testing.assert_allclose(m.accumulate(), 1.0, atol=1e-6)

    # duplicate match on the same gt counts as FP (visited flag)
    det2 = np.array([
        [1, 0.9, 0.1, 0.1, 0.4, 0.4],
        [1, 0.8, 0.11, 0.11, 0.4, 0.4],  # second hit on gt0 -> FP
    ], np.float32)
    m2 = DetectionMAP(overlap_threshold=0.5, ap_type="integral")
    m2.update(det2, np.array([2]), gt, np.array([2]))
    # ranks: p=1/1 r=.5; p=1/2 r=.5 -> AP = 0.5
    np.testing.assert_allclose(m2.accumulate(), 0.5, atol=1e-6)

    # 11point on the first case: recall thresholds 0..0.5 see p=1,
    # 0.6..1.0 see max precision 1.0 (rank2 TP) -> all 11 points get 1.0
    m3 = DetectionMAP(overlap_threshold=0.5, ap_type="11point")
    m3.update(det, np.array([3]), gt, np.array([2]))
    np.testing.assert_allclose(m3.accumulate(), 1.0, atol=1e-3)


def test_detection_map_difficult_and_accumulate():
    from paddle_tpu.metric import DetectionMAP

    gt = np.array([[1, 0.1, 0.1, 0.4, 0.4],
                   [1, 0.6, 0.6, 0.9, 0.9]], np.float32)
    difficult = np.array([0, 1])
    det = np.array([[1, 0.9, 0.1, 0.1, 0.4, 0.4]], np.float32)
    m = DetectionMAP(overlap_threshold=0.5, evaluate_difficult=False)
    m.update(det, np.array([1]), gt, np.array([2]), difficult=difficult)
    # difficult gt excluded: npos=1, one TP -> AP 1.0
    np.testing.assert_allclose(m.accumulate(), 1.0, atol=1e-6)
    # accumulation across batches: a second image with a miss halves recall
    m.update(np.zeros((0, 6), np.float32), np.array([0]),
             np.array([[1, 0.2, 0.2, 0.5, 0.5]], np.float32), np.array([1]))
    assert m.accumulate() < 1.0
    m.reset()
    assert m.accumulate() == 0.0


def test_precision_recall():
    """Numpy re-derivation of metrics/precision_recall_op.h with the
    op_test's reference loop semantics."""
    from paddle_tpu.metric import precision_recall

    idx = np.array([0, 1, 2, 1, 0], np.int32)
    lab = np.array([0, 1, 1, 2, 2], np.int32)
    w = np.array([1.0, 2.0, 1.0, 0.5, 1.0], np.float32)
    c = 3
    batch_m, accum_m, states = precision_recall(None, idx, lab, c, weights=w)

    # reference loop
    exp = np.zeros((c, 4))
    for i in range(5):
        wi = w[i]
        if idx[i] == lab[i]:
            exp[idx[i], 0] += wi
            exp[:, 2] += wi
            exp[idx[i], 2] -= wi
        else:
            exp[lab[i], 3] += wi
            exp[idx[i], 1] += wi
            exp[:, 2] += wi
            exp[idx[i], 2] -= wi
            exp[lab[i], 2] -= wi
    np.testing.assert_allclose(states, exp, atol=1e-12)

    def calc(st):
        precs, recs = [], []
        ttp = tfp = tfn = 0.0
        for i in range(c):
            tp, fp, _, fn = st[i]
            precs.append(tp / (tp + fp) if tp > 0 or fp > 0 else 1.0)
            recs.append(tp / (tp + fn) if tp > 0 or fn > 0 else 1.0)
            ttp, tfp, tfn = ttp + tp, tfp + fp, tfn + fn
        mp, mr = np.mean(precs), np.mean(recs)
        mf = 2 * mp * mr / (mp + mr) if mp + mr > 0 else 0.0
        up = ttp / (ttp + tfp) if ttp > 0 or tfp > 0 else 1.0
        ur = ttp / (ttp + tfn) if ttp > 0 or tfn > 0 else 1.0
        uf = 2 * up * ur / (up + ur) if up + ur > 0 else 0.0
        return np.array([mp, mr, mf, up, ur, uf])

    np.testing.assert_allclose(batch_m, calc(exp), atol=1e-12)

    # accumulate path: prior states add into accum metrics only
    prior = np.ones((c, 4))
    b2, a2, s2 = precision_recall(None, idx, lab, c, weights=w,
                                  states_info=prior)
    np.testing.assert_allclose(b2, batch_m)
    np.testing.assert_allclose(s2, exp + prior)
    np.testing.assert_allclose(a2, calc(exp + prior))


def test_positive_negative_pair():
    from paddle_tpu.metric import positive_negative_pair

    score = np.array([0.9, 0.5, 0.5, 0.3, 0.8], np.float32)
    label = np.array([1.0, 0.0, 1.0, 0.0, 1.0], np.float32)
    qid = np.array([0, 0, 0, 1, 1], np.int64)
    pos, neg, neu = positive_negative_pair(score, label, qid)
    # query 0 pairs with label diff: (0,1): s 0.9>0.5, l 1>0 -> pos
    #   (1,2): s equal, labels differ -> neu AND neg (reference quirk)
    # query 1: (3,4): s 0.3<0.8, l 0<1 -> pos
    assert pos == 2.0 and neg == 1.0 and neu == 1.0

    # accumulate + weights
    w = np.array([1.0, 3.0, 1.0, 2.0, 2.0], np.float32)
    pos2, neg2, neu2 = positive_negative_pair(
        score, label, qid, weight=w, accum_positive=10.0,
        accum_negative=20.0, accum_neutral=30.0)
    assert pos2 == 10.0 + 2.0 + 2.0  # pair(0,1) w=(1+3)/2, pair(3,4) w=2
    assert neg2 == 20.0 + 2.0        # pair(1,2) w=(3+1)/2
    assert neu2 == 30.0 + 2.0


def test_sequence_topk_avg_pooling():
    """Numpy re-derivation of sequence_topk_avg_pooling_op.h: per (batch,
    channel, row) average of top-k column scores, prefix-carry when a row
    has fewer valid columns than k."""
    rng = np.random.default_rng(5)
    B, C, Rm, Cm = 2, 2, 3, 5
    x = rng.standard_normal((B, C, Rm, Cm)).astype(np.float32)
    rl = np.array([3, 2])
    cl = np.array([5, 3])
    topks = [1, 3, 4]
    out = np.asarray(S.sequence_topk_avg_pooling(
        Tensor(x), rl, cl, topks, C)._data)

    exp = np.zeros((B, Rm, C * len(topks)), np.float32)
    for b in range(B):
        for r in range(int(rl[b])):
            for c in range(C):
                row = np.sort(x[b, c, r, :cl[b]])[::-1]
                for ki, k in enumerate(topks):
                    s = row[:min(k, len(row))].sum()
                    exp[b, r, c * len(topks) + ki] = s / k
    np.testing.assert_allclose(out, exp, atol=1e-5, rtol=1e-5)


def test_match_matrix_tensor():
    """match_matrix_tensor_op.cc: out[b,t,i,j] = x_i^T W[:,t,:] y_j with
    zero padding outside valid lengths; Tmp = x @ W."""
    rng = np.random.default_rng(6)
    B, Lm, Rm, D, T = 2, 3, 4, 5, 2
    x = rng.standard_normal((B, Lm, D)).astype(np.float32)
    y = rng.standard_normal((B, Rm, D)).astype(np.float32)
    w = rng.standard_normal((D, T, D)).astype(np.float32)
    xl, yl = np.array([3, 2]), np.array([4, 1])
    out, tmp = S.match_matrix_tensor(Tensor(x), Tensor(y), Tensor(w), xl, yl)
    out = np.asarray(out._data)
    tmp = np.asarray(tmp._data)

    exp = np.zeros((B, T, Lm, Rm), np.float32)
    for b in range(B):
        for t in range(T):
            for i in range(int(xl[b])):
                for j in range(int(yl[b])):
                    exp[b, t, i, j] = x[b, i] @ w[:, t, :] @ y[b, j]
    np.testing.assert_allclose(out, exp, atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(
        tmp[0, 0], np.einsum("d,dte->te", x[0, 0], w), atol=2e-5, rtol=2e-5)


def test_sequence_topk_avg_pooling_grad():
    """The top-k average is differentiable through lax.top_k's gather."""
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(np.random.default_rng(7).standard_normal((1, 1, 2, 4)),
                    jnp.float32)
    rl = jnp.array([2]); cl = jnp.array([4])

    def loss(x):
        out = S.sequence_topk_avg_pooling(x, rl, cl, [2], 1)
        a = out._data if hasattr(out, "_data") else out
        return jnp.sum(a)

    g = np.asarray(jax.grad(loss)(x))
    # each row's top-2 entries get 1/2 each, others 0
    for r in range(2):
        row = np.asarray(x[0, 0, r])
        top2 = set(np.argsort(-row)[:2])
        for cidx in range(4):
            expect = 0.5 if cidx in top2 else 0.0
            np.testing.assert_allclose(g[0, 0, r, cidx], expect, atol=1e-6)
