"""Checkpoint manager, auto-checkpoint resume, elastic restart protocol.

Parity model: reference incubate/checkpoint tests (test_auto_checkpoint*.py)
and elastic tests (test_fleet_elastic_manager.py), plus orbax-style sharded
save/reshard-on-load which the reference handles via reshard.py.
"""
import os
import signal
import subprocess
import sys
import textwrap

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.checkpoint import (
    CheckpointManager,
    load_checkpoint,
    save_checkpoint,
)


def test_checkpoint_roundtrip_nested(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    state = {
        "model": {"w": paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3))},
        "step": 42,
        "lr": 0.125,
        "history": [1, 2, 3],
        "arr": np.ones((4,), "int32"),
    }
    mgr.save(7, state, metadata={"note": "hi"})
    loaded, meta = mgr.load()
    assert meta["note"] == "hi"
    assert loaded["step"] == 42 and loaded["lr"] == 0.125
    assert loaded["history"] == [1, 2, 3]
    np.testing.assert_array_equal(loaded["model"]["w"].numpy(),
                                  state["model"]["w"].numpy())
    np.testing.assert_array_equal(np.asarray(loaded["arr"]), state["arr"])


def test_checkpoint_prune_and_latest(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_max=2)
    for s in (1, 5, 9):
        mgr.save(s, {"v": s})
    assert mgr.all_steps() == [5, 9]
    assert mgr.latest_step() == 9
    loaded, _ = mgr.load(5)
    assert loaded["v"] == 5


def test_checkpoint_async(tmp_path):
    mgr = CheckpointManager(str(tmp_path), async_save=True)
    mgr.save(1, {"x": np.zeros(3)})
    mgr.wait()
    assert mgr.latest_step() == 1


def test_sharded_save_reshard_on_load(tmp_path):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devs = np.array(jax.devices()[:8]).reshape(8)
    mesh8 = Mesh(devs, ("dp",))
    arr = jax.device_put(
        np.arange(64, dtype=np.float32).reshape(8, 8),
        NamedSharding(mesh8, PartitionSpec("dp", None)),
    )
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(0, {"w": arr})

    mesh4 = Mesh(np.array(jax.devices()[:4]), ("dp",))
    loaded, _ = mgr.load(0, mesh=mesh4)
    w = loaded["w"]
    np.testing.assert_array_equal(np.asarray(w), np.asarray(arr))
    # re-placed on the 4-device mesh with the saved spec
    assert w.sharding.mesh.shape["dp"] == 4
    assert w.sharding.spec == PartitionSpec("dp", None)


def test_save_load_checkpoint_train_state(tmp_path):
    net = paddle.nn.Linear(4, 2)
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    x = paddle.to_tensor(np.random.rand(8, 4).astype("float32"))
    for _ in range(3):
        loss = (net(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    w_ref = net.weight.numpy().copy()
    save_checkpoint(str(tmp_path), step=3, model=net, optimizer=opt,
                    extra={"cursor": 123})

    # clobber weights, then restore
    net.weight.set_value(np.zeros_like(w_ref))
    step, extra = load_checkpoint(str(tmp_path), model=net, optimizer=opt)
    assert step == 3 and extra["cursor"] == 123
    np.testing.assert_allclose(net.weight.numpy(), w_ref)


def test_train_epoch_range_resume(tmp_path):
    from paddle_tpu.incubate.checkpoint import TrainEpochRange

    net = paddle.nn.Linear(2, 2)
    seen = []
    # first launch "crashes" after finishing 3 of 5 epochs (the snapshot is
    # written as each epoch completes)
    r = TrainEpochRange(3, "job", checkpoint_inter=0, save_dir=str(tmp_path))
    r.attach(model=net)
    for epoch in r.get():
        seen.append(epoch)
        net.weight.set_value(np.full((2, 2), float(epoch), "float32"))
    assert seen == [0, 1, 2]

    net2 = paddle.nn.Linear(2, 2)
    r2 = TrainEpochRange(5, "job", checkpoint_inter=0, save_dir=str(tmp_path))
    r2.attach(model=net2)
    resumed = list(r2.get())
    assert resumed == [3, 4]
    assert r2.restored_from == 2
    # state restored from the epoch-2 snapshot
    np.testing.assert_allclose(net2.weight.numpy()[0, 0], 2.0)


def test_auto_checkpoint_env_checker(tmp_path, monkeypatch):
    from paddle_tpu.incubate.checkpoint import AutoCheckpointChecker, TrainEpochRange

    monkeypatch.setenv("PADDLE_RUNNING_ENV", "PADDLE_EDL_AUTO_CHECKPOINT")
    monkeypatch.setenv("PADDLE_JOB_ID", "job_xyz")
    monkeypatch.setenv("PADDLE_EDL_HDFS_CHECKPOINT_PATH", str(tmp_path))
    c = AutoCheckpointChecker()
    assert c.valid()
    r = TrainEpochRange(2, "rangename", checkpoint_inter=0)
    assert r._active and "job_xyz" in r._dir
    list(r.get())
    assert r._mgr.latest_step() == 1


def test_elastic_file_store_and_manager(tmp_path, monkeypatch):
    from paddle_tpu.distributed.fleet.elastic import ElasticManager, enable_elastic

    monkeypatch.setenv("PADDLE_ELASTIC_NP", "1")
    monkeypatch.setenv("PADDLE_ELASTIC_JOB_ID", "ejob")
    monkeypatch.setenv("PADDLE_ELASTIC_STORE_PATH", str(tmp_path / "store"))
    monkeypatch.setenv("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")
    assert enable_elastic()
    mgr = ElasticManager()
    mgr.register()
    try:
        assert mgr.store.nodes() == ["127.0.0.1_6170"]
        assert mgr.endpoints_env() == "127.0.0.1:6170"
        assert not mgr.changed()
        assert mgr.wait_for_np(1)
        # a second node joining is detected as membership change
        mgr.store.register("127.0.0.1_6171", "127.0.0.1:6171")
        assert mgr.changed()
    finally:
        mgr.exit()
    assert "127.0.0.1_6170" not in mgr.store.nodes()


def test_launch_elastic_restart_on_exit_code(tmp_path, monkeypatch):
    from paddle_tpu.distributed.fleet.elastic import launch_elastic

    monkeypatch.setenv("PADDLE_ELASTIC_NP", "1")
    monkeypatch.setenv("PADDLE_ELASTIC_STORE_PATH", str(tmp_path / "store"))
    monkeypatch.setenv("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6270")
    marker = tmp_path / "ran_once"
    script = tmp_path / "child.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        marker = {str(marker)!r}
        assert os.environ.get("DISTRIBUTED_TRAINER_ENDPOINTS")
        if not os.path.exists(marker):
            open(marker, "w").write(os.environ["PADDLE_ELASTIC_RESTART_NUM"])
            sys.exit(101)   # request relaunch (preemption)
        sys.exit(0)
    """))
    code = launch_elastic([sys.executable, str(script)], max_restarts=2)
    assert code == 0
    assert marker.read_text() == "0"


def test_kv_server_and_tcp_store(monkeypatch):
    """Cross-host elastic registry over the HTTP KV server (etcd stand-in;
    reference fleet/utils/http_server.py + elastic manager.py:103)."""
    from paddle_tpu.distributed.fleet.elastic.manager import _TcpStore
    from paddle_tpu.distributed.fleet.utils import KVServer

    with KVServer(0, host="127.0.0.1") as srv:
        s1 = _TcpStore(f"127.0.0.1:{srv.port}", "job1", ttl=5.0)
        s2 = _TcpStore(f"127.0.0.1:{srv.port}", "job1", ttl=5.0)
        s1.register("node_a", "10.0.0.1:8000")
        s2.register("node_b", "10.0.0.2:8000")
        assert s1.nodes() == ["node_a", "node_b"]
        assert s2.endpoints() == ["10.0.0.1:8000", "10.0.0.2:8000"]
        s1.deregister("node_a")
        assert s2.nodes() == ["node_b"]


def test_elastic_manager_over_tcp(monkeypatch):
    from paddle_tpu.distributed.fleet.elastic import ElasticManager
    from paddle_tpu.distributed.fleet.utils import KVServer

    with KVServer(0, host="127.0.0.1") as srv:
        monkeypatch.setenv("PADDLE_ELASTIC_NP", "1")
        monkeypatch.setenv("PADDLE_ELASTIC_JOB_ID", "tcpjob")
        monkeypatch.setenv("PADDLE_ELASTIC_SERVER", f"127.0.0.1:{srv.port}")
        monkeypatch.setenv("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:9999")
        mgr = ElasticManager()
        mgr.register()
        try:
            assert mgr.wait_for_np(1)
            assert mgr.endpoints_env() == "127.0.0.1:9999"
            assert not mgr.changed()
        finally:
            mgr.exit()
        assert mgr.store.nodes() == []


def test_preemption_drill_sigkill_relaunches(tmp_path, monkeypatch):
    """SIGKILL a launched child: the elastic loop must re-register the node
    and relaunch (reference fault-tolerance + exit-101 restart protocol)."""
    import subprocess
    import sys
    import threading
    import time

    from paddle_tpu.distributed.fleet.elastic import launch_elastic
    from paddle_tpu.distributed.fleet.elastic.manager import ElasticManager
    from paddle_tpu.distributed.fleet.utils import KVServer

    marker = tmp_path / "runs.txt"
    pidfile = tmp_path / "pid.txt"
    script = tmp_path / "train.py"
    script.write_text(
        "import os, sys, time, pathlib\n"
        f"m = pathlib.Path({str(marker)!r})\n"
        f"p = pathlib.Path({str(pidfile)!r})\n"
        "runs = (m.read_text() if m.exists() else '') + 'x'\n"
        "m.write_text(runs)\n"
        "p.write_text(str(os.getpid()))\n"
        "if len(runs) == 1:\n"
        "    time.sleep(60)  # first run: wait to be preempted (SIGKILL)\n"
        "sys.exit(0)\n"
    )

    with KVServer(0, host="127.0.0.1") as srv:
        monkeypatch.setenv("PADDLE_ELASTIC_NP", "1")
        monkeypatch.setenv("PADDLE_ELASTIC_JOB_ID", "drill")
        monkeypatch.setenv("PADDLE_ELASTIC_SERVER", f"127.0.0.1:{srv.port}")
        monkeypatch.setenv("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:7777")

        def killer():
            deadline = time.time() + 30
            while time.time() < deadline and not pidfile.exists():
                time.sleep(0.1)
            time.sleep(0.3)
            os.kill(int(pidfile.read_text()), signal.SIGKILL)

        t = threading.Thread(target=killer, daemon=True)
        t.start()
        code = launch_elastic([sys.executable, str(script)], max_restarts=2,
                              poll_interval=0.2)
        assert code == 0
        assert marker.read_text() == "xx"  # ran twice: killed once, relaunched


def test_kv_servers_are_isolated():
    """Two servers in one process must not share state (regression:
    class-level store)."""
    from paddle_tpu.distributed.fleet.utils import KVClient, KVServer

    with KVServer(0, host="127.0.0.1") as a:
        KVClient(f"127.0.0.1:{a.port}").put("job", "n1", "e1")
        with KVServer(0, host="127.0.0.1") as b:
            assert KVClient(f"127.0.0.1:{b.port}").scan("job") == {}
            assert KVClient(f"127.0.0.1:{a.port}").get("job", "n1") == "e1"
