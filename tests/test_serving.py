"""Continuous-batching serving engine (ISSUE 3): exact-match decode vs
sequential models.generate, slot reuse, bounded compile cache, backpressure,
graceful drain, streaming HTTP e2e — all on CPU."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import generate, sample_tokens
from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
from paddle_tpu.serving import (
    ContinuousBatchingEngine,
    FCFSScheduler,
    QueueFullError,
    Request,
    RequestFailedError,
    SchedulerClosed,
    ServingClient,
    ServingServer,
    power_of_two_buckets,
)

VOCAB = 64


def _tiny_model():
    paddle.seed(0)
    cfg = gpt_config("gpt2-small", vocab_size=VOCAB, hidden_size=32,
                     num_layers=2, num_attention_heads=4,
                     max_position_embeddings=64, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    m = GPTForPretraining(cfg)
    m.eval()
    return m


def _sequential(model, prompt, n, eos=None):
    out = generate(model, paddle.to_tensor(np.asarray(prompt)[None]),
                   max_new_tokens=n, eos_token_id=eos)
    return np.asarray(out._data)[0]


@pytest.fixture(scope="module")
def model():
    return _tiny_model()


# ---------------------------------------------------------------------------
# engine correctness
# ---------------------------------------------------------------------------
class TestEngineExactMatch:
    def test_concurrent_matches_sequential_greedy(self, model):
        """N=8 staggered mixed-length greedy requests through 4 slots ==
        sequential models.generate token-for-token, within the bounded
        compile budget (acceptance criterion)."""
        rng = np.random.default_rng(0)
        lens = [3, 5, 7, 4, 9, 6, 2, 8]
        news = [6, 4, 8, 5, 3, 7, 6, 5]
        prompts = [rng.integers(0, VOCAB, (l,)).astype(np.int32)
                   for l in lens]
        want = [_sequential(model, p, n) for p, n in zip(prompts, news)]

        buckets = [4, 8, 16]
        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=4,
                                       prefill_buckets=buckets)
        # stagger arrivals: first wave, a few ticks, second wave
        first = [eng.submit(Request(p, max_new_tokens=n))
                 for p, n in zip(prompts[:5], news[:5])]
        for _ in range(3):
            eng.step_once()
        second = [eng.submit(Request(p, max_new_tokens=n))
                  for p, n in zip(prompts[5:], news[5:])]
        eng.run_until_idle(timeout=300)

        for req, w in zip(first + second, want):
            np.testing.assert_array_equal(req.result(), w)
        # bounded compile cache: <= len(buckets) prefills + 1 decode step
        assert eng.trace_count <= len(buckets) + 1
        assert eng.trace_counts["step"] == 1

    def test_slot_reuse_after_eos(self, model):
        """A request finishing early (eos) frees its slot mid-run; a queued
        request reuses it and still decodes exactly."""
        rng = np.random.default_rng(1)
        prompts = [rng.integers(0, VOCAB, (4 + i % 3,)).astype(np.int32)
                   for i in range(6)]
        # derive a real eos: token the first request actually emits early
        probe = _sequential(model, prompts[0], 6)
        eos = int(probe[len(prompts[0]) + 1])  # its 2nd generated token
        want = [_sequential(model, p, 6, eos=eos) for p in prompts]

        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=2,
                                       prefill_buckets=[8],
                                       max_prefills_per_tick=2)
        reqs = [Request(p, max_new_tokens=6, eos_token_id=eos)
                for p in prompts]
        got = eng.generate_batch(reqs, timeout=300)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)
        # 6 requests over 2 slots => slots were reused
        assert eng.metrics.requests_completed == 6

    def test_prefill_bucket_compile_bound(self, model):
        """Many mixed-length requests; trace counter stays <= buckets + 1
        (the compile-cache guarantee the scheduler's bucketing buys)."""
        rng = np.random.default_rng(2)
        buckets = power_of_two_buckets(16, min_bucket=4)  # [4, 8, 16]
        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=3,
                                       prefill_buckets=buckets, max_queue=64)
        reqs = [Request(rng.integers(0, VOCAB, (int(l),)).astype(np.int32),
                        max_new_tokens=3)
                for l in rng.integers(1, 17, size=12)]
        eng.generate_batch(reqs, timeout=300)
        assert eng.trace_count <= len(buckets) + 1
        snap = eng.metrics.snapshot()
        assert snap["compile_cache"]["prefill_compiles"] <= len(buckets)
        assert snap["compile_cache"]["step_compiles"] == 1
        # cache HITS dominate once the buckets are warm
        assert snap["compile_cache"]["prefill_hits"] >= 12 - len(buckets)

    def test_mixed_sampling_single_program(self, model):
        """Greedy and sampled requests share the one compiled step; greedy
        outputs stay exact while sampled rows stay in-vocab."""
        rng = np.random.default_rng(3)
        greedy_p = rng.integers(0, VOCAB, (5,)).astype(np.int32)
        sampled_p = rng.integers(0, VOCAB, (6,)).astype(np.int32)
        want = _sequential(model, greedy_p, 5)
        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=2,
                                       prefill_buckets=[8])
        g = eng.submit(Request(greedy_p, max_new_tokens=5))
        s = eng.submit(Request(sampled_p, max_new_tokens=5, temperature=0.9,
                               top_k=8, top_p=0.95, seed=7))
        eng.run_until_idle(timeout=300)
        np.testing.assert_array_equal(g.result(), want)
        assert len(s.tokens) == 5
        assert all(0 <= t < VOCAB for t in s.tokens)
        assert eng.trace_counts["step"] == 1
        # same seed => same sampled continuation on a fresh engine
        eng2 = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=2,
                                        prefill_buckets=[8])
        s2 = eng2.submit(Request(sampled_p, max_new_tokens=5, temperature=0.9,
                                 top_k=8, top_p=0.95, seed=7))
        eng2.run_until_idle(timeout=300)
        assert s2.tokens == s.tokens

    def test_capacity_validation(self, model):
        eng = ContinuousBatchingEngine(model, max_seq_len=16, n_slots=1,
                                       prefill_buckets=[8])
        with pytest.raises(ValueError, match="KV capacity"):
            eng.submit(Request(np.arange(8, dtype=np.int32),
                               max_new_tokens=16))
        # r21: a PAGED engine chunks a prompt past the largest bucket
        # (the chunk loop always runs) — it is admitted, not rejected
        long = eng.submit(Request(np.arange(12, dtype=np.int32),
                                  max_new_tokens=1))
        eng.run_until_idle(timeout=300)
        assert long.state == Request.DONE
        # the slot layout has no chunk loop: over-bucket still rejects
        slot = ContinuousBatchingEngine(model, max_seq_len=16, n_slots=1,
                                        prefill_buckets=[8],
                                        kv_layout="slot")
        with pytest.raises(ValueError, match="bucket"):
            slot.submit(Request(np.arange(12, dtype=np.int32),
                                max_new_tokens=1))


# ---------------------------------------------------------------------------
# scheduler policy
# ---------------------------------------------------------------------------
class TestScheduler:
    def test_power_of_two_buckets(self):
        assert power_of_two_buckets(64, min_bucket=16) == [16, 32, 64]
        assert power_of_two_buckets(20, min_bucket=4) == [4, 8, 16, 20]
        assert power_of_two_buckets(4, min_bucket=8) == [4]

    def test_queue_backpressure(self):
        sched = FCFSScheduler([8], max_queue=2)
        sched.submit(Request([1, 2], max_new_tokens=1))
        sched.submit(Request([1, 2], max_new_tokens=1))
        with pytest.raises(QueueFullError):
            sched.submit(Request([1, 2], max_new_tokens=1))

    def test_fcfs_and_interleave_cap(self):
        sched = FCFSScheduler([8], max_queue=8, max_prefills_per_tick=2)
        reqs = [sched.submit(Request([i + 1], max_new_tokens=1))
                for i in range(5)]
        # prefill/decode interleave: at most 2 admissions per tick even
        # with more free slots
        takes = sched.take_admissions(free_slots=4)
        assert takes == reqs[:2]
        assert sched.take_admissions(free_slots=4) == reqs[2:4]

    def test_closed_rejects(self):
        sched = FCFSScheduler([8])
        sched.close()
        with pytest.raises(SchedulerClosed):
            sched.submit(Request([1], max_new_tokens=1))


# ---------------------------------------------------------------------------
# HTTP front-end
# ---------------------------------------------------------------------------
class TestServer:
    def test_streaming_endpoint_e2e(self, model):
        """Tokens arrive over the stream endpoint incrementally and match
        both the poll endpoint and sequential generate."""
        rng = np.random.default_rng(4)
        prompt = rng.integers(0, VOCAB, (5,)).astype(np.int32)
        want = _sequential(model, prompt, 8)
        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=2,
                                       prefill_buckets=[8])
        with ServingServer(eng) as srv:
            cli = ServingClient(srv.addr)
            rid = cli.submit(prompt, max_new_tokens=8)
            toks = list(cli.stream(rid))
            assert toks == list(want[5:])
            res = cli.wait(rid, timeout=60)
            assert res["status"] == "done"
            assert res["tokens"] == toks
            mx = cli.metrics()
            assert mx["ttft_seconds"]["count"] >= 1
            assert mx["tokens_generated"] >= 8
            assert mx["compile_cache"]["step_compiles"] == 1

    def test_backpressure_429_and_drain_503(self, model):
        """Queue overflow surfaces as 429 through the wire; after drain
        starts new submissions get 503 while in-flight requests finish."""
        rng = np.random.default_rng(5)
        prompts = [rng.integers(0, VOCAB, (4,)).astype(np.int32)
                   for _ in range(6)]
        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=1,
                                       prefill_buckets=[8], max_queue=2)
        srv = ServingServer(eng)
        # don't start the engine loop yet: force the queue to fill
        srv._http_thread = threading.Thread(
            target=srv._httpd.serve_forever, daemon=True)
        srv._http_thread.start()
        cli = ServingClient(srv.addr)
        ids = [cli.submit(p, max_new_tokens=4) for p in prompts[:2]]
        with pytest.raises(QueueFullError):
            cli.submit(prompts[2], max_new_tokens=4)
        # now start the engine and drain: queued requests must complete
        srv._engine_thread = threading.Thread(
            target=eng.serve_forever, args=(srv._stop,), daemon=True)
        srv._engine_thread.start()
        srv.drain(timeout=120)
        for rid in ids:
            res = cli.result(rid)
            assert res["status"] == "done"
            assert len(res["tokens"]) == 4
        with pytest.raises(SchedulerClosed):
            cli.submit(prompts[3], max_new_tokens=4)
        srv._httpd.shutdown()
        srv._httpd.server_close()

    def test_bad_requests(self, model):
        eng = ContinuousBatchingEngine(model, max_seq_len=16, n_slots=1,
                                       prefill_buckets=[8])
        with ServingServer(eng) as srv:
            cli = ServingClient(srv.addr)
            with pytest.raises(RuntimeError, match="submit failed \\(400\\)"):
                cli.submit(list(range(8)), max_new_tokens=64)  # capacity
            status, out = cli._call("GET", "/v1/result/nope")
            assert status == 404


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------
class TestMetrics:
    def test_snapshot_fields(self, model):
        rng = np.random.default_rng(6)
        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=2,
                                       prefill_buckets=[8])
        reqs = [Request(rng.integers(0, VOCAB, (4,)).astype(np.int32),
                        max_new_tokens=4) for _ in range(3)]
        eng.generate_batch(reqs, timeout=300)
        snap = eng.metrics.snapshot()
        assert snap["requests"]["submitted"] == 3
        assert snap["requests"]["completed"] == 3
        assert snap["tokens_generated"] == 12
        assert snap["ttft_seconds"]["count"] == 3
        assert snap["ttft_seconds"]["p50"] is not None
        assert snap["ttft_seconds"]["p95"] >= snap["ttft_seconds"]["p50"]
        assert snap["token_latency_seconds"]["count"] >= 1
        assert 0.0 <= snap["slot_occupancy"]["fraction"] <= 1.0
        assert snap["throughput_tokens_per_sec"] is None or \
            snap["throughput_tokens_per_sec"] > 0

    def test_profiler_scope_integration(self, model):
        """serving.prefill / serving.decode_step land in the profiler
        TimerRegistry when timers are armed, and in /metrics."""
        from paddle_tpu.profiler.scope import (
            disable_timers,
            enable_timers,
            reset_timers,
            timer_report,
        )

        rng = np.random.default_rng(7)
        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=1,
                                       prefill_buckets=[8])
        reset_timers()
        enable_timers()
        try:
            eng.generate_batch(
                [Request(rng.integers(0, VOCAB, (4,)).astype(np.int32),
                         max_new_tokens=3)], timeout=300)
            rep = timer_report()
        finally:
            disable_timers()
            reset_timers()
        assert rep["serving.prefill"]["count"] >= 1
        assert rep["serving.decode_step"]["count"] >= 1


# ---------------------------------------------------------------------------
# batched key-driven sampler (ISSUE 3 satellite)
# ---------------------------------------------------------------------------
class TestSampleTokens:
    def test_greedy_rows_exact(self):
        import jax
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.standard_normal((4, 16)).astype("float32"))
        assert (np.asarray(sample_tokens(logits, None))
                == np.asarray(jnp.argmax(logits, -1))).all()
        keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(4))
        out = np.asarray(sample_tokens(
            logits, keys, temperature=jnp.array([0.0, 1.0, 0.0, 0.5]),
            top_k=jnp.array([0, 3, 0, 2]), top_p=1.0))
        want = np.asarray(jnp.argmax(logits, -1))
        assert out[0] == want[0] and out[2] == want[2]

    def test_per_row_top_k_respected(self):
        import jax
        import jax.numpy as jnp

        rng = np.random.default_rng(1)
        logits = jnp.asarray(rng.standard_normal((2, 32)).astype("float32"))
        top3 = set(np.argsort(np.asarray(logits[1]))[-3:].tolist())
        for s in range(16):
            keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(2) + 10 * s)
            out = sample_tokens(logits, keys,
                                temperature=jnp.array([1.0, 1.0]),
                                top_k=jnp.array([0, 3]), top_p=1.0)
            assert int(out[1]) in top3

    def test_row_independence_of_batch(self):
        """A row's sample depends only on its own key/params — slots can't
        perturb each other's sampling."""
        import jax
        import jax.numpy as jnp

        rng = np.random.default_rng(2)
        logits = jnp.asarray(rng.standard_normal((3, 16)).astype("float32"))
        keys = jax.vmap(jax.random.PRNGKey)(jnp.asarray([5, 6, 7]))
        t = jnp.array([0.8, 0.8, 0.8])
        full = np.asarray(sample_tokens(logits, keys, t, 5, 0.9))
        solo = np.asarray(sample_tokens(logits[1:2], keys[1:2], t[1:2],
                                        5, 0.9))
        assert solo[0] == full[1]

    def test_one_trace_for_mixed_params(self):
        import jax
        import jax.numpy as jnp

        calls = [0]

        def f(lg, kk, t, k, p):
            calls[0] += 1
            return sample_tokens(lg, kk, t, k, p)

        jf = jax.jit(f)
        rng = np.random.default_rng(3)
        lg = jnp.asarray(rng.standard_normal((2, 8)).astype("float32"))
        keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(2))
        for t0 in (0.0, 0.5, 1.0):
            jf(lg, keys, jnp.full((2,), t0, jnp.float32),
               jnp.array([0, 4], jnp.int32), jnp.array([1.0, 0.9], jnp.float32))
        assert calls[0] == 1

    def test_generate_greedy_unchanged(self):
        """The refactor keeps generate()'s greedy path byte-identical and
        RNG-free (seeded programs reproduce)."""
        m = _tiny_model()
        rng = np.random.default_rng(4)
        prompt = rng.integers(0, VOCAB, (2, 5)).astype(np.int32)
        import jax

        paddle.seed(123)
        a = np.asarray(generate(m, paddle.to_tensor(prompt),
                                max_new_tokens=5)._data)
        state = np.asarray(jax.random.key_data(paddle.get_rng_state()))
        paddle.seed(123)
        b = np.asarray(generate(m, paddle.to_tensor(prompt),
                                max_new_tokens=5)._data)
        np.testing.assert_array_equal(a, b)
        # greedy draws no keys: rng state equals a fresh seed's state
        paddle.seed(123)
        np.testing.assert_array_equal(
            state, np.asarray(jax.random.key_data(paddle.get_rng_state())))


class TestEngineFailureContainment:
    def test_tick_failure_fails_requests_not_thread(self, model):
        """An exception inside a tick marks affected requests FAILED (with
        the error recorded) instead of silently killing the loop thread,
        and the client stream surfaces the failure (as RequestFailedError —
        the request's verdict, not a replica-health event)."""
        rng = np.random.default_rng(8)
        prompt = rng.integers(0, VOCAB, (4,)).astype(np.int32)
        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=1,
                                       prefill_buckets=[8])

        def boom(*a, **k):
            raise RuntimeError("injected device fault")

        eng._prefill_jit = boom
        with ServingServer(eng) as srv:
            cli = ServingClient(srv.addr)
            rid = cli.submit(prompt, max_new_tokens=4)
            res = cli.wait(rid, timeout=60)
            assert res["status"] == "failed"
            assert "injected device fault" in res["error"]
            with pytest.raises(RequestFailedError,
                               match="failed after 0 tokens"):
                list(cli.stream(rid))
