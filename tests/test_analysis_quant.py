"""Quantitative graph doctor (ISSUE 5): cost model, liveness/peak-HBM
estimator, memory rules, planner cross-check, and the NaN-attributing
sanitizer interpreter.

Cost/liveness tests hand-compute the documented conventions on minimal
jaxprs (dot chain, donated update, scan carry, shard_map-sharded sizes);
the sanitizer tests assert exact first-offender attribution (eqn + r6
profiler scope); the estimator-vs-measured test enforces the 15%
acceptance bound against a real (CPU) trainer step.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import analysis as an
from paddle_tpu.analysis import (
    AnalysisTarget,
    LowIntensityDotRule,
    MemoryBudgetRule,
    RematAdvisorRule,
    SanitizerConfig,
    Severity,
    estimate_memory,
    graph_cost,
    planner_drift_findings,
    sanitize,
)


def _sev(findings, severity):
    return [f for f in findings if f.severity == severity]


def _mesh(n, axes=("x",)):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} virtual devices")
    shape = (n,) if len(axes) == 1 else None
    return Mesh(np.array(jax.devices()[:n]).reshape(shape), axes)


# ---------------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------------
class TestCostModel:
    def test_dot_chain_exact_flops_and_bytes(self):
        def f(x, w1, w2):
            return (x @ w1) @ w2

        t = AnalysisTarget("t", f, (jnp.ones((4, 8), jnp.float32),
                                    jnp.ones((8, 16), jnp.float32),
                                    jnp.ones((16, 2), jnp.float32)))
        gc = graph_cost(t.graph())
        # dot1 = 2*4*16*8 = 1024, dot2 = 2*4*2*16 = 256
        assert gc.flops == 1024 + 256
        # dot1: in (4*8 + 8*16)*4 = 640, out 4*16*4 = 256
        # dot2: in (4*16 + 16*2)*4 = 384, out 4*2*4  = 32
        assert gc.bytes_accessed == 640 + 256 + 384 + 32
        assert gc.unknown == {} and not gc.estimated

    def test_elementwise_transcendental_reduction(self):
        from paddle_tpu.analysis.cost import TRANSCENDENTAL_FLOPS

        def f(x):
            return jnp.tanh(x * x).sum()

        t = AnalysisTarget("t", f, (jnp.ones((4, 8), jnp.float32),))
        gc = graph_cost(t.graph())
        # mul 32 + tanh 8*32 + reduce_sum 32 (per input element)
        assert gc.flops == 32 + TRANSCENDENTAL_FLOPS * 32 + 32

    def test_scan_body_multiplied_by_trip_count(self):
        def f(c, xs):
            return lax.scan(lambda c, x: (c * x, ()), c, xs)[0]

        t = AnalysisTarget("t", f, (jnp.ones(4), jnp.ones((5, 4))))
        gc = graph_cost(t.graph())
        assert gc.flops == 5 * 4            # one mul of 4 elems, 5 iters

    def test_collective_comm_bytes_from_mesh_axes(self):
        from paddle_tpu.distributed.spmd import shard_map

        mesh = _mesh(4)
        sm = shard_map(lambda a: lax.psum(a, "x"), mesh=mesh,
                       in_specs=P("x"), out_specs=P())
        t = AnalysisTarget("t", sm, (jnp.ones(8, jnp.float32),),
                           mesh_axes={"x": 4})
        gc = graph_cost(t.graph(), t.mesh_axes)
        # per-shard payload 2 f32 = 8 B; ring allreduce 2*(4-1)/4 * 8 = 12
        assert gc.comm_bytes == pytest.approx(12.0)

    def test_unknown_prim_reported_never_zero_costed(self):
        def f(x):
            return lax.sort(x)

        t = AnalysisTarget("t", f, (jnp.ones(16, jnp.float32),))
        gc = graph_cost(t.graph())
        assert "sort" in gc.unknown and gc.estimated
        # fallback still carries the bytes moved
        assert gc.bytes_accessed >= 2 * 16 * 4

    def test_intensity_classification(self):
        from paddle_tpu.analysis.cost import classify_intensity, cost_eqn

        c = cost_eqn("dot_general",
                      (((512, 512), "float32", False),
                       ((512, 512), "float32", False)),
                      (((512, 512), "float32", False),),
                      {"dimension_numbers": (((1,), (0,)), ((), ()))})
        assert c.flops == 2 * 512 ** 3
        assert classify_intensity(c.intensity, ridge=80.0) == "compute-bound"
        assert classify_intensity(c.intensity, ridge=240.0) == "memory-bound"


# ---------------------------------------------------------------------------
# liveness / peak HBM
# ---------------------------------------------------------------------------
class TestLiveness:
    def test_dot_chain_peak_exact(self):
        def f(x, w1, w2):
            return (x @ w1) @ w2

        t = AnalysisTarget("t", f, (jnp.ones((4, 8), jnp.float32),
                                    jnp.ones((8, 16), jnp.float32),
                                    jnp.ones((16, 2), jnp.float32)))
        est = estimate_memory(t)
        args = (4 * 8 + 8 * 16 + 16 * 2) * 4        # 768, held throughout
        # peak at dot2: args + h1 (4*16*4=256) + out (4*2*4=32)
        assert est.args_bytes == args
        assert est.peak_bytes == args + 256 + 32
        assert est.resident_bytes == args + 32      # args + out, no consts
        assert est.peak_prim == "dot_general"

    def test_donated_update_aliases_output(self):
        s = jnp.zeros((1024,), jnp.float32)         # 4096 B
        plain = estimate_memory(AnalysisTarget(
            "t", jax.jit(lambda st, x: (st + x, x.sum())), (s, s)))
        donated = estimate_memory(AnalysisTarget(
            "t", jax.jit(lambda st, x: (st + x, x.sum()),
                         donate_argnums=(0,)), (s, s)))
        assert donated.donated_bytes == 4096
        # non-donated: both input copies + new state + loss stay resident
        assert plain.resident_bytes == 2 * 4096 + 4096 + 4
        # donated: the new state reuses the donated buffer
        assert donated.resident_bytes == 2 * 4096 + 4
        assert donated.peak_bytes < plain.peak_bytes

    def test_intended_donation_override(self):
        """donate_argnums metadata models the TPU deployment even when the
        live jit gated donation off (serving on CPU)."""
        s = jnp.zeros((1024,), jnp.float32)
        f = jax.jit(lambda st, x: (st + x, x.sum()))    # no actual donation
        est = estimate_memory(AnalysisTarget("t", f, (s, s),
                                             donate_argnums=(0,)))
        assert est.donated_bytes == 4096
        assert est.resident_bytes == 2 * 4096 + 4

    def test_scan_carry_and_accumulator(self):
        def f(c, xs):
            def body(c, x):
                c = c + x
                return c, c * 2

            return lax.scan(body, c, xs)

        t = AnalysisTarget("t", f, (jnp.zeros(4, jnp.float32),
                                    jnp.ones((8, 4), jnp.float32)))
        est = estimate_memory(t)
        # args 16+128; outs (final carry 16 + stacked ys 128) allocated up
        # front; body peak adds carry-passthrough(16)+x-slice(16)+c1(16)
        # while ambient holds args+outs minus the carry passthrough
        assert est.args_bytes == 144
        assert est.peak_bytes == (144 + 144) - 16 + (16 + 16 + 16) + 16
        assert est.out_bytes == 144

    def test_shard_map_uses_per_shard_sizes(self):
        from paddle_tpu.distributed.spmd import shard_map

        mesh = _mesh(2)
        sm = shard_map(lambda a: a * 2, mesh=mesh, in_specs=P("x"),
                       out_specs=P("x"))
        est = estimate_memory(AnalysisTarget(
            "t", sm, (jnp.ones(8, jnp.float32),), mesh_axes={"x": 2}))
        # 8 f32 sharded over x=2 -> 16 B per device, inputs AND outputs
        assert est.args_bytes == 16
        assert est.out_bytes == 16
        assert est.sharded
        assert est.peak_bytes < 8 * 4 * 2       # well under the global view

    def test_sharded_pjit_entry_divides_arg_bytes(self):
        from jax.sharding import NamedSharding

        mesh = _mesh(2)
        sh = NamedSharding(mesh, P("x"))
        f = jax.jit(lambda x: x * 2, in_shardings=(sh,), out_shardings=sh)
        est = estimate_memory(AnalysisTarget(
            "t", f, (jax.device_put(jnp.ones(8, jnp.float32), sh),)))
        assert est.args_bytes == 16
        assert est.out_bytes == 16

    def test_consts_counted_resident(self):
        W = jnp.zeros((256, 256), jnp.float32)      # 256 KiB closure const
        est = estimate_memory(AnalysisTarget(
            "t", jax.jit(lambda x: x @ W), (jnp.ones((4, 256)),)))
        assert est.consts_bytes == 256 * 256 * 4
        assert est.resident_bytes >= est.consts_bytes

    def test_timeline_and_peak_site_attribution(self):
        from paddle_tpu.profiler.scope import scope

        def f(x):
            with scope("model.ffn"):
                h = x @ x
                return h.sum()

        est = estimate_memory(AnalysisTarget(
            "t", f, (jnp.ones((64, 64), jnp.float32),)))
        assert est.timeline and est.peak_bytes >= est.args_bytes
        assert "model.ffn" in est.peak_scope


# ---------------------------------------------------------------------------
# memory rules: trigger + clean pairs
# ---------------------------------------------------------------------------
class TestMemoryRules:
    def _dot_chain(self):
        def f(x, w1, w2):
            return (x @ w1) @ w2

        return AnalysisTarget("t", f, (jnp.ones((4, 8), jnp.float32),
                                       jnp.ones((8, 16), jnp.float32),
                                       jnp.ones((16, 2), jnp.float32)))

    def test_oom_risk_trigger_and_clean(self):
        t = self._dot_chain()                       # peak 1056 B
        fs = an.run_rules(t, [MemoryBudgetRule(budget_bytes=1000)])
        assert _sev(fs, Severity.HIGH), fs
        assert fs[0].details["peak_bytes"] == 1056
        t2 = self._dot_chain()
        assert an.run_rules(t2, [MemoryBudgetRule(budget_bytes=1 << 20)]) == []

    def test_oom_risk_headroom_medium(self):
        t = self._dot_chain()
        fs = an.run_rules(t, [MemoryBudgetRule(budget_bytes=1100)])
        assert _sev(fs, Severity.MEDIUM) and not _sev(fs, Severity.HIGH)

    def test_low_intensity_dot_trigger_and_clean(self):
        # GEMV: 2*4096*4096 flops over a 64 MiB weight read -> ~0.5 f/B
        gemv = AnalysisTarget(
            "t", lambda x, w: x @ w,
            (jnp.ones((1, 4096), jnp.float32),
             jnp.ones((4096, 4096), jnp.float32)))
        fs = an.run_rules(gemv, [LowIntensityDotRule()])
        assert _sev(fs, Severity.MEDIUM), fs
        assert fs[0].details["intensity"] < 1.0
        # square 512 matmul: ~85 f/B, compute-bound -> clean
        sq = AnalysisTarget(
            "t", lambda x, w: x @ w,
            (jnp.ones((512, 512), jnp.float32),
             jnp.ones((512, 512), jnp.float32)))
        assert an.run_rules(sq, [LowIntensityDotRule()]) == []

    def test_remat_advisor_trigger_and_clean(self):
        def f(x):
            a = jnp.tanh(x)         # cheap-to-recompute, live at the peak
            b = x * 2.0
            return (a * b).sum()

        t = AnalysisTarget("t", f, (jnp.ones((256, 256), jnp.float32),))
        fs = an.run_rules(t, [RematAdvisorRule(min_bytes=1024)])
        assert fs and fs[0].rule == "remat-advisor"
        assert fs[0].details["candidates"]
        # same program, default 1 MiB floor: too small to advise on
        t2 = AnalysisTarget("t", f, (jnp.ones((8, 8), jnp.float32),))
        assert an.run_rules(t2, [RematAdvisorRule()]) == []

    def test_remat_advisor_escalates_over_budget(self):
        def f(x):
            return (jnp.tanh(x) * (x * 2.0)).sum()

        t = AnalysisTarget("t", f, (jnp.ones((256, 256), jnp.float32),))
        fs = an.run_rules(t, [RematAdvisorRule(min_bytes=1024,
                                               budget_bytes=1024)])
        assert _sev(fs, Severity.MEDIUM), fs


# ---------------------------------------------------------------------------
# planner cross-check (satellite)
# ---------------------------------------------------------------------------
class TestPlannerDrift:
    def test_gpt_config_within_tolerance(self):
        fs = planner_drift_findings()
        assert _sev(fs, Severity.MEDIUM) == [], fs
        info = _sev(fs, Severity.INFO)
        assert info and "params" in info[0].message

    def test_drifting_stats_flagged_medium(self):
        from paddle_tpu.distributed.auto_parallel.planner import ModelStats

        bad = ModelStats(n_params=1000, n_layers=2, hidden=32, seq_len=16)
        fs = planner_drift_findings(stats=bad)
        meds = _sev(fs, Severity.MEDIUM)
        assert meds and meds[0].rule == "planner-drift"
        assert meds[0].details["component"] == "params"


# ---------------------------------------------------------------------------
# sanitizer: first-NaN attribution
# ---------------------------------------------------------------------------
class TestSanitizer:
    def _nan_net(self):
        from paddle_tpu.profiler.scope import scope

        def f(x, w):
            h = x @ w
            with scope("model.blk2"):
                h = jnp.log(h - 10.0)       # negative under zeros -> NaN
            return (h @ w).sum()

        return f

    def test_first_nan_exact_eqn_and_scope(self):
        r = sanitize(self._nan_net(),
                     (jnp.ones((2, 4), jnp.float32),
                      jnp.ones((4, 4), jnp.float32)))
        assert not r.ok
        assert r.first.prim == "log"              # the producer, not users
        assert "model.blk2" in r.first.scope
        assert "test_analysis_quant" in r.first.source
        assert r.first.n_nan == r.first.n_nonfinite == 8

    def test_clean_run_returns_outputs(self):
        f = self._nan_net()
        args = (jnp.full((2, 4), 10.0, jnp.float32),
                jnp.ones((4, 4), jnp.float32))
        r = sanitize(f, args)
        assert r.ok and r.checked_values > 0
        np.testing.assert_allclose(np.asarray(r.outputs[0]),
                                   np.asarray(f(*args)), rtol=1e-6)

    def test_pjit_recursion_preserves_attribution(self):
        r = sanitize(jax.jit(self._nan_net()),
                     (jnp.ones((2, 4), jnp.float32),
                      jnp.ones((4, 4), jnp.float32)))
        assert r.first.prim == "log" and "model.blk2" in r.first.scope
        assert any(p.startswith("pjit") for p in r.first.path)

    def test_scan_iteration_attributed(self):
        def f(x):
            def body(c, t):
                c = c / (t - 2.0)           # t == 2 -> division by zero
                return c, c

            return lax.scan(body, x, jnp.arange(5, dtype=jnp.float32))

        r = sanitize(f, (jnp.ones(3, jnp.float32),))
        assert r.first.prim == "div" and r.first.iteration == 2

    def test_cond_takes_concrete_branch(self):
        def f(x):
            return lax.cond(x.sum() > 0,
                            lambda v: jnp.log(v - 10.0),   # NaN branch
                            lambda v: v, x)

        r = sanitize(f, (jnp.ones(4, jnp.float32),))
        assert r.first.prim == "log"
        assert any("branch1" in p for p in r.first.path)
        r2 = sanitize(f, (-jnp.ones(4, jnp.float32),))     # clean branch
        assert r2.ok

    def test_chunk_size_does_not_change_attribution(self):
        f = self._nan_net()
        args = (jnp.ones((2, 4), jnp.float32),
                jnp.ones((4, 4), jnp.float32))
        r1 = sanitize(f, args, config=SanitizerConfig(check_every=1))
        r2 = sanitize(f, args, config=SanitizerConfig(check_every=1000))
        assert (r1.first.prim, r1.first.eqn_index) == \
            (r2.first.prim, r2.first.eqn_index)

    def test_nan_only_mode_ignores_inf(self):
        def f(x):
            return x / jnp.zeros_like(x)    # inf, never NaN

        args = (jnp.ones(4, jnp.float32),)
        assert sanitize(f, args).first.prim == "div"
        assert sanitize(
            f, args, config=SanitizerConfig(check_inf=False)).ok

    def test_masked_nan_literal_skipped_but_strict_flags(self):
        def f(x):
            return jnp.var(x)               # where(n>0, var, nan) guard

        args = (jnp.ones(8, jnp.float32),)
        assert sanitize(f, args).ok
        strict = sanitize(f, args, config=SanitizerConfig(
            skip_nonfinite_literals=False))
        assert not strict.ok

    def test_half_precision_inf_mask_literal_skipped(self):
        """bf16 -inf mask literals are ml_dtypes — np.issubdtype(...,
        np.floating) misses them, so the intentional-literal skip must
        use jnp dtype logic (the bf16 attention-mask idiom)."""
        def f(x):
            return jnp.where(x > 0, x,
                             jnp.asarray(-jnp.inf, jnp.bfloat16)).sum()

        args = (jnp.ones((2, 4), jnp.bfloat16),)
        assert sanitize(f, args).ok
        strict = sanitize(f, args, config=SanitizerConfig(
            skip_nonfinite_literals=False))
        assert not strict.ok

    def test_nan_only_count_excludes_intentional_inf(self):
        """check_inf=False: the report's bad-value count is NaNs only —
        intentional infs sharing the offending output are not counted."""
        def f(x, m):
            return (x / jnp.zeros_like(x)) * m   # [inf, inf, inf, nan]

        args = (jnp.ones(4, jnp.float32),
                jnp.asarray([1.0, 1.0, 1.0, 0.0], jnp.float32))
        r = sanitize(f, args, config=SanitizerConfig(check_inf=False))
        assert not r.ok and r.first.prim == "mul"
        assert r.first.n_nan == 1
        assert r.first.n_nonfinite == 1          # not the 3 masked infs

    def test_bind_whole_strips_donation(self):
        """The bind-whole path (recurse=False, or any structured-descent
        failure) must not honor a pjit's donated_invars — that would
        delete the caller's live arrays out from under it."""
        s = jnp.ones((64,), jnp.float32)
        f = jax.jit(lambda st, x: (st + x, x.sum()), donate_argnums=(0,))
        r = sanitize(f, (s, s), config=SanitizerConfig(recurse=False))
        assert r.ok
        np.testing.assert_allclose(np.asarray(s), 1.0)   # s still alive

    def test_while_replay_fidelity(self):
        def f(x):
            return lax.while_loop(lambda c: c[0] < 5,
                                  lambda c: (c[0] + 1, c[1] * 2.0),
                                  (jnp.int32(0), x))[1]

        args = (jnp.ones(3, jnp.float32),)
        r = sanitize(f, args)
        assert r.ok
        np.testing.assert_allclose(np.asarray(r.outputs[-1]),
                                   np.asarray(f(*args)))


# ---------------------------------------------------------------------------
# trainer sanitize_step (satellite wiring half)
# ---------------------------------------------------------------------------
class TestTrainerSanitize:
    def test_planted_nan_attributed_from_snapshot(self):
        import paddle_tpu.distributed as dist
        import paddle_tpu.optimizer as popt
        from paddle_tpu.nn import Linear, Sequential

        prev = dist.get_mesh()
        dist.init_mesh({"dp": 1})
        try:
            paddle.seed(0)
            model = Sequential(Linear(8, 16), Linear(16, 1))
            tr = dist.ParallelTrainer(
                model, lambda o, y: ((o - y) ** 2).mean(), popt.SGD(0.01),
                dp_axis=None)
            X = np.zeros((4, 8), np.float32)
            Y = np.zeros((4, 1), np.float32)
            tr.step(paddle.to_tensor(X), paddle.to_tensor(Y))
            snap = tr.capture_state()
            bad = X.copy()
            bad[0, 0] = np.nan
            res = tr.sanitize_step(bad, Y, state=snap)
            assert not res.ok
            # the planted input NaN surfaces at its first consumer
            assert res.first.n_nonfinite >= 1
            # the live training state was untouched by the eager replay
            tr.step(paddle.to_tensor(X), paddle.to_tensor(Y))
            # same guarantee on the bind-whole path (recurse=False binds
            # the donating top pjit as a unit; donation must be stripped)
            from paddle_tpu.analysis import SanitizerConfig as SC

            tr.sanitize_step(X, Y, config=SC(recurse=False))
            tr.step(paddle.to_tensor(X), paddle.to_tensor(Y))
        finally:
            dist.set_mesh(prev)


# ---------------------------------------------------------------------------
# estimator vs measured (ISSUE 5 acceptance: <= 15% on the CPU arm)
# ---------------------------------------------------------------------------
class TestEstimatorVsMeasured:
    def test_trainer_step_within_15_percent(self):
        import paddle_tpu.distributed as dist
        from bench import _analysis_estimator_vs_measured

        prev = dist.get_mesh()
        try:
            out = _analysis_estimator_vs_measured()
        finally:
            dist.set_mesh(prev)
        assert out["memory_measured_live_bytes"] > 0
        assert abs(out["memory_est_vs_measured"]) <= 0.15, out


# ---------------------------------------------------------------------------
# CLI: --memory / --sanitize / --device-budget
# ---------------------------------------------------------------------------
class TestCLIQuant:
    def test_memory_mode_end_to_end(self, tmp_path):
        import json

        from paddle_tpu.analysis.cli import main

        out = tmp_path / "mem.json"
        rc = main(["--memory", "--only", "static_program",
                   "--out", str(out)])
        assert rc == 0
        data = json.loads(out.read_text())
        assert data["schema_version"] == 1
        entry = data["entry_points"]["static_program"]
        assert entry["peak_hbm_bytes"] > 0
        assert entry["resident_bytes"] > 0
        assert "cost" in entry and entry["timeline"]
        # zero crashed rules (acceptance)
        assert not any("crashed" in f["message"] for f in data["findings"])

    def test_sanitize_mode_end_to_end(self, tmp_path):
        import json

        from paddle_tpu.analysis.cli import main

        out = tmp_path / "san.json"
        rc = main(["--sanitize", "--only", "static_program",
                   "--out", str(out)])
        assert rc == 0
        data = json.loads(out.read_text())
        assert data["entry_points"]["static_program"]["ok"] is True
        assert data["entry_points"]["static_program"]["checked_values"] > 0

    def test_conflicting_modes_are_usage_errors(self, tmp_path):
        from paddle_tpu.analysis.cli import main

        for argv in (["--memory", "--sanitize"],
                     ["--nan-only"],
                     ["--sanitize", "--device-budget", "100"]):
            with pytest.raises(SystemExit) as e:
                main(argv + ["--out", str(tmp_path / "x.json")])
            assert e.value.code == 2       # argparse usage error

    def test_device_budget_gates_exit_one(self, tmp_path):
        import json

        from paddle_tpu.analysis.cli import main

        out = tmp_path / "mem.json"
        rc = main(["--memory", "--only", "static_program",
                   "--device-budget", "64", "--out", str(out)])
        assert rc == 1
        data = json.loads(out.read_text())
        assert any(f["rule"] == "oom-risk" and f["severity"] == "HIGH"
                   for f in data["findings"])
