"""Quorum-replicated coordination store (ISSUE 12).

Fast tier: leader election + lease mechanics, quorum-acked writes with the
durability invariant across a leader kill, epoch fencing of a partitioned
stale leader, snapshot catch-up for lagging rejoiners, client-transparent
failover through `ReplicatedKVClient` and the `_TcpStore` multi-address
spec, the r13 inject seams (append drop / lease-renew faults / replica
kill), KVClient keep-alive reuse, and the deterministic injected twins:
leader-kill-during-rendezvous and leader-kill-during-allgather — both
replayed twice with identical fired logs and a training trajectory
bit-identical to the uninterrupted run.

Slow tier (``-m chaos``): the real-SIGKILL leader e2e — three replica
PROCESSES, the leader killed mid-elastic-DP-training.
"""
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.fleet.elastic.manager import (
    ElasticManager,
    StoreUnavailable,
    _TcpStore,
)
from paddle_tpu.distributed.fleet.utils.http_server import KVClient, KVServer
from paddle_tpu.distributed.fleet.utils.replicated_store import (
    ReplicatedKVClient,
    ReplicatedStoreCluster,
    quorum_size,
)
from paddle_tpu.resilience import FaultSchedule
from paddle_tpu.resilience.elastic_trainer import ElasticDPTrainer

LEASE = 0.5  # every in-process cluster in this file


@pytest.fixture()
def cluster():
    cl = ReplicatedStoreCluster(3, lease_ttl=LEASE).start()
    yield cl
    cl.stop()


def _client(cl, timeout=2.0):
    return ReplicatedKVClient(cl.addrs, timeout=timeout)


# =====================================================================
# quorum basics: election, replication, acks
# =====================================================================
class TestQuorumBasics:
    def test_quorum_size(self):
        assert [quorum_size(n) for n in (1, 2, 3, 4, 5)] == [1, 2, 2, 3, 3]

    def test_single_deterministic_leader_at_boot(self, cluster):
        lead = cluster.leader(timeout=10)
        # all replicas boot with equal (epoch, seq); the vote tiebreak
        # means only the highest id can collect a quorum
        assert lead.node_id == "s2"
        assert lead.epoch >= 1
        assert sum(s.is_leader() for s in cluster.servers) == 1

    def test_put_get_delete_scan_roundtrip(self, cluster):
        cluster.leader(timeout=10)
        c = _client(cluster)
        assert c.put("job", "k", "v", strict=True)
        assert c.get("job", "k", strict=True) == "v"
        assert c.get("job", "absent") is None
        assert c.put("job", "k2", "w", strict=True)
        scan = c.scan("job", strict=True)
        assert {k: v for k, (v, _a) in scan.items()} == {"k": "v", "k2": "w"}
        keys = c.scan("job", keys_only=True, prefix="k2")
        assert set(keys) == {"k2"} and keys["k2"][0] is None
        assert c.delete("job", "k", strict=True)
        assert c.get("job", "k") is None

    def test_follower_redirects_to_leader(self, cluster):
        import json

        lead = cluster.leader(timeout=10)
        follower = next(s for s in cluster.servers if not s.is_leader())
        raw = KVClient(follower.addr, timeout=2.0)
        # the hint is None in the brief window between granting the vote
        # and the winner's first lease append landing — poll past it
        deadline = time.monotonic() + 10
        hint = None
        while time.monotonic() < deadline and hint is None:
            status, body = raw._request("PUT", "/job/k", body=b"v")
            assert status == 409
            hint = json.loads(body.decode())["not_leader"]
            if hint is None:
                time.sleep(0.05)
        assert hint == lead.addr
        # the replicated client follows the hint transparently
        c = ReplicatedKVClient([follower.addr], timeout=2.0)
        assert c.put("job", "k", "v", strict=True)
        assert c.get("job", "k", strict=True) == "v"

    def test_write_needs_quorum(self, cluster):
        lead = cluster.leader(timeout=10)
        c = _client(cluster)
        assert c.put("job", "pre", "1", strict=True)
        for s in cluster.servers:
            if s is not lead:
                s.kill()
        # 1 of 3 alive: the survivor may still think itself leader but can
        # never ack — no false acknowledgements, strict raises
        assert c.put("job", "lost", "x") is False
        with pytest.raises(OSError):
            c.put("job", "lost", "x", strict=True)

    def test_replicated_ages_preserve_ttl_liveness(self, cluster):
        """Key ages ride the replication records, so TTL liveness judged
        on the NEW leader after a failover continues from the write time,
        not from the failover."""
        lead = cluster.leader(timeout=10)
        c = _client(cluster)
        assert c.put("job", "hb", "ep", strict=True)
        time.sleep(0.3)
        lead.kill()
        cluster.wait_for_leader_change(lead.node_id, timeout=10)
        deadline = time.monotonic() + 10
        age = None
        while time.monotonic() < deadline:
            try:
                age = c.scan("job", strict=True)["hb"][1]
                break
            except OSError:
                time.sleep(0.05)
        assert age is not None and age >= 0.3


# =====================================================================
# failover: durability invariant + client transparency
# =====================================================================
class TestFailover:
    def test_acked_writes_survive_leader_kill(self, cluster):
        """THE durability invariant: every write acknowledged before the
        leader is killed is readable after the election."""
        lead = cluster.leader(timeout=10)
        c = _client(cluster)
        acked = {}
        for i in range(25):
            assert c.put("job", f"key{i}", f"val{i}", strict=True)
            acked[f"key{i}"] = f"val{i}"
        lead.kill()
        new = cluster.wait_for_leader_change(lead.node_id, timeout=10)
        assert new.epoch > lead.epoch
        deadline = time.monotonic() + 10
        got = None
        while time.monotonic() < deadline:
            try:
                got = {k: v for k, (v, _a) in
                       c.scan("job", strict=True).items()}
                break
            except OSError:
                time.sleep(0.05)
        assert got is not None
        lost = {k: v for k, v in acked.items() if got.get(k) != v}
        assert lost == {}
        # and the new leader accepts writes
        assert c.put("job", "after", "x", strict=True)

    def test_tcpstore_multi_address_spec(self, cluster):
        cluster.leader(timeout=10)
        st = _TcpStore(cluster.addr_spec, "mjob", ttl=2.5, retries=5)
        assert isinstance(st.client, ReplicatedKVClient)
        st.register("node_a", "1.2.3.4:1")
        st.put("k", "v")
        assert st.get("k") == "v"
        assert st.nodes() == ["node_a"]
        assert st.endpoints() == ["1.2.3.4:1"]

    def test_tcpstore_single_address_unchanged(self):
        """The bit-comparison fallback: one address = the plain KVClient
        path, byte-for-byte the pre-r16 behavior."""
        with KVServer(0, host="127.0.0.1") as srv:
            st = _TcpStore(f"127.0.0.1:{srv.port}", "sjob", ttl=2.0)
            assert isinstance(st.client, KVClient)
            assert not isinstance(st.client, ReplicatedKVClient)
            st.register("n", "e")
            assert st.nodes() == ["n"]

    def test_heartbeat_rides_out_failover(self, cluster):
        lead = cluster.leader(timeout=10)
        st = _TcpStore(cluster.addr_spec, "hjob", ttl=2.5, retries=5)
        st.register("node_a", "ep")
        lead.kill()
        st.heartbeat("node_a")  # retry burst + redirects mask the election
        assert st.nodes() == ["node_a"]

    def test_unreachable_cluster_raises_store_unavailable(self):
        st = _TcpStore("127.0.0.1:1,127.0.0.1:2", "djob", ttl=0.4,
                       retries=1)
        with pytest.raises(StoreUnavailable):
            st.heartbeat("n")

    def test_lagging_follower_catches_up_via_snapshot(self, cluster):
        """A partitioned (≙ down) follower misses writes; on heal, the
        next append finds it behind and pushes a full snapshot."""
        cluster.leader(timeout=10)
        c = _client(cluster)
        assert c.put("job", "k0", "v0", strict=True)
        lag = next(s for s in cluster.servers if not s.is_leader())
        lag.partition(True)
        for i in range(1, 8):
            assert c.put("job", f"k{i}", f"v{i}", strict=True)
        assert lag.read_scope("job").get("k5") is None
        lag.partition(False)
        # the next replicated record (a write or a lease renewal) triggers
        # behind → install; renewals tick every lease/3
        assert c.put("job", "heal", "1", strict=True)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            got = {k: v for k, (v, _a) in lag.read_scope("job").items()}
            if got.get("k5") == "v5" and got.get("heal") == "1":
                break
            time.sleep(0.05)
        got = {k: v for k, (v, _a) in lag.read_scope("job").items()}
        assert got.get("k5") == "v5" and got.get("heal") == "1"


# =====================================================================
# epoch fencing: the stale-leader satellite
# =====================================================================
class TestFencing:
    def test_fenced_stale_leader_write_rejected(self, cluster):
        """A partitioned deposed leader keeps accepting client RPCs but
        its appends carry a lower epoch: followers reject them, the write
        is NEVER acknowledged, and the key never reaches the new epoch."""
        lead = cluster.leader(timeout=10)
        c = _client(cluster)
        assert c.put("job", "pre", "1", strict=True)
        lead.partition(True)
        stale = KVClient(lead.addr, timeout=2.0)
        status, _ = stale._request("PUT", "/job/stale_key", body=b"evil")
        assert status == 503  # accepted by nobody: NOT acknowledged
        new = cluster.wait_for_leader_change(lead.node_id, timeout=10)
        assert new.epoch > lead.epoch
        # client-transparent: the same client object now lands on the new
        # leader; the unacked stale write is invisible
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                assert c.get("job", "stale_key", strict=True) is None
                assert c.get("job", "pre", strict=True) == "1"
                break
            except OSError:
                time.sleep(0.05)
        assert c.put("job", "post", "2", strict=True)
        # heal: the deposed leader adopts the higher epoch, follows, and
        # its phantom record is TRUNCATED by snapshot install (not just
        # hidden behind the leader redirect)
        lead.partition(False)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            got = {k: v for k, (v, _a) in lead.read_scope("job").items()}
            if (lead.role == "follower" and lead.epoch >= new.epoch
                    and got.get("post") == "2"
                    and "stale_key" not in got):
                break
            time.sleep(0.05)
        assert lead.role == "follower"
        assert lead.epoch >= new.epoch
        got = {k: v for k, (v, _a) in lead.read_scope("job").items()}
        assert got.get("post") == "2" and "stale_key" not in got
        # the healed ex-leader is now safely electable: kill the current
        # leader — whoever wins must serve every acked write, no phantom
        new.kill()
        cluster.wait_for_leader_change(new.node_id, timeout=10)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                assert c.get("job", "pre", strict=True) == "1"
                assert c.get("job", "post", strict=True) == "2"
                assert c.get("job", "stale_key", strict=True) is None
                break
            except OSError:
                time.sleep(0.05)
        assert c.get("job", "stale_key") is None

    def test_phantom_tail_never_acks_new_leaders_append(self):
        """Log matching (the durability invariant's teeth): a replica
        whose tail was written by a DEPOSED leadership — locally applied,
        never acked — must not dup-ack the new leader's same-seq record
        nor accept a gap-free append on top; both demand a snapshot,
        which truncates the phantom even when seqs tie."""
        cl = ReplicatedStoreCluster(3, lease_ttl=30.0)  # never started
        try:
            a = cl.servers[0]
            a.epoch = 1
            a._apply({"epoch": 1, "seq": 1, "op": "put", "scope": "s",
                      "key": "k", "value": "phantom", "age": 0.0})
            # new leader (epoch 2) replicates ITS record at the SAME seq:
            # a false "already applied" ack here would count divergent
            # state toward the quorum and lose the acknowledged write
            status, doc = a.handle_replicate(
                {"epoch": 2, "seq": 1, "op": "put", "scope": "s",
                 "key": "k", "value": "acked", "age": 0.0,
                 "prev_epoch": 0, "leader": "x"})
            assert (status, doc["error"]) == (409, "behind")
            # the snapshot repairs the divergence even at equal seq
            status, _ = a.handle_install(
                {"epoch": 2, "seq": 1, "last_epoch": 2,
                 "kv": {"s": {"k": ["acked", 0.0]}}})
            assert status == 200
            assert a.read_scope("s")["k"][0] == "acked"
            assert (a.seq, a.last_epoch) == (1, 2)
            # gap-free append onto a mismatched tail is refused too
            b = cl.servers[1]
            b.epoch = 1
            b._apply({"epoch": 1, "seq": 1, "op": "put", "scope": "s",
                      "key": "k", "value": "phantom", "age": 0.0})
            status, doc = b.handle_replicate(
                {"epoch": 2, "seq": 2, "op": "put", "scope": "s",
                 "key": "k2", "value": "v", "age": 0.0,
                 "prev_epoch": 0, "leader": "x"})
            assert (status, doc["error"]) == (409, "behind")
        finally:
            cl.stop()

    def test_observability_series_and_flight_dump(self, cluster):
        from paddle_tpu.observability.flight import flight_recorder
        from paddle_tpu.observability.metrics import default_registry

        lead = cluster.leader(timeout=10)
        r = default_registry()
        assert r.get("store_role").value(node=lead.node_id) == 2
        follower = next(s for s in cluster.servers if not s.is_leader())
        assert r.get("store_role").value(node=follower.node_id) == 0
        assert r.get("store_epoch").value(node=lead.node_id) == lead.epoch
        before = r.get("store_failovers_total").value(node="s1")
        lead.kill()
        new = cluster.wait_for_leader_change(lead.node_id, timeout=10)
        assert r.get("store_role").value(node=new.node_id) == 2
        if new.node_id == "s1":
            assert (r.get("store_failovers_total").value(node="s1")
                    >= before + 1)
        # a leader change freezes a flight snapshot (in-memory even when
        # no directory is armed); the dump lands just after the role
        # flips, so poll briefly
        deadline = time.monotonic() + 5
        last = None
        while time.monotonic() < deadline:
            last = flight_recorder().last
            if (last is not None
                    and last["reason"] == "store_leader_change"
                    and last["extra"]["node"] == new.node_id):
                break
            time.sleep(0.02)
        assert last is not None
        assert last["reason"] == "store_leader_change"
        assert last["extra"]["node"] == new.node_id


# =====================================================================
# inject seams
# =====================================================================
class TestInjectSeams:
    def test_append_drop_single_peer_still_acks(self, cluster):
        """Dropping the append to ONE peer leaves a 2/3 quorum — the
        write still acknowledges; the fired log records the drop."""
        cluster.leader(timeout=10)
        c = _client(cluster)
        sched = FaultSchedule(seed=3).add(
            "store.replica.append", "drop", match={"peer": "s0"}, at=1)
        with sched:
            assert c.put("job", "k", "v", strict=True)
        assert [f["point"] for f in sched.fired_log()] == [
            "store.replica.append"]
        assert c.get("job", "k", strict=True) == "v"

    def test_append_drop_both_peers_no_ack(self, cluster):
        """Dropping the appends to BOTH peers starves the quorum: the
        client gets a failure, never a false ack."""
        lead = cluster.leader(timeout=10)
        c = _client(cluster)
        sched = (FaultSchedule(seed=4)
                 .add("store.replica.append", "drop",
                      match={"node": lead.node_id, "op": "put"}, every=1))
        with sched:
            assert c.put("job", "k", "v") is False
        assert len(sched.fired_log()) == 2  # one drop per peer

    def test_lease_renew_fault_forces_failover(self, cluster):
        """A leader whose every renewal raises cannot hold its lease: the
        survivors elect a successor and the deposed leader steps down."""
        lead = cluster.leader(timeout=10)
        sched = (FaultSchedule(seed=5)
                 .add("store.lease.renew", "raise",
                      match={"node": lead.node_id}, every=1))
        with sched:
            new = cluster.wait_for_leader_change(lead.node_id, timeout=15)
        assert new.node_id != lead.node_id
        assert len(sched.fired_log()) >= 1
        deadline = time.monotonic() + 10
        while lead.role == "leader" and time.monotonic() < deadline:
            time.sleep(0.05)
        assert lead.role != "leader"

    def test_replica_kill_replays_identically(self):
        """Two runs of the same seeded kill schedule produce the same
        fired log — the replay certificate."""
        logs = []
        for _ in range(2):
            sched = FaultSchedule(seed=11).add(
                "store.replica.kill", "kill", match={"node": "s2"}, at=4)
            cl = ReplicatedStoreCluster(3, lease_ttl=LEASE)
            with sched:
                cl.start()
                try:
                    new = cl.wait_for_leader_change("s2", timeout=15)
                    assert new.node_id in ("s0", "s1")
                    assert cl.servers[2].dead
                finally:
                    cl.stop()
            logs.append(sched.fired_log())
        assert logs[0] == logs[1] == [
            {"point": "store.replica.kill", "kind": "kill", "count": 4,
             "labels": {"node": "s2"}}]


# =====================================================================
# KVClient keep-alive reuse (satellite)
# =====================================================================
class TestKVClientKeepAlive:
    def test_connection_reused_across_rpcs(self):
        with KVServer(0, host="127.0.0.1") as srv:
            c = KVClient(f"127.0.0.1:{srv.port}", timeout=2.0)
            dials = {"n": 0}
            real = c._conn

            def counting():
                dials["n"] += 1
                return real()

            c._conn = counting
            for i in range(10):
                assert c.put("s", f"k{i}", "v")
            assert c.get("s", "k0") == "v"
            assert c.scan("s") and dials["n"] == 1

    def test_stale_connection_redials_transparently(self):
        srv = KVServer(0, host="127.0.0.1").start()
        port = srv.port
        c = KVClient(f"127.0.0.1:{port}", timeout=2.0)
        assert c.put("s", "k", "v")
        srv.stop()
        srv2 = KVServer(port, host="127.0.0.1").start()
        try:
            # cached connection is stale (old server gone): one redial,
            # no error surfaced to the caller
            assert c.put("s", "k2", "v2", strict=True)
            assert c.get("s", "k2", strict=True) == "v2"
        finally:
            srv2.stop()

    def test_dead_server_still_raises_for_strict(self):
        srv = KVServer(0, host="127.0.0.1").start()
        port = srv.port
        c = KVClient(f"127.0.0.1:{port}", timeout=1.0)
        assert c.put("s", "k", "v")
        srv.stop()
        with pytest.raises(OSError):
            c.put("s", "k", "v", strict=True)
        assert c.put("s", "k", "v") is False


# =====================================================================
# deterministic injected twins: leader kill under elastic DP training
# =====================================================================
_W_STAR = np.arange(12.0).reshape(4, 3) / 10.0


def _dp_grad_fn(params, step, rank, world):
    rng = np.random.default_rng(100000 + 1000 * step + 10 * world + rank)
    X = rng.standard_normal((8, 4))
    E = X @ params["w"] + params["b"] - X @ _W_STAR
    loss = float((E ** 2).mean())
    return loss, {"w": 2 * X.T @ E / E.size,
                  "b": 2 * E.sum(axis=0) / E.size}


def _dp_init_params():
    return {"w": np.zeros((4, 3)), "b": np.zeros((3,))}


class TestLeaderKillTwins:
    TOTAL = 5

    def _run_cohort(self, tag, ckpt, n_ranks, *, schedule=None,
                    start_delays=None, total=None):
        """Elastic-DP rank THREADS over a fresh 3-replica cluster;
        ``schedule`` (armed globally — the kill fires in a store monitor
        thread, not a rank thread) drives store chaos. Returns per-rank
        histories."""
        cl = ReplicatedStoreCluster(3, lease_ttl=LEASE)
        if schedule is not None:
            schedule.arm()
        cl.start()
        histories = {i: [] for i in range(n_ranks)}
        errors = {}

        def rank_fn(i):
            try:
                if start_delays:
                    time.sleep(start_delays[i])
                st = _TcpStore(cl.addr_spec, f"job_{tag}", ttl=2.5,
                               retries=5)
                mgr = ElasticManager(store=st)
                mgr.endpoint = f"127.0.0.1:{7700 + i}"
                mgr.node_id = f"node_{i}"
                tr = ElasticDPTrainer(
                    mgr, ckpt, _dp_grad_fn, _dp_init_params, lr=0.3,
                    momentum=0.9, min_ranks=1, step_timeout=60,
                    rendezvous_timeout=60,
                    on_step=lambda s, w, l: histories[i].append(
                        (s, w, np.float64(l).hex())))
                tr.run(total or self.TOTAL, wait_world=n_ranks)
                tr.close()
            except Exception as e:  # pragma: no cover - surfaced below
                errors[i] = repr(e)

        threads = [threading.Thread(target=rank_fn, args=(i,), daemon=True)
                   for i in range(n_ranks)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(180)
                assert not t.is_alive(), "rank thread hung"
        finally:
            if schedule is not None:
                schedule.disarm()
            cl.stop()
        assert not errors, errors
        return histories

    def _kill_schedule(self, at):
        # the boot-time leader is deterministically s2 (highest id wins
        # the equal-tuple tiebreak); kill it at its Nth monitor tick
        return FaultSchedule(seed=11).add(
            "store.replica.kill", "kill", match={"node": "s2"}, at=at)

    def test_leader_kill_during_allgather_bit_identical(self, tmp_path):
        """Kill the store leader mid-training (the ranks are inside the
        gradient allgather loop by then): training continues through the
        failover and the trajectory is bit-identical to an uninterrupted
        run — with identical fired logs across two replays."""
        runs, logs = [], []
        for leg in ("a", "b"):
            sched = self._kill_schedule(at=8)  # ~8 ticks ≈ mid-training
            runs.append(self._run_cohort(
                f"ag_{leg}", str(tmp_path / f"ck_{leg}"), 2,
                schedule=sched))
            logs.append(sched.fired_log())
        assert logs[0] == logs[1] == [
            {"point": "store.replica.kill", "kind": "kill", "count": 8,
             "labels": {"node": "s2"}}]
        plain = self._run_cohort("ag_p", str(tmp_path / "ck_p"), 2)
        assert runs[0] == runs[1] == plain
        steps = {s: (w, l) for s, w, l in runs[0][0]}
        assert sorted(steps) == list(range(self.TOTAL))
        assert all(w == 2 for w, _l in steps.values())

    def test_leader_kill_during_rendezvous_bit_identical(self, tmp_path):
        """Two ranks wait mid-rendezvous for a delayed third while the
        store leader is killed: rendezvous converges after the election
        and the trajectory matches the uninterrupted 3-rank run."""
        delays = [0.0, 0.0, 2.0]  # rank 2 joins after the failover
        runs, logs = [], []
        for leg in ("a", "b"):
            sched = self._kill_schedule(at=4)  # fires while 0/1 poll
            runs.append(self._run_cohort(
                f"rdv_{leg}", str(tmp_path / f"ck_{leg}"), 3,
                schedule=sched, start_delays=delays, total=3))
            logs.append(sched.fired_log())
        assert logs[0] == logs[1] == [
            {"point": "store.replica.kill", "kind": "kill", "count": 4,
             "labels": {"node": "s2"}}]
        plain = self._run_cohort("rdv_p", str(tmp_path / "ck_p"), 3,
                                 start_delays=delays, total=3)
        assert runs[0] == runs[1] == plain
        steps = {s: (w, l) for s, w, l in runs[0][0]}
        assert sorted(steps) == [0, 1, 2]
        assert all(w == 3 for w, _l in steps.values())


# =====================================================================
# real-SIGKILL leader e2e (chaos tier, like the other three suites)
# =====================================================================
@pytest.mark.slow
@pytest.mark.chaos
def test_sigkill_store_leader_mid_training_bit_identical(tmp_path):
    """Three replica PROCESSES; SIGKILL the leader process mid-elastic-DP
    training: rendezvous and allgather continue after lease expiry and
    the trajectory is bit-identical to an uninterrupted run."""
    import socket

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(
                   p for p in (repo, os.environ.get("PYTHONPATH")) if p))

    def launch_cluster():
        addrs = [f"127.0.0.1:{free_port()}" for _ in range(3)]
        spec = ",".join(addrs)
        procs = []
        for i in range(3):
            p = subprocess.Popen(
                [sys.executable, "-m",
                 "paddle_tpu.distributed.fleet.utils.replicated_store",
                 "--index", str(i), "--addrs", spec,
                 "--lease-ttl", "1.0"],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                env=env)
            procs.append(p)
        return addrs, spec, procs

    def wait_leader(addrs, timeout=30.0):
        c = ReplicatedKVClient(addrs, timeout=2.0)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            doc = c.leader_status()
            if doc is not None:
                return doc
            time.sleep(0.1)
        raise TimeoutError("no leader in the process cluster")

    def run_training(tag, spec, ckpt, kill=None):
        """Two rank threads; ``kill`` = (leader_pid, after_step): SIGKILL
        that pid once rank 0 passes the step."""
        histories = {0: [], 1: []}
        errors = {}
        killed = threading.Event()

        def on_step(i, s, w, l):
            histories[i].append((s, w, np.float64(l).hex()))
            if kill and i == 0 and s >= kill[1] and not killed.is_set():
                killed.set()
                os.kill(kill[0], signal.SIGKILL)

        def rank_fn(i):
            try:
                st = _TcpStore(spec, f"job_{tag}", ttl=4.0, retries=6)
                mgr = ElasticManager(store=st)
                mgr.endpoint = f"127.0.0.1:{7800 + i}"
                mgr.node_id = f"node_{i}"
                tr = ElasticDPTrainer(
                    mgr, ckpt, _dp_grad_fn, _dp_init_params, lr=0.3,
                    momentum=0.9, min_ranks=1, step_timeout=120,
                    rendezvous_timeout=120,
                    on_step=lambda s, w, l: on_step(i, s, w, l))
                tr.run(8, wait_world=2)
                tr.close()
            except Exception as e:  # pragma: no cover
                errors[i] = repr(e)

        threads = [threading.Thread(target=rank_fn, args=(i,), daemon=True)
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(240)
            assert not t.is_alive(), "rank thread hung"
        assert not errors, errors
        if kill:
            assert killed.is_set(), "kill trigger never reached"
        return histories

    # -- interrupted arm -------------------------------------------------
    addrs, spec, procs = launch_cluster()
    try:
        doc = wait_leader(addrs)
        leader_idx = int(doc["id"][1:])
        hist_kill = run_training("kill", spec, str(tmp_path / "ck_kill"),
                                 kill=(procs[leader_idx].pid, 2))
        assert procs[leader_idx].poll() is not None  # really died
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    # -- uninterrupted arm ----------------------------------------------
    addrs2, spec2, procs2 = launch_cluster()
    try:
        wait_leader(addrs2)
        hist_plain = run_training("plain", spec2,
                                  str(tmp_path / "ck_plain"))
    finally:
        for p in procs2:
            if p.poll() is None:
                p.kill()

    # the acceptance criterion: bit-identical trajectories, all steps at
    # world 2, both ranks agreeing
    assert hist_kill == hist_plain
    steps = {s: (w, l) for s, w, l in hist_kill[0]}
    assert sorted(steps) == list(range(8))
    assert all(w == 2 for w, _l in steps.values())
    assert hist_kill[0] == hist_kill[1]
