"""Int8 weight PTQ (ISSUE 18): per-channel absmax quantization math,
calibration, the pinned quality-delta certificate, the engine's
``weight_dtype="int8"`` plane, the durable quantized artifact
(save/load round trip + corrupt-scale detection), and the
dequant-materialization lint (positive, negative, and KV-exempt cases)
with the shipped int8 entry points coming back zero HIGH.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.analysis.graph import AnalysisTarget
from paddle_tpu.analysis.rules import DtypePromotionRule, analyze_targets
from paddle_tpu.inference import load_quantized, save_quantized
from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
from paddle_tpu.quantization import (
    calibrate_activations_,
    post_training_quantize_,
    quality_delta,
    quantize_model_weights_,
    quantized_layer_names,
)
from paddle_tpu.serving import ContinuousBatchingEngine, Request

VOCAB = 64


def _np(t):
    return np.asarray(t._data)


def _tiny_model(seed=0):
    paddle.seed(seed)
    cfg = gpt_config("gpt2-small", vocab_size=VOCAB, hidden_size=32,
                     num_layers=2, num_attention_heads=4,
                     max_position_embeddings=64, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    m = GPTForPretraining(cfg)
    m.eval()
    return m


class TestWeightQuant:
    def test_per_channel_absmax_roundtrip_error_bounded(self):
        paddle.seed(0)
        lin = nn.Linear(16, 8)
        w = _np(lin.weight).copy()
        (name,) = quantize_model_weights_(lin)
        q = _np(lin.weight)
        assert q.dtype == np.int8
        scale = _np(lin.weight_scale)
        assert scale.shape == (8,)
        np.testing.assert_allclose(
            scale, np.maximum(np.abs(w).max(axis=0) / 127.0, 1e-8),
            rtol=1e-6)
        # dequantized weight within half a quantization level per channel
        assert np.abs(q.astype(np.float32) * scale[None, :] - w).max() \
            <= scale.max() / 2 + 1e-7

    def test_idempotent_and_skip(self):
        paddle.seed(0)
        lin = nn.Linear(8, 4)
        assert quantize_model_weights_(lin)
        assert quantize_model_weights_(lin) == []  # already int8
        paddle.seed(0)
        lin2 = nn.Linear(8, 4)
        assert quantize_model_weights_(lin2, skip=lambda n: True) == []
        assert _np(lin2.weight).dtype == np.float32

    def test_outlier_ratio_keeps_fp(self):
        paddle.seed(0)
        lin = nn.Linear(8, 4)
        w = _np(lin.weight).copy()
        w[:, 0] *= 1e4  # one channel's absmax dominates
        lin.weight._set_data(jnp.asarray(w))
        assert quantize_model_weights_(lin, outlier_ratio=100.0) == []
        assert quantize_model_weights_(lin) != []  # no guard: quantizes

    def test_quantized_layer_names(self):
        model = _tiny_model()
        assert quantized_layer_names(model) == []
        done = quantize_model_weights_(model)
        assert sorted(done) == sorted(quantized_layer_names(model))
        assert len(done) == 8  # 4 linears x 2 blocks

    def test_calibration_registers_act_scale(self):
        model = _tiny_model()
        rng = np.random.default_rng(0)
        batches = [rng.integers(0, VOCAB, (1, 8)).astype(np.int32)
                   for _ in range(2)]
        records = calibrate_activations_(model, batches)
        assert records  # absmax observed per layer
        done = quantize_model_weights_(model)
        for name in done:
            layer = dict(model.named_sublayers(include_self=True))[name]
            assert float(_np(layer.act_scale)) > 0


class TestQualityDelta:
    def test_pinned_certificate(self):
        """The ISSUE's pinned quality delta on fixed seeds: small logit
        error, low greedy divergence — NOT bit-exactness."""
        fp = _tiny_model(0)
        q = _tiny_model(0)
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, VOCAB, (8,)) for _ in range(4)]
        cal = [rng.integers(0, VOCAB, (1, 8)).astype(np.int32)
               for _ in range(2)]
        post_training_quantize_(q, calibration_batches=cal)
        qd = quality_delta(fp, q, prompts)
        assert qd["positions"] == 32
        assert qd["logit_max_abs_err"] < 0.25
        assert qd["greedy_divergence_rate"] <= 0.15

    def test_identical_models_are_exact(self):
        m = _tiny_model(0)
        qd = quality_delta(m, m, [np.arange(1, 7)])
        assert qd["logit_max_abs_err"] == 0.0
        assert qd["greedy_divergence_rate"] == 0.0


class TestServingInt8Weights:
    def test_engine_weight_dtype_int8_serves(self):
        """weight_dtype="int8" quantizes at engine build; greedy output
        stays within the pinned divergence of the fp engine."""
        fp_model = _tiny_model(0)
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, VOCAB, (l,)).astype(np.int32)
                   for l in [3, 5, 7]]
        fp = ContinuousBatchingEngine(
            fp_model, max_seq_len=32, n_slots=3, prefill_buckets=[8],
            page_size=4)
        want = [fp.submit(Request(p, max_new_tokens=6)) for p in prompts]
        fp.run_until_idle(timeout=300)

        q_model = _tiny_model(0)
        q = ContinuousBatchingEngine(
            q_model, max_seq_len=32, n_slots=3, prefill_buckets=[8],
            page_size=4, weight_dtype="int8")
        assert quantized_layer_names(q_model)  # engine ran the PTQ pass
        got = [q.submit(Request(p, max_new_tokens=6)) for p in prompts]
        q.run_until_idle(timeout=300)
        div = tot = 0
        for r, w in zip(got, want):
            assert r.state == Request.DONE, (r.state, r.error)
            g, ww = np.asarray(r.result()), np.asarray(w.result())
            div += int((g != ww).sum())
            tot += len(ww)
        assert div / tot <= 0.15, f"divergence {div}/{tot}"


class TestQuantizedArtifact:
    def test_save_load_round_trip_exact(self, tmp_path):
        q = _tiny_model(0)
        rng = np.random.default_rng(0)
        cal = [rng.integers(0, VOCAB, (1, 8)).astype(np.int32)]
        names = post_training_quantize_(q, calibration_batches=cal)
        path = os.path.join(str(tmp_path), "model.pdq8")
        assert save_quantized(q, path) == sorted(names)
        # overlay onto the SAME fp base: bit-identical logits
        fresh = _tiny_model(0)
        assert load_quantized(fresh, path) == sorted(names)
        qd = quality_delta(q, fresh, [rng.integers(0, VOCAB, (6,))])
        assert qd["logit_max_abs_err"] == 0.0

    def test_corrupt_scale_detected(self, tmp_path):
        q = _tiny_model(0)
        quantize_model_weights_(q)
        path = os.path.join(str(tmp_path), "model.pdq8")
        save_quantized(q, path)
        blob = bytearray(open(path, "rb").read())
        blob[-5] ^= 0xFF  # flip a payload (scale-region) byte
        bad = path + ".bad"
        with open(bad, "wb") as f:
            f.write(bytes(blob))
        fresh = _tiny_model(0)
        before = {n: _np(l.weight).copy()
                  for n, l in fresh.named_sublayers(include_self=True)
                  if hasattr(l, "weight") and getattr(
                      l.weight, "ndim", 0) == 2}
        with pytest.raises(ValueError, match="CRC mismatch"):
            load_quantized(fresh, bad)
        # the model was left untouched
        for n, l in fresh.named_sublayers(include_self=True):
            if n in before:
                np.testing.assert_array_equal(_np(l.weight), before[n])

    def test_unquantized_model_refused(self, tmp_path):
        with pytest.raises(ValueError, match="no int8 layers"):
            save_quantized(_tiny_model(0),
                           os.path.join(str(tmp_path), "x.pdq8"))


class TestDequantLint:
    def _target(self, fn, args, name):
        return AnalysisTarget(name, fn, args)

    def test_materialized_dequant_flagged_high(self):
        def bad(x, wq, scale):
            w = wq.astype(jnp.float32) * scale[None, :]
            return x @ w

        sds = jax.ShapeDtypeStruct
        args = (sds((4, 16), np.float32), sds((16, 8), np.int8),
                sds((8,), np.float32))
        fs = DtypePromotionRule().run(self._target(bad, args, "bad"))
        assert any("dequantized int8 weight" in f.message
                   and str(f.severity).upper().endswith("HIGH")
                   for f in fs)

    def test_w8a8_scale_fused_clean(self):
        def good(x, wq, scale):
            sx = jnp.maximum(jnp.max(jnp.abs(x)) / 127.0, 1e-8)
            xq = jnp.clip(jnp.round(x / sx), -127, 127).astype(jnp.int8)
            acc = jax.lax.dot_general(
                xq, wq, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            return acc.astype(jnp.float32) * (sx * scale)

        sds = jax.ShapeDtypeStruct
        args = (sds((4, 16), np.float32), sds((16, 8), np.int8),
                sds((8,), np.float32))
        assert not DtypePromotionRule().run(
            self._target(good, args, "good"))

    def test_gather_fed_kv_dequant_exempt(self):
        def kvlike(q, pool, scales, pages):
            g = pool[pages]
            s = scales[pages]
            k = g.astype(jnp.float32) * s[:, :, None]
            return jnp.einsum("nd,npd->np", q, k)

        sds = jax.ShapeDtypeStruct
        args = (sds((2, 8), np.float32), sds((16, 4, 8), np.int8),
                sds((16, 4), np.float32), sds((2,), np.int32))
        fs = DtypePromotionRule().run(self._target(kvlike, args, "kv"))
        assert not [f for f in fs
                    if "dequantized int8 weight" in f.message]

    def test_shipped_int8_entry_points_zero_high(self):
        """The acceptance criterion: the quantized serving programs lint
        clean — no materialized dequant anywhere in the int8 plane."""
        from paddle_tpu.analysis.entrypoints import serving_int8_targets

        report = analyze_targets(serving_int8_targets())
        highs = [f for f in report.findings
                 if str(f.severity).upper().endswith("HIGH")]
        assert not highs, [f.message for f in highs]
