"""Fault-tolerant training runtime: anomaly sentinel, preemption-safe
checkpoints, self-healing elastic store.

Parity model: FLAGS_check_nan_inf device guards (nan_inf_utils_detail),
incubate/checkpoint auto-snapshot tests, and fleet elastic fault-tolerance
(test_fleet_elastic_manager.py) — redesigned per paddle_tpu/resilience/.
"""
import os
import signal
import subprocess
import sys
import textwrap
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.checkpoint import (
    CheckpointCorruptionError,
    CheckpointManager,
    _join_live_managers,
    load_checkpoint,
    save_checkpoint,
)
from paddle_tpu.resilience import (
    SENTINEL_NONFINITE,
    SENTINEL_OK,
    SENTINEL_SPIKE,
    AnomalyHalt,
    PreemptionGuard,
    RetryError,
    SentinelConfig,
    SentinelMonitor,
    backoff_delays,
    call_with_retries,
    capture_train_state,
    sentinel_init_state,
    sentinel_observe,
    sentinel_to_host,
)


def _tiny_trainer(sentinel=None, scaler=None, seed=0):
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.optimizer.optimizers import AdamW

    paddle.seed(seed)
    clear_mesh()
    init_mesh({"dp": 1})
    net = paddle.nn.Linear(4, 4)
    opt = AdamW(learning_rate=1e-2, parameters=net.parameters())
    return ParallelTrainer(net, lambda o, y: ((o - y) ** 2).mean(), opt,
                           dp_axis=None, sentinel=sentinel, scaler=scaler,
                           donate=False)


def _batch(rng, scale=1.0):
    x = paddle.to_tensor((rng.standard_normal((8, 4)) * scale).astype("float32"))
    y = paddle.to_tensor(rng.standard_normal((8, 4)).astype("float32"))
    return x, y


# =====================================================================
# sentinel state machine (pure functional)
# =====================================================================
class TestSentinelMachine:
    def test_nonfinite_loss_flagged(self):
        cfg = SentinelConfig(warmup_steps=0)
        code, st = sentinel_observe(sentinel_init_state(),
                                    jnp.asarray(jnp.nan), None, cfg)
        assert int(code) == SENTINEL_NONFINITE
        assert int(st["anomaly_count"]) == 1
        # stats stay untouched by the anomalous observation
        assert float(st["ema_mean"]) == 0.0 and int(st["count"]) == 0

    def test_nonfinite_grads_flagged(self):
        cfg = SentinelConfig(warmup_steps=0)
        code, _ = sentinel_observe(sentinel_init_state(), jnp.asarray(1.0),
                                   jnp.asarray(False), cfg)
        assert int(code) == SENTINEL_NONFINITE

    def test_spike_during_warmup_tolerated(self):
        # no baseline yet → a jump is absorbed into the statistics, not
        # flagged (the first iterations of a fresh run are legitimately wild)
        cfg = SentinelConfig(warmup_steps=3, spike_factor=4.0,
                             min_spike_delta=0.1, ema_beta=0.5)
        st = sentinel_init_state()
        for v in (1.0, 50.0):
            code, st = sentinel_observe(st, jnp.asarray(v), None, cfg)
            assert int(code) == SENTINEL_OK
        assert int(st["anomaly_count"]) == 0

    def test_spike_after_warmup_flagged(self):
        cfg = SentinelConfig(warmup_steps=3, spike_factor=4.0,
                             min_spike_delta=0.1, ema_beta=0.5)
        st = sentinel_init_state()
        for v in (1.0, 1.1, 0.9, 1.0):
            code, st = sentinel_observe(st, jnp.asarray(v), None, cfg)
            assert int(code) == SENTINEL_OK
        code, st = sentinel_observe(st, jnp.asarray(50.0), None, cfg)
        assert int(code) == SENTINEL_SPIKE
        # the spike did not drag the mean up
        assert float(st["ema_mean"]) < 2.0
        # recovery: the next normal loss is clean again
        code, st = sentinel_observe(st, jnp.asarray(1.0), None, cfg)
        assert int(code) == SENTINEL_OK
        assert int(st["anomaly_count"]) == 1

    def test_regime_shift_absorbed_after_streak_cap(self):
        # a PERSISTENT level shift must not skip forever: past the
        # consecutive-spike cap the new level is absorbed and the rolling
        # statistics catch up (livelock escape)
        cfg = SentinelConfig(warmup_steps=2, spike_factor=4.0,
                             min_spike_delta=0.1, ema_beta=0.5,
                             max_consecutive_spikes=3)
        st = sentinel_init_state()
        for v in (1.0, 1.0, 1.0):
            code, st = sentinel_observe(st, jnp.asarray(v), None, cfg)
            assert int(code) == SENTINEL_OK
        codes = []
        for _ in range(12):
            code, st = sentinel_observe(st, jnp.asarray(10.0), None, cfg)
            codes.append(int(code))
        assert codes[:3] == [SENTINEL_SPIKE] * 3  # first burst: skipped
        assert SENTINEL_OK in codes[3:]           # then absorbed
        assert codes[-1] == SENTINEL_OK
        assert float(st["ema_mean"]) > 5.0        # stats adapted to level 10

    def test_streak_cap_zero_disables_absorption(self):
        cfg = SentinelConfig(warmup_steps=1, spike_factor=4.0,
                             min_spike_delta=0.1, ema_beta=0.5,
                             max_consecutive_spikes=0)
        st = sentinel_init_state()
        for v in (1.0, 1.0):
            _, st = sentinel_observe(st, jnp.asarray(v), None, cfg)
        for _ in range(10):
            code, st = sentinel_observe(st, jnp.asarray(10.0), None, cfg)
            assert int(code) == SENTINEL_SPIKE

    def test_policy_validation(self):
        with pytest.raises(ValueError):
            SentinelConfig(policy="explode")

    def test_observe_is_jittable(self):
        cfg = SentinelConfig(warmup_steps=1)
        fn = jax.jit(lambda s, l: sentinel_observe(s, l, None, cfg))
        st = sentinel_init_state()
        for v in (1.0, 1.0, jnp.inf, 1.0):
            code, st = fn(st, jnp.asarray(v, jnp.float32))
        assert int(st["anomaly_count"]) == 1
        assert sentinel_to_host(st)["last_code"] == SENTINEL_OK


# =====================================================================
# sentinel wired into ParallelTrainer
# =====================================================================
class TestTrainerSentinel:
    def test_skip_policy_freezes_params_on_nan(self):
        tr = _tiny_trainer(SentinelConfig(warmup_steps=2, spike_factor=4.0,
                                          min_spike_delta=0.1))
        rng = np.random.default_rng(0)
        x, y = _batch(rng)
        for _ in range(4):
            tr.step(x, y)
        before = {n: np.asarray(a).copy() for n, a in tr.params.items()}
        opt_step_before = int(tr.opt_state["step"])
        xnan = paddle.to_tensor(np.full((8, 4), np.nan, "float32"))
        tr.step(xnan, y)
        rep = tr.sentinel_report()
        assert rep["last_code"] == SENTINEL_NONFINITE
        assert rep["anomaly_count"] == 1
        for n in before:
            np.testing.assert_array_equal(before[n], np.asarray(tr.params[n]))
        # the optimizer step counter was reverted too (full skip)
        assert int(tr.opt_state["step"]) == opt_step_before
        # next clean step trains again
        tr.step(x, y)
        assert any(not np.array_equal(before[n], np.asarray(tr.params[n]))
                   for n in before)

    def test_skip_policy_freezes_params_on_spike(self):
        tr = _tiny_trainer(SentinelConfig(warmup_steps=3, spike_factor=6.0,
                                          min_spike_delta=0.05))
        rng = np.random.default_rng(1)
        x, y = _batch(rng)
        for _ in range(6):
            tr.step(x, y)
        before = {n: np.asarray(a).copy() for n, a in tr.params.items()}
        xs, _ = _batch(rng, scale=1000.0)  # finite but absurd loss
        tr.step(xs, y)
        assert tr.sentinel_report()["last_code"] == SENTINEL_SPIKE
        for n in before:
            np.testing.assert_array_equal(before[n], np.asarray(tr.params[n]))

    def test_spike_rescales_through_grad_scaler(self):
        """skip-and-rescale: with a GradScaler attached a loss spike counts
        as a bad step, shrinking the loss scale."""
        from paddle_tpu.amp.grad_scaler import GradScaler

        scaler = GradScaler(init_loss_scaling=1024.0, incr_every_n_steps=100)
        tr = _tiny_trainer(
            SentinelConfig(warmup_steps=3, spike_factor=6.0,
                           min_spike_delta=0.05), scaler=scaler)
        rng = np.random.default_rng(2)
        x, y = _batch(rng)
        for _ in range(6):
            tr.step(x, y)
        assert float(tr.scale_state["loss_scale"]) == 1024.0
        xs, _ = _batch(rng, scale=1000.0)
        tr.step(xs, y)
        assert tr.sentinel_report()["last_code"] == SENTINEL_SPIKE
        assert float(tr.scale_state["loss_scale"]) == 512.0

    def test_jaxpr_identical_when_disabled(self):
        """Acceptance bar: a disabled sentinel adds ZERO trace-level
        overhead — the step compiles to the identical jaxpr."""
        def jaxpr_of(sent):
            tr = _tiny_trainer(sent)
            tr._build()
            xb = jnp.zeros((8, 4), jnp.float32)
            key = jax.random.key(0)
            lr = jnp.asarray(0.01, jnp.float32)
            return str(jax.make_jaxpr(tr._jit_step)(
                tr.params, tr.opt_state, tr.buffers, xb, xb, key,
                tr.scale_state, tr.sentinel_state, lr))

        assert jaxpr_of(None) == jaxpr_of(SentinelConfig(enabled=False))

    def test_monitor_halt_and_rollback(self):
        tr = _tiny_trainer(SentinelConfig(warmup_steps=2, policy="halt",
                                          min_spike_delta=0.1))
        rng = np.random.default_rng(3)
        x, y = _batch(rng)
        for _ in range(3):
            tr.step(x, y)
        monitor = SentinelMonitor(tr._sentinel)
        assert monitor.after_step(tr) is None
        xnan = paddle.to_tensor(np.full((8, 4), np.nan, "float32"))
        tr.step(xnan, y)
        with pytest.raises(AnomalyHalt):
            monitor.after_step(tr)

        # rollback: restore_fn reinstates a snapshot, monitor re-bases
        cfg = SentinelConfig(warmup_steps=2, policy="rollback",
                             min_spike_delta=0.1)
        tr2 = _tiny_trainer(cfg, seed=1)
        for _ in range(3):
            tr2.step(x, y)
        snap2 = jax.tree_util.tree_map(lambda a: np.asarray(a).copy(),
                                       tr2.capture_state())
        calls = []
        mon2 = SentinelMonitor(cfg, restore_fn=lambda: (
            calls.append(1), tr2.restore_state(snap2)))
        tr2.step(xnan, y)
        assert mon2.after_step(tr2) == "rollback"
        assert calls == [1]
        for n in snap2["params"]:
            np.testing.assert_array_equal(snap2["params"][n],
                                          np.asarray(tr2.params[n]))
        # the poll right after a rollback must not re-trigger
        assert mon2.after_step(tr2) is None

    def test_capture_restore_roundtrip(self):
        tr = _tiny_trainer(SentinelConfig(warmup_steps=2))
        rng = np.random.default_rng(4)
        x, y = _batch(rng)
        for _ in range(3):
            tr.step(x, y)
        snap = jax.tree_util.tree_map(lambda a: np.asarray(a).copy(),
                                      tr.capture_state())
        losses_ref = [float(tr.step(x, y)._data) for _ in range(3)]
        tr.restore_state(snap)
        losses = [float(tr.step(x, y)._data) for _ in range(3)]
        assert losses == losses_ref  # bit-identical replay


# =====================================================================
# sentinel × sanitizer wiring (ISSUE 5 satellite): on halt/rollback the
# monitor can replay the captured failing step eqn-by-eqn and name the
# eqn that produced the first NaN (off by default)
# =====================================================================
class TestSentinelSanitizerWiring:
    def _guarded_trainer(self):
        from paddle_tpu.distributed.env import clear_mesh, init_mesh
        from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
        from paddle_tpu.optimizer.optimizers import SGD
        from paddle_tpu.profiler.scope import scope as prof_scope

        def loss_fn(o, y):
            # planted mid-network hazard: log goes NaN once mse exceeds 3
            with prof_scope("loss.guard"):
                return paddle.log(3.0 - ((o - y) ** 2).mean())

        paddle.seed(0)
        clear_mesh()
        init_mesh({"dp": 1})
        net = paddle.nn.Linear(4, 4)
        return ParallelTrainer(
            net, loss_fn, SGD(learning_rate=1e-3,
                              parameters=net.parameters()),
            dp_axis=None, donate=False,
            sentinel=SentinelConfig(warmup_steps=2, policy="halt"))

    def test_halt_report_names_offending_eqn_and_scope(self):
        tr = self._guarded_trainer()
        rng = np.random.default_rng(0)
        x = paddle.to_tensor((rng.standard_normal((8, 4)) * 0.01
                              ).astype("float32"))
        y = paddle.to_tensor((rng.standard_normal((8, 4)) * 0.01
                              ).astype("float32"))
        for _ in range(3):
            tr.step(x, y)
        snap = tr.capture_state()
        bad_x = paddle.to_tensor(
            (rng.standard_normal((8, 4)) * 100.0).astype("float32"))
        monitor = SentinelMonitor(
            tr._sentinel,
            sanitize_fn=lambda: tr.sanitize_step(
                bad_x, y, state=snap).to_dict())
        tr.step(bad_x, y)      # mse >> 3 -> log(NaN); in-graph skip fires
        with pytest.raises(AnomalyHalt) as e:
            monitor.after_step(tr)
        san = e.value.report["sanitizer"]
        assert san["ok"] is False
        first = san["first_nonfinite"]
        assert first["prim"] == "log"
        assert "loss.guard" in first["scope"]
        assert first["n_nan"] >= 1
        assert "log" in str(e.value)        # the halt message names it
        assert monitor.last_sanitize is san

    def test_off_by_default_and_failure_contained(self):
        tr = self._guarded_trainer()
        rng = np.random.default_rng(1)
        x = paddle.to_tensor((rng.standard_normal((8, 4)) * 0.01
                              ).astype("float32"))
        for _ in range(3):
            tr.step(x, x)
        bad_x = paddle.to_tensor(
            (rng.standard_normal((8, 4)) * 100.0).astype("float32"))
        # default: no sanitizer in the report
        mon = SentinelMonitor(tr._sentinel)
        tr.step(bad_x, x)
        with pytest.raises(AnomalyHalt) as e:
            mon.after_step(tr)
        assert "sanitizer" not in e.value.report
        # a broken sanitize_fn must not mask the policy action
        tr2 = self._guarded_trainer()
        for _ in range(3):
            tr2.step(x, x)
        mon2 = SentinelMonitor(
            tr2._sentinel,
            sanitize_fn=lambda: (_ for _ in ()).throw(RuntimeError("boom")))
        tr2.step(bad_x, x)
        with pytest.raises(AnomalyHalt) as e2:
            mon2.after_step(tr2)
        assert "boom" in e2.value.report["sanitizer"]["error"]


# =====================================================================
# sentinel wired into the pipeline step
# =====================================================================
def _pipeline_step(sentinel):
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.meta_parallel.pipeline_schedule import (
        build_gpt_pipeline_step,
    )
    from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
    from paddle_tpu.optimizer.optimizers import AdamW

    cfg = gpt_config("gpt2-small", vocab_size=64, hidden_size=32, num_layers=2,
                     num_attention_heads=4, max_position_embeddings=32,
                     hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    paddle.seed(0)
    clear_mesh()
    init_mesh({"pp": 1})
    model = GPTForPretraining(cfg)
    opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
    return build_gpt_pipeline_step(model, opt, microbatches=2,
                                   sentinel=sentinel)


class TestPipelineSentinel:
    def test_pipeline_jaxpr_identical_when_disabled(self):
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(0, 64, (4, 16)).astype("int32"))
        kd = jax.random.key_data(jax.random.key(0))
        lr = jnp.asarray(1e-3, jnp.float32)

        def jaxpr_of(sent):
            s = _pipeline_step(sent)
            return str(jax.make_jaxpr(s.jitted)(
                s.state["params"], s.state["opt"], ids, ids, kd, lr,
                s.state["sentinel"]))

        assert jaxpr_of(None) == jaxpr_of(SentinelConfig(enabled=False))

    def test_pipeline_skip_on_anomaly(self):
        step = _pipeline_step(SentinelConfig(warmup_steps=2, spike_factor=4.0,
                                             min_spike_delta=0.05))
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 64, (4, 16)).astype("int32")
        for _ in range(4):
            step(ids, ids)
        assert sentinel_to_host(step.state["sentinel"])["anomaly_count"] == 0
        before = jax.tree_util.tree_map(lambda a: np.asarray(a).copy(),
                                        step.state["params"])
        # shuffled labels jump the loss far above the rolling mean
        bad = rng.integers(0, 64, (4, 16)).astype("int32")
        step(ids, bad)
        rep = sentinel_to_host(step.state["sentinel"])
        assert rep["last_code"] == SENTINEL_SPIKE
        for grp in before:
            for n in before[grp]:
                np.testing.assert_array_equal(
                    before[grp][n], np.asarray(step.state["params"][grp][n]))


# =====================================================================
# checkpoint integrity: checksums, corruption fallback, async race
# =====================================================================
class TestCheckpointIntegrity:
    def test_checksums_written(self, tmp_path):
        import json

        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, {"w": np.arange(6, dtype="float32")})
        meta = json.loads((tmp_path / "step_1" / "meta.json").read_text())
        assert "/w" in meta["checksums"] and "tree_crc" in meta

    def test_truncated_arrays_falls_back_with_warning(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_max=5)
        mgr.save(1, {"w": np.arange(4, dtype="float32")})
        mgr.save(2, {"w": np.arange(4, dtype="float32") * 2})
        f = tmp_path / "step_2" / "arrays.npz"
        f.write_bytes(f.read_bytes()[: f.stat().st_size // 2])
        with pytest.warns(RuntimeWarning, match="corrupt"):
            state, _ = mgr.load()
        np.testing.assert_array_equal(state["w"],
                                      np.arange(4, dtype="float32"))
        assert mgr.last_loaded_step == 1
        # an EXPLICIT step does not silently fall back
        with pytest.raises(CheckpointCorruptionError):
            mgr.load(2)

    def test_checksum_mismatch_detected(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_max=5)
        mgr.save(1, {"w": np.arange(4, dtype="float32")})
        mgr.save(2, {"w": np.arange(4, dtype="float32") * 2})
        # swap the array file for one with the right keys but wrong bytes
        np.savez(tmp_path / "step_2" / "arrays.npz",
                 **{"|w": np.zeros(4, "float32")})
        with pytest.warns(RuntimeWarning, match="corrupt"):
            state, _ = mgr.load()
        assert mgr.last_loaded_step == 1
        np.testing.assert_array_equal(state["w"],
                                      np.arange(4, dtype="float32"))

    def test_all_corrupt_raises(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, {"w": np.arange(4.0)})
        (tmp_path / "step_1" / "arrays.npz").write_bytes(b"junk")
        with pytest.warns(RuntimeWarning), pytest.raises(
                CheckpointCorruptionError):
            mgr.load()

    def test_async_save_sequence_and_exit_join(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_max=10, async_save=True)
        for s in range(4):  # back-to-back saves: each joins its predecessor
            mgr.save(s, {"w": np.full((64, 64), float(s))})
        _join_live_managers()  # the interpreter-exit hook
        assert mgr._thread is None
        assert mgr.all_steps() == [0, 1, 2, 3]
        for s in range(4):
            state, _ = mgr.load(s)  # every snapshot intact (checksums pass)
            assert float(state["w"][0, 0]) == float(s)

    def test_sync_save_overrides_async(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        mgr.save(7, {"w": np.ones(3)}, sync=True)
        assert mgr._thread is None  # wrote on the caller's thread
        assert mgr.latest_step() == 7

    def test_eager_mark_anomaly_skips_and_rescales(self):
        from paddle_tpu.amp.grad_scaler import GradScaler

        net = paddle.nn.Linear(2, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        scaler = GradScaler(init_loss_scaling=64.0)
        w_before = net.weight.numpy().copy()
        x = paddle.to_tensor(np.ones((4, 2), "float32"))
        loss = (net(x) ** 2).mean()
        scaler.scale(loss).backward()
        scaler.mark_anomaly()  # eager sentinel verdict: skip this step
        scaler.step(opt)
        scaler.update()
        opt.clear_grad()
        np.testing.assert_array_equal(net.weight.numpy(), w_before)
        assert scaler.get_loss_scaling() == 32.0

    def test_scaler_persisted_through_checkpoint(self, tmp_path):
        from paddle_tpu.amp.grad_scaler import GradScaler

        net = paddle.nn.Linear(2, 2)
        scaler = GradScaler(init_loss_scaling=2.0 ** 8,
                            incr_every_n_steps=10)
        scaler._good_steps = 7
        scaler._scale = 123.0
        save_checkpoint(str(tmp_path), step=1, model=net, scaler=scaler)
        fresh = GradScaler()
        step, _ = load_checkpoint(str(tmp_path), scaler=fresh)
        assert step == 1
        assert fresh.get_loss_scaling() == 123.0
        assert fresh._good_steps == 7
        assert fresh._incr_every_n_steps == 10


# =====================================================================
# preemption guard
# =====================================================================
class TestPreemptionGuard:
    def test_sigterm_triggers_emergency_save(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        guard = PreemptionGuard(mgr)
        guard.install()
        try:
            guard.update(5, {"w": np.arange(3, dtype="float32"), "step": 5})
            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.time() + 5.0
            while not guard.preempted and time.time() < deadline:
                time.sleep(0.01)  # delivery happens between bytecodes
            assert guard.preempted and guard.saved_step == 5
            state, meta = mgr.load()
            assert meta["preempted"] and state["step"] == 5
            # at-most-once: a second signal does not save again
            assert guard.emergency_save() is False
        finally:
            guard.uninstall()

    def test_state_thunk_deferred(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        guard = PreemptionGuard(mgr)
        pulls = []
        guard.update(2, lambda: (pulls.append(1), {"v": 2})[1])
        assert pulls == []  # nothing materialized until the emergency
        assert guard.emergency_save("test")
        assert pulls == [1]
        state, _ = mgr.load()
        assert state["v"] == 2

    def test_deadline_watchdog_saves(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        guard = PreemptionGuard(mgr, deadline=time.time() + 0.4, grace=0.2,
                                watchdog_interval=0.05)
        guard.update(3, {"w": np.ones(2)})
        guard.install()
        try:
            deadline = time.time() + 15.0
            while guard.saved_step is None and time.time() < deadline:
                time.sleep(0.05)
            assert guard.preempted and guard.saved_step == 3
        finally:
            guard.uninstall()

    def test_no_state_warns_not_crashes(self, tmp_path):
        guard = PreemptionGuard(CheckpointManager(str(tmp_path)))
        with pytest.warns(RuntimeWarning, match="no state"):
            assert guard.emergency_save() is False

    def test_capture_train_state_shape(self, tmp_path):
        from paddle_tpu.amp.grad_scaler import GradScaler

        net = paddle.nn.Linear(2, 2)
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters())
        st = capture_train_state(4, model=net, optimizer=opt,
                                 scaler=GradScaler())
        assert st["step"] == 4
        assert {"model", "optimizer", "scaler", "rng"} <= set(st)
        CheckpointManager(str(tmp_path)).save(4, st)  # round-trippable


# =====================================================================
# retry / backoff
# =====================================================================
class TestRetry:
    def test_backoff_grows_and_caps(self):
        ds = list(backoff_delays(6, base=0.1, max_delay=0.8, jitter=0.0))
        assert ds == [0.1, 0.2, 0.4, 0.8, 0.8, 0.8]
        jittered = list(backoff_delays(50, base=0.1, max_delay=0.8,
                                       jitter=0.5))
        assert all(0.05 <= d <= 1.2 for d in jittered)

    def test_retries_on_exception_then_succeeds(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("transient")
            return "up"

        assert call_with_retries(flaky, retries=4, sleep=lambda _: None) == "up"
        assert len(calls) == 3

    def test_retries_on_rejected_value(self):
        vals = iter([False, False, True])
        assert call_with_retries(lambda: next(vals), retries=3, ok=bool,
                                 sleep=lambda _: None) is True

    def test_exhaustion_raises(self):
        def dead():
            raise ConnectionError("down")

        with pytest.raises(RetryError):
            call_with_retries(dead, retries=2, sleep=lambda _: None)


# =====================================================================
# self-healing elastic store
# =====================================================================
class TestElasticSelfHealing:
    def test_file_store_endpoints_skips_vanished_node(self, tmp_path):
        """Regression: a node file expiring between the nodes() scan and
        the endpoints() open (deregister racing the TTL walk) must be
        skipped — endpoints() had no FileNotFoundError guard while
        nodes() did, so the caller's membership poll crashed."""
        from paddle_tpu.distributed.fleet.elastic.manager import _FileStore

        store = _FileStore(str(tmp_path), ttl=60.0)
        store.register("node_a", "1.1.1.1:1")
        store.register("node_b", "2.2.2.2:2")
        real_nodes = store.nodes

        def nodes_then_vanish():
            out = real_nodes()
            (tmp_path / "node_a").unlink(missing_ok=True)
            return out

        store.nodes = nodes_then_vanish
        assert store.endpoints() == ["2.2.2.2:2"]

    def test_tcp_store_retries_transient_failure(self):
        from paddle_tpu.distributed.fleet.elastic.manager import _TcpStore
        from paddle_tpu.distributed.fleet.utils import KVServer

        with KVServer(0, host="127.0.0.1") as srv:
            store = _TcpStore(f"127.0.0.1:{srv.port}", "retryjob", ttl=5.0)
            fails = {"n": 0}
            real_put = store.client.put

            def flaky_put(scope, key, value, strict=False):
                if fails["n"] < 2:
                    fails["n"] += 1
                    raise ConnectionError("transient")
                return real_put(scope, key, value, strict=strict)

            store.client.put = flaky_put
            store.register("node_a", "10.0.0.1:1")  # survives 2 flakes
            assert fails["n"] == 2
            assert store.nodes() == ["node_a"]

    def test_tcp_store_unavailable_after_budget(self):
        from paddle_tpu.distributed.fleet.elastic.manager import (
            StoreUnavailable,
            _TcpStore,
        )

        store = _TcpStore("127.0.0.1:1", "deadjob", ttl=0.4, retries=1)
        with pytest.raises(StoreUnavailable):
            store.heartbeat("n")

    def test_outage_degrades_then_rejoins(self, monkeypatch):
        from paddle_tpu.distributed.fleet.elastic.manager import (
            ElasticManager,
            _TcpStore,
        )
        from paddle_tpu.distributed.fleet.utils import KVServer

        monkeypatch.setenv("PADDLE_ELASTIC_NP", "1")
        monkeypatch.setenv("PADDLE_ELASTIC_JOB_ID", "healjob")
        monkeypatch.setenv("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6464")
        srv = KVServer(0, host="127.0.0.1").start()
        port = srv.port
        store = _TcpStore(f"127.0.0.1:{port}", "healjob", ttl=0.6, retries=1)
        mgr = ElasticManager(store=store)
        mgr.register()
        try:
            assert not mgr.degraded
            assert mgr.wait_for_np(1)
            srv.stop()
            # beat thread survives the outage and flips to degraded
            deadline = time.time() + 15.0
            while not mgr.degraded and time.time() < deadline:
                time.sleep(0.1)
            assert mgr.degraded
            assert mgr._hb_thread.is_alive()
            # graceful degradation: membership watch says "no change",
            # endpoints fall back to the last good snapshot
            assert mgr.changed() is False
            assert mgr.endpoints_env() == "127.0.0.1:6464"
            # store returns on the same port → automatic rejoin
            srv2 = KVServer(port, host="127.0.0.1").start()
            try:
                deadline = time.time() + 15.0
                while mgr.degraded and time.time() < deadline:
                    time.sleep(0.1)
                assert not mgr.degraded
                assert mgr.store.nodes() == ["127.0.0.1_6464"]
                assert not mgr.changed()
            finally:
                mgr.exit()
                srv2.stop()
        finally:
            mgr._stop.set()

    def test_register_with_dead_store_starts_single_node(self, monkeypatch):
        from paddle_tpu.distributed.fleet.elastic.manager import (
            ElasticManager,
            _TcpStore,
        )

        monkeypatch.setenv("PADDLE_ELASTIC_NP", "1")
        monkeypatch.setenv("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6465")
        store = _TcpStore("127.0.0.1:1", "nojob", ttl=0.4, retries=0)
        mgr = ElasticManager(store=store)
        with pytest.warns(RuntimeWarning, match="single-node"):
            mgr.register()
        try:
            assert mgr.degraded
            assert mgr.changed() is False
            assert mgr.endpoints_env() == "127.0.0.1:6465"
        finally:
            mgr._stop.set()


# =====================================================================
# kill-and-resume e2e: SIGTERM mid-training → restart → bit-identical
# loss trajectory vs. an uninterrupted run (CPU)
# =====================================================================
_E2E_SCRIPT = textwrap.dedent("""
    import os, sys
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.amp.grad_scaler import GradScaler
    from paddle_tpu.framework.checkpoint import (
        CheckpointManager, load_checkpoint)
    from paddle_tpu.resilience import PreemptionGuard, capture_train_state

    CKPT = sys.argv[1]
    TOTAL = 10

    paddle.seed(0)
    net = paddle.nn.Linear(4, 4)
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=net.parameters())
    # power-of-two scales keep unscale exact, so resume is bit-identical;
    # incr_every=3 makes the scale MOVE mid-run, proving its counters resume
    scaler = GradScaler(init_loss_scaling=2.0 ** 4, incr_every_n_steps=3)

    start, _ = load_checkpoint(CKPT, model=net, optimizer=opt, scaler=scaler)
    start = 0 if start is None else start + 1

    mgr = CheckpointManager(CKPT, keep_max=10)
    guard = PreemptionGuard(mgr, exit_code=101)
    guard.install()

    for step in range(start, TOTAL):
        rng = np.random.default_rng(1000 + step)  # step-keyed data stream
        x = paddle.to_tensor(rng.standard_normal((8, 4)).astype("float32"))
        y = paddle.to_tensor(rng.standard_normal((8, 4)).astype("float32"))
        loss = ((net(x) - y) ** 2).mean()
        scaled = scaler.scale(loss)
        scaled.backward()
        scaler.step(opt)
        scaler.update()
        opt.clear_grad()
        # register the completed step BEFORE announcing it, so a SIGTERM
        # landing after the print always has at least this step's state
        guard.update(step, capture_train_state(
            step, model=net, optimizer=opt, scaler=scaler))
        print(f"STEP {step} {float(loss.numpy()).hex()} "
              f"{scaler.get_loss_scaling().hex()}", flush=True)
    sys.exit(0)
""")


def _parse_steps(text):
    out = {}
    for line in text.splitlines():
        if line.startswith("STEP "):
            _, s, loss_hex, scale_hex = line.split()
            out[int(s)] = (loss_hex, scale_hex)
    return out


# ---------------------------------------------------------------------
# deterministic variant (tier-1): the same kill-and-resume scenario with
# the SIGTERM replaced by an injected `kill` at the preemption.update
# seam — in-process, no subprocesses, no signals, replays bit-identically
# ---------------------------------------------------------------------
def _injected_training_leg(ckpt_dir, total=10):
    """One training leg of the kill-and-resume scenario (the in-process
    twin of _E2E_SCRIPT): resumes from the newest snapshot in ``ckpt_dir``
    and returns {step: (loss_hex, scale_hex)} for the steps it ran.
    Raises InjectedDeath when an armed schedule kills it."""
    from paddle_tpu.amp.grad_scaler import GradScaler

    paddle.seed(0)
    net = paddle.nn.Linear(4, 4)
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=net.parameters())
    scaler = GradScaler(init_loss_scaling=2.0 ** 4, incr_every_n_steps=3)
    start, _ = load_checkpoint(str(ckpt_dir), model=net, optimizer=opt,
                               scaler=scaler)
    start = 0 if start is None else start + 1
    mgr = CheckpointManager(str(ckpt_dir), keep_max=10)
    guard = PreemptionGuard(mgr, exit_code=None)  # no signals installed
    out = {}
    for step in range(start, total):
        rng = np.random.default_rng(1000 + step)  # step-keyed data stream
        x = paddle.to_tensor(rng.standard_normal((8, 4)).astype("float32"))
        y = paddle.to_tensor(rng.standard_normal((8, 4)).astype("float32"))
        loss = ((net(x) - y) ** 2).mean()
        scaled = scaler.scale(loss)
        scaled.backward()
        scaler.step(opt)
        scaler.update()
        opt.clear_grad()
        # the injected kill fires INSIDE update() after this step's state
        # is registered — the same window the signal test aims at — so
        # record the step's numbers first: the real process printed them
        # before dying too
        out[step] = (float(loss.numpy()).hex(),
                     scaler.get_loss_scaling().hex())
        guard.update(step, capture_train_state(
            step, model=net, optimizer=opt, scaler=scaler))
    return out


def test_injected_kill_and_resume_bit_identical(tmp_path):
    """Tier-1 deterministic twin of the chaos kill-and-resume e2e: an
    injected kill at the 5th preemption.update (step 4) triggers the
    at-most-once emergency save; a plain re-run resumes from it and the
    stitched trajectory is bit-identical to an uninterrupted run. Two
    injected legs with the same schedule also replay identically — the
    fault-sequence determinism acceptance."""
    from paddle_tpu.resilience import FaultSchedule, InjectedDeath

    ref = _injected_training_leg(tmp_path / "ref")  # uninterrupted
    assert sorted(ref) == list(range(10))

    def injected_run(ckpt):
        sched = FaultSchedule(seed=8).add("preemption.update", "kill",
                                          match={"step": 4})
        with sched.scope():
            with pytest.raises(InjectedDeath):
                _injected_training_leg(ckpt)
        leg2 = _injected_training_leg(ckpt)  # "relaunch"
        return sched.fired_log(), leg2

    log_a, resumed_a = injected_run(tmp_path / "a")
    log_b, resumed_b = injected_run(tmp_path / "b")
    # identical fault sequence AND identical post-recovery trajectory
    # across the two replays
    assert log_a == log_b == [{"point": "preemption.update", "kind": "kill",
                               "count": 1, "labels": {"step": 4}}]
    assert resumed_a == resumed_b
    # really resumed from the emergency snapshot (step 4), not a restart
    assert min(resumed_a) == 5
    # the resumed leg matches the uninterrupted run bit for bit
    assert resumed_a == {s: v for s, v in ref.items() if s >= 5}
    # the emergency dump left a flight record naming the final step
    dumps = [f for f in os.listdir(tmp_path / "a")
             if f.startswith("flight_preemption_injected")]
    assert len(dumps) == 1


def test_preempt_now_saves_at_most_once(tmp_path):
    """preempt_now (the deterministic SIGTERM) funnels into the same
    at-most-once emergency save as the signal handler."""
    mgr = CheckpointManager(str(tmp_path))
    guard = PreemptionGuard(mgr, exit_code=None)
    guard.update(3, {"step": 3, "w": np.ones((2,))})
    assert guard.preempt_now("test") is True
    assert guard.preempted and guard.saved_step == 3
    assert guard.preempt_now("again") is False  # at-most-once
    state, meta = mgr.load()
    assert state["step"] == 3 and meta["preempted"]


@pytest.mark.chaos
def test_kill_and_resume_bit_identical(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(_E2E_SCRIPT)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(
                   p for p in (repo, os.environ.get("PYTHONPATH")) if p))

    # reference: uninterrupted run
    ref = subprocess.run(
        [sys.executable, str(script), str(tmp_path / "ckpt_ref")],
        capture_output=True, text=True, env=env, timeout=240)
    assert ref.returncode == 0, ref.stderr
    ref_steps = _parse_steps(ref.stdout)
    assert sorted(ref_steps) == list(range(10))

    # leg 1: SIGTERM after step 4 is announced
    ckpt = str(tmp_path / "ckpt_kill")
    proc = subprocess.Popen(
        [sys.executable, str(script), ckpt],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
    seen = []
    for line in proc.stdout:
        seen.append(line)
        if line.startswith("STEP 4 "):
            proc.send_signal(signal.SIGTERM)
            break
    rest, err1 = proc.communicate(timeout=240)
    assert proc.returncode == 101, (seen, rest, err1)  # elastic relaunch code
    leg1 = _parse_steps("".join(seen) + rest)
    assert 4 in leg1  # trained at least through the signal point

    # leg 2: plain restart resumes from the emergency snapshot
    res = subprocess.run([sys.executable, str(script), ckpt],
                         capture_output=True, text=True, env=env, timeout=240)
    assert res.returncode == 0, res.stderr
    leg2 = _parse_steps(res.stdout)
    resume_start = min(leg2)
    assert 0 < resume_start < 10  # really resumed, didn't start over

    stitched = {s: v for s, v in leg1.items() if s < resume_start}
    stitched.update(leg2)
    # bit-identical trajectory: loss AND loss-scale match the uninterrupted
    # run at every step (hex float compare — exact)
    assert stitched == ref_steps
