"""paddle.static compat surface: CompiledProgram/ParallelExecutor shims,
save/load program state, EMA, scope/name guards, Print/py_func, static
metrics (reference: fluid/compiler.py, io.py, optimizer.py EMA)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.static as static


rng = np.random.default_rng(23)


def _np(t):
    return np.asarray(t._data)


def _build_linear_program():
    paddle.enable_static()
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        w = static.create_parameter([4, 2], "float32", name="w0")
        out = paddle.matmul(x, w)
    return main, startup, x, out, w


class TestCompiledProgram:
    def test_compiled_program_runs(self):
        try:
            main, startup, x, out, w = _build_linear_program()
            exe = static.Executor()
            exe.run(startup)
            cp = static.CompiledProgram(main).with_data_parallel(loss_name=None)
            feed = {"x": np.ones((3, 4), "float32")}
            res = exe.run(cp, feed=feed, fetch_list=[out])
            want = np.ones((3, 4)) @ _np(w)
            np.testing.assert_allclose(res[0], want, rtol=1e-5)
        finally:
            paddle.disable_static()

    def test_parallel_executor_shim(self):
        try:
            main, startup, x, out, w = _build_linear_program()
            static.Executor().run(startup)
            pe = static.ParallelExecutor(use_cuda=False, main_program=main)
            res = pe.run(fetch_list=[out], feed={"x": np.zeros((2, 4), "float32")})
            np.testing.assert_allclose(res[0], np.zeros((2, 2)), atol=1e-6)
        finally:
            paddle.disable_static()

    def test_build_strategy_fields(self):
        bs = static.BuildStrategy()
        bs.fuse_all_reduce_ops = True
        bs.reduce_strategy = static.BuildStrategy.ReduceStrategy.Reduce
        assert "fuse_all_reduce_ops" in repr(bs)
        es = static.ExecutionStrategy()
        es.num_threads = 4


class TestProgramStateIO:
    def test_save_load_roundtrip(self, tmp_path):
        try:
            main, startup, x, out, w = _build_linear_program()
            static.Executor().run(startup)
            w_val = _np(w).copy()
            path = str(tmp_path / "model")
            static.save(main, path)
            # clobber and restore
            import jax.numpy as jnp

            w._set_data(jnp.zeros_like(w._data))
            static.load(main, path)
            np.testing.assert_allclose(_np(w), w_val)
            state = static.load_program_state(path)
            assert "w0" in state
        finally:
            paddle.disable_static()

    def test_save_load_vars_dir(self, tmp_path):
        try:
            main, startup, x, out, w = _build_linear_program()
            exe = static.Executor()
            exe.run(startup)
            w_val = _np(w).copy()
            static.save_vars(exe, str(tmp_path), main_program=main,
                             filename="all_vars")
            import jax.numpy as jnp

            w._set_data(jnp.ones_like(w._data))
            static.load_vars(exe, str(tmp_path), main_program=main,
                             filename="all_vars")
            np.testing.assert_allclose(_np(w), w_val)
        finally:
            paddle.disable_static()

    def test_serialize_persistables(self):
        try:
            main, startup, x, out, w = _build_linear_program()
            static.Executor().run(startup)
            blob = static.serialize_persistables([x], [out])
            import jax.numpy as jnp

            old = _np(w).copy()
            w._set_data(jnp.zeros_like(w._data))
            static.deserialize_persistables(main, blob)
            np.testing.assert_allclose(_np(w), old)
        finally:
            paddle.disable_static()


class TestEMA:
    def test_apply_restore(self):
        p = paddle.to_tensor(np.ones(3, "float32"))
        p.name = "p"
        ema = static.ExponentialMovingAverage(decay=0.5)
        ema.update([p])          # ema = 1
        import jax.numpy as jnp

        p._set_data(jnp.asarray(np.full(3, 3.0, "float32")))
        ema.update([p])          # ema = 0.5*1 + 0.5*3 = 2
        with ema.apply():
            np.testing.assert_allclose(_np(p), 2.0)
        np.testing.assert_allclose(_np(p), 3.0)  # restored


class TestMiscStatic:
    def test_scope_and_guards(self):
        s = static.Scope()
        with static.scope_guard(s):
            pass
        with static.name_scope("block1"):
            pass
        with static.device_guard("gpu:0"):
            pass

    def test_print_and_py_func(self, capsys):
        t = paddle.to_tensor(np.arange(3, dtype="float32"))
        out = static.Print(t, message="dbg")
        assert out is t
        assert "dbg" in capsys.readouterr().out
        res = paddle.to_tensor(np.zeros(3, "float32"))
        static.py_func(lambda a: a * 2, t, res)
        np.testing.assert_allclose(_np(res), [0, 2, 4])

    def test_static_metrics(self):
        scores = paddle.to_tensor(np.array([[0.2, 0.8], [0.9, 0.1]], "float32"))
        labels = paddle.to_tensor(np.array([[1], [0]], "int64"))
        acc = static.accuracy(scores, labels)
        np.testing.assert_allclose(float(_np(acc)), 1.0)
        a = static.auc(scores, labels)
        assert 0.0 <= float(_np(a)) <= 1.0

    def test_create_global_var(self):
        v = static.create_global_var([2, 2], 1.5, "float32", persistable=True)
        np.testing.assert_allclose(_np(v), np.full((2, 2), 1.5))

    def test_weight_norm_param_attr(self):
        attr = static.WeightNormParamAttr(dim=0, name="wn")
        assert attr.dim == 0


class TestOptimizerStateResume:
    def test_save_restores_opt_state(self, tmp_path):
        import paddle_tpu.nn.functional as F
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt

        try:
            paddle.enable_static()
            main = static.Program()
            startup = static.Program()
            with static.program_guard(main, startup):
                x = static.data("x", [None, 2], "float32")
                y = static.data("y", [None, 1], "float32")
                lin = nn.Linear(2, 1)
                loss = F.mse_loss(lin(x), y)
                adam = opt.Adam(learning_rate=0.01)
                adam.minimize(loss)
            exe = static.Executor()
            exe.run(startup)
            X = np.ones((4, 2), "float32")
            Y = np.ones((4, 1), "float32")
            for _ in range(3):
                exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
            path = str(tmp_path / "ckpt")
            static.save(main, path)
            import jax

            before = jax.tree_util.tree_map(np.asarray, main._opt_state)
            # clobber the functional slot state, then restore
            main._opt_state = jax.tree_util.tree_map(np.zeros_like, before)
            static.load(main, path)
            after = jax.tree_util.tree_map(np.asarray, main._opt_state)
            flat_b = jax.tree_util.tree_leaves(before)
            flat_a = jax.tree_util.tree_leaves(after)
            assert len(flat_b) == len(flat_a) and len(flat_b) > 0
            for b, a in zip(flat_b, flat_a):
                np.testing.assert_allclose(b, a, rtol=1e-6)
            # adam moments are non-trivial after 3 steps
            assert any(np.abs(leaf).sum() > 0 for leaf in flat_b)
        finally:
            paddle.disable_static()
