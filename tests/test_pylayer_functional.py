"""PyLayer + functional autodiff tests.

Parity model: reference unittests test_pylayer_op.py and
autograd/test_vjp_jvp.py / test_jacobian.py / test_hessian.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import PyLayer, functional


class TanhPyLayer(PyLayer):
    @staticmethod
    def forward(ctx, x):
        y = paddle.tanh(x)
        ctx.save_for_backward(y)
        return y

    @staticmethod
    def backward(ctx, dy):
        (y,) = ctx.saved_tensor()
        return dy * (1 - y * y)


def test_pylayer_matches_builtin_grad():
    x_np = np.random.randn(4, 5).astype("float32")
    x1 = paddle.to_tensor(x_np, stop_gradient=False)
    y1 = TanhPyLayer.apply(x1)
    y1.sum().backward()

    x2 = paddle.to_tensor(x_np, stop_gradient=False)
    y2 = paddle.tanh(x2)
    y2.sum().backward()

    np.testing.assert_allclose(y1.numpy(), y2.numpy(), rtol=1e-6)
    np.testing.assert_allclose(x1.grad.numpy(), x2.grad.numpy(), rtol=1e-5)


class ScaleTwoOut(PyLayer):
    @staticmethod
    def forward(ctx, x, y):
        return x * 2.0, y * 3.0

    @staticmethod
    def backward(ctx, dx, dy):
        return dx * 2.0, dy * 3.0


def test_pylayer_multi_io():
    x = paddle.to_tensor(np.ones((3,), "float32"), stop_gradient=False)
    y = paddle.to_tensor(np.ones((3,), "float32"), stop_gradient=False)
    a, b = ScaleTwoOut.apply(x, y)
    (a.sum() + b.sum()).backward()
    np.testing.assert_allclose(x.grad.numpy(), np.full((3,), 2.0), rtol=1e-6)
    np.testing.assert_allclose(y.grad.numpy(), np.full((3,), 3.0), rtol=1e-6)


def test_pylayer_wrong_grad_count_raises():
    class Bad(PyLayer):
        @staticmethod
        def forward(ctx, x, y):
            return x + y

        @staticmethod
        def backward(ctx, dz):
            return dz  # should be two grads

    x = paddle.to_tensor(np.ones((2,), "float32"), stop_gradient=False)
    y = paddle.to_tensor(np.ones((2,), "float32"), stop_gradient=False)
    z = Bad.apply(x, y)
    with pytest.raises(ValueError):
        z.sum().backward()


def test_pylayer_no_grad_passthrough():
    x = paddle.to_tensor(np.ones((2,), "float32"))  # stop_gradient=True
    y = TanhPyLayer.apply(x)
    assert y.stop_gradient


def test_vjp():
    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], "float32"))
    out, g = functional.vjp(lambda t: (t * t).sum(), x)
    np.testing.assert_allclose(out.numpy(), 14.0, rtol=1e-6)
    np.testing.assert_allclose(g.numpy(), [2.0, 4.0, 6.0], rtol=1e-6)


def test_jvp():
    x = paddle.to_tensor(np.array([1.0, 2.0], "float32"))
    v = paddle.to_tensor(np.array([1.0, 0.0], "float32"))
    out, tangent = functional.jvp(lambda t: t * t, x, v)
    np.testing.assert_allclose(tangent.numpy(), [2.0, 0.0], rtol=1e-6)


def test_jacobian_single():
    x = paddle.to_tensor(np.array([1.0, 2.0], "float32"))
    jac = functional.jacobian(lambda t: t * t, x)
    np.testing.assert_allclose(jac.numpy(), np.diag([2.0, 4.0]), rtol=1e-6)


def test_jacobian_multi_input():
    x = paddle.to_tensor(np.array([1.0, 2.0], "float32"))
    y = paddle.to_tensor(np.array([3.0, 4.0], "float32"))
    jac = functional.jacobian(lambda a, b: a * b, [x, y])
    np.testing.assert_allclose(jac[0].numpy(), np.diag([3.0, 4.0]), rtol=1e-6)
    np.testing.assert_allclose(jac[1].numpy(), np.diag([1.0, 2.0]), rtol=1e-6)


def test_hessian():
    x = paddle.to_tensor(np.array([1.0, 2.0], "float32"))
    hes = functional.hessian(lambda t: (t * t * t).sum(), x)
    np.testing.assert_allclose(hes.numpy(), np.diag([6.0, 12.0]), rtol=1e-6)


def test_double_grad_via_functional():
    # d2/dx2 of sin(x).sum() == -sin(x)
    x = paddle.to_tensor(np.array([0.3, 0.7], "float32"))
    hes = functional.hessian(lambda t: paddle.sin(t).sum(), x)
    np.testing.assert_allclose(
        np.diag(hes.numpy()), -np.sin([0.3, 0.7]), rtol=1e-5
    )
