"""Pallas kernel doctor (r24): planted-violation proofs + clean pins.

The coverage prover is only trustworthy if it catches the failure modes
it claims to catch, with enough detail to fix them: each planted toy
kernel here carries exactly one violation (a write hole, a
non-contiguous overlapping write, a bf16 accumulator) and the tests
assert the exact HIGH details — block index, grid coords, offending eqn
dtypes — not just "a finding exists".  The clean-pin tests hold the
shipped tree at zero HIGH/MEDIUM, and the CLI tests pin the exit-1
contract per planted kind.
"""
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.analysis.findings import Severity
from paddle_tpu.analysis.kernels import analyze_kernels, kernel_sweep
from paddle_tpu.ops.pallas import KernelCase, kernel_manifest
from paddle_tpu.ops.pallas.cost_registry import registered_kernels

BENCH_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks")


# ---------------------------------------------------------------------------
# planted toy kernels
# ---------------------------------------------------------------------------
def _copy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def _toy_hole():
    """Output has 4 row blocks but the grid only visits 2 → blocks
    (2,0) and (3,0) ship uninitialized memory."""
    x = np.ones((256, 128), np.float32)

    def fn(x):
        return pl.pallas_call(
            _copy_kernel, grid=(2,),
            in_specs=[pl.BlockSpec((64, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((64, 128), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((256, 128), jnp.float32),
            interpret=True, name="toy_write_hole")(x)

    return KernelCase(name="toy_write_hole", build=lambda: (fn, (x,)))


def _toy_race():
    """grid (4,) writes block (i % 2, 0): each output block is written
    by TWO non-contiguous runs — the second clobbers flushed data."""
    x = np.ones((128, 128), np.float32)

    def fn(x):
        return pl.pallas_call(
            _copy_kernel, grid=(4,),
            in_specs=[pl.BlockSpec((64, 128), lambda i: (i % 2, 0))],
            out_specs=pl.BlockSpec((64, 128), lambda i: (i % 2, 0)),
            out_shape=jax.ShapeDtypeStruct((128, 128), jnp.float32),
            interpret=True, name="toy_write_race")(x)

    return KernelCase(name="toy_write_race", build=lambda: (fn, (x,)))


def _toy_bf16_dot():
    """dot_general on bf16 operands without preferred_element_type=f32
    — accumulates in bf16 on the MXU."""
    x = np.ones((128, 128), np.float32).astype(jnp.bfloat16)

    def kern(x_ref, y_ref, o_ref):
        o_ref[...] = jax.lax.dot(x_ref[...], y_ref[...])

    def fn(x, y):
        return pl.pallas_call(
            kern, grid=(1,),
            in_specs=[pl.BlockSpec((128, 128), lambda i: (0, 0)),
                      pl.BlockSpec((128, 128), lambda i: (0, 0))],
            out_specs=pl.BlockSpec((128, 128), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((128, 128), jnp.bfloat16),
            interpret=True, name="toy_bf16_dot")(x, y)

    return KernelCase(name="toy_bf16_dot", build=lambda: (fn, (x, x)))


def _toy_bf16_reduce():
    """A true bf16 ``reduce_sum`` (bound directly — ``jnp.sum`` upcasts
    half floats to f32 for the accumulation, which is exactly the safe
    idiom; the lint hunts code that bypasses it)."""
    x = np.ones((128, 128), np.float32).astype(jnp.bfloat16)

    def kern(x_ref, o_ref):
        s = jax.lax.reduce_sum_p.bind(x_ref[...], axes=(1,))
        o_ref[...] = jnp.broadcast_to(s[:, None], o_ref.shape)

    def fn(x):
        return pl.pallas_call(
            kern, grid=(1,),
            in_specs=[pl.BlockSpec((128, 128), lambda i: (0, 0))],
            out_specs=pl.BlockSpec((128, 128), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((128, 128), jnp.bfloat16),
            interpret=True, name="toy_bf16_reduce")(x)

    return KernelCase(name="toy_bf16_reduce", build=lambda: (fn, (x,)))


def _findings(report, rule):
    return [f for f in report.findings if f.rule == rule]


class TestPlantedViolations:
    def test_write_hole_details(self):
        rep = analyze_kernels(cases=[_toy_hole()], check_registry=False)
        hits = _findings(rep, "kernel-write-hole")
        assert len(hits) == 1
        f = hits[0]
        assert f.severity == Severity.HIGH
        assert f.entry_point == "toy_write_hole"
        # blocks (2,0) and (3,0) of the 4x1 block grid are the holes
        assert f.details["missing_block"] == [2, 0]
        assert f.details["n_holes"] == 2
        assert f.details["nblocks"] == [4, 1]
        # nothing else fired HIGH — the hole is the one violation
        assert [x.rule for x in rep.high()] == ["kernel-write-hole"]

    def test_write_race_details(self):
        rep = analyze_kernels(cases=[_toy_race()], check_registry=False)
        hits = _findings(rep, "kernel-write-race")
        assert len(hits) == 1
        f = hits[0]
        assert f.severity == Severity.HIGH
        assert f.details["block_index"] == [0, 0]
        assert f.details["n_runs"] == 2
        # written at grid steps 0 and 2 (the two non-contiguous runs)
        assert f.details["grid_steps"] == [[0], [2]]
        assert f.details["n_raced_blocks"] == 2
        # a race is not a hole: every block IS visited
        assert not _findings(rep, "kernel-write-hole")

    def test_bf16_dot_accum_details(self):
        rep = analyze_kernels(cases=[_toy_bf16_dot()],
                              check_registry=False)
        hits = _findings(rep, "kernel-dot-accum")
        assert len(hits) == 1
        f = hits[0]
        assert f.severity == Severity.HIGH
        assert f.details["prim"] == "dot_general"
        assert f.details["in_dtypes"] == ["bfloat16", "bfloat16"]
        assert f.details["preferred_element_type"] not in (
            "float32", "float64")
        assert isinstance(f.details["eqn"], int)
        # coverage of the single-block launch is clean
        assert not _findings(rep, "kernel-write-hole")
        assert not _findings(rep, "kernel-write-race")

    def test_bf16_reduction_details(self):
        rep = analyze_kernels(cases=[_toy_bf16_reduce()],
                              check_registry=False)
        hits = _findings(rep, "kernel-reduction-dtype")
        assert len(hits) == 1
        f = hits[0]
        assert f.severity == Severity.HIGH
        assert f.details["prim"] == "reduce_sum"
        assert "bfloat16" in f.details["in_dtypes"]

    def test_fixed_twins_are_clean(self):
        """The f32-corrected twins of the dtype toys pass the lint —
        the rule keys on the accumulator dtype, not on bf16 inputs."""
        x = np.ones((128, 128), np.float32).astype(jnp.bfloat16)

        def kern(x_ref, y_ref, o_ref):
            acc = jax.lax.dot(x_ref[...], y_ref[...],
                              preferred_element_type=jnp.float32)
            s = jnp.sum(x_ref[...].astype(jnp.float32), axis=-1,
                        keepdims=True)
            o_ref[...] = (acc + s).astype(o_ref.dtype)

        def fn(x, y):
            return pl.pallas_call(
                kern, grid=(1,),
                in_specs=[pl.BlockSpec((128, 128), lambda i: (0, 0)),
                          pl.BlockSpec((128, 128), lambda i: (0, 0))],
                out_specs=pl.BlockSpec((128, 128), lambda i: (0, 0)),
                out_shape=jax.ShapeDtypeStruct((128, 128), jnp.bfloat16),
                interpret=True, name="toy_fixed")(x, y)

        rep = analyze_kernels(
            cases=[KernelCase(name="toy_fixed",
                              build=lambda: (fn, (x, x)))],
            check_registry=False)
        assert rep.high() == []


class TestRegistryCrossCheck:
    def test_unregistered_kernel_is_high(self):
        rep = analyze_kernels(cases=[_toy_hole()], check_registry=True)
        rules = {f.rule for f in rep.high()}
        assert "kernel-unregistered" in rules      # toy not in registry
        assert "kernel-registry-stale" in rules    # 12 entries unmatched

    def test_manifest_matches_registry_exactly(self):
        names = {c.name for c in kernel_manifest()}
        assert names == set(registered_kernels())

    def test_registry_metadata_complete(self):
        for name, meta in registered_kernels().items():
            assert meta.family, name
            assert meta.operand_roles, name


class TestShippedTreeClean:
    def test_zero_high_zero_medium(self):
        """The committed-artifact anchor: the shipped kernels prove
        coverage, pass the dtype lint, fit VMEM, and certify against
        their registered cost models."""
        rep = analyze_kernels()
        assert rep.high() == []
        assert rep.by_severity(Severity.MEDIUM) == []
        # every manifest kernel produced an audit row
        rows = {r["kernel"] for r in rep.meta["kernels"]}
        assert rows == {c.name for c in kernel_manifest()}

    def test_coverage_proved_everywhere(self):
        rep = analyze_kernels()
        for row in rep.meta["kernels"]:
            assert row["coverage_proved"], row["kernel"]

    def test_drift_within_tolerance(self):
        rep = analyze_kernels()
        for row in rep.meta["kernels"]:
            assert row["registered_flops"] is not None, row["kernel"]
            assert 0.5 <= row["flops_ratio"] <= 2.0, row
            lo = row["derived_bytes_unique"] / 2.0
            hi = row["derived_bytes_runs"] * 2.0
            assert lo <= row["registered_bytes"] <= hi, row

    def test_data_dependent_maps_declared(self):
        """The paged kernels' pool maps are data-dependent by design —
        declared in the manifest, so they surface as INFO, not MEDIUM."""
        rep = analyze_kernels()
        dd = _findings(rep, "kernel-data-dependent-map")
        assert dd, "paged pool maps should be flagged data-dependent"
        assert all(f.severity == Severity.INFO for f in dd)


class TestSweep:
    def test_sweep_covers_roadmap_lattice(self):
        sweep = kernel_sweep()
        assert sweep["schema_version"] == 1
        labels = [r["label"] for r in sweep["rows"]]
        assert any("ps=16" in l for l in labels)
        assert any("ps=32" in l for l in labels)
        assert any("vocab=151936" in l for l in labels)
        for row in sweep["rows"]:
            assert "error" not in row, row
            assert row["vmem_bytes"] > 0
            # serving shapes must actually fit
            assert row["vmem_frac_v5e"] < 1.0, row
            assert row["bound_v5e"] in ("compute", "memory")
            assert row["est_us_v5p"] <= row["est_us_v5e"], row


class TestKernelDoctorCLI:
    def _run(self, monkeypatch, tmp_path, cases, extra=()):
        from paddle_tpu.analysis import cli
        import paddle_tpu.ops.pallas as pallas_pkg

        if cases is not None:
            monkeypatch.setattr(pallas_pkg, "kernel_manifest",
                                lambda: cases)
        out = tmp_path / "kernels.json"
        rc = cli.main(["--kernels", "--out", str(out)] + list(extra))
        return rc, json.loads(out.read_text())

    def test_clean_tree_exits_zero(self, monkeypatch, tmp_path):
        rc, payload = self._run(monkeypatch, tmp_path, None)
        assert rc == 0
        assert payload["counts"]["HIGH"] == 0

    @pytest.mark.parametrize("toy,rule", [
        (_toy_hole, "kernel-write-hole"),
        (_toy_race, "kernel-write-race"),
        (_toy_bf16_dot, "kernel-dot-accum"),
        (_toy_bf16_reduce, "kernel-reduction-dtype"),
    ])
    def test_planted_violation_exits_one(self, monkeypatch, tmp_path,
                                         toy, rule):
        rc, payload = self._run(monkeypatch, tmp_path, [toy()])
        assert rc == 1
        assert rule in {f["rule"] for f in payload["findings"]}

    def test_sweep_exits_zero(self, tmp_path):
        from paddle_tpu.analysis import cli

        out = tmp_path / "sweep.json"
        rc = cli.main(["--kernels-sweep", "--out", str(out)])
        assert rc == 0
        payload = json.loads(out.read_text())
        assert payload["rows"]


class TestCommittedKernelArtifacts:
    def test_kernels_artifact_pinned(self):
        path = os.path.join(BENCH_DIR, "analysis_kernels.json")
        assert os.path.exists(path), "run: python -m paddle_tpu.analysis --kernels"
        payload = json.load(open(path))
        assert payload["schema_version"] == 2      # report schema
        assert payload["meta"]["schema_version"] == 1
        assert payload["counts"]["HIGH"] == 0
        assert payload["counts"]["MEDIUM"] == 0
        assert {r["kernel"] for r in payload["meta"]["kernels"]} \
            == set(registered_kernels())

    def test_sweep_artifact_pinned(self):
        path = os.path.join(BENCH_DIR, "analysis_kernels_sweep.json")
        assert os.path.exists(path), \
            "run: python -m paddle_tpu.analysis --kernels-sweep"
        payload = json.load(open(path))
        assert payload["schema_version"] == 1
        assert len(payload["rows"]) >= 8
