"""End-to-end training smoke: LeNet learns a synthetic MNIST-like task.

Parity: the reference's book/ tests (unittests/book/test_recognize_digits.py)
— tiny end-to-end convergence runs (SURVEY.md §4.6). BASELINE config #1.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
from paddle_tpu.vision.models import LeNet


def _synthetic_digits(n=256, seed=0):
    """Well-separated class blobs rendered into 28x28 images."""
    rng = np.random.RandomState(seed)
    protos = rng.randn(10, 28, 28).astype(np.float32)
    ys = rng.randint(0, 10, n)
    xs = protos[ys] + 0.3 * rng.randn(n, 28, 28).astype(np.float32)
    return xs[:, None, :, :], ys.astype(np.int64)


def test_lenet_converges():
    paddle.seed(42)
    xs, ys = _synthetic_digits(256)
    model = LeNet()
    optimizer = opt.Adam(1e-3, parameters=model.parameters())
    loss_fn = nn.CrossEntropyLoss()
    bs = 64
    losses = []
    for epoch in range(6):
        for i in range(0, len(xs), bs):
            xb = paddle.to_tensor(xs[i : i + bs])
            yb = paddle.to_tensor(ys[i : i + bs])
            logits = model(xb)
            loss = loss_fn(logits, yb)
            optimizer.clear_grad()
            loss.backward()
            optimizer.step()
        losses.append(float(loss))
    assert losses[-1] < 0.3, f"did not converge: {losses}"

    model.eval()
    logits = model(paddle.to_tensor(xs))
    acc = (logits.numpy().argmax(1) == ys).mean()
    assert acc > 0.9, f"train accuracy too low: {acc}"


def test_lenet_eager_vs_functional_grads():
    """The tape grads must match jax.grad over the functional form."""
    import jax

    paddle.seed(1)
    model = LeNet()
    xs, ys = _synthetic_digits(8, seed=3)
    x, y = paddle.to_tensor(xs), paddle.to_tensor(ys)

    loss = nn.CrossEntropyLoss()(model(x), y)
    loss.backward()
    eager_grads = {n: p.grad.numpy() for n, p in model.named_parameters()}

    params = model.state_pytree(trainable_only=True)

    def pure_loss(tree):
        with paddle.no_grad():
            pass
        out = model.functional_call(tree, x)
        return nn.CrossEntropyLoss()(out, y).value

    jg = jax.grad(pure_loss)(params)
    for n in eager_grads:
        np.testing.assert_allclose(eager_grads[n], np.asarray(jg[n]), atol=1e-4, err_msg=n)
