"""Perf doctor (ISSUE 9): scope-level roofline attribution + bench
regression watchdog.

Acceptance bars exercised here:
* scope-summed flops/bytes reconcile with whole-graph ``graph_cost``
  totals (within 1% — same walk, so exactly);
* the committed ``benchmarks/perf_attribution.json`` carries measured_s /
  roofline_min_s / efficiency / bound per scope and its ranked top
  trainer entry names an attention/matmul scope;
* ``bench-diff`` exits 0 on the known-good BENCH_r05 payload and 1 on a
  synthetic regression, naming the metric.
"""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.analysis.cost import graph_cost, scope_costs
from paddle_tpu.analysis.graph import AnalysisTarget, scope_components
from paddle_tpu.observability import baseline as bl
from paddle_tpu.observability import perf as perf_mod
from paddle_tpu.observability.__main__ import main as obs_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# =====================================================================
# name-stack normalization
# =====================================================================
class TestScopeComponents:
    def test_plain_path(self):
        assert scope_components("a/b") == ("a", "b")

    def test_strips_transform_wrappers(self):
        assert scope_components("jvp(gpt.attn)") == ("gpt.attn",)
        assert scope_components("transpose(jvp(gpt.attn))") == ("gpt.attn",)

    def test_backward_reentry_collapses_to_forward_row(self):
        # the rendered backward stack of a region under value_and_grad
        ns = "trainer.loss_grad/transpose(trainer.loss_grad)/jvp(gpt.attn)"
        assert scope_components(ns) == ("trainer.loss_grad", "gpt.attn")

    def test_empty_and_dedupe(self):
        assert scope_components("") == ()
        assert scope_components("a/a/b") == ("a", "b")


# =====================================================================
# scope-sliced roofline costs
# =====================================================================
def _toy_target():
    def f(p, x):
        with jax.named_scope("region.attn"):
            h = x @ p["w"]          # dot: 2*2*4*4 = 64 flops
            h = jnp.tanh(h)
        with jax.named_scope("region.mlp"):
            h = h @ p["w2"]         # dot: 64 flops
        return h.sum()              # unscoped reduction

    p = {"w": jnp.ones((4, 4), jnp.float32),
         "w2": jnp.ones((4, 4), jnp.float32)}
    return AnalysisTarget("toy", f, (p, jnp.ones((2, 4), jnp.float32)))


class TestScopeCosts:
    def test_hand_computed_dot_flops_per_scope(self):
        table = scope_costs(_toy_target().graph())
        by_name = {sc.name: sc for sc in table.values()}
        attn = by_name["region.attn"]
        # 2 * out_elems(2x4) * K(4) = 64 dot flops + 8 elems * 8 tanh flops
        assert attn.by_prim["dot_general"]["flops"] == 64.0
        assert attn.by_prim["tanh"]["flops"] == 64.0
        assert by_name["region.mlp"].by_prim["dot_general"]["flops"] == 64.0
        assert attn.dominant_prim in ("dot_general", "tanh")
        assert by_name["(unscoped)"].n_eqns >= 1  # the sum reduction

    def test_rows_reconcile_with_graph_cost_exactly(self):
        target = _toy_target()
        table = scope_costs(target.graph())
        gc = graph_cost(target.graph())
        assert sum(sc.flops for sc in table.values()) == gc.flops
        assert sum(sc.bytes_accessed
                   for sc in table.values()) == gc.bytes_accessed
        assert sum(sc.n_eqns for sc in table.values()) == gc.n_eqns


# =====================================================================
# measured join + ranking
# =====================================================================
class TestAttribute:
    def test_measured_total_apportioned_and_ranked(self):
        att = perf_mod.attribute(_toy_target(), peak_flops=1e12,
                                 peak_bw=1e12, measured_total_s=1.0)
        assert att.reconciliation["ok"]
        assert abs(sum(r.measured_s for r in att.rows) - 1.0) < 1e-9
        for r in att.rows:
            assert r.measured_source == "step-apportioned"
            assert r.efficiency is not None and 0 < r.efficiency <= 1
            assert r.bound in ("memory-bound", "compute-bound")
        gaps = [r.gap_s for r in att.rows]
        assert gaps == sorted(gaps, reverse=True)
        assert att.mfu is not None and att.mfu > 0

    def test_scope_timer_join_takes_direct_budget(self):
        att = perf_mod.attribute(
            _toy_target(), peak_flops=1e12, peak_bw=1e12,
            measured={"region.attn": 0.25}, measured_total_s=1.0)
        by_name = {r.scope: r for r in att.rows}
        attn = by_name["region.attn"]
        assert attn.measured_source == "scope-timer"
        assert attn.measured_s == pytest.approx(0.25)
        rest = [r for r in att.rows if r.scope != "region.attn"]
        assert all(r.measured_source == "step-apportioned" for r in rest)
        # the residual budget is the whole minus the directly-measured
        assert sum(r.measured_s for r in rest) == pytest.approx(0.75)

    def test_no_measurement_still_ranks_by_roofline(self):
        att = perf_mod.attribute(_toy_target(), peak_flops=1e12,
                                 peak_bw=1e12)
        assert all(r.measured_s is None for r in att.rows)
        rl = [r.roofline_min_s for r in att.rows]
        assert rl == sorted(rl, reverse=True)
        assert att.mfu is None

    def test_trainer_integration_rows_carry_trainer_scopes(self):
        """The REAL ParallelTrainer jit step attributes into the r6
        in-graph scopes (loss_grad / optimizer_apply)."""
        import paddle_tpu as paddle
        from paddle_tpu.distributed.env import clear_mesh, get_mesh, init_mesh, set_mesh
        from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
        from paddle_tpu.optimizer.optimizers import SGD
        from paddle_tpu.random import split_key

        prev = get_mesh()
        try:
            clear_mesh()
            init_mesh({"dp": 1})
            paddle.seed(0)
            net = paddle.nn.Linear(8, 8)
            tr = ParallelTrainer(net, lambda o, y: ((o - y) ** 2).mean(),
                                 SGD(0.01), dp_axis=None)
            tr._build()
            xb = jnp.zeros((4, 8), jnp.float32)
            args = (tr.params, tr.opt_state, tr.buffers, xb, xb,
                    split_key(), tr.scale_state, tr.sentinel_state,
                    jnp.asarray(0.01, jnp.float32))
            target = AnalysisTarget("t", tr._jit_step, args,
                                    mesh_axes={"dp": 1})
            att = perf_mod.attribute(target, measured_total_s=0.001)
            names = {r.scope for r in att.rows}
            assert any("trainer.loss_grad" in n for n in names)
            assert any("trainer.optimizer_apply" in n for n in names)
            assert att.reconciliation["ok"]
        finally:
            set_mesh(prev)


# =====================================================================
# the committed artifact (acceptance anchors, zero runtime cost)
# =====================================================================
class TestCommittedPerfArtifact:
    @pytest.fixture(scope="class")
    def doc(self):
        path = os.path.join(REPO, "benchmarks", "perf_attribution.json")
        with open(path) as f:
            return json.load(f)

    def test_schema_and_entries(self, doc):
        assert doc["schema_version"] == perf_mod.PERF_SCHEMA_VERSION
        assert set(doc["entries"]) >= {"trainer_step", "serving_decode"}

    def test_rows_carry_the_required_columns(self, doc):
        for entry in doc["entries"].values():
            assert entry["measured_total_s"] > 0
            for row in entry["rows"]:
                assert row["measured_s"] is not None
                assert row["roofline_min_s"] >= 0
                assert row["efficiency"] is None or row["efficiency"] > 0
                assert row["bound"] in ("memory-bound", "compute-bound")

    def test_scope_sums_reconcile_within_1pct(self, doc):
        for entry in doc["entries"].values():
            rec = entry["reconciliation"]
            assert rec["ok"], rec
            assert rec["flops_frac"] <= 0.01
            assert rec["bytes_frac"] <= 0.01

    def test_trainer_top_entry_is_a_matmul_scope(self, doc):
        """Sanity anchor for the Pallas target list: the biggest MFU-gap
        scope of the trainer step is attention/FFN matmul work."""
        top = doc["entries"]["trainer_step"]["rows"][0]
        assert top["dominant_prim"] == "dot_general"
        assert any(t in top["scope"]
                   for t in ("attn", "mlp", "lm_head", "matmul"))

    def test_serving_decode_names_model_and_sampling_scopes(self, doc):
        names = [r["scope"] for r in doc["entries"]["serving_decode"]["rows"]]
        assert any("gpt.attn" in n for n in names)
        assert any("serving.sample" in n for n in names)


@pytest.mark.slow
class TestPerfReportEndToEnd:
    def test_build_perf_report_regenerates(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu.profiler.scope import timer_registry, timers_enabled

        # a live process's accumulated spans AND its RNG stream must
        # survive the diagnostic run (review fixes: the report borrows,
        # snapshots, and restores the shared registry, and the entry
        # builders' paddle.seed(0) is undone afterwards)
        timer_registry.record("caller.span", 1.23)
        paddle.seed(12345)
        try:
            out = str(tmp_path / "perf.json")
            doc = perf_mod.build_perf_report(out_path=out, steps=2, ticks=4)
            with open(out) as f:
                on_disk = json.load(f)
            assert on_disk["schema_version"] == perf_mod.PERF_SCHEMA_VERSION
            for entry in doc["entries"].values():
                assert entry["reconciliation"]["ok"]
                assert entry["rows"][0]["measured_s"] > 0
            assert timer_registry.total("caller.span") == 1.23
            assert not timers_enabled()
            # and the report's own spans did not leak into the caller's view
            assert "serving.decode_step" not in timer_registry.averages()
            # the RNG continues the caller's seed-12345 stream, not seed 0
            after = np.asarray(paddle.randn([4])._data)
            paddle.seed(12345)
            control = np.asarray(paddle.randn([4])._data)
            np.testing.assert_array_equal(after, control)
        finally:
            timer_registry.reset()


# =====================================================================
# bench regression watchdog
# =====================================================================
def _lineage_files():
    return sorted(
        os.path.join(REPO, f) for f in os.listdir(REPO)
        if f.startswith("BENCH_r0") and f.endswith(".json"))


class TestBaselineRebuild:
    def test_flatten_payload_primary_secondary_nested(self):
        flat = bl.flatten_payload({
            "metric": "m_tokens_per_sec", "value": 10.0, "vs_baseline": 1.1,
            "secondary": {"a_ms": 2.0, "nested": {"ok": True, "x": 1}}})
        assert flat == {"m_tokens_per_sec": 10.0, "vs_baseline": 1.1,
                        "a_ms": 2.0, "nested.ok": True, "nested.x": 1}

    def test_classify_patterns(self):
        assert bl.classify_metric("gpt_tokens_per_sec", 1.0) == "higher"
        assert bl.classify_metric("serving_cb_ttft_p50_ms", 1.0) == "lower"
        assert bl.classify_metric("x_overhead_frac", 0.1) == "lower"
        assert bl.classify_metric("overload_shed_ttft_within_3x",
                                  True) == "flag"
        assert bl.classify_metric("a.silent_drops", 0) == "count_max"
        assert bl.classify_metric("serving_compiled_programs", 4) == "info"

    def test_rebuild_covers_its_own_lineage(self, tmp_path):
        out = str(tmp_path / "baseline.json")
        doc = bl.rebuild(_lineage_files(), out_path=out)
        assert doc["schema_version"] == bl.BASELINE_SCHEMA_VERSION
        # every lineage payload passes its own baseline by construction
        for path in _lineage_files():
            with open(path) as f:
                payload = json.load(f)
            verdict = bl.compare(payload, doc)
            assert verdict["ok"], (path, verdict["regressions"])

    def test_negative_valued_lineage_covers_itself(self, tmp_path):
        """Review fixes: sign-aware band widening (a negative extreme
        times (1+pad) moves the bound the WRONG way) and the `magnitude`
        class for zero-is-ideal drift metrics (an all-negative lineage
        must not flag a later PERFECT 0.0 as above the band ceiling)."""
        payloads = [
            {"metric": "m_tokens_per_sec", "value": 10.0,
             "secondary": {"observability_hbm_drift_frac": drift,
                           "weird_mfu": mfu}}
            for drift, mfu in ((-0.05, -2.0), (-0.02, -1.5), (-0.01, -1.0))]
        files = []
        for i, p in enumerate(payloads):
            f = tmp_path / f"BENCH_neg{i}.json"
            f.write_text(json.dumps(p))
            files.append(str(f))
        doc = bl.rebuild(files)
        assert doc["metrics"]["observability_hbm_drift_frac"]["class"] == \
            "magnitude"
        assert doc["metrics"]["weird_mfu"]["class"] == "higher"
        for p in payloads:
            verdict = bl.compare(p, doc)
            assert verdict["ok"], verdict["regressions"]
        # drift improving to a perfect 0.0 (or flipping sign inside the
        # magnitude band) is an IMPROVEMENT, never a regression
        perfect = {"metric": "m_tokens_per_sec", "value": 10.0,
                   "secondary": {"observability_hbm_drift_frac": 0.0,
                                 "weird_mfu": -1.0}}
        assert bl.compare(perfect, doc)["ok"]
        flipped = dict(perfect,
                       secondary={"observability_hbm_drift_frac": 0.04,
                                  "weird_mfu": -1.0})
        assert bl.compare(flipped, doc)["ok"]
        # genuinely-worse values still gate in both directions
        bad = {"metric": "m_tokens_per_sec", "value": 10.0,
               "secondary": {"observability_hbm_drift_frac": 0.5,
                             "weird_mfu": -5.0}}
        names = {r["metric"] for r in bl.compare(bad, doc)["regressions"]}
        assert names == {"observability_hbm_drift_frac", "weird_mfu"}

    def test_committed_baseline_matches_rebuild(self):
        committed = bl.load_baseline()
        fresh = bl.rebuild(_lineage_files())
        assert committed["metrics"] == json.loads(
            json.dumps(fresh["metrics"]))


class TestBenchDiff:
    def _regressed_payload(self, tmp_path):
        with open(os.path.join(REPO, "BENCH_r05.json")) as f:
            doc = json.load(f)
        doc["parsed"]["value"] = doc["parsed"]["value"] * 0.5
        doc["parsed"]["secondary"]["pipeline_step_ratio"] = 0.3
        p = tmp_path / "regressed.json"
        p.write_text(json.dumps(doc))
        return str(p)

    def test_known_good_r05_exits_0(self, capsys):
        rc = obs_main(["bench-diff", os.path.join(REPO, "BENCH_r05.json")])
        assert rc == 0

    def test_cpu_arm_payload_judged_against_cpu_bands_only(self, tmp_path):
        """r15 arm segregation: CPU smoke payloads share metric NAMES
        with the on-chip lineage but not comparable values — compare()
        must pick the band set matching the payload's arm, and untagged
        (pre-r15) payloads default to the tpu lineage."""
        assert bl.payload_arm({"metric": "m", "value": 1.0}) == "tpu"
        assert bl.payload_arm({"arm": "cpu", "metric": "m"}) == "cpu"
        tpu = {"metric": "m_tokens_per_sec", "value": 100.0}
        cpu = {"arm": "cpu", "metric": "m_tokens_per_sec", "value": 1.0}
        files = []
        for i, p in enumerate((tpu, cpu)):
            f = tmp_path / f"BENCH_arm{i}.json"
            f.write_text(json.dumps(p))
            files.append(str(f))
        doc = bl.rebuild(files)
        # each arm's own payload passes; the bands never cross arms (the
        # CPU value is 100x below the tpu band floor and vice versa)
        assert bl.compare(tpu, doc)["ok"]
        assert bl.compare(cpu, doc)["ok"]
        assert not bl.compare(dict(tpu, value=1.0), doc)["ok"]
        assert not bl.compare(dict(cpu, value=0.01), doc)["ok"]
        # a CPU payload against a baseline with NO cpu lineage is an
        # empty (trivially ok) verdict, not a false regression
        tpu_only = bl.rebuild(files[:1])
        v = bl.compare(cpu, tpu_only)
        assert v["ok"] and v["compared"] == 0

    def test_committed_baseline_carries_cpu_arm_bands(self):
        committed = bl.load_baseline()
        cpu = committed.get("metrics_cpu", {})
        # the r15 paged serving numbers are guarded on their own arm
        for name in ("serving_paged_tokens_per_sec",
                     "prefix_hit_ttft_p50_ms",
                     "prefix_hit_ttft_improved",
                     "serving_paged_exact_vs_slot"):
            assert name in cpu, name
        assert cpu["serving_paged_exact_vs_slot"]["class"] == "flag"
        assert cpu["serving_paged_exact_vs_slot"]["expect_true"]

    def test_synthetic_regression_exits_1_naming_metric(self, tmp_path,
                                                        capsys):
        rc = obs_main(["bench-diff", self._regressed_payload(tmp_path)])
        err = capsys.readouterr().err
        assert rc == 1
        assert "gpt3_1.3b_train_tokens_per_sec_chip" in err
        assert "pipeline_step_ratio" in err
        assert "PRIMARY" in err

    def test_compare_primary_regressions_lead(self, tmp_path):
        with open(self._regressed_payload(tmp_path)) as f:
            payload = json.load(f)
        verdict = bl.compare(payload, bl.load_baseline())
        assert not verdict["ok"]
        assert verdict["regressions"][0]["primary"] is True

    def test_flag_regression_gates(self):
        base = bl.rebuild(_lineage_files())
        base["metrics"]["fake_overhead_ok"] = {
            "class": "flag", "expect_true": True, "n": 1, "values": [True],
            "primary": False}
        verdict = bl.compare(
            {"metric": "x", "value": 1.0,
             "secondary": {"fake_overhead_ok": False}}, base)
        assert not verdict["ok"]
        assert verdict["regressions"][0]["metric"] == "fake_overhead_ok"

    def test_type_changed_metric_surfaces_as_missing_not_compared(self):
        """Review fix: a lineage float that a refactor turns into a bool
        must not be silently 'compared' — it can't gate, so it surfaces
        with the missing metrics."""
        base = bl.load_baseline()
        with open(os.path.join(REPO, "BENCH_r05.json")) as f:
            payload = json.load(f)
        good = bl.compare(payload, base)
        payload["parsed"]["secondary"]["pipeline_step_ratio"] = True
        verdict = bl.compare(payload, base)
        assert "pipeline_step_ratio" in verdict["missing_metrics"]
        assert verdict["compared"] == good["compared"] - 1

    def test_missing_metric_reported_not_silent(self):
        base = bl.load_baseline()
        verdict = bl.compare({"metric": "other", "value": 1.0,
                              "secondary": {}}, base)
        assert verdict["ok"]  # nothing regressed ...
        assert "pipeline_step_ratio" in verdict["missing_metrics"]

    def test_cli_subprocess_fidelity(self, tmp_path):
        """One real subprocess run: the committed baseline + r05 payload
        through the installed CLI exits 0 (the exact CI invocation)."""
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=os.pathsep.join(
                       p for p in (REPO, os.environ.get("PYTHONPATH"))
                       if p))
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.observability", "bench-diff",
             os.path.join(REPO, "BENCH_r05.json")],
            capture_output=True, text=True, env=env, cwd=REPO, timeout=240)
        assert proc.returncode == 0, proc.stderr
