"""Optimizer correctness (vs torch reference where available) + LR schedules."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt


def _quad_problem():
    p = nn.Parameter(np.array([1.0, -2.0, 3.0], np.float32))
    p.name = "p0"
    target = np.array([0.5, 0.5, 0.5], np.float32)

    def loss_fn():
        diff = p - paddle.to_tensor(target)
        return (diff * diff).sum()

    return p, loss_fn


@pytest.mark.parametrize(
    "factory",
    [
        lambda ps: opt.SGD(0.1, parameters=ps),
        lambda ps: opt.Momentum(0.05, 0.9, parameters=ps),
        lambda ps: opt.Adam(0.1, parameters=ps),
        lambda ps: opt.AdamW(0.1, parameters=ps),
        lambda ps: opt.Adamax(0.1, parameters=ps),
        lambda ps: opt.Adagrad(0.3, parameters=ps),
        lambda ps: opt.Adadelta(1.0, rho=0.9, epsilon=1e-2, parameters=ps),
        lambda ps: opt.RMSProp(0.05, parameters=ps),
        lambda ps: opt.Lamb(0.1, parameters=ps),
        lambda ps: opt.Lars(100.0, momentum=0.5, parameters=ps),
    ],
)
def test_converges(factory):
    p, loss_fn = _quad_problem()
    o = factory([p])
    for _ in range(60):
        loss = loss_fn()
        o.clear_grad()
        loss.backward()
        o.step()
    assert float(loss_fn()) < 0.05, f"{type(o).__name__} failed to converge: {float(loss_fn())}"


def test_sgd_matches_torch():
    import torch

    w0 = np.random.randn(4, 3).astype(np.float32)
    x = np.random.randn(8, 4).astype(np.float32)

    tp = torch.nn.Parameter(torch.tensor(w0.copy()))
    topt = torch.optim.SGD([tp], lr=0.1)
    for _ in range(3):
        loss = (torch.tensor(x) @ tp).pow(2).sum()
        topt.zero_grad()
        loss.backward()
        topt.step()

    pp = nn.Parameter(w0.copy())
    popt = opt.SGD(0.1, parameters=[pp])
    for _ in range(3):
        loss = (paddle.to_tensor(x) @ pp).square().sum()
        popt.clear_grad()
        loss.backward()
        popt.step()
    np.testing.assert_allclose(pp.numpy(), tp.detach().numpy(), atol=1e-4)


def test_adam_matches_torch():
    import torch

    w0 = np.random.randn(5).astype(np.float32)
    tp = torch.nn.Parameter(torch.tensor(w0.copy()))
    topt = torch.optim.Adam([tp], lr=0.01, betas=(0.9, 0.999), eps=1e-8)
    pp = nn.Parameter(w0.copy())
    popt = opt.Adam(0.01, parameters=[pp])
    for _ in range(5):
        tl = (tp * tp).sum()
        topt.zero_grad()
        tl.backward()
        topt.step()
        pl = (pp * pp).sum()
        popt.clear_grad()
        pl.backward()
        popt.step()
    np.testing.assert_allclose(pp.numpy(), tp.detach().numpy(), atol=1e-5)


def test_adamw_decoupled_decay():
    import torch

    w0 = np.random.randn(5).astype(np.float32)
    tp = torch.nn.Parameter(torch.tensor(w0.copy()))
    topt = torch.optim.AdamW([tp], lr=0.01, weight_decay=0.1)
    pp = nn.Parameter(w0.copy())
    popt = opt.AdamW(0.01, parameters=[pp], weight_decay=0.1)
    for _ in range(5):
        tl = (tp * tp).sum()
        topt.zero_grad(); tl.backward(); topt.step()
        pl = (pp * pp).sum()
        popt.clear_grad(); pl.backward(); popt.step()
    # paddle AdamW: p -= lr*(update + wd*p) vs torch p *= (1-lr*wd) first — tiny diff
    np.testing.assert_allclose(pp.numpy(), tp.detach().numpy(), atol=1e-4)


def test_weight_decay_l2():
    p = nn.Parameter(np.array([1.0], np.float32))
    p.name = "w"
    o = opt.SGD(0.1, parameters=[p], weight_decay=0.5)
    (p * 0).sum().backward()
    o.step()
    # grad = 0 + 0.5*1.0 -> p = 1 - 0.1*0.5
    np.testing.assert_allclose(p.numpy(), [0.95], rtol=1e-6)


def test_grad_clip_in_optimizer():
    p = nn.Parameter(np.array([1.0, 1.0], np.float32))
    p.name = "w"
    o = opt.SGD(1.0, parameters=[p], grad_clip=nn.ClipGradByGlobalNorm(0.1))
    (p * paddle.to_tensor(np.array([30.0, 40.0], np.float32))).sum().backward()
    o.step()
    g_norm = np.linalg.norm(np.array([1.0, 1.0]) - p.numpy())
    np.testing.assert_allclose(g_norm, 0.1, rtol=1e-4)


def test_state_dict_roundtrip():
    p, loss_fn = _quad_problem()
    o = opt.Adam(0.1, parameters=[p])
    loss_fn().backward()
    o.step()
    sd = o.state_dict()
    p2, _ = _quad_problem()
    o2 = opt.Adam(0.1, parameters=[p2])
    o2.set_state_dict(sd)
    assert o2._global_step == 1
    np.testing.assert_allclose(
        o2._accumulators[id(p2)]["moment1"], o._accumulators[id(p)]["moment1"]
    )


def test_functional_api_matches_eager():
    import jax.numpy as jnp

    w0 = np.random.randn(3).astype(np.float32)
    pp = nn.Parameter(w0.copy())
    eager = opt.Adam(0.05, parameters=[pp])
    for _ in range(3):
        (pp * pp).sum().backward()
        eager.step()
        pp.clear_grad()

    fopt = opt.Adam(0.05)
    params = {"w": jnp.asarray(w0)}
    state = fopt.init_state(params)
    for _ in range(3):
        grads = {"w": 2 * params["w"]}
        params, state = fopt.apply_gradients(params, grads, state)
    np.testing.assert_allclose(pp.numpy(), np.asarray(params["w"]), atol=1e-6)


class TestLRSchedulers:
    def test_step_decay(self):
        s = opt.lr.StepDecay(1.0, step_size=2, gamma=0.5)
        lrs = [s()]
        for _ in range(4):
            s.step()
            lrs.append(s())
        np.testing.assert_allclose(lrs, [1.0, 1.0, 0.5, 0.5, 0.25])

    def test_cosine(self):
        s = opt.lr.CosineAnnealingDecay(1.0, T_max=10)
        assert abs(s() - 1.0) < 1e-6
        for _ in range(10):
            s.step()
        assert abs(s() - 0.0) < 1e-6

    def test_warmup(self):
        s = opt.lr.LinearWarmup(1.0, warmup_steps=5, start_lr=0.0, end_lr=1.0)
        vals = [s()]
        for _ in range(5):
            s.step()
            vals.append(s())
        np.testing.assert_allclose(vals, [0.0, 0.2, 0.4, 0.6, 0.8, 1.0])

    def test_optimizer_uses_scheduler(self):
        p, loss_fn = _quad_problem()
        sched = opt.lr.StepDecay(0.1, step_size=1, gamma=0.1)
        o = opt.SGD(sched, parameters=[p])
        assert o.get_lr() == 0.1
        sched.step()
        assert abs(o.get_lr() - 0.01) < 1e-9

    def test_noam(self):
        s = opt.lr.NoamDecay(d_model=512, warmup_steps=100, learning_rate=1.0)
        for _ in range(99):
            s.step()
        peak_region = s()
        for _ in range(400):
            s.step()
        assert s() < peak_region

    def test_reduce_on_plateau(self):
        s = opt.lr.ReduceOnPlateau(1.0, patience=1, factor=0.5)
        s.step(1.0)
        s.step(1.0)
        s.step(1.0)
        assert s() == 0.5


# ---------------------------------------------------------------------------
# legacy optimizer tail (VERDICT r4 #4): numpy re-derivations of the
# reference update rules in operators/optimizers/{ftrl,dpsgd,proximal_gd,
# proximal_adagrad,decayed_adagrad}_op.h
# ---------------------------------------------------------------------------
def _run_steps(o, p, loss_fn, n=3):
    outs = []
    for _ in range(n):
        loss = loss_fn()
        o.clear_grad()
        loss.backward()
        o.step()
        outs.append(np.asarray(p._data).copy())
    return outs


def test_ftrl_matches_numpy():
    rng = np.random.default_rng(3)
    p0 = rng.standard_normal(4).astype(np.float32)
    p = nn.Parameter(p0.copy()); p.name = "p0"
    tgt = paddle.to_tensor(np.zeros(4, np.float32))
    loss_fn = lambda: ((p - tgt) * (p - tgt)).sum()
    lr, l1, l2, lrp = 0.1, 0.05, 0.1, -0.5
    o = opt.Ftrl(lr, l1=l1, l2=l2, lr_power=lrp, parameters=[p])
    got = _run_steps(o, p, loss_fn, n=3)

    # numpy re-derivation (ftrl_op.h FTRLFunctor)
    pw, n_acc, z = p0.copy(), np.zeros(4, np.float32), np.zeros(4, np.float32)
    for _ in range(3):
        g = 2 * pw  # d/dp sum((p-0)^2)
        n_new = n_acc + g * g
        sigma = (np.sqrt(n_new) - np.sqrt(n_acc)) / lr
        z = z + g - sigma * pw
        y = np.sqrt(n_new) / lr + 2 * l2
        x = np.sign(z) * l1 - z
        pw = np.where(np.abs(z) > l1, x / y, 0.0).astype(np.float32)
        n_acc = n_new
    np.testing.assert_allclose(got[-1], pw, atol=1e-5, rtol=1e-5)


def test_dpsgd_clips_and_steps():
    # sigma=0 removes the noise term; the reference then reduces to
    # p -= lr * g / max(1, ||g||/clip)  (dpsgd_op.h)
    p0 = np.array([3.0, 4.0], np.float32)  # ||g|| = 2*5 = 10 > clip
    p = nn.Parameter(p0.copy()); p.name = "p0"
    loss_fn = lambda: (p * p).sum()
    clip = 1.0
    o = opt.Dpsgd(0.1, clip=clip, batch_size=8.0, sigma=0.0, parameters=[p])
    got = _run_steps(o, p, loss_fn, n=1)[0]
    g = 2 * p0
    scale = np.linalg.norm(g) / clip
    np.testing.assert_allclose(got, p0 - 0.1 * g / scale, atol=1e-5, rtol=1e-5)


def test_dpsgd_noise_reproducible():
    p = nn.Parameter(np.zeros(2, np.float32)); p.name = "p0"
    loss_fn = lambda: (p * p).sum()
    o = opt.Dpsgd(0.1, clip=10.0, batch_size=1.0, sigma=1.0, seed=7,
                  parameters=[p])
    a = _run_steps(o, p, loss_fn, n=2)
    p2 = nn.Parameter(np.zeros(2, np.float32)); p2.name = "p0"
    loss_fn2 = lambda: (p2 * p2).sum()
    o2 = opt.Dpsgd(0.1, clip=10.0, batch_size=1.0, sigma=1.0, seed=7,
                   parameters=[p2])
    b = _run_steps(o2, p2, loss_fn2, n=2)
    np.testing.assert_array_equal(a[-1], b[-1])
    assert np.any(a[-1] != 0.0)  # noise actually applied (grad is 0)


def test_proximal_gd_matches_numpy():
    p0 = np.array([1.0, -2.0, 0.05], np.float32)
    p = nn.Parameter(p0.copy()); p.name = "p0"
    loss_fn = lambda: (p * p).sum()
    lr, l1, l2 = 0.1, 0.2, 0.3
    o = opt.ProximalGD(lr, l1=l1, l2=l2, parameters=[p])
    got = _run_steps(o, p, loss_fn, n=2)
    pw = p0.copy()
    for _ in range(2):
        g = 2 * pw
        prox = pw - lr * g
        pw = (np.sign(prox) * np.maximum(np.abs(prox) - lr * l1, 0.0)
              / (1.0 + lr * l2)).astype(np.float32)
    np.testing.assert_allclose(got[-1], pw, atol=1e-6, rtol=1e-6)


def test_proximal_adagrad_matches_numpy():
    p0 = np.array([1.0, -2.0, 3.0], np.float32)
    p = nn.Parameter(p0.copy()); p.name = "p0"
    loss_fn = lambda: (p * p).sum()
    lr, l1, l2 = 0.1, 0.1, 0.2
    o = opt.ProximalAdagrad(lr, l1=l1, l2=l2, parameters=[p])
    got = _run_steps(o, p, loss_fn, n=3)
    pw, m = p0.copy(), np.zeros(3, np.float32)
    for _ in range(3):
        g = 2 * pw
        m = m + g * g
        prox = pw - lr * g / np.sqrt(m)
        pw = (np.sign(prox) * np.maximum(np.abs(prox) - lr * l1, 0.0)
              / (1.0 + lr * l2)).astype(np.float32)
    np.testing.assert_allclose(got[-1], pw, atol=1e-5, rtol=1e-5)


def test_decayed_adagrad_matches_numpy():
    p0 = np.array([1.0, -2.0, 3.0], np.float32)
    p = nn.Parameter(p0.copy()); p.name = "p0"
    loss_fn = lambda: (p * p).sum()
    lr, decay, eps = 0.1, 0.95, 1e-6
    o = opt.DecayedAdagrad(lr, decay=decay, epsilon=eps, parameters=[p])
    got = _run_steps(o, p, loss_fn, n=3)
    pw, m = p0.copy(), np.zeros(3, np.float32)
    for _ in range(3):
        g = 2 * pw
        m = decay * m + (1 - decay) * g * g
        pw = (pw - lr * g / (np.sqrt(m) + eps)).astype(np.float32)
    np.testing.assert_allclose(got[-1], pw, atol=1e-5, rtol=1e-5)
