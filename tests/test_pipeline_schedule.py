"""Stage-parallel ppermute-scan pipeline tests (8-virtual-device mesh).

Parity strategy per the reference's pipeline tests
(test_parallel_dygraph_pipeline_layer.py): the pipelined model must match
the NON-pipelined model — same loss on the same weights, and matching
training trajectories.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import P
from paddle_tpu.distributed.meta_parallel.pipeline_schedule import (
    GPTPipelineModule,
    build_gpt_pipeline_step,
)
from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
from paddle_tpu.optimizer.optimizers import SGD, AdamW


def tiny_cfg(**kw):
    base = dict(vocab_size=64, hidden_size=32, num_layers=4,
                num_attention_heads=4, max_position_embeddings=32,
                hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    base.update(kw)
    return gpt_config("gpt2-small", **base)


@pytest.fixture(autouse=True)
def _clean():
    yield
    dist.clear_mesh()


def _data(b, t=16, vocab=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, vocab, (b, t)).astype("int32")
    return x, x.copy()


def _dense_loss(model, x, y):
    """Reference loss: full model + shifted-free CE (same as _head_loss)."""
    logits = model(paddle.to_tensor(x))
    logp = jax.nn.log_softmax(jnp.asarray(logits._data, jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, jnp.asarray(y)[..., None], axis=-1)
    return float(-ll.mean())


class TestPipelineLoss:
    def test_pipeline_loss_matches_dense(self):
        """pp=4 pipelined forward loss == single-device loss, same weights."""
        dist.init_mesh({"pp": 4, "dp": 2})
        paddle.seed(0)
        model = GPTForPretraining(tiny_cfg())
        model.eval()
        x, y = _data(8)
        ref = _dense_loss(model, x, y)

        pipe = GPTPipelineModule(model, num_stages=4, microbatches=2)
        mesh = dist.get_mesh()

        from jax import shard_map

        def fn(st, sh, x, y):
            return jax.lax.pmean(pipe.local_loss(st, sh, x, y), "dp")

        f = jax.jit(shard_map(
            fn, mesh=mesh,
            in_specs=({k: P("pp") for k in pipe.stage_params}, P(), P("dp"), P("dp")),
            out_specs=P(),
            check_vma=False,
        ))
        loss = float(f(pipe.stage_params, pipe.shared_params, x, y))
        # mean over dp halves of the microbatch-mean CE == full-batch CE
        assert abs(loss - ref) < 2e-4, (loss, ref)

    def test_train_step_converges_pp4_dp2(self):
        dist.init_mesh({"pp": 4, "dp": 2})
        paddle.seed(0)
        model = GPTForPretraining(tiny_cfg())
        opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
        step = build_gpt_pipeline_step(model, opt, microbatches=2)
        x, y = _data(8)
        losses = [float(step(x, y)) for _ in range(10)]
        assert losses[-1] < losses[0] * 0.9, losses

    def test_pipeline_matches_dense_training(self):
        """One SGD step through the pipeline == one SGD step dense."""
        dist.init_mesh({"pp": 4})
        paddle.seed(0)
        cfg = tiny_cfg()
        model = GPTForPretraining(cfg)
        x, y = _data(4, seed=3)

        # dense reference: same functional loss, plain jax grad + sgd
        pipe_ref = GPTPipelineModule(model, num_stages=4, microbatches=2)
        lr = 0.1

        def dense_loss(stages, shared):
            h = pipe_ref._embed(shared, jnp.asarray(x))
            flat = jax.tree_util.tree_map(
                lambda a: a.reshape((4,) + a.shape[2:]), stages)
            for i in range(4):
                lp = jax.tree_util.tree_map(lambda a: a[i], flat)
                h = pipe_ref._apply_block(lp, h)
            return pipe_ref._head_loss(shared, h, jnp.asarray(y))

        g_st, g_sh = jax.grad(dense_loss, argnums=(0, 1))(
            pipe_ref.stage_params, pipe_ref.shared_params)
        want_st = jax.tree_util.tree_map(
            lambda p, g: p - lr * g, pipe_ref.stage_params, g_st)
        want_sh = jax.tree_util.tree_map(
            lambda p, g: p - lr * g, pipe_ref.shared_params, g_sh)

        opt = SGD(learning_rate=lr, parameters=model.parameters())
        step = build_gpt_pipeline_step(model, opt, microbatches=2)
        step(x, y)
        got_st = step.state["params"]["stages"]
        got_sh = step.state["params"]["shared"]
        for n in want_st:
            np.testing.assert_allclose(
                np.asarray(got_st[n]), np.asarray(want_st[n]),
                rtol=2e-4, atol=2e-5, err_msg=n)
        for n in want_sh:
            np.testing.assert_allclose(
                np.asarray(got_sh[n]), np.asarray(want_sh[n]),
                rtol=2e-4, atol=2e-5, err_msg=n)

    def test_sync_to_model_roundtrip(self):
        dist.init_mesh({"pp": 4})
        paddle.seed(0)
        model = GPTForPretraining(tiny_cfg())
        opt = SGD(learning_rate=0.01, parameters=model.parameters())
        step = build_gpt_pipeline_step(model, opt, microbatches=2)
        x, y = _data(4)
        step(x, y)
        step.sync_to_model()
        # model now runs with trained weights eagerly
        out = model(paddle.to_tensor(x))
        assert list(out.shape) == [4, 16, 64]

    def test_dropout_rejected(self):
        dist.init_mesh({"pp": 4})
        model = GPTForPretraining(tiny_cfg(hidden_dropout_prob=0.1))
        with pytest.raises(ValueError, match="dropout"):
            GPTPipelineModule(model, 4, 2)
