"""Stage-parallel ppermute-scan pipeline tests (8-virtual-device mesh).

Parity strategy per the reference's pipeline tests
(test_parallel_dygraph_pipeline_layer.py): the pipelined model must match
the NON-pipelined model — same loss on the same weights, and matching
training trajectories.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import P
from paddle_tpu.distributed.meta_parallel.pipeline_schedule import (
    GPTPipelineModule,
    build_gpt_pipeline_step,
)
from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
from paddle_tpu.optimizer.optimizers import SGD, AdamW


def tiny_cfg(**kw):
    base = dict(vocab_size=64, hidden_size=32, num_layers=4,
                num_attention_heads=4, max_position_embeddings=32,
                hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    base.update(kw)
    return gpt_config("gpt2-small", **base)


@pytest.fixture(autouse=True)
def _clean():
    yield
    dist.clear_mesh()


def _data(b, t=16, vocab=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, vocab, (b, t)).astype("int32")
    return x, x.copy()


def _dense_loss(model, x, y):
    """Reference loss: full model + shifted-free CE (same as _head_loss)."""
    logits = model(paddle.to_tensor(x))
    logp = jax.nn.log_softmax(jnp.asarray(logits._data, jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, jnp.asarray(y)[..., None], axis=-1)
    return float(-ll.mean())


class TestPipelineLoss:
    def test_pipeline_loss_matches_dense(self):
        """pp=4 pipelined forward loss == single-device loss, same weights."""
        dist.init_mesh({"pp": 4, "dp": 2})
        paddle.seed(0)
        model = GPTForPretraining(tiny_cfg())
        model.eval()
        x, y = _data(8)
        ref = _dense_loss(model, x, y)

        pipe = GPTPipelineModule(model, num_stages=4, microbatches=2)
        mesh = dist.get_mesh()

        from paddle_tpu.distributed.spmd import shard_map

        def fn(st, sh, x, y):
            return jax.lax.pmean(pipe.local_loss(st, sh, x, y), "dp")

        f = jax.jit(shard_map(
            fn, mesh=mesh,
            in_specs=({k: P("pp") for k in pipe.stage_params}, P(), P("dp"), P("dp")),
            out_specs=P(),
            check_vma=False,
        ))
        loss = float(f(pipe.stage_params, pipe.shared_params, x, y))
        # mean over dp halves of the microbatch-mean CE == full-batch CE
        assert abs(loss - ref) < 2e-4, (loss, ref)

    def test_train_step_converges_pp4_dp2(self):
        dist.init_mesh({"pp": 4, "dp": 2})
        paddle.seed(0)
        model = GPTForPretraining(tiny_cfg())
        opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
        step = build_gpt_pipeline_step(model, opt, microbatches=2)
        x, y = _data(8)
        losses = [float(step(x, y)) for _ in range(10)]
        assert losses[-1] < losses[0] * 0.9, losses

    def test_pipeline_matches_dense_training(self):
        """One SGD step through the pipeline == one SGD step dense."""
        dist.init_mesh({"pp": 4})
        paddle.seed(0)
        cfg = tiny_cfg()
        model = GPTForPretraining(cfg)
        x, y = _data(4, seed=3)

        # dense reference: same functional loss, plain jax grad + sgd
        pipe_ref = GPTPipelineModule(model, num_stages=4, microbatches=2)
        lr = 0.1

        def dense_loss(stages, shared):
            h = pipe_ref._embed(shared, jnp.asarray(x))
            flat = jax.tree_util.tree_map(
                lambda a: a.reshape((4,) + a.shape[2:]), stages)
            for i in range(4):
                lp = jax.tree_util.tree_map(lambda a: a[i], flat)
                h = pipe_ref._apply_block(lp, h)
            return pipe_ref._head_loss(shared, h, jnp.asarray(y))

        g_st, g_sh = jax.grad(dense_loss, argnums=(0, 1))(
            pipe_ref.stage_params, pipe_ref.shared_params)
        want_st = jax.tree_util.tree_map(
            lambda p, g: p - lr * g, pipe_ref.stage_params, g_st)
        want_sh = jax.tree_util.tree_map(
            lambda p, g: p - lr * g, pipe_ref.shared_params, g_sh)

        opt = SGD(learning_rate=lr, parameters=model.parameters())
        step = build_gpt_pipeline_step(model, opt, microbatches=2)
        step(x, y)
        got_st = step.state["params"]["stages"]
        got_sh = step.state["params"]["shared"]
        for n in want_st:
            np.testing.assert_allclose(
                np.asarray(got_st[n]), np.asarray(want_st[n]),
                rtol=2e-4, atol=2e-5, err_msg=n)
        for n in want_sh:
            np.testing.assert_allclose(
                np.asarray(got_sh[n]), np.asarray(want_sh[n]),
                rtol=2e-4, atol=2e-5, err_msg=n)

    def test_sync_to_model_roundtrip(self):
        dist.init_mesh({"pp": 4})
        paddle.seed(0)
        model = GPTForPretraining(tiny_cfg())
        opt = SGD(learning_rate=0.01, parameters=model.parameters())
        step = build_gpt_pipeline_step(model, opt, microbatches=2)
        x, y = _data(4)
        step(x, y)
        step.sync_to_model()
        # model now runs with trained weights eagerly
        out = model(paddle.to_tensor(x))
        assert list(out.shape) == [4, 16, 64]

    def test_moe_misaligned_rejected(self):
        """4 layers over 4 stages = 1 layer/stage, but MoE-every-2 gives the
        stages different structures — must fail loudly, not silently."""
        dist.init_mesh({"pp": 4})
        model = GPTForPretraining(tiny_cfg(num_experts=4))
        with pytest.raises(ValueError, match="slot"):
            GPTPipelineModule(model, 4, 2)


class TestMoEPipeline:
    """EP composed into the hybrid (VERDICT r2 missing #2): MoE blocks run
    their all_to_all over 'ep' inside the same shard_map as pp/dp."""

    def _cfg(self, **kw):
        base = dict(num_experts=2, moe_every=2, moe_capacity_factor=8.0,
                    moe_aux_loss_weight=0.0)
        base.update(kw)
        return tiny_cfg(**base)

    def test_moe_pipeline_loss_matches_dense(self):
        """pp=2 x ep=2 x dp=2 pipelined loss == eager dense loss (capacity
        large enough that no token drops => sharded gating is exact)."""
        dist.init_mesh({"pp": 2, "ep": 2, "dp": 2})
        paddle.seed(0)
        model = GPTForPretraining(self._cfg())
        model.eval()
        x, y = _data(8)
        ref = _dense_loss(model, x, y)

        pipe = GPTPipelineModule(model, num_stages=2, microbatches=2)
        mesh = dist.get_mesh()

        from paddle_tpu.distributed.spmd import shard_map

        def fn(st, sh, x, y):
            l = pipe.local_loss(st, sh, x, y)
            return jax.lax.pmean(jax.lax.pmean(l, "dp"), "ep")

        f = jax.jit(shard_map(
            fn, mesh=mesh,
            in_specs=({k: pipe.stage_specs[k] for k in pipe.stage_params},
                      P(), P(("dp", "ep")), P(("dp", "ep"))),
            out_specs=P(),
            check_vma=False,
        ))
        import jax as _jax
        placed = {
            k: _jax.device_put(
                v, _jax.sharding.NamedSharding(mesh, pipe.stage_specs[k]))
            for k, v in pipe.stage_params.items()
        }
        loss = float(f(placed, pipe.shared_params, x, y))
        assert abs(loss - ref) < 5e-4, (loss, ref)

    def test_moe_pipeline_step_matches_dense(self):
        """Gradient exactness for the pp x ep step (ADVICE r3): expert-
        sharded grads arrive as a cross-rank SUM via the all_to_all
        transpose and must be rescaled by 1/ep so one SGD step equals the
        dense (no-mesh) reference — same convention as the GSPMD EP path."""
        dist.init_mesh({"pp": 2, "ep": 2})
        paddle.seed(0)
        model = GPTForPretraining(self._cfg())
        x, y = _data(8, seed=3)
        lr = 0.1

        ref_pipe = GPTPipelineModule(model, num_stages=2, microbatches=2)
        # heterogeneous (per-slot) dense reference: MoE pipelines stack
        # params as slot{i}.{name} [S, v, ...], not one scanned [S, k, ...]
        m = ref_pipe.microbatches
        mb = x.shape[0] // m
        x_mb = jnp.asarray(x).reshape((m, mb) + x.shape[1:])
        y_mb = jnp.asarray(y).reshape((m, mb) + y.shape[1:])
        S, kv, v = (ref_pipe.num_stages, ref_pipe.layers_per_chunk,
                    ref_pipe.num_virtual)

        def dense_loss(stages, shared):
            total = 0.0
            for j in range(m):
                h = ref_pipe._embed(shared, x_mb[j])
                for l in range(S * v * kv):
                    q, i = divmod(l, kv)
                    s, c = q % S, q // S
                    prefix = f"slot{i}."
                    lp = {n[len(prefix):]: a[s, c] for n, a in stages.items()
                          if n.startswith(prefix)}
                    h, _ = ref_pipe._apply_slot(
                        ref_pipe.slot_templates[i], lp, h)
                total = total + ref_pipe._head_loss(shared, h, y_mb[j])
            return total / m

        g_st, g_sh = jax.grad(dense_loss, argnums=(0, 1))(
            ref_pipe.stage_params, ref_pipe.shared_params)
        want_st = jax.tree_util.tree_map(
            lambda p, g: p - lr * g, ref_pipe.stage_params, g_st)
        want_sh = jax.tree_util.tree_map(
            lambda p, g: p - lr * g, ref_pipe.shared_params, g_sh)

        opt = SGD(learning_rate=lr, parameters=model.parameters())
        step = build_gpt_pipeline_step(model, opt, microbatches=2)
        step(x, y)
        for n in want_st:
            np.testing.assert_allclose(
                np.asarray(step.state["params"]["stages"][n]),
                np.asarray(want_st[n]), rtol=2e-4, atol=2e-5, err_msg=n)
        for n in want_sh:
            np.testing.assert_allclose(
                np.asarray(step.state["params"]["shared"][n]),
                np.asarray(want_sh[n]), rtol=2e-4, atol=2e-5, err_msg=n)

    def test_moe_pipeline_trains_pp2_ep2_dp2(self):
        """Full hybrid train step with MoE aux loss converges."""
        dist.init_mesh({"pp": 2, "ep": 2, "dp": 2})
        paddle.seed(0)
        model = GPTForPretraining(self._cfg(moe_aux_loss_weight=0.01))
        opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
        step = build_gpt_pipeline_step(model, opt, microbatches=2)
        x, y = _data(16)
        losses = [float(step(x, y)) for _ in range(10)]
        assert losses[-1] < losses[0] * 0.95, losses
        step.sync_to_model()  # expert shards write back without error


class TestPP1Specialization:
    """pp=1 runs the schedule-free fast path (VERDICT r3 do#7) — it must
    stay step-exact with the dense reference and with ZeRO-2 sharding."""

    @pytest.mark.parametrize("axes", [
        {"pp": 1}, {"pp": 1, "dp": 2}, {"pp": 1, "sharding": 2},
    ])
    def test_pp1_step_matches_dense(self, axes):
        dist.init_mesh(axes)
        paddle.seed(0)
        model = GPTForPretraining(tiny_cfg())
        x, y = _data(8, seed=21)
        lr = 0.1
        ref_pipe = GPTPipelineModule(model, num_stages=1, microbatches=2)
        want_st, want_sh = _dense_step_reference(ref_pipe, x, y, lr)
        opt = SGD(learning_rate=lr, parameters=model.parameters())
        step = build_gpt_pipeline_step(model, opt, microbatches=2)
        step(x, y)
        for n in want_st:
            np.testing.assert_allclose(
                np.asarray(step.state["params"]["stages"][n]),
                np.asarray(want_st[n]), rtol=2e-4, atol=2e-5, err_msg=n)
        for n in want_sh:
            np.testing.assert_allclose(
                np.asarray(step.state["params"]["shared"][n]),
                np.asarray(want_sh[n]), rtol=2e-4, atol=2e-5, err_msg=n)

    def test_pp1_dropout_matches_pp2_semantics(self):
        """Same seed → same loss trajectory shape (PRNG folding contract is
        per-(microbatch, layer) on both paths); smoke that dropout runs."""
        dist.init_mesh({"pp": 1})
        paddle.seed(0)
        model = GPTForPretraining(tiny_cfg(hidden_dropout_prob=0.1))
        model.train()
        opt = SGD(learning_rate=0.05, parameters=model.parameters())
        step = build_gpt_pipeline_step(model, opt, microbatches=2)
        x, y = _data(8, seed=23)
        losses = [float(step(x, y)) for _ in range(4)]
        assert losses[-1] < losses[0]


class TestZeRO3Pipeline:
    """Stage-3 sharding composed with the pipeline (VERDICT r3 missing #3 /
    north-star config 'sharding stage2/3 + pipeline'): stage params live
    sliced over 'sharding' and are all-gathered on use inside the per-layer
    remat region; grads come back reduce-scattered through the gather VJP.
    Reference: sharding_optimizer.py:140 hybrid + sharding/shard.py:22."""

    @pytest.mark.parametrize("axes", [
        {"pp": 2, "sharding": 2, "dp": 2},
        {"pp": 2, "sharding": 4},
        {"pp": 2, "mp": 2, "sharding": 2},
        # the COMPLETE north-star composition: all four axes on one mesh
        # (dp degenerate at 1 on 8 devices but present in every spec —
        # sharding_optimizer.py:140's mp x sharding x pp x dp shape)
        {"pp": 2, "mp": 2, "sharding": 2, "dp": 1},
    ])
    def test_stage3_step_matches_dense(self, axes):
        if "mp" in axes:
            from paddle_tpu.distributed.spmd import _VMA_KW

            if _VMA_KW == "check_rep":
                # jax < 0.5 (check_rep-era shard_map) double-counts the
                # mp-sharded ZeRO-3 leaves' grads through its older
                # collective transposes; passes on the target jax
                # (benchmarks/full_suite_r5.log) — see README "Running"
                pytest.skip("mp x sharding_stage=3 grad transpose semantics "
                            "differ on jax<0.5; known 0.4.x-only residue")
        dist.init_mesh(axes)
        paddle.seed(0)
        model = GPTForPretraining(tiny_cfg())
        x, y = _data(8, seed=11)
        lr = 0.1

        ref_pipe = GPTPipelineModule(model, num_stages=2, microbatches=2)
        want_st, want_sh = _dense_step_reference(ref_pipe, x, y, lr)

        opt = SGD(learning_rate=lr, parameters=model.parameters())
        step = build_gpt_pipeline_step(model, opt, microbatches=2,
                                       sharding_stage=3)
        assert step.pipe._stage3
        step(x, y)
        got_st = step.pipe.maybe_from_stage3(step.state["params"]["stages"])
        got_sh = step.state["params"]["shared"]
        for n in want_st:
            np.testing.assert_allclose(
                np.asarray(got_st[n]), np.asarray(want_st[n]),
                rtol=2e-4, atol=2e-5, err_msg=n)
        for n in want_sh:
            np.testing.assert_allclose(
                np.asarray(got_sh[n]), np.asarray(want_sh[n]),
                rtol=2e-4, atol=2e-5, err_msg=n)

    def test_stage3_global_norm_clip_matches_dense(self):
        """Global-norm clip under ZeRO-3: stage grads are distinct slices
        per sharding rank, so the norm must psum over 'sharding' too."""
        from paddle_tpu.nn.clip import ClipGradByGlobalNorm

        dist.init_mesh({"pp": 2, "sharding": 2})
        paddle.seed(0)
        model = GPTForPretraining(tiny_cfg())
        x, y = _data(8, seed=13)
        lr, clip_norm = 0.1, 0.05

        ref_pipe = GPTPipelineModule(model, num_stages=2, microbatches=2)
        m = ref_pipe.microbatches
        mb = x.shape[0] // m
        x_mb = jnp.asarray(x).reshape((m, mb) + x.shape[1:])
        y_mb = jnp.asarray(y).reshape((m, mb) + y.shape[1:])

        def dense_loss(stages, shared):
            total = 0.0
            for j in range(m):
                h = ref_pipe._embed(shared, x_mb[j])
                flat = jax.tree_util.tree_map(
                    lambda a: a.reshape((4,) + a.shape[2:]), stages)
                for l in range(4):
                    lp = jax.tree_util.tree_map(lambda a: a[l], flat)
                    h = ref_pipe._apply_block(lp, h)
                total = total + ref_pipe._head_loss(shared, h, y_mb[j])
            return total / m

        g_st, g_sh = jax.grad(dense_loss, argnums=(0, 1))(
            ref_pipe.stage_params, ref_pipe.shared_params)
        leaves = jax.tree_util.tree_leaves((g_st, g_sh))
        norm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
        scale = clip_norm / jnp.maximum(norm, clip_norm)
        want_st = jax.tree_util.tree_map(
            lambda p, g: p - lr * g * scale, ref_pipe.stage_params, g_st)

        opt = SGD(learning_rate=lr, parameters=model.parameters(),
                  grad_clip=ClipGradByGlobalNorm(clip_norm))
        step = build_gpt_pipeline_step(model, opt, microbatches=2,
                                       sharding_stage=3)
        step(x, y)
        got_st = step.pipe.maybe_from_stage3(step.state["params"]["stages"])
        for n in want_st:
            np.testing.assert_allclose(
                np.asarray(got_st[n]), np.asarray(want_st[n]),
                rtol=2e-4, atol=2e-5, err_msg=n)

    def test_stage3_memory_accounting_and_adamw(self):
        """Per-rank stage-param bytes shrink by the shard degree (the
        memory-accounting line VERDICT asks for), AdamW trains, and
        sync_to_model restores full-layout weights."""
        dist.init_mesh({"pp": 2, "sharding": 2, "dp": 2})
        paddle.seed(0)
        model = GPTForPretraining(tiny_cfg())
        ref2 = GPTPipelineModule(model, num_stages=2, microbatches=2,
                                 sharding_stage=2)
        rep2 = ref2.param_memory_report()
        opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
        step = build_gpt_pipeline_step(model, opt, microbatches=2,
                                       sharding_stage=3)
        rep3 = step.pipe.param_memory_report()
        assert rep3["stage3"] and not rep2["stage3"]
        # stage-2 replicates stage params over 'sharding'; stage-3 slices
        # them 1/n_shard (padding adds < 2%)
        assert rep3["stage_param_bytes_per_rank"] <= (
            rep2["stage_param_bytes_per_rank"] // 2 * 1.02)

        x, y = _data(16, seed=17)
        losses = [float(step(x, y)) for _ in range(8)]
        assert losses[-1] < losses[0] * 0.97, losses
        step.sync_to_model()
        # model weights restored at full shape
        w = model.gpt.h[0].attn.qkv_proj.weight
        assert tuple(w.shape) == (32, 3 * 32)


def _dense_step_reference(pipe, x, y, lr):
    """One SGD step on the stacked params, computed densely (no mesh axes):
    mean loss over microbatches, plain jax.grad."""
    m = pipe.microbatches
    mb = x.shape[0] // m
    x_mb = jnp.asarray(x).reshape((m, mb) + x.shape[1:])
    y_mb = jnp.asarray(y).reshape((m, mb) + y.shape[1:])
    n_layers = pipe.num_stages * pipe.layers_per_stage

    def dense_loss(stages, shared):
        total = 0.0
        for j in range(m):
            h = pipe._embed(shared, x_mb[j])
            if pipe._unstacked_pp1:
                for l in range(n_layers):
                    prefix = f"L{l}."
                    lp = {n[len(prefix):]: a for n, a in stages.items()
                          if n.startswith(prefix)}
                    h = pipe._apply_block(lp, h)
            else:
                flat = jax.tree_util.tree_map(
                    lambda a: a.reshape((n_layers,) + a.shape[2:]), stages)
                for l in range(n_layers):
                    lp = jax.tree_util.tree_map(lambda a: a[l], flat)
                    h = pipe._apply_block(lp, h)
            total = total + pipe._head_loss(shared, h, y_mb[j])
        return total / m

    g_st, g_sh = jax.grad(dense_loss, argnums=(0, 1))(
        pipe.stage_params, pipe.shared_params)
    want_st = jax.tree_util.tree_map(
        lambda p, g: p - lr * g, pipe.stage_params, g_st)
    want_sh = jax.tree_util.tree_map(
        lambda p, g: p - lr * g, pipe.shared_params, g_sh)
    return want_st, want_sh


class TestHybridPipeline:
    """The north-star hybrid: pp x mp x (dp | sharding) composed in one
    jitted step (reference: sharding_optimizer.py:140 hybrid degrees,
    p2p-under-mp p2p_communication.py:149)."""

    @pytest.mark.parametrize("axes", [
        {"pp": 2, "mp": 2, "dp": 2},
        {"pp": 2, "mp": 2, "sharding": 2},
        {"pp": 2, "mp": 4},
        {"pp": 2, "sharding": 2, "dp": 2},
    ])
    def test_hybrid_step_matches_dense(self, axes):
        dist.init_mesh(axes)
        paddle.seed(0)
        model = GPTForPretraining(tiny_cfg())
        x, y = _data(8, seed=5)
        lr = 0.1

        ref_pipe = GPTPipelineModule(model, num_stages=2, microbatches=2)
        want_st, want_sh = _dense_step_reference(ref_pipe, x, y, lr)

        opt = SGD(learning_rate=lr, parameters=model.parameters())
        step = build_gpt_pipeline_step(model, opt, microbatches=2)
        step(x, y)
        got_st = step.state["params"]["stages"]
        got_sh = step.state["params"]["shared"]
        for n in want_st:
            np.testing.assert_allclose(
                np.asarray(got_st[n]), np.asarray(want_st[n]),
                rtol=2e-4, atol=2e-5, err_msg=n)
        for n in want_sh:
            np.testing.assert_allclose(
                np.asarray(got_sh[n]), np.asarray(want_sh[n]),
                rtol=2e-4, atol=2e-5, err_msg=n)

    def test_hybrid_global_norm_clip_matches_dense(self):
        """ClipGradByGlobalNorm inside the hybrid shard_map must reduce the
        norm over 'pp'/'mp' before scaling (shard-local norms would diverge
        the replicated params)."""
        from paddle_tpu.nn.clip import ClipGradByGlobalNorm

        dist.init_mesh({"pp": 2, "mp": 2, "sharding": 2})
        paddle.seed(0)
        model = GPTForPretraining(tiny_cfg())
        x, y = _data(8, seed=9)
        lr, clip_norm = 0.1, 0.05  # tiny clip so scaling definitely kicks in

        pipe_ref = GPTPipelineModule(model, num_stages=2, microbatches=2)
        m = pipe_ref.microbatches
        mb = x.shape[0] // m
        x_mb = jnp.asarray(x).reshape((m, mb) + x.shape[1:])
        y_mb = jnp.asarray(y).reshape((m, mb) + y.shape[1:])

        def dense_loss(stages, shared):
            total = 0.0
            for j in range(m):
                h = pipe_ref._embed(shared, x_mb[j])
                flat = jax.tree_util.tree_map(
                    lambda a: a.reshape((4,) + a.shape[2:]), stages)
                for l in range(4):
                    lp = jax.tree_util.tree_map(lambda a: a[l], flat)
                    h = pipe_ref._apply_block(lp, h)
                total = total + pipe_ref._head_loss(shared, h, y_mb[j])
            return total / m

        g_st, g_sh = jax.grad(dense_loss, argnums=(0, 1))(
            pipe_ref.stage_params, pipe_ref.shared_params)
        leaves = jax.tree_util.tree_leaves((g_st, g_sh))
        norm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
        scale = clip_norm / jnp.maximum(norm, clip_norm)
        want_st = jax.tree_util.tree_map(
            lambda p, g: p - lr * g * scale, pipe_ref.stage_params, g_st)
        want_sh = jax.tree_util.tree_map(
            lambda p, g: p - lr * g * scale, pipe_ref.shared_params, g_sh)

        opt = SGD(learning_rate=lr, parameters=model.parameters(),
                  grad_clip=ClipGradByGlobalNorm(clip_norm))
        step = build_gpt_pipeline_step(model, opt, microbatches=2)
        step(x, y)
        for n in want_st:
            np.testing.assert_allclose(
                np.asarray(step.state["params"]["stages"][n]),
                np.asarray(want_st[n]), rtol=2e-4, atol=2e-5, err_msg=n)
        for n in want_sh:
            np.testing.assert_allclose(
                np.asarray(step.state["params"]["shared"][n]),
                np.asarray(want_sh[n]), rtol=2e-4, atol=2e-5, err_msg=n)

    def test_hybrid_adamw_converges(self):
        """pp2 x mp2 x sharding2 trains end-to-end with sharded Adam slots."""
        dist.init_mesh({"pp": 2, "mp": 2, "sharding": 2})
        paddle.seed(0)
        model = GPTForPretraining(tiny_cfg())
        opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
        step = build_gpt_pipeline_step(model, opt, microbatches=2)
        x, y = _data(8)
        losses = [float(step(x, y)) for _ in range(10)]
        assert losses[-1] < losses[0] * 0.9, losses
        # ZeRO layout: Adam moments are stored sliced 1/n over 'sharding'
        slots = step.state["opt"]["slots"]["stages"]
        leaf = next(iter(slots.values()))["moment1"]
        assert leaf.shape[2] == 2  # n_shard slices


class TestPipelineDropout:
    """Per-(microbatch, layer) PRNG keys through the pipeline scan: same
    seeds => same masks => same loss as a sequential run (replaces the
    reference RNG tracker, parallel_layers/random.py)."""

    def _dense_loss_with_keys(self, pipe, x, y, key):
        from paddle_tpu.random import get_rng_state, set_rng_state

        m = pipe.microbatches
        mb = x.shape[0] // m
        x_mb = jnp.asarray(x).reshape((m, mb) + x.shape[1:])
        y_mb = jnp.asarray(y).reshape((m, mb) + y.shape[1:])
        n_layers = pipe.num_stages * pipe.layers_per_stage
        flat = jax.tree_util.tree_map(
            lambda a: a.reshape((n_layers,) + a.shape[2:]), pipe.stage_params)
        total = 0.0
        for j in range(m):
            mb_key = jax.random.fold_in(key, j)
            h = pipe._embed(pipe.shared_params, x_mb[j],
                            jax.random.fold_in(mb_key, 1 << 20))
            for l in range(n_layers):
                lp = jax.tree_util.tree_map(lambda a: a[l], flat)
                saved = get_rng_state()
                set_rng_state(jax.random.fold_in(mb_key, l))
                try:
                    h = pipe._apply_block(lp, h)
                finally:
                    set_rng_state(saved)
            total = total + pipe._head_loss(pipe.shared_params, h, y_mb[j])
        return float(total / m)

    def test_pipeline_dropout_matches_sequential(self):
        dist.init_mesh({"pp": 4})
        paddle.seed(0)
        model = GPTForPretraining(tiny_cfg(hidden_dropout_prob=0.3,
                                           attention_dropout_prob=0.2))
        model.train()
        x, y = _data(4, seed=7)
        pipe = GPTPipelineModule(model, num_stages=4, microbatches=2)
        key = jax.random.key(42)
        ref = self._dense_loss_with_keys(pipe, x, y, key)

        from paddle_tpu.distributed.spmd import shard_map
        mesh = dist.get_mesh()

        def fn(st, sh, x, y, kd):
            return pipe.local_loss(st, sh, x, y, jax.random.wrap_key_data(kd))

        f = jax.jit(shard_map(
            fn, mesh=mesh,
            in_specs=(pipe.stage_specs, pipe.shared_specs, P(), P(), P()),
            out_specs=P(),
            check_vma=False,
        ))
        got = float(f(pipe.stage_params, pipe.shared_params, x, y,
                      jax.random.key_data(key)))
        assert abs(got - ref) < 2e-4, (got, ref)

    def test_dropout_training_converges(self):
        dist.init_mesh({"pp": 4, "dp": 2})
        paddle.seed(0)
        model = GPTForPretraining(tiny_cfg(hidden_dropout_prob=0.1))
        model.train()
        opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
        step = build_gpt_pipeline_step(model, opt, microbatches=2)
        x, y = _data(8)
        losses = [float(step(x, y)) for _ in range(10)]
        assert losses[-1] < losses[0] * 0.9, losses


class TestPipelineCheckpoint:
    def test_hybrid_state_checkpoint_resume(self, tmp_path):
        """CheckpointManager round-trips the hybrid step's sharded state
        (stacked stage params on 'pp'/'mp', ZeRO slot slices on 'sharding')
        and training resumes bit-exactly (reference auto-checkpoint +
        sharded save: SURVEY 5.4)."""
        from paddle_tpu.framework.checkpoint import CheckpointManager

        dist.init_mesh({"pp": 2, "mp": 2, "sharding": 2})
        paddle.seed(0)
        model = GPTForPretraining(tiny_cfg())
        opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
        step = build_gpt_pipeline_step(model, opt, microbatches=2)
        x, y = _data(8)
        for _ in range(3):
            step(x, y)

        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        mgr.save(3, step.state)
        # keep training the original for a reference trajectory
        ref_losses = [float(step(x, y)) for _ in range(3)]

        # fresh process-equivalent: new model/opt/step, restore, resume
        paddle.seed(0)
        model2 = GPTForPretraining(tiny_cfg())
        opt2 = AdamW(learning_rate=1e-3, parameters=model2.parameters())
        step2 = build_gpt_pipeline_step(model2, opt2, microbatches=2)
        restored, _meta = mgr.load(step=3)
        step2.state["params"] = restored["params"]
        step2.state["opt"] = restored["opt"]
        paddle.seed(1234)  # dropout disabled: keys don't matter, but align
        got_losses = [float(step2(x, y)) for _ in range(3)]
        np.testing.assert_allclose(got_losses, ref_losses, rtol=1e-6)
        dist.clear_mesh()


class TestInterleavedVirtualStages:
    """num_virtual_pipeline_stages (VERDICT r2 missing #3): interleaved
    chunk assignment, parity at v=2, and the smaller schedule bubble."""

    def test_v2_matches_v1_one_sgd_step(self):
        dist.init_mesh({"pp": 2})
        cfg = tiny_cfg()  # 4 layers: pp2 x v2 -> kv=1
        x, y = _data(4, seed=5)
        lr = 0.1

        results = {}
        for v in (1, 2):
            paddle.seed(0)
            model = GPTForPretraining(cfg)
            opt = SGD(learning_rate=lr, parameters=model.parameters())
            step = build_gpt_pipeline_step(
                model, opt, microbatches=2, num_virtual_stages=v)
            loss = float(step(x, y))
            step.sync_to_model()
            results[v] = (loss, {n: np.asarray(p._data)
                                 for n, p in model.named_parameters()})
        l1, p1 = results[1]
        l2, p2 = results[2]
        assert abs(l1 - l2) < 1e-5, (l1, l2)
        for n in p1:
            np.testing.assert_allclose(p2[n], p1[n], rtol=2e-4, atol=2e-5,
                                       err_msg=n)

    def test_v2_shrinks_bubble(self):
        dist.init_mesh({"pp": 2})
        paddle.seed(0)
        model = GPTForPretraining(tiny_cfg())
        pipe_v1 = GPTPipelineModule(model, 2, 4, num_virtual_stages=1)
        pipe_v2 = GPTPipelineModule(model, 2, 4, num_virtual_stages=2)
        assert pipe_v1.schedule_ticks() == 4 + 2 - 1
        assert pipe_v2.schedule_ticks() == 2 * 4 + 2 - 1
        assert pipe_v2.bubble_fraction() < pipe_v1.bubble_fraction()


class TestPipelineLayerStep:
    """Generic PipelineLayer pipelining (VERDICT r2 missing #1): a
    LayerDesc-built MLP rotates activations over 'pp' with non-uniform
    edge layers running pp-replicated."""

    def _build(self, with_edges=True):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.meta_parallel.pp_layers import (
            LayerDesc, PipelineLayer)

        def mse(out, y):
            d = out - y
            return (d * d).mean()

        descs = []
        if with_edges:
            descs.append(LayerDesc(nn.Linear, 8, 16))
        descs += [LayerDesc(nn.Linear, 16, 16) for _ in range(8)]
        if with_edges:
            descs.append(LayerDesc(nn.Linear, 16, 4))
        return PipelineLayer(descs, num_stages=4, loss_fn=mse)

    def test_pipeline_layer_matches_dense_pp4(self):
        from paddle_tpu.distributed.meta_parallel.pipeline_schedule import (
            build_pipeline_layer_step)

        dist.init_mesh({"pp": 4})
        paddle.seed(0)
        pl = self._build()
        rng = np.random.default_rng(7)
        x = rng.standard_normal((4, 8)).astype("float32")
        y = rng.standard_normal((4, 4)).astype("float32")

        # dense reference: full forward + MSE on the same weights
        out = pl(paddle.to_tensor(x))
        d = np.asarray(out._data) - y
        ref = float((d * d).mean())
        # snapshot BEFORE the step: the jitted program donates the originals
        params0 = {n: np.asarray(p._data) for n, p in pl.named_parameters()}

        lr = 0.05
        opt = SGD(learning_rate=lr, parameters=pl.parameters())
        step = build_pipeline_layer_step(pl, opt, microbatches=2)
        loss = float(step(x, y))
        assert abs(loss - ref) < 1e-5, (loss, ref)

        def dense_loss(tree):
            h = jnp.asarray(x)
            for j, lyr in enumerate(pl.run_function):
                w = tree[f"run_function.{j}.weight"]
                b = tree[f"run_function.{j}.bias"]
                h = h @ w + b
            dd = h - jnp.asarray(y)
            return (dd * dd).mean()

        g = jax.grad(dense_loss)({n: jnp.asarray(a) for n, a in params0.items()})
        step.sync_to_model()
        for n, p in pl.named_parameters():
            want = params0[n] - lr * np.asarray(g[n])
            np.testing.assert_allclose(np.asarray(p._data), want,
                                       rtol=2e-4, atol=2e-5, err_msg=n)

    def test_train_batch_routes_to_real_pipeline(self):
        """PipelineParallel.train_batch on a pp>1 mesh uses the ppermute
        step (not the GSPMD fallback) for a pipelineable stack."""
        from paddle_tpu.distributed.meta_parallel.pipeline_parallel import (
            PipelineParallel)
        from paddle_tpu.distributed.topology import HybridCommunicateGroup

        paddle.seed(0)
        # the hcg installs the global {"pp": 4, "dp": 2} mesh itself
        hcg = HybridCommunicateGroup(pp_degree=4, dp_degree=2)
        pl = self._build(with_edges=False)
        pp = PipelineParallel(pl, hcg)
        opt = SGD(learning_rate=0.05, parameters=pl.parameters())
        rng = np.random.default_rng(8)
        x = rng.standard_normal((8, 16)).astype("float32")
        y = rng.standard_normal((8, 16)).astype("float32")
        l0 = float(pp.train_batch((x, y), opt))
        assert hasattr(pp._train_step_fn, "_pipeline_step"), (
            "train_batch fell back to the GSPMD step")
        for _ in range(5):
            l = float(pp.train_batch((x, y), opt))
        assert l < l0, (l0, l)

    def test_non_uniform_stack_falls_back_loudly(self):
        import warnings

        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.meta_parallel.pp_layers import (
            LayerDesc, PipelineLayer)
        from paddle_tpu.distributed.parallel_trainer import build_pipeline_step

        dist.init_mesh({"pp": 4, "dp": 2})
        paddle.seed(0)
        # every layer a different width: nothing to pipeline
        widths = [8, 12, 16, 20, 24]
        descs = [LayerDesc(nn.Linear, widths[i], widths[i + 1])
                 for i in range(4)]
        pl = PipelineLayer(descs, num_stages=4,
                           loss_fn=lambda o, y: (o * o).mean())
        opt = SGD(learning_rate=0.01, parameters=pl.parameters())
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            run = build_pipeline_step(pl, None, opt)
        assert any("NON-pipelined" in str(x.message) for x in w), (
            [str(x.message) for x in w])


class TestDecayParamFun:
    """AdamW apply_decay_param_fun under the hybrid (VERDICT r2 missing #7):
    no-decay leaves (LN/bias convention) must update exactly like wd=0."""

    def test_hybrid_adamw_decay_mask(self):
        """Same machinery A/B: one hybrid AdamW step with
        apply_decay_param_fun excluding 1-D params (LN/bias convention) vs
        one with wd=0. No-decay leaves must be bit-identical; decayed leaves
        must differ by exactly lr*wd*p0 (decoupled decay, step 1)."""
        cfg = tiny_cfg()
        x, y = _data(4, seed=9)
        lr, wd = 0.01, 0.5

        ndim_of = {}

        def one_step(weight_decay, masked):
            dist.clear_mesh()
            dist.init_mesh({"pp": 2})
            paddle.seed(0)
            model = GPTForPretraining(cfg)
            ndim_of.update({n: p._data.ndim
                            for n, p in model.named_parameters()})
            fn = None
            if masked:
                # no-decay set from THIS model's params (names are unique
                # per instance): every 1-D param = LN scales + biases
                no_decay = {p.name for p in model.parameters()
                            if p._data.ndim <= 1}
                fn = lambda pname: pname not in no_decay
            p0 = {n: np.asarray(p._data)
                  for n, p in model.named_parameters()}
            opt = AdamW(learning_rate=lr, weight_decay=weight_decay,
                        parameters=model.parameters(),
                        apply_decay_param_fun=fn)
            step = build_gpt_pipeline_step(model, opt, microbatches=2)
            step(x, y)
            step.sync_to_model()
            p1 = {n: np.asarray(p._data)
                  for n, p in model.named_parameters()}
            return p0, p1

        p0, with_mask = one_step(wd, True)
        _, without_wd = one_step(0.0, False)

        saw_decayed = saw_skipped = False
        for n in with_mask:
            if ndim_of[n] <= 1:
                # masked leaves: decay must not have been applied at all
                np.testing.assert_array_equal(
                    with_mask[n], without_wd[n], err_msg=n)
                saw_skipped = True
            else:
                delta = with_mask[n] - (without_wd[n] - lr * wd * p0[n])
                np.testing.assert_allclose(delta, 0.0, atol=1e-6, err_msg=n)
                saw_decayed = True
        assert saw_decayed and saw_skipped


def test_pipeline_compute_dtype_bf16_converges():
    """compute_dtype='bfloat16' (AMP O2 master-weight pattern in the hybrid
    step): f32 masters, bf16 forward — still trains."""
    dist.init_mesh({"pp": 2, "dp": 2})
    paddle.seed(0)
    model = GPTForPretraining(tiny_cfg())
    opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
    step = build_gpt_pipeline_step(model, opt, microbatches=2,
                                   compute_dtype="bfloat16")
    x, y = _data(8)
    losses = [float(step(x, y)) for _ in range(10)]
    assert losses[-1] < losses[0] * 0.9, losses
    # masters stayed f32
    import jax

    leaf = next(iter(step.state["params"]["stages"].values()))
    assert leaf.dtype == jax.numpy.float32


def test_pipeline_layer_with_mp_pp2_mp2_dp2():
    """Generic PipelineLayer body with tensor-parallel blocks: the stacked
    stage params keep their 'mp' placements and the blocks run the explicit
    Megatron collectives inside the same shard_map as 'pp'/'dp'."""
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.meta_parallel.mp_layers import (
        ColumnParallelLinear, RowParallelLinear)
    from paddle_tpu.distributed.meta_parallel.pipeline_schedule import (
        build_pipeline_layer_step)
    from paddle_tpu.distributed.meta_parallel.pp_layers import PipelineLayer
    from paddle_tpu.nn.layer import Layer

    class MpBlock(Layer):
        def __init__(self, h):
            super().__init__()
            self.fc_in = ColumnParallelLinear(h, 2 * h, gather_output=False)
            self.fc_out = RowParallelLinear(2 * h, h, input_is_parallel=True)

        def forward(self, x):
            import paddle_tpu.nn.functional as F

            return x + self.fc_out(F.gelu(self.fc_in(x)))

    dist.init_mesh({"pp": 2, "mp": 2, "dp": 2})
    paddle.seed(0)
    h = 16
    blocks = [MpBlock(h) for _ in range(4)]

    def mse(out, y):
        d = out - y
        return (d * d).mean()

    pl = PipelineLayer(blocks, num_stages=2, loss_fn=mse)
    r = np.random.default_rng(17)
    x = r.standard_normal((8, h)).astype("float32")
    y = r.standard_normal((8, h)).astype("float32")

    # dense reference on the same weights (replicated eager path)
    out = pl(paddle.to_tensor(x))
    d = np.asarray(out._data) - y
    ref = float((d * d).mean())

    from paddle_tpu.optimizer.optimizers import SGD

    opt = SGD(learning_rate=0.05, parameters=pl.parameters())
    step = build_pipeline_layer_step(pl, opt, microbatches=2)
    # column/row placements survived into the stacked stage specs
    specs = step.pipe.stage_specs
    assert any("mp" in str(s) for s in specs.values()), specs
    loss = float(step(x, y))
    assert abs(loss - ref) < 1e-5, (loss, ref)
    losses = [float(step(x, y)) for _ in range(8)]
    assert losses[-1] < loss, (loss, losses[-1])


class TestHeadLossDtypeParity:
    """ADVICE r5 #1 regression: under bf16 compute the non-mp CE head now
    runs float32 softmax statistics matching the mp branch, so the pipeline
    loss no longer depends on the mp degree (r5's native-dtype log_softmax
    carried ~1e-2 relative bf16 logsumexp error on the mp=1 side only)."""

    def _bf16_loss(self, axes):
        import jax.numpy as jnp

        from paddle_tpu.distributed.spmd import shard_map

        dist.clear_mesh()
        dist.init_mesh(axes)
        paddle.seed(0)
        model = GPTForPretraining(tiny_cfg())
        model.eval()
        x, y = _data(4, seed=11)
        pipe = GPTPipelineModule(model, num_stages=2, microbatches=2)
        mesh = dist.get_mesh()

        def cast(tree):
            return {k: (v.astype(jnp.bfloat16)
                        if jnp.issubdtype(v.dtype, jnp.floating) else v)
                    for k, v in tree.items()}

        stages, shared = cast(pipe.stage_params), cast(pipe.shared_params)
        f = jax.jit(shard_map(
            lambda st, sh, x, y: pipe.local_loss(st, sh, x, y),
            mesh=mesh,
            in_specs=(pipe.stage_specs, pipe.shared_specs, P(), P()),
            out_specs=P(),
            check_vma=False,
        ))
        return float(f(stages, shared, x, y))

    def test_mp1_vs_mp2_bf16_losses_agree(self):
        l_mp1 = self._bf16_loss({"pp": 2})
        l_mp2 = self._bf16_loss({"pp": 2, "mp": 2})
        # f32-statistics tolerance (measured ~2e-5 here): the r5
        # native-dtype head measured ~3e-4 on this tiny config and ~1e-2
        # at a 50k vocab, so 1e-4 discriminates old from new
        assert abs(l_mp1 - l_mp2) / abs(l_mp1) < 1e-4, (l_mp1, l_mp2)
