"""Top-level API parity vs the reference's paddle/__init__.py __all__ plus
the small compat modules (distribution, regularizer, hub, reader, dataset,
compat, metric.accuracy)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle


rng = np.random.default_rng(3)


def _np(t):
    return np.asarray(t._data)


REFERENCE_TOP_LEVEL = """
    abs acos add add_n addmm all allclose any arange argmax argmin argsort
    asin atan batch bernoulli bincount bmm broadcast_shape broadcast_tensors
    broadcast_to cast ceil check_shape cholesky chunk clip clone concat conj
    cos cosh crop cross cumprod cumsum diag diagonal digamma disable_signal_handler
    dist divide dot empty empty_like equal equal_all erf exp expand expand_as
    eye flatten flip floor floor_divide full full_like gather gather_nd
    greater_equal greater_than histogram imag increment index_sample
    index_select inverse is_tensor isfinite isinf isnan kron less_equal
    less_than lgamma linspace log log10 log1p log2 logical_and logical_not
    logical_or logical_xor logsumexp masked_select matmul max maximum mean
    median meshgrid min minimum mm mod multinomial multiply mv neg nonzero
    norm normal not_equal numel ones ones_like pow prod rand randint randn
    randperm rank real reciprocal remainder reshape reverse roll round rsqrt
    scale scatter scatter_nd scatter_nd_add seed shape shard_index sign sin
    sinh slice sort split sqrt square squeeze stack stanh std strided_slice
    subtract sum t tanh tensordot tile to_tensor tolist topk trace transpose
    tril triu unbind uniform unique unsqueeze unstack var where zeros
    zeros_like
"""


class TestTopLevelNames:
    @pytest.mark.parametrize("name", REFERENCE_TOP_LEVEL.split())
    def test_name_exists(self, name):
        assert getattr(paddle, name, None) is not None, name

    def test_lazy_modules(self):
        for mod in ("fft", "signal", "distribution", "regularizer", "hub",
                    "dataset", "reader", "compat", "quantization"):
            assert getattr(paddle, mod) is not None


class TestDistribution:
    def test_normal(self):
        from paddle_tpu.distribution import Normal, kl_divergence

        paddle.seed(0)
        d = Normal(0.0, 2.0)
        s = d.sample((5000,))
        assert abs(float(np.mean(_np(s)))) < 0.15
        assert abs(float(np.std(_np(s))) - 2.0) < 0.15
        lp = d.log_prob(paddle.to_tensor(np.array([0.0], "float32")))
        want = -np.log(2.0) - 0.5 * np.log(2 * np.pi)
        np.testing.assert_allclose(_np(lp)[0], want, rtol=1e-5)
        ent = d.entropy()
        np.testing.assert_allclose(float(_np(ent)), 0.5 + 0.5 * np.log(2 * np.pi) + np.log(2.0), rtol=1e-6)
        kl = kl_divergence(Normal(0.0, 1.0), Normal(0.0, 1.0))
        np.testing.assert_allclose(float(_np(kl)), 0.0, atol=1e-6)
        kl2 = kl_divergence(Normal(1.0, 1.0), Normal(0.0, 1.0))
        np.testing.assert_allclose(float(_np(kl2)), 0.5, rtol=1e-5)

    def test_uniform(self):
        from paddle_tpu.distribution import Uniform

        paddle.seed(1)
        d = Uniform(1.0, 3.0)
        s = _np(d.sample((2000,)))
        assert s.min() >= 1.0 and s.max() < 3.0
        np.testing.assert_allclose(float(_np(d.entropy())), np.log(2.0), rtol=1e-6)
        lp = d.log_prob(paddle.to_tensor(np.array([2.0, 5.0], "float32")))
        np.testing.assert_allclose(_np(lp)[0], -np.log(2.0), rtol=1e-6)
        assert _np(lp)[1] == -np.inf

    def test_categorical(self):
        from paddle_tpu.distribution import Categorical

        paddle.seed(2)
        logits = np.log(np.array([0.2, 0.3, 0.5], "float32"))
        d = Categorical(logits)
        s = _np(d.sample((4000,)))
        freq = np.bincount(s, minlength=3) / 4000
        np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.04)
        lp = d.log_prob(paddle.to_tensor(np.array([2], "int64")))
        np.testing.assert_allclose(_np(lp)[0], np.log(0.5), rtol=1e-5)
        ent = float(_np(d.entropy()))
        want = -(0.2 * np.log(0.2) + 0.3 * np.log(0.3) + 0.5 * np.log(0.5))
        np.testing.assert_allclose(ent, want, rtol=1e-5)


class TestRegularizer:
    def test_l2_decay_in_optimizer(self):
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt
        from paddle_tpu.regularizer import L2Decay

        paddle.seed(0)
        lin = nn.Linear(2, 2, bias_attr=False)
        w0 = _np(lin.weight).copy()
        sgd = opt.SGD(learning_rate=0.1, parameters=lin.parameters(),
                      weight_decay=L2Decay(0.5))
        out = lin(paddle.to_tensor(np.zeros((1, 2), "float32")))
        out.sum().backward()
        sgd.step()
        # grad is zero, so update = -lr * coeff * w
        np.testing.assert_allclose(_np(lin.weight), w0 * (1 - 0.1 * 0.5),
                                   rtol=1e-5)


class TestHub:
    def test_local_hub(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def toy_model(scale=2):\n"
            "    'build a toy'\n"
            "    return {'scale': scale}\n")
        assert paddle.hub.list(str(tmp_path)) == ["toy_model"]
        assert "toy" in paddle.hub.help(str(tmp_path), "toy_model")
        assert paddle.hub.load(str(tmp_path), "toy_model", scale=7) == {"scale": 7}

    def test_remote_rejected(self):
        with pytest.raises(ValueError):
            paddle.hub.list("some/repo", source="github")


class TestReaderDecorators:
    def test_pipeline(self):
        r = paddle.reader.chain(lambda: iter([1, 2]), lambda: iter([3]))
        assert list(r()) == [1, 2, 3]
        r2 = paddle.reader.firstn(lambda: iter(range(100)), 5)
        assert list(r2()) == [0, 1, 2, 3, 4]
        r3 = paddle.reader.map_readers(lambda a, b: a + b,
                                       lambda: iter([1, 2]), lambda: iter([10, 20]))
        assert list(r3()) == [11, 22]
        r4 = paddle.reader.buffered(lambda: iter(range(10)), 3)
        assert list(r4()) == list(range(10))
        r5 = paddle.reader.cache(lambda: iter([5, 6]))
        assert list(r5()) == [5, 6] and list(r5()) == [5, 6]
        r6 = paddle.reader.xmap_readers(lambda x: x * 2,
                                        lambda: iter(range(8)), 3, 4, order=True)
        assert list(r6()) == [0, 2, 4, 6, 8, 10, 12, 14]
        shuffled = sorted(paddle.reader.shuffle(lambda: iter(range(10)), 4)())
        assert shuffled == list(range(10))

    def test_batch(self):
        b = paddle.batch(lambda: iter(range(7)), 3)
        assert [len(x) for x in b()] == [3, 3, 1]
        b2 = paddle.batch(lambda: iter(range(7)), 3, drop_last=True)
        assert [len(x) for x in b2()] == [3, 3]


class TestCompat:
    def test_text_bytes(self):
        assert paddle.compat.to_text(b"abc") == "abc"
        assert paddle.compat.to_bytes("abc") == b"abc"
        assert paddle.compat.to_text([b"a", {b"k": b"v"}]) == ["a", {"k": "v"}]
        assert paddle.compat.round(2.5) == 3.0
        assert paddle.compat.round(-2.5) == -3.0


class TestDatasetNamespace:
    def test_legacy_module_shape(self):
        m = paddle.dataset.mnist
        assert callable(m.train) and callable(m.test)


class TestAsyncCollectives:
    def test_all_gather_object_single(self):
        import paddle_tpu.distributed as dist

        out = []
        dist.all_gather_object(out, {"rank": 0, "data": [1, 2]})
        assert out == [{"rank": 0, "data": [1, 2]}]

    def test_isend_irecv_handles(self):
        import paddle_tpu.distributed as dist

        t = paddle.to_tensor(np.ones(2, "float32"))
        task = dist.isend(t, dst=0)
        assert task.is_completed()
        task.wait()
