"""Top-level API parity vs the reference's paddle/__init__.py __all__ plus
the small compat modules (distribution, regularizer, hub, reader, dataset,
compat, metric.accuracy)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle


rng = np.random.default_rng(3)


def _np(t):
    return np.asarray(t._data)


REFERENCE_TOP_LEVEL = """
    abs acos add add_n addmm all allclose any arange argmax argmin argsort
    asin atan batch bernoulli bincount bmm broadcast_shape broadcast_tensors
    broadcast_to cast ceil check_shape cholesky chunk clip clone concat conj
    cos cosh crop cross cumprod cumsum diag diagonal digamma disable_signal_handler
    dist divide dot empty empty_like equal equal_all erf exp expand expand_as
    eye flatten flip floor floor_divide full full_like gather gather_nd
    greater_equal greater_than histogram imag increment index_sample
    index_select inverse is_tensor isfinite isinf isnan kron less_equal
    less_than lgamma linspace log log10 log1p log2 logical_and logical_not
    logical_or logical_xor logsumexp masked_select matmul max maximum mean
    median meshgrid min minimum mm mod multinomial multiply mv neg nonzero
    norm normal not_equal numel ones ones_like pow prod rand randint randn
    randperm rank real reciprocal remainder reshape reverse roll round rsqrt
    scale scatter scatter_nd scatter_nd_add seed shape shard_index sign sin
    sinh slice sort split sqrt square squeeze stack stanh std strided_slice
    subtract sum t tanh tensordot tile to_tensor tolist topk trace transpose
    tril triu unbind uniform unique unsqueeze unstack var where zeros
    zeros_like
"""


class TestTopLevelNames:
    @pytest.mark.parametrize("name", REFERENCE_TOP_LEVEL.split())
    def test_name_exists(self, name):
        assert getattr(paddle, name, None) is not None, name

    def test_lazy_modules(self):
        for mod in ("fft", "signal", "distribution", "regularizer", "hub",
                    "dataset", "reader", "compat", "quantization"):
            assert getattr(paddle, mod) is not None


class TestDistribution:
    def test_normal(self):
        from paddle_tpu.distribution import Normal, kl_divergence

        paddle.seed(0)
        d = Normal(0.0, 2.0)
        s = d.sample((5000,))
        assert abs(float(np.mean(_np(s)))) < 0.15
        assert abs(float(np.std(_np(s))) - 2.0) < 0.15
        lp = d.log_prob(paddle.to_tensor(np.array([0.0], "float32")))
        want = -np.log(2.0) - 0.5 * np.log(2 * np.pi)
        np.testing.assert_allclose(_np(lp)[0], want, rtol=1e-5)
        ent = d.entropy()
        np.testing.assert_allclose(float(_np(ent)), 0.5 + 0.5 * np.log(2 * np.pi) + np.log(2.0), rtol=1e-6)
        kl = kl_divergence(Normal(0.0, 1.0), Normal(0.0, 1.0))
        np.testing.assert_allclose(float(_np(kl)), 0.0, atol=1e-6)
        kl2 = kl_divergence(Normal(1.0, 1.0), Normal(0.0, 1.0))
        np.testing.assert_allclose(float(_np(kl2)), 0.5, rtol=1e-5)

    def test_uniform(self):
        from paddle_tpu.distribution import Uniform

        paddle.seed(1)
        d = Uniform(1.0, 3.0)
        s = _np(d.sample((2000,)))
        assert s.min() >= 1.0 and s.max() < 3.0
        np.testing.assert_allclose(float(_np(d.entropy())), np.log(2.0), rtol=1e-6)
        lp = d.log_prob(paddle.to_tensor(np.array([2.0, 5.0], "float32")))
        np.testing.assert_allclose(_np(lp)[0], -np.log(2.0), rtol=1e-6)
        assert _np(lp)[1] == -np.inf

    def test_categorical(self):
        from paddle_tpu.distribution import Categorical

        paddle.seed(2)
        logits = np.log(np.array([0.2, 0.3, 0.5], "float32"))
        d = Categorical(logits)
        s = _np(d.sample((4000,)))
        freq = np.bincount(s, minlength=3) / 4000
        np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.04)
        lp = d.log_prob(paddle.to_tensor(np.array([2], "int64")))
        np.testing.assert_allclose(_np(lp)[0], np.log(0.5), rtol=1e-5)
        ent = float(_np(d.entropy()))
        want = -(0.2 * np.log(0.2) + 0.3 * np.log(0.3) + 0.5 * np.log(0.5))
        np.testing.assert_allclose(ent, want, rtol=1e-5)


class TestRegularizer:
    def test_l2_decay_in_optimizer(self):
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt
        from paddle_tpu.regularizer import L2Decay

        paddle.seed(0)
        lin = nn.Linear(2, 2, bias_attr=False)
        w0 = _np(lin.weight).copy()
        sgd = opt.SGD(learning_rate=0.1, parameters=lin.parameters(),
                      weight_decay=L2Decay(0.5))
        out = lin(paddle.to_tensor(np.zeros((1, 2), "float32")))
        out.sum().backward()
        sgd.step()
        # grad is zero, so update = -lr * coeff * w
        np.testing.assert_allclose(_np(lin.weight), w0 * (1 - 0.1 * 0.5),
                                   rtol=1e-5)


class TestHub:
    def test_local_hub(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def toy_model(scale=2):\n"
            "    'build a toy'\n"
            "    return {'scale': scale}\n")
        assert paddle.hub.list(str(tmp_path)) == ["toy_model"]
        assert "toy" in paddle.hub.help(str(tmp_path), "toy_model")
        assert paddle.hub.load(str(tmp_path), "toy_model", scale=7) == {"scale": 7}

    def test_remote_rejected(self):
        with pytest.raises(ValueError):
            paddle.hub.list("some/repo", source="github")


class TestReaderDecorators:
    def test_pipeline(self):
        r = paddle.reader.chain(lambda: iter([1, 2]), lambda: iter([3]))
        assert list(r()) == [1, 2, 3]
        r2 = paddle.reader.firstn(lambda: iter(range(100)), 5)
        assert list(r2()) == [0, 1, 2, 3, 4]
        r3 = paddle.reader.map_readers(lambda a, b: a + b,
                                       lambda: iter([1, 2]), lambda: iter([10, 20]))
        assert list(r3()) == [11, 22]
        r4 = paddle.reader.buffered(lambda: iter(range(10)), 3)
        assert list(r4()) == list(range(10))
        r5 = paddle.reader.cache(lambda: iter([5, 6]))
        assert list(r5()) == [5, 6] and list(r5()) == [5, 6]
        r6 = paddle.reader.xmap_readers(lambda x: x * 2,
                                        lambda: iter(range(8)), 3, 4, order=True)
        assert list(r6()) == [0, 2, 4, 6, 8, 10, 12, 14]
        shuffled = sorted(paddle.reader.shuffle(lambda: iter(range(10)), 4)())
        assert shuffled == list(range(10))

    def test_batch(self):
        b = paddle.batch(lambda: iter(range(7)), 3)
        assert [len(x) for x in b()] == [3, 3, 1]
        b2 = paddle.batch(lambda: iter(range(7)), 3, drop_last=True)
        assert [len(x) for x in b2()] == [3, 3]


class TestCompat:
    def test_text_bytes(self):
        assert paddle.compat.to_text(b"abc") == "abc"
        assert paddle.compat.to_bytes("abc") == b"abc"
        assert paddle.compat.to_text([b"a", {b"k": b"v"}]) == ["a", {"k": "v"}]
        assert paddle.compat.round(2.5) == 3.0
        assert paddle.compat.round(-2.5) == -3.0


class TestDatasetNamespace:
    def test_legacy_module_shape(self):
        m = paddle.dataset.mnist
        assert callable(m.train) and callable(m.test)


class TestAsyncCollectives:
    def test_all_gather_object_single(self):
        import paddle_tpu.distributed as dist

        out = []
        dist.all_gather_object(out, {"rank": 0, "data": [1, 2]})
        assert out == [{"rank": 0, "data": [1, 2]}]

    def test_isend_irecv_handles(self):
        import paddle_tpu.distributed as dist

        t = paddle.to_tensor(np.ones(2, "float32"))
        task = dist.isend(t, dst=0)
        assert task.is_completed()
        task.wait()


class TestIncubate:
    def test_segment_ops(self):
        import paddle_tpu.incubate as inc

        data = paddle.to_tensor(np.array([[1., 2.], [3., 4.], [5., 6.]], "float32"))
        ids = paddle.to_tensor(np.array([0, 0, 1], "int32"))
        np.testing.assert_allclose(_np(inc.segment_sum(data, ids)),
                                   [[4, 6], [5, 6]])
        np.testing.assert_allclose(_np(inc.segment_mean(data, ids)),
                                   [[2, 3], [5, 6]])
        np.testing.assert_allclose(_np(inc.segment_max(data, ids)),
                                   [[3, 4], [5, 6]])
        np.testing.assert_allclose(_np(inc.segment_min(data, ids)),
                                   [[1, 2], [5, 6]])

    def test_softmax_mask_fuse(self):
        import paddle_tpu.incubate as inc

        x = paddle.to_tensor(rng.standard_normal((1, 2, 3, 3)).astype("float32"))
        mask = paddle.to_tensor(np.zeros((1, 1, 3, 3), "float32"))
        out = _np(inc.softmax_mask_fuse(x, mask))
        np.testing.assert_allclose(out.sum(-1), np.ones((1, 2, 3)), rtol=1e-5)
        ut = _np(inc.softmax_mask_fuse_upper_triangle(x))
        # causal: first row attends only position 0
        np.testing.assert_allclose(ut[..., 0, 1:], 0.0, atol=1e-6)

    def test_lookahead(self):
        import paddle_tpu.incubate as inc
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt

        paddle.seed(0)
        lin = nn.Linear(2, 1)
        inner = opt.SGD(learning_rate=0.1, parameters=lin.parameters())
        la = inc.LookAhead(inner, alpha=0.5, k=2)
        X = paddle.to_tensor(np.ones((4, 2), "float32"))
        Y = paddle.to_tensor(np.zeros((4, 1), "float32"))
        w0 = _np(lin.weight).copy()
        for _ in range(4):
            loss = ((lin(X) - Y) ** 2).mean()
            loss.backward()
            la.step()
            la.clear_grad()
        assert not np.allclose(_np(lin.weight), w0)

    def test_model_average(self):
        import paddle_tpu.incubate as inc

        p = paddle.to_tensor(np.ones(2, "float32"))
        ma = inc.ModelAverage(parameters=[p])
        ma.step()  # avg = 1
        import jax.numpy as jnp

        p._set_data(jnp.asarray(np.full(2, 3.0, "float32")))
        ma.step()  # avg = 2
        with ma.apply():
            np.testing.assert_allclose(_np(p), 2.0)
        np.testing.assert_allclose(_np(p), 3.0)


class TestLinalgNamespace:
    def test_cond_and_exports(self):
        import paddle_tpu.linalg as L

        m = paddle.to_tensor(np.diag([1.0, 4.0]).astype("float32"))
        np.testing.assert_allclose(float(_np(L.cond(m))), 4.0, rtol=1e-5)
        for n in ("svd", "qr", "solve", "pinv", "lstsq", "eigh"):
            assert hasattr(L, n)


class TestInplaceTensorMethods:
    def test_inplace_chain(self):
        t = paddle.to_tensor(np.full((2, 2), 4.0, "float32"))
        t.sqrt_().add_(paddle.to_tensor(np.ones((2, 2), "float32"))).scale_(2.0)
        np.testing.assert_allclose(_np(t), 6.0)

    def test_random_inplace(self):
        paddle.seed(0)
        t = paddle.to_tensor(np.zeros((100,), "float32"))
        t.uniform_(2.0, 3.0)
        assert (_np(t) >= 2.0).all() and (_np(t) < 3.0).all()
        t.normal_(0.0, 1.0)
        assert abs(_np(t).mean()) < 0.5


class TestUtilsExtras:
    def test_unique_name(self):
        from paddle_tpu.utils import unique_name

        a = unique_name.generate("fc")
        b = unique_name.generate("fc")
        assert a != b and a.startswith("fc_")
        with unique_name.guard():
            c = unique_name.generate("fc")
            assert c == "fc_0"
        d = unique_name.generate("fc")
        assert d not in (a, b, c) or d.split("_")[-1] > b.split("_")[-1]

    def test_dlpack_roundtrip(self):
        from paddle_tpu.utils import dlpack

        t = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3))
        back = dlpack.from_dlpack(dlpack.to_dlpack(t))
        np.testing.assert_allclose(_np(back), _np(t))

    def test_dlpack_torch_interop(self):
        import torch

        from paddle_tpu.utils import dlpack

        tt = torch.arange(4, dtype=torch.float32)
        jt = dlpack.from_dlpack(tt)
        np.testing.assert_allclose(_np(jt), [0, 1, 2, 3])

    def test_cpp_extension_load(self, tmp_path):
        from paddle_tpu.utils import cpp_extension

        src = tmp_path / "ext.cc"
        src.write_text('extern "C" double mul2(double x) { return x * 2; }')
        lib = cpp_extension.load("parity_ext", [str(src)],
                                 build_directory=str(tmp_path))
        import ctypes

        lib.mul2.restype = ctypes.c_double
        lib.mul2.argtypes = [ctypes.c_double]
        assert lib.mul2(2.5) == 5.0

    def test_cuda_extension_raises(self):
        import pytest as _pytest

        from paddle_tpu.utils import cpp_extension

        with _pytest.raises(RuntimeError):
            cpp_extension.CUDAExtension(sources=["x.cu"])


class TestTracedLayer:
    def test_trace_call_save(self, tmp_path):
        import paddle_tpu.jit as jit
        import paddle_tpu.nn as nn

        paddle.seed(0)
        lin = nn.Linear(3, 2)
        x = paddle.to_tensor(rng.standard_normal((2, 3)).astype("float32"))
        outs, tl = jit.TracedLayer.trace(lin, [x])
        np.testing.assert_allclose(_np(tl(x)), _np(lin(x)), rtol=1e-5)
        tl.save_inference_model(str(tmp_path / "traced"))
        loaded = jit.load(str(tmp_path / "traced"))
        np.testing.assert_allclose(np.asarray(loaded(x)._data), _np(lin(x)),
                                   rtol=1e-5)


class TestInitializerExtras:
    def test_bilinear_upsampling_kernel(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.nn.initializer import Bilinear

        # conv_transpose with the bilinear kernel interpolates a constant
        # image back to a constant (partition of unity in the interior)
        up = nn.Conv2DTranspose(1, 1, 4, stride=2, padding=1,
                                weight_attr=None, bias_attr=False)
        import jax.numpy as jnp

        up.weight._set_data(jnp.asarray(np.asarray(Bilinear()(tuple(up.weight.shape)))))
        x = paddle.to_tensor(np.ones((1, 1, 4, 4), "float32"))
        out = _np(up(x))
        assert out.shape == (1, 1, 8, 8)
        np.testing.assert_allclose(out[0, 0, 2:6, 2:6], 1.0, rtol=1e-5)

    def test_pylayer_context_export(self):
        from paddle_tpu.autograd import PyLayer, PyLayerContext

        assert PyLayer is not None and PyLayerContext is not None


class TestFleetSurface:
    def test_ps_surface_and_util(self):
        import paddle_tpu.distributed.fleet as fleet

        fleet.init(is_collective=True)
        assert fleet.server_num() == 0
        fleet.init_worker()   # no-op in collective mode
        fleet.stop_worker()
        with pytest.raises(RuntimeError):
            fleet.run_server()
        u = fleet.fleet.util
        assert u.get_file_shard(["a", "b", "c"]) == ["a", "b", "c"]
        out = u.all_gather(np.array([1.0], "float32"))
        assert len(out) >= 1

    def test_version_module(self):
        assert paddle.version.full_version == paddle.__version__
        assert paddle.version.cuda() == "False"


class TestFleetCheckpointSurface:
    def test_save_persistables_and_inference_model(self, tmp_path):
        import paddle_tpu.distributed.fleet as fleet
        import paddle_tpu.static as static

        fleet.init(is_collective=True)
        try:
            paddle.enable_static()
            main = static.Program()
            startup = static.Program()
            with static.program_guard(main, startup):
                x = static.data("x", [None, 3], "float32")
                w = static.create_parameter([3, 2], "float32", name="w")
                out = paddle.matmul(x, w)
            exe = static.Executor()
            exe.run(startup)
            fleet.save_persistables(exe, str(tmp_path), main_program=main)
            assert (tmp_path / "fleet_ckpt.pdparams").exists()
            fleet.save_inference_model(exe, str(tmp_path), ["x"], [out],
                                       main_program=main)
            assert any(f.name.startswith("model") for f in tmp_path.iterdir())
        finally:
            paddle.disable_static()

    def test_contiguous_file_shard(self):
        import paddle_tpu.distributed.fleet as fleet

        fleet.init(is_collective=True)
        # world size 1: everything, in order
        assert fleet.util.get_file_shard(["a", "b", "c"]) == ["a", "b", "c"]
