"""Paged flash-decode + fused softmax-CE Pallas kernels (r20, interpret
mode on the CPU harness) and the kernel cost registry that prices them:
kernel-vs-reference parity via the manifest differential harness (r24),
cost-model pricing of pallas_call eqns, unknown-prim scope attribution,
and the committed perf-attribution pins.
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas import differential_cases
from paddle_tpu.ops.pallas.cost_registry import (
    kernel_cost_model,
    registered_kernels,
)
from paddle_tpu.ops.pallas.paged_attention import paged_flash_attention

BENCH_DIR = os.path.join(os.path.dirname(__file__), "..", "benchmarks")


def _paged_fixture(rng, b=3, h=4, d=16, ps=8, mp=6, n_pages=20,
                   lens=(5, 13, 40)):
    """Pools + tables for slots with mixed live lengths; table entries
    past each slot's pages point at the reserved trash page 0."""
    pk = jnp.asarray(rng.normal(size=(n_pages, h, ps, d)), jnp.float32)
    pv = jnp.asarray(rng.normal(size=(n_pages, h, ps, d)), jnp.float32)
    pages = np.zeros((b, mp), np.int32)
    nxt = iter(range(1, n_pages))
    for i, ln in enumerate(lens):
        for j in range(-(-(ln + 1) // ps)):
            pages[i, j] = next(nxt)
    pos = jnp.asarray(list(lens), jnp.int32)
    return pk, pv, jnp.asarray(pages), pos, ps


@pytest.mark.pallas
class TestDifferentialHarness:
    """The manifest's interpret-mode differential lattice (r24): every
    shipped kernel vs its jitted-XLA reference, parametrized over the
    shape/tiling lattice — non-dividing vocab tails, page_size 16/32,
    bf16 arms, grads through the custom VJPs.  This replaces the former
    per-kernel ad-hoc comparison tests: the lattice IS the test set, and
    the kernel doctor audits the same cases statically."""

    @pytest.mark.parametrize("case", differential_cases(),
                             ids=lambda c: c.id)
    def test_kernel_matches_reference(self, case):
        got, want = case.run()
        got_leaves = jax.tree_util.tree_leaves(got)
        want_leaves = jax.tree_util.tree_leaves(want)
        assert len(got_leaves) == len(want_leaves), case.id
        for g, w in zip(got_leaves, want_leaves):
            np.testing.assert_allclose(
                np.asarray(g, np.float64), np.asarray(w, np.float64),
                atol=case.atol, rtol=case.rtol, err_msg=case.id)

    def test_lattice_covers_the_hard_shapes(self):
        cases = differential_cases()
        ids = [c.id for c in cases]
        assert any("ps16" in i for i in ids)
        assert any("ps32" in i for i in ids)
        assert any("tail" in i for i in ids)      # vocab % block != 0
        kernels = {c.kernel for c in cases}
        assert {"paged_flash_attention", "paged_flash_attention_int8",
                "softmax_ce_fwd", "softmax_ce_partials_fwd",
                "flash_attention_fwd", "rope_fwd", "swiglu_fwd",
                "fused_residual_dropout_ln_fwd"} <= kernels


@pytest.mark.pallas
class TestPagedFlashKernel:
    def test_trash_pages_never_leak(self):
        """Scribbling on trash page 0 must not change any slot's output —
        padded table entries are masked by position, not by page id."""
        rng = np.random.default_rng(2)
        pk, pv, pages, pos, ps = _paged_fixture(rng)
        q = jnp.asarray(rng.normal(size=(3, 4, 1, 16)), jnp.float32)
        base = paged_flash_attention(q, pk, pv, pages, pos, page_size=ps,
                                     interpret=True)
        pk2 = pk.at[0].set(1e6)
        pv2 = pv.at[0].set(-1e6)
        poisoned = paged_flash_attention(q, pk2, pv2, pages, pos,
                                         page_size=ps, interpret=True)
        np.testing.assert_array_equal(np.asarray(base), np.asarray(poisoned))

    def test_shared_page_two_tables(self):
        """COW-safety precondition: two slots whose tables reference the
        SAME page (shared prefix) read identical values through it."""
        rng = np.random.default_rng(3)
        pk, pv, pages, pos, ps = _paged_fixture(rng, lens=(7, 7, 7))
        shared = np.array(pages)
        shared[1] = shared[0]  # slot 1 aliases slot 0's pages wholesale
        pages2 = jnp.asarray(shared)
        q = jnp.asarray(rng.normal(size=(3, 4, 1, 16)), jnp.float32)
        q = q.at[1].set(q[0])
        out = paged_flash_attention(q, pk, pv, pages2, pos, page_size=ps,
                                    interpret=True)
        np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(out[1]))


@pytest.mark.pallas
class TestSoftmaxCEKernel:
    def test_ignore_rows_exactly_zero(self):
        """Ignore rows (label == -100) are EXACTLY zero, not merely
        small — the semantic detail an allclose differential can miss."""
        from paddle_tpu.ops.pallas.softmax_ce import softmax_ce_loss

        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.normal(size=(4, 16, 64)), jnp.float32)
        labels = jnp.asarray(rng.integers(0, 64, (4, 16)), jnp.int32)
        labels = labels.at[0, 3].set(-100).at[2, 0].set(-100)
        loss = softmax_ce_loss(logits, labels, interpret=True)
        assert float(loss[0, 3]) == 0.0 and float(loss[2, 0]) == 0.0

    def test_criterion_flag_parity(self):
        """GPTPretrainingCriterion under the flag == without, fwd + grad
        (the non-mp ParallelCrossEntropy branch, f32 inputs)."""
        import paddle_tpu as paddle
        from paddle_tpu.framework.flags import set_flags
        from paddle_tpu.models.gpt import (
            GPTForPretraining,
            GPTPretrainingCriterion,
            gpt_config,
        )

        paddle.seed(0)
        cfg = gpt_config("gpt2-small", vocab_size=64, hidden_size=32,
                         num_layers=2, num_attention_heads=4,
                         max_position_embeddings=64, hidden_dropout_prob=0.0,
                         attention_dropout_prob=0.0)
        model = GPTForPretraining(cfg)
        crit = GPTPretrainingCriterion()
        ids = paddle.to_tensor(
            np.random.default_rng(0).integers(0, 64, (2, 8)).astype("int32"))

        def loss_and_grad():
            loss = crit(model(ids), ids)
            loss.backward()
            g = {n: np.asarray(p.grad._data)
                 for n, p in model.named_parameters() if p.grad is not None}
            model.clear_gradients()
            return float(loss._data), g

        l0, g0 = loss_and_grad()
        set_flags({"FLAGS_use_pallas_softmax_ce": True})
        try:
            l1, g1 = loss_and_grad()
        finally:
            set_flags({"FLAGS_use_pallas_softmax_ce": False})
        assert abs(l0 - l1) < 1e-5, (l0, l1)
        assert g0.keys() == g1.keys()
        for n in g0:
            np.testing.assert_allclose(g0[n], g1[n], rtol=1e-4, atol=1e-6)


@pytest.mark.pallas
class TestKernelCostRegistry:
    def test_shipped_kernels_registered(self):
        names = registered_kernels()
        for k in ("paged_flash_attention", "softmax_ce_fwd",
                  "softmax_ce_bwd", "softmax_ce_partials_fwd",
                  "flash_attention_fwd", "flash_attention_bwd_dq",
                  "flash_attention_bwd_dkv", "rope_fwd", "swiglu_fwd",
                  "fused_residual_dropout_ln_fwd"):
            assert k in names, (k, names)
        assert kernel_cost_model("no_such_kernel") is None

    def test_pallas_eqn_priced_not_unknown(self):
        """graph_cost over a program containing the paged kernel: the
        pallas_call eqn is priced from the registry (flops > 0, no
        GraphCost.unknown tally) and the kernel-body inner eqns are not
        double counted."""
        from paddle_tpu.analysis.cost import graph_cost
        from paddle_tpu.analysis.graph import AnalysisTarget

        rng = np.random.default_rng(0)
        pk, pv, pages, pos, ps = _paged_fixture(rng)
        q = jnp.asarray(rng.normal(size=(3, 4, 1, 16)), jnp.float32)

        def fn(q, pk, pv):
            return paged_flash_attention(q, pk, pv, pages, pos,
                                         page_size=ps, interpret=True)

        t = AnalysisTarget("paged_kernel", fn, (q, pk, pv))
        gc = graph_cost(t.graph(), t.mesh_axes)
        assert "pallas_call" not in gc.unknown, gc.unknown
        assert gc.flops > 0
        model = kernel_cost_model("paged_flash_attention")
        # hand-check the registered model against the kernel's operands:
        # bytes = touched pages (B*MP K+V blocks) + q/out/table — far less
        # than the gather path's materialized [B, cap, H, D] round-trip
        b, mp = pages.shape
        _, h, t_, d = q.shape
        in_avals = [((b, mp), "int32", False), ((b,), "int32", False),
                    (tuple(q.shape), "float32", False),
                    (tuple(pk.shape), "float32", False),
                    (tuple(pv.shape), "float32", False)]
        out_avals = [(tuple(q.shape), "float32", False)]
        flops, bts = model(in_avals, out_avals, {})
        s = mp * ps
        assert flops == 4.0 * b * h * t_ * s * d + 16.0 * b * h * t_ * s
        assert bts == (b * mp * h * ps * d * 8      # K+V pages, f32
                       + q.size * 4 * 2 + pages.size * 4 + pos.size * 4)

    def test_unregistered_kernel_keeps_loud_fallback(self):
        """A pallas_call without a registered cost model still lands in
        GraphCost.unknown (bytes-only) — never silently zero-costed."""
        from jax.experimental import pallas as pl

        from paddle_tpu.analysis.cost import graph_cost
        from paddle_tpu.analysis.graph import AnalysisTarget

        def kern(x_ref, o_ref):
            o_ref[...] = x_ref[...] * 2.0

        def fn(x):
            return pl.pallas_call(
                kern, out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
                interpret=True, name="not_in_registry")(x)

        t = AnalysisTarget("anon_kernel", fn,
                          (jnp.ones((8, 128), jnp.float32),))
        gc = graph_cost(t.graph(), t.mesh_axes)
        assert gc.unknown.get("pallas_call") == 1
        assert gc.estimated

    def test_unknown_where_scope_attribution(self):
        """Satellite: GraphCost.unknown entries carry the r14 scope path
        of the first offending eqn, so an unpriced prim is attributable
        without a jaxpr dig."""
        from paddle_tpu.analysis.cost import graph_cost
        from paddle_tpu.analysis.graph import AnalysisTarget
        from paddle_tpu.profiler.scope import scope

        def fn(x):
            with scope("model.sorter"):
                y = jnp.sort(x, axis=-1)
            return y + jnp.sort(x, axis=0)

        t = AnalysisTarget("sorty", fn, (jnp.ones((8, 16), jnp.float32),))
        gc = graph_cost(t.graph(), t.mesh_axes)
        assert "sort" in gc.unknown
        assert gc.unknown_where["sort"] == "model.sorter"  # FIRST offender
        assert "unknown_where" in gc.to_dict()

    def test_planner_prices_shift_when_ce_kernel_flips(self):
        """Acceptance pin: analysis/plan.py candidate prices provably
        change when the softmax-CE kernel flag flips (the lowered loss
        head changes, and the registry prices its pallas_call eqns)."""
        from paddle_tpu.analysis.plan import plan_gpt
        from paddle_tpu.framework.flags import set_flags
        from paddle_tpu.models.gpt import gpt_config

        cfg = gpt_config("gpt2-small", vocab_size=128, hidden_size=32,
                         num_layers=2, num_attention_heads=4,
                         max_position_embeddings=32,
                         hidden_dropout_prob=0.0,
                         attention_dropout_prob=0.0)

        def prices():
            plan = plan_gpt(cfg, n_devices=2, global_batch=4, seq_len=16,
                            max_lowered=1)
            return {str(r.spec): (r.flops_per_device,
                                  r.hbm_bytes_per_device, r.step_time_s)
                    for r in plan.candidates if r.priced_by == "analysis"}

        off = prices()
        set_flags({"FLAGS_use_pallas_softmax_ce": True})
        try:
            on = prices()
        finally:
            set_flags({"FLAGS_use_pallas_softmax_ce": False})
        assert off and on
        common = set(off) & set(on)
        assert common and any(off[k] != on[k] for k in common), (off, on)


@pytest.mark.pallas
class TestServingEntryPointPins:
    @pytest.fixture(scope="class")
    def serving(self):
        from paddle_tpu.analysis.entrypoints import serving_targets

        return {t.name: t for t in serving_targets()}

    def test_kernel_on_decode_zero_unknown_pallas(self, serving):
        """Acceptance pin: the kernel-on serving entry points lint with
        ZERO unknown-prim pallas entries."""
        from paddle_tpu.analysis.cost import graph_cost

        for name in ("serving_decode_pallas", "serving_prefill_pallas"):
            t = serving[name]
            gc = graph_cost(t.graph(), t.mesh_axes)
            assert "pallas_call" not in gc.unknown, (name, gc.unknown)

    def test_paged_attn_intensity_improves(self, serving):
        """The serving.paged_attn scope's arithmetic intensity under the
        flash kernel beats the XLA gather arm (the gather materializes
        the [B, cap, H, D] tensor; the kernel streams pages once)."""
        from paddle_tpu.analysis.cost import scope_costs

        def attn_intensity(name):
            sc = scope_costs(serving[name].graph(),
                             serving[name].mesh_axes)
            fl = by = 0.0
            for key, row in sc.items():
                if "serving.paged_attn" in key:
                    fl += row.flops
                    by += row.bytes_accessed
            assert by > 0, name
            return fl / by

        assert attn_intensity("serving_decode_pallas") \
            > 2.0 * attn_intensity("serving_decode")


@pytest.mark.pallas
class TestCommittedArtifactPins:
    """Pins over the regenerated benchmarks/perf_attribution.json: both
    serving arms are committed side by side, the kernel-on arm prices
    every pallas_call, and its paged-attn row's roofline position
    improves on the gather row."""

    @pytest.fixture(scope="class")
    def perf(self):
        path = os.path.join(BENCH_DIR, "perf_attribution.json")
        with open(path) as f:
            return json.load(f)

    def test_both_serving_arms_committed(self, perf):
        entries = perf["entries"]
        assert "serving_decode" in entries
        assert "serving_decode_pallas" in entries
        assert entries["serving_decode_pallas"]["config"]["attn_impl"] \
            == "pallas"

    def test_kernel_arm_zero_unknown_pallas(self, perf):
        unk = perf["entries"]["serving_decode_pallas"]["graph_cost"][
            "unknown_prims"]
        assert "pallas_call" not in unk, unk

    def test_paged_attn_row_improves_vs_gather(self, perf):
        def attn_rows(entry):
            fl = by = 0.0
            for row in perf["entries"][entry]["rows"]:
                if "serving.paged_attn" in row["scope"]:
                    fl += row["flops"]
                    by += row["bytes_accessed"]
            assert by > 0, entry
            return fl / by

        gather = attn_rows("serving_decode")
        flash = attn_rows("serving_decode_pallas")
        assert flash > 2.0 * gather, (gather, flash)
