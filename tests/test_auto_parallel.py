"""Auto-parallel front door: ProcessMesh / shard_tensor / shard_op.

Parity model: reference auto_parallel tests (test_auto_parallel_api.py).
"""
import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.auto_parallel import Engine, ProcessMesh, shard_op, shard_tensor


@pytest.fixture(autouse=True)
def _clean_mesh():
    yield
    from paddle_tpu.distributed.env import clear_mesh

    clear_mesh()


def test_process_mesh_shape_and_names():
    pm = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["x", "y"])
    assert pm.shape == [2, 4] and pm.ndim == 2
    assert pm.jax_mesh().shape["x"] == 2


def test_shard_tensor_places_array():
    pm = ProcessMesh([[0, 1], [2, 3]], dim_names=["dp", "mp"])
    x = paddle.to_tensor(np.arange(32, dtype="float32").reshape(8, 4))
    sx = shard_tensor(x, pm, ["dp", None])
    assert sx.value.sharding.spec == P("dp", None)
    np.testing.assert_array_equal(sx.numpy(), x.numpy())
    # reference-style dims_mapping ints: 1 -> mesh dim 'mp', -1 -> replicated
    sy = shard_tensor(x, pm, [-1, 1])
    assert sy.value.sharding.spec == P(None, "mp")


def test_shard_tensor_inside_jit_constrains():
    pm = ProcessMesh(list(range(8)), dim_names=["dp"])

    def f(a):
        return shard_tensor(a * 2.0, pm, ["dp", None])

    x = np.ones((8, 4), "float32")
    out = jax.jit(lambda a: f(a))(x)
    np.testing.assert_allclose(np.asarray(out.numpy() if hasattr(out, "numpy") else out), 2.0)


def test_shard_op_annotates_inputs_outputs():
    pm = ProcessMesh(list(range(8)), dim_names=["dp"])
    matmul = shard_op(paddle.matmul, pm,
                      in_shard_specs=[["dp", None], None],
                      out_shard_specs=[["dp", None]])
    a = paddle.to_tensor(np.random.rand(8, 4).astype("float32"))
    b = paddle.to_tensor(np.random.rand(4, 3).astype("float32"))
    out = matmul(a, b)
    np.testing.assert_allclose(out.numpy(), a.numpy() @ b.numpy(), rtol=1e-5)
    assert out.value.sharding.spec == P("dp", None)


def test_engine_trains_sharded():
    pm = ProcessMesh(list(range(8)), dim_names=["dp"])
    net = paddle.nn.Linear(4, 2)
    crit = paddle.nn.MSELoss()
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    eng = Engine(net, lambda o, y: crit(o, y), opt, pm)
    trainer = eng.fit_step()
    x = paddle.to_tensor(np.random.rand(16, 4).astype("float32"))
    y = paddle.to_tensor(np.random.rand(16, 2).astype("float32"))
    l0 = float(trainer.step(x, y).numpy())
    for _ in range(20):
        l = float(trainer.step(x, y).numpy())
    assert l < l0


def test_shard_tensor_keeps_autograd():
    pm = ProcessMesh(list(range(8)), dim_names=["dp"])
    w = paddle.to_tensor(np.random.rand(4, 2).astype("float32"), stop_gradient=False)
    x = paddle.to_tensor(np.random.rand(8, 4).astype("float32"))
    out = shard_tensor(paddle.matmul(x, w), pm, ["dp", None])
    out.sum().backward()
    assert w.grad is not None
    np.testing.assert_allclose(w.grad.numpy(), x.numpy().T @ np.ones((8, 2)),
                               rtol=1e-5)


class TestPlanner:
    """Analytic cost-model planner (reference cost_model.py/planner.py role,
    VERDICT r3 missing #6)."""

    def _stats(self, n_params, layers=24, hidden=2048, seq=1024):
        from paddle_tpu.distributed.auto_parallel.planner import ModelStats

        return ModelStats(n_params=n_params, n_layers=layers, hidden=hidden,
                          seq_len=seq)

    def test_small_model_prefers_pure_dp(self):
        from paddle_tpu.distributed.auto_parallel.planner import plan_strategy

        # 350M on 8 x 16GB: fits replicated; dp-only should win (no mp/pp
        # comm, no bubble)
        plan = plan_strategy(self._stats(350_000_000), 8, global_batch=64)
        assert plan.best.mp == 1 and plan.best.pp == 1
        assert plan.best.dp == 8

    def test_huge_model_forced_to_shard(self):
        from paddle_tpu.distributed.auto_parallel.planner import plan_strategy

        # 6B f32 masters cannot be replicated on 16GB (24 GB params alone):
        # the planner must pick model sharding (mp/pp) and/or ZeRO-3
        plan = plan_strategy(self._stats(6_000_000_000, layers=32,
                                         hidden=4096), 8, global_batch=32)
        b = plan.best
        assert b.mp * b.pp > 1 or b.zero_stage >= 3
        assert b.mem_bytes <= 16e9

    def test_memory_model_zero_stages_monotone(self):
        from paddle_tpu.distributed.auto_parallel.planner import _score

        s = self._stats(1_300_000_000)
        mems = []
        for z in (0, 1, 2, 3):
            c = _score(s, s.n_params, 8, 1, 1, z, 1, False, 64,
                       16e9, 197e12, 4.5e10, 0.5)
            mems.append(c.mem_bytes)
        assert mems[0] > mems[1] > mems[2] > mems[3]

    def test_nothing_fits_raises_with_diagnostics(self):
        from paddle_tpu.distributed.auto_parallel.planner import plan_strategy

        with pytest.raises(ValueError, match="infeasible"):
            plan_strategy(self._stats(500_000_000_000, layers=96,
                                      hidden=12288), 2, global_batch=2,
                          hbm_bytes=16e9)

    def test_explain_lists_candidates(self):
        from paddle_tpu.distributed.auto_parallel.planner import plan_strategy

        plan = plan_strategy(self._stats(1_300_000_000), 8, global_batch=32)
        txt = plan.explain()
        assert "mem(GB)" in txt and len(txt.splitlines()) > 2

    def test_engine_auto_builds_trainer(self):
        import numpy as np

        from paddle_tpu.distributed.auto_parallel import Engine
        from paddle_tpu.models.gpt import (
            GPTForPretraining, GPTPretrainingCriterion, gpt_config)
        from paddle_tpu.optimizer.optimizers import AdamW

        paddle.seed(0)
        cfg = gpt_config("gpt2-small", vocab_size=64, hidden_size=32,
                         num_layers=2, num_attention_heads=4,
                         max_position_embeddings=32,
                         hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
        model = GPTForPretraining(cfg)
        crit = GPTPretrainingCriterion(cfg)
        opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
        eng = Engine.auto(model, lambda o, y: crit(o, y), opt,
                          global_batch=8, seq_len=16)
        assert eng.plan is not None and eng.plan.best.dp >= 1
        trainer = eng.fit_step()
        ids = paddle.to_tensor(np.random.default_rng(0).integers(
            0, 64, (8, 16)).astype("int32"))

        def run(t, x):
            out = t.step(x, x)
            return float(np.asarray(getattr(out, "_data", out)))

        l0 = run(trainer, ids)
        l5 = l0
        for _ in range(5):
            l5 = run(trainer, ids)
        assert l5 < l0


class TestPlannerValidation:
    """VERDICT r4 #7: the planner's rankings checked against the repo's OWN
    measured sweeps (benchmarks/measured_r5.json). Constants were calibrated
    from the measured feasibility boundary (760m-b8-no-remat fits,
    1.3b-b4-no-remat does not) and the measured MFU band (0.47-0.60)."""

    @pytest.fixture(scope="class")
    def measured(self):
        import json
        import os

        p = os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                         "measured_r5.json")
        with open(p) as f:
            return json.load(f)["workloads"]

    def _plan_one_chip(self, wl):
        from paddle_tpu.distributed.auto_parallel.planner import (
            ModelStats, plan_strategy)

        stats = ModelStats(n_params=wl["n_params"], n_layers=wl["layers"],
                           hidden=wl["hidden"], seq_len=wl["seq"],
                           moment_bytes=2)
        return plan_strategy(stats, 1, global_batch=wl["batch"])

    def test_ranks_measured_best_on_three_workloads(self, measured):
        # 350m b8: measured best is NO remat — planner must agree
        p350 = self._plan_one_chip(measured["gpt3-350m"])
        assert p350.best.recompute is False

        # 760m b8: no-remat measured to fit and win
        p760 = self._plan_one_chip(measured["gpt3-760m"])
        assert p760.best.recompute is False

        # 1.3b b4: no-remat measured to OOM — planner must require remat
        p13 = self._plan_one_chip(measured["gpt3-1.3b"])
        assert p13.best.recompute is True
        no_remat = [c for c in p13.candidates if not c.recompute]
        assert not no_remat, "planner wrongly thinks 1.3b no-remat fits"

    def test_predicted_vs_measured_step_time(self, measured):
        errors = {}
        for name, wl in measured.items():
            plan = self._plan_one_chip(wl)
            tokens_per_step = wl["batch"] * wl["seq"]
            pred_tok_s = tokens_per_step / plan.best.step_time_s
            best_meas = wl["variants"][wl["best"]]
            errors[name] = abs(pred_tok_s - best_meas) / best_meas
        # compute-model error stays within the calibrated band; the 1.3b
        # row is the coarsest (the planner's binary remat = full 4/3 flops,
        # the measured best remats every 3rd block and saves flash)
        assert errors["gpt3-350m"] < 0.25, errors
        assert errors["gpt3-760m"] < 0.15, errors
        assert errors["gpt3-1.3b"] < 0.45, errors
        assert sorted(errors.values())[1] < 0.25, errors  # median

    def test_explain_shows_calibrated_numbers(self, measured):
        plan = self._plan_one_chip(measured["gpt3-1.3b"])
        txt = plan.explain()
        assert "mem(GB)" in txt
        # the winner's memory must reflect the calibrated model: params
        # 5.3GB + moments 5.3GB + 0.5x grads + remat activations < 16GB
        assert plan.best.mem_bytes < 16e9
        assert plan.best.mem_breakdown["grads"] == pytest.approx(
            0.5 * plan.best.mem_breakdown["params"], rel=1e-6)


class TestGradFactorGate:
    """ADVICE r5 #2: the calibrated 0.5x grad-bytes factor holds only for
    the fused donated-buffer step; held grad accumulators (user-level
    accumulate_steps, pipeline microbatching, non-fused optimizers) need
    the full 1.0x, so plan_strategy must stop admitting plans that OOM."""

    def _stats_13b(self):
        from paddle_tpu.distributed.auto_parallel.planner import ModelStats

        return ModelStats(n_params=1_315_819_520, n_layers=24, hidden=2048,
                          seq_len=1024, moment_bytes=2)

    def test_accumulation_doubles_grad_bytes(self):
        from paddle_tpu.distributed.auto_parallel.planner import (
            ModelStats, plan_strategy)

        stats = ModelStats(n_params=355_919_872, n_layers=24, hidden=1024,
                           seq_len=1024, moment_bytes=2)
        fused = plan_strategy(stats, 1, global_batch=8)
        held = plan_strategy(stats, 1, global_batch=8, accumulate_steps=2)
        by_key = {(c.dp, c.mp, c.pp, c.zero_stage, c.microbatches,
                   c.recompute): c for c in fused.candidates}
        for c in held.candidates:
            twin = by_key[(c.dp, c.mp, c.pp, c.zero_stage, c.microbatches,
                           c.recompute)]
            assert c.mem_breakdown["grads"] == pytest.approx(
                2 * twin.mem_breakdown["grads"])

    def test_13b_with_held_grads_does_not_fit_one_chip(self):
        """params 5.3G + bf16 moments 5.3G + FULL f32 grads 5.3G ~= 15.9G
        before activations: the measured feasibility boundary (1.3b b4
        fits only because the fused step aliases grads)."""
        from paddle_tpu.distributed.auto_parallel.planner import plan_strategy

        stats = self._stats_13b()
        assert plan_strategy(stats, 1, global_batch=4).best is not None
        with pytest.raises(ValueError, match="no parallel strategy fits"):
            plan_strategy(stats, 1, global_batch=4, accumulate_steps=2)
        with pytest.raises(ValueError, match="no parallel strategy fits"):
            plan_strategy(stats, 1, global_batch=4, fused_grad_buffers=False)

    def test_pipeline_candidates_hold_grads(self):
        from paddle_tpu.distributed.auto_parallel.planner import (
            ModelStats, plan_strategy)

        stats = ModelStats(n_params=355_919_872, n_layers=24, hidden=1024,
                           seq_len=1024, moment_bytes=2)
        plan = plan_strategy(stats, 4, global_batch=8)
        cands = [c for c in plan.candidates
                 if c.mp == 1 and c.zero_stage == 0 and not c.recompute]
        by_key = {(c.pp, c.dp, c.microbatches): c for c in cands}
        # EVERY pp>1 candidate (any m) holds a full grad accumulator
        # across the tick scan: 1.0x its param shard...
        pp2 = by_key[(2, 2, 1)]
        assert pp2.mem_breakdown["grads"] == pytest.approx(
            1.0 * stats.n_params / 2 * stats.param_bytes)
        assert by_key[(2, 2, 2)].mem_breakdown["grads"] == pytest.approx(
            pp2.mem_breakdown["grads"])
        # ...while the fused single-microbatch pp=1 step aliases (0.5x)
        pp1 = by_key[(1, 4, 1)]
        assert pp1.mem_breakdown["grads"] == pytest.approx(
            0.5 * stats.n_params * stats.param_bytes)
