"""Auto-parallel front door: ProcessMesh / shard_tensor / shard_op.

Parity model: reference auto_parallel tests (test_auto_parallel_api.py).
"""
import jax
import numpy as np
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.auto_parallel import Engine, ProcessMesh, shard_op, shard_tensor


def test_process_mesh_shape_and_names():
    pm = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["x", "y"])
    assert pm.shape == [2, 4] and pm.ndim == 2
    assert pm.jax_mesh().shape["x"] == 2


def test_shard_tensor_places_array():
    pm = ProcessMesh([[0, 1], [2, 3]], dim_names=["dp", "mp"])
    x = paddle.to_tensor(np.arange(32, dtype="float32").reshape(8, 4))
    sx = shard_tensor(x, pm, ["dp", None])
    assert sx.value.sharding.spec == P("dp", None)
    np.testing.assert_array_equal(sx.numpy(), x.numpy())
    # reference-style dims_mapping ints: 1 -> mesh dim 'mp', -1 -> replicated
    sy = shard_tensor(x, pm, [-1, 1])
    assert sy.value.sharding.spec == P(None, "mp")


def test_shard_tensor_inside_jit_constrains():
    pm = ProcessMesh(list(range(8)), dim_names=["dp"])

    def f(a):
        return shard_tensor(a * 2.0, pm, ["dp", None])

    x = np.ones((8, 4), "float32")
    out = jax.jit(lambda a: f(a))(x)
    np.testing.assert_allclose(np.asarray(out.numpy() if hasattr(out, "numpy") else out), 2.0)


def test_shard_op_annotates_inputs_outputs():
    pm = ProcessMesh(list(range(8)), dim_names=["dp"])
    matmul = shard_op(paddle.matmul, pm,
                      in_shard_specs=[["dp", None], None],
                      out_shard_specs=[["dp", None]])
    a = paddle.to_tensor(np.random.rand(8, 4).astype("float32"))
    b = paddle.to_tensor(np.random.rand(4, 3).astype("float32"))
    out = matmul(a, b)
    np.testing.assert_allclose(out.numpy(), a.numpy() @ b.numpy(), rtol=1e-5)
    assert out.value.sharding.spec == P("dp", None)


def test_engine_trains_sharded():
    pm = ProcessMesh(list(range(8)), dim_names=["dp"])
    net = paddle.nn.Linear(4, 2)
    crit = paddle.nn.MSELoss()
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    eng = Engine(net, lambda o, y: crit(o, y), opt, pm)
    trainer = eng.fit_step()
    x = paddle.to_tensor(np.random.rand(16, 4).astype("float32"))
    y = paddle.to_tensor(np.random.rand(16, 2).astype("float32"))
    l0 = float(trainer.step(x, y).numpy())
    for _ in range(20):
        l = float(trainer.step(x, y).numpy())
    assert l < l0


def test_shard_tensor_keeps_autograd():
    pm = ProcessMesh(list(range(8)), dim_names=["dp"])
    w = paddle.to_tensor(np.random.rand(4, 2).astype("float32"), stop_gradient=False)
    x = paddle.to_tensor(np.random.rand(8, 4).astype("float32"))
    out = shard_tensor(paddle.matmul(x, w), pm, ["dp", None])
    out.sum().backward()
    assert w.grad is not None
    np.testing.assert_allclose(w.grad.numpy(), x.numpy().T @ np.ones((8, 2)),
                               rtol=1e-5)
