"""Zero-loss streams (ISSUE 17): deterministic stream resurrection on
replica death + live stream migration.

Both recovery paths share one mechanism — the CONTINUATION JOIN: an
engine admits a request whose transcript is already partially generated,
prefills prompt+observed through the ordinary chunk-bucket programs,
fast-forwards the per-request PRNG key chain by len(observed) draws, and
resumes decode at the right position. The continued trajectory is
bit-identical to the uninterrupted run for greedy AND sampled requests.

Covered here: engine-level join equivalence (mixed greedy/sampled
batch), continuation validation and pricing, the CRC-stamped
continuation record, export_stream, router resurrection certificates
(two-run injected-twin + uninterrupted-reference equality),
ResurrectionFailedError, the deadline-remainder stall regression, live
migration (zero dropped/duplicated tokens while a neighbor slot keeps
decoding), and the mid-migration death fallback.
"""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
from paddle_tpu.serving import (
    ContinuousBatchingEngine,
    Request,
    RequestFailedError,
    ResurrectionFailedError,
    ServingRouter,
    ServingServer,
    make_continuation_record,
    verify_continuation_record,
)

VOCAB = 32


def _tiny_model():
    paddle.seed(0)
    cfg = gpt_config("gpt2-small", vocab_size=VOCAB, hidden_size=16,
                     num_layers=1, num_attention_heads=2,
                     max_position_embeddings=64, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    m = GPTForPretraining(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _tiny_model()


def _prompt(n=4, seed=0):
    return np.random.default_rng(seed).integers(0, VOCAB, (n,)).tolist()


def _engine(model, n_slots=2, **kw):
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("prefill_buckets", [8])
    kw.setdefault("max_queue", 16)
    return ContinuousBatchingEngine(model, n_slots=n_slots, **kw)


def _run_engine(model, reqs, n_slots=4):
    """Submit ``reqs`` to a fresh engine, run to completion, return the
    per-request transcripts."""
    eng = _engine(model, n_slots=n_slots)
    stop = threading.Event()
    t = threading.Thread(target=eng.serve_forever, args=(stop,),
                         daemon=True)
    t.start()
    try:
        for r in reqs:
            eng.submit(r)
        for r in reqs:
            r.wait(120)
            assert r.state == Request.DONE, (r.state, r.error)
    finally:
        stop.set()
        t.join(30)
    return [list(r.tokens) for r in reqs]


def _server(model, n_slots=1, throttle_s=None, **kw):
    eng = _engine(model, n_slots=n_slots, **kw)
    if throttle_s:
        # slow decode so a stream is still in flight when the test acts
        # on it (the engine generates independently of router polls)
        orig = eng.step_once
        eng.step_once = lambda o=orig: (time.sleep(throttle_s), o())[1]
    return ServingServer(eng).start()


# =====================================================================
# engine level: the continuation join itself
# =====================================================================
class TestContinuationJoin:
    def _specs(self):
        # per-row mixed greedy/sampled batch: the certificate must hold
        # for every sampling mode side by side in the same engine
        return [dict(max_new_tokens=16),
                dict(max_new_tokens=16, temperature=0.9, seed=7),
                dict(max_new_tokens=12, temperature=0.7, top_k=8, seed=11),
                dict(max_new_tokens=12, temperature=1.1, top_p=0.9,
                     seed=13)]

    def test_join_bit_identical_mixed_batch(self, model):
        """Uninterrupted reference vs continuation joins cut at several
        points, all rows running CONCURRENTLY in one engine: every
        continued transcript equals its uninterrupted twin bit for bit —
        greedy, temperature, top-k and top-p rows alike."""
        specs = self._specs()
        prompt = _prompt()
        refs = _run_engine(model,
                           [Request(prompt, **s) for s in specs])
        for cut in (1, 5):
            cont = _run_engine(model, [
                Request(prompt, observed_tokens=ref[:cut], **s)
                for s, ref in zip(specs, refs)])
            assert cont == refs, f"cut={cut}"

    def test_terminal_continuation_completes_without_prefill(self, model):
        """An observed transcript that already hit max_new_tokens (or
        eos) has nothing left to generate: submit() settles it DONE
        immediately — no slot, no prefill, poll/stream just replay."""
        prompt = _prompt()
        [ref] = _run_engine(model, [Request(prompt, max_new_tokens=8)])
        eng = _engine(model)
        req = eng.submit(Request(prompt, max_new_tokens=8,
                                 observed_tokens=ref))
        assert req.state == Request.DONE  # engine loop never ran
        assert list(req.tokens) == ref
        # eos-terminal: same short-circuit
        req = eng.submit(Request(prompt, max_new_tokens=8,
                                 eos_token_id=ref[2],
                                 observed_tokens=ref[:3]))
        assert req.state == Request.DONE
        assert list(req.tokens) == ref[:3]

    def test_continuation_validation(self):
        prompt = _prompt()
        # the observed log can never legitimately exceed the generation
        # budget
        with pytest.raises(ValueError, match="max_new_tokens"):
            Request(prompt, max_new_tokens=4, observed_tokens=[1] * 5)
        # a sampled continuation without a pinned seed cannot reproduce
        # the dead replica's key chain
        with pytest.raises(ValueError, match="seed"):
            Request(prompt, max_new_tokens=8, temperature=0.8,
                    observed_tokens=[1, 2])
        # join math: prompt + observed[:-1] is what prefill runs over
        req = Request(prompt, max_new_tokens=8, observed_tokens=[9, 8, 7])
        assert req.prefill_len == len(prompt) + 2
        assert req.prefill_ids().tolist() == prompt + [9, 8]
        assert list(req.tokens) == [9, 8, 7]  # pre-populated for replay

    def test_fast_forward_key_matches_manual_chain(self):
        import jax

        from paddle_tpu.models.generation import fast_forward_key

        key = jax.random.PRNGKey(7)
        manual = key
        for _ in range(5):
            manual = jax.random.split(manual)[0]
        assert np.array_equal(np.asarray(fast_forward_key(key, 5)),
                              np.asarray(manual))
        assert np.array_equal(np.asarray(fast_forward_key(key, 0)),
                              np.asarray(key))
        with pytest.raises(ValueError):
            fast_forward_key(key, -1)


class TestContinuationRecord:
    def _record(self):
        req = Request(_prompt(), max_new_tokens=8, temperature=0.9,
                      seed=3, observed_tokens=[4, 5])
        return make_continuation_record(req, deadline_remaining=1.5)

    def test_roundtrip(self):
        rec = self._record()
        out = verify_continuation_record(rec)
        assert out["tokens"] == [4, 5]
        assert out["seed"] == 3
        assert out["deadline_remaining"] == 1.5

    def test_crc_rejects_tampering(self):
        rec = self._record()
        rec["tokens"] = [4, 6]  # one flipped token
        with pytest.raises(ValueError, match="CRC"):
            verify_continuation_record(rec)

    def test_rejects_wrong_shape(self):
        with pytest.raises(ValueError):
            verify_continuation_record({"kind": "nonsense"})
        rec = self._record()
        del rec["seed"]
        with pytest.raises(ValueError):
            verify_continuation_record(rec)


class TestExportStream:
    def test_export_frees_slot_and_settles_migrated(self, model):
        from paddle_tpu.serving import MIGRATED_ERROR_TYPE

        eng = _engine(model, n_slots=1)
        stop = threading.Event()
        t = threading.Thread(target=eng.serve_forever, args=(stop,),
                             daemon=True)
        t.start()
        try:
            req = eng.submit(Request(_prompt(), max_new_tokens=24,
                                     temperature=0.9, seed=5))
            deadline = time.perf_counter() + 30
            while len(req.tokens) < 3:
                assert time.perf_counter() < deadline
                time.sleep(0.005)
            rec = eng.export_stream(req.request_id)
            verify_continuation_record(rec)
            assert rec["tokens"] == list(req.tokens)
            assert rec["seed"] == 5 and rec["temperature"] == 0.9
            # the source half settles with the typed "moved" verdict and
            # the slot frees for new work
            assert req.state == Request.FAILED
            assert req.error_type == MIGRATED_ERROR_TYPE
            assert eng.metrics.snapshot()["slot_occupancy"]["active"] == 0
            # importing the record elsewhere resumes the identical run
            cont = _run_engine(model, [Request(
                rec["prompt"], observed_tokens=rec["tokens"],
                max_new_tokens=rec["max_new_tokens"],
                temperature=rec["temperature"], seed=rec["seed"])])
            [ref] = _run_engine(model, [Request(
                _prompt(), max_new_tokens=24, temperature=0.9, seed=5)])
            assert cont == [ref]
        finally:
            stop.set()
            t.join(30)

    def test_export_unknown_or_queued_raises(self, model):
        eng = _engine(model)
        with pytest.raises(KeyError):
            eng.export_stream("no-such-id")


# =====================================================================
# admission gate: continuation pricing (satellite 1)
# =====================================================================
class TestContinuationAdmission:
    def test_gate_prices_join_not_bare_prompt(self, model):
        from paddle_tpu.serving import AdmissionGate

        eng = _engine(model, prefill_buckets=[4, 8], max_seq_len=40)
        gate = AdmissionGate(eng, 1 << 40)
        prompt = _prompt(n=3)
        bare = gate.check(Request(prompt, max_new_tokens=20))
        join = gate.check(Request(prompt, max_new_tokens=20,
                                  observed_tokens=list(range(6))))
        # join length 3+5=8 lands in the 8-bucket, the bare prompt in 4:
        # the gate prices what prefill will actually run over
        assert bare["bucket"] == 4
        assert join["bucket"] == 8
        assert (join["predicted_peak_hbm_bytes"]
                > bare["predicted_peak_hbm_bytes"])

    def test_pages_needed_nets_radix_resident_join(self, model):
        """A re-homed stream whose prompt prefix is radix-resident on the
        survivor is nearly free page-wise: pages_needed discounts the
        shared pages against the JOIN sequence."""
        eng = _engine(model, page_size=4, max_seq_len=32)
        prompt = _prompt(n=8)
        cold = eng.pages_needed(Request(prompt, max_new_tokens=8,
                                        observed_tokens=[1, 2, 3, 4, 5]))
        # make the join's first pages resident (as a prior request's
        # prefill would have): 2 pages cover the 8-token prompt
        eng._radix.insert(np.asarray(prompt, np.int32),
                          eng._pool.alloc(2))
        warm = eng.pages_needed(Request(prompt, max_new_tokens=8,
                                        observed_tokens=[1, 2, 3, 4, 5]))
        assert warm == cold - 2


# =====================================================================
# router level: resurrection
# =====================================================================
def _routed_pair(model, n_slots=1, throttle_s=None):
    servers = {s.addr: s
               for s in (_server(model, n_slots=n_slots,
                                 throttle_s=throttle_s),
                         _server(model, n_slots=n_slots,
                                 throttle_s=throttle_s))}
    router = ServingRouter(list(servers), health_interval_s=0.1,
                           cooldown_s=30.0, request_timeout=5.0)
    return servers, router


def _kill_all(servers):
    for s in servers.values():
        try:
            s.kill()
        except Exception:
            pass


def _warm(router, n=2, prompt=None):
    for rr in [router.submit(prompt or _prompt(), max_new_tokens=2)
               for _ in range(n)]:
        router.wait(rr, timeout=120)
    router.check_health()


class TestResurrection:
    def _run_sampled_scenario(self, model):
        """Kill the replica mid-SAMPLED-stream at a deterministic tick;
        returns (fired_log, transcript, resurrections)."""
        from paddle_tpu.resilience import FaultSchedule

        servers, router = _routed_pair(model)
        try:
            with router:
                router.check_health()
                _warm(router)
                rr = router.submit(_prompt(), max_new_tokens=24,
                                   temperature=0.9, seed=21)
                victim = rr.replica_addr
                deadline = time.perf_counter() + 30
                while not rr.tokens:
                    router.poll(rr)
                    assert time.perf_counter() < deadline
                    time.sleep(0.005)
                # arm as soon as generation visibly started: the victim
                # dies at its NEXT productive tick, well inside the
                # 24-token run
                sched = FaultSchedule(seed=9).add(
                    "replica.tick", "kill", at=1,
                    match={"replica": victim})
                with sched:
                    out = router.wait(rr, timeout=120)
                assert out["status"] == Request.DONE, rr.error
                assert rr.replica_addr != victim
                log = sched.fired_log()
                for e in log:
                    if e["labels"].get("replica") == victim:
                        e["labels"]["replica"] = "victim"
                return (log, list(rr.tokens),
                        router.snapshot()["resurrections"])
        finally:
            _kill_all(servers)

    def test_sampled_resurrection_bit_identical_two_run(self, model):
        """The acceptance certificate: a SAMPLED stream killed
        mid-generation resumes token-for-token identical to the
        uninterrupted run, and two injected-twin replays produce the
        identical fired log and transcript."""
        # uninterrupted reference (same spec, no chaos, single replica)
        [ref] = _run_engine(model, [Request(
            _prompt(), max_new_tokens=24, temperature=0.9, seed=21)])
        run_a = self._run_sampled_scenario(model)
        run_b = self._run_sampled_scenario(model)
        assert run_a == run_b  # fired log + transcript, bit for bit
        log, tokens, resurrections = run_a
        assert log == [{"point": "replica.tick", "kind": "kill",
                        "count": 1, "labels": {"replica": "victim"}}]
        assert tokens == ref  # continuation == uninterrupted, bitwise
        assert resurrections == 1

    def test_router_mints_seed_for_sampled_requests(self, model):
        """A sampled request submitted WITHOUT a seed must still be
        resurrectable: the router pins a deterministic seed at the entry
        point (the engine's fallback seed would die with the replica)."""
        servers, router = _routed_pair(model)
        try:
            with router:
                router.check_health()
                rr = router.submit(_prompt(), max_new_tokens=4,
                                   temperature=0.9)
                assert rr.spec["seed"] is not None
                greedy = router.submit(_prompt(), max_new_tokens=4)
                assert greedy.spec.get("seed") is None  # greedy untouched
                router.wait(rr, timeout=120)
                router.wait(greedy, timeout=120)
        finally:
            _kill_all(servers)

    def test_no_survivor_raises_resurrection_failed(self, model):
        """Single replica, stream started, replica dies: the typed
        terminal verdict is ResurrectionFailedError — live AND on settled
        replay — never a silent retry loop."""
        srv = _server(model)
        router = ServingRouter([srv.addr], health_interval_s=5.0,
                               request_timeout=5.0, resubmit_retries=0)
        try:
            with router:
                router.check_health()
                rr = router.submit(_prompt(), max_new_tokens=24)
                deadline = time.perf_counter() + 30
                while len(rr.tokens) < 2:
                    router.poll(rr)
                    assert time.perf_counter() < deadline
                    time.sleep(0.01)
                srv.kill()
                with pytest.raises(ResurrectionFailedError,
                                   match="no survivor"):
                    list(router.stream(rr))
                assert rr.state == Request.FAILED
                assert rr.failure_kind == "resurrection"
                # the observed log survives for salvage
                assert len(rr.tokens) >= 2
                # settled replay keeps the type
                with pytest.raises(ResurrectionFailedError):
                    list(router.stream(rr))
                snap = router.snapshot()
                assert snap["inflight_failures"] == 1
                assert snap["resurrections"] == 0
        finally:
            try:
                srv.kill()
            except Exception:
                pass

    def test_resurrection_stall_burns_the_same_deadline(self, model):
        """Deadline-remainder regression (satellite 3): time burned on
        the dead replica AND in the recovery machinery is deducted from
        the request's ONE deadline — an injected stall at the
        resurrection seam longer than the remainder must surface the
        typed deadline verdict, not grant the continuation a fresh
        clock."""
        from paddle_tpu.resilience import FaultSchedule

        servers, router = _routed_pair(model)
        try:
            with router:
                router.check_health()
                _warm(router)
                rr = router.submit(_prompt(), max_new_tokens=24,
                                   deadline_s=2.0)
                victim = rr.replica_addr
                deadline = time.perf_counter() + 30
                while len(rr.tokens) < 2:
                    router.poll(rr)
                    assert time.perf_counter() < deadline
                    time.sleep(0.01)
                sched = FaultSchedule(seed=3).add(
                    "router.resurrect", "stall", at=1, seconds=2.5)
                with sched:
                    servers[victim].kill()
                    with pytest.raises(RequestFailedError,
                                       match="[Dd]eadline"):
                        for _ in router.stream(rr):
                            pass
                assert rr.state == Request.FAILED
                assert rr.failure_kind == "request"
                assert sched.fired_log()[0]["point"] == "router.resurrect"
        finally:
            _kill_all(servers)

    def test_observed_log_capped_at_max_new_tokens(self):
        """Satellite 2: the router-side transcript can never grow past
        the generation budget, whatever a racing stream replays."""
        from paddle_tpu.serving import RoutedRequest

        rr = RoutedRequest(_prompt(), max_new_tokens=4)
        rr._observe(list(range(10)))
        assert rr.tokens == [0, 1, 2, 3]
        rr._observe(list(range(8)))  # longer replay: still capped
        assert rr.tokens == [0, 1, 2, 3]


# =====================================================================
# router level: live migration
# =====================================================================
class TestLiveMigration:
    def test_migration_zero_drop_zero_dup_neighbor_decoding(self, model):
        """Drain one stream off a replica mid-generation while a
        NEIGHBOR slot on the target keeps decoding: the migrated
        transcript equals the uninterrupted reference exactly (zero
        dropped, zero duplicated) and the neighbor is undisturbed."""
        [ref] = _run_engine(model, [Request(
            _prompt(), max_new_tokens=20, temperature=0.8, seed=17)])
        [ref_n] = _run_engine(model, [Request(
            _prompt(n=5, seed=2), max_new_tokens=20)])
        servers, router = _routed_pair(model, n_slots=2, throttle_s=0.04)
        try:
            with router:
                router.check_health()
                _warm(router)
                rr = router.submit(_prompt(), max_new_tokens=20,
                                   temperature=0.8, seed=17)
                src = rr.replica_addr
                dst = next(a for a in servers if a != src)
                # neighbor decodes on the TARGET throughout
                neighbor = None
                while neighbor is None or neighbor.replica_addr != dst:
                    neighbor = router.submit(_prompt(n=5, seed=2),
                                             max_new_tokens=20)
                got = []
                t = threading.Thread(
                    target=lambda: got.extend(router.stream(rr)))
                t.start()
                deadline = time.perf_counter() + 30
                while len(got) < 5:
                    assert time.perf_counter() < deadline
                    time.sleep(0.005)
                router.migrate(rr, dst)
                t.join(120)
                assert not t.is_alive()
                assert got == ref  # bitwise: no drop, no dup, no fork
                assert rr.replica_addr == dst
                assert rr.state == Request.DONE
                router.wait(neighbor, timeout=120)
                assert list(neighbor.tokens) == ref_n
                snap = router.snapshot()
                assert snap["migrations"] == 1
                assert snap["migration_fallbacks"] == 0
        finally:
            _kill_all(servers)

    def test_mid_migration_death_falls_back_to_resurrection(self, model):
        """The import hop dying mid-migration must NOT lose the stream:
        the source already exported (slot freed), so the router re-homes
        the continuation exactly like a death resurrection."""
        from paddle_tpu.resilience import FaultSchedule

        [ref] = _run_engine(model, [Request(
            _prompt(), max_new_tokens=16, temperature=0.9, seed=23)])
        servers, router = _routed_pair(model, throttle_s=0.04)
        try:
            with router:
                router.check_health()
                _warm(router)
                rr = router.submit(_prompt(), max_new_tokens=16,
                                   temperature=0.9, seed=23)
                src = rr.replica_addr
                dst = next(a for a in servers if a != src)
                deadline = time.perf_counter() + 30
                while len(rr.tokens) < 3:
                    router.poll(rr)
                    assert time.perf_counter() < deadline
                    time.sleep(0.01)
                sched = FaultSchedule(seed=7).add(
                    "router.transport", "raise", at=1,
                    match={"path": "/admin/migrate_import"})
                with sched:
                    router.migrate(rr, dst)  # falls back, does not raise
                assert [f["labels"]["path"] for f in sched.fired_log()] \
                    == ["/admin/migrate_import"]
                out = router.wait(rr, timeout=120)
                assert out["status"] == Request.DONE, rr.error
                assert list(rr.tokens) == ref  # still bit-identical
                snap = router.snapshot()
                assert snap["migrations"] == 0
                assert snap["migration_fallbacks"] == 1
                assert snap["resurrections"] == 1
        finally:
            _kill_all(servers)

    def test_poll_of_exported_source_is_transient(self, model):
        """The poll/export race: a poll hitting the SOURCE after the
        export but before the router flips routing sees the MigratedError
        verdict and must report RUNNING (moved), never settle the
        stream."""
        servers, router = _routed_pair(model)
        try:
            with router:
                router.check_health()
                rr = router.submit(_prompt(), max_new_tokens=24)
                deadline = time.perf_counter() + 30
                while len(rr.tokens) < 2:
                    router.poll(rr)
                    assert time.perf_counter() < deadline
                    time.sleep(0.01)
                src = rr.replica_addr
                dst = next(a for a in servers if a != src)
                # simulate the mid-migration window: exported, not yet
                # flipped
                rec = servers[src].engine.export_stream(rr.remote_id)
                out = router.poll(rr)
                assert out["status"] == Request.RUNNING
                assert not rr.done
                # finish the flip by hand (what migrate() does)
                rr.remote_id = router.replicas[dst].client.migrate_import(
                    rec)
                rr.replica_addr = dst
                out = router.wait(rr, timeout=120)
                assert out["status"] == Request.DONE
                assert len(rr.tokens) == 24
        finally:
            _kill_all(servers)

    def test_migrate_validation(self, model):
        servers, router = _routed_pair(model)
        try:
            with router:
                router.check_health()
                rr = router.submit(_prompt(), max_new_tokens=4)
                with pytest.raises(KeyError, match="unknown replica"):
                    router.migrate(rr, "127.0.0.1:1")
                home = rr.replica_addr
                router.migrate(rr, home)  # same-home: a no-op
                assert router.snapshot()["migrations"] == 0
                router.wait(rr, timeout=120)
                with pytest.raises(ValueError, match="settled"):
                    router.migrate(rr, home)
        finally:
            _kill_all(servers)
