"""hapi Model.fit under the launcher (2-proc CPU) + the dataset tail
(VERDICT r3 do#9; reference python/paddle/tests/dist_hapi_mnist_dynamic.py,
vision/datasets/{folder,flowers,voc2012}.py)."""
import io
import json
import os
import subprocess
import sys
import tarfile
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DIST_HAPI_RUNNER = textwrap.dedent("""
    import json, os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.env import ParallelEnv
    from paddle_tpu.hapi.model import Model
    from paddle_tpu.io import DataLoader
    from paddle_tpu.io.sampler import DistributedBatchSampler
    from paddle_tpu.optimizer.optimizers import Adam
    from paddle_tpu.vision.datasets import FakeData

    out_dir = sys.argv[1]
    env = ParallelEnv()
    paddle.seed(0)

    ds = FakeData(size=32, image_shape=(8,), num_classes=4, seed=7)
    sampler = DistributedBatchSampler(ds, batch_size=4,
                                      num_replicas=env.world_size,
                                      rank=env.rank, shuffle=False)
    seen = [i for batch in sampler for i in batch]
    loader = DataLoader(ds, batch_sampler=sampler)

    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    model = Model(net)
    model.prepare(Adam(learning_rate=0.01, parameters=net.parameters()),
                  loss=lambda out, y: nn.functional.cross_entropy(out, y))
    hist = model.fit(loader, epochs=2, verbose=0)
    evals = model.evaluate(loader, verbose=0)
    with open(os.path.join(out_dir, f"rank{env.rank}.json"), "w") as f:
        json.dump({"rank": env.rank, "world": env.world_size,
                   "indices": seen, "loss": evals["loss"]}, f)
""")


def _launch(script, nproc, args=(), timeout=240):
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node", str(nproc), str(script), *args]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                          text=True, timeout=timeout)


def test_dist_hapi_fit_under_launcher(tmp_path):
    """Model.fit runs under 2 launched processes; DistributedBatchSampler
    shards the dataset disjointly and both ranks train + evaluate."""
    script = tmp_path / "runner.py"
    script.write_text(DIST_HAPI_RUNNER)
    out = tmp_path / "out"
    out.mkdir()
    r = _launch(script, 2, args=(str(out),))
    assert r.returncode == 0, r.stderr[-3000:]
    recs = []
    for rank in (0, 1):
        with open(out / f"rank{rank}.json") as f:
            recs.append(json.load(f))
    assert recs[0]["world"] == recs[1]["world"] == 2
    s0, s1 = set(recs[0]["indices"]), set(recs[1]["indices"])
    assert not (s0 & s1), "ranks must see disjoint shards"
    assert len(s0) + len(s1) == 32
    for rec in recs:
        assert np.isfinite(rec["loss"][0] if isinstance(rec["loss"], list)
                           else rec["loss"])


# ---------------------------------------------------------------------------
# dataset tail
# ---------------------------------------------------------------------------

def _write_jpg(path, color, size=(8, 8)):
    from PIL import Image

    Image.new("RGB", size, color).save(path)


def test_dataset_folder_and_image_folder(tmp_path):
    for ci, cls in enumerate(["cats", "dogs"]):
        d = tmp_path / "root" / cls
        d.mkdir(parents=True)
        for i in range(3):
            _write_jpg(d / f"{i}.jpg", (ci * 100, 0, 0))
    from paddle_tpu.vision.datasets import DatasetFolder, ImageFolder

    ds = DatasetFolder(str(tmp_path / "root"))
    assert ds.classes == ["cats", "dogs"]
    assert len(ds) == 6
    img, target = ds[0]
    assert target == 0 and np.asarray(img).shape == (8, 8, 3)
    img, target = ds[5]
    assert target == 1

    flat = ImageFolder(str(tmp_path / "root"))
    assert len(flat) == 6
    (img,) = flat[2]
    assert np.asarray(img).shape == (8, 8, 3)

    # transform applies
    ds2 = DatasetFolder(str(tmp_path / "root"),
                        transform=lambda im: np.asarray(im, np.float32) / 255)
    img, _ = ds2[0]
    assert img.dtype == np.float32 and img.max() <= 1.0


def test_flowers_dataset(tmp_path):
    import scipy.io as scio

    jpg = tmp_path / "flowers" / "jpg"
    jpg.mkdir(parents=True)
    for i in range(1, 7):
        _write_jpg(jpg / ("image_%05d.jpg" % i), (i * 20, 0, 0))
    labels = np.arange(1, 7)[None]  # 1-based class labels
    scio.savemat(tmp_path / "imagelabels.mat", {"labels": labels})
    scio.savemat(tmp_path / "setid.mat", {
        "trnid": np.asarray([[1, 2, 3, 4]]),
        "valid": np.asarray([[5]]),
        "tstid": np.asarray([[6]]),
    })
    from paddle_tpu.vision.datasets import Flowers

    # parity quirk (flowers.py:37): the reference SWAPS trnid/tstid — the
    # 'train' mode reads tstid and 'test' reads trnid
    tr = Flowers(str(tmp_path / "flowers"), str(tmp_path / "imagelabels.mat"),
                 str(tmp_path / "setid.mat"), mode="train")
    assert len(tr) == 1 and tr[0][1].tolist() == [6]
    te = Flowers(str(tmp_path / "flowers"), str(tmp_path / "imagelabels.mat"),
                 str(tmp_path / "setid.mat"), mode="test")
    assert len(te) == 4
    img, lbl = te[1]
    assert img.shape == (8, 8, 3) and lbl.tolist() == [2]
    va = Flowers(str(tmp_path / "flowers"), str(tmp_path / "imagelabels.mat"),
                 str(tmp_path / "setid.mat"), mode="valid")
    assert len(va) == 1 and va[0][1].tolist() == [5]


def test_voc2012_dataset_from_tar(tmp_path):
    from PIL import Image

    base = "VOCdevkit/VOC2012"
    names = ["2007_000001", "2007_000002"]
    tar_path = tmp_path / "voc.tar"
    with tarfile.open(tar_path, "w") as t:
        def add(rel, data):
            info = tarfile.TarInfo(rel)
            info.size = len(data)
            t.addfile(info, io.BytesIO(data))

        add(f"{base}/ImageSets/Segmentation/trainval.txt",
            ("\n".join(names) + "\n").encode())
        add(f"{base}/ImageSets/Segmentation/train.txt",
            (names[0] + "\n").encode())
        add(f"{base}/ImageSets/Segmentation/val.txt",
            (names[1] + "\n").encode())
        for i, n in enumerate(names):
            buf = io.BytesIO()
            Image.new("RGB", (6, 4), (i * 50, 0, 0)).save(buf, format="JPEG")
            add(f"{base}/JPEGImages/{n}.jpg", buf.getvalue())
            buf = io.BytesIO()
            Image.fromarray(np.full((4, 6), i, np.uint8), "L").save(
                buf, format="PNG")
            add(f"{base}/SegmentationClass/{n}.png", buf.getvalue())

    from paddle_tpu.vision.datasets import VOC2012

    ds = VOC2012(str(tar_path), mode="train")
    assert len(ds) == 2
    img, lbl = ds[1]
    assert img.shape == (4, 6, 3) and lbl.shape == (4, 6)
    assert (lbl == 1).all()
    va = VOC2012(str(tar_path), mode="valid")
    assert len(va) == 1
