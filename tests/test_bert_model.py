"""BERT family tests (BASELINE config #3: BERT-base pretrain, DP allreduce).
Mirrors tests/test_gpt_model.py's strategy: tiny configs, shape checks,
loss-drop convergence, and a dp-sharded ParallelTrainer step on the
8-virtual-device mesh."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.bert import (
    BertForPretraining,
    BertModel,
    BertPretrainingCriterion,
    bert_config,
)


def _np(t):
    return np.asarray(t._data)


def tiny_cfg(**kw):
    base = dict(vocab_size=128, hidden_size=32, num_layers=2,
                num_attention_heads=4, max_position_embeddings=32,
                type_vocab_size=2, hidden_dropout_prob=0.0,
                attention_dropout_prob=0.0)
    base.update(kw)
    return bert_config("bert-base", **base)


rng = np.random.default_rng(0)


class TestBertModel:
    def test_forward_shapes(self):
        paddle.seed(0)
        m = BertModel(tiny_cfg())
        ids = paddle.to_tensor(rng.integers(0, 128, (2, 16)).astype("int32"))
        tt = paddle.to_tensor(np.zeros((2, 16), "int32"))
        seq, pooled = m(ids, tt)
        assert tuple(seq.shape) == (2, 16, 32)
        assert tuple(pooled.shape) == (2, 32)

    def test_attention_mask_blocks_pad(self):
        """Padding positions must not influence un-padded outputs."""
        paddle.seed(0)
        m = BertModel(tiny_cfg())
        m.eval()
        ids = rng.integers(0, 128, (1, 8)).astype("int32")
        mask = np.ones((1, 8), "float32")
        mask[0, 6:] = 0.0
        seq1, _ = m(paddle.to_tensor(ids), attention_mask=paddle.to_tensor(mask))
        ids2 = ids.copy()
        ids2[0, 6:] = 77  # change only the padded tokens
        seq2, _ = m(paddle.to_tensor(ids2), attention_mask=paddle.to_tensor(mask))
        np.testing.assert_allclose(_np(seq1)[0, :6], _np(seq2)[0, :6],
                                   rtol=1e-4, atol=1e-5)

    def test_bidirectional_not_causal(self):
        """Changing a LATER token must change an EARLIER position's output
        (unlike GPT's causal attention)."""
        paddle.seed(0)
        m = BertModel(tiny_cfg())
        m.eval()
        ids = rng.integers(0, 128, (1, 8)).astype("int32")
        seq1, _ = m(paddle.to_tensor(ids))
        ids2 = ids.copy()
        ids2[0, -1] = (ids2[0, -1] + 1) % 128
        seq2, _ = m(paddle.to_tensor(ids2))
        assert np.abs(_np(seq1)[0, 0] - _np(seq2)[0, 0]).max() > 1e-6


class TestBertPretraining:
    def test_heads_and_criterion(self):
        paddle.seed(0)
        model = BertForPretraining(tiny_cfg())
        crit = BertPretrainingCriterion()
        ids = paddle.to_tensor(rng.integers(0, 128, (2, 12)).astype("int32"))
        logits, nsp = model(ids)
        assert tuple(logits.shape) == (2, 12, 128)
        assert tuple(nsp.shape) == (2, 2)
        labels = np.full((2, 12), -100, "int32")
        labels[:, 3] = 7
        loss = crit(logits, paddle.to_tensor(labels), nsp,
                    paddle.to_tensor(np.array([0, 1], "int32")))
        assert np.isfinite(float(_np(loss)))

    def test_masked_positions_only(self):
        """Loss must ignore -100 positions: logits at unmasked positions
        should receive zero gradient through the MLM term."""
        paddle.seed(0)
        crit = BertPretrainingCriterion()
        logits = paddle.to_tensor(
            rng.standard_normal((1, 4, 16)).astype("float32"))
        logits.stop_gradient = False
        labels = np.full((1, 4), -100, "int32")
        labels[0, 1] = 5
        loss = crit(logits, paddle.to_tensor(labels))
        loss.backward()
        g = _np(logits.grad)
        assert np.abs(g[0, 1]).sum() > 0
        assert np.abs(g[0, [0, 2, 3]]).max() < 1e-8

    def test_mlm_converges(self):
        """Tiny overfit: model learns to fill one masked token."""
        import paddle_tpu.optimizer as opt

        paddle.seed(1)
        cfg = tiny_cfg(num_layers=1)
        model = BertForPretraining(cfg)
        crit = BertPretrainingCriterion()
        adam = opt.Adam(learning_rate=1e-3, parameters=model.parameters())
        ids = rng.integers(1, 128, (4, 8)).astype("int32")
        masked = ids.copy()
        masked[:, 2] = 0  # [MASK]
        labels = np.full((4, 8), -100, "int32")
        labels[:, 2] = ids[:, 2]
        first = last = None
        for _ in range(60):
            logits, _ = model(paddle.to_tensor(masked))
            loss = crit(logits, paddle.to_tensor(labels))
            loss.backward()
            adam.step()
            adam.clear_grad()
            v = float(_np(loss))
            first = v if first is None else first
            last = v
        assert last < 0.5 * first, (first, last)

    def test_tied_decoder_weight(self):
        """MLM decoder must share the embedding parameter (one tensor)."""
        model = BertForPretraining(tiny_cfg())
        emb_w = model.bert.embeddings.word_embeddings.weight
        names = [n for n, p in model.named_parameters() if p is emb_w]
        assert len(names) == 1  # appears once; the head reuses it


class TestBertDP:
    def test_dp_trainer_step(self):
        """BASELINE #3 shape: dp-sharded batch over the 8-device mesh."""
        from paddle_tpu.distributed.env import clear_mesh, init_mesh
        from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
        import paddle_tpu.optimizer as opt

        paddle.seed(0)
        init_mesh({"dp": 8})
        try:
            cfg = tiny_cfg()
            model = BertForPretraining(cfg)
            crit = BertPretrainingCriterion()
            adam = opt.AdamW(learning_rate=1e-4,
                             parameters=model.parameters())

            def loss_fn(outputs, labels):
                logits, nsp = outputs
                return crit(logits, labels)

            trainer = ParallelTrainer(model, loss_fn, adam, dp_axis="dp")
            ids = rng.integers(0, 128, (16, 8)).astype("int32")
            labels = np.full((16, 8), -100, "int32")
            labels[:, 1] = ids[:, 1]
            l1 = trainer.step(paddle.to_tensor(ids), paddle.to_tensor(labels))
            l2 = trainer.step(paddle.to_tensor(ids), paddle.to_tensor(labels))
            assert np.isfinite(float(_np(l1))) and np.isfinite(float(_np(l2)))
        finally:
            clear_mesh()


class TestBertPipeline:
    """BERT encoder stack through the generic PipelineLayer pipeline
    (VERDICT r2 missing #1 done-criterion): embeddings run as the
    pp-replicated prefix edge, the 8 uniform encoder blocks rotate over
    'pp', a linear head + MSE close the loss."""

    def test_bert_encoder_pipeline_pp4_matches_dense(self):
        import paddle_tpu.distributed as dist
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.meta_parallel.pipeline_schedule import (
            build_pipeline_layer_step)
        from paddle_tpu.distributed.meta_parallel.pp_layers import PipelineLayer
        from paddle_tpu.models.bert import BertEmbeddings, BertLayer
        from paddle_tpu.optimizer.optimizers import SGD

        dist.init_mesh({"pp": 4})
        try:
            paddle.seed(0)
            cfg = tiny_cfg(num_layers=8)
            emb = BertEmbeddings(cfg)
            blocks = [BertLayer(cfg) for _ in range(8)]
            head = nn.Linear(cfg.hidden_size, 8)

            def mse(out, y):
                d = out - y
                return (d * d).mean()

            pl = PipelineLayer([emb] + blocks + [head], num_stages=4,
                               loss_fn=mse)
            r = np.random.default_rng(13)
            x = r.integers(0, cfg.vocab_size, (4, 16)).astype("int32")
            y = r.standard_normal((4, 16, 8)).astype("float32")

            out = pl(paddle.to_tensor(x))
            d = _np(out) - y
            ref = float((d * d).mean())

            opt = SGD(learning_rate=0.05, parameters=pl.parameters())
            step = build_pipeline_layer_step(pl, opt, microbatches=2)
            # the embeddings landed in the pp-replicated prefix edge, the
            # 8 BertLayers are the rotating body
            assert len(step.pipe._prefix) == 1
            assert len(step.pipe._blocks) == 8
            loss = float(step(x, y))
            assert abs(loss - ref) < 1e-5, (loss, ref)
            losses = [float(step(x, y)) for _ in range(8)]
            assert losses[-1] < loss, (loss, losses)
        finally:
            dist.clear_mesh()
