"""Multi-process launcher harness (parity: the reference's
TestDistRunnerBase pattern — unittests/test_dist_base.py:60 forks trainer
subprocesses with the PADDLE_* env protocol and asserts 1-proc vs N-proc
parity; collective runner scripts test_collective_base.py style).

Here the parity assertion is on the data-parallel *gradient semantics*: two
launched ranks each compute grads on their half of the batch and dump them;
the parent averages the per-rank grads and checks exact agreement with the
single-process full-batch gradient (what the per-step allreduce/pmean
produces on the mesh)."""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RUNNER = textwrap.dedent("""
    import json, os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.env import ParallelEnv

    out_dir = sys.argv[1]
    env = ParallelEnv()
    # env protocol sanity (reference launch_utils.py:490-501 contract)
    contract = {
        "rank": env.rank,
        "world": env.world_size,
        "endpoint": os.environ.get("PADDLE_CURRENT_ENDPOINT", ""),
        "endpoints": os.environ.get("PADDLE_TRAINER_ENDPOINTS", ""),
    }

    paddle.seed(0)
    model = nn.Linear(4, 2)
    X = np.arange(32, dtype="float32").reshape(8, 4) / 10.0
    Y = np.ones((8, 2), dtype="float32")
    # each rank takes its contiguous shard of the global batch
    shard = 8 // env.world_size
    lo = env.rank * shard
    xb = paddle.to_tensor(X[lo:lo + shard])
    yb = paddle.to_tensor(Y[lo:lo + shard])
    loss = ((model(xb) - yb) ** 2).mean()
    loss.backward()
    grads = {n: np.asarray(p.grad._data).tolist()
             for n, p in model.named_parameters()}
    with open(os.path.join(out_dir, f"rank{env.rank}.json"), "w") as f:
        json.dump({"contract": contract, "grads": grads,
                   "loss": float(np.asarray(loss._data))}, f)
""")

FAILING_RUNNER = "import sys; sys.exit(3 if __import__('os').environ.get('PADDLE_TRAINER_ID') == '1' else 0)"


def _launch(script_path, nproc, extra_args=(), timeout=180):
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node", str(nproc), str(script_path), *extra_args]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                          text=True, timeout=timeout)


class TestLauncherContract:
    def test_two_proc_env_and_grad_parity(self, tmp_path):
        script = tmp_path / "runner.py"
        script.write_text(RUNNER)
        res = _launch(script, 2, (str(tmp_path),))
        assert res.returncode == 0, res.stdout + res.stderr

        r0 = json.loads((tmp_path / "rank0.json").read_text())
        r1 = json.loads((tmp_path / "rank1.json").read_text())
        # env protocol
        assert r0["contract"]["rank"] == 0 and r1["contract"]["rank"] == 1
        assert r0["contract"]["world"] == 2
        eps = r0["contract"]["endpoints"].split(",")
        assert len(eps) == 2 and r0["contract"]["endpoint"] == eps[0] \
            and r1["contract"]["endpoint"] == eps[1]

        # single-process full-batch reference
        import jax

        jax.config.update("jax_platforms", "cpu")
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn

        paddle.seed(0)
        model = nn.Linear(4, 2)
        X = np.arange(32, dtype="float32").reshape(8, 4) / 10.0
        Y = np.ones((8, 2), dtype="float32")
        loss = ((model(paddle.to_tensor(X)) - paddle.to_tensor(Y)) ** 2).mean()
        loss.backward()
        for n, p in model.named_parameters():
            avg = (np.asarray(r0["grads"][n]) + np.asarray(r1["grads"][n])) / 2
            np.testing.assert_allclose(avg, np.asarray(p.grad._data),
                                       rtol=1e-5, atol=1e-6,
                                       err_msg=f"grad mismatch for {n}")
        # mean loss parity too
        np.testing.assert_allclose((r0["loss"] + r1["loss"]) / 2,
                                   float(np.asarray(loss._data)), rtol=1e-5)

    def test_abnormal_exit_propagates(self, tmp_path):
        script = tmp_path / "bad.py"
        script.write_text(FAILING_RUNNER)
        res = _launch(script, 2)
        assert res.returncode != 0
