"""Mini end-to-end trainings (parity: the reference's unittests/book/ —
fit_a_line, recognize_digits, word2vec: small models that must CONVERGE,
asserting the whole stack end to end in both paradigms)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
import paddle_tpu.static as static


rng = np.random.default_rng(31)


def _np(t):
    return np.asarray(t._data)


class TestFitALine:
    """book/test_fit_a_line parity: linear regression to convergence."""

    def test_dygraph(self):
        paddle.seed(0)
        true_w = np.array([[2.0], [-3.4], [1.7], [0.5]], "float32")
        X = rng.standard_normal((256, 4)).astype("float32")
        Y = X @ true_w + 4.2
        model = nn.Linear(4, 1)
        sgd = opt.SGD(learning_rate=0.05, parameters=model.parameters())
        for _ in range(300):
            loss = F.mse_loss(model(paddle.to_tensor(X)), paddle.to_tensor(Y))
            loss.backward()
            sgd.step()
            sgd.clear_grad()
        assert float(_np(loss)) < 1e-3
        np.testing.assert_allclose(_np(model.weight), true_w, atol=0.05)
        np.testing.assert_allclose(_np(model.bias)[0], 4.2, atol=0.05)

    def test_static(self):
        """Same regression through the static Program/Executor paradigm."""
        paddle.seed(0)
        true_w = np.array([[1.5], [-2.0]], "float32")
        X = rng.standard_normal((128, 2)).astype("float32")
        Y = X @ true_w + 1.0
        try:
            paddle.enable_static()
            main = static.Program()
            startup = static.Program()
            with static.program_guard(main, startup):
                x = static.data("x", [None, 2], "float32")
                y = static.data("y", [None, 1], "float32")
                lin = nn.Linear(2, 1)
                pred = lin(x)
                loss = F.mse_loss(pred, y)
                sgd = opt.SGD(learning_rate=0.1)
                sgd.minimize(loss)
            exe = static.Executor()
            exe.run(startup)
            for _ in range(200):
                (lv,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
            assert float(lv) < 1e-2
        finally:
            paddle.disable_static()


class TestRecognizeDigits:
    """book/test_recognize_digits parity: softmax-regression + MLP converge
    on a separable synthetic 'digits' task."""

    def _data(self, n=512):
        labels = rng.integers(0, 10, n)
        # class-dependent mean + noise: linearly separable-ish
        centers = rng.standard_normal((10, 64)).astype("float32") * 2
        X = centers[labels] + 0.3 * rng.standard_normal((n, 64)).astype("float32")
        return X.astype("float32"), labels.astype("int64")

    def test_mlp_converges(self):
        paddle.seed(0)
        X, y = self._data()
        model = nn.Sequential(nn.Linear(64, 32), nn.ReLU(), nn.Linear(32, 10))
        adam = opt.Adam(learning_rate=1e-2, parameters=model.parameters())
        acc = 0.0
        for _ in range(100):
            logits = model(paddle.to_tensor(X))
            loss = F.cross_entropy(logits, paddle.to_tensor(y))
            loss.backward()
            adam.step()
            adam.clear_grad()
        pred = _np(logits).argmax(-1)
        acc = (pred == y).mean()
        assert acc > 0.95, acc


class TestWord2Vec:
    """book/test_word2vec parity: skip-gram-style embedding learning — the
    embedding of co-occurring tokens must end up closer than random pairs."""

    def test_embeddings_learn_cooccurrence(self):
        paddle.seed(0)
        vocab, dim = 20, 8
        # pairs: token 2i co-occurs with 2i+1
        centers = np.repeat(np.arange(0, vocab, 2), 50)
        contexts = centers + 1
        emb = nn.Embedding(vocab, dim)
        out = nn.Linear(dim, vocab)
        adam = opt.Adam(learning_rate=5e-2,
                        parameters=list(emb.parameters()) + list(out.parameters()))
        for _ in range(60):
            h = emb(paddle.to_tensor(centers.astype("int64")))
            logits = out(h)
            loss = F.cross_entropy(logits, paddle.to_tensor(contexts.astype("int64")))
            loss.backward()
            adam.step()
            adam.clear_grad()
        logits = _np(out(emb(paddle.to_tensor(centers.astype("int64")))))
        acc = (logits.argmax(-1) == contexts).mean()
        assert acc > 0.9, acc
