"""AMP: autocast O1/O2, GradScaler state machine, in-graph loss scaling.

Parity: reference AMP tests (test_amp_check_finite_and_scale_op.py,
test_update_loss_scaling_op.py, test_imperative_auto_mixed_precision.py).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import amp


def test_autocast_o1_white_black():
    x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    w = paddle.to_tensor(np.random.randn(8, 8).astype("float32"))
    with amp.auto_cast(level="O1", dtype="bfloat16"):
        y = paddle.matmul(x, w)       # white -> bf16
        s = paddle.nn.functional.softmax(y)  # black -> fp32
    assert str(y.dtype).endswith("bfloat16")
    assert str(s.dtype).endswith("float32")
    # outside the context nothing is cast
    y2 = paddle.matmul(x, w)
    assert str(y2.dtype).endswith("float32")


def test_autocast_grads_restore_param_dtype():
    x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    lin = nn.Linear(8, 2)
    with amp.auto_cast(level="O1", dtype="bfloat16"):
        out = lin(x)
        loss = out.sum()
    loss.backward()
    g = lin.weight.grad
    assert g is not None
    assert str(g._data.dtype if hasattr(g, "_data") else g.dtype).endswith("float32")


def test_autocast_o2():
    x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    with amp.auto_cast(level="O2", dtype="bfloat16"):
        y = x * 2.0 + 1.0  # gray op, O2 casts anyway
    assert str(y.dtype).endswith("bfloat16")


def test_decorate_o2_casts_params():
    lin = nn.Linear(8, 2)
    amp.decorate(lin, level="O2", dtype="bfloat16")
    assert str(lin.weight._data.dtype) == "bfloat16"


def test_grad_scaler_state_machine():
    sc = amp.GradScaler(init_loss_scaling=8.0, incr_ratio=2.0, decr_ratio=0.5,
                        incr_every_n_steps=2, decr_every_n_nan_or_inf=1)
    # two finite steps -> grow
    sc._found_inf = False; sc.update()
    assert sc.get_loss_scaling() == 8.0
    sc._found_inf = False; sc.update()
    assert sc.get_loss_scaling() == 16.0
    # one inf step -> shrink immediately
    sc._found_inf = True; sc.update()
    assert sc.get_loss_scaling() == 8.0
    # state dict round trip
    st = sc.state_dict()
    sc2 = amp.GradScaler()
    sc2.load_state_dict(st)
    assert sc2.get_loss_scaling() == 8.0


def test_grad_scaler_eager_step_skips_on_inf():
    from paddle_tpu.optimizer.optimizers import SGD

    lin = nn.Linear(4, 2)
    opt = SGD(learning_rate=0.1, parameters=lin.parameters())
    sc = amp.GradScaler(init_loss_scaling=4.0)
    w0 = np.asarray(lin.weight._data).copy()

    x = paddle.to_tensor(np.random.randn(2, 4).astype("float32"))
    loss = sc.scale(lin(x).sum())
    loss.backward()
    # poison a gradient with inf
    import jax.numpy as jnp
    lin.weight.grad = paddle.Tensor(jnp.full_like(lin.weight.grad._data, jnp.inf))
    sc.step(opt)
    sc.update()
    np.testing.assert_array_equal(np.asarray(lin.weight._data), w0)  # skipped
    assert sc.get_loss_scaling() == 2.0  # shrunk


def test_grad_scaler_eager_unscales():
    from paddle_tpu.optimizer.optimizers import SGD

    lin = nn.Linear(4, 1)
    opt = SGD(learning_rate=0.0, parameters=lin.parameters())
    sc = amp.GradScaler(init_loss_scaling=4.0)
    x = paddle.to_tensor(np.ones((2, 4), "float32"))
    loss = sc.scale(lin(x).sum())
    loss.backward()
    sc.unscale_(opt)
    # d(sum(xW+b))/dW = sum over batch of x = 2s; scaled by 4 then unscaled
    np.testing.assert_allclose(np.asarray(lin.weight.grad._data),
                               np.full((4, 1), 2.0), rtol=1e-6)


def test_trainer_in_graph_loss_scaling():
    from paddle_tpu.distributed.env import init_mesh, clear_mesh
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.optimizer.optimizers import AdamW

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 8))
    loss_fn = lambda out, y: ((out - y) ** 2).mean()
    init_mesh({"dp": 1})
    try:
        opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
        sc = amp.GradScaler(init_loss_scaling=1024.0, incr_every_n_steps=3)
        tr = ParallelTrainer(model, loss_fn, opt, dp_axis=None, scaler=sc)
        x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
        y = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
        losses = [float(tr.step(x, y)._data) for _ in range(6)]
        assert losses[-1] < losses[0]
        # after 6 finite steps with incr_every=3, scale grew twice
        assert float(tr.scale_state["loss_scale"]) == 4096.0
        # sync back into the scaler for checkpointing
        tr.sync_to_model()
        assert sc.get_loss_scaling() == 4096.0
    finally:
        clear_mesh()


def test_trainer_static_loss_scaling_stays_fixed():
    from paddle_tpu.distributed.env import init_mesh, clear_mesh
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.optimizer.optimizers import SGD

    paddle.seed(0)
    model = nn.Linear(4, 4)
    init_mesh({"dp": 1})
    try:
        opt = SGD(learning_rate=1e-2, parameters=model.parameters())
        sc = amp.GradScaler(init_loss_scaling=128.0, incr_every_n_steps=1,
                            use_dynamic_loss_scaling=False)
        tr = ParallelTrainer(model, lambda o, y: ((o - y) ** 2).mean(), opt,
                             dp_axis=None, scaler=sc)
        x = paddle.to_tensor(np.random.randn(2, 4).astype("float32"))
        for _ in range(3):
            tr.step(x, x)
        assert float(tr.scale_state["loss_scale"]) == 128.0
    finally:
        clear_mesh()
