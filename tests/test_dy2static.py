"""@to_static AST conversion of data-dependent Python control flow.

Parity model: the reference dygraph_to_static transpiler tests
(dygraph_to_static/test_ifelse.py, test_loop.py shapes): tensor-valued
if/else and while loops must work under the jit trace.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit import to_static


class TestIfConversion:
    def test_tensor_if_both_paths(self):
        @to_static
        def f(x):
            if x.sum() > 0:
                y = x * 2.0
            else:
                y = x - 1.0
            return y + 1.0

        xs = paddle.to_tensor([1.0, 2.0])
        np.testing.assert_allclose(np.asarray(f(xs)._data), [3.0, 5.0])
        xs = paddle.to_tensor([-1.0, -2.0])
        np.testing.assert_allclose(np.asarray(f(xs)._data), [-1.0, -2.0])

    def test_if_reads_pre_existing_var(self):
        @to_static
        def f(x):
            y = x + 10.0
            if x.sum() > 0:
                y = y * 2.0
            return y

        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([1.0]))._data), [22.0])
        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([-1.0]))._data), [9.0])

    def test_concrete_python_if_untouched(self):
        @to_static
        def f(x, flag=True):
            if flag:
                return x * 2.0
            return x * 3.0

        # `return` inside the branch is unconvertible → stays Python; works
        # because the predicate is concrete
        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([2.0]))._data), [4.0])

    def test_grad_through_converted_if(self):
        @to_static
        def f(x):
            if x.sum() > 0:
                y = (x * x).sum()
            else:
                y = (2.0 * x).sum()
            return y

        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        f(x).backward()
        np.testing.assert_allclose(np.asarray(x.grad._data), [2.0, 4.0])


class TestWhileConversion:
    def test_tensor_while_accumulates(self):
        @to_static
        def f(x):
            i = paddle.to_tensor([0.0])
            acc = x * 0.0
            while i.sum() < 3:
                acc = acc + x
                i = i + 1.0
            return acc

        out = f(paddle.to_tensor([2.0, 4.0]))
        np.testing.assert_allclose(np.asarray(out._data), [6.0, 12.0])

    def test_while_on_traced_bound(self):
        @to_static
        def f(x, n):
            i = n * 0
            out = x
            while (i < n).sum() > 0:
                out = out * 2.0
                i = i + 1
            return out

        out = f(paddle.to_tensor([1.0]), paddle.to_tensor(3))
        np.testing.assert_allclose(np.asarray(out._data), [8.0])


class TestConversionHygiene:
    def test_unconvertible_keeps_original(self):
        from paddle_tpu.jit.dy2static import convert_function

        def g(x):
            for item in [1, 2]:  # no tensor control flow at all
                x = x + item
            return x

        assert convert_function(g) is g

    def test_not_to_static_respected(self):
        from paddle_tpu.jit import not_to_static
        from paddle_tpu.jit.dy2static import convert_function

        @not_to_static
        def g(x):
            if x.sum() > 0:
                y = x
            else:
                y = -x
            return y

        assert convert_function(g) is g


class TestConversionEdgeCases:
    def test_annassign_and_for_targets_captured(self):
        @to_static
        def f(x):
            if x.sum() > 0:
                y: object = x * 2.0
            else:
                y: object = x * 3.0
            total = x * 0.0
            if x.sum() > 0:
                for _i in [1.0, 2.0]:
                    total = total + y * _i
            else:
                total = y
            return total

        out = f(paddle.to_tensor([1.0]))
        np.testing.assert_allclose(np.asarray(out._data), [6.0])
        out = f(paddle.to_tensor([-1.0]))
        np.testing.assert_allclose(np.asarray(out._data), [-3.0])

    def test_undefined_on_untaken_branch_is_loud_on_use(self):
        from paddle_tpu.jit.dy2static import pd_cond

        out = pd_cond(False, lambda y: (y,), lambda y: (y,),
                      (__import__("paddle_tpu.jit.dy2static",
                                  fromlist=["UNDEFINED"]).UNDEFINED,))
        with pytest.raises(UnboundLocalError, match="untaken branch"):
            out[0] + 1


class TestDoubleGradThroughJit:
    def test_create_graph_through_to_static(self):
        """paddle.grad(create_graph=True) across a @to_static boundary
        (reference: double grad through a converted ProgramTranslator fn)."""
        from paddle_tpu.autograd import tape

        @to_static
        def f(x):
            return (x * x * x).sum()

        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        y = f(x)
        (g1,) = tape.grad(y, [x], create_graph=True)
        np.testing.assert_allclose(np.asarray(g1._data), [3.0, 12.0])
        (g2,) = tape.grad(g1.sum(), [x])
        np.testing.assert_allclose(np.asarray(g2._data), [6.0, 12.0])
