"""@to_static AST conversion of data-dependent Python control flow.

Parity model: the reference dygraph_to_static transpiler tests
(dygraph_to_static/test_ifelse.py, test_loop.py shapes): tensor-valued
if/else and while loops must work under the jit trace.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit import to_static


class TestIfConversion:
    def test_tensor_if_both_paths(self):
        @to_static
        def f(x):
            if x.sum() > 0:
                y = x * 2.0
            else:
                y = x - 1.0
            return y + 1.0

        xs = paddle.to_tensor([1.0, 2.0])
        np.testing.assert_allclose(np.asarray(f(xs)._data), [3.0, 5.0])
        xs = paddle.to_tensor([-1.0, -2.0])
        np.testing.assert_allclose(np.asarray(f(xs)._data), [-1.0, -2.0])

    def test_if_reads_pre_existing_var(self):
        @to_static
        def f(x):
            y = x + 10.0
            if x.sum() > 0:
                y = y * 2.0
            return y

        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([1.0]))._data), [22.0])
        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([-1.0]))._data), [9.0])

    def test_concrete_python_if_untouched(self):
        @to_static
        def f(x, flag=True):
            if flag:
                return x * 2.0
            return x * 3.0

        # `return` inside the branch is unconvertible → stays Python; works
        # because the predicate is concrete
        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([2.0]))._data), [4.0])

    def test_grad_through_converted_if(self):
        @to_static
        def f(x):
            if x.sum() > 0:
                y = (x * x).sum()
            else:
                y = (2.0 * x).sum()
            return y

        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        f(x).backward()
        np.testing.assert_allclose(np.asarray(x.grad._data), [2.0, 4.0])


class TestWhileConversion:
    def test_tensor_while_accumulates(self):
        @to_static
        def f(x):
            i = paddle.to_tensor([0.0])
            acc = x * 0.0
            while i.sum() < 3:
                acc = acc + x
                i = i + 1.0
            return acc

        out = f(paddle.to_tensor([2.0, 4.0]))
        np.testing.assert_allclose(np.asarray(out._data), [6.0, 12.0])

    def test_while_on_traced_bound(self):
        @to_static
        def f(x, n):
            i = n * 0
            out = x
            while (i < n).sum() > 0:
                out = out * 2.0
                i = i + 1
            return out

        out = f(paddle.to_tensor([1.0]), paddle.to_tensor(3))
        np.testing.assert_allclose(np.asarray(out._data), [8.0])


class TestConversionHygiene:
    def test_unconvertible_keeps_original(self):
        from paddle_tpu.jit.dy2static import convert_function

        def g(x):
            for item in [1, 2]:  # no tensor control flow at all
                x = x + item
            return x

        assert convert_function(g) is g

    def test_not_to_static_respected(self):
        from paddle_tpu.jit import not_to_static
        from paddle_tpu.jit.dy2static import convert_function

        @not_to_static
        def g(x):
            if x.sum() > 0:
                y = x
            else:
                y = -x
            return y

        assert convert_function(g) is g


class TestConversionEdgeCases:
    def test_annassign_and_for_targets_captured(self):
        @to_static
        def f(x):
            if x.sum() > 0:
                y: object = x * 2.0
            else:
                y: object = x * 3.0
            total = x * 0.0
            if x.sum() > 0:
                for _i in [1.0, 2.0]:
                    total = total + y * _i
            else:
                total = y
            return total

        out = f(paddle.to_tensor([1.0]))
        np.testing.assert_allclose(np.asarray(out._data), [6.0])
        out = f(paddle.to_tensor([-1.0]))
        np.testing.assert_allclose(np.asarray(out._data), [-3.0])

    def test_undefined_on_untaken_branch_is_loud_on_use(self):
        from paddle_tpu.jit.dy2static import pd_cond

        out = pd_cond(False, lambda y: (y,), lambda y: (y,),
                      (__import__("paddle_tpu.jit.dy2static",
                                  fromlist=["UNDEFINED"]).UNDEFINED,))
        with pytest.raises(UnboundLocalError, match="untaken branch"):
            out[0] + 1


class TestDoubleGradThroughJit:
    def test_create_graph_through_to_static(self):
        """paddle.grad(create_graph=True) across a @to_static boundary
        (reference: double grad through a converted ProgramTranslator fn)."""
        from paddle_tpu.autograd import tape

        @to_static
        def f(x):
            return (x * x * x).sum()

        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        y = f(x)
        (g1,) = tape.grad(y, [x], create_graph=True)
        np.testing.assert_allclose(np.asarray(g1._data), [3.0, 12.0])
        (g2,) = tape.grad(g1.sum(), [x])
        np.testing.assert_allclose(np.asarray(g2._data), [6.0, 12.0])


class TestLoopBreadth:
    """Round-4 breadth (reference loop_transformer / break_continue_
    transformer / return_transformer test shapes, dygraph_to_static/
    test_loop.py, test_break_continue.py, test_return.py)."""

    def test_for_range_tensor_carry(self):
        @to_static
        def f(x):
            acc = x * 0.0
            for i in range(4):
                acc = acc + x * float(i)
            return acc

        got = f(paddle.to_tensor([1.0, 2.0]))
        np.testing.assert_allclose(np.asarray(got._data), [6.0, 12.0])

    def test_for_range_traced_bound(self):
        @to_static
        def f(x, n):
            acc = x * 0.0
            for i in range(n):
                acc = acc + x
            return acc

        got = f(paddle.to_tensor([2.0]), paddle.to_tensor(3))
        np.testing.assert_allclose(np.asarray(got._data), [6.0])

    def test_for_range_start_step(self):
        @to_static
        def f(x):
            acc = 0.0 * x
            for i in range(1, 10, 3):  # 1, 4, 7
                acc = acc + float(i) * x
            return acc

        got = f(paddle.to_tensor([1.0]))
        np.testing.assert_allclose(np.asarray(got._data), [12.0])

    def test_break_in_while(self):
        @to_static
        def f(x):
            i = paddle.to_tensor(0.0)
            while i < 10.0:
                if (x + i).sum() > 3.0:
                    break
                i = i + 1.0
            return i

        got = f(paddle.to_tensor([1.0]))
        np.testing.assert_allclose(np.asarray(got._data), 3.0)

    def test_break_in_for_loop(self):
        @to_static
        def f(x):
            acc = x * 0.0
            for i in range(10):
                if i >= 3:
                    break
                acc = acc + x
            return acc

        got = f(paddle.to_tensor([1.0]))
        np.testing.assert_allclose(np.asarray(got._data), [3.0])

    def test_continue_in_while(self):
        @to_static
        def f(x):
            i = x * 0.0
            acc = x * 0.0
            while i.sum() < 5.0:
                i = i + 1.0
                if i.sum() % 2.0 == 0.0:
                    continue
                acc = acc + i
            return acc  # 1 + 3 + 5

        got = f(paddle.to_tensor([1.0]))
        np.testing.assert_allclose(np.asarray(got._data), [9.0])

    def test_continue_in_for(self):
        @to_static
        def f(x):
            acc = x * 0.0
            for i in range(6):
                if i % 2 == 1:
                    continue
                acc = acc + float(i) * x
            return acc  # 0 + 2 + 4

        got = f(paddle.to_tensor([1.0]))
        np.testing.assert_allclose(np.asarray(got._data), [6.0])

    def test_early_return_in_if(self):
        @to_static
        def f(x):
            if x.sum() > 0.0:
                return x * 2.0
            return x * 3.0

        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([1.0]))._data), [2.0])
        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([-1.0]))._data), [-3.0])

    def test_return_in_while(self):
        @to_static
        def f(x):
            i = x * 0.0
            while i.sum() < 100.0:
                i = i + 1.0
                if i.sum() >= 4.0:
                    return i * 10.0
            return i

        got = f(paddle.to_tensor([1.0]))
        np.testing.assert_allclose(np.asarray(got._data), [40.0])

    def test_while_else_no_break(self):
        @to_static
        def f(x):
            i = 0
            while i < 3:
                i += 1
            else:
                x = x + 100.0
            return x

        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([1.0]))._data), [101.0])

    def test_for_else_with_break(self):
        @to_static
        def f(x, cut):
            found = x * 0.0
            for i in range(5):
                if float(i) == cut:
                    break
            else:
                found = found + 1.0
            return found

        # break taken → else skipped
        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([1.0]), 2.0)._data), [0.0])
        # loop exhausts → else runs
        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([1.0]), 99.0)._data), [1.0])

    def test_traced_everything_under_jit(self):
        """The whole construct compiles inside one jax.jit trace."""
        import jax

        @to_static
        def f(x):
            acc = x * 0.0
            for i in range(8):
                if i >= 5:
                    break
                acc = acc + x
            return acc

        calls = []

        def raw(a):
            calls.append(1)
            import paddle_tpu as pd

            return f(pd.Tensor(a))._data

        j = jax.jit(raw)
        out = j(np.asarray([1.0], np.float32))
        np.testing.assert_allclose(np.asarray(out), [5.0])

    def test_empty_range_keeps_prebound_target(self):
        """Python semantics: `for i in range(0)` leaves a pre-existing `i`
        untouched (review r4: the lowering must not clobber it)."""
        @to_static
        def f(x):
            i = 100.0
            for i in range(0):
                x = x + 1.0
            return x + i

        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([1.0]))._data), [101.0])

    def test_soft_positions_with_container_output(self):
        """A tuple-valued user variable sorting before __pd_ret_val must not
        shift the soft-index mapping (review r4: per-position, not
        per-leaf)."""
        @to_static
        def f(x):
            Stats = (x * 2.0, x * 3.0)  # noqa: N806 — sorts before "__pd_*"
            if x.sum() > 0.0:
                return Stats[0] + Stats[1]
            Stats = (x, x)
            return Stats[0]

        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([1.0]))._data), [5.0])
        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([-2.0]))._data), [-2.0])

    def test_sequential_early_returns(self):
        """Two sequential early-return ifs: the outer guard's outputs must
        survive inner conversions (regression: stale liveness counts
        dropped __pd_ret_val assigned inside a nested guard)."""
        @to_static
        def f(x):
            s = x.sum()
            if s > 10.0:
                return x * 0.0 + 2.0
            if s > 0.0:
                return x * 0.0 + 1.0
            return x * 0.0

        for v, want in [([5.0, 6.0], 2.0), ([1.0], 1.0), ([-3.0], 0.0)]:
            got = float(f(paddle.to_tensor(v))._data[0])
            assert got == want, (v, got, want)

    def test_break_under_traced_if_in_concrete_loop(self):
        """A concrete-bound loop whose break flag becomes traced mid-loop
        hands the remaining iterations to lax.while_loop."""
        @to_static
        def f(w, x, y):
            loss = ((w * x - y) ** 2).mean()
            for _ in range(50):
                if loss < 0.01:
                    break
                g = 2.0 * ((w * x - y) * x).mean()
                w = w - 0.1 * g
                loss = ((w * x - y) ** 2).mean()
            return w

        w = f(paddle.to_tensor([0.0]), paddle.to_tensor([1.0, 2.0]),
              paddle.to_tensor([2.0, 4.0]))
        assert abs(float(w._data[0]) - 2.0) < 0.1


class TestContainersAndIteration:
    """Ported reference dygraph_to_static patterns (VERDICT r4 #5):
    test_for_enumerate.py (for-in-range-over-tensor, for-iter-list,
    for-enumerate-list, for-iter-var, for-enumerate-var),
    test_list.py (append without control flow / in if / in for+concat),
    test_print.py, test_assert.py, nested function conversion
    (program_translator.py:768)."""

    def test_for_in_range_tensor_bound(self):
        # test_for_enumerate.py for_in_range: trip count from a tensor VALUE
        @to_static
        def f(n):
            z = paddle.to_tensor(0)
            for i in range(n[0]):
                z = z + i
            return z

        assert int(np.asarray(f(paddle.to_tensor([5]))._data)) == 10
        assert int(np.asarray(f(paddle.to_tensor([0]))._data)) == 0

    def test_for_iter_list(self):
        @to_static
        def f(xs):
            z = paddle.to_tensor(0.0)
            for x in xs:
                z = z + x
            return z

        vals = [paddle.to_tensor(v) for v in (1.0, 2.0, 3.0)]
        np.testing.assert_allclose(np.asarray(f(vals)._data), 6.0)

    def test_for_enumerate_list(self):
        @to_static
        def f(xs):
            z = paddle.to_tensor(0.0)
            for i, x in enumerate(xs):
                z = z + x + i
            return z

        vals = [paddle.to_tensor(v) for v in (1.0, 2.0)]
        np.testing.assert_allclose(np.asarray(f(vals)._data), 4.0)

    def test_for_iter_over_tensor(self):
        # loop_transformer.py for-over-tensor: rows unroll on the static
        # leading dim
        @to_static
        def f(x):
            z = x[0] * 0.0
            for row in x:
                z = z + row
            return z

        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(3, 2))
        np.testing.assert_allclose(np.asarray(f(x)._data), [6.0, 9.0])

    def test_for_enumerate_over_tensor(self):
        @to_static
        def f(x):
            y = x[0] * 0.0
            z = x[0] * 0.0
            for i, row in enumerate(x):
                y = y + i
                z = z + row
            return y, z

        x = paddle.to_tensor(np.ones((3, 2), np.float32))
        y, z = f(x)
        np.testing.assert_allclose(np.asarray(y._data), [3.0, 3.0])
        np.testing.assert_allclose(np.asarray(z._data), [3.0, 3.0])

    def test_list_append_without_control_flow(self):
        @to_static
        def f(x):
            a = []
            a.append(x)
            a.append(x * 2.0)
            return a[0] + a[1]

        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([1.0]))._data), [3.0])

    def test_list_append_in_if_traced_pred(self):
        # test_list.py test_list_append_in_if: both branches append one
        # same-shaped value; the list rides through lax.cond as a pytree
        @to_static
        def f(x):
            a = []
            if x.sum() > 0:
                a.append(x)
            else:
                a.append(x * -1.0)
            return a[0]

        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([2.0]))._data), [2.0])
        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([-3.0]))._data), [3.0])

    def test_list_append_in_for_with_concat(self):
        # test_list.py test_list_append_in_for_subscript: the shape-derived
        # bound is static under XLA, so appends unroll and concat sees a
        # fixed-length list
        @to_static
        def f(x):
            a = []
            for i in range(x.shape[0]):
                x = x + 1.0
                a.append(x)
            import paddle_tpu as pd

            return pd.concat(a)[0]

        x = paddle.to_tensor(np.zeros((3, 2), np.float32))
        np.testing.assert_allclose(np.asarray(f(x)._data), [1.0, 1.0])

    def test_print_traced(self, capfd):
        @to_static
        def f(x):
            print("value:", x)
            return x * 2.0

        out = f(paddle.to_tensor([1.5]))
        np.testing.assert_allclose(np.asarray(out._data), [3.0])
        # traced print renders through jax.debug.print (async host cb)
        import jax

        jax.effects_barrier()
        captured = capfd.readouterr()
        assert "1.5" in captured.out

    def test_assert_concrete_and_traced(self):
        @to_static
        def f(x):
            assert x.shape[0] == 2, "static shape assert"
            return x + 1.0

        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([1.0, 2.0]))._data), [2.0, 3.0])
        with pytest.raises(AssertionError):
            f(paddle.to_tensor([1.0, 2.0, 3.0]))

    def test_nested_function_conversion(self):
        # program_translator.py:768: functions DEFINED inside the converted
        # function get their control flow converted too
        @to_static
        def f(x):
            def inner(v):
                if v.sum() > 0:
                    return v * 2.0
                return v - 1.0

            return inner(x) + inner(x * -1.0)

        got = np.asarray(f(paddle.to_tensor([1.0]))._data)
        # inner(1) = 2; inner(-1) = -2  -> 0... inner(-1): sum<0 -> -1-1=-2
        np.testing.assert_allclose(got, [0.0])


class TestStatementRewriteScoping:
    """Review r5: the append rewrite must not capture closure mutation, and
    pd_assert must keep Python truthiness for non-tensor predicates."""

    def test_nested_closure_append_untouched(self):
        @to_static
        def f(x):
            a = []

            def add(v):
                a.append(v)

            add(x)
            add(x * 2.0)
            return a[0] + a[1]

        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([1.0]))._data), [3.0])

    def test_assert_empty_list_fails(self):
        @to_static
        def f(x):
            results = []
            assert results, "no detections"
            return x

        with pytest.raises(AssertionError, match="no detections"):
            f(paddle.to_tensor([1.0]))

    def test_assert_nonempty_list_passes(self):
        @to_static
        def f(x):
            results = [1]
            assert results
            return x + len(results)

        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([1.0]))._data), [2.0])


class TestDictLenIsinstance:
    """Ported reference patterns: test_dict.py (dict containers),
    test_len.py (len of tensors), test_isinstance.py."""

    def test_dict_of_tensors(self):
        @to_static
        def f(x):
            cache = {}
            cache["k"] = x * 2.0
            cache["v"] = x + 1.0
            if x.sum() > 0:
                out = cache["k"]
            else:
                out = cache["v"]
            return out

        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([1.0]))._data), [2.0])
        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([-1.0]))._data), [0.0])

    def test_len_of_tensor(self):
        @to_static
        def f(x):
            n = len(x)  # static leading dim
            return x.sum() / n

        np.testing.assert_allclose(
            float(np.asarray(f(paddle.to_tensor([2.0, 4.0]))._data)), 3.0)

    def test_isinstance_dispatch(self):
        from paddle_tpu.tensor import Tensor as T

        @to_static
        def f(x):
            if isinstance(x, T):
                return x * 2.0
            return x

        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([3.0]))._data), [6.0])


def test_list_alias_preserved_eager():
    """Review r5: `b = a; a.append(x)` keeps b aliased (in-place append)."""
    @to_static
    def f(x):
        a = []
        b = a
        a.append(x)
        return b[0]

    np.testing.assert_allclose(
        np.asarray(f(paddle.to_tensor([7.0]))._data), [7.0])


class TestR6AdviceFixes:
    """ADVICE r5 #3/#4: async-def scope collection + nested list copies."""

    def test_async_function_converted(self):
        import asyncio

        from paddle_tpu.jit.dy2static import convert_function

        async def f(x):
            if x.sum() > 0:
                y = x + 1.0
            else:
                y = x - 1.0
            return y

        g = convert_function(f)
        # the per-scope passes must SEE the async scope (previously the
        # FunctionDef-only collection returned zero scopes -> original fn)
        assert g is not f
        x = paddle.to_tensor(np.array([1.0, 2.0], dtype="float32"))
        out = asyncio.run(g(x))
        np.testing.assert_allclose(np.asarray(out._data), [2.0, 3.0])
        out = asyncio.run(g(paddle.to_tensor(
            np.array([-1.0, -2.0], dtype="float32"))))
        np.testing.assert_allclose(np.asarray(out._data), [-2.0, -3.0])

    def test_copy_list_args_copies_nested_lists(self):
        from paddle_tpu.jit.dy2static import _copy_list_args

        inner_d = [1]
        inner_t = [2]
        top = [3]
        args = ({"k": inner_d}, (inner_t,), top)
        copies = _copy_list_args(args)
        copies[0]["k"].append(10)
        copies[1][0].append(20)
        copies[2].append(30)
        # probe-time appends must not leak back into the caller's lists
        assert inner_d == [1] and inner_t == [2] and top == [3]

    def test_copy_list_args_shares_leaves(self):
        from paddle_tpu.jit.dy2static import _copy_list_args

        t = paddle.to_tensor(np.array([1.0], dtype="float32"))
        (copy,) = _copy_list_args(({"a": [t]},))
        assert copy["a"][0] is t  # tensors are shared, containers fresh

    def test_copy_list_args_preserves_container_types(self):
        import collections

        from paddle_tpu.jit.dy2static import _copy_list_args

        Pt = collections.namedtuple("Pt", "x y")
        od = collections.OrderedDict([("a", [1])])
        (pt, odc) = _copy_list_args((Pt([1], 2), od))
        assert type(pt) is Pt and pt.x == [1] and pt.y == 2
        assert type(odc) is collections.OrderedDict
        odc["a"].append(9)
        assert od["a"] == [1]

    def test_copy_list_args_defaultdict_and_counter(self):
        import collections

        from paddle_tpu.jit.dy2static import _copy_list_args

        dd = collections.defaultdict(list, {"a": [1]})
        cn = collections.Counter({"a": 2})
        (ddc, cnc) = _copy_list_args((dd, cn))
        assert type(ddc) is collections.defaultdict
        assert ddc.default_factory is list
        ddc["a"].append(9)
        ddc["new"].append(1)  # factory still works
        assert dd["a"] == [1] and "new" not in dd
        assert type(cnc) is collections.Counter and cnc["a"] == 2


class TestCheckedAsserts:
    """ISSUE 3 satellite: pd_assert's synchronous checked-error path via
    jax.experimental.checkify (ADVICE r5 #5 — async debug.callback failure
    semantics now have a sync alternative)."""

    def test_checked_sync_raise_with_message(self):
        import jax.numpy as jnp

        from paddle_tpu.jit import checked

        def f(x):
            assert (x > 0).all(), "x must be positive"
            return x * 2

        cf = checked(f)
        out = cf(jnp.asarray([1.0, 2.0]))
        np.testing.assert_allclose(np.asarray(out), [2.0, 4.0])
        with pytest.raises(Exception, match="x must be positive"):
            cf(jnp.asarray([1.0, -2.0]))

    def test_checked_composes_with_jit(self):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.jit import checked
        from paddle_tpu.jit.dy2static import pd_assert

        @jax.jit
        def f(x):
            pd_assert(x > 0, "needs positive")
            return x + 1

        cf = checked(f)
        assert float(cf(jnp.asarray(1.0))) == 2.0
        with pytest.raises(Exception, match="needs positive"):
            cf(jnp.asarray(-1.0))

    def test_concrete_path_keeps_python_truthiness(self):
        from paddle_tpu.jit.dy2static import pd_assert

        with pytest.raises(AssertionError, match="empty"):
            pd_assert([], "empty")
        pd_assert([0], None)  # non-empty list is truthy, like plain assert

    def test_plain_jit_fallback_stays_async_callback(self):
        """Without checked(), pd_assert must stage the debug.callback path
        (no checkify trace error at lowering time) and pass clean inputs."""
        import jax
        import jax.numpy as jnp

        from paddle_tpu.jit.dy2static import pd_assert

        @jax.jit
        def f(x):
            pd_assert(x > 0, "positive")
            return x * 3

        out = f(jnp.asarray(2.0))
        jax.block_until_ready(out)
        assert float(out) == 6.0

    def test_checked_message_with_braces(self):
        import jax.numpy as jnp

        from paddle_tpu.jit import checked
        from paddle_tpu.jit.dy2static import pd_assert

        def f(x):
            pd_assert(x > 0, "x must be in {0,1}")
            return x

        cf = checked(f)
        with pytest.raises(Exception, match=r"x must be in \{0,1\}"):
            cf(jnp.asarray(-1.0))
