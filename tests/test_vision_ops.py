"""Detection op parity vs independent numpy goldens (reference test strategy:
unittests/test_roi_align_op.py, test_roi_pool_op.py, test_psroi_pool_op.py,
test_yolo_box_op.py, test_yolov3_loss_op.py, test_deform_conv2d.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as V


rng = np.random.default_rng(7)


def _np(t):
    return np.asarray(t._data)


def np_bilinear(fm, y, x):
    C, H, W = fm.shape
    if y < -1.0 or y > H or x < -1.0 or x > W:
        return np.zeros(C, fm.dtype)
    y = min(max(y, 0.0), H - 1.0)
    x = min(max(x, 0.0), W - 1.0)
    y0, x0 = int(np.floor(y)), int(np.floor(x))
    y1, x1 = min(y0 + 1, H - 1), min(x0 + 1, W - 1)
    ly, lx = y - y0, x - x0
    return ((1 - ly) * (1 - lx) * fm[:, y0, x0] + (1 - ly) * lx * fm[:, y0, x1]
            + ly * (1 - lx) * fm[:, y1, x0] + ly * lx * fm[:, y1, x1])


def np_roi_align(x, boxes, batch_ids, out_hw, scale, sampling, aligned):
    ph, pw = out_hw
    s = sampling if sampling > 0 else 2
    C = x.shape[1]
    out = np.zeros((len(boxes), C, ph, pw), np.float32)
    for bi, (bid, box) in enumerate(zip(batch_ids, boxes)):
        off = 0.5 if aligned else 0.0
        x1, y1, x2, y2 = box * scale - off
        rw, rh = x2 - x1, y2 - y1
        if not aligned:
            rw, rh = max(rw, 1.0), max(rh, 1.0)
        bh, bw = rh / ph, rw / pw
        for i in range(ph):
            for j in range(pw):
                acc = np.zeros(C, np.float32)
                for iy in range(s):
                    for ix in range(s):
                        yy = y1 + (i + (iy + 0.5) / s) * bh
                        xx = x1 + (j + (ix + 0.5) / s) * bw
                        acc += np_bilinear(x[bid], yy, xx)
                out[bi, :, i, j] = acc / (s * s)
    return out


class TestRoIAlign:
    def test_vs_golden(self):
        x = rng.standard_normal((2, 3, 12, 16)).astype("float32")
        boxes = np.array([[1, 1, 9, 7], [0, 2, 14, 11], [3.5, 2.5, 10.2, 9.9]],
                         np.float32)
        boxes_num = np.array([2, 1], np.int32)
        got = V.roi_align(paddle.to_tensor(x), paddle.to_tensor(boxes),
                          paddle.to_tensor(boxes_num), output_size=4,
                          spatial_scale=0.5)
        want = np_roi_align(x, boxes, [0, 0, 1], (4, 4), 0.5, -1, True)
        np.testing.assert_allclose(_np(got), want, rtol=1e-4, atol=1e-4)

    def test_not_aligned_with_ratio(self):
        x = rng.standard_normal((1, 2, 10, 10)).astype("float32")
        boxes = np.array([[0, 0, 8, 8]], np.float32)
        got = V.roi_align(paddle.to_tensor(x), paddle.to_tensor(boxes),
                          paddle.to_tensor(np.array([1], np.int32)),
                          output_size=(2, 3), sampling_ratio=3, aligned=False)
        want = np_roi_align(x, boxes, [0], (2, 3), 1.0, 3, False)
        np.testing.assert_allclose(_np(got), want, rtol=1e-4, atol=1e-4)

    def test_layer_and_grad(self):
        x = paddle.to_tensor(rng.standard_normal((1, 2, 8, 8)).astype("float32"))
        x.stop_gradient = False
        layer = V.RoIAlign(output_size=2)
        out = layer(x, paddle.to_tensor(np.array([[1, 1, 6, 6]], np.float32)),
                    paddle.to_tensor(np.array([1], np.int32)))
        assert tuple(out.shape) == (1, 2, 2, 2)
        out.sum().backward()
        assert np.isfinite(_np(x.grad)).all() and np.abs(_np(x.grad)).sum() > 0


def np_roi_pool(x, boxes, batch_ids, out_hw, scale):
    ph, pw = out_hw
    C, H, W = x.shape[1:]
    out = np.zeros((len(boxes), C, ph, pw), np.float32)
    for bi, (bid, box) in enumerate(zip(batch_ids, boxes)):
        x1, y1, x2, y2 = np.round(box * scale)
        rh = max(y2 - y1 + 1, 1.0)
        rw = max(x2 - x1 + 1, 1.0)
        for i in range(ph):
            hs = int(np.clip(np.floor(i * rh / ph + y1), 0, H))
            he = int(np.clip(np.ceil((i + 1) * rh / ph + y1), 0, H))
            for j in range(pw):
                ws = int(np.clip(np.floor(j * rw / pw + x1), 0, W))
                we = int(np.clip(np.ceil((j + 1) * rw / pw + x1), 0, W))
                if he > hs and we > ws:
                    out[bi, :, i, j] = x[bid][:, hs:he, ws:we].max(axis=(1, 2))
    return out


class TestRoIPool:
    def test_vs_golden(self):
        x = rng.standard_normal((2, 4, 14, 14)).astype("float32")
        boxes = np.array([[0, 0, 13, 13], [2, 3, 10, 8], [5, 5, 6, 6]], np.float32)
        boxes_num = np.array([1, 2], np.int32)
        got = V.roi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                         paddle.to_tensor(boxes_num), 3)
        want = np_roi_pool(x, boxes, [0, 1, 1], (3, 3), 1.0)
        np.testing.assert_allclose(_np(got), want, rtol=1e-5, atol=1e-5)

    def test_scale(self):
        x = rng.standard_normal((1, 1, 8, 8)).astype("float32")
        boxes = np.array([[2, 2, 12, 12]], np.float32)
        got = V.roi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                         paddle.to_tensor(np.array([1], np.int32)), 2, 0.5)
        want = np_roi_pool(x, boxes, [0], (2, 2), 0.5)
        np.testing.assert_allclose(_np(got), want, rtol=1e-5, atol=1e-5)


def np_psroi_pool(x, boxes, batch_ids, out_hw, scale):
    ph, pw = out_hw
    C, H, W = x.shape[1:]
    co = C // (ph * pw)
    out = np.zeros((len(boxes), co, ph, pw), np.float32)
    for bi, (bid, box) in enumerate(zip(batch_ids, boxes)):
        x1, y1, x2, y2 = box * scale
        rh = max(y2 - y1, 0.1)
        rw = max(x2 - x1, 0.1)
        for i in range(ph):
            hs = int(np.clip(np.floor(i * rh / ph + y1), 0, H))
            he = int(np.clip(np.ceil((i + 1) * rh / ph + y1), 0, H))
            for j in range(pw):
                ws = int(np.clip(np.floor(j * rw / pw + x1), 0, W))
                we = int(np.clip(np.ceil((j + 1) * rw / pw + x1), 0, W))
                for c in range(co):
                    cin = (c * ph + i) * pw + j
                    if he > hs and we > ws:
                        out[bi, c, i, j] = x[bid, cin, hs:he, ws:we].mean()
    return out


class TestPSRoIPool:
    def test_vs_golden(self):
        x = rng.standard_normal((2, 2 * 3 * 3, 10, 12)).astype("float32")
        boxes = np.array([[1, 2, 9, 9], [0, 0, 11, 9]], np.float32)
        boxes_num = np.array([1, 1], np.int32)
        got = V.psroi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                           paddle.to_tensor(boxes_num), 3)
        want = np_psroi_pool(x, boxes, [0, 1], (3, 3), 1.0)
        np.testing.assert_allclose(_np(got), want, rtol=1e-4, atol=1e-4)

    def test_channel_check(self):
        x = paddle.to_tensor(rng.standard_normal((1, 10, 4, 4)).astype("float32"))
        with pytest.raises(ValueError):
            V.psroi_pool(x, paddle.to_tensor(np.zeros((1, 4), np.float32)),
                         paddle.to_tensor(np.array([1], np.int32)), 3)


class TestDeformConv2D:
    def test_zero_offset_matches_conv(self):
        """With zero offsets and unit mask, deform_conv2d == plain conv2d."""
        import paddle_tpu.nn.functional as F

        x = rng.standard_normal((2, 4, 9, 9)).astype("float32")
        w = (rng.standard_normal((6, 4, 3, 3)) * 0.2).astype("float32")
        b = rng.standard_normal(6).astype("float32")
        off = np.zeros((2, 2 * 9, 9, 9), np.float32)
        got = V.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                              paddle.to_tensor(w), paddle.to_tensor(b),
                              stride=1, padding=1)
        want = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w),
                        paddle.to_tensor(b), stride=1, padding=1)
        np.testing.assert_allclose(_np(got), _np(want), rtol=1e-4, atol=1e-4)

    def test_integer_offset_shifts_sampling(self):
        """A +1 x-offset on every kernel point equals convolving the
        x-shifted image (interior pixels)."""
        x = rng.standard_normal((1, 1, 8, 8)).astype("float32")
        w = np.ones((1, 1, 1, 1), np.float32)
        off = np.zeros((1, 2, 8, 8), np.float32)
        off[:, 1] = 1.0  # x-offset
        got = V.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                              paddle.to_tensor(w))
        np.testing.assert_allclose(_np(got)[0, 0, :, :-1], x[0, 0, :, 1:],
                                   rtol=1e-5, atol=1e-5)

    def test_mask_and_groups(self):
        x = rng.standard_normal((1, 4, 6, 6)).astype("float32")
        w = (rng.standard_normal((4, 2, 3, 3)) * 0.1).astype("float32")
        off = (rng.standard_normal((1, 2 * 2 * 9, 6, 6)) * 0.3).astype("float32")
        mask = rng.uniform(0, 1, (1, 2 * 9, 6, 6)).astype("float32")
        got = V.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                              paddle.to_tensor(w), padding=1, groups=2,
                              deformable_groups=2,
                              mask=paddle.to_tensor(mask))
        assert tuple(got.shape) == (1, 4, 6, 6)
        # half mask -> halve output (linearity in mask)
        got2 = V.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                               paddle.to_tensor(w), padding=1, groups=2,
                               deformable_groups=2,
                               mask=paddle.to_tensor(mask * 0.5))
        np.testing.assert_allclose(_np(got2), _np(got) * 0.5, rtol=1e-4, atol=1e-5)

    def test_layer(self):
        layer = V.DeformConv2D(3, 5, 3, padding=1)
        x = paddle.to_tensor(rng.standard_normal((2, 3, 7, 7)).astype("float32"))
        off = paddle.to_tensor(np.zeros((2, 18, 7, 7), np.float32))
        out = layer(x, off)
        assert tuple(out.shape) == (2, 5, 7, 7)
        out.sum().backward()
        assert layer.weight.grad is not None


def np_yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample,
                clip_bbox=True, scale_x_y=1.0):
    n, _, h, w = x.shape
    an_num = len(anchors) // 2
    bias = -0.5 * (scale_x_y - 1.0)
    in_h, in_w = downsample * h, downsample * w
    body = x.reshape(n, an_num, 5 + class_num, h, w)
    sig = lambda v: 1 / (1 + np.exp(-v))  # noqa: E731
    boxes = np.zeros((n, an_num * h * w, 4), np.float32)
    scores = np.zeros((n, an_num * h * w, class_num), np.float32)
    for i in range(n):
        ih, iw = img_size[i]
        for a in range(an_num):
            for r in range(h):
                for c in range(w):
                    conf = sig(body[i, a, 4, r, c])
                    if conf < conf_thresh:
                        continue
                    cx = (c + sig(body[i, a, 0, r, c]) * scale_x_y + bias) * iw / w
                    cy = (r + sig(body[i, a, 1, r, c]) * scale_x_y + bias) * ih / h
                    bw = np.exp(body[i, a, 2, r, c]) * anchors[2 * a] * iw / in_w
                    bh = np.exp(body[i, a, 3, r, c]) * anchors[2 * a + 1] * ih / in_h
                    k = a * h * w + r * w + c
                    bb = [cx - bw / 2, cy - bh / 2, cx + bw / 2, cy + bh / 2]
                    if clip_bbox:
                        bb[0] = max(bb[0], 0)
                        bb[1] = max(bb[1], 0)
                        bb[2] = min(bb[2], iw - 1)
                        bb[3] = min(bb[3], ih - 1)
                    boxes[i, k] = bb
                    scores[i, k] = conf * sig(body[i, a, 5:, r, c])
    return boxes, scores


class TestYoloBox:
    def test_vs_golden(self):
        np.random.seed(3)
        n, an, C, h = 2, 2, 4, 5
        x = rng.standard_normal((n, an * (5 + C), h, h)).astype("float32")
        img = np.array([[320, 480], [416, 416]], np.int32)
        anchors = [10, 13, 16, 30]
        gb, gs = V.yolo_box(paddle.to_tensor(x), paddle.to_tensor(img),
                            anchors, C, 0.4, 32)
        wb, ws = np_yolo_box(x, img, anchors, C, 0.4, 32)
        np.testing.assert_allclose(_np(gb), wb, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(_np(gs), ws, rtol=1e-4, atol=1e-4)

    def test_scale_xy_noclip(self):
        x = rng.standard_normal((1, 9, 3, 3)).astype("float32")
        img = np.array([[96, 96]], np.int32)
        gb, gs = V.yolo_box(paddle.to_tensor(x), paddle.to_tensor(img),
                            [6, 8], 4, 0.0, 32, clip_bbox=False, scale_x_y=1.2)
        wb, ws = np_yolo_box(x, img, [6, 8], 4, 0.0, 32, clip_bbox=False,
                             scale_x_y=1.2)
        np.testing.assert_allclose(_np(gb), wb, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(_np(gs), ws, rtol=1e-4, atol=1e-4)

    def test_iou_aware(self):
        n, an, C, h = 1, 2, 3, 4
        x = rng.standard_normal((n, an * (6 + C), h, h)).astype("float32")
        img = np.array([[128, 128]], np.int32)
        gb, gs = V.yolo_box(paddle.to_tensor(x), paddle.to_tensor(img),
                            [10, 13, 16, 30], C, 0.0, 32, iou_aware=True,
                            iou_aware_factor=0.4)
        sig = lambda v: 1 / (1 + np.exp(-v))  # noqa: E731
        body = x[:, an:].reshape(n, an, 5 + C, h, h)
        iou = sig(x[:, :an])
        conf = sig(body[:, :, 4]) ** 0.6 * iou ** 0.4
        assert _np(gs).max() <= conf.max() + 1e-5


class TestYoloLoss:
    def _loss(self, x, gt_box, gt_label, **kw):
        return V.yolo_loss(paddle.to_tensor(x), paddle.to_tensor(gt_box),
                           paddle.to_tensor(gt_label), **kw)

    def test_perfect_prediction_low_loss(self):
        """Constructed logits that exactly hit one gt box give near-zero
        location/class loss at the positive cell."""
        h, C = 4, 3
        anchors = [10, 14, 23, 27, 37, 58]
        amask = [0, 1, 2]
        down = 32
        insz = down * h
        # gt: centered box matching anchor 1 exactly
        gw, gh = 23 / insz, 27 / insz
        gt_box = np.array([[[0.5 + 1e-6, 0.5 + 1e-6, gw, gh]]], np.float32)
        gt_label = np.array([[1]], np.int64)
        x = np.zeros((1, 3 * (5 + C), h, h), np.float32)
        body = x.reshape(1, 3, 5 + C, h, h)
        gi = gj = int(0.5 * h)
        # tx target = 0.5*h - gi = 0 -> logit -inf; use large negative
        body[0, 1, 0, gj, gi] = -20  # sigmoid -> ~0
        body[0, 1, 1, gj, gi] = -20
        body[0, 1, 2, gj, gi] = 0.0  # tw target = log(1) = 0
        body[0, 1, 3, gj, gi] = 0.0
        body[0, 1, 4, gj, gi] = 20  # obj -> 1
        body[0, 1, 5 + 1, gj, gi] = 20  # class 1 -> 1
        body[0, 1, 5 + 0, gj, gi] = -20
        body[0, 1, 5 + 2, gj, gi] = -20
        loss = self._loss(x, gt_box, gt_label, anchors=anchors,
                          anchor_mask=amask, class_num=C, ignore_thresh=0.7,
                          downsample_ratio=down, use_label_smooth=False)
        # remaining loss is just negative-objectness at the other cells
        neg_cells = 3 * h * h - 1
        expect_obj_neg = neg_cells * np.log1p(np.exp(0.0))
        np.testing.assert_allclose(_np(loss)[0], expect_obj_neg, rtol=0.02)

    def test_ignore_thresh_masks_obj(self):
        """With ignore_thresh=0 every cell overlapping a gt is ignored, so
        the only obj loss comes from zero-IoU cells."""
        # gt matches an anchor outside anchor_mask -> no positive cell, so
        # ignored cells (best_iou > thresh) directly reduce the obj loss
        h, C = 2, 2
        x = np.zeros((1, 1 * (5 + C), h, h), np.float32)
        gt_box = np.array([[[0.5, 0.5, 0.9, 0.9]]], np.float32)
        gt_label = np.array([[0]], np.int64)
        kw = dict(anchor_mask=[0], class_num=C, downsample_ratio=32,
                  use_label_smooth=False, anchors=[8, 8, 60, 60])
        l_lo = self._loss(x, gt_box, gt_label, ignore_thresh=1e-6, **kw)
        l_hi = self._loss(x, gt_box, gt_label, ignore_thresh=0.99, **kw)
        assert _np(l_lo)[0] < _np(l_hi)[0]

    def test_label_smooth_changes_class_loss(self):
        h, C = 2, 4
        x = rng.standard_normal((1, 5 + C, h, h)).astype("float32")
        gt_box = np.array([[[0.5, 0.5, 0.25, 0.25]]], np.float32)
        gt_label = np.array([[2]], np.int64)
        kw = dict(anchors=[16, 16], anchor_mask=[0], class_num=C,
                  ignore_thresh=0.7, downsample_ratio=32)
        l_sm = self._loss(x, gt_box, gt_label, use_label_smooth=True, **kw)
        l_ns = self._loss(x, gt_box, gt_label, use_label_smooth=False, **kw)
        assert not np.allclose(_np(l_sm), _np(l_ns))

    def test_grad_flows(self):
        h, C = 3, 2
        x = paddle.to_tensor(rng.standard_normal((2, 3 * (5 + C), h, h))
                             .astype("float32"))
        x.stop_gradient = False
        gt_box = paddle.to_tensor(
            np.array([[[0.4, 0.4, 0.3, 0.25]], [[0.6, 0.5, 0.2, 0.2]]],
                     np.float32))
        gt_label = paddle.to_tensor(np.array([[0], [1]], np.int64))
        loss = V.yolo_loss(x, gt_box, gt_label,
                           anchors=[10, 13, 16, 30, 33, 23],
                           anchor_mask=[0, 1, 2], class_num=C,
                           ignore_thresh=0.5, downsample_ratio=32)
        loss.sum().backward()
        g = _np(x.grad)
        assert np.isfinite(g).all() and np.abs(g).sum() > 0

    def test_gt_score_weights(self):
        h, C = 2, 2
        x = rng.standard_normal((1, 5 + C, h, h)).astype("float32")
        gt_box = np.array([[[0.5, 0.5, 0.25, 0.25]]], np.float32)
        gt_label = np.array([[1]], np.int64)
        kw = dict(anchors=[16, 16], anchor_mask=[0], class_num=C,
                  ignore_thresh=0.7, downsample_ratio=32,
                  use_label_smooth=False)
        l1 = self._loss(x, gt_box, gt_label,
                        gt_score=paddle.to_tensor(np.array([[1.0]], np.float32)), **kw)
        l_half = self._loss(x, gt_box, gt_label,
                            gt_score=paddle.to_tensor(np.array([[0.5]], np.float32)), **kw)
        assert not np.allclose(_np(l1), _np(l_half))


class TestNMS:
    def test_basic(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]],
                         np.float32)
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        keep = V.nms(paddle.to_tensor(boxes), 0.5,
                     paddle.to_tensor(scores))
        np.testing.assert_array_equal(_np(keep), [0, 2])

    def test_categories(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], np.float32)
        scores = np.array([0.9, 0.8], np.float32)
        cats = np.array([0, 1], np.int32)
        keep = V.nms(paddle.to_tensor(boxes), 0.5, paddle.to_tensor(scores),
                     category_idxs=paddle.to_tensor(cats),
                     categories=[0, 1])
        assert len(_np(keep)) == 2  # different categories never suppress

    def test_top_k(self):
        boxes = np.array([[0, 0, 5, 5], [10, 10, 15, 15], [20, 20, 25, 25]],
                         np.float32)
        scores = np.array([0.5, 0.9, 0.7], np.float32)
        keep = V.nms(paddle.to_tensor(boxes), 0.5, paddle.to_tensor(scores),
                     top_k=2)
        np.testing.assert_array_equal(_np(keep), [1, 2])


class TestImageIO:
    def test_read_decode_jpeg_roundtrip(self, tmp_path):
        from PIL import Image

        # smooth gradient image: survives lossy JPEG within tolerance
        yy, xx = np.mgrid[0:16, 0:20]
        arr = np.stack([yy * 8, xx * 6, (yy + xx) * 4], -1).astype("uint8")
        p = tmp_path / "img.jpg"
        Image.fromarray(arr).save(p, quality=95)
        raw = V.read_file(str(p))
        assert raw._data.dtype == np.uint8
        img = V.decode_jpeg(raw)
        assert tuple(img.shape) == (3, 16, 20)
        # lossy codec: just check it's close-ish
        got = np.asarray(img._data).transpose(1, 2, 0).astype("float32")
        assert np.abs(got - arr.astype("float32")).mean() < 15

    def test_decode_gray(self, tmp_path):
        from PIL import Image

        arr = (rng.uniform(0, 255, (8, 8, 3))).astype("uint8")
        p = tmp_path / "g.jpg"
        Image.fromarray(arr).save(p)
        img = V.decode_jpeg(V.read_file(str(p)), mode="gray")
        assert tuple(img.shape) == (1, 8, 8)


# ---------------------------------------------------------------------------
# vision misc tail (VERDICT r4 #4): numpy re-derivations of affine_grid_op.h,
# temporal_shift_op.h, correlation_op.cu, bilateral_slice_op.cu
# ---------------------------------------------------------------------------
class TestAffineGrid:
    @pytest.mark.parametrize("align", [True, False])
    def test_vs_numpy(self, align):
        rng = np.random.default_rng(0)
        theta = rng.standard_normal((2, 2, 3)).astype(np.float32)
        n, c, h, w = 2, 3, 4, 5
        got = np.asarray(V.affine_grid(
            paddle.to_tensor(theta), (n, c, h, w), align_corners=align)._data)

        def lin(cnt):
            s, e = -1.0, 1.0
            if align:
                step = (e - s) / (cnt - 1)
            else:
                step = (e - s) / cnt
                s = s * (cnt - 1) / cnt
            return s + np.arange(cnt) * step

        xs, ys = lin(w), lin(h)
        exp = np.zeros((n, h, w, 2), np.float32)
        for b in range(n):
            for i in range(h):
                for j in range(w):
                    base = np.array([xs[j], ys[i], 1.0])
                    exp[b, i, j] = theta[b] @ base
        np.testing.assert_allclose(got, exp, atol=1e-5, rtol=1e-5)

    def test_identity_theta_centers(self):
        theta = np.tile(np.array([[[1, 0, 0], [0, 1, 0]]], np.float32), (1, 1, 1))
        g = np.asarray(V.affine_grid(paddle.to_tensor(theta), (1, 1, 3, 3),
                                     align_corners=True)._data)
        np.testing.assert_allclose(g[0, 1, 1], [0.0, 0.0], atol=1e-6)
        np.testing.assert_allclose(g[0, 0, 0], [-1.0, -1.0], atol=1e-6)
        np.testing.assert_allclose(g[0, 2, 2], [1.0, 1.0], atol=1e-6)

    def test_grad_flows_to_theta(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.vision.ops import _affine_grid_op

        def loss(t):
            return jnp.sum(_affine_grid_op.__wrapped__(t, (3, 3), True) ** 2)

        g = jax.grad(loss)(jnp.ones((1, 2, 3), np.float32))
        assert np.all(np.isfinite(np.asarray(g)))


class TestTemporalShift:
    def test_vs_numpy(self):
        rng = np.random.default_rng(1)
        n, t, c, h, w = 2, 4, 8, 3, 3
        x = rng.standard_normal((n * t, c, h, w)).astype(np.float32)
        ratio = 0.25
        got = np.asarray(V.temporal_shift(paddle.to_tensor(x), t, ratio)._data)

        c1, c2 = int(c * ratio), int(c * 2 * ratio)
        xr = x.reshape(n, t, c, h, w)
        exp = np.zeros_like(xr)
        for it in range(t):
            # [0,c1): from it-1; [c1,c2): from it+1; rest: identity
            if it - 1 >= 0:
                exp[:, it, :c1] = xr[:, it - 1, :c1]
            if it + 1 < t:
                exp[:, it, c1:c2] = xr[:, it + 1, c1:c2]
            exp[:, it, c2:] = xr[:, it, c2:]
        np.testing.assert_allclose(got, exp.reshape(n * t, c, h, w))

    def test_nhwc_matches_nchw(self):
        rng = np.random.default_rng(2)
        x = rng.standard_normal((4, 8, 3, 3)).astype(np.float32)
        a = np.asarray(V.temporal_shift(paddle.to_tensor(x), 2, 0.25)._data)
        xb = np.transpose(x, (0, 2, 3, 1)).copy()
        b = np.asarray(V.temporal_shift(paddle.to_tensor(xb), 2, 0.25,
                                        data_format="NHWC")._data)
        np.testing.assert_allclose(a, np.transpose(b, (0, 3, 1, 2)), atol=1e-6)


class TestCorrelation:
    def test_vs_numpy(self):
        rng = np.random.default_rng(3)
        n, c, h, w = 1, 3, 8, 8
        pad, ksize, maxd, s1, s2 = 4, 1, 4, 1, 1
        x1 = rng.standard_normal((n, c, h, w)).astype(np.float32)
        x2 = rng.standard_normal((n, c, h, w)).astype(np.float32)
        got = np.asarray(V.correlation(
            paddle.to_tensor(x1), paddle.to_tensor(x2), pad, ksize, maxd,
            s1, s2)._data)

        krad = (ksize - 1) // 2
        drad = maxd // s2
        border = krad + maxd
        ph, pw = h + 2 * pad, w + 2 * pad
        out_h = int(np.ceil((ph - 2 * border) / s1))
        out_w = int(np.ceil((pw - 2 * border) / s1))
        a = np.pad(x1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        b = np.pad(x2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        D = 2 * drad + 1
        exp = np.zeros((n, D * D, out_h, out_w), np.float32)
        nelems = ksize * ksize * c
        for bi in range(n):
            for oh in range(out_h):
                for ow in range(out_w):
                    h1 = oh * s1 + maxd
                    w1 = ow * s1 + maxd
                    d = 0
                    for tj in range(-drad, drad + 1):
                        for ti in range(-drad, drad + 1):
                            h2, w2 = h1 + tj * s2, w1 + ti * s2
                            acc = 0.0
                            for j in range(-krad, krad + 1):
                                for i in range(-krad, krad + 1):
                                    acc += np.sum(
                                        a[bi, :, h1 + j, w1 + i]
                                        * b[bi, :, h2 + j, w2 + i])
                            exp[bi, d, oh, ow] = acc / nelems
                            d += 1
        assert got.shape == exp.shape
        np.testing.assert_allclose(got, exp, atol=1e-4, rtol=1e-4)


class TestBilateralSlice:
    def _np_ref(self, x, guide, grid, has_offset):
        n, ci, h, w = x.shape
        _, gc, gd, gh, gw = grid.shape
        stride = ci + 1 if has_offset else ci
        co = gc // stride
        out = np.zeros((n, co, h, w), np.float32)
        for b in range(n):
            for oc in range(co):
                for y in range(h):
                    for xx in range(w):
                        gx = (xx + 0.5) * gw / w
                        gy = (y + 0.5) * gh / h
                        gz = guide[b, y, xx] * gd
                        fx = int(np.floor(gx - 0.5))
                        fy = int(np.floor(gy - 0.5))
                        fz = int(np.floor(gz - 0.5))
                        val = 0.0
                        for in_c in range(stride):
                            cs = 0.0
                            for xi in range(fx, fx + 2):
                                x_ = min(max(xi, 0), gw - 1)
                                wx = max(1.0 - abs(xi + 0.5 - gx), 0.0)
                                for yi in range(fy, fy + 2):
                                    y_ = min(max(yi, 0), gh - 1)
                                    wy = max(1.0 - abs(yi + 0.5 - gy), 0.0)
                                    for zi in range(fz, fz + 2):
                                        z_ = min(max(zi, 0), gd - 1)
                                        wz = max(1.0 - abs(zi + 0.5 - gz), 0.0)
                                        c_ = stride * oc + in_c
                                        cs += grid[b, c_, z_, y_, x_] * wx * wy * wz
                            if in_c < ci:
                                val += cs * x[b, in_c, y, xx]
                            else:
                                val += cs
                        out[b, oc, y, xx] = val
        return out

    @pytest.mark.parametrize("has_offset", [False, True])
    def test_vs_numpy(self, has_offset):
        rng = np.random.default_rng(4)
        n, ci, h, w = 1, 3, 6, 6
        co, gd, gh, gw = 2, 4, 3, 3
        gc = co * (ci + 1) if has_offset else co * ci
        x = rng.standard_normal((n, ci, h, w)).astype(np.float32)
        guide = rng.uniform(0, 1, (n, h, w)).astype(np.float32)
        grid = rng.standard_normal((n, gc, gd, gh, gw)).astype(np.float32)
        got = np.asarray(V.bilateral_slice(
            paddle.to_tensor(x), paddle.to_tensor(guide),
            paddle.to_tensor(grid), has_offset)._data)
        exp = self._np_ref(x, guide, grid, has_offset)
        np.testing.assert_allclose(got, exp, atol=1e-4, rtol=1e-4)


class TestCorrelationKernel3:
    def test_vs_numpy_k3(self):
        """kernel_size=3 exercises the zero-filled combined
        displacement+kernel taps (the reference CUDA kernel reads out of
        bounds there; this op defines them as zeros)."""
        rng = np.random.default_rng(11)
        n, c, h, w = 1, 2, 8, 8
        pad, ksize, maxd, s1, s2 = 2, 3, 2, 1, 1
        x1 = rng.standard_normal((n, c, h, w)).astype(np.float32)
        x2 = rng.standard_normal((n, c, h, w)).astype(np.float32)
        got = np.asarray(V.correlation(
            paddle.to_tensor(x1), paddle.to_tensor(x2), pad, ksize, maxd,
            s1, s2)._data)

        krad = (ksize - 1) // 2
        drad = maxd // s2
        border = krad + maxd
        ph_, pw_ = h + 2 * pad, w + 2 * pad
        out_h = int(np.ceil((ph_ - 2 * border) / s1))
        out_w = int(np.ceil((pw_ - 2 * border) / s1))
        a = np.pad(x1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        b = np.pad(x2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))

        def read(arr, bi, ch, y, x):
            if 0 <= y < ph_ and 0 <= x < pw_:
                return arr[bi, ch, y, x]
            return 0.0

        D_ = 2 * drad + 1
        exp = np.zeros((n, D_ * D_, out_h, out_w), np.float32)
        nelems = ksize * ksize * c
        for oh in range(out_h):
            for ow in range(out_w):
                h1, w1 = oh * s1 + maxd, ow * s1 + maxd
                d = 0
                for tj in range(-drad, drad + 1):
                    for ti in range(-drad, drad + 1):
                        h2, w2 = h1 + tj * s2, w1 + ti * s2
                        acc = 0.0
                        for j in range(-krad, krad + 1):
                            for i in range(-krad, krad + 1):
                                for ch in range(c):
                                    acc += (read(a, 0, ch, h1 + j, w1 + i)
                                            * read(b, 0, ch, h2 + j, w2 + i))
                        exp[0, d, oh, ow] = acc / nelems
                        d += 1
        assert got.shape == exp.shape
        np.testing.assert_allclose(got, exp, atol=1e-4, rtol=1e-4)


class TestPrRoIPool:
    def test_vs_numerical_integration(self):
        """The closed-form tent-integral contraction must match brute-force
        numerical integration of the bilinear interpolant (prroi_pool_op.h
        PrRoIPoolingMatCalculation semantics)."""
        rng = np.random.default_rng(13)
        H = W = 8
        x = rng.standard_normal((1, 2, H, W)).astype(np.float32)
        boxes = np.array([[1.3, 0.7, 6.2, 5.9], [0.0, 0.0, 3.0, 3.0]],
                         np.float32)
        ph = pw = 2
        got = np.asarray(V.prroi_pool(
            paddle.to_tensor(x), paddle.to_tensor(boxes), np.array([2]),
            (ph, pw), 1.0)._data)

        def bilin(img, yy, xx):
            # zero outside the grid (PrRoIPoolingGetData)
            val = 0.0
            y0, x0 = int(np.floor(yy)), int(np.floor(xx))
            for (yi, wy_) in ((y0, 1 - (yy - y0)), (y0 + 1, yy - y0)):
                for (xi, wx_) in ((x0, 1 - (xx - x0)), (x0 + 1, xx - x0)):
                    if 0 <= yi < H and 0 <= xi < W:
                        val += wy_ * wx_ * img[yi, xi]
            return val

        S = 64  # quadrature points per axis
        for b in range(2):
            x1, y1, x2, y2 = boxes[b]
            bh, bw = (y2 - y1) / ph, (x2 - x1) / pw
            for c in range(2):
                for i in range(ph):
                    for j in range(pw):
                        ys = y1 + (i + (np.arange(S) + 0.5) / S) * bh
                        xs = x1 + (j + (np.arange(S) + 0.5) / S) * bw
                        acc = np.mean([bilin(x[0, c], yy, xx)
                                       for yy in ys for xx in xs])
                        np.testing.assert_allclose(
                            got[b, c, i, j], acc, atol=5e-3, rtol=5e-3)

    def test_grad_flows_to_input(self):
        import jax
        import jax.numpy as jnp

        x = jnp.asarray(np.random.default_rng(14).standard_normal((1, 1, 6, 6)),
                        jnp.float32)
        boxes = np.array([[0.5, 0.5, 5.0, 5.0]], np.float32)

        def loss(x):
            out = V.prroi_pool(x, boxes, np.array([1]), (2, 2), 1.0)
            a = out._data if hasattr(out, "_data") else out
            return jnp.sum(a ** 2)

        g = np.asarray(jax.grad(loss)(x))
        assert np.isfinite(g).all() and np.abs(g).sum() > 0
