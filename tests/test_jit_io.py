"""to_static / jit.save+load / paddle.save+load / DataLoader tests."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.jit import InputSpec, load as jit_load, save as jit_save, to_static


def _rand(*shape):
    return np.random.uniform(-1, 1, shape).astype(np.float32)


class Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(4, 8)
        self.fc2 = nn.Linear(8, 2)

    def forward(self, x):
        import paddle_tpu.nn.functional as F

        return self.fc2(F.relu(self.fc1(x)))


class TestToStatic:
    def test_matches_eager(self):
        net = Net()
        x = paddle.to_tensor(_rand(3, 4))
        eager = net(x).numpy()
        snet = to_static(Net())
        snet.set_state_dict(net.state_dict())
        got = snet(x).numpy()
        np.testing.assert_allclose(got, eager, atol=1e-6)

    def test_cache_reuse_and_retrace(self):
        net = to_static(Net())
        x3 = paddle.to_tensor(_rand(3, 4))
        x5 = paddle.to_tensor(_rand(5, 4))
        net(x3)
        net(x3)
        assert len(net.forward._cache) == 1
        net(x5)
        assert len(net.forward._cache) == 2

    def test_backward_through_jit(self):
        net = to_static(Net())
        x = paddle.to_tensor(_rand(6, 4))
        loss = net(x).sum()
        loss.backward()
        g = net.fc1.weight.grad
        assert g is not None and g.shape == [4, 8]
        # compare against eager clone
        net2 = Net()
        net2.set_state_dict(net.state_dict())
        loss2 = net2(x).sum()
        loss2.backward()
        np.testing.assert_allclose(g.numpy(), net2.fc1.weight.grad.numpy(), atol=1e-5)

    def test_training_with_jit_converges(self):
        paddle.seed(0)
        net = to_static(Net())
        o = opt.Adam(0.01, parameters=net.parameters())
        X = _rand(64, 4)
        w = _rand(4, 2)
        Y = (X @ w).argmax(1)
        for _ in range(100):
            loss = nn.CrossEntropyLoss()(net(paddle.to_tensor(X)), paddle.to_tensor(Y))
            o.clear_grad()
            loss.backward()
            o.step()
        assert float(loss) < 0.2

    def test_function_decorator(self):
        @to_static
        def f(x, y):
            return paddle.tanh(x) + y

        a, b = paddle.to_tensor(_rand(3)), paddle.to_tensor(_rand(3))
        np.testing.assert_allclose(f(a, b).numpy(), np.tanh(a.numpy()) + b.numpy(), atol=1e-6)

    def test_bn_buffer_update_under_jit(self):
        net = to_static(nn.BatchNorm1D(4, data_format="NC"))
        before = net._mean.numpy().copy()
        net.train()
        net(paddle.to_tensor(_rand(16, 4) + 3.0))
        after = net._mean.numpy()
        assert not np.allclose(before, after)

    def test_dropout_differs_across_jit_calls(self):
        net = to_static(nn.Dropout(0.5))
        x = paddle.to_tensor(np.ones((100,), np.float32))
        a, b = net(x).numpy(), net(x).numpy()
        assert not np.allclose(a, b)


class TestJitSaveLoad:
    def test_roundtrip(self):
        net = Net()
        net.eval()
        x = _rand(2, 4)
        want = net(paddle.to_tensor(x)).numpy()
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "model")
            jit_save(net, path, input_spec=[InputSpec([-1, 4], "float32")])
            assert os.path.exists(path + ".pdmodel")
            loaded = jit_load(path)
            got = loaded(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, want, atol=1e-6)


class TestSaveLoad:
    def test_state_dict_roundtrip(self):
        net = Net()
        o = opt.Adam(0.01, parameters=net.parameters())
        loss = net(paddle.to_tensor(_rand(4, 4))).sum()
        loss.backward()
        o.step()
        with tempfile.TemporaryDirectory() as d:
            paddle.save(net.state_dict(), os.path.join(d, "m.pdparams"))
            paddle.save(o.state_dict(), os.path.join(d, "m.pdopt"))
            net2 = Net()
            o2 = opt.Adam(0.01, parameters=net2.parameters())
            net2.set_state_dict(paddle.load(os.path.join(d, "m.pdparams")))
            o2.set_state_dict(paddle.load(os.path.join(d, "m.pdopt")))
        x = paddle.to_tensor(_rand(2, 4))
        np.testing.assert_allclose(net(x).numpy(), net2(x).numpy())
        assert o2._global_step == 1

    def test_nested_objects(self):
        obj = {"a": paddle.to_tensor(_rand(3)), "b": [1, "s", paddle.ones([2])]}
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "obj.pkl")
            paddle.save(obj, p)
            back = paddle.load(p)
        np.testing.assert_allclose(back["a"].numpy(), obj["a"].numpy())
        assert back["b"][1] == "s"


class TestDataLoader:
    def test_basic_batching(self):
        from paddle_tpu.io import DataLoader, TensorDataset

        xs, ys = _rand(10, 3), np.arange(10)
        dl = DataLoader(TensorDataset([xs, ys]), batch_size=4)
        batches = list(dl)
        assert len(batches) == 3
        assert batches[0][0].shape == [4, 3]
        assert batches[2][0].shape == [2, 3]
        np.testing.assert_allclose(batches[0][1].numpy(), [0, 1, 2, 3])

    def test_shuffle_drop_last(self):
        from paddle_tpu.io import DataLoader, TensorDataset

        dl = DataLoader(TensorDataset([np.arange(10)]), batch_size=3, shuffle=True, drop_last=True)
        batches = list(dl)
        assert len(batches) == 3
        seen = np.concatenate([b[0].numpy() for b in batches])
        assert len(set(seen.tolist())) == 9

    def test_custom_dataset_and_collate(self):
        from paddle_tpu.io import DataLoader, Dataset

        class DS(Dataset):
            def __len__(self):
                return 6

            def __getitem__(self, i):
                return {"x": np.full((2,), i, np.float32), "y": i}

        dl = DataLoader(DS(), batch_size=2)
        b = next(iter(dl))
        assert b["x"].shape == [2, 2] and b["y"].shape == [2]

    def test_multiprocess_workers(self):
        from paddle_tpu.io import DataLoader, Dataset

        class DS(Dataset):
            def __len__(self):
                return 20

            def __getitem__(self, i):
                return np.full((3,), i, np.float32)

        dl = DataLoader(DS(), batch_size=5, num_workers=2)
        batches = list(dl)
        assert len(batches) == 4
        np.testing.assert_allclose(batches[0].numpy()[:, 0], [0, 1, 2, 3, 4])

    def test_iterable_dataset(self):
        from paddle_tpu.io import DataLoader, IterableDataset

        class Stream(IterableDataset):
            def __iter__(self):
                for i in range(7):
                    yield np.float32(i)

        dl = DataLoader(Stream(), batch_size=3)
        batches = list(dl)
        assert len(batches) == 3 and batches[2].shape == [1]

    def test_distributed_batch_sampler(self):
        from paddle_tpu.io import DistributedBatchSampler, TensorDataset

        ds = TensorDataset([np.arange(10)])
        s0 = DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=0)
        s1 = DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=1)
        i0 = [i for b in s0 for i in b]
        i1 = [i for b in s1 for i in b]
        assert len(i0) == len(i1) == 5
        assert set(i0) | set(i1) == set(range(10))


def test_jit_save_bf16_precision_export(tmp_path):
    """Inference-optimization pass: precision='bfloat16' exports a bf16
    program (reference TRT fp16-mode analog)."""
    import jax.numpy as jnp

    import paddle_tpu.nn as nn
    from paddle_tpu.jit.input_spec import InputSpec
    from paddle_tpu.jit.save_load import load as jit_load
    from paddle_tpu.jit.save_load import save as jit_save

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    p = str(tmp_path / "m")
    jit_save(net, p, input_spec=[InputSpec([None, 8], "float32", "x")],
             precision="bfloat16")
    loaded = jit_load(p)
    # params restored as bf16
    lp = next(iter(loaded._loaded_params.values()))
    assert lp._data.dtype == jnp.bfloat16
    x = np.random.default_rng(0).normal(size=(3, 8)).astype("float32")
    want = np.asarray(net(paddle.to_tensor(x))._data)
    got = np.asarray(jnp.asarray(loaded(paddle.to_tensor(x))._data, jnp.float32))
    np.testing.assert_allclose(got, want, rtol=0.05, atol=0.05)  # bf16 tol


def test_jit_save_int8_weight_export(tmp_path):
    """Weight-only PTQ artifact: int8 + per-channel scales, dequantized at
    load (reference post-training quantization role)."""
    import os

    import paddle_tpu.nn as nn
    from paddle_tpu.jit.input_spec import InputSpec
    from paddle_tpu.jit.save_load import load as jit_load
    from paddle_tpu.jit.save_load import save as jit_save

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(64, 128), nn.ReLU(), nn.Linear(128, 8))
    spec = [InputSpec([None, 64], "float32", "x")]
    p32 = str(tmp_path / "f32")
    p8 = str(tmp_path / "i8")
    jit_save(net, p32, input_spec=spec)
    jit_save(net, p8, input_spec=spec, precision="int8")
    # artifact really shrinks
    sz32 = os.path.getsize(p32 + ".pdiparams")
    sz8 = os.path.getsize(p8 + ".pdiparams")
    assert sz8 < sz32 * 0.45, (sz8, sz32)
    loaded = jit_load(p8)
    x = np.random.default_rng(0).normal(size=(5, 64)).astype("float32")
    want = np.asarray(net(paddle.to_tensor(x))._data)
    got = np.asarray(loaded(paddle.to_tensor(x))._data)
    # int8 weight quantization error stays small for well-scaled layers
    denom = np.maximum(np.abs(want).max(), 1e-6)
    assert np.abs(got - want).max() / denom < 0.05
