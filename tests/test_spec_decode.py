"""Speculative decoding under the tick scheduler (ISSUE 18): exact-match
verify keeps greedy (and seeded sampled) output token-for-token identical
to the plain paged engine over staggered mixed-length requests — including
shared-prefix joins and COW — while acceptance / rollback accounting and
the ``serving.spec.verify`` fault seam (typed failure, plain-decode
fallback, two-run replay certificate) are pinned on CPU.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
from paddle_tpu.resilience.inject import FaultSchedule
from paddle_tpu.serving import (
    ContinuousBatchingEngine,
    Request,
    SpecDecodeConfig,
)

VOCAB = 64


def _tiny_model(seed=0):
    paddle.seed(seed)
    cfg = gpt_config("gpt2-small", vocab_size=VOCAB, hidden_size=32,
                     num_layers=2, num_attention_heads=4,
                     max_position_embeddings=64, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    m = GPTForPretraining(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _tiny_model(0)


@pytest.fixture(scope="module")
def draft():
    # independently-initialized draft: proposals are usually WRONG, so
    # the rejection/rollback paths run for real
    return _tiny_model(1)


def _mixed_prompts(rng, with_prefix=True):
    lens = [3, 5, 7, 4, 9, 6]
    prompts = [rng.integers(0, VOCAB, (l,)).astype(np.int32) for l in lens]
    news = [6, 4, 8, 5, 3, 7]
    if with_prefix:
        base = rng.integers(0, VOCAB, (8,)).astype(np.int32)  # 2 pages @4
        prompts.append(np.concatenate(
            [base, rng.integers(0, VOCAB, (3,)).astype(np.int32)]))
        prompts.append(base.copy())  # whole-prompt prefix hit -> COW
        news += [6, 5]
    return prompts, news


def _drive_staggered(eng, prompts, news, **req_kw):
    cut = len(prompts) - 3
    reqs = [eng.submit(Request(p, max_new_tokens=n, **req_kw))
            for p, n in zip(prompts[:cut], news[:cut])]
    for _ in range(3):
        eng.step_once()
    reqs += [eng.submit(Request(p, max_new_tokens=n, **req_kw))
             for p, n in zip(prompts[cut:], news[cut:])]
    eng.run_until_idle(timeout=300)
    return reqs


def _spec_engine(model, dm, k=3, **kw):
    return ContinuousBatchingEngine(
        model, max_seq_len=32, n_slots=4, prefill_buckets=[4, 8, 16],
        page_size=4, spec_decode=SpecDecodeConfig(dm, k=k), **kw)


def _plain_engine(model, **kw):
    return ContinuousBatchingEngine(
        model, max_seq_len=32, n_slots=4, prefill_buckets=[4, 8, 16],
        page_size=4, **kw)


class TestSpecExactness:
    def test_greedy_identical_to_baseline_self_draft(self, model):
        """Self-speculation (draft == target): every proposal accepted,
        output still token-for-token the baseline's (the acceptance
        criterion's replay certificate)."""
        rng = np.random.default_rng(0)
        prompts, news = _mixed_prompts(rng)
        want = [np.asarray(r.result()) for r in
                _drive_staggered(_plain_engine(model), prompts, news)]
        eng = _spec_engine(model, model, k=3)
        got = _drive_staggered(eng, prompts, news)
        for r, w in zip(got, want):
            assert r.state == Request.DONE, (r.state, r.error)
            np.testing.assert_array_equal(np.asarray(r.result()), w)
        sd = eng.metrics.snapshot()["spec_decode"]
        assert sd["acceptance_rate"] == 1.0
        assert sd["accepted_per_verify"] > 1.0
        # COW / prefix sharing engaged alongside speculation
        st = eng.page_state()
        assert st["prefix_hits"] >= 1

    def test_greedy_identical_to_baseline_real_draft(self, model, draft):
        """A draft that is usually WRONG: rejections, rollbacks, and the
        catch-up path all fire, and the output is still bit-identical —
        emitted tokens are always the target's own samples."""
        rng = np.random.default_rng(1)
        prompts, news = _mixed_prompts(rng)
        want = [np.asarray(r.result()) for r in
                _drive_staggered(_plain_engine(model), prompts, news)]
        eng = _spec_engine(model, draft, k=4)
        got = _drive_staggered(eng, prompts, news)
        for r, w in zip(got, want):
            assert r.state == Request.DONE, (r.state, r.error)
            np.testing.assert_array_equal(np.asarray(r.result()), w)
        sd = eng.metrics.snapshot()["spec_decode"]
        assert sd["acceptance_rate"] < 1.0  # real rejections happened
        assert sd["accepted_per_verify"] >= 1.0  # never slower than plain

    def test_sampled_identical_to_baseline(self, model):
        """temperature > 0 with an explicit seed: verify consumes the
        SAME per-slot key chain as the plain step (one split per emitted
        token), so even sampled streams replay bit-identically."""
        rng = np.random.default_rng(2)
        prompts = [rng.integers(0, VOCAB, (l,)).astype(np.int32)
                   for l in [3, 5, 7, 4]]
        news = [6, 5, 7, 6]

        def drive(eng):
            reqs = [eng.submit(Request(p, max_new_tokens=n, temperature=0.8,
                                       top_k=8, seed=123 + i))
                    for i, (p, n) in enumerate(zip(prompts, news))]
            eng.run_until_idle(timeout=300)
            return reqs

        want = [np.asarray(r.result()) for r in drive(_plain_engine(model))]
        got = drive(_spec_engine(model, model, k=3))
        for r, w in zip(got, want):
            assert r.state == Request.DONE, (r.state, r.error)
            np.testing.assert_array_equal(np.asarray(r.result()), w)


class TestSpecAccounting:
    def test_acceptance_counters_self_draft(self, model):
        eng = _spec_engine(model, model, k=3, prefix_sharing=False)
        rng = np.random.default_rng(3)
        reqs = [eng.submit(Request(
            rng.integers(0, VOCAB, (5,)).astype(np.int32), max_new_tokens=7))
            for _ in range(2)]
        eng.run_until_idle(timeout=300)
        assert all(r.state == Request.DONE for r in reqs)
        sd = eng.metrics.snapshot()["spec_decode"]
        assert sd["accepted"] == sd["proposed"]  # self-draft: all accepted
        assert sd["accepted"] <= sd["proposed"]
        # every verify emits [1, k+1] tokens
        assert sd["verify_steps"] <= sd["emitted"] \
            <= sd["accepted"] + sd["verify_steps"]
        assert sd["rollback_pages"] == 0  # nothing ever rejected

    def test_rollback_accounting_and_no_page_leak(self, model, draft):
        eng = _spec_engine(model, draft, k=4, prefix_sharing=False)
        rng = np.random.default_rng(4)
        reqs = [eng.submit(Request(
            rng.integers(0, VOCAB, (6,)).astype(np.int32),
            max_new_tokens=9)) for _ in range(3)]
        eng.run_until_idle(timeout=300)
        assert all(r.state == Request.DONE for r in reqs)
        sd = eng.metrics.snapshot()["spec_decode"]
        # a mostly-wrong draft must have had lookahead pages rolled back
        assert sd["accepted"] < sd["proposed"]
        assert sd["rollback_pages"] >= 1
        # rolled-back pages were actually RELEASED: pool drains to empty
        assert eng.page_state()["used"] == 0

    def test_emitted_tokens_counted_once(self, model):
        eng = _spec_engine(model, model, k=3, prefix_sharing=False)
        r = eng.submit(Request(np.arange(1, 6, dtype=np.int32),
                               max_new_tokens=8))
        eng.run_until_idle(timeout=300)
        assert r.state == Request.DONE
        assert eng.metrics.tokens_generated == 8
        sd = eng.metrics.snapshot()["spec_decode"]
        # the first token is sampled by prefill; spec emits the rest
        assert sd["emitted"] == 7

    def test_spec_requires_paged_layout(self, model):
        with pytest.raises(ValueError, match="paged"):
            ContinuousBatchingEngine(
                model, max_seq_len=32, n_slots=2, kv_layout="slot",
                spec_decode=SpecDecodeConfig(model, k=2))

    def test_bounded_compile(self, model):
        """Spec adds its OWN bounded program set (draft prefill buckets +
        draft step + verify) without disturbing the engine's gauge."""
        eng = _spec_engine(model, model, k=3)
        rng = np.random.default_rng(5)
        for _ in range(2):
            reqs = [eng.submit(Request(
                rng.integers(0, VOCAB, (5,)).astype(np.int32),
                max_new_tokens=6)) for _ in range(3)]
            eng.run_until_idle(timeout=300)
            assert all(r.state == Request.DONE for r in reqs)
        assert eng.trace_counts["step"] <= 1  # plain step possibly unused
        sc = eng._spec.trace_counts
        assert sc["verify"] == 1
        assert sc["draft_step"] == 1
        assert sc["draft_prefill"] <= len(eng.chunk_buckets)


class TestSpecVerifySeam:
    def _run(self, model, sched=None):
        eng = _spec_engine(model, model, k=3)
        rng = np.random.default_rng(6)
        prompts = [rng.integers(0, VOCAB, (l,)).astype(np.int32)
                   for l in [5, 7, 4]]
        reqs = [eng.submit(Request(p, max_new_tokens=n,
                                   request_id=f"r{i}"))
                for i, (p, n) in enumerate(zip(prompts, [8, 6, 7]))]
        if sched is not None:
            with sched:
                eng.run_until_idle(timeout=300)
        else:
            eng.run_until_idle(timeout=300)
        return eng, reqs

    def test_fault_fails_only_victim_and_falls_back(self, model):
        _, base = self._run(model)
        want = [np.asarray(r.result()) for r in base]
        s = FaultSchedule().add("serving.spec.verify", "raise", at=2)
        eng, got = self._run(model, s)
        failed = [r for r in got if r.state == Request.FAILED]
        done = [r for r in got if r.state == Request.DONE]
        assert len(failed) == 1 and len(done) == 2
        assert "speculative verify failed" in failed[0].error
        # survivors fell back to plain decode that tick AND stayed exact
        sd = eng.metrics.snapshot()["spec_decode"]
        assert sd["fallback_ticks"] >= 1
        for r, w in zip(got, want):
            if r.state == Request.DONE:
                np.testing.assert_array_equal(np.asarray(r.result()), w)
        # the seam labels the victim
        (f,) = s.fired_log()
        assert f["point"] == "serving.spec.verify"
        assert failed[0].request_id == f["labels"]["request_id"]

    def test_two_run_replay_certificate(self, model):
        """Same schedule, two runs: identical fired logs, identical
        terminal states, identical survivor transcripts."""
        s1 = FaultSchedule().add("serving.spec.verify", "raise", at=2)
        _, got1 = self._run(model, s1)
        s2 = FaultSchedule().add("serving.spec.verify", "raise", at=2)
        _, got2 = self._run(model, s2)
        assert s1.fired_log() == s2.fired_log()
        for a, b in zip(got1, got2):
            assert a.state == b.state
            if a.state == Request.DONE:
                np.testing.assert_array_equal(
                    np.asarray(a.result()), np.asarray(b.result()))


class TestSpecConfigValidation:
    def test_k_must_be_positive(self, model):
        with pytest.raises(ValueError):
            SpecDecodeConfig(model, k=0)

    def test_vocab_mismatch_rejected(self, model):
        paddle.seed(7)
        cfg = gpt_config("gpt2-small", vocab_size=32, hidden_size=32,
                         num_layers=2, num_attention_heads=4,
                         max_position_embeddings=64,
                         hidden_dropout_prob=0.0,
                         attention_dropout_prob=0.0)
        bad = GPTForPretraining(cfg)
        bad.eval()
        with pytest.raises(ValueError, match="vocab"):
            _spec_engine(model, bad)
