"""Replicated checkpoint data plane (ISSUE 15, r19).

Fast tier, all deterministic (no signals, no SIGKILL):

* state blob pack/unpack + global reassembly, chunked blob transport over
  the KV plane (head-last commit, CRC rejection, bandwidth gate),
* the local blob store's atomic-rename + CRC-sidecar protocol,
* save → replica push → manifest commit end to end; the visibility rule
  (an incomplete multi-rank snapshot is NEVER observable as a manifest),
* push-fault recovery (drop / garbage / torn re-pushed after the confirm
  timeout) with two-run replay certificates,
* scrub & repair: injected bit-rot is quarantined (renamed, never
  deleted), counted, flight-dumped and re-replicated from peers,
* the HEADLINE chaos twin: kill one of 3 dp ranks AND wipe its checkpoint
  directory mid-run → survivors recover from the newest committed
  manifest, a replacement rank with an EMPTY disk joins the recovery
  rendezvous and pulls every shard from peer replicas, and the trajectory
  is bit-identical to an uninterrupted run; identical fired logs across
  two runs; zero committed manifests lost or torn,
* elastic world GROWTH: a dp=2 cohort grows to dp=3 when a replacement
  joins mid-run; the post-growth trajectory is bit-identical to a fresh
  dp=3 run resumed from the same manifest,
* PreemptionGuard's deadline-capped emergency publish (a stalled
  replicated store — ``store.replica.append`` stall — cannot delay the
  exit protocol past the cap),
* the CheckpointManager._prune audit: pruning can never delete the newest
  INTACT snapshot even when the newest published snapshot is torn,
* the corrupt-snapshot fallback's first-class telemetry (counter + flight
  dump naming corrupt and loaded steps).
"""
import contextlib
import json
import os
import shutil
import threading
import time
import zlib

import numpy as np
import pytest

from paddle_tpu.distributed.fleet.elastic.manager import (
    ElasticManager,
    _TcpStore,
)
from paddle_tpu.distributed.fleet.utils.http_server import KVServer
from paddle_tpu.framework.checkpoint import (
    CheckpointManager,
    durable_write_bytes,
)
from paddle_tpu.observability.flight import flight_recorder
from paddle_tpu.observability.metrics import default_registry
from paddle_tpu.resilience import (
    BlobCorruptionError,
    BlobTransport,
    CheckpointDataPlane,
    DurabilityConfig,
    FaultSchedule,
    InjectedDeath,
    PreemptionGuard,
)
from paddle_tpu.resilience.durability import (
    _BandwidthGate,
    assemble_global_state,
    pack_state,
    unpack_state,
)
from paddle_tpu.resilience.elastic_trainer import ElasticDPTrainer


@pytest.fixture()
def kv():
    srv = KVServer().start()
    yield f"127.0.0.1:{srv.port}"
    srv.stop()


def _store(addr, job="job", ttl=2.0):
    return _TcpStore(addr, job, ttl=ttl, retries=1)


def _fast_cfg(**kw):
    kw.setdefault("replicas", 1)
    kw.setdefault("push_confirm_timeout_s", 0.25)
    kw.setdefault("manifest_timeout_s", 10.0)
    kw.setdefault("pull_hop_timeout_s", 1.0)
    return DurabilityConfig(**kw)


def _wait(pred, timeout=15.0, interval=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


# =====================================================================
# state blobs + transport
# =====================================================================
class TestStateBlobs:
    def test_pack_unpack_roundtrip(self):
        state = {"params": {"w": np.arange(6.0).reshape(2, 3),
                            "b": np.ones((3,), np.float32)},
                 "velocity": {"w": np.zeros((2, 3))},
                 "step": 7, "note": "hello"}
        out = unpack_state(pack_state(state))
        np.testing.assert_array_equal(out["params"]["w"],
                                      state["params"]["w"])
        assert out["params"]["b"].dtype == np.float32
        assert out["step"] == 7 and out["note"] == "hello"

    def test_assemble_concatenates_layout_paths_only(self):
        layout = {"/velocity/w": {"axis": 0, "world": 2}}
        s0 = {"params": {"w": np.arange(4.0)}, "velocity": {"w": np.ones((1, 2))},
              "step": 3}
        s1 = {"params": {"w": np.arange(4.0)}, "velocity": {"w": np.ones((1, 2)) * 2},
              "step": 3}
        g = assemble_global_state([s0, s1], layout)
        np.testing.assert_array_equal(g["velocity"]["w"],
                                      np.asarray([[1.0, 1.0], [2.0, 2.0]]))
        np.testing.assert_array_equal(g["params"]["w"], np.arange(4.0))
        assert g["step"] == 3


class TestBlobTransport:
    def test_roundtrip_and_chunk_bound(self, kv):
        st = _store(kv)
        tx = BlobTransport(st, chunk_bytes=64)
        data = os.urandom(500)
        head = tx.put("blob:a", data)
        assert head["chunks"] > 1 and head["nbytes"] == 500
        # every stored chunk record respects the configured bound
        for k, (v, _age) in st.scan(prefix="blob:a.c").items():
            assert len(v) <= tx.chunk_chars
        assert tx.get("blob:a") == data
        tx.delete("blob:a")
        assert tx.get("blob:a") is None
        assert st.scan(prefix="blob:a") == {}

    def test_head_last_commit_point(self, kv):
        """Chunks without a head are invisible — a reader can never
        observe a half-written transfer."""
        st = _store(kv)
        tx = BlobTransport(st, chunk_bytes=64)
        st.put("blob:b.c0", "QUJD")  # chunks present, head absent
        assert tx.get("blob:b") is None

    def test_corrupt_transfer_rejected(self, kv):
        st = _store(kv)
        tx = BlobTransport(st, chunk_bytes=1 << 16)
        data = b"x" * 100
        tx.put("blob:c", data)
        # rot one chunk in place: the head's CRC convicts it
        st.put("blob:c.c0", "Z" + st.get("blob:c.c0")[1:])
        with pytest.raises(BlobCorruptionError):
            tx.get("blob:c")

    def test_bandwidth_gate_bounds_inflight(self):
        gate = _BandwidthGate(100)
        gate.acquire(80)
        assert gate.inflight == 80
        blocked = threading.Event()

        def second():
            gate.acquire(50)  # 80+50 > 100: must wait
            blocked.set()
            gate.release(50)

        t = threading.Thread(target=second, daemon=True)
        t.start()
        time.sleep(0.1)
        assert not blocked.is_set()
        gate.release(80)
        t.join(5)
        assert blocked.is_set() and gate.inflight == 0
        # an oversize blob is admitted ALONE rather than deadlocking
        gate.acquire(500)
        gate.release(500)


class TestLocalBlobStore:
    def test_durable_write_bytes_atomic(self, tmp_path):
        p = str(tmp_path / "f.bin")
        durable_write_bytes(p, b"one")
        durable_write_bytes(p, b"two")
        assert open(p, "rb").read() == b"two"
        assert [n for n in os.listdir(tmp_path)
                if n.startswith(".tmp_")] == []

    def test_write_read_verify_and_quarantine(self, kv, tmp_path):
        plane = CheckpointDataPlane(_store(kv), "n0", str(tmp_path),
                                    _fast_cfg())
        try:
            plane._write_local(3, 0, b"payload", source="own")
            assert plane._read_local(3, 0) == b"payload"
            assert (3, 0) in plane.resident()
            # rot the file: read raises, quarantine renames (never deletes)
            path = plane._blob_path(3, 0)
            with open(path, "r+b") as f:
                f.write(b"XX")
            with pytest.raises(BlobCorruptionError):
                plane._read_local(3, 0)
            plane._quarantine(3, 0)
            assert (3, 0) not in plane.resident()
            q = os.listdir(plane.quarantine_dir)
            assert any(n.startswith("b_") and ".npz.q" in n for n in q)
        finally:
            plane.close()


# =====================================================================
# plane protocol: save -> push -> manifest commit; visibility rule
# =====================================================================
def _mk_state(step, rank, rows=2):
    return {"params": {"w": np.arange(8.0).reshape(4, 2)},
            "velocity": {"w": np.full((rows, 2), float(rank + 1))},
            "step": step}


_LAYOUT2 = {"/velocity/w": {"axis": 0, "world": 2}}


class TestPlaneProtocol:
    def test_save_replicate_commit_and_empty_disk_recovery(self, kv, tmp_path):
        members = ["node_0", "node_1"]
        p0 = CheckpointDataPlane(_store(kv), "node_0",
                                 str(tmp_path / "r0"), _fast_cfg())
        p1 = CheckpointDataPlane(_store(kv), "node_1",
                                 str(tmp_path / "r1"), _fast_cfg())
        try:
            p0.save_shard(3, _mk_state(3, 0), rank=0, world=2,
                          members=members, layout=_LAYOUT2)
            p1.save_shard(3, _mk_state(3, 1), rank=1, world=2,
                          members=members, layout=_LAYOUT2)
            _wait(lambda: p0.manifest(3) is not None, msg="manifest commit")
            m = p0.manifest(3)
            assert sorted(m["shards"]) == ["0", "1"]
            assert m["shards"]["0"]["owner"] == "node_0"
            assert m["shards"]["0"]["replicas"] == ["node_1"]
            # replicas became resident on the peers' DISKS
            _wait(lambda: (3, 1) in p0.resident(), msg="replica resident")
            assert (3, 0) in p1.resident()
            # a replacement rank with an EMPTY disk assembles the global
            # snapshot entirely from peer replicas, CRC-checked
            p2 = CheckpointDataPlane(_store(kv), "node_2",
                                     str(tmp_path / "r2"), _fast_cfg())
            try:
                state, layout = p2.load_step(3, timeout=10)
                np.testing.assert_array_equal(
                    state["velocity"]["w"],
                    np.asarray([[1.0, 1.0], [1.0, 1.0],
                                [2.0, 2.0], [2.0, 2.0]]))
                assert layout == _LAYOUT2
                # recovery restored redundancy: the pulled copies are now
                # resident and announced
                assert {(3, 0), (3, 1)} <= set(p2.resident())
            finally:
                p2.close()
        finally:
            p0.close()
            p1.close()

    def test_incomplete_snapshot_never_observable(self, kv, tmp_path):
        """Only rank 0 of a world-2 snapshot saves: NO manifest may ever
        appear — the commit requires every shard's ready record."""
        p0 = CheckpointDataPlane(
            _store(kv), "node_0", str(tmp_path / "r0"),
            _fast_cfg(manifest_timeout_s=0.5))
        try:
            p0.save_shard(5, _mk_state(5, 0), rank=0, world=2,
                          members=["node_0", "node_1"], layout=_LAYOUT2)
            time.sleep(1.2)  # past the commit deadline
            assert p0.manifest_steps() == []
            assert p0.newest_recoverable() is None
        finally:
            p0.close()

    def test_stale_ready_records_cannot_poison_recommit(self, kv, tmp_path):
        """Shard-ready records left behind by an ABANDONED commit must
        never satisfy a later commit of the same step number (the step is
        re-executed after an elastic regroup, under a HIGHER rendezvous
        generation): the manifest would carry CRCs matching no surviving
        data, and every recovery pull would then fail its manifest CRC
        check. The generation fence holds the commit until the
        re-executed save publishes fresh records."""
        members = ["node_0", "node_1"]
        admin = _store(kv)
        stale_crc = 1234567
        for j in (0, 1):
            admin.put(f"ckrdy:9:{j}",
                      json.dumps({"owner": members[j], "replicas": [],
                                  "crc": stale_crc, "generation": 1,
                                  "nbytes": 11}))
        p0 = CheckpointDataPlane(_store(kv), "node_0",
                                 str(tmp_path / "r0"), _fast_cfg())
        p1 = CheckpointDataPlane(_store(kv), "node_1",
                                 str(tmp_path / "r1"), _fast_cfg())
        try:
            p0.save_shard(9, _mk_state(9, 0), rank=0, world=2,
                          members=members, layout=_LAYOUT2, generation=2)
            p1.save_shard(9, _mk_state(9, 1), rank=1, world=2,
                          members=members, layout=_LAYOUT2, generation=2)
            _wait(lambda: p0.manifest(9) is not None, msg="recommit")
            m = p0.manifest(9)
            assert m["generation"] == 2
            assert all(int(info["crc"]) != stale_crc
                       for info in m["shards"].values())
            # the committed snapshot actually assembles, CRC-clean
            state, _layout = p0.load_step(9, timeout=10)
            np.testing.assert_array_equal(
                state["velocity"]["w"],
                np.asarray([[1.0, 1.0], [1.0, 1.0],
                            [2.0, 2.0], [2.0, 2.0]]))
        finally:
            p0.close()
            p1.close()

    def test_retired_manifests_gcd_and_blobs_pruned_on_every_rank(
            self, kv, tmp_path):
        """Rotation past ``keep_manifests``: the committer DELETES the
        retired manifests (and residency receipts) from the store, and
        every rank — replica holders included, not just the committer —
        prunes the backing blobs; retained snapshots keep loading."""
        members = ["node_0", "node_1"]
        cfg = lambda: _fast_cfg(keep_manifests=2)  # noqa: E731
        p0 = CheckpointDataPlane(_store(kv), "node_0",
                                 str(tmp_path / "r0"), cfg())
        p1 = CheckpointDataPlane(_store(kv), "node_1",
                                 str(tmp_path / "r1"), cfg())
        try:
            for s in (1, 2, 3, 4, 5):
                p0.save_shard(s, _mk_state(s, 0), rank=0, world=2,
                              members=members, layout=_LAYOUT2)
                p1.save_shard(s, _mk_state(s, 1), rank=1, world=2,
                              members=members, layout=_LAYOUT2)
                _wait(lambda s=s: p0.manifest(s) is not None,
                      msg=f"manifest {s}")
            _wait(lambda: p0.manifest_steps() == [4, 5],
                  msg="manifest retirement")
            # no stale advertisement: receipts for retired steps are gone
            assert p0.store.scan(keys_only=True, prefix="ckres:1:") == {}
            # blobs pruned on BOTH ranks once the worker's prune tick ran
            _wait(lambda: {s for s, _j in p0.resident()} <= {4, 5},
                  msg="committer blobs pruned")
            _wait(lambda: {s for s, _j in p1.resident()} <= {4, 5},
                  msg="replica-holder blobs pruned")
            # retained snapshots still assemble
            state, _ = p1.load_step(5, timeout=10)
            assert int(state["step"]) == 5
        finally:
            p0.close()
            p1.close()

    def test_coverage_lost_manifest_walked_past(self, kv, tmp_path):
        """The cluster-level newest-intact rule: a manifest whose shard
        has NO live holder is walked past; the newest manifest with full
        live coverage wins."""
        st = _store(kv)
        plane = CheckpointDataPlane(st, "node_0", str(tmp_path),
                                    _fast_cfg())
        try:
            m1 = {"step": 1, "world": 2, "layout": {}, "shards": {
                "0": {"owner": "node_0", "replicas": ["node_1"],
                      "crc": 1, "nbytes": 1},
                "1": {"owner": "node_1", "replicas": ["node_0"],
                      "crc": 2, "nbytes": 1}}}
            # step 2 committed with shard 1 resident ONLY on node_1
            m2 = {"step": 2, "world": 2, "layout": {}, "shards": {
                "0": {"owner": "node_0", "replicas": [],
                      "crc": 3, "nbytes": 1},
                "1": {"owner": "node_1", "replicas": [],
                      "crc": 4, "nbytes": 1}}}
            st.put("ckmf:%012d" % 1, json.dumps(m1))
            st.put("ckmf:%012d" % 2, json.dumps(m2))
            # node_1 died: step 2's shard 1 has no live holder left, but
            # step 1's shard 1 replica lives on node_0
            assert plane.newest_recoverable(["node_0"]) == 1
            # with node_1 alive the newest manifest wins
            assert plane.newest_recoverable(["node_0", "node_1"]) == 2
            # the asking node always counts itself live (it IS running)
            assert plane.newest_recoverable([]) == 1
        finally:
            plane.close()


class TestPushFaults:
    def _run_leg(self, tmp_path, tag, kind):
        srv = KVServer().start()
        sched = FaultSchedule(seed=3).add(
            "ckpt.replica.push", kind, at=1, match={"peer": "node_1"})
        try:
            with sched.scope():
                p0 = CheckpointDataPlane(
                    _store(f"127.0.0.1:{srv.port}"), "node_0",
                    str(tmp_path / f"r0_{tag}"), _fast_cfg())
            p1 = CheckpointDataPlane(
                _store(f"127.0.0.1:{srv.port}"), "node_1",
                str(tmp_path / f"r1_{tag}"), _fast_cfg())
            try:
                p0.save_shard(4, _mk_state(4, 0), rank=0, world=2,
                              members=["node_0", "node_1"], layout=_LAYOUT2)
                p1.save_shard(4, _mk_state(4, 1), rank=1, world=2,
                              members=["node_0", "node_1"], layout=_LAYOUT2)
                _wait(lambda: p0.manifest(4) is not None,
                      msg=f"manifest after {kind} push fault")
                _wait(lambda: (4, 0) in p1.resident(),
                      msg="replica resident after re-push")
                # the replica the peer persisted is the CLEAN bytes
                assert zlib.crc32(p1._read_local(4, 0)) == int(
                    p0.manifest(4)["shards"]["0"]["crc"])
            finally:
                p0.close()
                p1.close()
        finally:
            srv.stop()
        return sched.fired_log()

    @pytest.mark.parametrize("kind", ["drop", "garbage", "torn"])
    def test_faulted_push_repushed_and_replay_deterministic(
            self, tmp_path, kind):
        """A dropped/corrupted/truncated push costs one confirm timeout,
        never the snapshot: the owner re-pushes, the receiver CRC-gates,
        and the manifest still commits. Two runs fire identically."""
        log_a = self._run_leg(tmp_path, f"{kind}_a", kind)
        log_b = self._run_leg(tmp_path, f"{kind}_b", kind)
        assert log_a == log_b
        assert [(f["point"], f["kind"], f["count"]) for f in log_a] == [
            ("ckpt.replica.push", kind, 1)]


# =====================================================================
# scrub & repair
# =====================================================================
class TestScrubRepair:
    def test_injected_bitrot_quarantined_counted_dumped_repaired(
            self, kv, tmp_path):
        members = ["node_0", "node_1"]
        p0 = CheckpointDataPlane(_store(kv), "node_0",
                                 str(tmp_path / "r0"), _fast_cfg())
        p1 = CheckpointDataPlane(_store(kv), "node_1",
                                 str(tmp_path / "r1"), _fast_cfg())
        try:
            p0.save_shard(2, _mk_state(2, 0), rank=0, world=2,
                          members=members, layout=_LAYOUT2)
            p1.save_shard(2, _mk_state(2, 1), rank=1, world=2,
                          members=members, layout=_LAYOUT2)
            _wait(lambda: p0.manifest(2) is not None
                  and (2, 1) in p0.resident(), msg="replicated snapshot")
            c0 = p0._c_scrub.value(node="node_0")
            # deterministic bit-rot on the FIRST resident blob only
            sched = FaultSchedule(seed=5).add(
                "ckpt.scrub.corrupt", "corrupt", at=1)
            with sched.scope():
                found = p0.scrub_once()
            assert found["corrupt"] == 1 and found["checked"] >= 2
            assert found["repaired"] == 1
            assert p0._c_scrub.value(node="node_0") == c0 + 1
            # quarantine holds the forensic copy (renamed, not deleted)
            assert any(".npz.q" in n
                       for n in os.listdir(p0.quarantine_dir))
            # the flight recorder froze the episode
            dump = flight_recorder().last
            assert dump is not None
            assert dump["reason"] == "ckpt_scrub_corruption"
            assert dump["extra"]["node"] == "node_0"
            # repair restored the clean copy from the peer: CRC matches
            # the manifest again and BOTH blobs are resident + intact
            m = p0.manifest(2)
            for j in (0, 1):
                data = p0._read_local(2, j)
                assert data is not None
                assert zlib.crc32(data) == int(m["shards"][str(j)]["crc"])
        finally:
            p0.close()
            p1.close()

    def test_scrub_never_touches_intact_copies(self, kv, tmp_path):
        plane = CheckpointDataPlane(_store(kv), "n0", str(tmp_path),
                                    _fast_cfg())
        try:
            plane._write_local(1, 0, b"alpha", source="own")
            plane._write_local(2, 0, b"beta", source="own")
            found = plane.scrub_once()
            assert found == {"checked": 2, "corrupt": 0, "repaired": 0}
            assert plane._read_local(1, 0) == b"alpha"
            assert plane._read_local(2, 0) == b"beta"
            assert os.listdir(plane.quarantine_dir) == []
        finally:
            plane.close()


# =====================================================================
# elastic cohort harness (threads; per-rank private checkpoint dirs)
# =====================================================================
_W_STAR = np.arange(12.0).reshape(4, 3) / 10.0


def _dp_grad_fn(params, step, rank, world):
    rng = np.random.default_rng(500000 + 1000 * step + 10 * world + rank)
    X = rng.standard_normal((8, 4))
    E = X @ params["w"] + params["b"] - X @ _W_STAR
    loss = float((E ** 2).mean())
    return loss, {"w": 2 * X.T @ E / E.size,
                  "b": 2 * E.sum(axis=0) / E.size}


def _dp_init_params():
    return {"w": np.zeros((4, 3)), "b": np.zeros((3,))}


class _Cohort:
    """Drive ElasticDPTrainer rank THREADS (durability mode, per-rank
    dirs) over one KV server; ranks can be added mid-run (growth /
    replacement)."""

    def __init__(self, addr, job, base_dir, total, ttl=1.2):
        self.addr = addr
        self.job = job
        self.base = base_dir
        self.total = total
        self.ttl = ttl
        self.hist = {}
        self.events = {}
        self.errors = {}
        self.threads = {}

    def start_rank(self, idx, node, *, schedule=None, resume_step=None,
                   wait_world=None):
        self.hist.setdefault(node, [])
        self.events.setdefault(node, [])

        def run():
            st = _TcpStore(self.addr, self.job, ttl=self.ttl, retries=1)
            mgr = ElasticManager(store=st)
            mgr.endpoint = f"127.0.0.1:{7800 + idx}"
            mgr.node_id = node
            tr = ElasticDPTrainer(
                mgr, os.path.join(self.base, node), _dp_grad_fn,
                _dp_init_params, lr=0.3, momentum=0.9, min_ranks=1,
                step_timeout=60, rendezvous_timeout=60,
                durability=_fast_cfg(),
                on_step=lambda s, w, l: self.hist[node].append(
                    (s, w, np.float64(l).hex())),
                on_event=self.events[node].append)
            ctx = (schedule.scope() if schedule is not None
                   else contextlib.nullcontext())
            try:
                with ctx:
                    tr.run(self.total, resume_step=resume_step,
                           wait_world=wait_world)
            except InjectedDeath:
                self.events[node].append("DIED")
                return
            except Exception as e:  # pragma: no cover - surfaced by join
                self.errors[node] = e
                raise
            tr.close()

        t = threading.Thread(target=run, daemon=True)
        self.threads[node] = t
        t.start()
        return t

    def join(self, timeout=240):
        for node, t in self.threads.items():
            t.join(timeout)
            assert not t.is_alive(), f"rank thread {node} hung"
        assert not self.errors, self.errors

    def steps(self, node, world=None):
        return {s: (w, l) for s, w, l in self.hist[node]
                if world is None or w == world}


# =====================================================================
# HEADLINE: disk-loss chaos twin
# =====================================================================
class TestDiskLossChaos:
    TOTAL = 6
    KILL_STEP = 3

    def _chaos_leg(self, tmp_path, tag):
        srv = KVServer().start()
        addr = f"127.0.0.1:{srv.port}"
        sched = FaultSchedule(seed=11).add(
            "ckpt.disk.loss", "kill", match={"step": self.KILL_STEP})
        co = _Cohort(addr, f"job_{tag}", str(tmp_path / tag), self.TOTAL)
        try:
            for i in range(3):
                co.start_rank(i, f"node_{i}",
                              schedule=sched if i == 2 else None,
                              wait_world=3)
            _wait(lambda: "DIED" in co.events["node_2"], timeout=120,
                  msg="victim death")
            # the victim's disk is GONE with it
            assert not os.path.exists(
                os.path.join(str(tmp_path / tag), "node_2"))
            # replacement with an EMPTY disk joins the recovery rendezvous
            co.start_rank(3, "node_3", wait_world=1)
            co.join()
            # snapshot the committed manifests before the store goes down
            manifests = dict(_TcpStore(addr, f"job_{tag}", ttl=5.0,
                                       retries=1).scan(prefix="ckmf:"))
        finally:
            srv.stop()
        return co, sched.fired_log(), manifests

    def _verify_no_manifest_lost(self, tmp_path, tag, manifests):
        """Re-serve the surviving ranks' blob dirs under a fresh store and
        prove every step that ever committed a manifest still assembles
        CRC-clean — zero committed snapshots lost, zero observed torn."""
        assert manifests, "no manifests ever committed"
        srv = KVServer().start()
        addr = f"127.0.0.1:{srv.port}"
        dst = _TcpStore(addr, "verify", ttl=5.0, retries=1)
        for k, (v, _age) in manifests.items():
            dst.put(k, v)
        planes = []
        try:
            for node in ("node_0", "node_1", "node_3"):
                d = os.path.join(str(tmp_path / tag), node)
                if os.path.exists(d):
                    planes.append(CheckpointDataPlane(
                        _store(addr, "verify"), node, d, _fast_cfg()))
            verifier = CheckpointDataPlane(
                _store(addr, "verify"), "verifier",
                str(tmp_path / f"verify_{tag}"), _fast_cfg())
            planes.append(verifier)
            steps = verifier.manifest_steps()
            assert steps
            for s in steps:
                state, _layout = verifier.load_step(s, timeout=30)
                assert int(state["step"]) == s
            return steps
        finally:
            for p in planes:
                p.close()
            srv.stop()

    def test_disk_loss_recovery_bit_identical_and_replayable(self, tmp_path):
        co_a, log_a, manifests_a = self._chaos_leg(tmp_path, "a")
        committed = self._verify_no_manifest_lost(tmp_path, "a",
                                                  manifests_a)
        co_b, log_b, _manifests_b = self._chaos_leg(tmp_path, "b")

        # replay certificate: identical fired logs across the two runs
        assert log_a == log_b == [
            {"point": "ckpt.disk.loss", "kind": "kill", "count": 1,
             "labels": {"rank": 2, "step": self.KILL_STEP,
                        "node": "node_2"}}]

        # survivors + replacement covered every step at dp=3, identically
        for co in (co_a, co_b):
            s0 = co.steps("node_0")
            assert sorted(s0) == list(range(self.TOTAL))
            assert all(w == 3 for w, _l in s0.values())
            assert co.steps("node_1") == s0
            # the victim never got past the kill step
            assert max(s for s, _w, _l in co.hist["node_2"]) < self.KILL_STEP
            # the replacement's steps agree with the survivors'
            s3 = co.steps("node_3")
            assert s3 and all(s0[s] == v for s, v in s3.items())
            # exactly one recovery, resharded from a committed manifest
            recover = [e for e in co.events["node_0"]
                       if e.startswith("restore: snapshot")]
            assert len(recover) == 1, co.events["node_0"]
        assert co_a.steps("node_0") == co_b.steps("node_0")

        # bit-identical to the UNINTERRUPTED run: a fresh dp=3 cohort
        # with no chaos produces the same per-step losses
        srv = KVServer().start()
        co_u = _Cohort(f"127.0.0.1:{srv.port}", "job_u",
                       str(tmp_path / "u"), self.TOTAL)
        try:
            for i in range(3):
                co_u.start_rank(i, f"node_{i}", wait_world=3)
            co_u.join()
        finally:
            srv.stop()
        assert co_u.steps("node_0") == co_a.steps("node_0")
        # and the manifests the chaos run committed survived it all
        assert committed


# =====================================================================
# elastic world GROWTH during recovery (satellite)
# =====================================================================
class TestWorldGrowth:
    TOTAL = 6

    def test_growth_reshard_bit_identical_to_fresh_dp3(self, tmp_path):
        srv = KVServer().start()
        addr = f"127.0.0.1:{srv.port}"
        co = _Cohort(addr, "job_g", str(tmp_path / "g"), self.TOTAL)
        try:
            for i in range(2):
                co.start_rank(i, f"node_{i}", wait_world=2)
            # let the dp=2 cohort commit at least one manifest, then grow
            _wait(lambda: len(co.hist["node_0"]) >= 2, timeout=60,
                  msg="dp=2 progress")
            co.start_rank(2, "node_2", wait_world=1)
            co.join()
            manifests = dict(_TcpStore(addr, "job_g", ttl=5.0,
                                       retries=1).scan(prefix="ckmf:"))
        finally:
            srv.stop()

        # the cohort grew: a recovery rendezvous committed dp=3 and
        # resharded the dp=2 manifest onto three ranks — including the
        # JOINER, whose disk was empty (it pulled every shard from peers)
        s0 = co.steps("node_0")
        assert sorted(s0) == list(range(self.TOTAL))
        grown = {s: v for s, v in co.steps("node_0", world=3).items()}
        assert grown, "cohort never grew to dp=3"
        recover = [e for e in co.events["node_0"]
                   if e.startswith("restore: snapshot")]
        assert len(recover) == 1, co.events["node_0"]
        snap = int(recover[0].split("step=")[1].split()[0])
        assert "resharded to world=3" in recover[0]
        # the empty-disk joiner's steps agree with the incumbents'
        joiner = co.steps("node_2", world=3)
        assert joiner and all(grown[s] == v for s, v in joiner.items())

        # fresh dp=3 arm resumed from the SAME manifest: node_0/node_1
        # bring copies of their dirs, node_2 starts empty; the manifests
        # are copied into the fresh store
        srv2 = KVServer().start()
        addr2 = f"127.0.0.1:{srv2.port}"
        base2 = str(tmp_path / "g3")
        for node in ("node_0", "node_1"):
            shutil.copytree(os.path.join(str(tmp_path / "g"), node),
                            os.path.join(base2, node))
        dst = _TcpStore(addr2, "job_g3", ttl=5.0, retries=1)
        for k, (v, _age) in manifests.items():
            dst.put(k, v)
        co3 = _Cohort(addr2, "job_g3", base2, self.TOTAL)
        try:
            for i in range(3):
                co3.start_rank(i, f"node_{i}", resume_step=snap,
                               wait_world=3)
            co3.join()
        finally:
            srv2.stop()
        fresh = co3.steps("node_0")
        assert co3.steps("node_1") == fresh
        # the acceptance criterion: post-growth trajectory bit-identical
        # to the fresh dp=3 run from the same snapshot
        post = {s: v for s, v in grown.items() if s > snap}
        assert post
        assert {s: v for s, v in fresh.items() if s > snap} == post


# =====================================================================
# PreemptionGuard: deadline-capped emergency publish (satellite)
# =====================================================================
class TestPreemptionPublish:
    def test_emergency_flush_makes_final_step_peer_recoverable(
            self, kv, tmp_path):
        """The dying rank's final shard reaches its peer through the
        capped flush even though its own worker never runs (interval
        pinned huge) — its disk can then vanish and the snapshot still
        commits and assembles from the survivors."""
        members = ["node_0", "node_1", "node_2"]
        layout3 = {"/velocity/w": {"axis": 0, "world": 3}}
        p0 = CheckpointDataPlane(_store(kv), "node_0",
                                 str(tmp_path / "r0"), _fast_cfg())
        p1 = CheckpointDataPlane(_store(kv), "node_1",
                                 str(tmp_path / "r1"), _fast_cfg())
        # the DYING rank: frozen worker, everything must ride the flush
        p2 = CheckpointDataPlane(_store(kv), "node_2",
                                 str(tmp_path / "r2"),
                                 _fast_cfg(worker_interval_s=999.0))
        try:
            for rank, plane in enumerate((p0, p1, p2)):
                plane.save_shard(
                    7, {"params": {"w": np.arange(8.0).reshape(4, 2)},
                        "velocity": {"w": np.full((1, 2), float(rank))},
                        "step": 7},
                    rank=rank, world=3, members=members, layout=layout3)
            out = p2.emergency_flush(deadline_s=5.0)
            assert out["pushed"] >= 1 and out["ready"] >= 1
            # p2's shard reached its replica peer's DISK
            assert (7, 2) in p0.resident()
            _wait(lambda: p0.manifest(7) is not None, msg="manifest")
            # the dying rank's disk goes away — the step survives
            p2.wipe()
            verifier = CheckpointDataPlane(_store(kv), "node_9",
                                           str(tmp_path / "r9"),
                                           _fast_cfg())
            try:
                state, _ = verifier.load_step(7, timeout=15)
                assert int(state["step"]) == 7
                np.testing.assert_array_equal(
                    state["velocity"]["w"],
                    np.asarray([[0.0, 0.0], [1.0, 1.0], [2.0, 2.0]]))
            finally:
                verifier.close()
        finally:
            for p in (p0, p1, p2):
                p.close()

    def test_stalled_store_cannot_delay_exit_past_cap(self, tmp_path):
        """A publisher blocked on a stalled replicated store
        (``store.replica.append`` stall seam) is abandoned at the cap;
        the local emergency save and the exit protocol are unaffected."""
        from paddle_tpu.distributed.fleet.utils.replicated_store import (
            ReplicatedStoreCluster,
        )

        with ReplicatedStoreCluster(3, lease_ttl=0.5) as cl:
            cl.leader(timeout=30)
            # a production-shaped client: generous TTL and retry budget,
            # so a stalled store burns real backoff for many seconds —
            # exactly what the publish cap must cut off
            st = _TcpStore(cl.addr_spec, "pubjob", ttl=60.0, retries=5)
            st.put("warm", "1")  # leader discovered before the stall arms
            mgr = CheckpointManager(str(tmp_path))
            sched = FaultSchedule(seed=9).add(
                "store.replica.append", "stall", every=1, seconds=4.0)
            guard = PreemptionGuard(
                mgr, publisher=lambda step: st.put(f"final{step}", "x"),
                publish_deadline_s=1.0)
            guard.update(5, {"w": np.arange(3.0), "step": 5})
            sched.arm()
            try:
                t0 = time.monotonic()
                saved = guard.preempt_now(reason="test")
                wall = time.monotonic() - t0
            finally:
                sched.disarm()
            assert saved is True
            assert guard.saved_step == 5
            assert mgr.all_steps() == [5]
            # the stalled publish was cut at the 1s cap, not the 8s+ the
            # stalled quorum appends would have taken
            assert wall < 3.0, wall
            assert guard.publish_completed is False

    def test_publisher_runs_within_cap_flag(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        hit = []
        guard = PreemptionGuard(mgr, publisher=lambda step: hit.append(step),
                                publish_deadline_s=2.0)
        guard.update(3, {"w": np.ones(2)})
        assert guard.preempt_now(reason="test") is True
        assert hit == [3]
        assert guard.publish_completed is True


# =====================================================================
# CheckpointManager._prune audit (satellite)
# =====================================================================
class TestPruneAudit:
    def test_torn_newest_publish_cannot_evict_newest_intact(self, tmp_path):
        """keep_max=1 + a torn publish of step 2: pruning must spare
        step 1 (the newest INTACT snapshot) even though by step-count it
        is past the keep window — otherwise the newest-intact fallback
        has nothing left to fall back to."""
        mgr = CheckpointManager(str(tmp_path), keep_max=1)
        mgr.save(1, {"w": np.arange(4.0)})
        sched = FaultSchedule(seed=2).add("checkpoint.write", "torn", at=1)
        with sched.scope():
            mgr.save(2, {"w": np.arange(4.0) * 2})
        assert set(mgr.all_steps()) == {1, 2}  # step 1 spared
        with pytest.warns(RuntimeWarning, match="corrupt"):
            state, _ = mgr.load()
        assert mgr.last_loaded_step == 1
        np.testing.assert_array_equal(state["w"], np.arange(4.0))

    def test_async_save_with_torn_publish_keeps_newest_intact(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_max=1, async_save=True)
        mgr.save(1, {"w": np.arange(3.0)})
        mgr.wait()
        sched = FaultSchedule(seed=2).add("checkpoint.write", "torn", at=1)
        sched.arm()  # async writer thread: thread-local scope won't reach
        try:
            mgr.save(2, {"w": np.arange(3.0) * 3})
            mgr.wait()
        finally:
            sched.disarm()
        assert 1 in mgr.all_steps()
        with pytest.warns(RuntimeWarning, match="corrupt"):
            mgr.load()
        assert mgr.last_loaded_step == 1

    def test_prune_still_evicts_when_kept_set_is_intact(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_max=2)
        for s in (1, 2, 3, 4):
            mgr.save(s, {"w": np.full(3, float(s))})
        assert mgr.all_steps() == [3, 4]


# =====================================================================
# corrupt-fallback telemetry (satellite)
# =====================================================================
class TestCorruptionFallbackTelemetry:
    def test_counter_and_flight_dump_on_fallback(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_max=10)
        mgr.save(1, {"w": np.arange(4.0)})
        mgr.save(2, {"w": np.arange(4.0) * 2})
        good = tmp_path / "step_2"
        torn = tmp_path / "step_3"
        shutil.copytree(good, torn)
        blob = (torn / "meta.json").read_bytes()
        (torn / "meta.json").write_bytes(blob[: len(blob) // 2])
        ctr = default_registry().counter(
            "ckpt_corruption_fallbacks_total",
            "corrupt snapshots skipped by the newest-intact fallback",
            ("directory",))
        before = ctr.value(directory=str(tmp_path))
        with pytest.warns(RuntimeWarning, match="corrupt"):
            mgr.load()
        assert mgr.last_loaded_step == 2
        assert ctr.value(directory=str(tmp_path)) == before + 1
        dump = flight_recorder().last
        assert dump is not None
        assert dump["reason"] == "ckpt_corruption_fallback"
        assert dump["extra"]["corrupt_steps"] == [3]
        assert dump["extra"]["loaded_step"] == 2
