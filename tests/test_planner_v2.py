"""Auto-parallel planner v2 (analysis.plan): static-analysis-driven search.

Covers the ISSUE-13 acceptance surface:

* first-class collective models with hand-computed bytes (the honest
  ZeRO / MoE pricing terms);
* abstract lowering fidelity — the ShapeDtypeStruct trainer builds the
  bit-identical jaxpr of the concrete trainer, at zero allocation;
* the ROADMAP-mandated validation: planner v2 reproduces the known-good
  1.3B single-chip config (remat REQUIRED and chosen) and refuses the
  measured BENCH_r02 16 GB OOM config (f32 moments), both on lowered-but-
  never-executed 1.3B targets;
* <0.5% self-consistency between the chosen plan's recorded peak and a
  fresh liveness estimate on the same target (equality by construction),
  with the legacy-constant fallback still drift-checked;
* the planner-emitted jax.checkpoint policy: bit-identical trajectories
  where remat is optional, identical jaxpr where no remat is planned;
* the --plan CLI exit contract and the committed plan_table.json artifact.
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis.cost import (
    all_gather_bytes,
    all_to_all_bytes,
    collective_comm_bytes,
    cost_eqn,
    reduce_scatter_bytes,
    ring_all_reduce_bytes,
)
from paddle_tpu.analysis.plan import (
    CandidateSpec,
    DeviceSpec,
    RematPolicy,
    enumerate_candidates,
    plan_consistency_findings,
    plan_gpt,
)
from paddle_tpu.distributed.env import clear_mesh, init_mesh
from paddle_tpu.models.gpt import (
    GPTForPretraining,
    GPTPretrainingCriterion,
    gpt_config,
)
from paddle_tpu.optimizer.optimizers import AdamW

_GiB = 1024 ** 3


@pytest.fixture(autouse=True)
def _clean_mesh():
    yield
    clear_mesh()


def _small_cfg(**over):
    base = dict(vocab_size=64, hidden_size=32, num_layers=2,
                num_attention_heads=4, max_position_embeddings=32,
                hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    base.update(over)
    return gpt_config("gpt2-small", **base)


def _trainer(model, crit, **kw):
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer

    return ParallelTrainer(
        model, lambda o, y: crit(o, y),
        AdamW(learning_rate=1e-3, parameters=model.parameters()),
        dp_axis=None, **kw)


# ---------------------------------------------------------------------------
# collective models (hand-computed bytes)
# ---------------------------------------------------------------------------
class TestCollectiveModels:
    def test_ring_all_reduce_hand_computed(self):
        # 4 ranks, 100 B payload: 2 * (4-1)/4 * 100 = 150 B over the ring
        assert ring_all_reduce_bytes(100, 4) == pytest.approx(150.0)
        assert ring_all_reduce_bytes(100, 1) == 0.0

    def test_reduce_scatter_hand_computed(self):
        # the ZeRO grad-sync half: (n-1)/n of the INPUT
        assert reduce_scatter_bytes(100, 4) == pytest.approx(75.0)
        assert reduce_scatter_bytes(4096, 8) == pytest.approx(3584.0)
        assert reduce_scatter_bytes(100, 1) == 0.0

    def test_all_gather_hand_computed(self):
        assert all_gather_bytes(80, 8) == pytest.approx(70.0)
        assert all_gather_bytes(80, 1) == 0.0

    def test_all_to_all_hand_computed(self):
        # MoE dispatch: each rank keeps 1/n, ships (n-1)/n
        assert all_to_all_bytes(64, 4) == pytest.approx(48.0)
        assert all_to_all_bytes(64, 1) == 0.0

    def test_cost_eqn_delegates_to_the_shared_models(self):
        # one psum_scatter of a [16, 16] f32 over a 4-way axis: input
        # 1024 B, comm = (4-1)/4 * 1024 = 768 B — the SAME function the
        # planner prices ZeRO with
        c = cost_eqn("psum_scatter",
                     ((((16, 16), "float32", False)),),
                     ((((4, 16), "float32", False)),),
                     {"axes": ("x",)}, {"x": 4})
        assert c.comm_bytes == pytest.approx(
            reduce_scatter_bytes(16 * 16 * 4, 4))
        assert c.known
        c2 = cost_eqn("all_to_all",
                      ((((16, 16), "float32", False)),),
                      ((((16, 16), "float32", False)),),
                      {"axis_name": "x"}, {"x": 4})
        assert c2.comm_bytes == pytest.approx(all_to_all_bytes(1024, 4))

    def test_unknown_collective_is_never_silently_zero_costed(self):
        comm, modeled = collective_comm_bytes("future_collective",
                                              1000, 2000, 4)
        assert not modeled and comm == pytest.approx(2000.0)

    def test_unmodeled_collective_prim_lands_in_unknown(self, monkeypatch):
        # a prim in COLLECTIVE_PRIMS with no model entry must fall back
        # bytes-only with known=False (→ GraphCost.unknown), not zero
        from paddle_tpu.analysis import cost as cost_mod

        models = dict(cost_mod._COLLECTIVE_MODELS)
        models.pop("psum")
        monkeypatch.setattr(cost_mod, "_COLLECTIVE_MODELS", models)
        c = cost_eqn("psum", ((((8,), "float32", False)),),
                     ((((8,), "float32", False)),),
                     {"axes": ("x",)}, {"x": 4})
        assert not c.known and c.comm_bytes > 0


# ---------------------------------------------------------------------------
# abstract lowering fidelity
# ---------------------------------------------------------------------------
class TestAbstractLowering:
    def test_abstract_model_matches_real_param_tree(self):
        from paddle_tpu.nn.initializer import abstract_init

        cfg = _small_cfg()
        paddle.seed(0)
        real = GPTForPretraining(cfg)
        with abstract_init():
            abstr = GPTForPretraining(cfg)
        rp = {n: p._data for n, p in real.named_parameters()}
        ap = {n: p._data for n, p in abstr.named_parameters()}
        assert set(rp) == set(ap)
        for n in rp:
            assert isinstance(ap[n], jax.ShapeDtypeStruct), n
            assert tuple(ap[n].shape) == tuple(rp[n].shape), n
            assert ap[n].dtype == rp[n].dtype, n

    def test_abstract_trainer_jaxpr_identical_to_concrete(self):
        from paddle_tpu.nn.initializer import abstract_init
        from paddle_tpu.random import split_key

        cfg = _small_cfg()
        init_mesh({"dp": 1})
        paddle.seed(0)
        m1 = GPTForPretraining(cfg)
        t1 = _trainer(m1, GPTPretrainingCriterion(cfg))
        t1._build()
        key = split_key()
        x = jnp.zeros((2, 16), jnp.int32)
        j1 = jax.make_jaxpr(t1._jit_step)(
            t1.params, t1.opt_state, t1.buffers, x, x, key,
            t1.scale_state, t1.sentinel_state,
            jnp.asarray(1e-3, jnp.float32))

        with abstract_init():
            m2 = GPTForPretraining(cfg)
        t2 = _trainer(m2, GPTPretrainingCriterion(cfg), abstract=True)
        t2._build()
        xs = jax.ShapeDtypeStruct((2, 16), jnp.int32)
        j2 = jax.make_jaxpr(t2._jit_step)(
            *t2.lowered_step_args(xs, xs, rng_key=key, lr=1e-3))
        assert str(j1) == str(j2)

    def test_abstract_trainer_refuses_to_execute(self):
        from paddle_tpu.nn.initializer import abstract_init

        cfg = _small_cfg()
        init_mesh({"dp": 1})
        with abstract_init():
            m = GPTForPretraining(cfg)
        t = _trainer(m, GPTPretrainingCriterion(cfg), abstract=True)
        with pytest.raises(RuntimeError, match="abstract trainer"):
            t.step(jnp.zeros((2, 16), jnp.int32),
                   jnp.zeros((2, 16), jnp.int32))

    def test_slot_shard_axis_shards_slots_only(self):
        # ZeRO-1/2 realization: moments sharded over 'sharding', params
        # replicated — the in_shardings divisor the planner prices
        from jax.sharding import PartitionSpec as P

        cfg = _small_cfg()
        init_mesh({"sharding": 4})
        paddle.seed(0)
        m = GPTForPretraining(cfg)
        t = _trainer(m, GPTPretrainingCriterion(cfg),
                     slot_shard_axis="sharding")
        del P
        wname = "gpt.h.0.mlp.fc_in.weight"
        # params replicated (no mesh axis in the spec)...
        assert not any(d for d in t.params[wname].sharding.spec)
        # ...while the Adam moments are sharded over the slot axis
        slot = t.opt_state["slots"][wname]["moment1"]
        assert "sharding" in str(slot.sharding.spec)


# ---------------------------------------------------------------------------
# the search on a small config
# ---------------------------------------------------------------------------
class TestSearchSmall:
    @pytest.fixture(scope="class")
    def plan(self):
        cfg = _small_cfg()
        return plan_gpt(cfg, 4, 8, seq_len=16, max_lowered=12)

    def test_enumeration_lattice(self):
        from paddle_tpu.distributed.auto_parallel.planner import ModelStats

        stats = ModelStats(n_params=1000, n_layers=2, hidden=32, seq_len=16)
        specs = enumerate_candidates(stats, 4, 8)
        ids = {s.plan_id for s in specs}
        assert "dp4-mp1-pp1-zero1-m1-remat1" in ids
        assert "dp1-mp4-pp1-zero0-m1-remat0" in ids
        assert "dp2-mp1-pp2-zero0-m2-remat0" in ids
        # dp=1 never carries a ZeRO stage
        assert not any(s.dp == 1 and s.zero_stage for s in specs)

    def test_chosen_is_analysis_priced_and_feasible(self, plan):
        assert plan.chosen is not None
        assert plan.n_lowered > 0
        ranked = [c for c in plan.candidates if c.feasible]
        assert ranked[0] is plan.chosen
        # analysis-priced rows outrank the legacy fallback; step time is
        # monotone within each pricing tier
        assert plan.chosen.priced_by == "analysis"
        tiers = [c.priced_by != "analysis" for c in ranked]
        assert tiers == sorted(tiers)
        exact = [c.step_time_s for c in ranked
                 if c.priced_by == "analysis"]
        assert exact == sorted(exact)

    def test_table_schema(self, plan):
        tb = plan.table()
        assert tb["schema_version"] == 1
        assert tb["chosen"] == plan.chosen.spec.plan_id
        row = tb["candidates"][0]
        for key in ("plan_id", "priced_by", "feasible", "predicted_step_s",
                    "predicted_peak_hbm_bytes", "binding_term",
                    "collective_bytes", "runtime_axes"):
            assert key in row, key

    def test_zero_slot_sharding_shrinks_peak(self, plan):
        rows = {c.spec.plan_id: c for c in plan.candidates
                if c.priced_by == "analysis"}
        z0 = rows.get("dp4-mp1-pp1-zero0-m1-remat0")
        z1 = rows.get("dp4-mp1-pp1-zero1-m1-remat0")
        if z0 is None or z1 is None:
            pytest.skip("both zero twins were not in the lowered set")
        assert z1.peak_hbm_bytes < z0.peak_hbm_bytes

    def test_dp_candidates_price_grad_sync(self, plan):
        dp_rows = [c for c in plan.candidates
                   if c.priced_by == "analysis" and c.spec.dp > 1]
        assert dp_rows
        for c in dp_rows:
            keys = set(c.collective_bytes)
            if c.spec.zero_stage >= 3:
                assert "reduce_scatter:grads@dp" in keys
                assert "all_gather:params@dp" in keys
            else:
                assert "all_reduce:grads@dp" in keys

    def test_mp_candidates_price_activation_allreduce(self, plan):
        mp_rows = [c for c in plan.candidates
                   if c.priced_by == "analysis" and c.spec.mp > 1]
        assert mp_rows
        for c in mp_rows:
            assert "all_reduce:activations@mp" in c.collective_bytes
            # hand-check: 4 allreduces/layer of b_local*t*h*act_bytes
            expect = 4 * 2 * ring_all_reduce_bytes(
                (8 // c.spec.dp) * 16 * 32 * 2, c.spec.mp)
            assert c.collective_bytes["all_reduce:activations@mp"] == \
                pytest.approx(expect)

    def test_self_consistency_by_construction(self, plan):
        fs = plan_consistency_findings(plan)
        assert all(f.severity.name != "HIGH" for f in fs), fs
        info = [f for f in fs if f.rule == "planner-consistency"]
        assert info and "by construction" in info[0].message
        assert info[0].details["drift"] < 0.005

    def test_tampered_peak_is_flagged_high(self, plan):
        import copy

        tampered = copy.copy(plan)
        tampered.chosen = copy.copy(plan.chosen)
        tampered.chosen.peak_hbm_bytes = int(
            plan.chosen.peak_hbm_bytes * 1.02)
        fs = plan_consistency_findings(tampered)
        assert any(f.severity.name == "HIGH" for f in fs)

    def test_legacy_fallback_mode_stays_drift_checked(self):
        # max_lowered=0 forces every row onto the legacy prior — the
        # consistency check must then run the old constant-model drift
        # check (satellite: fallback path keeps its gate)
        cfg = _small_cfg()
        plan = plan_gpt(cfg, 1, 2, seq_len=16, max_lowered=0)
        assert plan.chosen is not None
        assert plan.chosen.priced_by == "legacy-prior"
        fs = plan_consistency_findings(plan)
        rules = {f.rule for f in fs}
        assert "planner-drift" in rules
        assert "planner-consistency" in rules
        assert all(f.severity.name != "HIGH" for f in fs), fs

    def test_pp_candidates_fall_back_to_legacy_prior(self, plan):
        pp_rows = [c for c in plan.candidates if c.spec.pp > 1]
        assert pp_rows
        assert all(c.priced_by == "legacy-prior" for c in pp_rows)
        assert all(c.lowering_error for c in pp_rows)


# ---------------------------------------------------------------------------
# ROADMAP validation: 1.3B known-good + BENCH_r02 OOM, on SDS targets
# ---------------------------------------------------------------------------
def _cfg_13b(seq):
    return gpt_config("gpt3-1.3b", hidden_dropout_prob=0.0,
                      attention_dropout_prob=0.0,
                      max_position_embeddings=seq)


class TestValidation13B:
    @pytest.fixture(scope="class")
    def known_good(self):
        # BENCH_r05 lineage: 1.3B, batch 4, seq 1024, bf16 Adam moments —
        # measured 14.8k tok/s/chip WITH remat; no-remat compile-OOMs
        return plan_gpt(_cfg_13b(1024), 1, 4, seq_len=1024,
                        moment_dtype="bfloat16", max_lowered=4)

    @pytest.fixture(scope="class")
    def oom_r02(self):
        # BENCH_r02: f32 params + Adam moments ~15.6 GB — measured OOM on
        # a 16 GB v5e-1 with AND without remat
        return plan_gpt(_cfg_13b(1024), 1, 4, seq_len=1024,
                        moment_dtype="float32", max_lowered=4)

    def test_known_good_chooses_remat(self, known_good):
        chosen = known_good.require_feasible()
        assert chosen.spec.remat is True
        assert chosen.priced_by == "analysis"
        assert chosen.peak_hbm_bytes <= 16 * _GiB

    def test_known_good_refuses_no_remat(self, known_good):
        twin = next(c for c in known_good.candidates
                    if not c.spec.remat and c.priced_by == "analysis")
        assert not twin.feasible
        assert twin.refusal and twin.spec.plan_id in twin.refusal
        assert twin.peak_hbm_bytes > 16 * _GiB

    def test_known_good_self_consistency(self, known_good):
        fs = plan_consistency_findings(known_good)
        assert all(f.severity.name != "HIGH" for f in fs), fs
        info = [f for f in fs if f.rule == "planner-consistency"][0]
        assert info.details["drift"] < 0.005

    def test_known_good_emits_remat_policy(self, known_good):
        pol = known_good.remat_policy()
        assert pol.enabled
        assert pol.plan_id == known_good.chosen.spec.plan_id
        assert pol.scopes  # peak-path profiler scopes named

    def test_oom_config_refused_with_named_candidates(self, oom_r02):
        assert oom_r02.chosen is None
        assert all(not c.feasible for c in oom_r02.candidates)
        analysis_rows = [c for c in oom_r02.candidates
                         if c.priced_by == "analysis"]
        assert analysis_rows
        for c in analysis_rows:
            assert c.refusal and c.spec.plan_id in c.refusal
            assert c.peak_hbm_bytes > 16 * _GiB
        with pytest.raises(ValueError, match="no candidate fits"):
            oom_r02.require_feasible()
        assert not oom_r02.remat_policy().enabled

    def test_peaks_track_the_measured_boundary(self, known_good, oom_r02):
        # the liveness estimator must separate the two configs the way the
        # hardware did: bf16-moments+remat under 16 GiB, everything else
        # decisively over
        rows = {c.spec.remat: c.peak_hbm_bytes
                for c in known_good.candidates if c.priced_by == "analysis"}
        assert rows[True] < 16 * _GiB < rows[False]
        oom_rows = [c.peak_hbm_bytes for c in oom_r02.candidates
                    if c.priced_by == "analysis"]
        assert min(oom_rows) > 16 * _GiB


# ---------------------------------------------------------------------------
# planner-emitted remat policy: applied by the trainer
# ---------------------------------------------------------------------------
class TestRematPolicyApplication:
    def _run_steps(self, trainer, ids, n=4):
        losses = []
        for _ in range(n):
            losses.append(np.asarray(trainer.step(ids, ids)._data).copy())
        return losses

    def test_policy_vs_unremated_bitwise_forward_tight_trajectory(self):
        # a config that fits with or without remat.  Pinned invariants:
        # (1) from identical state the FORWARD loss is bit-identical (remat
        #     only restructures the backward);
        # (2) the loss/param trajectories track to tight f32 tolerance.
        # Strict grad bit-identity remat-vs-no-remat is NOT a property jax
        # provides — the checkpoint transpose reassociates the cotangent
        # accumulation (measured: ulp-level diffs even under
        # jax.disable_jit, i.e. with no XLA fusion at all).  The
        # bit-for-bit guarantee lives one test down: the policy-applied
        # program IS the priced remat program, jaxpr-identical.
        cfg = _small_cfg()
        ids = paddle.to_tensor(np.random.default_rng(0).integers(
            0, 64, (4, 16)).astype("int32"))

        init_mesh({"dp": 1})
        paddle.seed(7)
        m_plain = GPTForPretraining(cfg)
        t_plain = _trainer(m_plain, GPTPretrainingCriterion(cfg))
        paddle.seed(7)
        ref = self._run_steps(t_plain, ids)

        paddle.seed(7)
        m_pol = GPTForPretraining(cfg)
        pol = RematPolicy(enabled=True, granularity="full", interval=1,
                          scopes=("gpt.attn", "gpt.mlp"))
        t_pol = _trainer(m_pol, GPTPretrainingCriterion(cfg),
                         remat_policy=pol)
        assert m_pol.gpt.h[0]._use_recompute  # policy reached the blocks
        paddle.seed(7)
        got = self._run_steps(t_pol, ids)

        # (1) step-1 loss: same params, same forward → same bits
        np.testing.assert_array_equal(ref[0], got[0])
        # (2) the whole trajectory stays within f32 noise
        np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                                   rtol=1e-5, atol=1e-6)
        sa = t_plain.capture_state()["params"]
        sb = t_pol.capture_state()["params"]
        for n in sa:
            # absolute bound: Adam's rsqrt amplifies ulp-level grad diffs
            # on near-zero second moments, so relative tolerance is
            # meaningless for near-zero bias entries
            np.testing.assert_allclose(sa[n], sb[n], rtol=0,
                                       atol=2e-3, err_msg=n)

    def test_policy_realizes_the_priced_program(self):
        # trainer(policy) ≡ trainer(model constructed with use_recompute):
        # the program the planner priced is the program the policy builds
        from paddle_tpu.random import split_key

        init_mesh({"dp": 1})
        key = split_key()
        x = jnp.zeros((2, 16), jnp.int32)

        def jaxpr_of(trainer):
            trainer._build()
            return str(jax.make_jaxpr(trainer._jit_step)(
                trainer.params, trainer.opt_state, trainer.buffers, x, x,
                key, trainer.scale_state, trainer.sentinel_state,
                jnp.asarray(1e-3, jnp.float32)))

        paddle.seed(3)
        m_cfg = GPTForPretraining(_small_cfg(use_recompute=True))
        j_cfg = jaxpr_of(_trainer(m_cfg, GPTPretrainingCriterion(
            _small_cfg(use_recompute=True))))

        paddle.seed(3)
        m_pol = GPTForPretraining(_small_cfg())
        pol = RematPolicy(enabled=True, granularity="full", interval=1)
        j_pol = jaxpr_of(_trainer(m_pol, GPTPretrainingCriterion(
            _small_cfg()), remat_policy=pol))
        assert j_cfg == j_pol
        assert "remat2" in j_pol

    def test_disabled_policy_is_a_jaxpr_noop(self):
        from paddle_tpu.random import split_key

        init_mesh({"dp": 1})
        key = split_key()
        x = jnp.zeros((2, 16), jnp.int32)

        def jaxpr_of(trainer):
            trainer._build()
            return str(jax.make_jaxpr(trainer._jit_step)(
                trainer.params, trainer.opt_state, trainer.buffers, x, x,
                key, trainer.scale_state, trainer.sentinel_state,
                jnp.asarray(1e-3, jnp.float32)))

        cfg = _small_cfg()
        paddle.seed(5)
        m1 = GPTForPretraining(cfg)
        j1 = jaxpr_of(_trainer(m1, GPTPretrainingCriterion(cfg)))
        paddle.seed(5)
        m2 = GPTForPretraining(cfg)
        j2 = jaxpr_of(_trainer(m2, GPTPretrainingCriterion(cfg),
                               remat_policy=RematPolicy(enabled=False)))
        assert j1 == j2
        assert "remat2" not in j2

    def test_policy_falls_back_to_loss_checkpoint_for_non_gpt(self):
        from paddle_tpu.nn import Linear, ReLU, Sequential
        from paddle_tpu.optimizer.optimizers import SGD
        from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
        from paddle_tpu.random import split_key

        init_mesh({"dp": 1})
        paddle.seed(0)
        model = Sequential(Linear(8, 16), ReLU(), Linear(16, 4))
        pol = RematPolicy(enabled=True)
        t = ParallelTrainer(model, lambda o, y: ((o - y) ** 2).mean(),
                            SGD(0.1), dp_axis=None, remat_policy=pol)
        assert t.recompute is True
        t._build()
        j = str(jax.make_jaxpr(t._jit_step)(
            t.params, t.opt_state, t.buffers,
            jnp.zeros((2, 8), jnp.float32), jnp.zeros((2, 4), jnp.float32),
            split_key(), t.scale_state, t.sentinel_state,
            jnp.asarray(0.1, jnp.float32)))
        assert "remat2" in j


# ---------------------------------------------------------------------------
# CLI + committed artifact
# ---------------------------------------------------------------------------
class TestPlanCLI:
    def _argv(self, tmp_path, *extra):
        return ["--plan", "--plan-model", "gpt2-small",
                "--plan-devices", "1", "--plan-batch", "2",
                "--plan-seq", "16", "--plan-max-lowered", "2",
                "--plan-hidden", "32", "--plan-layers", "2",
                "--plan-vocab", "64", "--plan-heads", "4",
                "--out", str(tmp_path / "plan.json"), *extra]

    def test_custom_plan_writes_table_and_exits_zero(self, tmp_path):
        from paddle_tpu.analysis.cli import main

        rc = main(self._argv(tmp_path))
        assert rc == 0
        doc = json.loads((tmp_path / "plan.json").read_text())
        assert doc["schema_version"] == 1
        (key, tb), = doc["scenarios"].items()
        assert tb["chosen"] is not None
        assert tb["candidates"][0]["priced_by"] == "analysis"

    def test_infeasible_under_budget_exits_one(self, tmp_path):
        from paddle_tpu.analysis.cli import main

        rc = main(self._argv(tmp_path, "--device-budget", "100000"))
        assert rc == 1
        doc = json.loads((tmp_path / "plan.json").read_text())
        (key, tb), = doc["scenarios"].items()
        assert tb["chosen"] is None
        assert all(r["refusal"] for r in tb["candidates"]
                   if r["priced_by"] == "analysis")

    def test_pinned_candidate_gates_exit(self, tmp_path):
        from paddle_tpu.analysis.cli import main

        rc = main(self._argv(tmp_path, "--plan-pin",
                             "dp1-mp1-pp1-zero0-m1-remat0"))
        assert rc == 0
        rc = main(self._argv(tmp_path, "--plan-pin", "no-such-plan"))
        assert rc == 1

    def test_plan_flags_require_plan_mode(self):
        from paddle_tpu.analysis.cli import main

        with pytest.raises(SystemExit):
            main(["--plan-model", "gpt2-small"])

    def test_committed_artifact_anchors(self):
        # the committed benchmarks/plan_table.json IS the validation run:
        # known-good 1.3B chose a remat plan, BENCH_r02 refused everything
        path = os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                            "plan_table.json")
        doc = json.load(open(path))
        assert doc["schema_version"] == 1
        assert doc["all_expectations_met"] is True
        good = doc["scenarios"]["gpt3-1.3b_v5e1_bf16moments"]
        assert good["chosen"] and good["chosen"].endswith("remat1")
        assert good["remat_policy"]["enabled"] is True
        chosen_row = next(r for r in good["candidates"]
                          if r["plan_id"] == good["chosen"])
        assert chosen_row["predicted_peak_hbm_bytes"] <= 16 * _GiB
        oom = doc["scenarios"]["gpt3-1.3b_v5e1_f32moments_bench_r02"]
        assert oom["chosen"] is None
        assert all(not r["feasible"] for r in oom["candidates"])

    def test_committed_artifact_peak_matches_estimator_to_half_percent(
            self):
        # acceptance: the committed chosen-plan peak must match the
        # liveness estimator on a freshly lowered target to <0.5% (same
        # estimator, same lowering — equality in practice)
        from paddle_tpu.analysis.memory import estimate_memory
        from paddle_tpu.analysis.plan import _gpt_builder, lower_candidate

        path = os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                            "plan_table.json")
        doc = json.load(open(path))
        good = doc["scenarios"]["gpt3-1.3b_v5e1_bf16moments"]
        row = next(r for r in good["candidates"]
                   if r["plan_id"] == good["chosen"])
        spec = CandidateSpec(
            dp=row["dp"], mp=row["mp"], pp=row["pp"],
            zero_stage=row["zero_stage"], microbatches=row["microbatches"],
            remat=row["remat"])
        target = lower_candidate(
            spec, _gpt_builder(_cfg_13b(1024), moment_dtype="bfloat16"),
            global_batch=good["global_batch"], seq_len=good["seq_len"])
        est = estimate_memory(target)
        drift = (abs(est.peak_bytes - row["predicted_peak_hbm_bytes"])
                 / row["predicted_peak_hbm_bytes"])
        assert drift < 0.005, (est.peak_bytes,
                               row["predicted_peak_hbm_bytes"])
