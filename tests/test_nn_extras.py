"""Tests for the nn-surface completion batch: unpool, grid_sample,
affine_grid, gumbel_softmax, temporal_shift, bilinear, margin CE,
class_center_sample, sparse_attention, fused MHA, inplace activations,
LayerDict, weight/spectral norm utils, beam-search decode."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


rng = np.random.default_rng(17)


def _np(t):
    return np.asarray(t._data)


class TestUnpool:
    def test_pool_mask_roundtrip(self):
        x = rng.standard_normal((2, 3, 8, 8)).astype("float32")
        out, idx = F.max_pool2d(paddle.to_tensor(x), 2, return_mask=True)
        assert tuple(out.shape) == (2, 3, 4, 4)
        # indices point at the argmax source elements
        flat = x.reshape(2, 3, 64)
        picked = np.take_along_axis(flat, _np(idx).reshape(2, 3, 16), axis=2)
        np.testing.assert_allclose(picked.reshape(2, 3, 4, 4), _np(out))
        # unpool scatters back to those positions
        up = F.max_unpool2d(out, idx, 2)
        assert tuple(up.shape) == (2, 3, 8, 8)
        nz = _np(up) != 0
        assert nz.sum() <= 2 * 3 * 16
        np.testing.assert_allclose(_np(up).sum(), _np(out).sum(), rtol=1e-5)

    def test_pool_mask_with_padding(self):
        x = rng.standard_normal((1, 1, 5, 5)).astype("float32")
        out, idx = F.max_pool2d(paddle.to_tensor(x), 3, stride=2, padding=1,
                                return_mask=True)
        ref = F.max_pool2d(paddle.to_tensor(x), 3, stride=2, padding=1)
        np.testing.assert_allclose(_np(out), _np(ref), rtol=1e-6)


class TestGridSample:
    def test_identity_grid(self):
        x = rng.standard_normal((1, 2, 6, 6)).astype("float32")
        theta = np.array([[[1, 0, 0], [0, 1, 0]]], "float32")
        grid = F.affine_grid(paddle.to_tensor(theta), [1, 2, 6, 6])
        out = F.grid_sample(paddle.to_tensor(x), grid)
        np.testing.assert_allclose(_np(out), x, rtol=1e-4, atol=1e-5)

    def test_translation(self):
        x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
        # shift sampling one pixel right: out[..., j] = x[..., j+1]
        theta = np.array([[[1, 0, 2.0 / 3.0], [0, 1, 0]]], "float32")
        grid = F.affine_grid(paddle.to_tensor(theta), [1, 1, 4, 4])
        out = F.grid_sample(paddle.to_tensor(x), grid)
        np.testing.assert_allclose(_np(out)[0, 0, :, :3], x[0, 0, :, 1:],
                                   rtol=1e-4, atol=1e-4)

    def test_nearest_and_border(self):
        x = rng.standard_normal((1, 1, 4, 4)).astype("float32")
        g = np.zeros((1, 2, 2, 2), "float32")
        g[..., 0] = 3.0  # far outside
        out_z = F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(g))
        assert np.allclose(_np(out_z), 0.0)
        out_b = F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(g),
                              padding_mode="border")
        assert not np.allclose(_np(out_b), 0.0)
        out_n = F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(g),
                              mode="nearest", padding_mode="border")
        assert np.isfinite(_np(out_n)).all()


class TestMiscFunctional:
    def test_gumbel_softmax(self):
        paddle.seed(0)
        x = paddle.to_tensor(rng.standard_normal((4, 6)).astype("float32"))
        y = F.gumbel_softmax(x, temperature=0.5)
        np.testing.assert_allclose(_np(y).sum(-1), np.ones(4), rtol=1e-5)
        yh = F.gumbel_softmax(x, hard=True)
        assert set(np.unique(_np(yh))).issubset({0.0, 1.0})
        np.testing.assert_allclose(_np(yh).sum(-1), np.ones(4))

    def test_temporal_shift(self):
        nt, c, h, w = 4, 8, 2, 2  # n=2 segments of T=2
        x = rng.standard_normal((nt, c, h, w)).astype("float32")
        out = _np(F.temporal_shift(paddle.to_tensor(x), seg_num=2))
        v = x.reshape(2, 2, c, h, w)
        # fwd channels [0:2]: out[t] = v[t+1]; last t zero
        np.testing.assert_allclose(out.reshape(2, 2, c, h, w)[:, 0, :2], v[:, 1, :2])
        assert np.allclose(out.reshape(2, 2, c, h, w)[:, 1, :2], 0)
        # bwd channels [2:4]: out[t] = v[t-1]; first t zero
        np.testing.assert_allclose(out.reshape(2, 2, c, h, w)[:, 1, 2:4], v[:, 0, 2:4])
        # rest unchanged
        np.testing.assert_allclose(out.reshape(2, 2, c, h, w)[:, :, 4:], v[:, :, 4:])

    def test_bilinear_layer(self):
        b = nn.Bilinear(3, 4, 5)
        x1 = paddle.to_tensor(rng.standard_normal((2, 3)).astype("float32"))
        x2 = paddle.to_tensor(rng.standard_normal((2, 4)).astype("float32"))
        out = b(x1, x2)
        assert tuple(out.shape) == (2, 5)
        want = np.einsum("bi,oij,bj->bo", _np(x1), _np(b.weight), _np(x2)) + _np(b.bias)
        np.testing.assert_allclose(_np(out), want, rtol=1e-4, atol=1e-5)

    def test_pairwise_distance(self):
        pd = nn.PairwiseDistance(p=2.0)
        x = rng.standard_normal((3, 5)).astype("float32")
        y = rng.standard_normal((3, 5)).astype("float32")
        got = _np(pd(paddle.to_tensor(x), paddle.to_tensor(y)))
        want = np.linalg.norm(x - y + 1e-6, axis=-1)
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_losses_sane(self):
        probs = paddle.to_tensor(np.full((2, 3), 1 / 3, "float32"))
        lab = paddle.to_tensor(np.array([[1], [2]], "int64"))
        d = F.dice_loss(probs, lab)
        assert 0 <= float(_np(d)) <= 1
        p = paddle.to_tensor(np.array([0.9, 0.1], "float32"))
        l = paddle.to_tensor(np.array([1.0, 0.0], "float32"))  # noqa: E741
        ll = F.log_loss(p, l)
        np.testing.assert_allclose(_np(ll), -np.log(np.array([0.9, 0.9]) + 1e-4),
                                   rtol=1e-3)
        anchor = paddle.to_tensor(rng.standard_normal((4, 8)).astype("float32"))
        pos = paddle.to_tensor(rng.standard_normal((4, 8)).astype("float32"))
        labels = paddle.to_tensor(np.array([0, 0, 1, 1], "int64"))
        npl = F.npair_loss(anchor, pos, labels)
        assert np.isfinite(float(_np(npl)))

    def test_thresholded_relu(self):
        x = paddle.to_tensor(np.array([-1.0, 0.5, 2.0], "float32"))
        np.testing.assert_allclose(_np(F.thresholded_relu(x)), [0, 0, 2.0])

    def test_inplace_variants(self):
        x = paddle.to_tensor(np.array([-1.0, 1.0], "float32"))
        F.relu_(x)
        np.testing.assert_allclose(_np(x), [0.0, 1.0])
        y = paddle.to_tensor(np.array([0.0, 1.0], "float32"))
        F.softmax_(y)
        np.testing.assert_allclose(_np(y).sum(), 1.0, rtol=1e-6)
        z = paddle.to_tensor(np.array([0.5], "float32"))
        F.tanh_(z)
        np.testing.assert_allclose(_np(z), np.tanh(0.5), rtol=1e-6)
        w = paddle.to_tensor(np.array([-1.0], "float32"))
        F.elu_(w)
        np.testing.assert_allclose(_np(w), np.expm1(-1.0), rtol=1e-5)


class TestMarginCE:
    def test_zero_margin_equals_softmax_ce(self):
        cos = rng.uniform(-0.9, 0.9, (4, 10)).astype("float32")
        lab = np.array([1, 3, 5, 7], "int64")
        loss = F.margin_cross_entropy(paddle.to_tensor(cos),
                                      paddle.to_tensor(lab), margin1=1.0,
                                      margin2=0.0, margin3=0.0, scale=8.0,
                                      reduction="none")
        logits = cos * 8.0
        lp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
        want = -lp[np.arange(4), lab]
        np.testing.assert_allclose(_np(loss).reshape(-1), want, rtol=1e-4)

    def test_margin_increases_loss(self):
        cos = rng.uniform(-0.5, 0.5, (4, 10)).astype("float32")
        lab = paddle.to_tensor(np.array([0, 1, 2, 3], "int64"))
        l0 = F.margin_cross_entropy(paddle.to_tensor(cos), lab, margin2=0.0)
        l1 = F.margin_cross_entropy(paddle.to_tensor(cos), lab, margin2=0.5)
        assert float(_np(l1)) > float(_np(l0))

    def test_class_center_sample(self):
        lab = paddle.to_tensor(np.array([3, 7, 3, 11], "int64"))
        remapped, sampled = F.class_center_sample(lab, num_classes=20,
                                                  num_samples=8)
        s = _np(sampled)
        assert len(s) == 8 and {3, 7, 11}.issubset(set(s.tolist()))
        r = _np(remapped)
        for orig, rm in zip([3, 7, 3, 11], r):
            assert s[rm] == orig


class TestSparseAttention:
    def test_full_csr_matches_dense(self):
        B, H, T, D = 1, 2, 4, 8
        q = rng.standard_normal((B, H, T, D)).astype("float32")
        k = rng.standard_normal((B, H, T, D)).astype("float32")
        v = rng.standard_normal((B, H, T, D)).astype("float32")
        # full pattern: every row attends everything
        offs = np.tile(np.arange(0, (T + 1) * T, T), (B, H, 1)).astype("int32")
        cols = np.tile(np.tile(np.arange(T), T), (B, H, 1)).astype("int32")
        out = F.sparse_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                                 paddle.to_tensor(v), paddle.to_tensor(offs),
                                 paddle.to_tensor(cols))
        s = np.einsum("bhtd,bhsd->bhts", q, k) / np.sqrt(D)
        w = np.exp(s) / np.exp(s).sum(-1, keepdims=True)
        want = np.einsum("bhts,bhsd->bhtd", w, v)
        np.testing.assert_allclose(_np(out), want, rtol=1e-4, atol=1e-5)

    def test_masked_rows(self):
        B, H, T, D = 1, 1, 4, 4
        q = rng.standard_normal((B, H, T, D)).astype("float32")
        k = rng.standard_normal((B, H, T, D)).astype("float32")
        v = rng.standard_normal((B, H, T, D)).astype("float32")
        # each row attends only itself
        offs = np.arange(T + 1, dtype="int32").reshape(1, 1, -1)
        cols = np.arange(T, dtype="int32").reshape(1, 1, -1)
        out = F.sparse_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                                 paddle.to_tensor(v), paddle.to_tensor(offs),
                                 paddle.to_tensor(cols))
        np.testing.assert_allclose(_np(out)[0, 0], v[0, 0], rtol=1e-4, atol=1e-5)


class TestFusedMHA:
    def test_matches_manual(self):
        paddle.seed(0)
        B, T, Hd, heads = 2, 5, 16, 4
        x = rng.standard_normal((B, T, Hd)).astype("float32")
        qkv_w = (rng.standard_normal((Hd, 3 * Hd)) * 0.1).astype("float32")
        qkv_b = np.zeros(3 * Hd, "float32")
        out_w = (rng.standard_normal((Hd, Hd)) * 0.1).astype("float32")
        out_b = np.zeros(Hd, "float32")
        got = F.fused_multi_head_attention(
            paddle.to_tensor(x), paddle.to_tensor(qkv_w),
            paddle.to_tensor(out_w), qkv_bias=paddle.to_tensor(qkv_b),
            linear_bias=paddle.to_tensor(out_b), num_heads=heads,
            ln_scale=paddle.to_tensor(np.ones(Hd, "float32")),
            ln_bias=paddle.to_tensor(np.zeros(Hd, "float32")))
        # manual: qkv -> attention -> proj -> residual -> LN
        qkv = x @ qkv_w + qkv_b
        qkv = qkv.reshape(B, T, 3, heads, Hd // heads).transpose(2, 0, 3, 1, 4)
        q, k, v = qkv[0], qkv[1], qkv[2]
        s = np.einsum("bhtd,bhsd->bhts", q, k) / np.sqrt(Hd // heads)
        w = np.exp(s - s.max(-1, keepdims=True))
        w = w / w.sum(-1, keepdims=True)
        att = np.einsum("bhts,bhsd->bhtd", w, v).transpose(0, 2, 1, 3).reshape(B, T, Hd)
        y = x + (att @ out_w + out_b)
        mu = y.mean(-1, keepdims=True)
        var = y.var(-1, keepdims=True)
        want = (y - mu) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(_np(got), want, rtol=2e-3, atol=2e-3)


class TestContainersAndUtils:
    def test_layer_dict(self):
        ld = nn.LayerDict({"a": nn.Linear(2, 2), "b": nn.ReLU()})
        assert set(ld.keys()) == {"a", "b"}
        assert "a" in ld and len(ld) == 2
        ld["c"] = nn.Linear(2, 3)
        assert isinstance(ld.pop("c"), nn.Linear)
        # registered as sublayers -> parameters visible
        assert len(list(ld.parameters())) == 2

    def test_weight_norm(self):
        lin = nn.Linear(4, 3)
        w0 = _np(lin.weight).copy()
        nn.utils.weight_norm(lin, dim=0)
        names = dict(lin.named_parameters())
        assert any(n.endswith("weight_g") for n in names)
        x = paddle.to_tensor(rng.standard_normal((2, 4)).astype("float32"))
        out1 = _np(lin(x))
        # initial reparameterization reproduces the original weight
        want = _np(x) @ w0 + _np(lin.bias)
        np.testing.assert_allclose(out1, want, rtol=1e-4, atol=1e-5)
        nn.utils.remove_weight_norm(lin)
        np.testing.assert_allclose(_np(lin.weight), w0, rtol=1e-5, atol=1e-6)

    def test_weight_norm_g_shape(self):
        # weight_g is stored as a vector [w.shape[dim]] (reference
        # state-dict shape), not keepdims
        lin = nn.Linear(4, 3)
        nn.utils.weight_norm(lin, dim=1)
        g = dict(lin.named_parameters())["weight_g"]
        assert _np(g).shape == (3,)

    def test_weight_norm_dim_none(self):
        # dim=None: whole-tensor norm with scalar g
        lin = nn.Linear(4, 3)
        w0 = _np(lin.weight).copy()
        nn.utils.weight_norm(lin, dim=None)
        g = dict(lin.named_parameters())["weight_g"]
        assert _np(g).shape == ()
        np.testing.assert_allclose(_np(g), np.linalg.norm(w0), rtol=1e-6)
        x = paddle.to_tensor(rng.standard_normal((2, 4)).astype("float32"))
        np.testing.assert_allclose(
            _np(lin(x)), _np(x) @ w0 + _np(lin.bias), rtol=1e-4, atol=1e-5)
        nn.utils.remove_weight_norm(lin)
        np.testing.assert_allclose(_np(lin.weight), w0, rtol=1e-5, atol=1e-6)

    def test_spectral_norm_util(self):
        lin = nn.Linear(6, 4)
        nn.utils.spectral_norm(lin, n_power_iterations=20)
        x = paddle.to_tensor(rng.standard_normal((2, 6)).astype("float32"))
        lin(x)
        # after normalization the effective weight has unit top singular value
        eff = _np(lin._parameters["weight_orig"])
        sn_layer = lin._sub_layers["weight_spectral_norm"]
        w_eff = _np(sn_layer(lin._parameters["weight_orig"]))
        s = np.linalg.svd(w_eff, compute_uv=False)
        np.testing.assert_allclose(s[0], 1.0, rtol=0.05)


class TestBeamSearch:
    def test_beam1_equals_greedy(self):
        """A deterministic 'cell' emitting fixed logits: beam size 1 must
        reproduce greedy argmax decoding, ending at end_token."""
        V = 6
        chain = {0: 3, 3: 4, 4: 5, 5: 1}  # 1 = end token

        class FixedCell:
            def __call__(self, tokens, states):
                t = _np(tokens).astype(int)
                logits = np.full((len(t), V), -5.0, "float32")
                for i, tok in enumerate(t):
                    logits[i, chain.get(tok, 1)] = 5.0
                return paddle.to_tensor(logits), states

        dec = nn.BeamSearchDecoder(FixedCell(), start_token=0, end_token=1,
                                   beam_size=1)
        states = {"h": paddle.to_tensor(np.zeros((2, 3), "float32"))}
        ids, scores = nn.dynamic_decode(dec, states, max_step_num=10)
        seq = _np(ids)[0, :, 0].tolist()
        assert seq[:4] == [3, 4, 5, 1]

    def test_beam_finds_better_path(self):
        """First step: token A slightly better than B, but B leads to a much
        better continuation — beam 2 must pick the B path."""
        V = 4  # tokens: 0 start, 1 end, 2 A, 3 B

        class Cell:
            def __call__(self, tokens, states):
                t = _np(tokens).astype(int)
                logits = np.zeros((len(t), V), "float32")
                for i, tok in enumerate(t):
                    if tok == 0:
                        logits[i] = [-9, -9, 1.0, 0.9]  # A edges B
                    elif tok == 2:  # after A: uniform (low-confidence) step
                        logits[i] = [0.0, 0.0, 0.0, 0.0]
                    elif tok == 3:  # after B: strong end
                        logits[i] = [-9, 9.0, -9, -9]
                    else:
                        logits[i] = [-9, 9.0, -9, -9]
                return paddle.to_tensor(logits), states

        dec = nn.BeamSearchDecoder(Cell(), start_token=0, end_token=1, beam_size=2)
        states = {"h": paddle.to_tensor(np.zeros((1, 2), "float32"))}
        ids, scores = nn.dynamic_decode(dec, states, max_step_num=5)
        best = _np(ids)[0, :, 0].tolist()
        assert best[0] == 3  # beam search picked B despite lower step-1 score


class TestRound2GapFill:
    """Round-2 functional-surface completion: rearrange ops, fold/col2im,
    margin/NLL loss family, pdist, rrelu, and the new tensor ops."""

    def test_fold_inverts_unfold(self):
        import paddle_tpu.nn.functional as F

        x = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(2, 3, 8, 8)).astype("float32"))
        u = F.unfold(x, 4, strides=4)
        back = F.fold(u, 8, 4, strides=4)
        np.testing.assert_allclose(np.asarray(back._data),
                                   np.asarray(x._data), rtol=1e-6)

    def test_pixel_unshuffle_roundtrip(self):
        import paddle_tpu.nn.functional as F

        x = paddle.to_tensor(np.random.default_rng(1).normal(
            size=(2, 4, 6, 6)).astype("float32"))
        y = F.pixel_unshuffle(x, 2)
        assert list(y.shape) == [2, 16, 3, 3]
        z = F.pixel_shuffle(y, 2)
        np.testing.assert_allclose(np.asarray(z._data), np.asarray(x._data),
                                   rtol=1e-6)

    def test_loss_family_matches_manual(self):
        import paddle_tpu.nn.functional as F

        rng = np.random.default_rng(2)
        a = rng.normal(size=(5,)).astype("float32")
        y = np.asarray([1, -1, 1, -1, 1], "float32")
        got = float(F.soft_margin_loss(paddle.to_tensor(a),
                                       paddle.to_tensor(y))._data)
        want = np.log1p(np.exp(-y * a)).mean()
        np.testing.assert_allclose(got, want, rtol=1e-5)

        x1 = rng.normal(size=(4, 8)).astype("float32")
        x2 = rng.normal(size=(4, 8)).astype("float32")
        lab = np.asarray([1, -1, 1, -1], "float32")
        got = float(F.cosine_embedding_loss(
            paddle.to_tensor(x1), paddle.to_tensor(x2),
            paddle.to_tensor(lab), margin=0.1)._data)
        cos = (x1 * x2).sum(-1) / (np.linalg.norm(x1, axis=-1)
                                   * np.linalg.norm(x2, axis=-1))
        want = np.where(lab == 1, 1 - cos, np.maximum(0, cos - 0.1)).mean()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_pdist_matches_scipy_style(self):
        import paddle_tpu.nn.functional as F

        x = np.random.default_rng(3).normal(size=(5, 4)).astype("float32")
        got = np.asarray(F.pdist(paddle.to_tensor(x))._data)
        want = []
        for i in range(5):
            for j in range(i + 1, 5):
                want.append(np.linalg.norm(x[i] - x[j]))
        np.testing.assert_allclose(got, np.asarray(want), rtol=1e-5)

    def test_new_tensor_ops(self):
        x = paddle.to_tensor(np.asarray([[4.0, np.nan], [2.0, 8.0]]))
        np.testing.assert_allclose(float(paddle.nanmedian(x)._data), 4.0)
        t = paddle.take(paddle.to_tensor(np.arange(6.0).reshape(2, 3)),
                        paddle.to_tensor(np.asarray([5, 0])))
        np.testing.assert_allclose(np.asarray(t._data), [5.0, 0.0])
        p = paddle.polar(paddle.to_tensor(np.asarray([1.0, 2.0])),
                         paddle.to_tensor(np.asarray([0.0, np.pi / 2])))
        np.testing.assert_allclose(np.asarray(p._data).real, [1.0, 0.0],
                                   atol=1e-6)
        s = paddle.bitwise_left_shift(
            paddle.to_tensor(np.asarray([1, 2], "int32")), 2)
        np.testing.assert_array_equal(np.asarray(s._data), [4, 8])


class TestHSigmoidLoss:
    """OpTest numpy re-derivation of hierarchical_sigmoid_op.h +
    matrix_bit_code.h SimpleCode (default tree) and the custom-table path."""

    def _np_ref(self, x, lbl, w, b, nc):
        B, D = x.shape
        L = max((nc - 1).bit_length(), 1)
        out = np.zeros((B, 1), np.float64)
        for i in range(B):
            c = int(lbl[i]) + nc
            length = c.bit_length() - 1
            pre = np.zeros(L)
            for j in range(L):
                if j < length:
                    node = (c >> (j + 1)) - 1
                    v = w[node] @ x[i] + (b[node] if b is not None else 0.0)
                    pre[j] = np.clip(v, -40.0, 40.0)
            s = np.log1p(np.exp(pre)).sum()  # padded slots add ln 2 (parity)
            for j in range(min(length, L)):
                if (c >> j) & 1:
                    s -= pre[j]
            out[i, 0] = s
        return out

    def test_default_tree_matches_numpy(self):
        import paddle_tpu.nn.functional as F

        rng = np.random.default_rng(0)
        B, D, nc = 5, 6, 7
        x = rng.standard_normal((B, D)).astype(np.float32)
        lbl = rng.integers(0, nc, (B,)).astype(np.int64)
        w = rng.standard_normal((nc - 1, D)).astype(np.float32)
        b = rng.standard_normal((nc - 1,)).astype(np.float32)
        got = _np(F.hsigmoid_loss(paddle.to_tensor(x), paddle.to_tensor(lbl),
                                  nc, paddle.to_tensor(w), paddle.to_tensor(b)))
        want = self._np_ref(x, lbl, w, b, nc)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_custom_path_table(self):
        import paddle_tpu.nn.functional as F

        rng = np.random.default_rng(1)
        B, D, nc = 3, 4, 5
        x = rng.standard_normal((B, D)).astype(np.float32)
        lbl = np.array([0, 2, 4], np.int64)
        w = rng.standard_normal((nc, D)).astype(np.float32)
        # per-class node rows/codes, -1 = padding
        ptab = np.array([[0, 1, -1], [0, 2, 3], [1, 2, -1],
                         [0, 1, 2], [3, 4, -1]], np.int64)
        pcode = np.array([[1, 0, 0], [0, 1, 1], [1, 1, 0],
                          [0, 0, 1], [1, 0, 0]], np.int64)
        got = _np(F.hsigmoid_loss(paddle.to_tensor(x), paddle.to_tensor(lbl),
                                  nc, paddle.to_tensor(w), None,
                                  path_table=paddle.to_tensor(ptab),
                                  path_code=paddle.to_tensor(pcode)))
        want = np.zeros((B, 1))
        for i in range(B):
            rows, codes = ptab[lbl[i]], pcode[lbl[i]]
            s = 0.0
            for j in range(3):
                if rows[j] < 0:
                    s += np.log(2.0)  # padded slot parity (pre_out = 0)
                    continue
                v = np.clip(w[rows[j]] @ x[i], -40, 40)
                s += np.log1p(np.exp(v))
                if codes[j]:
                    s -= v
            want[i, 0] = s
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_layer_trains(self):
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt

        paddle.seed(0)
        layer = nn.HSigmoidLoss(8, 6)
        o = opt.SGD(0.2, parameters=layer.parameters())
        rng = np.random.default_rng(2)
        x = paddle.to_tensor(rng.standard_normal((16, 8)).astype("float32"))
        lbl = paddle.to_tensor(rng.integers(0, 6, (16,)).astype("int64"))
        first = None
        for _ in range(30):
            loss = layer(x, lbl).mean()
            o.clear_grad(); loss.backward(); o.step()
            if first is None:
                first = float(loss)
        assert float(loss) < first


class TestNCE:
    """OpTest re-derivation of nce_op.h (uniform sampler, fixed samples by
    seeding the framework PRNG)."""

    def test_matches_numpy_uniform(self):
        import paddle_tpu.nn.functional as F

        rng = np.random.default_rng(3)
        B, D, nc, nneg = 4, 5, 9, 6
        x = rng.standard_normal((B, D)).astype(np.float32)
        lbl = rng.integers(0, nc, (B, 1)).astype(np.int64)
        w = rng.standard_normal((nc, D)).astype(np.float32)
        b = rng.standard_normal((nc,)).astype(np.float32)
        paddle.seed(7)
        got = _np(F.nce(paddle.to_tensor(x), paddle.to_tensor(lbl), nc,
                        paddle.to_tensor(w), paddle.to_tensor(b),
                        num_neg_samples=nneg))
        assert got.shape == (B, 1)
        # per-row lower bound: the true-class term alone with o in (0,1)
        assert np.isfinite(got).all() and (got > 0).all()

        # deterministic under the framework PRNG: same seed, same loss
        paddle.seed(7)
        got2 = _np(F.nce(paddle.to_tensor(x), paddle.to_tensor(lbl), nc,
                         paddle.to_tensor(w), paddle.to_tensor(b),
                         num_neg_samples=nneg))
        np.testing.assert_array_equal(got, got2)

        # exact re-derivation for the TRUE-class terms: subtracting the
        # numpy-computed true part leaves only noise terms (all >= 0 since
        # -log(b/(o+b)) > 0)
        o_true = 1.0 / (1.0 + np.exp(-(np.einsum("bd,bd->b", x, w[lbl[:, 0]])
                                       + b[lbl[:, 0]])))
        pb = (1.0 / nc) * nneg
        true_cost = -np.log(o_true / (o_true + pb))
        noise_part = got[:, 0] - true_cost
        assert (noise_part > 0).all()

    def test_log_uniform_and_custom(self):
        import paddle_tpu.nn.functional as F

        rng = np.random.default_rng(4)
        B, D, nc = 3, 4, 8
        x = rng.standard_normal((B, D)).astype(np.float32)
        lbl = rng.integers(0, nc, (B, 1)).astype(np.int64)
        w = rng.standard_normal((nc, D)).astype(np.float32)
        paddle.seed(1)
        a = _np(F.nce(paddle.to_tensor(x), paddle.to_tensor(lbl), nc,
                      paddle.to_tensor(w), sampler="log_uniform"))
        assert np.isfinite(a).all()
        probs = np.full((nc,), 1.0 / nc, np.float32)
        paddle.seed(1)
        c = _np(F.nce(paddle.to_tensor(x), paddle.to_tensor(lbl), nc,
                      paddle.to_tensor(w), sampler="custom_dist",
                      custom_dist=paddle.to_tensor(probs)))
        assert np.isfinite(c).all()

    def test_grad_flows(self):
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        import paddle_tpu.optimizer as opt

        paddle.seed(0)
        w = nn.Parameter(np.random.default_rng(5).standard_normal(
            (6, 4)).astype(np.float32))
        w.name = "w"
        o = opt.SGD(0.1, parameters=[w])
        x = paddle.to_tensor(np.random.default_rng(6).standard_normal(
            (8, 4)).astype(np.float32))
        lbl = paddle.to_tensor(np.zeros((8, 1), np.int64))
        first = None
        for _ in range(20):
            loss = F.nce(x, lbl, 6, w, num_neg_samples=3).mean()
            o.clear_grad(); loss.backward(); o.step()
            if first is None:
                first = float(loss)
        assert float(loss) < first
