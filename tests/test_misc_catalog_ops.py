"""Numpy-parity tests for ops/misc_catalog.py + retinanet_detection_output
(OpTest pattern; reference kernels named per-op in the module)."""
import math

import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.ops import misc_catalog as M
from paddle_tpu.tensor import Tensor


def _np(x):
    return np.asarray(x._data if isinstance(x, Tensor) else x)


def test_add_position_encoding_half1():
    # enc_size == 2: reference computes val = pos / 10000.0
    x = np.zeros((1, 3, 2), np.float32)
    got = _np(M.add_position_encoding(x, alpha=1.0, beta=1.0))
    for j in range(3):
        np.testing.assert_allclose(got[0, j, 0], math.sin(j / 10000.0),
                                   rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(got[0, j, 1], math.cos(j / 10000.0),
                                   rtol=1e-5)


def test_add_position_encoding():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 3, 8)).astype(np.float32)
    got = _np(M.add_position_encoding(x, alpha=0.5, beta=2.0))
    half = 4
    want = np.empty_like(x)
    for j in range(3):
        for k in range(half):
            val = j / (10000.0 ** (k / (half - 1)))
            want[:, j, k] = 0.5 * x[:, j, k] + 2.0 * math.sin(val)
            want[:, j, half + k] = 0.5 * x[:, j, half + k] + 2.0 * math.cos(val)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_sampling_id_distribution():
    paddle.seed(0)
    probs = np.tile(np.array([[0.0, 0.0, 1.0, 0.0]], np.float32), (16, 1))
    got = _np(M.sampling_id(probs))
    assert (got == 2).all()


def test_squared_l2_distance_and_norm():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((4, 5)).astype(np.float32)
    y = rng.standard_normal((4, 5)).astype(np.float32)
    out, sub = M.squared_l2_distance(x, y)
    np.testing.assert_allclose(_np(out)[:, 0], ((x - y) ** 2).sum(-1), rtol=1e-5)
    np.testing.assert_allclose(_np(sub), x - y, rtol=1e-6)
    np.testing.assert_allclose(_np(M.squared_l2_norm(x))[0], (x ** 2).sum(),
                               rtol=1e-5)


def test_center_loss():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((4, 3)).astype(np.float32)
    centers = rng.standard_normal((5, 3)).astype(np.float32)
    label = np.array([1, 1, 0, 3])
    loss, new_c = M.center_loss(x, label, centers, alpha=0.5)
    want_loss = 0.5 * ((x - centers[label]) ** 2).sum(-1)
    np.testing.assert_allclose(_np(loss)[:, 0], want_loss, rtol=1e-5)
    # class 1 center moves by alpha * sum(diff)/(1+2)
    diff1 = (x[0] - centers[1]) + (x[1] - centers[1])
    np.testing.assert_allclose(_np(new_c)[1], centers[1] + 0.5 * diff1 / 3.0,
                               rtol=1e-5)
    np.testing.assert_allclose(_np(new_c)[2], centers[2], rtol=1e-6)  # unused


def test_bpr_loss():
    rng = np.random.default_rng(3)
    x = rng.standard_normal((3, 4)).astype(np.float32)
    label = np.array([[1], [0], [3]])
    got = _np(M.bpr_loss(x, label))[:, 0]
    want = np.zeros(3)
    for i in range(3):
        y = label[i, 0]
        s = sum(np.log1p(np.exp(x[i, j] - x[i, y])) for j in range(4) if j != y)
        want[i] = s / 3
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_fsp_and_cos_sim():
    rng = np.random.default_rng(4)
    x = rng.standard_normal((2, 3, 4, 5)).astype(np.float32)
    y = rng.standard_normal((2, 6, 4, 5)).astype(np.float32)
    got = _np(M.fsp_matrix(x, y))
    want = np.einsum("nchw,ndhw->ncd", x, y) / 20.0
    np.testing.assert_allclose(got, want, rtol=1e-4)

    a = rng.standard_normal((3, 4)).astype(np.float32)
    b = rng.standard_normal((3, 4)).astype(np.float32)
    cs = _np(M.cos_sim(a, b))[:, 0]
    want = (a * b).sum(-1) / (np.linalg.norm(a, axis=-1) * np.linalg.norm(b, axis=-1))
    np.testing.assert_allclose(cs, want, rtol=1e-5)


def test_affine_shuffle_space():
    rng = np.random.default_rng(5)
    x = rng.standard_normal((1, 4, 2, 2)).astype(np.float32)
    s = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
    b = np.array([0.5, 0.0, -1.0, 2.0], np.float32)
    got = _np(M.affine_channel(x, s, b))
    np.testing.assert_allclose(got, x * s[None, :, None, None] + b[None, :, None, None],
                               rtol=1e-6)

    x2 = np.arange(8, dtype=np.float32).reshape(1, 4, 1, 2)
    got = _np(M.shuffle_channel(x2, group=2))
    # channels [0,1,2,3] grouped (2,2) transposed -> [0,2,1,3]
    np.testing.assert_allclose(got[0, :, 0, 0], x2[0, [0, 2, 1, 3], 0, 0])

    # space_to_depth is the darknet reorg: [B, C, H, W] (C % bs^2 == 0)
    # -> [B, C/bs^2, H*bs, W*bs]; check against the reference kernel's
    # index formula (space_to_depth_op.h space_to_depth_compute)
    x3 = np.arange(1 * 4 * 2 * 3, dtype=np.float32).reshape(1, 4, 2, 3)
    got = _np(M.space_to_depth(x3, 2))
    assert got.shape == (1, 1, 4, 6)
    want = np.zeros((1, 1, 4, 6), np.float32)
    out_c = 1
    for k in range(4):
        for j in range(2):
            for i in range(3):
                c2, off = k % out_c, k // out_c
                want[0, c2, j * 2 + off // 2, i * 2 + off % 2] = x3[0, k, j, i]
    np.testing.assert_allclose(got, want)
    import pytest
    with pytest.raises(ValueError, match="blocksize"):
        M.space_to_depth(np.zeros((1, 3, 4, 4), np.float32), 2)


def test_random_crop_shape_and_content():
    paddle.seed(0)
    x = np.arange(2 * 8 * 8, dtype=np.float32).reshape(2, 8, 8)
    got = _np(M.random_crop(x, (4, 4)))
    assert got.shape == (2, 4, 4)
    # crop is a contiguous window: row deltas are 1, col deltas 8
    assert np.allclose(np.diff(got[0], axis=1), 1.0)


def test_partial_concat_sum():
    x1 = np.arange(12, dtype=np.float32).reshape(3, 4)
    x2 = 100 + x1
    got = _np(M.partial_concat([x1, x2], start_index=1, length=2))
    np.testing.assert_allclose(got, np.concatenate([x1[:, 1:3], x2[:, 1:3]], 1))
    got = _np(M.partial_sum([x1, x2], start_index=1, length=2))
    np.testing.assert_allclose(got, x1[:, 1:3] + x2[:, 1:3])


def test_grads_flow_through_losses():
    x = paddle.to_tensor(np.random.default_rng(6).standard_normal(
        (3, 4)).astype(np.float32), stop_gradient=False)
    loss = M.bpr_loss(x, np.array([[0], [1], [2]]))
    loss.sum().backward()
    assert np.isfinite(_np(x.grad)).all()


def test_retinanet_detection_output():
    from paddle_tpu.vision import detection as D

    rng = np.random.default_rng(7)
    # two levels, 1 image, 3 classes
    anchors = [np.array([[0, 0, 15, 15], [8, 8, 31, 31]], np.float32),
               np.array([[0, 0, 31, 31]], np.float32)]
    deltas = [np.zeros((1, 2, 4), np.float32), np.zeros((1, 1, 4), np.float32)]
    scores = [np.array([[[0.9, 0.01, 0.02], [0.01, 0.8, 0.01]]], np.float32),
              np.array([[[0.02, 0.01, 0.7]]], np.float32)]
    im_info = np.array([[64.0, 64.0, 1.0]], np.float32)
    out, cnt = D.retinanet_detection_output(
        deltas, scores, anchors, im_info, score_threshold=0.05,
        nms_threshold=0.5, keep_top_k=10)
    out, cnt = _np(out), _np(cnt)
    assert cnt[0] == 3
    rows = out[: cnt[0]]
    # class-ascending rows; zero deltas decode back to the anchors
    assert rows[0, 0] == 0 and abs(rows[0, 1] - 0.9) < 1e-5
    np.testing.assert_allclose(rows[0, 2:], [0, 0, 15, 15], atol=1e-4)
    assert rows[1, 0] == 1
    assert rows[2, 0] == 2
    np.testing.assert_allclose(rows[2, 2:], [0, 0, 31, 31], atol=1e-4)


def test_retinanet_pixel_convention_and_im_scale():
    """Non-zero deltas use the +1 width convention (w = x2-x1+1) and boxes
    map back to original-image coords via im_info[2] (review r4)."""
    from paddle_tpu.vision import detection as D

    anchors = [np.array([[0, 0, 15, 15]], np.float32)]
    # dw = log(2): reference width 16 -> 32
    deltas = [np.array([[[0.0, 0.0, np.log(2.0), 0.0]]], np.float32)]
    scores = [np.array([[[0.9]]], np.float32)]
    im_info = np.array([[64.0, 64.0, 2.0]], np.float32)  # scaled 2x
    out, cnt = D.retinanet_detection_output(deltas, scores, anchors, im_info,
                                            keep_top_k=5)
    out = _np(out)
    assert _np(cnt)[0] == 1
    # decode (+1 conv): aw=16, acx=8; w = exp(log2)*16 = 32 ->
    # x1 = 8-16 = -8, x2 = 8+16-1 = 23; y stays [0, 15]
    # /scale 2 -> [-4, 0, 11.5, 7.5], clip to [0, 31]
    np.testing.assert_allclose(out[0, 2:], [0.0, 0.0, 11.5, 7.5], atol=1e-3)


def test_cvm():
    from paddle_tpu.ops.misc_catalog import cvm

    x = np.array([[3.0, 1.0, 5.0, 6.0], [0.0, 0.0, 7.0, 8.0]], np.float32)
    got = _np(cvm(Tensor(jnp.asarray(x)), None, use_cvm=True))
    exp = x.copy()
    exp[:, 0] = np.log(x[:, 0] + 1)
    exp[:, 1] = np.log(x[:, 1] + 1) - exp[:, 0]
    np.testing.assert_allclose(got, exp, rtol=1e-6)
    got2 = _np(cvm(Tensor(jnp.asarray(x)), None, use_cvm=False))
    np.testing.assert_allclose(got2, x[:, 2:])


def test_shuffle_batch():
    import paddle_tpu as paddle
    from paddle_tpu.ops.misc_catalog import shuffle_batch

    x = np.arange(12, dtype=np.float32).reshape(6, 2)
    out, idx, seed_out = shuffle_batch(Tensor(jnp.asarray(x)), seed=5)
    out, idx = _np(out), np.asarray(_np(idx))
    assert sorted(idx.tolist()) == list(range(6))
    np.testing.assert_allclose(out, x[idx])
    assert seed_out == 6
    # deterministic for the same seed
    out2, idx2, _ = shuffle_batch(Tensor(jnp.asarray(x)), seed=5)
    np.testing.assert_array_equal(idx, np.asarray(_np(idx2)))


def test_data_norm():
    from paddle_tpu.ops.misc_catalog import data_norm

    rng = np.random.default_rng(0)
    x = rng.standard_normal((4, 3)).astype(np.float32)
    bsz = np.full((3,), 10.0, np.float32)
    bsum = rng.standard_normal(3).astype(np.float32) * 10
    bsq = np.abs(rng.standard_normal(3)).astype(np.float32) * 10 + 5
    y, means, scales = data_norm(Tensor(jnp.asarray(x)), bsz, bsum, bsq)
    m = bsum / bsz
    s = np.sqrt(bsz / bsq)
    np.testing.assert_allclose(_np(means), m, rtol=1e-6)
    np.testing.assert_allclose(_np(scales), s, rtol=1e-6)
    np.testing.assert_allclose(_np(y), (x - m) * s, rtol=1e-5)


def test_batch_fc():
    from paddle_tpu.ops.misc_catalog import batch_fc

    rng = np.random.default_rng(1)
    s_, n_, i_, o_ = 3, 4, 5, 2
    x = rng.standard_normal((s_, n_, i_)).astype(np.float32)
    w = rng.standard_normal((s_, i_, o_)).astype(np.float32)
    b = rng.standard_normal((s_, o_)).astype(np.float32)
    got = _np(batch_fc(Tensor(jnp.asarray(x)), Tensor(jnp.asarray(w)),
                       Tensor(jnp.asarray(b))))
    exp = np.einsum("sni,sio->sno", x, w) + b[:, None, :]
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)


def test_tdm_child():
    from paddle_tpu.ops.misc_catalog import tdm_child

    # tree rows: [item_id, layer, parent, child0, child1]
    info = np.array([
        [0, 0, 0, 0, 0],    # node 0: pad
        [0, 0, 0, 2, 3],    # node 1: internal, children 2,3
        [7, 1, 1, 0, 0],    # node 2: leaf item 7
        [0, 1, 1, 4, 0],    # node 3: internal, child 4
        [9, 2, 3, 0, 0],    # node 4: leaf item 9
    ], np.int64)
    x = np.array([[1], [2], [3]], np.int64)
    child, mask = tdm_child(Tensor(jnp.asarray(x)), info, child_nums=2)
    child, mask = _np(child), _np(mask)
    np.testing.assert_array_equal(child[0, 0], [2, 3])   # node 1 children
    np.testing.assert_array_equal(mask[0, 0], [1, 0])    # 2 is item, 3 not
    np.testing.assert_array_equal(child[1, 0], [0, 0])   # leaf: no children
    np.testing.assert_array_equal(mask[1, 0], [0, 0])
    np.testing.assert_array_equal(child[2, 0], [4, 0])   # child slot + pad
    np.testing.assert_array_equal(mask[2, 0], [1, 0])


def test_filter_by_instag():
    from paddle_tpu.ops.misc_catalog import filter_by_instag

    # 3 instances of 2/1/1 rows; tags: {1,2}, {3}, {2}
    x = np.arange(8, dtype=np.float32).reshape(4, 2)
    tags = np.array([1, 2, 3, 2], np.int64)
    out, imap, lw = filter_by_instag(
        x, tags, np.array([2], np.int64), is_lod=True,
        ins_lengths=[2, 1, 1], tag_lengths=[2, 1, 1])
    # instances 0 (tags 1,2) and 2 (tag 2) kept
    np.testing.assert_allclose(out, np.concatenate([x[0:2], x[3:4]]))
    np.testing.assert_array_equal(imap, [[0, 0, 2], [2, 3, 1]])
    np.testing.assert_allclose(lw, [[1.0], [1.0]])

    # nothing matches -> out_val_if_empty row, zero weight
    out2, imap2, lw2 = filter_by_instag(
        x, tags, np.array([9], np.int64), is_lod=True,
        ins_lengths=[2, 1, 1], tag_lengths=[2, 1, 1], out_val_if_empty=7)
    np.testing.assert_allclose(out2, np.full((1, 2), 7.0))
    np.testing.assert_allclose(lw2, [[0.0]])


def test_sample_logits_customized():
    """Exact path with externally-chosen candidates (sample_logits_op.h:
    gather + accidental-hit -1e20 + -log q + TolerableValue clamp)."""
    from paddle_tpu.ops.misc_catalog import sample_logits

    rng = np.random.default_rng(2)
    B, C, T, S = 3, 10, 1, 4
    logits = rng.standard_normal((B, C)).astype(np.float32)
    labels = np.array([[2], [5], [7]], np.int64)
    cust = np.concatenate(
        [labels, np.tile(np.array([[1, 2, 8, 9]], np.int64), (B, 1))], axis=1)
    probs = np.full((B, T + S), 0.25, np.float32)
    sam, pr, sl, lab = sample_logits(
        Tensor(jnp.asarray(logits)), labels, S,
        use_customized_samples=True, customized_samples=cust,
        customized_probabilities=probs)
    sl = _np(sl)
    exp = np.take_along_axis(logits, cust, axis=1).astype(np.float64)
    exp[0, 1 + 1] -= 1e20  # row 0: sampled col '2' collides with label 2
    exp = exp - np.log(0.25)
    exp = np.clip(exp, -1e10, 1e10)
    np.testing.assert_allclose(sl, exp, rtol=1e-5)
    np.testing.assert_array_equal(_np(lab), np.zeros((B, 1), np.int64))


def test_sample_logits_sampled_path():
    import paddle_tpu as paddle
    from paddle_tpu.ops.misc_catalog import sample_logits

    paddle.seed(3)
    logits = np.random.default_rng(3).standard_normal((2, 20)).astype(np.float32)
    labels = np.array([[4], [6]], np.int64)
    sam, pr, sl, lab = sample_logits(Tensor(jnp.asarray(logits)), labels, 5)
    assert _np(sam).shape == (2, 6) and _np(sl).shape == (2, 6)
    assert (_np(sam)[:, 0] == labels[:, 0]).all()
    assert np.isfinite(_np(pr)).all() and (_np(pr) > 0).all()
