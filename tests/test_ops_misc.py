"""Small-op parity vs numpy (bucketize, logcumsumexp, renorm, index_add,
index_put, vander, polygamma, sgn, nanquantile)."""
import numpy as np
from scipy import special

import paddle_tpu as paddle


rng = np.random.default_rng(5)


def _np(t):
    return np.asarray(t._data)


def test_logcumsumexp():
    x = rng.standard_normal((3, 4)).astype("float32")
    got = paddle.logcumsumexp(paddle.to_tensor(x), axis=1)
    want = np.logaddexp.accumulate(x, axis=1)
    np.testing.assert_allclose(_np(got), want, rtol=1e-5, atol=1e-5)
    # axis=None flattens
    got = paddle.logcumsumexp(paddle.to_tensor(x))
    np.testing.assert_allclose(_np(got), np.logaddexp.accumulate(x.ravel()),
                               rtol=1e-5, atol=1e-5)


def test_bucketize():
    edges = np.array([1.0, 3.0, 5.0], "float32")
    x = np.array([[0.5, 1.0], [3.3, 7.0]], "float32")
    got = paddle.bucketize(paddle.to_tensor(x), paddle.to_tensor(edges))
    np.testing.assert_array_equal(_np(got), np.searchsorted(edges, x))
    got_r = paddle.bucketize(paddle.to_tensor(x), paddle.to_tensor(edges), right=True)
    np.testing.assert_array_equal(_np(got_r), np.searchsorted(edges, x, side="right"))


def test_renorm():
    x = rng.standard_normal((3, 4, 2)).astype("float32")
    got = _np(paddle.renorm(paddle.to_tensor(x), p=2.0, axis=1, max_norm=1.0))
    for j in range(4):
        sub = x[:, j, :]
        n = np.sqrt((sub ** 2).sum())
        want = sub * min(1.0, 1.0 / n)
        np.testing.assert_allclose(got[:, j, :], want, rtol=1e-5, atol=1e-5)


def test_index_add_accumulates():
    x = np.zeros((4, 3), "float32")
    idx = np.array([1, 1, 3], "int32")
    val = np.ones((3, 3), "float32")
    got = paddle.index_add(paddle.to_tensor(x), paddle.to_tensor(idx), 0,
                           paddle.to_tensor(val))
    want = np.zeros((4, 3), "float32")
    want[1] = 2
    want[3] = 1
    np.testing.assert_allclose(_np(got), want)


def test_index_add_axis1_grad():
    x = paddle.to_tensor(rng.standard_normal((2, 4)).astype("float32"))
    x.stop_gradient = False
    val = paddle.to_tensor(np.ones((2, 2), "float32"))
    out = paddle.index_add(x, paddle.to_tensor(np.array([0, 2], "int32")), 1, val)
    out.sum().backward()
    np.testing.assert_allclose(_np(x.grad), np.ones((2, 4)))


def test_index_put():
    x = np.zeros((3, 3), "float32")
    i = np.array([0, 2], "int32")
    j = np.array([1, 2], "int32")
    got = paddle.index_put(paddle.to_tensor(x),
                           (paddle.to_tensor(i), paddle.to_tensor(j)),
                           paddle.to_tensor(np.array([5.0, 7.0], "float32")))
    want = x.copy()
    want[0, 1] = 5
    want[2, 2] = 7
    np.testing.assert_allclose(_np(got), want)


def test_vander():
    x = np.array([1.0, 2.0, 3.0], "float32")
    got = paddle.vander(paddle.to_tensor(x), 4)
    np.testing.assert_allclose(_np(got), np.vander(x, 4))
    got_inc = paddle.vander(paddle.to_tensor(x), 3, increasing=True)
    np.testing.assert_allclose(_np(got_inc), np.vander(x, 3, increasing=True))


def test_polygamma():
    x = rng.uniform(0.5, 4.0, (5,)).astype("float32")
    for n in (1, 2):
        got = paddle.polygamma(paddle.to_tensor(x), n)
        np.testing.assert_allclose(_np(got), special.polygamma(n, x),
                                   rtol=1e-4, atol=1e-4)


def test_sgn():
    z = np.array([3 + 4j, 0 + 0j, -1 - 1j], "complex64")
    got = _np(paddle.sgn(paddle.to_tensor(z)))
    want = np.where(np.abs(z) == 0, 0, z / np.where(np.abs(z) == 0, 1, np.abs(z)))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    r = np.array([-2.0, 0.0, 5.0], "float32")
    np.testing.assert_allclose(_np(paddle.sgn(paddle.to_tensor(r))), np.sign(r))


def test_nanquantile():
    x = np.array([[1.0, np.nan, 3.0], [4.0, 5.0, np.nan]], "float32")
    got = paddle.nanquantile(paddle.to_tensor(x), 0.5, axis=1)
    np.testing.assert_allclose(_np(got), np.nanquantile(x, 0.5, axis=1),
                               rtol=1e-6)


class TestSequenceOps:
    def test_pad_unpad_roundtrip(self):
        flat = paddle.to_tensor(rng.standard_normal((7, 3)).astype("float32"))
        lens = paddle.to_tensor(np.array([3, 4], "int64"))
        padded, out_lens = paddle.sequence_pad(flat, -1.0, length=lens)
        assert tuple(padded.shape) == (2, 4, 3)
        assert np.allclose(_np(padded)[0, 3], -1.0)
        back = paddle.sequence_unpad(padded, out_lens)
        np.testing.assert_allclose(_np(back), _np(flat))

    def test_pad_maxlen_and_grad(self):
        flat = paddle.to_tensor(rng.standard_normal((4, 2)).astype("float32"))
        flat.stop_gradient = False
        padded, _ = paddle.sequence_pad(
            flat, 0.0, maxlen=5, length=paddle.to_tensor(np.array([1, 3], "int64")))
        assert tuple(padded.shape) == (2, 5, 2)
        padded.sum().backward()
        np.testing.assert_allclose(_np(flat.grad), np.ones((4, 2)))

    def test_expand_reverse_softmax(self):
        x = paddle.to_tensor(np.array([[1.0], [2.0]], "float32"))
        exp = paddle.sequence_expand(x, paddle.to_tensor(np.array([1, 2], "int64")))
        assert _np(exp)[:, 0].tolist() == [1, 2, 2]
        seq = paddle.to_tensor(np.arange(8, dtype="float32").reshape(2, 4))
        rev = paddle.sequence_reverse(seq, paddle.to_tensor(np.array([2, 4], "int64")))
        np.testing.assert_allclose(_np(rev)[0], [1, 0, 2, 3])
        np.testing.assert_allclose(_np(rev)[1], [7, 6, 5, 4])
        sm = paddle.sequence_softmax(seq, paddle.to_tensor(np.array([2, 4], "int64")))
        np.testing.assert_allclose(_np(sm).sum(-1), [1, 1], rtol=1e-6)
        assert np.allclose(_np(sm)[0, 2:], 0)


class TestTensorArray:
    """create_array/array_write/array_read/array_length (reference
    python/paddle/tensor/array.py over write_to_array framework ops)."""

    def test_write_read_length(self):
        import paddle_tpu as paddle

        arr = paddle.create_array()
        x0 = paddle.to_tensor([1.0, 2.0])
        x1 = paddle.to_tensor([3.0, 4.0])
        arr = paddle.array_write(x0, 0, arr)
        arr = paddle.array_write(x1, paddle.to_tensor(1), arr)
        assert int(np.asarray(paddle.array_length(arr)._data)) == 2
        np.testing.assert_allclose(
            np.asarray(paddle.array_read(arr, 1)._data), [3.0, 4.0])
        # overwrite
        arr = paddle.array_write(x1 * 2.0, 0, arr)
        np.testing.assert_allclose(
            np.asarray(paddle.array_read(arr, 0)._data), [6.0, 8.0])

    def test_initialized_list_and_bounds(self):
        import paddle_tpu as paddle
        import pytest as _pytest

        arr = paddle.create_array(
            initialized_list=[paddle.to_tensor([1.0])])
        assert int(np.asarray(paddle.array_length(arr)._data)) == 1
        with _pytest.raises(IndexError):
            paddle.array_write(paddle.to_tensor([2.0]), 5, arr)

    def test_under_to_static_concrete_indices(self):
        import paddle_tpu as paddle
        from paddle_tpu.jit import to_static

        @to_static
        def f(x):
            arr = paddle.create_array()
            for i in range(3):
                arr = paddle.array_write(x * float(i + 1), i, arr)
            return (paddle.array_read(arr, 0) + paddle.array_read(arr, 2))

        np.testing.assert_allclose(
            np.asarray(f(paddle.to_tensor([1.0]))._data), [4.0])
