"""Pallas kernel suite parity tests (interpret mode on the CPU harness):
fused RoPE, fused swiglu, fused residual+dropout+LN — the TPU-native
equivalents of the reference's fused CUDA ops
(fused_attention_op.cu, fused_transformer_op.h, fused_dropout_helper.h).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.fused_ln import (
    fused_residual_dropout_ln,
    fused_residual_dropout_ln_reference,
)
from paddle_tpu.ops.pallas.rope import build_rope_cache, rope, rope_reference
from paddle_tpu.ops.pallas.swiglu import swiglu, swiglu_reference


class TestRope:
    def test_forward_matches_reference(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(4, 256, 128)), jnp.float32)
        cos, sin = build_rope_cache(256, 128)
        out = rope(x, cos, sin, interpret=True)
        ref = rope_reference(x, cos, sin)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_grad_matches_reference(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(2, 128, 128)), jnp.float32)
        cos, sin = build_rope_cache(128, 128)
        g1 = jax.grad(lambda x: jnp.sum(jnp.sin(
            rope(x, cos, sin, interpret=True))))(x)
        g2 = jax.grad(lambda x: jnp.sum(jnp.sin(
            rope_reference(x, cos, sin))))(x)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-4, atol=1e-5)

    def test_norm_preserved(self):
        """Rotations preserve pairwise norms."""
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.normal(size=(1, 128, 128)), jnp.float32)
        cos, sin = build_rope_cache(128, 128)
        out = rope(x, cos, sin, interpret=True)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(out), axis=-1),
            np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-4)

    def test_fallback_small_dims(self):
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(size=(2, 16, 64)), jnp.float32)
        cos, sin = build_rope_cache(16, 64)
        out = rope(x, cos, sin)  # falls back to reference
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(rope_reference(x, cos, sin)),
                                   rtol=1e-5)


class TestSwiglu:
    def test_forward_matches_reference(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(64, 128)), jnp.float32)
        wg = jnp.asarray(rng.normal(size=(128, 256)) * 0.05, jnp.float32)
        wu = jnp.asarray(rng.normal(size=(128, 256)) * 0.05, jnp.float32)
        out = swiglu(x, wg, wu, interpret=True)
        ref = swiglu_reference(x, wg, wu)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_grads_match_reference(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(32, 128)), jnp.float32)
        wg = jnp.asarray(rng.normal(size=(128, 128)) * 0.05, jnp.float32)
        wu = jnp.asarray(rng.normal(size=(128, 128)) * 0.05, jnp.float32)
        f1 = lambda x, wg, wu: jnp.sum(jnp.tanh(swiglu(x, wg, wu, interpret=True)))
        f2 = lambda x, wg, wu: jnp.sum(jnp.tanh(swiglu_reference(x, wg, wu)))
        g1 = jax.grad(f1, argnums=(0, 1, 2))(x, wg, wu)
        g2 = jax.grad(f2, argnums=(0, 1, 2))(x, wg, wu)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=1e-5)


class TestFusedResidualDropoutLN:
    def test_forward_no_dropout(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(32, 128)), jnp.float32)
        r = jnp.asarray(rng.normal(size=(32, 128)), jnp.float32)
        gamma = jnp.asarray(rng.normal(size=(128,)), jnp.float32)
        beta = jnp.asarray(rng.normal(size=(128,)), jnp.float32)
        out, y = fused_residual_dropout_ln(x, r, gamma, beta, p=0.0,
                                           interpret=True)
        ref_out, ref_y = fused_residual_dropout_ln_reference(
            x, r, None, gamma, beta, 0.0)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref_y), rtol=1e-6)

    def test_forward_with_mask(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(16, 128)), jnp.float32)
        r = jnp.asarray(rng.normal(size=(16, 128)), jnp.float32)
        gamma = jnp.ones((128,), jnp.float32)
        beta = jnp.zeros((128,), jnp.float32)
        mask = jax.random.bernoulli(jax.random.key(0), 0.9, (16, 128))
        out, y = fused_residual_dropout_ln(x, r, gamma, beta, p=0.1,
                                           mask=mask, interpret=True)
        ref_out, ref_y = fused_residual_dropout_ln_reference(
            x, r, mask, gamma, beta, 0.1)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                                   rtol=1e-5, atol=1e-5)

    def test_grads_match_reference(self):
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.normal(size=(16, 128)), jnp.float32)
        r = jnp.asarray(rng.normal(size=(16, 128)), jnp.float32)
        gamma = jnp.asarray(1 + 0.1 * rng.normal(size=(128,)), jnp.float32)
        beta = jnp.asarray(0.1 * rng.normal(size=(128,)), jnp.float32)
        mask = jax.random.bernoulli(jax.random.key(1), 0.8, (16, 128))

        def f1(x, r, gamma, beta):
            out, y = fused_residual_dropout_ln(x, r, gamma, beta, p=0.2,
                                               mask=mask, interpret=True)
            return jnp.sum(jnp.sin(out)) + jnp.sum(jnp.cos(y))

        def f2(x, r, gamma, beta):
            out, y = fused_residual_dropout_ln_reference(
                x, r, mask, gamma, beta, 0.2)
            return jnp.sum(jnp.sin(out)) + jnp.sum(jnp.cos(y))

        g1 = jax.grad(f1, argnums=(0, 1, 2, 3))(x, r, gamma, beta)
        g2 = jax.grad(f2, argnums=(0, 1, 2, 3))(x, r, gamma, beta)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)


class TestLlamaFamily:
    def test_llama_style_gpt_trains(self):
        """rope + swiglu wired into the GPT family (llama configs)."""
        import paddle_tpu as paddle
        from paddle_tpu.models.gpt import (
            GPTForPretraining,
            GPTPretrainingCriterion,
            gpt_config,
        )
        from paddle_tpu.optimizer.optimizers import AdamW

        paddle.seed(0)
        cfg = gpt_config("llama-1b", vocab_size=128, hidden_size=64,
                         num_layers=2, num_attention_heads=4,
                         intermediate_size=128,
                         max_position_embeddings=64)
        model = GPTForPretraining(cfg)
        assert not model.gpt.embeddings.use_wpe
        crit = GPTPretrainingCriterion()
        opt = AdamW(learning_rate=3e-3, parameters=model.parameters())
        ids = paddle.to_tensor(
            np.random.default_rng(0).integers(0, 128, (4, 16)).astype("int32"))
        losses = []
        for _ in range(8):
            loss = crit(model(ids), ids)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss._data))
        assert losses[-1] < losses[0], losses

    def test_llama_pipeline_trains(self):
        """rope configs work through the hybrid pipeline (no wpe shared)."""
        import paddle_tpu as paddle
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.meta_parallel.pipeline_schedule import (
            build_gpt_pipeline_step,
        )
        from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
        from paddle_tpu.optimizer.optimizers import AdamW

        dist.init_mesh({"pp": 2, "mp": 2, "dp": 2})
        try:
            paddle.seed(0)
            cfg = gpt_config("llama-1b", vocab_size=128, hidden_size=64,
                             num_layers=2, num_attention_heads=4,
                             intermediate_size=128,
                             max_position_embeddings=64)
            model = GPTForPretraining(cfg)
            opt = AdamW(learning_rate=3e-3, parameters=model.parameters())
            step = build_gpt_pipeline_step(model, opt, microbatches=2)
            x = np.random.default_rng(0).integers(0, 128, (8, 16)).astype("int32")
            losses = [float(step(x, x)) for _ in range(8)]
            assert losses[-1] < losses[0], losses
        finally:
            dist.clear_mesh()
