"""Elastic multi-rank data-parallel runtime (ISSUE 6, training half).

Fast tier: collective rendezvous/allgather over the KV store, dead-rank
detection, checkpoint sharding-layout metadata + reshard helpers, and the
crash-consistency regressions (torn snapshot fallback, stale temp sweep,
reshard errors not walked past).

Slow tier (``-m slow``, CPU-multiprocess): SIGKILL one of N=3 dp rank
processes mid-training → survivors detect the heartbeat lapse, reshard the
newest intact checkpoint to dp=2 and continue; their post-recovery loss
trajectory is bit-identical to a fresh dp=2 run restored from the same
resharded snapshot (the acceptance criterion).
"""
import json
import os
import shutil
import signal
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.fleet.elastic.collective import (
    ElasticCollective,
    RankFailure,
    pack_arrays,
    unpack_arrays,
)
from paddle_tpu.distributed.fleet.elastic.manager import _TcpStore
from paddle_tpu.distributed.fleet.utils.http_server import KVServer
from paddle_tpu.framework.checkpoint import (
    CheckpointCorruptionError,
    CheckpointManager,
    CheckpointReshardError,
    reshard_train_state,
    shard_bounds,
    shard_slice,
    unshard,
)


@pytest.fixture()
def kv():
    srv = KVServer().start()
    yield f"127.0.0.1:{srv.port}"
    srv.stop()


def _store(addr, job="job", ttl=1.0):
    return _TcpStore(addr, job, ttl=ttl, retries=1)


# =====================================================================
# shard helpers + reshard_train_state
# =====================================================================
class TestShardHelpers:
    def test_bounds_cover_and_order(self):
        assert shard_bounds(4, 3) == [(0, 2), (2, 3), (3, 4)]
        assert shard_bounds(6, 2) == [(0, 3), (3, 6)]
        assert shard_bounds(2, 4) == [(0, 1), (1, 2), (2, 2), (2, 2)]

    def test_slice_unshard_roundtrip(self):
        a = np.arange(28.0).reshape(7, 4)
        for world in (1, 2, 3, 7, 9):
            parts = [shard_slice(a, world, r) for r in range(world)]
            np.testing.assert_array_equal(unshard(parts), a)

    def test_reshard_slices_only_layout_paths(self):
        state = {"params": {"w": np.arange(6.0)},
                 "velocity": {"w": np.arange(6.0) * 2}, "step": 3}
        layout = {"/velocity/w": {"axis": 0, "world": 3}}
        out = reshard_train_state(state, layout, 2, 1)
        np.testing.assert_array_equal(out["params"]["w"], state["params"]["w"])
        np.testing.assert_array_equal(out["velocity"]["w"],
                                      np.asarray([6.0, 8.0, 10.0]))
        assert out["step"] == 3

    def test_even_layout_indivisible_raises_reshard_error(self):
        state = {"v": np.zeros((4, 2))}
        with pytest.raises(CheckpointReshardError, match="evenly"):
            reshard_train_state(
                state, {"/v": {"axis": 0, "world": 2, "even": True}}, 3, 0)

    def test_mesh_spec_layout_rejected_not_silently_dp_cut(self):
        """A ParallelTrainer.state_layout() entry ({"axes","mesh"} schema)
        fed to reshard_train_state must raise, not default to an axis-0 dp
        cut that silently corrupts model-parallel params."""
        state = {"params": {"w": np.arange(8.0).reshape(4, 2)}}
        layout = {"/params/w": {"axes": [["model"], None],
                                "mesh": {"model": 2}}}
        with pytest.raises(CheckpointReshardError, match="restore_state"):
            reshard_train_state(state, layout, 2, 0)

    def test_pack_unpack_roundtrip(self):
        tree = {"a": np.arange(5.0), "b": np.float64(2.5)}
        out = unpack_arrays(pack_arrays(tree))
        np.testing.assert_array_equal(out["a"], tree["a"])
        assert float(out["b"]) == 2.5


# =====================================================================
# checkpoint metadata + crash consistency
# =====================================================================
class TestCheckpointLayout:
    def test_layout_and_shapes_in_meta(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, {"w": np.zeros((4, 3))},
                 layout={"/w": {"axis": 0, "world": 3}},
                 metadata={"world": 3})
        _state, meta = mgr.load(1)
        assert meta == {"world": 3}
        assert mgr.last_loaded_meta["layout"] == {
            "/w": {"axis": 0, "world": 3}}
        assert mgr.last_loaded_meta["shapes"] == {"/w": [4, 3]}

    def test_torn_snapshot_falls_back_to_previous_intact(self, tmp_path):
        """A snapshot published by a non-atomic/non-fsynced writer (full
        arrays, torn meta.json) must cost at most itself — load() walks
        back to the previous intact step."""
        mgr = CheckpointManager(str(tmp_path), keep_max=10)
        mgr.save(1, {"w": np.arange(4.0)})
        mgr.save(2, {"w": np.arange(4.0) * 2})
        good = tmp_path / "step_2"
        torn = tmp_path / "step_3"
        shutil.copytree(good, torn)
        blob = (torn / "meta.json").read_bytes()
        (torn / "meta.json").write_bytes(blob[: len(blob) // 2])
        with pytest.warns(RuntimeWarning, match="corrupt"):
            state, _ = mgr.load()
        assert mgr.last_loaded_step == 2
        np.testing.assert_array_equal(state["w"], np.arange(4.0) * 2)

    def test_crash_before_rename_leaves_no_step_dir(self, tmp_path):
        """The write protocol publishes via atomic rename: everything
        before the rename lives in a dot-temp dir that all_steps ignores
        and a later manager sweeps."""
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(5, {"w": np.arange(3.0)})
        # emulate a crash mid-save: a temp dir with partial contents
        dead = tmp_path / ".tmp_step_6_deadbeef"
        dead.mkdir()
        (dead / "arrays.npz").write_bytes(b"partial")
        assert mgr.all_steps() == [5]  # never visible as a snapshot
        old = time.time() - 7200
        os.utime(dead, (old, old))
        CheckpointManager(str(tmp_path))  # init sweeps stale temps
        assert not dead.exists()
        # a FRESH temp (another live writer) is left alone
        live = tmp_path / ".tmp_step_7_cafe"
        live.mkdir()
        CheckpointManager(str(tmp_path))
        assert live.exists()

    def test_reshard_error_not_walked_past(self, tmp_path):
        """An intact snapshot whose layout cannot map onto the current
        mesh raises CheckpointReshardError from load(step=None) — falling
        back to an OLDER snapshot with the same layout would just hide the
        topology problem."""
        from paddle_tpu.distributed.env import clear_mesh, init_mesh

        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, {"w": np.zeros((3, 2))})
        mgr.save(2, {"w": np.zeros((3, 2))})
        for step in (1, 2):
            mp = tmp_path / f"step_{step}" / "meta.json"
            meta = json.loads(mp.read_text())
            meta["specs"] = {"/w": ["dp"]}  # dim0 extent 3 sharded over dp
            mp.write_text(json.dumps(meta))
        clear_mesh()
        init_mesh({"dp": 2})  # 3 % 2 != 0 → not mappable
        try:
            with pytest.raises(CheckpointReshardError, match="dim 0"):
                mgr.load()
        finally:
            clear_mesh()


class TestTrainerStateLayout:
    def test_scalar_params_rejected_with_guidance(self):
        """A 0-d parameter cannot be row-sharded: the trainer must say so
        up front, not IndexError deep inside the first step."""
        from paddle_tpu.resilience.elastic_trainer import ElasticDPTrainer

        ElasticDPTrainer._check_shardable({"w": np.zeros((2, 2))})
        with pytest.raises(ValueError, match=r"0-d.*reshape"):
            ElasticDPTrainer._check_shardable(
                {"w": np.zeros((2, 2)), "t": np.float32(1.0)})

    def test_capture_layout_and_restore_validation(self):
        import paddle_tpu as paddle
        from paddle_tpu.distributed.env import clear_mesh, init_mesh
        from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
        from paddle_tpu.optimizer.optimizers import AdamW

        paddle.seed(0)
        clear_mesh()
        init_mesh({"dp": 1})
        try:
            net = paddle.nn.Linear(4, 4)
            opt = AdamW(learning_rate=1e-2, parameters=net.parameters())
            tr = ParallelTrainer(net, lambda o, y: ((o - y) ** 2).mean(),
                                 opt, dp_axis=None, donate=False)
            layout = tr.state_layout()
            assert set(layout) == {f"/params/{n}" for n in tr.params}
            for entry in layout.values():
                assert entry["mesh"] == {"dp": 1}
            # snapshots restore cleanly on the same topology
            snap = tr.capture_state()
            tr.restore_state(snap)
            # an extent the mesh cannot divide is refused with the
            # reshard error, not an XLA crash
            from jax.sharding import PartitionSpec as P

            tr.param_specs["weight"] = P("dp")
            tr.mesh = _FakeMesh({"dp": 3})
            with pytest.raises(CheckpointReshardError):
                tr.restore_state(snap)
        finally:
            clear_mesh()


class _FakeMesh:
    def __init__(self, shape):
        self.shape = shape


# =====================================================================
# collective: rendezvous / allgather / failure detection
# =====================================================================
class TestCollective:
    def _spawn(self, fn, n):
        out, errs = {}, {}

        def wrap(i):
            try:
                out[i] = fn(i)
            except Exception as e:  # surfaced by the assert below
                errs[i] = e

        ts = [threading.Thread(target=wrap, args=(i,)) for i in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert not errs, errs
        return out

    def test_scan_keys_only_and_prefix(self, kv):
        """The poll loops scan on key presence only — the server must
        filter by prefix and omit payload values on request, so a slow
        peer never causes a per-poll download of every gradient blob."""
        st = _store(kv)
        st.put("ag0:g:0", "B" * 4096)
        st.put("ag0:g:1", "C" * 4096)
        st.put("rdv0:node_0", "1")
        full = st.scan()
        assert set(full) == {"ag0:g:0", "ag0:g:1", "rdv0:node_0"}
        assert full["ag0:g:0"][0] == "B" * 4096
        keys = st.scan(keys_only=True)
        assert set(keys) == set(full)
        assert all(v is None and isinstance(age, float)
                   for v, age in keys.values())
        pfx = st.scan(prefix="ag0:g:")
        assert set(pfx) == {"ag0:g:0", "ag0:g:1"}
        assert pfx["ag0:g:1"][0] == "C" * 4096
        assert set(st.scan(keys_only=True, prefix="rdv")) == {"rdv0:node_0"}

    def test_rendezvous_assigns_sorted_ranks(self, kv):
        def rank(i):
            st = _store(kv)
            nid = f"node_{i}"
            st.register(nid, f"ep{i}")
            col = ElasticCollective(st, nid)
            r = col.rendezvous(0, min_ranks=3, timeout=30)
            return r, col.world, tuple(col.members)

        out = self._spawn(rank, 3)
        assert sorted(v[0] for v in out.values()) == [0, 1, 2]
        assert all(v[1] == 3 for v in out.values())
        assert len({v[2] for v in out.values()}) == 1  # identical views

    def test_racing_generations_converge(self, kv):
        """A rank that proposes gen g must adopt a peer's higher live
        proposal instead of deadlocking one generation apart."""
        def rank(i):
            st = _store(kv)
            nid = f"node_{i}"
            st.register(nid, f"ep{i}")
            col = ElasticCollective(st, nid)
            col.rendezvous(i, min_ranks=2, timeout=30)  # propose 0 and 1
            return col.generation

        out = self._spawn(rank, 2)
        assert set(out.values()) == {1}

    def test_allgather_rank_order_and_gc(self, kv):
        def rank(i):
            st = _store(kv)
            nid = f"node_{i}"
            st.register(nid, f"ep{i}")
            col = ElasticCollective(st, nid)
            col.rendezvous(0, min_ranks=2, timeout=30)
            for s in range(3):
                got = col.allgather(f"s{s}", f"payload-{s}-{col.rank}",
                                    timeout=30)
                assert got == [f"payload-{s}-0", f"payload-{s}-1"]
            return True

        out = self._spawn(rank, 2)
        assert all(out.values())

    def test_dead_rank_raises_rank_failure(self, kv):
        """A member that stops heartbeating mid-allgather is detected via
        TTL expiry, not a blind timeout."""
        stores = {}

        def rank(i):
            st = _store(kv, ttl=0.8)
            stores[i] = st
            nid = f"node_{i}"
            st.register(nid, f"ep{i}")
            col = ElasticCollective(st, nid)
            col.rendezvous(0, min_ranks=2, timeout=30)
            if i == 1:
                return "died"  # never publishes, never beats again
            with pytest.raises(RankFailure) as ei:
                while True:  # keep our own liveness fresh while waiting
                    stores[0].heartbeat("node_0")
                    col.allgather("s0", "x", timeout=10)
            assert ei.value.dead == ["node_1"]
            return "survived"

        out = self._spawn(rank, 2)
        assert out[0] == "survived"


# =====================================================================
# deterministic kill-one-rank (tier-1): the SIGKILL replaced by an
# injected `kill` at the elastic.rank.step seam — rank THREADS in one
# process, each with its own thread-local FaultSchedule; heartbeats halt
# and the thread dies abruptly, so survivors see the same TTL-expiry
# liveness path as a real process kill. Replays bit-identically.
# =====================================================================
_W_STAR = np.arange(12.0).reshape(4, 3) / 10.0


def _dp_grad_fn(params, step, rank, world):
    rng = np.random.default_rng(100000 + 1000 * step + 10 * world + rank)
    X = rng.standard_normal((8, 4))
    E = X @ params["w"] + params["b"] - X @ _W_STAR
    loss = float((E ** 2).mean())
    return loss, {"w": 2 * X.T @ E / E.size,
                  "b": 2 * E.sum(axis=0) / E.size}


def _dp_init_params():
    return {"w": np.zeros((4, 3)), "b": np.zeros((3,))}


class TestInjectedRankLoss:
    TOTAL = 6
    KILL_STEP = 2

    def _run_cohort(self, addr, job, ckpt, n_ranks, *, victim=None,
                    schedule=None, resume_step=None, wait_world=None,
                    ttl=1.2):
        """Drive ``n_ranks`` ElasticDPTrainer threads over one KV server.
        ``victim`` (rank-thread index) runs under ``schedule.scope()`` and
        is expected to die of InjectedDeath. Returns (history, events)
        per thread index."""
        import contextlib

        from paddle_tpu.distributed.fleet.elastic.manager import ElasticManager
        from paddle_tpu.resilience import InjectedDeath
        from paddle_tpu.resilience.elastic_trainer import ElasticDPTrainer

        histories = {i: [] for i in range(n_ranks)}
        events = {i: [] for i in range(n_ranks)}
        errors = {}

        def rank_fn(i):
            st = _TcpStore(addr, job, ttl=ttl, retries=1)
            mgr = ElasticManager(store=st)
            mgr.endpoint = f"127.0.0.1:{7600 + i}"
            mgr.node_id = f"node_{i}"
            tr = ElasticDPTrainer(
                mgr, ckpt, _dp_grad_fn, _dp_init_params, lr=0.3,
                momentum=0.9, min_ranks=1, step_timeout=60,
                rendezvous_timeout=60,
                on_step=lambda s, w, l: histories[i].append(
                    (s, w, np.float64(l).hex())),
                on_event=events[i].append)
            ctx = (schedule.scope() if schedule is not None and i == victim
                   else contextlib.nullcontext())
            try:
                with ctx:
                    tr.run(self.TOTAL, resume_step=resume_step,
                           wait_world=wait_world or n_ranks)
            except InjectedDeath:
                events[i].append("DIED")
                return  # abrupt: no tr.close(), no deregister
            except Exception as e:  # pragma: no cover - surfaced below
                errors[i] = e
                raise
            tr.close()

        threads = [threading.Thread(target=rank_fn, args=(i,), daemon=True)
                   for i in range(n_ranks)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(240)
            assert not t.is_alive(), "rank thread hung"
        assert not errors, errors
        return histories, events

    def _kill_schedule(self):
        from paddle_tpu.resilience import FaultSchedule

        return FaultSchedule(seed=11).add(
            "elastic.rank.step", "kill", match={"step": self.KILL_STEP})

    def _injected_leg(self, tmp_path, tag):
        srv = KVServer().start()
        try:
            sched = self._kill_schedule()
            hist, events = self._run_cohort(
                f"127.0.0.1:{srv.port}", f"job_{tag}",
                str(tmp_path / f"ckpt_{tag}"), 3, victim=2,
                schedule=sched)
        finally:
            srv.stop()
        return hist, events, sched.fired_log()

    def test_injected_rank_loss_resharded_recovery_bit_identical(
            self, tmp_path):
        """The tier-1 twin of the slow SIGKILL e2e, plus the replay
        acceptance: two runs of the injected scenario produce the
        identical fault sequence AND bit-identical trajectories, and the
        post-recovery trajectory matches a fresh dp=2 run restored from
        the same resharded snapshot."""
        hist_a, events_a, log_a = self._injected_leg(tmp_path, "a")
        hist_b, _, log_b = self._injected_leg(tmp_path, "b")

        # replay certificate: same fault sequence, bit-identical histories
        assert log_a == log_b == [
            {"point": "elastic.rank.step", "kind": "kill", "count": 1,
             "labels": {"rank": 2, "step": self.KILL_STEP,
                        "node": "node_2"}}]
        assert hist_a == hist_b

        # survivors ran the full trajectory, identically; victim died
        steps0 = {s: (w, l) for s, w, l in hist_a[0]}
        assert sorted(steps0) == list(range(self.TOTAL))
        assert hist_a[0] == hist_a[1]
        assert "DIED" in events_a[2]
        assert max(s for s, _, _ in hist_a[2]) < self.KILL_STEP

        # exactly one recovery, resharded from the newest intact snapshot
        recover = [e for e in events_a[0]
                   if e.startswith("restore: snapshot")]
        assert len(recover) == 1, events_a[0]
        snap = int(recover[0].split("step=")[1].split()[0])
        assert snap == self.KILL_STEP - 1  # the kill step never published
        post = {s: v for s, v in steps0.items() if s > snap}
        assert post and all(w == 2 for w, _ in post.values())
        assert all(w == 3 for s, (w, _) in steps0.items() if s <= snap)

        # fresh dp=2 arm restored from the SAME resharded snapshot
        ckpt2 = str(tmp_path / "ckpt_fresh")
        shutil.copytree(str(tmp_path / "ckpt_a"), ckpt2)
        srv2 = KVServer().start()
        try:
            fresh_hist, _ = self._run_cohort(
                f"127.0.0.1:{srv2.port}", "job_fresh", ckpt2, 2,
                resume_step=snap, wait_world=2)
        finally:
            srv2.stop()
        fsteps = {s: (w, l) for s, w, l in fresh_hist[0]}
        assert fresh_hist[0] == fresh_hist[1]
        # the acceptance criterion: bit-identical post-recovery trajectory
        assert {s: v for s, v in fsteps.items() if s > snap} == post


# =====================================================================
# kill-one-rank e2e (CPU-multiprocess, slow tier)
# =====================================================================
_RANK_SCRIPT = textwrap.dedent("""
    import os, sys
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    addr, job, ckpt, port, total, wait = (
        sys.argv[1], sys.argv[2], sys.argv[3], sys.argv[4],
        int(sys.argv[5]), int(sys.argv[6]))
    resume = int(sys.argv[7]) if len(sys.argv) > 7 else None

    os.environ["PADDLE_CURRENT_ENDPOINT"] = f"127.0.0.1:{port}"
    os.environ["PADDLE_ELASTIC_NP"] = "0"

    from paddle_tpu.distributed.fleet.elastic.manager import (
        ElasticManager, _TcpStore)
    from paddle_tpu.resilience.elastic_trainer import ElasticDPTrainer

    W_STAR = np.arange(12.0).reshape(4, 3) / 10.0

    def grad_fn(params, step, rank, world):
        rng = np.random.default_rng(100000 + 1000 * step + 10 * world + rank)
        X = rng.standard_normal((8, 4))
        E = X @ params["w"] + params["b"] - X @ W_STAR
        loss = float((E ** 2).mean())
        return loss, {"w": 2 * X.T @ E / E.size,
                      "b": 2 * E.sum(axis=0) / E.size}

    def init_params():
        return {"w": np.zeros((4, 3)), "b": np.zeros((3,))}

    mgr = ElasticManager(store=_TcpStore(addr, job, ttl=1.5, retries=1))
    tr = ElasticDPTrainer(
        mgr, ckpt, grad_fn, init_params, lr=0.3, momentum=0.9,
        min_ranks=1, step_timeout=60, rendezvous_timeout=60,
        on_step=lambda s, w, l: print(
            f"STEP {s} {w} {np.float64(l).hex()}", flush=True),
        on_event=lambda m: print(f"EV {m}", flush=True))
    tr.run(total, resume_step=resume, wait_world=wait)
    tr.close()
    print("EXIT ok", flush=True)
""")


def _parse_steps(text, world=None):
    out = {}
    for line in text.splitlines():
        if line.startswith("STEP "):
            _, s, w, loss_hex = line.split()
            if world is None or int(w) == world:
                out[int(s)] = (int(w), loss_hex)
    return out


@pytest.mark.slow
@pytest.mark.chaos
def test_kill_one_rank_resharded_recovery_bit_identical(tmp_path):
    """SIGKILL 1 of 3 dp ranks mid-training: survivors re-rendezvous at
    dp=2, reshard the newest intact snapshot, continue — and the post-
    recovery trajectory matches a fresh dp=2 run restored from the same
    resharded snapshot, bit for bit."""
    srv = KVServer().start()
    addr = f"127.0.0.1:{srv.port}"
    script = tmp_path / "rank.py"
    script.write_text(_RANK_SCRIPT)
    ckpt = str(tmp_path / "ckpt")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(
                   p for p in (repo, os.environ.get("PYTHONPATH")) if p))
    TOTAL = 12

    def launch(job, port, wait, extra=()):
        return subprocess.Popen(
            [sys.executable, str(script), addr, job, ckpt, str(port),
             str(TOTAL), str(wait), *map(str, extra)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)

    procs = [launch("jobA", 7301 + i, wait=3) for i in range(3)]
    victim = procs[2]  # highest node_id → non-leader, non-writer
    try:
        # SIGKILL the victim once it announces step 4 (mid-training)
        for line in victim.stdout:
            if line.startswith("STEP 4 "):
                victim.kill()
                break
        outs = []
        for p in procs[:2]:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, (out, err)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        srv.stop()

    # both survivors ran the full trajectory, identically
    steps0, steps1 = _parse_steps(outs[0]), _parse_steps(outs[1])
    assert sorted(steps0) == list(range(TOTAL))
    assert steps0 == steps1
    recover = [ln for ln in outs[0].splitlines()
               if ln.startswith("EV restore: snapshot")]
    assert len(recover) == 1, outs[0]
    snap = int(recover[0].split("step=")[1].split()[0])
    post = {s: v for s, v in steps0.items() if s > snap}
    assert post and all(w == 2 for w, _ in post.values())
    assert all(w == 3 for s, (w, _) in steps0.items() if s <= snap)

    # fresh dp=2 arm restored from the SAME resharded snapshot
    ckpt2 = str(tmp_path / "ckpt_fresh")
    shutil.copytree(ckpt, ckpt2)
    srv2 = KVServer().start()
    addr2 = f"127.0.0.1:{srv2.port}"
    try:
        fresh = [subprocess.Popen(
            [sys.executable, str(script), addr2, "jobB", ckpt2,
             str(7401 + i), str(TOTAL), "2", str(snap)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for i in range(2)]
        fouts = []
        for p in fresh:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, (out, err)
            fouts.append(out)
    finally:
        srv2.stop()
    fsteps = _parse_steps(fouts[0])
    assert fsteps == _parse_steps(fouts[1])
    # the acceptance criterion: bit-identical post-recovery trajectory
    assert {s: v for s, v in fsteps.items() if s > snap} == post
