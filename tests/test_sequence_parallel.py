"""Sequence parallelism tests: ring attention + Ulysses vs full attention.

The reference has no sequence parallelism (SURVEY §5.7) — these validate the
TPU-native addition: exact numerical parity with dense attention on the
8-virtual-device 'sp' mesh, forward and backward, causal and full.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import P
from paddle_tpu.distributed.meta_parallel.sequence_parallel import (
    _ring_attention_raw,
    _ulysses_raw,
    gather_sequence,
    split_sequence,
)

B, H, T, D = 2, 8, 64, 16  # T sharded 8 ways -> 8 tokens per shard


@pytest.fixture
def sp_mesh():
    dist.init_mesh({"sp": 8})
    yield
    dist.clear_mesh()


def _qkv(seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: rng.standard_normal((B, H, T, D)).astype(np.float32)
    return mk(), mk(), mk()


def _dense(q, k, v, causal):
    scale = 1.0 / np.sqrt(D)
    logits = np.einsum("bhtd,bhsd->bhts", q, k) * scale
    if causal:
        mask = np.tril(np.ones((T, T), bool))
        logits = np.where(mask, logits, -1e9)
    w = jax.nn.softmax(jnp.asarray(logits), axis=-1)
    return np.einsum("bhts,bhsd->bhtd", np.asarray(w), v)


def _run_sharded(fn, q, k, v):
    f = dist.run_on_mesh(
        fn,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None),
    )
    return np.asarray(f(q, k, v))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, sp_mesh, causal):
        q, k, v = _qkv()
        out = _run_sharded(
            lambda q, k, v: _ring_attention_raw(q, k, v, "sp", causal, None), q, k, v)
        np.testing.assert_allclose(out, _dense(q, k, v, causal), rtol=2e-4, atol=2e-5)

    def test_backward_matches_dense(self, sp_mesh):
        q, k, v = _qkv(1)

        def ring_loss(q, k, v):
            # local loss only: cross-shard gradient credit flows through the
            # ppermute transposes; a psum here would double-count it n times
            out = _ring_attention_raw(q, k, v, "sp", True, None)
            return jnp.sum(out**2)

        grad_f = dist.run_on_mesh(
            jax.grad(ring_loss, argnums=(0, 1, 2)),
            in_specs=(P(None, None, "sp", None),) * 3,
            out_specs=(P(None, None, "sp", None),) * 3,
        )
        dq, dk, dv = (np.asarray(g) for g in grad_f(q, k, v))

        def dense_loss(q, k, v):
            scale = 1.0 / np.sqrt(D)
            logits = jnp.einsum("bhtd,bhsd->bhts", q, k) * scale
            mask = jnp.tril(jnp.ones((T, T), bool))
            logits = jnp.where(mask, logits, -1e9)
            w = jax.nn.softmax(logits, axis=-1)
            out = jnp.einsum("bhts,bhsd->bhtd", w, v)
            return jnp.sum(out**2)

        rq, rk, rv = jax.grad(dense_loss, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        np.testing.assert_allclose(dq, np.asarray(rq), rtol=2e-3, atol=2e-4)
        np.testing.assert_allclose(dk, np.asarray(rk), rtol=2e-3, atol=2e-4)
        np.testing.assert_allclose(dv, np.asarray(rv), rtol=2e-3, atol=2e-4)


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, sp_mesh, causal):
        q, k, v = _qkv(2)
        out = _run_sharded(
            lambda q, k, v: _ulysses_raw(q, k, v, "sp", causal, None), q, k, v)
        np.testing.assert_allclose(out, _dense(q, k, v, causal), rtol=2e-4, atol=2e-5)

    def test_head_divisibility_check(self, sp_mesh):
        q = np.zeros((B, 4, T, D), np.float32)  # 4 heads < 8 shards
        with pytest.raises(Exception, match="divisible"):
            _run_sharded(lambda q, k, v: _ulysses_raw(q, k, v, "sp", False, None), q, q, q)


class TestSequenceHelpers:
    def test_split_gather_roundtrip(self, sp_mesh):
        x = np.random.randn(2, 64, 4).astype(np.float32)

        def fn(x_full):
            loc = split_sequence(x_full, seq_axis=1)
            return gather_sequence(loc, seq_axis=1)

        f = dist.run_on_mesh(fn, in_specs=P(), out_specs=P())
        np.testing.assert_allclose(np.asarray(f(x)), x)


class TestGPTSequenceParallel:
    def test_gpt_attention_sp_matches_dense(self, sp_mesh):
        """GPT block with sequence_parallel='ring' under shard_map equals the
        dense model on the same weights."""
        from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
        from paddle_tpu.tensor import Tensor

        paddle.seed(0)
        cfg = dict(vocab_size=128, hidden_size=32, num_layers=1,
                   num_attention_heads=8, max_position_embeddings=64,
                   hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
        dense = GPTForPretraining(gpt_config("gpt2-small", **cfg))
        dense.eval()
        ids = np.random.default_rng(0).integers(0, 128, (2, 64)).astype("int32")
        ref = np.asarray(dense(paddle.to_tensor(ids))._data)

        sp = GPTForPretraining(gpt_config("gpt2-small", sequence_parallel="ring", **cfg))
        sp.eval()
        sp.set_state_dict(dense.state_dict())
        params = {n: p._data for n, p in sp.named_parameters()}
        buffers = {n: b._data for n, b in sp.named_buffers()}

        def fwd(params, ids_loc, pos_loc):
            with paddle.no_grad():
                out, _ = sp.functional_call_with_state(
                    params, buffers, Tensor(ids_loc), Tensor(pos_loc))
            return out._data

        pos = np.broadcast_to(np.arange(64, dtype="int32"), (2, 64)).copy()
        f = dist.run_on_mesh(
            fwd,
            in_specs=(P(), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp", None),
        )
        out = np.asarray(f(params, ids, pos))
        np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)

        # default position_ids must be GLOBAL on each shard (rank offset)
        def fwd_nopos(params, ids_loc):
            with paddle.no_grad():
                out, _ = sp.functional_call_with_state(params, buffers, Tensor(ids_loc))
            return out._data

        f2 = dist.run_on_mesh(
            fwd_nopos, in_specs=(P(), P(None, "sp")), out_specs=P(None, "sp", None))
        out2 = np.asarray(f2(params, ids))
        np.testing.assert_allclose(out2, ref, rtol=2e-3, atol=2e-3)


class TestRingFlashAttention:
    """Flash-blocked ring (VERDICT r2 weak #4): per-hop Pallas kernels with
    cross-hop online merge — parity vs dense, fwd + grads, interpret mode."""

    B2, H2, T2, D2 = 1, 2, 256, 32  # sp=2 -> T_loc=128; D=32 pads to 64

    def _qkv2(self, seed=2):
        rng = np.random.default_rng(seed)
        mk = lambda: rng.standard_normal(
            (self.B2, self.H2, self.T2, self.D2)).astype(np.float32)
        return mk(), mk(), mk()

    def _dense2(self, q, k, v, causal):
        scale = 1.0 / np.sqrt(self.D2)
        logits = np.einsum("bhtd,bhsd->bhts", q, k) * scale
        if causal:
            mask = np.tril(np.ones((self.T2, self.T2), bool))
            logits = np.where(mask, logits, -1e9)
        w = jax.nn.softmax(jnp.asarray(logits), axis=-1)
        return np.einsum("bhts,bhsd->bhtd", np.asarray(w), v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_ring_matches_dense(self, causal):
        from paddle_tpu.distributed.meta_parallel.sequence_parallel import (
            _ring_attention_flash)

        dist.init_mesh({"sp": 2})
        try:
            q, k, v = self._qkv2()
            f = dist.run_on_mesh(
                lambda q, k, v: _ring_attention_flash(
                    q, k, v, "sp", causal, None, True),
                in_specs=(P(None, None, "sp", None),) * 3,
                out_specs=P(None, None, "sp", None),
            )
            out = np.asarray(f(q, k, v))
            np.testing.assert_allclose(out, self._dense2(q, k, v, causal),
                                       rtol=2e-4, atol=2e-5)
        finally:
            dist.clear_mesh()

    def test_flash_ring_backward_matches_dense(self):
        from paddle_tpu.distributed.meta_parallel.sequence_parallel import (
            _ring_attention_flash)

        dist.init_mesh({"sp": 2})
        try:
            q, k, v = self._qkv2(3)

            def ring_loss(q, k, v):
                out = _ring_attention_flash(q, k, v, "sp", True, None, True)
                return jnp.sum(out**2)

            grad_f = dist.run_on_mesh(
                jax.grad(ring_loss, argnums=(0, 1, 2)),
                in_specs=(P(None, None, "sp", None),) * 3,
                out_specs=(P(None, None, "sp", None),) * 3,
            )
            dq, dk, dv = (np.asarray(g) for g in grad_f(q, k, v))

            scale = 1.0 / np.sqrt(self.D2)

            def dense_loss(q, k, v):
                logits = jnp.einsum("bhtd,bhsd->bhts", q, k) * scale
                mask = jnp.tril(jnp.ones((self.T2, self.T2), bool))
                logits = jnp.where(mask, logits, -1e9)
                w = jax.nn.softmax(logits, axis=-1)
                out = jnp.einsum("bhts,bhsd->bhtd", w, v)
                return jnp.sum(out**2)

            rq, rk, rv = jax.grad(dense_loss, argnums=(0, 1, 2))(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
            np.testing.assert_allclose(dq, np.asarray(rq), rtol=2e-3, atol=2e-4)
            np.testing.assert_allclose(dk, np.asarray(rk), rtol=2e-3, atol=2e-4)
            np.testing.assert_allclose(dv, np.asarray(rv), rtol=2e-3, atol=2e-4)
        finally:
            dist.clear_mesh()
