"""Vision model zoo: forward-shape tests (reference test style:
python/paddle/tests/test_vision_models.py — instantiate, forward, check
logit shape)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models


def _run(model, size=64, channels=3, batch=1):
    model.eval()
    x = paddle.to_tensor(np.random.randn(batch, channels, size, size).astype("float32"))
    return model(x)


@pytest.mark.parametrize("name,kwargs,size", [
    ("resnet18", {}, 64),
    ("resnet50", {}, 64),
    ("wide_resnet50_2", {}, 64),
    ("resnext50_32x4d", {}, 64),
    ("vgg11", {}, 64),
    ("alexnet", {}, 96),
    ("mobilenet_v1", {"scale": 0.25}, 64),
    ("mobilenet_v2", {"scale": 0.25}, 64),
    ("squeezenet1_0", {}, 96),
    ("squeezenet1_1", {}, 96),
    ("shufflenet_v2_x0_25", {}, 64),
    ("densenet121", {}, 64),
    ("inception_v3", {}, 75),
])
def test_model_forward_shape(name, kwargs, size):
    ctor = getattr(models, name)
    model = ctor(num_classes=10, **kwargs)
    out = _run(model, size=size)
    assert list(out.shape) == [1, 10]


def test_googlenet_train_aux_heads():
    model = models.googlenet(num_classes=10)
    model.eval()
    x = paddle.to_tensor(np.random.randn(1, 3, 64, 64).astype("float32"))
    out = model(x)
    assert list(out.shape) == [1, 10]
    model.train()
    out, a1, a2 = model(x)
    assert list(a1.shape) == [1, 10] and list(a2.shape) == [1, 10]


def test_resnet_trains():
    from paddle_tpu.optimizer.optimizers import SGD

    paddle.seed(0)
    model = models.resnet18(num_classes=4)
    opt = SGD(learning_rate=0.05, parameters=model.parameters())
    x = paddle.to_tensor(np.random.randn(8, 3, 32, 32).astype("float32"))
    y = paddle.to_tensor(np.random.default_rng(0).integers(0, 4, (8,)).astype("int64"))
    import paddle_tpu.nn.functional as F

    losses = []
    for _ in range(5):
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss._data))
    assert losses[-1] < losses[0], losses


class TestTransforms:
    def test_compose_pipeline(self):
        from paddle_tpu.vision import transforms as T

        t = T.Compose([
            T.Resize(40), T.CenterCrop(32), T.RandomHorizontalFlip(0.5),
            T.ToTensor(), T.Normalize([0.5] * 3, [0.5] * 3),
        ])
        img = (np.random.rand(48, 56, 3) * 255).astype(np.uint8)
        out = t(img)
        assert out.shape == (3, 32, 32)
        assert out.dtype == np.float32
        assert -1.01 <= out.min() and out.max() <= 1.01

    def test_resize_shapes(self):
        from paddle_tpu.vision import transforms as T

        img = (np.random.rand(32, 64, 3) * 255).astype(np.uint8)
        assert T.resize(img, 16).shape[:2] == (16, 32)  # short side
        assert T.resize(img, (20, 24)).shape[:2] == (20, 24)
        assert T.resize(img, 16, "nearest").shape[:2] == (16, 32)

    def test_pad_and_crop(self):
        from paddle_tpu.vision import transforms as T

        img = np.ones((8, 8, 3), np.uint8)
        assert T.pad(img, 2).shape == (12, 12, 3)
        assert T.crop(img, 1, 2, 4, 5).shape == (4, 5, 3)
        assert T.center_crop(img, 4).shape == (4, 4, 3)

    def test_random_resized_crop(self):
        from paddle_tpu.vision import transforms as T

        img = (np.random.rand(50, 60, 3) * 255).astype(np.uint8)
        out = T.RandomResizedCrop(24)(img)
        assert out.shape[:2] == (24, 24)

    def test_color_ops(self):
        from paddle_tpu.vision import transforms as T

        img = (np.random.rand(8, 8, 3) * 255).astype(np.uint8)
        assert T.adjust_brightness(img, 1.5).shape == img.shape
        assert T.adjust_contrast(img, 0.5).shape == img.shape
        assert T.to_grayscale(img, 3).shape == img.shape
        assert T.ColorJitter(0.4, 0.4, 0.4)(img).shape == img.shape


class TestDatasets:
    def test_fake_data_loader(self):
        from paddle_tpu.io import DataLoader
        from paddle_tpu.vision.datasets import FakeData

        ds = FakeData(size=16, image_shape=(3, 8, 8), num_classes=4)
        dl = DataLoader(ds, batch_size=4, shuffle=True)
        batches = list(dl)
        assert len(batches) == 4
        xb, yb = batches[0]
        assert tuple(xb.shape) == (4, 3, 8, 8)

    def test_mnist_idx_parser(self, tmp_path):
        import gzip
        import struct

        # write a tiny idx pair and read it back
        imgs = (np.arange(2 * 28 * 28) % 255).astype(np.uint8).reshape(2, 28, 28)
        lbls = np.asarray([3, 7], np.uint8)
        ip = tmp_path / "imgs.gz"
        lp = tmp_path / "lbls.gz"
        with gzip.open(ip, "wb") as f:
            f.write(struct.pack(">IIII", 2051, 2, 28, 28) + imgs.tobytes())
        with gzip.open(lp, "wb") as f:
            f.write(struct.pack(">II", 2049, 2) + lbls.tobytes())

        from paddle_tpu.vision.datasets import MNIST

        ds = MNIST(image_path=str(ip), label_path=str(lp))
        assert len(ds) == 2
        img, lbl = ds[1]
        assert img.shape == (28, 28) and int(lbl) == 7

    def test_no_egress_error(self):
        from paddle_tpu.vision.datasets import Cifar10

        with pytest.raises(RuntimeError, match="egress"):
            Cifar10()


class TestNewTransforms:
    def test_rotate_identity_and_90(self):
        from paddle_tpu.vision import transforms as T

        img = np.arange(5 * 5, dtype=np.uint8).reshape(5, 5)
        np.testing.assert_array_equal(T.rotate(img, 0), img)
        np.testing.assert_array_equal(T.rotate(img, 90), np.rot90(img, -1))
        # 90-degree rotation keeps all pixels (square, no fill needed)
        assert set(T.rotate(img, 90).flatten()) == set(img.flatten())

    def test_random_rotation_respects_degrees(self):
        from paddle_tpu.vision import transforms as T

        img = np.arange(9, dtype=np.uint8).reshape(3, 3)
        out = T.RandomRotation(0)(img)  # 0 degrees must be identity
        np.testing.assert_array_equal(out, img)

    def test_adjust_hue_roundtrip(self):
        from paddle_tpu.vision import transforms as T

        img = (np.random.rand(6, 6, 3) * 255).astype(np.uint8)
        np.testing.assert_allclose(T.adjust_hue(img, 0.0), img, atol=2)
        shifted = T.adjust_hue(img, 0.25)
        assert shifted.shape == img.shape and shifted.dtype == img.dtype
        # full-turn shift restores the image
        np.testing.assert_allclose(T.adjust_hue(img, 1.0), img, atol=2)


def test_conv_model_trains_under_compute_dtype_bf16():
    """compute_dtype='bfloat16' (AMP O2 master-weight pattern) must work for
    conv nets: lax.conv requires matching dtypes, so activations follow the
    downcast weights onto the MXU (regression: ResNet-50 bench failure)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed.env import clear_mesh, init_mesh
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.optimizer.optimizers import Momentum
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    clear_mesh()
    init_mesh({"dp": 1})
    try:
        m = LeNet()
        ce = paddle.nn.CrossEntropyLoss()
        opt = Momentum(learning_rate=0.05, momentum=0.9,
                       parameters=m.parameters())
        tr = ParallelTrainer(m, lambda o, y: ce(o, y), opt, dp_axis=None,
                             compute_dtype="bfloat16")
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(
            rng.standard_normal((16, 1, 28, 28)).astype("float32"))
        y = paddle.to_tensor(rng.integers(0, 10, (16,)).astype("int64"))
        l0 = float(tr.step(x, y)._data)
        for _ in range(20):
            l = float(tr.step(x, y)._data)
        assert l < l0, (l0, l)
    finally:
        clear_mesh()
