"""Int8 paged KV cache (ISSUE 18): per-page-scale quantized pool halves
the per-stream KV HBM (ratio pinned <= 55% of the fp layout), greedy
divergence vs the fp engine is pinned on fixed seeds, the Pallas int8
flash-decode kernel matches the XLA gather-dequant path bit-for-bit, and
admission 429 bodies cite the quantized page layout.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
from paddle_tpu.serving import (
    AdmissionRejected,
    ContinuousBatchingEngine,
    Request,
)
from paddle_tpu.serving.admission import AdmissionGate

VOCAB = 64


def _tiny_model(seed=0):
    paddle.seed(seed)
    cfg = gpt_config("gpt2-small", vocab_size=VOCAB, hidden_size=32,
                     num_layers=2, num_attention_heads=4,
                     max_position_embeddings=64, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    m = GPTForPretraining(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _tiny_model(0)


def _engine(model, **kw):
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("n_slots", 3)
    kw.setdefault("prefill_buckets", [4, 8, 16])
    kw.setdefault("page_size", 4)
    return ContinuousBatchingEngine(model, **kw)


def _drive(eng, prompts, news):
    reqs = [eng.submit(Request(p, max_new_tokens=n))
            for p, n in zip(prompts[:3], news[:3])]
    for _ in range(2):
        eng.step_once()
    reqs += [eng.submit(Request(p, max_new_tokens=n))
             for p, n in zip(prompts[3:], news[3:])]
    eng.run_until_idle(timeout=300)
    return reqs


class TestInt8KV:
    def test_page_bytes_at_most_55pct_of_fp(self, model):
        """The acceptance bound: int8 pages (payload + per-token scale
        rows) cost <= 55% of the fp pages, so one HBM budget admits
        ~2x the streams."""
        fp = _engine(model)
        q = _engine(model, kv_dtype="int8")
        assert q.page_bytes / fp.page_bytes <= 0.55
        # per-slot worst case the admission gate prices shrinks too
        g_fp = AdmissionGate(fp, budget_bytes=1 << 30)
        g_q = AdmissionGate(q, budget_bytes=1 << 30)
        assert (g_q.kv_bytes_per_slot() / g_fp.kv_bytes_per_slot()
                <= 0.55)

    def test_greedy_divergence_pinned(self, model):
        """Quantized KV is NOT bit-exact; the pinned certificate: on
        fixed seeds, all streams complete and greedy divergence vs the
        fp engine stays under 15% of positions."""
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, VOCAB, (l,)).astype(np.int32)
                   for l in [3, 5, 7, 4, 9]]
        news = [6, 4, 8, 5, 7]
        want = [np.asarray(r.result())
                for r in _drive(_engine(model), prompts, news)]
        got = _drive(_engine(model, kv_dtype="int8"), prompts, news)
        div = tot = 0
        for r, w in zip(got, want):
            assert r.state == Request.DONE, (r.state, r.error)
            g = np.asarray(r.result())
            assert len(g) == len(w)
            div += int((g != w).sum())
            tot += len(w)
        assert div / tot <= 0.15, f"divergence {div}/{tot}"

    def test_pallas_int8_matches_xla_int8(self, model):
        """The int8 flash-decode kernel (interpret mode on CPU) is
        bit-identical to the XLA gather-dequant reference."""
        rng = np.random.default_rng(1)
        prompts = [rng.integers(0, VOCAB, (l,)).astype(np.int32)
                   for l in [3, 5, 7, 4]]
        news = [6, 5, 7, 6]
        xla = _drive(_engine(model, kv_dtype="int8"), prompts, news)
        pl = _drive(_engine(model, kv_dtype="int8", attn_impl="pallas"),
                    prompts, news)
        for a, b in zip(pl, xla):
            assert a.state == Request.DONE, (a.state, a.error)
            np.testing.assert_array_equal(
                np.asarray(a.result()), np.asarray(b.result()))

    def test_int8_kernel_priced_in_cost_registry(self):
        from paddle_tpu.ops.pallas.paged_attention import (
            PAGED_ATTENTION_INT8_KERNEL_NAME,
        )
        from paddle_tpu.ops.pallas.cost_registry import kernel_cost_model

        assert kernel_cost_model(
            PAGED_ATTENTION_INT8_KERNEL_NAME) is not None

    def test_429_body_cites_quantized_layout(self, model):
        """A page-budget refusal on the int8 engine names kv_dtype in
        both the estimate dict and the message — operators see WHICH
        layout the budget was priced for."""
        eng = _engine(model, kv_dtype="int8", prefix_sharing=False)
        eng.admission_gate = AdmissionGate(
            eng, budget_bytes=1 << 30, page_budget=2)
        with pytest.raises(AdmissionRejected) as ei:
            eng.submit(Request(np.arange(1, 12, dtype=np.int32),
                               max_new_tokens=8))
        pages = ei.value.estimate["pages"]
        assert pages["kv_dtype"] == "int8"
        assert "kv_dtype int8" in str(ei.value)
        # the fp engine cites its own layout the same way
        fp = _engine(model, prefix_sharing=False)
        fp.admission_gate = AdmissionGate(
            fp, budget_bytes=1 << 30, page_budget=2)
        with pytest.raises(AdmissionRejected) as ei2:
            fp.submit(Request(np.arange(1, 12, dtype=np.int32),
                              max_new_tokens=8))
        assert ei2.value.estimate["pages"]["kv_dtype"] == "float32"

    def test_same_budget_admits_double_the_pages(self, model):
        """The operational payoff: a fixed HBM byte budget converts to
        >= 2x the page budget under the int8 layout."""
        fp = _engine(model)
        q = _engine(model, kv_dtype="int8")
        hbm = 64 * fp.page_bytes  # an arbitrary fixed byte budget
        assert hbm // q.page_bytes >= 2 * (hbm // fp.page_bytes)

    def test_int8_requires_paged_layout(self, model):
        with pytest.raises(ValueError, match="paged"):
            ContinuousBatchingEngine(model, max_seq_len=32, n_slots=2,
                                     kv_layout="slot", kv_dtype="int8")

    def test_pool_reset_reallocates_scales(self, model):
        """Cache-loss recovery re-zeros the scale tensors alongside the
        pools (a stale scale would mis-dequantize every later write)."""
        eng = _engine(model, kv_dtype="int8", prefix_sharing=False)
        r = eng.submit(Request(np.arange(1, 6, dtype=np.int32),
                               max_new_tokens=4))
        eng.run_until_idle(timeout=300)
        assert r.state == Request.DONE
        assert float(np.asarray(eng._scale_k).max()) > 0  # scales written
        eng.fail_pending("test reset")
        eng._reset_cache()
        assert float(np.asarray(eng._scale_k).max()) == 0.0
        assert float(np.asarray(eng._scale_v).max()) == 0.0
        # the engine still serves correctly after the reset
        r2 = eng.submit(Request(np.arange(1, 6, dtype=np.int32),
                                max_new_tokens=4))
        eng.run_until_idle(timeout=300)
        assert r2.state == Request.DONE
        np.testing.assert_array_equal(np.asarray(r2.result()),
                                      np.asarray(r.result()))
