"""Layer system + nn layer correctness tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def _rand(*shape):
    return np.random.uniform(-1, 1, shape).astype(np.float32)


class TestLayerSystem:
    def test_registration(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(4, 8)
                self.fc2 = nn.Linear(8, 2)

            def forward(self, x):
                return self.fc2(F.relu(self.fc1(x)))

        net = Net()
        names = [n for n, _ in net.named_parameters()]
        assert set(names) == {"fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"}
        assert len(net.parameters()) == 4
        assert len(net.sublayers()) == 2
        out = net(paddle.to_tensor(_rand(3, 4)))
        assert out.shape == [3, 2]

    def test_state_dict_roundtrip(self):
        net1, net2 = nn.Linear(4, 3), nn.Linear(4, 3)
        sd = net1.state_dict()
        net2.set_state_dict(sd)
        x = paddle.to_tensor(_rand(2, 4))
        np.testing.assert_allclose(net1(x).numpy(), net2(x).numpy())

    def test_train_eval(self):
        net = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5))
        net.eval()
        assert not net[1].training
        x = paddle.to_tensor(_rand(10, 4))
        np.testing.assert_allclose(net(x).numpy(), net(x).numpy())
        net.train()
        assert net[1].training

    def test_hooks(self):
        net = nn.Linear(2, 2)
        calls = []
        h = net.register_forward_post_hook(lambda layer, inp, out: calls.append(1))
        net(paddle.to_tensor(_rand(1, 2)))
        assert calls == [1]
        h.remove()
        net(paddle.to_tensor(_rand(1, 2)))
        assert calls == [1]

    def test_buffers(self):
        class B(nn.Layer):
            def __init__(self):
                super().__init__()
                self.register_buffer("running", paddle.zeros([3]))

            def forward(self, x):
                return x

        b = B()
        assert "running" in b.state_dict()

    def test_layerlist_paramlist(self):
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        assert len(ll) == 3 and len(list(ll.parameters())) == 6
        ll.append(nn.Linear(2, 2))
        assert len(ll) == 4


class TestLayers:
    def test_linear_grad(self):
        fc = nn.Linear(3, 2)
        x = paddle.to_tensor(_rand(4, 3), stop_gradient=False)
        y = fc(x).sum()
        y.backward()
        assert fc.weight.grad is not None and fc.weight.grad.shape == [3, 2]
        assert fc.bias.grad is not None
        np.testing.assert_allclose(fc.bias.grad.numpy(), [4.0, 4.0])

    def test_conv2d(self):
        conv = nn.Conv2D(3, 8, 3, stride=1, padding=1)
        x = paddle.to_tensor(_rand(2, 3, 8, 8))
        out = conv(x)
        assert out.shape == [2, 8, 8, 8]

    def test_conv2d_matches_torch_semantics(self):
        import torch
        import torch.nn.functional as TF

        x = _rand(2, 3, 6, 6)
        w = _rand(4, 3, 3, 3)
        b = _rand(4)
        got = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w), paddle.to_tensor(b), stride=2, padding=1)
        want = TF.conv2d(torch.tensor(x), torch.tensor(w), torch.tensor(b), stride=2, padding=1)
        np.testing.assert_allclose(got.numpy(), want.numpy(), atol=2e-5)

    def test_conv2d_grouped(self):
        import torch
        import torch.nn.functional as TF

        x, w = _rand(1, 4, 5, 5), _rand(6, 2, 3, 3)
        got = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w), groups=2)
        want = TF.conv2d(torch.tensor(x), torch.tensor(w), groups=2)
        np.testing.assert_allclose(got.numpy(), want.numpy(), atol=2e-5)

    def test_conv2d_transpose(self):
        import torch
        import torch.nn.functional as TF

        x, w = _rand(1, 3, 5, 5), _rand(3, 4, 3, 3)
        got = F.conv2d_transpose(paddle.to_tensor(x), paddle.to_tensor(w), stride=2, padding=1, output_padding=1)
        want = TF.conv_transpose2d(torch.tensor(x), torch.tensor(w), stride=2, padding=1, output_padding=1)
        np.testing.assert_allclose(got.numpy(), want.numpy(), atol=2e-5)

    def test_maxpool_avgpool(self):
        import torch
        import torch.nn.functional as TF

        x = _rand(2, 3, 8, 8)
        got = F.max_pool2d(paddle.to_tensor(x), 2, 2)
        want = TF.max_pool2d(torch.tensor(x), 2, 2)
        np.testing.assert_allclose(got.numpy(), want.numpy())
        got = F.avg_pool2d(paddle.to_tensor(x), 3, 2, 1)
        want = TF.avg_pool2d(torch.tensor(x), 3, 2, 1, count_include_pad=False)
        np.testing.assert_allclose(got.numpy(), want.numpy(), atol=1e-6)

    def test_adaptive_pool(self):
        x = _rand(2, 3, 8, 8)
        out = F.adaptive_avg_pool2d(paddle.to_tensor(x), 1)
        np.testing.assert_allclose(out.numpy()[:, :, 0, 0], x.mean(axis=(2, 3)), atol=1e-6)
        out = F.adaptive_avg_pool2d(paddle.to_tensor(x), 3)
        assert out.shape == [2, 3, 3, 3]

    def test_batchnorm(self):
        bn = nn.BatchNorm2D(4)
        x = paddle.to_tensor(_rand(8, 4, 5, 5) * 3 + 1)
        bn.train()
        out = bn(x)
        np.testing.assert_allclose(out.numpy().mean(axis=(0, 2, 3)), np.zeros(4), atol=1e-5)
        np.testing.assert_allclose(out.numpy().std(axis=(0, 2, 3)), np.ones(4), atol=1e-2)
        # running stats moved
        assert abs(bn._mean.numpy()).max() > 0
        bn.eval()
        out2 = bn(x)
        assert not np.allclose(out2.numpy(), out.numpy())

    def test_layernorm(self):
        import torch

        x = _rand(2, 3, 8)
        ln = nn.LayerNorm(8)
        got = ln(paddle.to_tensor(x))
        tln = torch.nn.LayerNorm(8)
        want = tln(torch.tensor(x)).detach()
        np.testing.assert_allclose(got.numpy(), want.numpy(), atol=1e-5)

    def test_groupnorm(self):
        import torch

        x = _rand(2, 6, 4, 4)
        got = F.group_norm(paddle.to_tensor(x), 3)
        want = torch.nn.functional.group_norm(torch.tensor(x), 3)
        np.testing.assert_allclose(got.numpy(), want.numpy(), atol=1e-5)

    def test_embedding(self):
        emb = nn.Embedding(10, 4)
        ids = paddle.to_tensor(np.array([[1, 2], [3, 4]]))
        out = emb(ids)
        assert out.shape == [2, 2, 4]
        np.testing.assert_allclose(out.numpy()[0, 0], emb.weight.numpy()[1])

    def test_embedding_padding_idx_grad(self):
        emb = nn.Embedding(5, 3, padding_idx=0)
        ids = paddle.to_tensor(np.array([0, 1, 2]))
        emb(ids).sum().backward()
        g = emb.weight.grad.numpy()
        np.testing.assert_allclose(g[0], np.zeros(3))
        np.testing.assert_allclose(g[1], np.ones(3))

    def test_dropout_scale(self):
        x = paddle.to_tensor(np.ones((1000,), np.float32))
        out = F.dropout(x, 0.5, training=True)
        kept = out.numpy()[out.numpy() > 0]
        np.testing.assert_allclose(kept, 2.0 * np.ones_like(kept))
        out_eval = F.dropout(x, 0.5, training=False)
        np.testing.assert_allclose(out_eval.numpy(), x.numpy())

    def test_activations_vs_torch(self):
        import torch
        import torch.nn.functional as TF

        x = _rand(4, 5)
        for ours, theirs in [
            (F.relu, TF.relu), (F.gelu, TF.gelu), (F.silu, TF.silu),
            (F.softplus, TF.softplus), (F.elu, TF.elu),
            (F.hardswish, TF.hardswish), (F.log_sigmoid, TF.logsigmoid),
        ]:
            np.testing.assert_allclose(
                ours(paddle.to_tensor(x)).numpy(), theirs(torch.tensor(x)).numpy(),
                atol=2e-5, err_msg=str(theirs),
            )

    def test_softmax_ce(self):
        import torch
        import torch.nn.functional as TF

        logits = _rand(6, 5)
        labels = np.random.randint(0, 5, (6,))
        got = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels))
        want = TF.cross_entropy(torch.tensor(logits), torch.tensor(labels))
        np.testing.assert_allclose(got.numpy(), want.numpy(), rtol=1e-5)

    def test_ce_ignore_index(self):
        import torch
        import torch.nn.functional as TF

        logits = _rand(6, 5)
        labels = np.array([0, 1, -100, 3, -100, 2])
        got = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels), ignore_index=-100)
        want = TF.cross_entropy(torch.tensor(logits), torch.tensor(labels), ignore_index=-100)
        np.testing.assert_allclose(got.numpy(), want.numpy(), rtol=1e-5)

    def test_bce_with_logits(self):
        import torch
        import torch.nn.functional as TF

        x, y = _rand(4, 3), np.random.randint(0, 2, (4, 3)).astype(np.float32)
        got = F.binary_cross_entropy_with_logits(paddle.to_tensor(x), paddle.to_tensor(y))
        want = TF.binary_cross_entropy_with_logits(torch.tensor(x), torch.tensor(y))
        np.testing.assert_allclose(got.numpy(), want.numpy(), rtol=1e-5)

    def test_interpolate(self):
        x = _rand(1, 2, 4, 4)
        out = F.interpolate(paddle.to_tensor(x), scale_factor=2, mode="nearest")
        assert out.shape == [1, 2, 8, 8]
        np.testing.assert_allclose(out.numpy()[0, 0, ::2, ::2], x[0, 0])

    def test_mha_shapes(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = paddle.to_tensor(_rand(2, 6, 16))
        out = mha(x, x, x)
        assert out.shape == [2, 6, 16]

    def test_transformer_encoder(self):
        layer = nn.TransformerEncoderLayer(d_model=16, nhead=4, dim_feedforward=32, dropout=0.0)
        enc = nn.TransformerEncoder(layer, 2)
        x = paddle.to_tensor(_rand(2, 5, 16))
        out = enc(x)
        assert out.shape == [2, 5, 16]

    def test_lstm(self):
        lstm = nn.LSTM(4, 8, num_layers=2, direction="bidirectional")
        x = paddle.to_tensor(_rand(3, 7, 4))
        y, (h, c) = lstm(x)
        assert y.shape == [3, 7, 16]
        assert h.shape == [4, 3, 8] and c.shape == [4, 3, 8]

    def test_lstm_vs_torch(self):
        import torch

        tl = torch.nn.LSTM(3, 5, num_layers=1, batch_first=True)
        ours = nn.LSTM(3, 5, num_layers=1)
        ours.weight_ih_l0.set_value(tl.weight_ih_l0.detach().numpy())
        ours.weight_hh_l0.set_value(tl.weight_hh_l0.detach().numpy())
        ours.bias_ih_l0.set_value(tl.bias_ih_l0.detach().numpy())
        ours.bias_hh_l0.set_value(tl.bias_hh_l0.detach().numpy())
        x = _rand(2, 6, 3)
        y_t, (h_t, c_t) = tl(torch.tensor(x))
        y_o, (h_o, c_o) = ours(paddle.to_tensor(x))
        np.testing.assert_allclose(y_o.numpy(), y_t.detach().numpy(), atol=1e-5)
        np.testing.assert_allclose(h_o.numpy(), h_t.detach().numpy(), atol=1e-5)

    def test_gru_vs_torch(self):
        import torch

        tl = torch.nn.GRU(3, 5, num_layers=1, batch_first=True)
        ours = nn.GRU(3, 5, num_layers=1)
        ours.weight_ih_l0.set_value(tl.weight_ih_l0.detach().numpy())
        ours.weight_hh_l0.set_value(tl.weight_hh_l0.detach().numpy())
        ours.bias_ih_l0.set_value(tl.bias_ih_l0.detach().numpy())
        ours.bias_hh_l0.set_value(tl.bias_hh_l0.detach().numpy())
        x = _rand(2, 6, 3)
        y_t, h_t = tl(torch.tensor(x))
        y_o, h_o = ours(paddle.to_tensor(x))
        np.testing.assert_allclose(y_o.numpy(), y_t.detach().numpy(), atol=1e-5)


class TestClip:
    def test_global_norm(self):
        g1 = paddle.to_tensor(np.full((3,), 3.0, np.float32))
        g2 = paddle.to_tensor(np.full((4,), 4.0, np.float32))
        p1, p2 = nn.Parameter(np.zeros(3, np.float32)), nn.Parameter(np.zeros(4, np.float32))
        clip = nn.ClipGradByGlobalNorm(1.0)
        out = clip([(p1, g1), (p2, g2)])
        total = np.sqrt(sum((g.numpy() ** 2).sum() for _, g in out))
        np.testing.assert_allclose(total, 1.0, rtol=1e-5)

    def test_by_value(self):
        clip = nn.ClipGradByValue(0.5)
        p = nn.Parameter(np.zeros(3, np.float32))
        g = paddle.to_tensor(np.array([-1.0, 0.2, 1.0], np.float32))
        (pp, gg), = clip([(p, g)])
        np.testing.assert_allclose(gg.numpy(), [-0.5, 0.2, 0.5])
