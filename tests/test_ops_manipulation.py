"""Manipulation / indexing / search / linalg op tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_grad, check_output


def _rand(*shape):
    return np.random.uniform(-1, 1, shape).astype(np.float32)


class TestShape:
    def test_reshape(self):
        check_output(lambda x: paddle.reshape(x, [2, 6]), lambda x: x.reshape(2, 6), [_rand(3, 4)])
        check_output(lambda x: paddle.reshape(x, [-1]), lambda x: x.reshape(-1), [_rand(3, 4)])
        check_grad(lambda x: paddle.reshape(x, [4, 3]), lambda x: x.reshape(4, 3), [_rand(3, 4)])

    def test_transpose(self):
        check_output(
            lambda x: paddle.transpose(x, [1, 0, 2]), lambda x: x.transpose(1, 0, 2), [_rand(2, 3, 4)]
        )
        check_grad(lambda x: paddle.transpose(x, [1, 0]), lambda x: x.T, [_rand(3, 4)])

    def test_squeeze_unsqueeze(self):
        check_output(lambda x: paddle.squeeze(x, [1]), lambda x: x.squeeze(1), [_rand(3, 1, 4)])
        check_output(lambda x: paddle.unsqueeze(x, 0), lambda x: x[None], [_rand(3, 4)])
        check_output(lambda x: paddle.unsqueeze(x, [0, 2]), lambda x: np.expand_dims(x[None], 2), [_rand(3,)])

    def test_flatten(self):
        check_output(
            lambda x: paddle.flatten(x, 1, 2), lambda x: x.reshape(2, 12, 5), [_rand(2, 3, 4, 5)]
        )

    def test_expand_tile(self):
        check_output(lambda x: paddle.expand(x, [3, 4]), lambda x: np.broadcast_to(x, (3, 4)), [_rand(1, 4)])
        check_output(lambda x: paddle.tile(x, [2, 3]), lambda x: np.tile(x, (2, 3)), [_rand(2, 2)])

    def test_flip_roll(self):
        check_output(lambda x: paddle.flip(x, [0]), lambda x: np.flip(x, 0), [_rand(3, 4)])
        check_output(lambda x: paddle.roll(x, 2, 0), lambda x: np.roll(x, 2, 0), [_rand(5, 2)])


class TestJoinSplit:
    def test_concat(self):
        a, b = _rand(2, 3), _rand(4, 3)
        got = paddle.concat([paddle.to_tensor(a), paddle.to_tensor(b)], axis=0)
        np.testing.assert_allclose(got.numpy(), np.concatenate([a, b], 0))

    def test_concat_grad(self):
        a, b = paddle.to_tensor(_rand(2, 3), stop_gradient=False), paddle.to_tensor(
            _rand(2, 3), stop_gradient=False
        )
        out = paddle.concat([a, b], axis=1).sum()
        out.backward()
        np.testing.assert_allclose(a.grad.numpy(), np.ones((2, 3)))
        np.testing.assert_allclose(b.grad.numpy(), np.ones((2, 3)))

    def test_stack(self):
        a, b = _rand(2, 3), _rand(2, 3)
        got = paddle.stack([paddle.to_tensor(a), paddle.to_tensor(b)], axis=1)
        np.testing.assert_allclose(got.numpy(), np.stack([a, b], 1))

    def test_split(self):
        x = _rand(6, 4)
        parts = paddle.split(paddle.to_tensor(x), 3, axis=0)
        assert len(parts) == 3
        np.testing.assert_allclose(parts[1].numpy(), x[2:4])
        parts = paddle.split(paddle.to_tensor(x), [1, 2, -1], axis=0)
        np.testing.assert_allclose(parts[2].numpy(), x[3:])

    def test_unbind(self):
        x = _rand(3, 4)
        parts = paddle.unbind(paddle.to_tensor(x), 0)
        assert len(parts) == 3 and parts[0].shape == [4]


class TestIndexing:
    def test_gather(self):
        x, idx = _rand(5, 3), np.array([0, 2, 4])
        got = paddle.gather(paddle.to_tensor(x), paddle.to_tensor(idx))
        np.testing.assert_allclose(got.numpy(), x[idx])

    def test_gather_nd(self):
        x = _rand(3, 4, 5)
        idx = np.array([[0, 1], [2, 3]])
        got = paddle.gather_nd(paddle.to_tensor(x), paddle.to_tensor(idx))
        np.testing.assert_allclose(got.numpy(), x[[0, 2], [1, 3]])

    def test_scatter(self):
        x = np.zeros((4, 2), np.float32)
        idx = np.array([1, 3])
        upd = _rand(2, 2)
        got = paddle.scatter(paddle.to_tensor(x), paddle.to_tensor(idx), paddle.to_tensor(upd))
        want = x.copy()
        want[idx] = upd
        np.testing.assert_allclose(got.numpy(), want)

    def test_index_select(self):
        x = _rand(4, 5)
        got = paddle.index_select(paddle.to_tensor(x), paddle.to_tensor(np.array([1, 1, 3])), axis=0)
        np.testing.assert_allclose(got.numpy(), x[[1, 1, 3]])

    def test_getitem(self):
        x = _rand(4, 5, 6)
        t = paddle.to_tensor(x)
        np.testing.assert_allclose(t[1].numpy(), x[1])
        np.testing.assert_allclose(t[1:3, ::2].numpy(), x[1:3, ::2])
        np.testing.assert_allclose(t[..., -1].numpy(), x[..., -1])

    def test_getitem_grad(self):
        t = paddle.to_tensor(_rand(4, 5), stop_gradient=False)
        t[1:3].sum().backward()
        want = np.zeros((4, 5))
        want[1:3] = 1
        np.testing.assert_allclose(t.grad.numpy(), want)

    def test_setitem(self):
        t = paddle.to_tensor(np.zeros((3, 3), np.float32))
        t[1] = 5.0
        assert t.numpy()[1].sum() == 15

    def test_where(self):
        c = np.array([[True, False], [False, True]])
        a, b = _rand(2, 2), _rand(2, 2)
        got = paddle.where(paddle.to_tensor(c), paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_allclose(got.numpy(), np.where(c, a, b))

    def test_masked_select_nonzero(self):
        x = np.array([[1.0, -2.0], [3.0, -4.0]], np.float32)
        got = paddle.masked_select(paddle.to_tensor(x), paddle.to_tensor(x > 0))
        np.testing.assert_allclose(got.numpy(), np.array([1.0, 3.0]))
        nz = paddle.nonzero(paddle.to_tensor(x > 0))
        np.testing.assert_allclose(nz.numpy(), np.array([[0, 0], [1, 0]]))


class TestSearchSort:
    def test_argmax_argmin(self):
        x = _rand(3, 4)
        assert paddle.argmax(paddle.to_tensor(x)).item() == np.argmax(x)
        np.testing.assert_allclose(
            paddle.argmax(paddle.to_tensor(x), axis=1).numpy(), np.argmax(x, 1)
        )

    def test_sort_argsort(self):
        x = _rand(3, 5)
        np.testing.assert_allclose(paddle.sort(paddle.to_tensor(x), axis=1).numpy(), np.sort(x, 1))
        np.testing.assert_allclose(
            paddle.argsort(paddle.to_tensor(x), axis=1).numpy(), np.argsort(x, 1, kind="stable")
        )

    def test_topk(self):
        x = _rand(3, 6)
        vals, idx = paddle.topk(paddle.to_tensor(x), 2, axis=1)
        want = np.sort(x, 1)[:, ::-1][:, :2]
        np.testing.assert_allclose(vals.numpy(), want)
        np.testing.assert_allclose(np.take_along_axis(x, idx.numpy(), 1), want)

    def test_topk_grad(self):
        t = paddle.to_tensor(np.array([1.0, 5.0, 3.0], np.float32), stop_gradient=False)
        vals, _ = paddle.topk(t, 2)
        vals.sum().backward()
        np.testing.assert_allclose(t.grad.numpy(), np.array([0.0, 1.0, 1.0]))


class TestLinalg:
    def test_matmul(self):
        check_output(paddle.matmul, np.matmul, [_rand(3, 4), _rand(4, 5)])
        check_grad(paddle.matmul, np.matmul, [_rand(3, 4), _rand(4, 5)], wrt=(0, 1))

    def test_matmul_transpose(self):
        a, b = _rand(4, 3), _rand(4, 5)
        got = paddle.matmul(paddle.to_tensor(a), paddle.to_tensor(b), transpose_x=True)
        np.testing.assert_allclose(got.numpy(), a.T @ b, rtol=1e-5)

    def test_batched_matmul(self):
        check_output(paddle.matmul, np.matmul, [_rand(2, 3, 4), _rand(2, 4, 5)])
        check_output(paddle.bmm, np.matmul, [_rand(2, 3, 4), _rand(2, 4, 5)])

    def test_norm_inverse_solve(self):
        x = _rand(4, 4) + 4 * np.eye(4, dtype=np.float32)
        np.testing.assert_allclose(
            paddle.inverse(paddle.to_tensor(x)).numpy(), np.linalg.inv(x), atol=1e-4
        )
        b = _rand(4, 2)
        np.testing.assert_allclose(
            paddle.linalg.solve(paddle.to_tensor(x), paddle.to_tensor(b)).numpy(),
            np.linalg.solve(x, b),
            atol=1e-4,
        )
        np.testing.assert_allclose(
            paddle.norm(paddle.to_tensor(x)).numpy(), np.linalg.norm(x), rtol=1e-5
        )

    def test_einsum(self):
        a, b = _rand(3, 4), _rand(4, 5)
        got = paddle.einsum("ij,jk->ik", paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_allclose(got.numpy(), a @ b, rtol=1e-5)


class TestCastDtype:
    def test_cast(self):
        x = _rand(3, 3)
        t = paddle.to_tensor(x).astype("float64")
        assert t.dtype == "float64"
        i = paddle.to_tensor(x).cast("int32")
        assert i.dtype == paddle.int32

    def test_dtype_objects(self):
        t = paddle.ones([2], dtype=paddle.float32)
        assert t.dtype == paddle.float32 and t.dtype == "float32"


class TestCreation:
    def test_basic(self):
        assert paddle.zeros([2, 3]).shape == [2, 3]
        assert paddle.ones([2], dtype="int64").dtype == "int64"
        np.testing.assert_allclose(paddle.full([2, 2], 3.5).numpy(), np.full((2, 2), 3.5))
        np.testing.assert_allclose(paddle.arange(1, 7, 2).numpy(), np.arange(1, 7, 2))
        np.testing.assert_allclose(paddle.eye(3).numpy(), np.eye(3))
        np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5))

    def test_tril_triu(self):
        x = _rand(4, 4)
        np.testing.assert_allclose(paddle.tril(paddle.to_tensor(x)).numpy(), np.tril(x))
        np.testing.assert_allclose(paddle.triu(paddle.to_tensor(x), 1).numpy(), np.triu(x, 1))

    def test_randoms(self):
        paddle.seed(7)
        a = paddle.rand([100])
        assert 0 <= float(a.min()) and float(a.max()) <= 1
        paddle.seed(7)
        b = paddle.rand([100])
        np.testing.assert_allclose(a.numpy(), b.numpy())
        p = paddle.randperm(10).numpy()
        assert sorted(p.tolist()) == list(range(10))
