"""ctc_loss / gather_tree / edit_distance parity tests (reference:
unittests/test_warpctc_op.py, test_gather_tree_op.py,
test_edit_distance_op.py)."""
import itertools

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


rng = np.random.default_rng(11)


def _np(t):
    return np.asarray(t._data)


def brute_force_ctc(probs, labels, blank):
    """-log P(labels | probs) by enumerating all alignments. probs: (T, C)."""
    T, C = probs.shape
    total = 0.0
    for path in itertools.product(range(C), repeat=T):
        # collapse path: remove repeats then blanks
        collapsed = [k for k, _ in itertools.groupby(path) if k != blank]
        if collapsed == list(labels):
            p = 1.0
            for t, k in enumerate(path):
                p *= probs[t, k]
            total += p
    return -np.log(total)


class TestCTCLoss:
    def test_vs_brute_force(self):
        T, C = 4, 3
        logits = rng.standard_normal((T, 1, C)).astype("float32")
        probs = np.exp(logits[:, 0]) / np.exp(logits[:, 0]).sum(-1, keepdims=True)
        labels = [1, 2]
        want = brute_force_ctc(probs, labels, blank=0)
        got = F.ctc_loss(
            paddle.to_tensor(logits),
            paddle.to_tensor(np.array([labels], np.int64)),
            paddle.to_tensor(np.array([T], np.int64)),
            paddle.to_tensor(np.array([2], np.int64)),
            reduction="none")
        np.testing.assert_allclose(_np(got)[0], want, rtol=1e-4, atol=1e-4)

    def test_repeated_label(self):
        T, C = 5, 3
        logits = rng.standard_normal((T, 1, C)).astype("float32")
        probs = np.exp(logits[:, 0]) / np.exp(logits[:, 0]).sum(-1, keepdims=True)
        labels = [2, 2]  # repeat forces a blank between
        want = brute_force_ctc(probs, labels, blank=0)
        got = F.ctc_loss(paddle.to_tensor(logits),
                         paddle.to_tensor(np.array([labels], np.int64)),
                         paddle.to_tensor(np.array([T], np.int64)),
                         paddle.to_tensor(np.array([2], np.int64)),
                         reduction="none")
        np.testing.assert_allclose(_np(got)[0], want, rtol=1e-4, atol=1e-4)

    def test_batch_and_lengths(self):
        T, B, C = 6, 3, 4
        logits = rng.standard_normal((T, B, C)).astype("float32")
        labels = np.array([[1, 2, 0], [3, 0, 0], [1, 1, 2]], np.int64)
        in_len = np.array([6, 4, 6], np.int64)
        lab_len = np.array([2, 1, 3], np.int64)
        got = F.ctc_loss(paddle.to_tensor(logits), paddle.to_tensor(labels),
                         paddle.to_tensor(in_len), paddle.to_tensor(lab_len),
                         reduction="none")
        probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
        for b in range(B):
            want = brute_force_ctc(probs[:in_len[b], b],
                                   list(labels[b, :lab_len[b]]), 0)
            np.testing.assert_allclose(_np(got)[b], want, rtol=1e-4, atol=1e-4)

    def test_nonzero_blank_and_reductions(self):
        T, C = 4, 3
        logits = rng.standard_normal((T, 1, C)).astype("float32")
        probs = np.exp(logits[:, 0]) / np.exp(logits[:, 0]).sum(-1, keepdims=True)
        want = brute_force_ctc(probs, [0, 1], blank=2)
        got = F.ctc_loss(paddle.to_tensor(logits),
                         paddle.to_tensor(np.array([[0, 1]], np.int64)),
                         paddle.to_tensor(np.array([T], np.int64)),
                         paddle.to_tensor(np.array([2], np.int64)),
                         blank=2, reduction="none")
        np.testing.assert_allclose(_np(got)[0], want, rtol=1e-4, atol=1e-4)
        got_mean = F.ctc_loss(paddle.to_tensor(logits),
                              paddle.to_tensor(np.array([[0, 1]], np.int64)),
                              paddle.to_tensor(np.array([T], np.int64)),
                              paddle.to_tensor(np.array([2], np.int64)),
                              blank=2, reduction="mean")
        np.testing.assert_allclose(_np(got_mean), want / 2, rtol=1e-4, atol=1e-4)

    def test_grad(self):
        logits = paddle.to_tensor(rng.standard_normal((5, 2, 4)).astype("float32"))
        logits.stop_gradient = False
        loss = F.ctc_loss(logits,
                          paddle.to_tensor(np.array([[1, 2], [3, 1]], np.int64)),
                          paddle.to_tensor(np.array([5, 5], np.int64)),
                          paddle.to_tensor(np.array([2, 2], np.int64)))
        loss.backward()
        g = _np(logits.grad)
        assert np.isfinite(g).all() and np.abs(g).sum() > 0

    def test_layer(self):
        from paddle_tpu.nn import CTCLoss

        loss_fn = CTCLoss(blank=0, reduction="sum")
        out = loss_fn(paddle.to_tensor(rng.standard_normal((4, 1, 3)).astype("float32")),
                      paddle.to_tensor(np.array([[1]], np.int64)),
                      paddle.to_tensor(np.array([4], np.int64)),
                      paddle.to_tensor(np.array([1], np.int64)))
        assert _np(out).shape == ()


class TestGatherTree:
    def test_vs_golden(self):
        # reference test_gather_tree_op.py style: manual backtrack
        T, B, K = 3, 2, 2
        ids = rng.integers(0, 9, (T, B, K)).astype("int64")
        parents = rng.integers(0, K, (T, B, K)).astype("int64")
        got = F.gather_tree(paddle.to_tensor(ids), paddle.to_tensor(parents))
        want = np.zeros_like(ids)
        for b in range(B):
            for k in range(K):
                par = k
                for t in range(T - 1, -1, -1):
                    want[t, b, k] = ids[t, b, par]
                    par = parents[t, b, par]
        np.testing.assert_array_equal(_np(got), want)

    def test_chain(self):
        # simple known case: parents chain beams straight through
        ids = np.array([[[1, 2]], [[3, 4]], [[5, 6]]], np.int64)
        parents = np.array([[[0, 0]], [[1, 0]], [[0, 1]]], np.int64)
        got = _np(F.gather_tree(paddle.to_tensor(ids), paddle.to_tensor(parents)))
        # beam 0 at t=2: id 5, parent 0 -> t=1 id 3? parent at t=1 beam0 = 1
        want = np.zeros_like(ids)
        for k in range(2):
            par = k
            for t in range(2, -1, -1):
                want[t, 0, k] = ids[t, 0, par]
                par = parents[t, 0, par]
        np.testing.assert_array_equal(got, want)


def np_levenshtein(a, b):
    d = np.zeros((len(a) + 1, len(b) + 1))
    d[:, 0] = np.arange(len(a) + 1)
    d[0, :] = np.arange(len(b) + 1)
    for i in range(1, len(a) + 1):
        for j in range(1, len(b) + 1):
            d[i, j] = min(d[i - 1, j] + 1, d[i, j - 1] + 1,
                          d[i - 1, j - 1] + (a[i - 1] != b[j - 1]))
    return d[-1, -1]


class TestEditDistance:
    def test_vs_golden(self):
        hyp = np.array([[1, 2, 3, 4], [5, 6, 7, 0]], np.int64)
        ref = np.array([[1, 3, 4, 0, 0], [5, 6, 8, 9, 0]], np.int64)
        hyp_len = np.array([4, 3], np.int64)
        ref_len = np.array([3, 4], np.int64)
        dist, num = F.edit_distance(
            paddle.to_tensor(hyp), paddle.to_tensor(ref), normalized=False,
            input_length=paddle.to_tensor(hyp_len),
            label_length=paddle.to_tensor(ref_len))
        for b in range(2):
            want = np_levenshtein(hyp[b, :hyp_len[b]], ref[b, :ref_len[b]])
            np.testing.assert_allclose(_np(dist)[b, 0], want)
        assert _np(num)[0] == 2

    def test_normalized(self):
        hyp = np.array([[1, 2]], np.int64)
        ref = np.array([[1, 3, 4]], np.int64)
        dist, _ = F.edit_distance(paddle.to_tensor(hyp), paddle.to_tensor(ref),
                                  normalized=True)
        want = np_levenshtein([1, 2], [1, 3, 4]) / 3
        np.testing.assert_allclose(_np(dist)[0, 0], want, rtol=1e-6)

    def test_ignored_tokens(self):
        hyp = np.array([[1, 0, 2, 0]], np.int64)
        ref = np.array([[1, 2, 0, 0]], np.int64)
        ln = paddle.to_tensor(np.array([4], np.int64))
        dist, _ = F.edit_distance(paddle.to_tensor(hyp), paddle.to_tensor(ref),
                                  normalized=False, ignored_tokens=[0],
                                  input_length=ln, label_length=ln)
        # after dropping 0s both are [1, 2]
        np.testing.assert_allclose(_np(dist)[0, 0], 0.0)

    def test_full_padded_no_lengths(self):
        hyp = np.array([[1, 2, 3]], np.int64)
        ref = np.array([[3, 2, 1]], np.int64)
        dist, _ = F.edit_distance(paddle.to_tensor(hyp), paddle.to_tensor(ref),
                                  normalized=False)
        np.testing.assert_allclose(_np(dist)[0, 0],
                                   np_levenshtein([1, 2, 3], [3, 2, 1]))


class TestRealDatasetParsing:
    """Real archive parsing with local fixtures (VERDICT: synthetic-only
    text datasets are API padding; reference parses real archives)."""

    def test_movielens_ml1m_zip(self, tmp_path):
        import zipfile

        from paddle_tpu.text.datasets import Movielens

        zpath = tmp_path / "ml-1m.zip"
        with zipfile.ZipFile(zpath, "w") as z:
            z.writestr("ml-1m/users.dat",
                       "1::F::1::10::48067\n2::M::56::16::70072\n")
            z.writestr("ml-1m/movies.dat",
                       "1::Toy Story (1995)::Animation|Children's|Comedy\n")
            z.writestr("ml-1m/ratings.dat",
                       "1::1193::5::978300760\n2::661::3::978302109\n")
        ds = Movielens(data_file=str(zpath))
        assert len(ds) == 2
        uid, gender, age, job, mid, rating = ds[0]
        assert (int(uid), int(gender), int(age), int(job)) == (1, 1, 0, 10)
        assert int(mid) == 1193 and float(rating) == 5.0

    def test_wmt_parallel_tarball(self, tmp_path):
        import tarfile

        from paddle_tpu.text.datasets import WMT14

        src = "le chat est noir\nil pleut\n"
        trg = "the cat is black\nit rains\n"
        tpath = tmp_path / "wmt.tar.gz"
        with tarfile.open(tpath, "w:gz") as tf:
            for name, data in (("train.src", src), ("train.trg", trg)):
                import io

                blob = data.encode()
                info = tarfile.TarInfo(name)
                info.size = len(blob)
                tf.addfile(info, io.BytesIO(blob))
        ds = WMT14(data_file=str(tpath), mode="train")
        assert len(ds) == 2
        s, t_in, t_out = ds[0]
        assert s.dtype == np.int64 and len(s) == 4
        assert t_in[0] == 0 and t_out[-1] == 1  # <s> shifted / </s> ended
        # round-trippable vocab
        inv = {i: w for w, i in ds.src_idx.items()}
        assert [inv[i] for i in s] == ["le", "chat", "est", "noir"]

    def test_conll05_column_file(self, tmp_path):
        from paddle_tpu.text.datasets import Conll05st

        p = tmp_path / "srl.txt"
        p.write_text(
            "The - B-A0\ncat - I-A0\nsat sat B-V\n\n"
            "Dogs - B-A0\nbark bark B-V\nloudly - B-AM\n")
        ds = Conll05st(data_file=str(p))
        assert len(ds) == 2
        words, pred, labels = ds[0]
        assert len(words) == 3 and int(pred) == 2
        assert labels.dtype == np.int64

    def test_imdb_real_tar(self, tmp_path):
        import io
        import tarfile

        from paddle_tpu.text.datasets import Imdb

        tpath = tmp_path / "aclImdb.tar.gz"
        with tarfile.open(tpath, "w:gz") as tf:
            for name, text in (
                ("aclImdb/train/pos/0_9.txt", "a great great movie"),
                ("aclImdb/train/neg/0_2.txt", "a terrible movie"),
            ):
                blob = text.encode()
                info = tarfile.TarInfo(name)
                info.size = len(blob)
                tf.addfile(info, io.BytesIO(blob))
        ds = Imdb(data_file=str(tpath), mode="train", cutoff=1)
        assert len(ds) == 2
        labels = sorted(int(y) for (_, y) in ds.samples)
        assert labels == [0, 1]


class TestBertTokenizer:
    VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "the", "cat", "sat",
             "un", "##aff", "##able", "hello", ",", "!"]

    def test_basic_tokenizer(self):
        from paddle_tpu.text import BasicTokenizer

        bt = BasicTokenizer()
        assert bt.tokenize("Hello, WORLD!") == ["hello", ",", "world", "!"]
        assert bt.tokenize("café") == ["cafe"]  # accent strip
        assert bt.tokenize("中文ab") == ["中", "文", "ab"]

    def test_wordpiece_longest_match(self):
        from paddle_tpu.text import BertTokenizer

        tok = BertTokenizer(self.VOCAB)
        assert tok.tokenize("unaffable") == ["un", "##aff", "##able"]
        assert tok.tokenize("xyzzy") == ["[UNK]"]

    def test_batch_encode_contract(self):
        from paddle_tpu.text import BertTokenizer

        tok = BertTokenizer(self.VOCAB)
        out = tok(["the cat sat", "hello"], max_seq_len=6,
                  pad_to_max_seq_len=True)
        ids = out["input_ids"]
        assert ids.shape == (2, 6) and ids.dtype == np.int64
        assert ids[0][0] == 2 and 3 in ids[0]  # [CLS] ... [SEP]
        assert ids[1][-1] == 0  # padded
        pair = tok("the cat", text_pair="sat", max_seq_len=8)
        assert pair["token_type_ids"].count(1) == 2  # sat + [SEP]


def test_sequence_expand_nested():
    """2-level-LoD expansion in the dense+lengths redesign: whole sequences
    repeat (reference sequence_expand_op.cc ref_level semantics)."""
    from paddle_tpu.ops.sequence import sequence_expand

    # x: two sequences [a, b] (len 2) and [c] (len 1)
    x = paddle.to_tensor(np.asarray([[1.0], [2.0], [3.0]], "float32"))
    out = sequence_expand(x, y_lengths=[2, 3], x_lengths=[2, 1])
    np.testing.assert_allclose(
        np.asarray(out._data).ravel(),
        [1, 2, 1, 2, 3, 3, 3])  # seq0 x2, seq1 x3
    # differentiable: grads accumulate per source row
    x2 = paddle.to_tensor(np.asarray([[1.0], [2.0], [3.0]], "float32"),
                          stop_gradient=False)
    out = sequence_expand(x2, y_lengths=[2, 3], x_lengths=[2, 1])
    out.sum().backward()
    np.testing.assert_allclose(np.asarray(x2.grad._data).ravel(), [2, 2, 3])
