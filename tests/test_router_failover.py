"""Serving router: health-checked failover over N engine replicas (ISSUE 6).

Fast tier: least-loaded routing off /metrics, circuit-breaker
eject/half-open rejoin, 429 spillover + Retry-After backpressure hints,
drain-aware zero-drop takedown, in-process replica-kill failover
(queued request re-homed, in-flight stream resurrected as a
continuation join — ISSUE 17), configurable graceful-drain deadline.

Slow tier (CPU-multiprocess): SIGKILL one of two replica PROCESSES
mid-stream — queued requests complete on the survivor, recovery time
(kill → first token on the survivor) is measured.
"""
import os
import socket
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
from paddle_tpu.serving import (
    ContinuousBatchingEngine,
    NoReplicaAvailable,
    QueueFullError,
    Request,
    ServingClient,
    ServingRouter,
    ServingServer,
)

VOCAB = 32


def _tiny_model():
    paddle.seed(0)
    cfg = gpt_config("gpt2-small", vocab_size=VOCAB, hidden_size=16,
                     num_layers=1, num_attention_heads=2,
                     max_position_embeddings=64, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    m = GPTForPretraining(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _tiny_model()


def _server(model, n_slots=1, max_queue=16, port=0, **kw):
    eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=n_slots,
                                   prefill_buckets=[8], max_queue=max_queue)
    return ServingServer(eng, port=port, **kw).start()


def _frozen_server(model, max_queue=1):
    """HTTP plane up, engine loop NOT running: submissions pile up in the
    admission queue and stay there — deterministic backpressure."""
    eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=1,
                                   prefill_buckets=[8], max_queue=max_queue)
    srv = ServingServer(eng)
    srv._http_thread = threading.Thread(target=srv._httpd.serve_forever,
                                        daemon=True)
    srv._http_thread.start()
    return srv


def _prompt(rng=None, n=4):
    rng = rng or np.random.default_rng(0)
    return rng.integers(0, VOCAB, (n,)).tolist()


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_metrics(addr, pred, timeout=60.0):
    """Poll a replica's /metrics until ``pred(snapshot)`` holds (engine
    gauges update per tick; the first tick includes a compile)."""
    c = ServingClient(addr)
    deadline = time.perf_counter() + timeout
    while True:
        snap = c.metrics()
        if pred(snap):
            return snap
        assert time.perf_counter() < deadline, f"metrics never settled: {snap}"
        time.sleep(0.02)


# =====================================================================
# routing + breaker
# =====================================================================
class TestRouting:
    def test_least_loaded_routing(self, model):
        # A's engine loop is frozen so its preloaded queue CANNOT drain —
        # the load difference the router must see is pinned, not raced
        a = _frozen_server(model, max_queue=8)
        b = _server(model, n_slots=2)
        try:
            with ServingRouter([a.addr, b.addr], health_interval_s=5.0,
                               request_timeout=5.0) as router:
                # pre-load replica A directly (bypassing the router)
                direct = ServingClient(a.addr)
                for _ in range(3):
                    direct.submit(_prompt(), max_new_tokens=24)
                assert direct.metrics()["queue_depth"] == 3  # live gauge
                router.check_health()
                rr = router.submit(_prompt(), max_new_tokens=2)
                assert rr.replica_addr == b.addr  # the idle one
                router.wait(rr, timeout=60)
                assert rr.state == Request.DONE
        finally:
            a.kill()
            b.stop()

    def test_breaker_ejects_and_halfopen_rejoins(self, model):
        port = _free_port()
        router = ServingRouter([f"127.0.0.1:{port}"], failure_threshold=2,
                               cooldown_s=0.2, request_timeout=1.0)
        rep = router.replicas[f"127.0.0.1:{port}"]
        router.check_health()
        router.check_health()
        assert rep.state == "open"  # consecutive failures ejected it
        with pytest.raises(NoReplicaAvailable):
            router.submit(_prompt(), max_new_tokens=1)
        # replica comes up on that port → cooldown elapses → half-open
        # probe succeeds → rejoined
        srv = _server(model, port=port)
        try:
            time.sleep(0.25)
            router.check_health()
            assert rep.state == "closed"
            rr = router.submit(_prompt(), max_new_tokens=2)
            router.wait(rr, timeout=60)
            assert rr.state == Request.DONE
        finally:
            srv.stop()

    def test_429_spillover_and_retry_after(self, model):
        """A full replica spills to the next one; when EVERY replica is
        full the 429 surfaces WITH the Retry-After hint. Frozen engine
        loops keep the queues deterministically full."""
        a = _frozen_server(model, max_queue=1)
        b = _frozen_server(model, max_queue=1)
        try:
            ServingClient(a.addr).submit(_prompt(), max_new_tokens=8)
            with ServingRouter([a.addr, b.addr], health_interval_s=5.0,
                               request_timeout=5.0) as router:
                rr = router.submit(_prompt(), max_new_tokens=2)
                assert rr.replica_addr == b.addr  # spilled off full A
                with pytest.raises(QueueFullError) as ei:  # now B full too
                    router.submit(_prompt(), max_new_tokens=2)
                assert ei.value.retry_after is not None
                assert ei.value.retry_after >= 1.0
        finally:
            a.kill()
            b.kill()

    def test_retry_after_header_from_direct_client(self, model):
        srv = _frozen_server(model, max_queue=1)
        try:
            c = ServingClient(srv.addr)
            c.submit(_prompt(), max_new_tokens=8)
            with pytest.raises(QueueFullError) as ei:
                c.submit(_prompt(), max_new_tokens=2)
            assert ei.value.retry_after is not None
        finally:
            srv.kill()


# =====================================================================
# drain
# =====================================================================
class TestDrain:
    def test_drain_zero_dropped_and_no_new_routing(self, model):
        a, b = _server(model, n_slots=1), _server(model, n_slots=1)
        try:
            with ServingRouter([a.addr, b.addr], health_interval_s=5.0,
                               request_timeout=10.0) as router:
                router.check_health()
                rrs = [router.submit(_prompt(), max_new_tokens=12)
                       for _ in range(4)]
                on_a = [r for r in rrs if r.replica_addr == a.addr]
                assert on_a  # some work is queued/running on A
                router.drain(a.addr, timeout=60)
                # zero dropped: everything routed to A completed there
                for rr in on_a:
                    out = router.wait(rr, timeout=60)
                    assert out["status"] == Request.DONE
                    assert len(out["tokens"]) == 12
                # A is out of rotation for NEW work, and reports draining
                assert ServingClient(a.addr).metrics()["draining"] is True
                rr2 = router.submit(_prompt(), max_new_tokens=2)
                assert rr2.replica_addr == b.addr
                router.wait(rr2, timeout=60)
                for rr in rrs:
                    router.wait(rr, timeout=60)
                    assert rr.state == Request.DONE
        finally:
            a.kill()
            b.stop()

    def test_drain_timeout_s_is_configurable(self, model):
        srv = _server(model, n_slots=1, drain_timeout_s=0.02)
        assert srv.drain_timeout_s == 0.02
        # the first prefill compiles (≫ 20ms), so the engine cannot
        # possibly drain inside the configured deadline
        ServingClient(srv.addr).submit(_prompt(), max_new_tokens=26)
        with pytest.raises(TimeoutError, match="drain_timeout_s"):
            srv.drain()  # the configured (tiny) default applies
        srv.stop(timeout=120)  # explicit override still wins

    def test_drain_waits_for_mid_prefill_request(self, model, monkeypatch):
        """A request POPPED from the admission queue but still inside
        prefill (e.g. the first-bucket compile) is in neither queue_depth
        nor an active slot: drain must count it (in_admission) instead of
        declaring the replica empty and letting the operator kill it."""
        orig = ContinuousBatchingEngine._admit_one

        def slow_admit(self, req, slot):
            time.sleep(0.6)  # hold the pop→activate window wide open
            return orig(self, req, slot)

        monkeypatch.setattr(ContinuousBatchingEngine, "_admit_one",
                            slow_admit)
        srv = _server(model, n_slots=1)
        try:
            with ServingRouter([srv.addr], health_interval_s=5.0,
                               request_timeout=10.0) as router:
                router.check_health()
                rr = router.submit(_prompt(), max_new_tokens=4)
                time.sleep(0.2)  # tick pops it; now mid-prefill
                m = ServingClient(srv.addr).metrics()
                assert (int(m["queue_depth"]) + int(m["in_admission"])
                        + int(m["slot_occupancy"]["active"])) >= 1
                router.drain(srv.addr, timeout=120)
                # drain returned ⇒ the request must already be DONE
                out = router.poll(rr)
                assert out["status"] == Request.DONE
                assert len(out["tokens"]) == 4
        finally:
            srv.kill()


# =====================================================================
# in-process replica kill (the fast half of the chaos coverage)
# =====================================================================
class TestReplicaKill:
    def _pair_with_two_on_victim(self, router, addrs):
        """Submit until one replica holds 2 requests (1 running + 1
        queued); returns (victim_addr, running_rr, queued_rr, others)."""
        placed = {a: [] for a in addrs}
        rrs = []
        for _ in range(3):
            rr = router.submit(_prompt(), max_new_tokens=24)
            rrs.append(rr)
            placed[rr.replica_addr].append(rr)
            victim = next((a for a, v in placed.items() if len(v) == 2), None)
            if victim:
                running, queued = placed[victim]
                others = [r for r in rrs if r not in (running, queued)]
                return victim, running, queued, others
        raise AssertionError(f"no replica got 2 requests: {placed}")

    def test_kill_requeues_queued_and_resurrects_inflight(self, model):
        servers = {s.addr: s for s in (_server(model, n_slots=1),
                                       _server(model, n_slots=1))}
        addrs = list(servers)
        try:
            with ServingRouter(addrs, health_interval_s=0.1,
                               cooldown_s=30.0, request_timeout=5.0) as router:
                router.check_health()
                victim, running, queued, others = \
                    self._pair_with_two_on_victim(router, addrs)
                # observe tokens from the RUNNING one (poll) so the router
                # knows its generation started
                deadline = time.perf_counter() + 30
                while not running.tokens:
                    router.poll(running)
                    assert time.perf_counter() < deadline
                    time.sleep(0.01)
                prefix = list(running.tokens)
                n_before = len(prefix)
                servers[victim].kill()
                # in-flight: RESURRECTED as a continuation join on the
                # survivor — completes with the full transcript, never a
                # truncation or a from-scratch regeneration
                out = router.wait(running, timeout=60)
                assert out["status"] == Request.DONE, running.error
                assert len(out["tokens"]) == 24
                assert out["tokens"][:n_before] == prefix
                assert running.resurrections == 1
                assert running.replica_addr != victim
                # queued (never prefilled): completes on the survivor
                out = router.wait(queued, timeout=60)
                assert out["status"] == Request.DONE, queued.error
                assert len(out["tokens"]) == 24
                assert queued.resubmits == 1
                assert queued.replica_addr != victim
                for rr in others:
                    router.wait(rr, timeout=60)
                    assert rr.state == Request.DONE, rr.error
                snap = router.snapshot()
                assert snap["replicas"][victim]["state"] == "open"
                assert snap["resubmits"] >= 1
                assert snap["inflight_failures"] == 0
                assert snap["resurrections"] == 1
                assert snap["resurrected_tokens"] >= n_before
        finally:
            for s in servers.values():
                try:
                    s.kill()
                except Exception:
                    pass

    def test_stream_of_settled_request_replays_not_reconnects(self, model):
        """Streaming a request that already completed (polled to DONE)
        after its replica died must replay the recorded tokens and
        terminate — not reconnect to the corpse in a busy loop."""
        srv = _server(model, n_slots=1)
        try:
            with ServingRouter([srv.addr], health_interval_s=5.0,
                               request_timeout=5.0) as router:
                router.check_health()
                rr = router.submit(_prompt(), max_new_tokens=6)
                out = router.wait(rr, timeout=60)
                assert out["status"] == Request.DONE
                srv.kill()  # the replica is now a corpse
                assert list(router.stream(rr)) == out["tokens"]
        finally:
            try:
                srv.kill()
            except Exception:
                pass

    def test_settled_failure_replays_typed_exception(self):
        """stream() of an ALREADY-settled failure must raise the same
        exception class a live observation raised: RequestFailedError for
        a request-level verdict (the documented switch point for callers),
        RuntimeError for a replica death — settling first must not change
        the type."""
        router = ServingRouter(["127.0.0.1:1"])  # never dialed: rr.done
        from paddle_tpu.serving import RequestFailedError
        from paddle_tpu.serving.router import RoutedRequest
        verdict = RoutedRequest(_prompt(), max_new_tokens=2)
        verdict.state = Request.FAILED
        verdict.failure_kind = "request"
        verdict.error = "poison prompt"
        with pytest.raises(RequestFailedError, match="poison"):
            list(router.stream(verdict))
        death = RoutedRequest(_prompt(), max_new_tokens=2)
        death.state = Request.FAILED
        death.failure_kind = "transport"
        death.error = "replica 127.0.0.1:1 died after 3 tokens"
        with pytest.raises(RuntimeError, match="died after") as ei:
            list(router.stream(death))
        assert not isinstance(ei.value, RequestFailedError)

    def test_probe_client_uses_short_timeout(self):
        """Health probes must carry their own short deadline, not the full
        request_timeout — one black-holed replica would otherwise stall
        the sequential health loop for every replica."""
        router = ServingRouter(["127.0.0.1:1", "127.0.0.1:2"],
                               request_timeout=10.0, probe_timeout_s=0.5)
        for rep in router.replicas.values():
            assert rep.probe_client.timeout == 0.5
            assert rep.client.timeout == 10.0
        # capped by request_timeout when the request deadline is shorter
        router = ServingRouter(["127.0.0.1:1"], request_timeout=0.2,
                               probe_timeout_s=1.0)
        assert next(iter(router.replicas.values())).probe_client.timeout == 0.2

    def test_transport_error_against_live_replica_is_not_a_death(self, model):
        """One caller-side transport error (e.g. a poll timing out while
        the replica GIL-holds a long jit) must NOT trigger failover: the
        confirming probe sees the replica answering /metrics, so the
        request stays in place (no duplicate generation on a survivor, no
        permanent FAILED for a request the replica will finish)."""
        srv = _server(model, n_slots=1)
        try:
            with ServingRouter([srv.addr], health_interval_s=5.0,
                               request_timeout=5.0) as router:
                router.check_health()
                rr = router.submit(_prompt(), max_new_tokens=4)
                home = rr.replica_addr
                assert router._handle_replica_death(
                    rr, OSError("timed out"), home) is True
                snap = router.snapshot()
                assert snap["resubmits"] == 0 and snap["failovers"] == 0
                assert rr.replica_addr == home and not rr.done
                assert snap["replicas"][home]["consecutive_failures"] == 0
                out = router.wait(rr, timeout=60)
                assert out["status"] == Request.DONE and len(out["tokens"]) == 4
        finally:
            try:
                srv.kill()
            except Exception:
                pass

    def test_observe_never_regresses_token_log(self):
        """A stream thread replaying from token 0 races a poll that already
        recorded a longer log: _observe must be monotonic, never shrinking
        rr.tokens (a settled replay would yield the truncated log as a
        complete generation)."""
        from paddle_tpu.serving.router import RoutedRequest
        rr = RoutedRequest(_prompt(), max_new_tokens=8)
        rr._observe([1, 2, 3, 4, 5])
        rr._observe([1, 2])  # late, shorter observation of the same run
        assert rr.tokens == [1, 2, 3, 4, 5]

    def test_failover_idempotent_for_racing_observers(self, model):
        """poll() and stream() may observe the SAME replica death
        concurrently: the second observer must not resubmit the prompt
        again (a duplicate generation) nor charge the breaker of the
        survivor the first observer re-homed onto."""
        servers = {s.addr: s for s in (_server(model, n_slots=1),
                                       _server(model, n_slots=1))}
        try:
            with ServingRouter(list(servers), health_interval_s=5.0,
                               cooldown_s=30.0, request_timeout=5.0) as router:
                router.check_health()
                rr = router.submit(_prompt(), max_new_tokens=8)
                dead = rr.replica_addr
                servers[dead].kill()
                err = OSError("connection refused")
                assert router._handle_replica_death(rr, err, dead) is True
                survivor = rr.replica_addr
                assert survivor != dead
                n = router.snapshot()["resubmits"]
                # the racing second observer of the SAME death: no-op
                assert router._handle_replica_death(rr, err, dead) is True
                snap = router.snapshot()
                assert snap["resubmits"] == n
                assert rr.replica_addr == survivor
                assert snap["replicas"][survivor]["consecutive_failures"] == 0
                out = router.wait(rr, timeout=60)
                assert out["status"] == Request.DONE and len(out["tokens"]) == 8
        finally:
            for s in servers.values():
                try:
                    s.kill()
                except Exception:
                    pass

    def test_kill_mid_stream_requeues_and_streams_from_survivor(self, model):
        servers = {s.addr: s for s in (_server(model, n_slots=1),
                                       _server(model, n_slots=1))}
        addrs = list(servers)
        try:
            with ServingRouter(addrs, health_interval_s=0.1,
                               cooldown_s=30.0, request_timeout=5.0) as router:
                router.check_health()
                victim, running, queued, _ = \
                    self._pair_with_two_on_victim(router, addrs)
                got = []

                def consume():
                    for tok in router.stream(queued):
                        got.append(tok)

                t = threading.Thread(target=consume)
                t.start()
                time.sleep(0.1)  # the stream is blocked on the queued req
                servers[victim].kill()
                t.join(60)
                assert not t.is_alive()
                # the stream failed over transparently: every token came
                # from the survivor, none were dropped
                assert queued.state == Request.DONE
                assert len(got) == 24
                assert queued.replica_addr != victim
        finally:
            for s in servers.values():
                try:
                    s.kill()
                except Exception:
                    pass


# =====================================================================
# deterministic replica kill (tier-1): the process SIGKILL replaced by an
# injected `kill` at the replica.tick seam — the engine loop tears the
# whole replica down (HTTP plane severed, no drain) at an exact
# productive-tick count, so the failover scenario replays identically
# =====================================================================
class TestInjectedReplicaKill:
    def _run_scenario(self, model):
        """One full injected-failover pass; returns (fired_log,
        failover_tokens, (runner_state, runner_tokens), victim_addr,
        survivor_tokens)."""
        from paddle_tpu.resilience import FaultSchedule

        servers = {s.addr: s for s in (_server(model, n_slots=1),
                                       _server(model, n_slots=1))}
        addrs = list(servers)
        try:
            with ServingRouter(addrs, health_interval_s=0.1,
                               cooldown_s=30.0, request_timeout=5.0) as router:
                router.check_health()
                # place 1 running + 1 queued on a victim (deterministic:
                # least-loaded off identical gauges is insertion-ordered)
                placed = {a: [] for a in addrs}
                rrs = []
                for _ in range(3):
                    rr = router.submit(_prompt(), max_new_tokens=14)
                    rrs.append(rr)
                    placed[rr.replica_addr].append(rr)
                victim = next(a for a, v in placed.items() if len(v) == 2)
                running, queued = placed[victim]
                other = next(r for r in rrs if r not in (running, queued))
                # observe tokens from the RUNNING one so the router knows
                # its generation started (the resurrection half of the
                # scenario: it re-homes as a continuation, not a resubmit)
                deadline = time.perf_counter() + 30
                while not running.tokens:
                    router.poll(running)
                    assert time.perf_counter() < deadline
                    time.sleep(0.01)
                # arm AFTER placement so earlier ticks don't advance the
                # trigger count; the victim dies at its 3rd productive
                # tick from now
                sched = FaultSchedule(seed=5).add(
                    "replica.tick", "kill", at=3,
                    match={"replica": victim})
                with sched:
                    out_q = router.wait(queued, timeout=120)
                    out_r = router.wait(running, timeout=120)
                    router.wait(other, timeout=120)
                assert out_q["status"] == Request.DONE, queued.error
                assert queued.replica_addr != victim
                assert queued.resubmits == 1
                # the in-flight one is RESURRECTED: full transcript on the
                # survivor, bit-identical continuation (asserted by the
                # twin-run comparison below)
                assert out_r["status"] == Request.DONE, running.error
                assert running.resurrections == 1
                assert running.replica_addr != victim
                assert other.state == Request.DONE
                # normalize the ephemeral victim address out of the log:
                # the replay certificate is (point, kind, count, WHICH
                # replica by position), not which OS port it got
                log = sched.fired_log()
                for entry in log:
                    if entry["labels"].get("replica") == victim:
                        entry["labels"]["replica"] = "victim"
                return (log, list(queued.tokens),
                        (running.state, list(running.tokens)),
                        addrs.index(victim), list(other.tokens))
        finally:
            for s in servers.values():
                try:
                    s.kill()
                except Exception:
                    pass

    def test_injected_replica_kill_token_identical_replay(self, model):
        """Tier-1 twin of the SIGKILL-a-replica chaos test PLUS the
        replay acceptance: the queued request (zero observed tokens)
        re-homes and completes on the survivor, the in-flight one is
        RESURRECTED as a continuation join with its full transcript, and
        two runs of the same schedule produce the identical fault
        sequence and token-identical failover transcripts."""
        run_a = self._run_scenario(model)
        run_b = self._run_scenario(model)
        assert run_a == run_b  # fault log + transcripts, bit for bit
        log, failover_tokens, (runner_state, runner_tokens), _, \
            other_tokens = run_a
        assert log == [{"point": "replica.tick", "kind": "kill",
                        "count": 3, "labels": {"replica": "victim"}}]
        assert len(failover_tokens) == 14  # nothing dropped or truncated
        assert len(other_tokens) == 14
        assert runner_state == Request.DONE
        assert len(runner_tokens) == 14  # resurrected, not truncated


# =====================================================================
# multiprocess chaos (slow tier): SIGKILL a replica PROCESS mid-stream
# =====================================================================
_REPLICA_SCRIPT = textwrap.dedent("""
    import os, sys, time
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
    from paddle_tpu.serving import ContinuousBatchingEngine, ServingServer

    paddle.seed(0)
    cfg = gpt_config("gpt2-small", vocab_size=32, hidden_size=16,
                     num_layers=1, num_attention_heads=2,
                     max_position_embeddings=64, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    m = GPTForPretraining(cfg)
    m.eval()
    eng = ContinuousBatchingEngine(m, max_seq_len=128, n_slots=1,
                                   prefill_buckets=[8], max_queue=16)
    srv = ServingServer(eng).start()
    print(f"ADDR {srv.addr}", flush=True)
    while True:
        time.sleep(1)
""")


@pytest.mark.slow
@pytest.mark.chaos
def test_replica_process_sigkill_mid_stream(tmp_path):
    """Kill 1 of 2 engine replica PROCESSES mid-stream: zero queued
    requests dropped (they complete on the survivor) and the recovery
    time (kill → first token on the survivor) is measurable — the bench
    secondary's scenario, asserted."""
    script = tmp_path / "replica.py"
    script.write_text(_REPLICA_SCRIPT)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(
                   p for p in (repo, os.environ.get("PYTHONPATH")) if p))
    procs = [subprocess.Popen([sys.executable, str(script)],
                              stdout=subprocess.PIPE, text=True, env=env)
             for _ in range(2)]
    try:
        addrs = [p.stdout.readline().split()[1] for p in procs]
        with ServingRouter(addrs, health_interval_s=0.1, cooldown_s=30.0,
                           request_timeout=5.0) as router:
            router.check_health()
            # warm both replicas (compile prefill+decode out of the way)
            warm = [router.submit(_prompt(), max_new_tokens=2)
                    for _ in range(2)]
            for rr in warm:
                router.wait(rr, timeout=120)
            router.check_health()
            # load both replicas with LONG generations (n_slots=1, so each
            # replica holds one runner + queued work for ~100 ticks — the
            # kill must land while the target is still queued)
            rrs = [router.submit(_prompt(), max_new_tokens=100)
                   for _ in range(4)]
            placed = {}
            for rr in rrs:
                placed.setdefault(rr.replica_addr, []).append(rr)
            victim_addr = next(a for a, v in placed.items() if len(v) >= 2)
            victim_proc = procs[addrs.index(victim_addr)]
            queued = placed[victim_addr][-1]
            got = []

            def consume():
                for tok in router.stream(queued):
                    got.append(tok)

            t = threading.Thread(target=consume)
            t.start()
            time.sleep(0.05)
            assert not queued.tokens  # still queued behind the runner
            t_kill = time.perf_counter()
            victim_proc.kill()  # SIGKILL — no goodbye, no drain
            t.join(120)
            assert not t.is_alive()
            assert queued.state == Request.DONE
            assert len(got) == 100  # nothing dropped, nothing truncated
            assert queued.replica_addr != victim_addr
            assert queued.failover_first_token_at is not None
            recovery_s = queued.failover_first_token_at - t_kill
            assert 0 < recovery_s < 60
            # EVERY request survives the death: queued ones re-home,
            # in-flight ones resurrect as continuation joins — nothing
            # truncated, nothing regenerated from scratch
            for rr in rrs:
                router.wait(rr, timeout=120)
                assert rr.state == Request.DONE, rr.error
                assert len(rr.tokens) == 100
            assert router.snapshot()["inflight_failures"] == 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
