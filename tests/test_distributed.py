"""Distributed core tests on the 8-virtual-device CPU mesh.

Parity: the reference's collective op tests (test_collective_base.py pattern)
and topology tests (test_hybrid_parallel_topology.py) — here single-process
SPMD via shard_map instead of subprocess ranks (SURVEY §4 TPU translation).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import P


@pytest.fixture(autouse=True)
def _mesh():
    dist.init_mesh({"dp": 8})
    yield
    dist.env._global_mesh = None


def _g(axis="dp"):
    return dist.new_group(axis_name=axis)


class TestCollectives:
    def test_all_reduce_sum(self):
        g = _g()

        def fn(x):
            return dist.all_reduce(x, group=g)

        f = dist.run_on_mesh(fn, in_specs=P("dp"), out_specs=P("dp"))
        x = np.arange(8, dtype=np.float32)
        out = np.asarray(f(x))
        np.testing.assert_allclose(out, np.full(8, x.sum()))

    def test_all_reduce_max_min(self):
        g = _g()
        for op, want in [(dist.ReduceOp.MAX, 7.0), (dist.ReduceOp.MIN, 0.0)]:
            f = dist.run_on_mesh(
                lambda x: dist.all_reduce(x, op=op, group=g),
                in_specs=P("dp"), out_specs=P("dp"),
            )
            out = np.asarray(f(np.arange(8, dtype=np.float32)))
            np.testing.assert_allclose(out, np.full(8, want))

    def test_all_gather(self):
        g = _g()
        f = dist.run_on_mesh(
            lambda x: dist.all_gather(x, group=g), in_specs=P("dp"), out_specs=P(None)
        )
        x = np.arange(8, dtype=np.float32)
        # each shard gathers the full vector; out replicated
        out = np.asarray(f(x))
        np.testing.assert_allclose(out, x)

    def test_reduce_scatter(self):
        g = _g()
        f = dist.run_on_mesh(
            lambda x: dist.reduce_scatter(x, group=g), in_specs=P(None), out_specs=P("dp")
        )
        x = np.ones((8,), np.float32)
        out = np.asarray(f(x))
        np.testing.assert_allclose(out, np.full(8, 8.0))

    def test_broadcast(self):
        g = _g()
        f = dist.run_on_mesh(
            lambda x: dist.broadcast(x, src=3, group=g), in_specs=P("dp"), out_specs=P("dp")
        )
        x = np.arange(8, dtype=np.float32)
        out = np.asarray(f(x))
        np.testing.assert_allclose(out, np.full(8, 3.0))

    def test_alltoall_single(self):
        g = _g()
        f = dist.run_on_mesh(
            lambda x: dist.alltoall_single(x, group=g), in_specs=P("dp"), out_specs=P("dp")
        )
        # shard r holds values [r*8 .. r*8+7]; after all2all shard r holds
        # element r of every rank
        x = np.arange(64, dtype=np.float32)
        out = np.asarray(f(x)).reshape(8, 8)
        want = np.arange(64, dtype=np.float32).reshape(8, 8).T
        np.testing.assert_allclose(out, want)

    def test_shift_p2p(self):
        from paddle_tpu.distributed.p2p_utils import shift

        g = _g()
        f = dist.run_on_mesh(
            lambda x: shift(x, 1, g, wrap=False), in_specs=P("dp"), out_specs=P("dp")
        )
        x = np.arange(8, dtype=np.float32)
        out = np.asarray(f(x))
        np.testing.assert_allclose(out, [0, 0, 1, 2, 3, 4, 5, 6])

    def test_eager_world1_noops(self):
        dist.env._global_mesh = None
        g = dist.Group(ranks=[0])
        t = paddle.to_tensor(np.ones(3, np.float32))
        assert dist.all_reduce(t, group=g) is t
        assert dist.barrier() is None


class TestTopology:
    def test_communicate_topology(self):
        topo = dist.CommunicateTopology(["data", "pipe", "model"], [2, 2, 2])
        assert topo.world_size() == 8
        assert topo.get_rank(data=1, pipe=0, model=1) == 5
        assert topo.get_coord(5) == (1, 0, 1)
        assert topo.get_axis_list("data", 0) == [0, 1, 2, 3]
        comm = topo.get_comm_list("model")
        assert [0, 1] in comm and len(comm) == 4

    def test_hcg_degrees_and_mesh(self):
        hcg = dist.HybridCommunicateGroup(dp_degree=2, mp_degree=2, pp_degree=2, rank=0)
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.get_model_parallel_world_size() == 2
        assert hcg.get_pipe_parallel_world_size() == 2
        assert hcg.mesh is not None
        assert dict(hcg.mesh.shape) == {"dp": 2, "pp": 2, "sharding": 1, "sp": 1, "mp": 2}
        assert hcg.is_first_stage()

    def test_hcg_ranks(self):
        hcg = dist.HybridCommunicateGroup(dp_degree=2, mp_degree=2, pp_degree=2, rank=5)
        # topo order: data, pipe, sharding, sep, model with dims 2,2,1,1,2
        assert hcg.get_data_parallel_rank() == 1
        assert hcg.get_stage_id() == 0
        assert hcg.get_model_parallel_rank() == 1


class TestShardingPlacement:
    def test_shard_array(self):
        x = paddle.to_tensor(np.arange(16, dtype=np.float32))
        dist.shard_array(x, P("dp"))
        assert len(x.value.sharding.device_set) == 8

    def test_with_sharding_constraint_under_jit(self):
        def f(x):
            return dist.with_sharding_constraint(paddle.Tensor(x) * 2, P("dp")).value

        out = jax.jit(f)(jnp.arange(16, dtype=jnp.float32))
        np.testing.assert_allclose(np.asarray(out), np.arange(16) * 2)


class TestDataParallelTraining:
    def test_dp_training_matches_single_device(self):
        """Loss-parity: 8-way dp jitted training == single-device training
        (parity: test_dist_base.py loss-comparison methodology)."""
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt

        X = np.random.RandomState(0).randn(64, 10).astype(np.float32)
        W = np.random.RandomState(1).randn(10, 4).astype(np.float32)
        Y = (X @ W).argmax(1)

        def make_model():
            paddle.seed(7)
            return nn.Sequential(nn.Linear(10, 16), nn.ReLU(), nn.Linear(16, 4))

        def train(dp_axis):
            model = make_model()
            trainer = dist.ParallelTrainer(
                model, lambda out, y: nn.CrossEntropyLoss()(out, y),
                opt.SGD(0.1), dp_axis=dp_axis,
            )
            losses = []
            for _ in range(5):
                losses.append(float(trainer.step(paddle.to_tensor(X), paddle.to_tensor(Y))))
            return losses

        dp_losses = train("dp")
        dist.init_mesh({"dp": 1})
        single_losses = train(None)
        np.testing.assert_allclose(dp_losses, single_losses, rtol=1e-4)

    def test_gradient_merge_matches_full_batch(self):
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as opt

        X = np.random.RandomState(0).randn(32, 6).astype(np.float32)
        Y = np.random.RandomState(1).randn(32, 3).astype(np.float32)

        def make():
            paddle.seed(3)
            return nn.Linear(6, 3)

        m1 = make()
        t1 = dist.ParallelTrainer(m1, lambda o, y: nn.MSELoss()(o, y), opt.SGD(0.1), dp_axis=None)
        l1 = float(t1.step(paddle.to_tensor(X), paddle.to_tensor(Y)))
        m2 = make()
        t2 = dist.ParallelTrainer(
            m2, lambda o, y: nn.MSELoss()(o, y), opt.SGD(0.1), dp_axis=None, accumulate_steps=4
        )
        l2 = float(t2.step(paddle.to_tensor(X), paddle.to_tensor(Y)))
        np.testing.assert_allclose(l1, l2, rtol=1e-5)
        t1.sync_to_model()
        t2.sync_to_model()
        np.testing.assert_allclose(m1.weight.numpy(), m2.weight.numpy(), atol=1e-6)


class TestTensorParallelLayers:
    def _mp_mesh(self):
        return dist.init_mesh({"dp": 2, "mp": 4})

    def test_column_row_parity_with_dense(self):
        """TP GSPMD output == dense single-device output."""
        from paddle_tpu.distributed.meta_parallel import ColumnParallelLinear, RowParallelLinear

        self._mp_mesh()
        paddle.seed(0)
        col = ColumnParallelLinear(8, 16, gather_output=False, has_bias=True)
        row = RowParallelLinear(16, 8, input_is_parallel=True, has_bias=True)
        x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))

        got = row(col(x)).numpy()
        want = (
            (x.numpy() @ col.weight.numpy() + col.bias.numpy()) @ row.weight.numpy()
            + row.bias.numpy()
        )
        np.testing.assert_allclose(got, want, atol=1e-5)
        # weights really sharded on the mesh
        dist.shard_array(col.weight, col.weight.partition_spec)
        shard_shapes = {s.data.shape for s in col.weight.value.addressable_shards}
        assert shard_shapes == {(8, 4)}

    def test_vocab_parallel_embedding_explicit(self):
        """Explicit shard_map path == reference c_embedding semantics."""
        from paddle_tpu.distributed.meta_parallel.mp_layers import MP_AXIS

        mesh = dist.init_mesh({"mp": 8})
        paddle.seed(0)
        W = np.random.randn(16, 4).astype(np.float32)
        ids = np.array([[0, 5], [9, 15]])

        def fn(w_shard, ids):
            import jax

            rank = jax.lax.axis_index(MP_AXIS)
            per = w_shard.shape[0]
            local = ids - rank * per
            ok = (local >= 0) & (local < per)
            emb = jnp.take(w_shard, jnp.where(ok, local, 0), axis=0)
            emb = jnp.where(ok[..., None], emb, 0.0)
            return jax.lax.psum(emb, MP_AXIS)

        f = dist.run_on_mesh(fn, in_specs=(P("mp", None), P(None, None)), out_specs=P(None))
        out = np.asarray(f(W, ids))
        np.testing.assert_allclose(out, W[ids], atol=1e-6)

    def test_parallel_cross_entropy_explicit(self):
        from paddle_tpu.distributed.meta_parallel.mp_layers import ParallelCrossEntropy

        dist.init_mesh({"mp": 8})
        logits = np.random.randn(4, 32).astype(np.float32)
        labels = np.array([0, 9, 17, 31])
        pce = ParallelCrossEntropy()

        def fn(lg, lb):
            return pce(paddle.Tensor(lg), paddle.Tensor(lb)).value

        f = dist.run_on_mesh(fn, in_specs=(P(None, "mp"), P(None)), out_specs=P(None))
        got = np.asarray(f(logits, labels))[:, 0]
        # reference: plain softmax CE
        e = np.exp(logits - logits.max(1, keepdims=True))
        p = e / e.sum(1, keepdims=True)
        want = -np.log(p[np.arange(4), labels])
        np.testing.assert_allclose(got, want, rtol=1e-4)


class TestFleet:
    def test_fleet_init_and_strategy(self):
        from paddle_tpu.distributed import fleet

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.get_hybrid_communicate_group()
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.get_model_parallel_world_size() == 2
        assert fleet.worker_num() == 1  # single controller

    def test_strategy_fields_and_serialization(self, tmp_path):
        from paddle_tpu.distributed.fleet import DistributedStrategy

        s = DistributedStrategy()
        s.amp = True
        s.amp_configs = {"init_loss_scaling": 1024.0}
        s.recompute = True
        s.sharding = True
        s.sharding_configs = {"stage": 2}
        with pytest.raises(ValueError):
            s.not_a_field = 1
        p = str(tmp_path / "strategy.json")
        s.save_to_prototxt(p)
        s2 = DistributedStrategy()
        s2.load_from_prototxt(p)
        assert s2.amp and s2.amp_configs["init_loss_scaling"] == 1024.0
        assert s2.sharding_configs["stage"] == 2
        assert "sharding" in s2.effective()

    def test_distributed_model_dp(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed import fleet

        strategy = fleet.DistributedStrategy()
        fleet.init(strategy=strategy)
        model = fleet.distributed_model(nn.Linear(4, 4))
        out = model(paddle.to_tensor(np.ones((8, 4), np.float32)))
        assert out.shape == [8, 4]

    def test_pipeline_layer_segmentation(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.meta_parallel import LayerDesc, PipelineLayer

        pipe = PipelineLayer(
            layers=[LayerDesc(nn.Linear, 4, 4) for _ in range(8)], num_stages=4
        )
        assert pipe.segment_parts == [0, 2, 4, 6, 8]
        assert len(pipe.get_stage_layers(1)) == 2
        out = pipe(paddle.to_tensor(np.ones((2, 4), np.float32)))
        assert out.shape == [2, 4]

    def test_shared_layer_desc_ties_weights(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.meta_parallel import PipelineLayer, SharedLayerDesc

        pipe = PipelineLayer(
            layers=[
                SharedLayerDesc("emb", nn.Linear, None, "weight", 4, 4),
                SharedLayerDesc("emb", nn.Linear, None, "weight", 4, 4),
            ],
            num_stages=1,
        )
        l0, l1 = list(pipe.run_function)
        assert l0.weight is l1.weight
        n_params = len({id(p) for p in pipe.parameters()})
        assert n_params == 3  # tied weight + two biases


class TestReplicatedEagerCollectives:
    """Eager collectives over a >1 group under the single-controller model:
    replicated-eager closed forms (reference dygraph metric-reduction idiom
    `all_reduce(loss); loss /= nranks` must be exact)."""

    def test_eager_all_reduce_closed_forms(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.collective import ReduceOp, all_reduce

        dist.init_mesh({"dp": 8})
        try:
            t = paddle.to_tensor([2.0, 3.0])
            out = all_reduce(t, op=ReduceOp.SUM)
            np.testing.assert_allclose(np.asarray(out._data), [16.0, 24.0])
            t2 = paddle.to_tensor([2.0])
            assert float(all_reduce(t2, op=ReduceOp.MAX)._data[0]) == 2.0
            from paddle_tpu.distributed.group import get_default_group

            loss = all_reduce(paddle.to_tensor([4.0]))
            loss = loss / get_default_group().nranks  # the metric idiom
            np.testing.assert_allclose(np.asarray(loss._data), [4.0])
        finally:
            dist.clear_mesh()

    def test_eager_all_gather_and_broadcast(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.collective import all_gather, broadcast

        dist.init_mesh({"dp": 4})
        try:
            t = paddle.to_tensor([1.0, 2.0])
            outs = []
            all_gather(outs, t)
            assert len(outs) == 4
            np.testing.assert_allclose(np.asarray(outs[2]._data), [1.0, 2.0])
            b = broadcast(paddle.to_tensor([5.0]), src=1)
            np.testing.assert_allclose(np.asarray(b._data), [5.0])
        finally:
            dist.clear_mesh()

    def test_rank_divergent_ops_raise_teachably(self):
        import pytest as _pytest

        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.collective import reduce_scatter

        dist.init_mesh({"dp": 4})
        try:
            with _pytest.raises(RuntimeError, match="replicated-eager"):
                reduce_scatter(paddle.to_tensor([1.0, 2.0, 3.0, 4.0]))
        finally:
            dist.clear_mesh()


def test_lr_schedule_applies_to_jitted_step():
    """The compiled trainer step must read the CURRENT lr each call (a
    trace-time read would bake the initial value and freeze schedules)."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.parallel_trainer import ParallelTrainer
    from paddle_tpu.optimizer.lr import StepDecay
    from paddle_tpu.optimizer.optimizers import SGD

    dist.clear_mesh()
    dist.init_mesh({"dp": 1})
    try:
        paddle.seed(0)
        net = paddle.nn.Linear(4, 4)
        sched = StepDecay(learning_rate=1.0, step_size=1, gamma=0.1)
        opt = SGD(learning_rate=sched, parameters=net.parameters())
        trainer = ParallelTrainer(
            net, lambda out, y: ((out - y) ** 2).mean(), opt, dp_axis=None)
        x = paddle.to_tensor(np.eye(4, dtype="float32"))
        y = paddle.to_tensor(np.zeros((4, 4), "float32"))

        w0 = np.asarray(trainer.params[list(trainer.params)[0]])
        trainer.step(x, y)
        w1 = np.asarray(trainer.params[list(trainer.params)[0]])
        d1 = np.abs(w1 - w0).max()
        sched.step()  # lr: 1.0 -> 0.1
        trainer.step(x, y)
        w2 = np.asarray(trainer.params[list(trainer.params)[0]])
        d2 = np.abs(w2 - w1).max()
        # SGD delta scales with lr: the second step must be ~10x smaller
        # (not exactly — the loss surface moved — but far below a frozen lr)
        assert d2 < 0.5 * d1, (d1, d2)
    finally:
        dist.clear_mesh()
