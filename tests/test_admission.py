"""Memory-aware admission control + overload protection (ISSUE 8).

The admission gate prices each request's KV+prefill HBM with the r10
liveness estimator and refuses over-budget work citing the estimate; the
deadline layer sheds queue-expired work before prefill (typed 503); the
load-shed policy bounds queue wait under sustained overload without ever
killing a request that reached a slot. The accounting test holds the
gate's predicted resident footprint against the ``jax.live_arrays()``
census after prefill — the r10 estimator-vs-measured 15% bound, on the
serving plane.
"""
import gc
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTForPretraining, gpt_config
from paddle_tpu.serving import (
    AdmissionGate,
    AdmissionRejected,
    ContinuousBatchingEngine,
    DeadlineExceededError,
    LoadShedPolicy,
    QueueFullError,
    Request,
    ServingClient,
    ServingRouter,
    ServingServer,
)
from paddle_tpu.serving.admission import DEADLINE_ERROR_TYPE, SHED_ERROR_TYPE

VOCAB = 64


def _tiny_model(layers=1, hidden=32):
    paddle.seed(0)
    cfg = gpt_config("gpt2-small", vocab_size=VOCAB, hidden_size=hidden,
                     num_layers=layers, num_attention_heads=2,
                     max_position_embeddings=64, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0)
    m = GPTForPretraining(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _tiny_model()


def _prompt(n=4):
    return np.arange(1, n + 1, dtype=np.int32)


def _drain(eng, reqs, timeout=120.0):
    deadline = time.perf_counter() + timeout
    while any(not r.done for r in reqs):
        assert time.perf_counter() < deadline, "engine did not finish"
        eng.step_once()


# =====================================================================
# admission gate: liveness pricing vs device budget
# =====================================================================
class TestAdmissionGate:
    def test_refusal_cites_liveness_estimate(self, model):
        """The acceptance criterion: an over-budget request is refused
        and the refusal carries the liveness numbers it was judged by."""
        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=2,
                                       hbm_budget_bytes=1024)
        with pytest.raises(AdmissionRejected) as ei:
            eng.submit(_prompt(), max_new_tokens=2)
        est = ei.value.estimate
        assert est["source"] == "analysis.memory liveness estimator"
        assert est["predicted_peak_hbm_bytes"] > est["budget_bytes"] == 1024
        assert est["kv_bytes_per_slot"] > 0
        assert str(est["predicted_peak_hbm_bytes"]) in str(ei.value)
        assert eng.metrics.requests_rejected == 1

    def test_within_budget_admits_and_generates(self, model):
        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=2,
                                       hbm_budget_bytes=1 << 30)
        reqs = [eng.submit(_prompt(), max_new_tokens=4) for _ in range(2)]
        _drain(eng, reqs)
        assert all(r.state == Request.DONE for r in reqs)

    def test_http_refusal_is_429_with_estimate_body(self, model):
        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=2,
                                       hbm_budget_bytes=1024)
        with ServingServer(eng) as srv:
            with pytest.raises(AdmissionRejected) as ei:
                ServingClient(srv.addr).submit(_prompt().tolist(),
                                               max_new_tokens=2)
            # the typed class survived the wire, estimate body included
            assert ei.value.estimate["budget_bytes"] == 1024
            assert ei.value.retry_after is not None

    def test_pricing_does_not_perturb_compile_accounting(self, model):
        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=2,
                                       hbm_budget_bytes=1 << 30)
        gate = eng.admission_gate
        for b in eng.scheduler.buckets:
            gate.price(b)
        assert eng.trace_counts == {"prefill": 0, "step": 0}
        # pricing is cached: second pass hits the dict
        before = dict(gate._estimates)
        gate.price(eng.scheduler.buckets[0])
        assert dict(gate._estimates) == before

    def test_larger_bucket_prices_no_smaller(self, model):
        eng = ContinuousBatchingEngine(model, max_seq_len=64, n_slots=2,
                                       hbm_budget_bytes=1 << 30)
        gate = eng.admission_gate
        peaks = [gate.price(b)["predicted_peak_hbm_bytes"]
                 for b in sorted(eng.scheduler.buckets)]
        assert peaks == sorted(peaks)

    def test_gate_accounting_within_15pct_of_live_arrays(self):
        """Predicted resident HBM for N admitted slots vs the
        ``jax.live_arrays()`` census after prefill — the estimator's 15%
        certification, exercised on the serving plane it now gates."""
        import jax

        gc.collect()
        base = sum(a.nbytes for a in jax.live_arrays())
        model = _tiny_model(layers=2, hidden=32)
        eng = ContinuousBatchingEngine(
            model, max_seq_len=32, n_slots=4, max_prefills_per_tick=4,
            hbm_budget_bytes=1 << 30)
        reqs = [eng.submit(_prompt(), max_new_tokens=16) for _ in range(4)]
        eng.step_once()  # prefills all four (interleave cap raised)
        assert eng.active_slots() == 4
        gc.collect()
        census = sum(a.nbytes for a in jax.live_arrays()) - base
        predicted = eng.admission_gate.predicted_live_bytes()
        assert census > 0
        drift = abs(predicted - census) / census
        assert drift <= 0.15, (predicted, census, drift)
        _drain(eng, reqs)


# =====================================================================
# deadlines: propagation + queue-wait shedding
# =====================================================================
class TestDeadlines:
    def test_expired_on_arrival_is_typed_503(self, model):
        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=1)
        with ServingServer(eng) as srv:
            with pytest.raises(DeadlineExceededError):
                ServingClient(srv.addr).submit(_prompt().tolist(),
                                               max_new_tokens=2,
                                               deadline_s=-1.0)

    def test_non_finite_deadline_rejected_not_silently_disabled(
            self, model):
        """float('nan') compares False against every expiry check, so a
        NaN deadline would silently mean NO deadline while the client
        believes one is set — it must be a 400, not an open-ended wait."""
        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=1)
        with pytest.raises(ValueError, match="finite"):
            eng.submit(_prompt(), max_new_tokens=2,
                       deadline_s=float("nan"))
        with ServingServer(eng) as srv:
            with pytest.raises(RuntimeError, match="400"):
                ServingClient(srv.addr).submit(_prompt().tolist(),
                                               max_new_tokens=2,
                                               deadline_s=float("nan"))
        from paddle_tpu.serving.router import RoutedRequest

        with pytest.raises(ValueError, match="finite"):
            RoutedRequest(_prompt(), deadline_s=float("nan"))

    def test_queue_expiry_sheds_before_prefill(self, model):
        """A request whose deadline elapses while QUEUED fails typed,
        before any prefill ran for it."""
        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=1)
        blocker = eng.submit(_prompt(), max_new_tokens=12)
        doomed = eng.submit(_prompt(), max_new_tokens=4, deadline_s=0.01)
        time.sleep(0.05)  # the deadline lapses in the queue
        prefills_before = eng.metrics.prefill_calls
        while not doomed.done:
            eng.step_once()
        assert doomed.state == Request.FAILED
        assert doomed.error_type == DEADLINE_ERROR_TYPE
        assert doomed.tokens == []
        # it never prefilled: only the blocker's prefill ever ran
        assert eng.metrics.prefill_calls == max(prefills_before, 1)
        _drain(eng, [blocker])
        assert blocker.state == Request.DONE

    def test_mid_queue_expiry_race_regression(self, model, monkeypatch):
        """The race: a request is POPPED while its deadline is still
        valid, but the deadline lapses before prefill begins. The
        post-pop re-check must shed it — the prefill program must never
        run for it. (The sweep is disabled so the pop path is the one
        under test.)"""
        from paddle_tpu.serving.scheduler import FCFSScheduler

        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=2)
        monkeypatch.setattr(FCFSScheduler, "sweep_expired",
                            lambda self: [])
        req = eng.submit(_prompt(), max_new_tokens=4, deadline_s=60.0)
        # valid at pop time, expired by the re-check: the pop happens
        # inside the step_once below — move the deadline into the past
        # after submit but before the tick, which is exactly the window
        # between pop and prefill once sweep_expired is inert
        req.deadline_at = time.perf_counter() - 1e-3
        prefills = eng.metrics.prefill_calls
        eng.step_once()
        assert req.state == Request.FAILED
        assert req.error_type == DEADLINE_ERROR_TYPE
        assert eng.metrics.prefill_calls == prefills  # never prefilled
        assert eng.scheduler.in_admission() == 0      # settled, not leaked
        # the slot freed by the shed is immediately usable
        ok = eng.submit(_prompt(), max_new_tokens=2)
        _drain(eng, [ok])
        assert ok.state == Request.DONE

    def test_deadline_rides_header_through_router(self, model):
        srv = ServingServer(
            ContinuousBatchingEngine(model, max_seq_len=32, n_slots=1)
        ).start()
        try:
            with ServingRouter([srv.addr], health_interval_s=5.0,
                               request_timeout=5.0) as router:
                router.check_health()
                rr = router.submit(_prompt(), max_new_tokens=4,
                                   deadline_s=60.0)
                out = router.wait(rr, timeout=60)
                assert out["status"] == Request.DONE
                # an already-expired deadline is shed AT THE ROUTER
                with pytest.raises(DeadlineExceededError):
                    router.submit(_prompt(), max_new_tokens=4,
                                  deadline_s=-0.5)
        finally:
            srv.kill()

    def test_poll_surfaces_typed_deadline_failure(self, model):
        eng = ContinuousBatchingEngine(model, max_seq_len=32, n_slots=1)
        with ServingServer(eng) as srv:
            c = ServingClient(srv.addr)
            blocker = c.submit(_prompt().tolist(), max_new_tokens=12)
            rid = c.submit(_prompt().tolist(), max_new_tokens=2,
                           deadline_s=0.01)
            out = c.wait(rid, timeout=60)
            assert out["status"] == Request.FAILED
            assert out["error_type"] == DEADLINE_ERROR_TYPE
            c.wait(blocker, timeout=60)


# =====================================================================
# load shedding under sustained overload
# =====================================================================
class TestLoadShed:
    def _overloaded_engine(self, model, shed: bool, n_slots=2, max_new=6):
        # sustain_s=0: the sustain window is WALL-clock while this test
        # drives fixed TICK counts — on a fast box a nonzero window fits
        # arbitrarily many growth ticks before the first shed, making
        # any queue-depth bound box-speed-dependent (the flake class
        # this PR exists to kill). With 0 the policy sheds one tick
        # after the crossing: overshoot is bounded in ticks, not seconds
        policy = (LoadShedPolicy(sustain_s=0.0) if shed else None)
        return ContinuousBatchingEngine(
            model, max_seq_len=32, n_slots=n_slots, max_queue=256,
            shed_policy=policy), max_new

    def _drive_overload(self, eng, max_new, rounds=40):
        """Tick-driven 2× synthetic overload: each request occupies a
        slot for ~max_new ticks, so the service rate is n_slots/max_new
        requests per tick; arrivals accumulate at exactly twice that."""
        warm = eng.submit(_prompt(), max_new_tokens=2)
        _drain(eng, [warm])  # compiles out of the TTFT samples
        rate = 2.0 * eng.n_slots / max_new
        reqs, depths = [], []
        acc = 0.0
        for _ in range(rounds):
            acc += rate
            while acc >= 1.0:
                reqs.append(eng.submit(_prompt(), max_new_tokens=max_new))
                acc -= 1.0
            eng.step_once()
            depths.append(eng.scheduler.depth())
        _drain(eng, reqs)
        return reqs, depths

    def test_sustained_overload_sheds_visibly_never_kills_admitted(
            self, model):
        """The overload acceptance in one drive: shedding is VISIBLE
        (typed failures + Retry-After hints, no silent drops), zero
        requests that started decoding are killed by it, and the shed
        counter lands in the Prometheus exposition."""
        eng, max_new = self._overloaded_engine(model, shed=True)
        reqs, _ = self._drive_overload(eng, max_new)
        done = [r for r in reqs if r.state == Request.DONE]
        failed = [r for r in reqs if r.state == Request.FAILED]
        # every request settled one way — nothing dropped silently
        assert len(done) + len(failed) == len(reqs)
        assert all(r.error_type == SHED_ERROR_TYPE and r.error
                   for r in failed)
        assert eng.metrics.requests_shed == len(failed)
        assert len(failed) > 0  # 2× overload really shed
        assert all("retry after" in r.error for r in failed)
        # zero ADMITTED (started decoding) requests were shed
        assert all(not r.tokens for r in failed)
        assert all(len(r.tokens) == max_new or
                   r.tokens[-1:] == [r.eos_token_id] for r in done)
        text = eng.metrics.prometheus_text()
        assert "serving_requests_shed_total" in text
        assert 'reason="overload"' in text

    def test_shed_bounds_queue_vs_no_shed(self, model):
        """Goodput shape, asserted on the TICK-DETERMINISTIC invariant
        (wall-clock TTFT comparisons flake under concurrent CI load —
        bench owns the timing claims): with shedding the queue is bounded
        near the watermark, so admitted queue WAIT is bounded; without,
        the queue grows with the overload for the whole drive."""
        eng_a, max_new = self._overloaded_engine(model, shed=True)
        reqs_a, depths_a = self._drive_overload(eng_a, max_new)
        eng_b, _ = self._overloaded_engine(model, shed=False)
        reqs_b, depths_b = self._drive_overload(eng_b, max_new)
        # overshoot past the watermark is bounded by growth during the
        # sustain window (a few ticks' arrivals)
        assert max(depths_a) <= eng_a.shed_policy.high_watermark \
            + eng_a.n_slots + 2, depths_a
        # the unprotected arm's queue grows well past the shed arm's cap
        assert max(depths_b) > max(depths_a)
        # no-shed admitted everything; shed arm failed only queued work
        assert all(r.state == Request.DONE for r in reqs_b)
        assert any(r.state == Request.FAILED for r in reqs_a)

    def test_watermarks_default_to_slot_fractions(self, model):
        eng, _ = self._overloaded_engine(model, shed=True, n_slots=3)
        assert eng.shed_policy.high_watermark == 3
        assert eng.shed_policy.low_watermark == 1
