"""QAT / fake-quant parity tests (reference:
unittests/test_fake_quantize_op.py, test_imperative_qat.py)."""
import numpy as np

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu import quantization as Q
from paddle_tpu.tensor import Tensor


rng = np.random.default_rng(9)


def _np(t):
    return np.asarray(t._data)


class TestFakeQuantOps:
    def test_abs_max(self):
        x = rng.standard_normal((4, 5)).astype("float32")
        out, scale = Q.fake_quantize_abs_max(paddle.to_tensor(x), 8)
        s = np.abs(x).max()
        np.testing.assert_allclose(_np(scale), s, rtol=1e-6)
        want = np.clip(np.round(x / s * 127), -127, 127) * s / 127
        np.testing.assert_allclose(_np(out), want, rtol=1e-5, atol=1e-6)
        # quantization error bounded by half a level
        assert np.abs(_np(out) - x).max() <= s / 127

    def test_channel_wise(self):
        w = rng.standard_normal((6, 3, 2, 2)).astype("float32")
        out, scales = Q.fake_channel_wise_quantize_abs_max(
            paddle.to_tensor(w), 8, quant_axis=0)
        assert _np(scales).shape == (6,)
        for c in range(6):
            s = np.abs(w[c]).max()
            np.testing.assert_allclose(_np(scales)[c], s, rtol=1e-6)
            want = np.clip(np.round(w[c] / s * 127), -127, 127) * s / 127
            np.testing.assert_allclose(_np(out)[c], want, rtol=1e-5, atol=1e-6)

    def test_moving_average(self):
        x1 = paddle.to_tensor(np.array([2.0, -4.0], "float32"))
        scale0 = paddle.to_tensor(np.asarray(0.0, dtype="float32"))
        # bias-corrected rule: first step yields the full abs-max
        out, s1, a1, st1 = Q.fake_quantize_moving_average_abs_max(
            x1, scale0, moving_rate=0.9)
        np.testing.assert_allclose(_np(s1), 4.0, rtol=1e-6)
        np.testing.assert_allclose(_np(a1), 4.0, rtol=1e-6)
        np.testing.assert_allclose(_np(st1), 1.0, rtol=1e-6)
        # second step: accum=0.9*4+2, state=0.9+1
        x2 = paddle.to_tensor(np.array([2.0, -1.0], "float32"))
        out2, s2, a2, st2 = Q.fake_quantize_moving_average_abs_max(
            x2, s1, a1, st1, moving_rate=0.9)
        np.testing.assert_allclose(_np(s2), (0.9 * 4.0 + 2.0) / 1.9, rtol=1e-6)
        # eval mode: scale frozen
        out3, frozen, _, _ = Q.fake_quantize_moving_average_abs_max(
            x1, s2, a2, st2, moving_rate=0.9, training=False)
        np.testing.assert_allclose(_np(frozen), _np(s2))

    def test_ste_gradient(self):
        x = paddle.to_tensor(rng.standard_normal((3, 3)).astype("float32"))
        x.stop_gradient = False
        out, _ = Q.fake_quantize_abs_max(x, 8)
        out.sum().backward()
        # straight-through: gradient of sum is all-ones
        np.testing.assert_allclose(_np(x.grad), np.ones((3, 3)), rtol=1e-6)

    def test_lower_bits(self):
        x = rng.standard_normal((8,)).astype("float32")
        out4, _ = Q.fake_quantize_abs_max(paddle.to_tensor(x), 4)
        uniq = np.unique(_np(out4))
        assert len(uniq) <= 15  # 4-bit signed: at most 15 levels


class TestQATTraining:
    def _make_model(self):
        paddle.seed(1)
        return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))

    def test_quantize_replaces_layers(self):
        model = self._make_model()
        qat = Q.ImperativeQuantAware(weight_quantize_type="channel_wise_abs_max")
        qat.quantize(model)
        kinds = [type(m).__name__ for m in model.sublayers()]
        assert kinds.count("QuantizedLinear") == 2

    def test_skip_quant(self):
        model = self._make_model()
        model[0].skip_quant = True
        Q.ImperativeQuantAware().quantize(model)
        assert type(model[0]).__name__ == "Linear"
        assert type(model[2]).__name__ == "QuantizedLinear"

    def test_qat_trains_and_tracks_scales(self):
        model = self._make_model()
        Q.ImperativeQuantAware().quantize(model)
        adam = opt.Adam(learning_rate=0.01, parameters=model.parameters())
        X = rng.standard_normal((128, 8)).astype("float32")
        W = rng.standard_normal((8, 1)).astype("float32")
        Y = X @ W
        first = last = None
        for _ in range(100):
            pred = model(paddle.to_tensor(X))
            loss = ((pred - paddle.to_tensor(Y)) ** 2).mean()
            loss.backward()
            adam.step()
            adam.clear_grad()
            v = float(_np(loss))
            first = v if first is None else first
            last = v
        assert last < 0.3 * first, (first, last)
        # activation scale settled near the input abs-max
        assert abs(model[0].act_scale - np.abs(X).max()) < 1.5

    def test_state_dict_roundtrip(self):
        model = self._make_model()
        Q.ImperativeQuantAware().quantize(model)
        model(paddle.to_tensor(rng.standard_normal((4, 8)).astype("float32")))
        sd = model.state_dict()
        model2 = self._make_model()
        Q.ImperativeQuantAware().quantize(model2)
        model2.set_state_dict(sd)
        for (n1, p1), (n2, p2) in zip(model.named_parameters(),
                                      model2.named_parameters()):
            np.testing.assert_allclose(_np(p1), _np(p2))


class TestPTQ:
    def test_calibration_freezes_scales(self):
        paddle.seed(2)
        model = nn.Sequential(nn.Linear(4, 4), nn.ReLU(), nn.Linear(4, 2))
        data = [(paddle.to_tensor(rng.standard_normal((16, 4)).astype("float32")),)
                for _ in range(10)]
        ptq = Q.PostTrainingQuantization(model, data, batch_nums=8)
        qmodel = ptq.quantize()
        scale_after_cal = qmodel[0].act_scale
        assert scale_after_cal > 0
        # further eval passes do not move the scale
        qmodel.eval()
        qmodel(paddle.to_tensor(100 * rng.standard_normal((16, 4)).astype("float32")))
        assert qmodel[0].act_scale == scale_after_cal


class TestInt8ArtifactEndToEnd:
    """VERDICT r4 #6: calibration -> baked-scale int8 artifact -> Predictor.

    Reference: trt_int8_calibrator.cc collects activation ranges from
    sample batches and bakes them into the engine; here the calibrated EMA
    scales ride the traced StableHLO as frozen buffers and the weights are
    stored per-channel int8."""

    def _calibrate_and_export(self, model, calib_x, spec, tmp_path, tag):
        from paddle_tpu.quantization import (
            PostTrainingQuantization, save_quantized_model)

        loader = [(Tensor(jnp.asarray(b)),) for b in calib_x]
        ptq = PostTrainingQuantization(model, loader)
        qmodel = ptq.quantize()
        path = str(tmp_path / tag)
        save_quantized_model(qmodel, path, input_spec=spec)
        return qmodel, path

    def _predict(self, path, x):
        from paddle_tpu.inference import Config, create_predictor

        cfg = Config(path)
        pred = create_predictor(cfg)
        names = pred.get_input_names()
        h = pred.get_input_handle(names[0])
        h.copy_from_cpu(np.asarray(x))
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0])
        return out.copy_to_cpu()

    def test_vision_conv_net(self, tmp_path):
        import paddle_tpu.nn as nn
        from paddle_tpu.static import InputSpec

        paddle.seed(0)
        model = nn.Sequential(
            nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
            nn.Conv2D(8, 8, 3, padding=1), nn.ReLU(),
            nn.Flatten(), nn.Linear(8 * 8 * 8, 10))
        rng = np.random.default_rng(0)
        calib = [rng.standard_normal((2, 3, 8, 8)).astype(np.float32)
                 for _ in range(4)]
        x = rng.standard_normal((2, 3, 8, 8)).astype(np.float32)
        model.eval()
        fp_out = np.asarray(model(Tensor(jnp.asarray(x)))._data)

        qmodel, path = self._calibrate_and_export(
            model, calib, [InputSpec([-1, 3, 8, 8], "float32")], tmp_path,
            "vision_int8")
        got = self._predict(path, x)
        # int8 QDQ keeps outputs close to fp (abs_max symmetric, 8 bits)
        np.testing.assert_allclose(got, fp_out, atol=0.15, rtol=0.1)
        err = np.abs(got - fp_out).mean() / (np.abs(fp_out).mean() + 1e-9)
        assert err < 0.05, f"relative int8 error too large: {err}"

        # measured size row: int8 artifact params ~4x smaller than f32
        import os
        sz_q = os.path.getsize(path + ".pdiparams")
        n_params = sum(int(np.prod(p._data.shape))
                       for p in qmodel.parameters())
        assert sz_q < n_params * 4 * 0.5, (sz_q, n_params * 4)

    def test_gpt_head(self, tmp_path):
        import paddle_tpu.nn as nn
        from paddle_tpu.static import InputSpec

        paddle.seed(1)

        class Head(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(64, 256)
                self.act = nn.GELU()
                self.fc2 = nn.Linear(256, 64)

            def forward(self, x):
                return self.fc2(self.act(self.fc1(x)))

        model = Head()
        rng = np.random.default_rng(1)
        calib = [rng.standard_normal((4, 16, 64)).astype(np.float32)
                 for _ in range(4)]
        x = rng.standard_normal((4, 16, 64)).astype(np.float32)
        model.eval()
        fp_out = np.asarray(model(Tensor(jnp.asarray(x)))._data)
        _, path = self._calibrate_and_export(
            model, calib, [InputSpec([-1, 16, 64], "float32")], tmp_path,
            "gpt_head_int8")
        got = self._predict(path, x)
        err = np.abs(got - fp_out).mean() / (np.abs(fp_out).mean() + 1e-9)
        assert err < 0.08, f"relative int8 error too large: {err}"
