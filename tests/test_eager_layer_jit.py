"""Transparent per-layer jit caching for eager mode (SURVEY §7 hard-part 4).

Parity model: the reference's generated core.ops.* fast path
(/root/reference/paddle/fluid/pybind/op_function_generator.cc:551) — these
tests assert the cached-jit dispatch is semantically invisible: same
outputs, same gradients, fresh dropout masks, MoE exempt.
Forced on via FLAGS_eager_layer_jit="force" (CPU backend).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


@pytest.fixture
def jit_forward():
    paddle.set_flags({"FLAGS_eager_layer_jit": "force"})
    yield
    paddle.set_flags({"FLAGS_eager_layer_jit": True})


def _x(shape=(4, 8), seed=0):
    return paddle.to_tensor(
        np.random.default_rng(seed).standard_normal(shape).astype("float32"))


def test_outputs_match_unjitted(jit_forward):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
    x = _x()
    out_j = np.asarray(net(x)._data)
    paddle.set_flags({"FLAGS_eager_layer_jit": False})
    out_e = np.asarray(net(x)._data)
    np.testing.assert_allclose(out_j, out_e, rtol=1e-5, atol=1e-6)


def test_cache_hit_on_second_call(jit_forward):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    x = _x()
    net(x)
    cache = net.__dict__.get("_eager_jit_cache")
    assert cache and len(cache) == 1
    net(x)
    assert len(cache) == 1  # same closure reused
    net.eval()
    net(x)
    assert len(cache) == 2  # training flag is part of the key


def test_gradients_match_unjitted(jit_forward):
    paddle.seed(1)
    net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
    x = _x(seed=2)
    y = paddle.to_tensor(np.ones((4, 4), "float32"))

    paddle.set_flags({"FLAGS_eager_layer_jit": False})
    loss = ((net(x) - y) ** 2).mean()
    loss.backward()
    ref = {n: np.asarray(p.grad._data) for n, p in net.named_parameters()}
    l_ref = float(loss._data)
    for p in net.parameters():
        p.clear_grad()

    paddle.set_flags({"FLAGS_eager_layer_jit": "force"})
    loss2 = ((net(x) - y) ** 2).mean()
    loss2.backward()
    assert abs(float(loss2._data) - l_ref) < 1e-6
    for n, p in net.named_parameters():
        np.testing.assert_allclose(np.asarray(p.grad._data), ref[n],
                                   rtol=1e-5, atol=1e-6, err_msg=n)


def test_dropout_mask_fresh_per_call(jit_forward):
    paddle.seed(3)
    net = nn.Sequential(nn.Linear(8, 32), nn.Dropout(0.5))
    x = _x()
    a = np.asarray(net(x)._data)
    b = np.asarray(net(x)._data)
    assert not np.allclose(a, b), "dropout mask baked into the jitted closure"
    net.eval()
    np.testing.assert_allclose(np.asarray(net(x)._data),
                               np.asarray(net(x)._data))


def test_optimizer_step_trains(jit_forward):
    paddle.seed(4)
    net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 1))
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    rng = np.random.default_rng(5)
    X = rng.standard_normal((64, 8)).astype("float32")
    Y = (X @ rng.standard_normal((8, 1))).astype("float32")
    first = last = None
    for _ in range(60):
        loss = ((net(paddle.to_tensor(X)) - paddle.to_tensor(Y)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        last = float(loss._data)
        first = first if first is not None else last
    assert last < 0.2 * first, (first, last)


def test_moe_layer_exempt(jit_forward):
    from paddle_tpu.distributed.meta_parallel.moe_layer import MoELayer

    paddle.seed(6)
    moe = MoELayer(8, 16, 2, top_k=1, capacity_factor=4.0)
    x = _x((2, 4, 8), seed=7)
    out = moe(x)
    assert moe.l_aux is not None
    float(moe.l_aux._data if hasattr(moe.l_aux, "_data") else moe.l_aux)
    assert "_eager_jit_cache" not in moe.__dict__


def test_gpt_forward_parity(jit_forward):
    from paddle_tpu.models.gpt import GPTForPretraining, gpt_config

    cfg = gpt_config("gpt2-small", vocab_size=64, hidden_size=32, num_layers=2,
                     num_attention_heads=4, max_position_embeddings=32,
                     hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    paddle.seed(8)
    m = GPTForPretraining(cfg)
    ids = paddle.to_tensor(
        np.random.default_rng(9).integers(0, 64, (2, 8)).astype("int32"))
    out_j = np.asarray(m(ids)._data)
    paddle.set_flags({"FLAGS_eager_layer_jit": False})
    out_e = np.asarray(m(ids)._data)
    np.testing.assert_allclose(out_j, out_e, rtol=1e-5, atol=1e-6)


def test_structure_change_invalidates_ancestor_cache(jit_forward):
    """Replacing a nested sublayer (e.g. swapping in a MoE layer) must
    revalidate ANCESTOR layers' cached structure gates — the stale walk
    would jit through the exempt layer and leak its aux tracer."""
    from paddle_tpu.distributed.meta_parallel.moe_layer import MoELayer

    paddle.seed(10)
    net = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 8))
    x = _x((2, 8), seed=11)
    net(x)
    assert net.__dict__.get("_eager_jit_cache")

    net.add_sublayer("1", MoELayer(8, 16, 2, top_k=1, capacity_factor=4.0))
    out = net(x)  # must fall back to eager (MoE exempt)
    # the aux loss must be a concrete value, not a leaked tracer
    float(net[1].l_aux._data if hasattr(net[1].l_aux, "_data")
          else net[1].l_aux)
    assert out.shape[0] == 2


def test_double_grad_through_jitted_layer(jit_forward):
    """paddle.grad(create_graph=True) re-differentiates the cached jitted
    forward (the tape keeps its pure_fn; jax differentiates through jit)."""
    paddle.seed(12)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
    x = _x((4, 4), seed=13)
    x.stop_gradient = False
    out = net(x)
    loss = (out * out).mean()
    (gx,) = paddle.grad(loss, [x], create_graph=True)
    gnorm = (gx * gx).sum()
    gnorm.backward()
    assert net.parameters()[0].grad is not None
    assert float(gnorm._data) > 0
