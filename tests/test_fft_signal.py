"""fft + signal module parity vs numpy (reference test strategy: OpTest-style
numpy-golden comparisons, python/paddle/fluid/tests/unittests/test_fft.py and
test_signal.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle


RTOL = 2e-4
ATOL = 2e-4


def _np(t):
    return np.asarray(t._data)


class TestFFT:
    x_real = np.random.default_rng(0).standard_normal((3, 8, 10)).astype("float32")
    x_cplx = (x_real + 1j * np.roll(x_real, 1, -1)).astype("complex64")

    @pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
    @pytest.mark.parametrize("n,axis", [(None, -1), (6, -1), (12, 1)])
    def test_fft_ifft(self, norm, n, axis):
        got = paddle.fft.fft(paddle.to_tensor(self.x_cplx), n=n, axis=axis, norm=norm)
        np.testing.assert_allclose(
            _np(got), np.fft.fft(self.x_cplx, n=n, axis=axis, norm=norm),
            rtol=RTOL, atol=ATOL)
        got = paddle.fft.ifft(paddle.to_tensor(self.x_cplx), n=n, axis=axis, norm=norm)
        np.testing.assert_allclose(
            _np(got), np.fft.ifft(self.x_cplx, n=n, axis=axis, norm=norm),
            rtol=RTOL, atol=ATOL)

    def test_fft_real_input_promotes(self):
        got = paddle.fft.fft(paddle.to_tensor(self.x_real))
        assert _np(got).dtype == np.complex64
        np.testing.assert_allclose(_np(got), np.fft.fft(self.x_real), rtol=RTOL, atol=ATOL)

    @pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
    def test_rfft_irfft(self, norm):
        got = paddle.fft.rfft(paddle.to_tensor(self.x_real), norm=norm)
        want = np.fft.rfft(self.x_real, norm=norm)
        np.testing.assert_allclose(_np(got), want, rtol=RTOL, atol=ATOL)
        back = paddle.fft.irfft(got, n=10, norm=norm)
        np.testing.assert_allclose(_np(back), self.x_real, rtol=RTOL, atol=ATOL)

    def test_fft2_roundtrip(self):
        got = paddle.fft.fft2(paddle.to_tensor(self.x_cplx))
        np.testing.assert_allclose(_np(got), np.fft.fft2(self.x_cplx), rtol=RTOL, atol=1e-3)
        back = paddle.fft.ifft2(got)
        np.testing.assert_allclose(_np(back), self.x_cplx, rtol=RTOL, atol=ATOL)

    def test_fftn_axes(self):
        got = paddle.fft.fftn(paddle.to_tensor(self.x_cplx), axes=(0, 2))
        np.testing.assert_allclose(
            _np(got), np.fft.fftn(self.x_cplx, axes=(0, 2)), rtol=RTOL, atol=1e-3)

    def test_rfftn_irfftn(self):
        got = paddle.fft.rfftn(paddle.to_tensor(self.x_real))
        np.testing.assert_allclose(_np(got), np.fft.rfftn(self.x_real), rtol=RTOL, atol=1e-3)
        back = paddle.fft.irfftn(got, s=self.x_real.shape)
        np.testing.assert_allclose(_np(back), self.x_real, rtol=RTOL, atol=ATOL)

    def test_hfft_ihfft(self):
        spec = np.fft.rfft(self.x_real).astype("complex64")
        got = paddle.fft.hfft(paddle.to_tensor(spec), n=10)
        np.testing.assert_allclose(_np(got), np.fft.hfft(spec, n=10), rtol=RTOL, atol=1e-3)
        got = paddle.fft.ihfft(paddle.to_tensor(self.x_real))
        np.testing.assert_allclose(_np(got), np.fft.ihfft(self.x_real), rtol=RTOL, atol=ATOL)

    def test_hfft2_matches_composed_numpy(self):
        # hfftn == forward c2c over leading axes then hfft over last axis
        spec = (np.fft.rfft2(self.x_real)).astype("complex64")
        got = paddle.fft.hfft2(paddle.to_tensor(spec), s=(8, 10))
        want = np.fft.hfft(np.fft.fft(spec, axis=-2), n=10, axis=-1)
        np.testing.assert_allclose(_np(got), want, rtol=2e-3, atol=2e-2)

    def test_ihfft2_roundtrip_against_hfft2(self):
        x = self.x_real
        spec = paddle.fft.ihfft2(paddle.to_tensor(x))
        back = paddle.fft.hfft2(spec, s=(8, 10))
        np.testing.assert_allclose(_np(back), x, rtol=2e-3, atol=2e-2)

    def test_fftfreq_shift(self):
        np.testing.assert_allclose(
            _np(paddle.fft.fftfreq(9, d=0.5)), np.fft.fftfreq(9, d=0.5).astype("float32"),
            rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(
            _np(paddle.fft.rfftfreq(9, d=0.5)), np.fft.rfftfreq(9, d=0.5).astype("float32"),
            rtol=RTOL, atol=ATOL)
        x = paddle.to_tensor(self.x_real)
        np.testing.assert_allclose(
            _np(paddle.fft.fftshift(x)), np.fft.fftshift(self.x_real))
        np.testing.assert_allclose(
            _np(paddle.fft.ifftshift(x, axes=(1,))), np.fft.ifftshift(self.x_real, axes=(1,)))

    def test_fft_grad(self):
        # d/dx sum(|fft(x)|^2) = 2*N*x by Parseval; checks the vjp tape path
        x = paddle.to_tensor(self.x_real[0, 0])
        x.stop_gradient = False
        y = paddle.fft.fft(x)
        loss = (y.abs() ** 2).sum()
        loss.backward()
        np.testing.assert_allclose(
            _np(x.grad), 2 * 10 * self.x_real[0, 0], rtol=1e-3, atol=1e-3)


class TestSignal:
    rng = np.random.default_rng(1)

    def _np_frame(self, x, frame_length, hop_length):
        n = 1 + (x.shape[-1] - frame_length) // hop_length
        out = np.stack(
            [x[..., i * hop_length: i * hop_length + frame_length] for i in range(n)],
            axis=-1)
        return out

    def test_frame_last_axis(self):
        x = self.rng.standard_normal((2, 3, 20)).astype("float32")
        got = paddle.signal.frame(paddle.to_tensor(x), frame_length=6, hop_length=3)
        np.testing.assert_allclose(_np(got), self._np_frame(x, 6, 3))

    def test_frame_axis0(self):
        x = self.rng.standard_normal((20, 2)).astype("float32")
        got = paddle.signal.frame(paddle.to_tensor(x), 6, 3, axis=0)
        assert _np(got).shape == (5, 6, 2)
        want = np.stack([x[i * 3: i * 3 + 6] for i in range(5)], axis=0)
        np.testing.assert_allclose(_np(got), want)

    def test_overlap_add_inverts_frame_when_nonoverlapping(self):
        x = self.rng.standard_normal((2, 18)).astype("float32")
        frames = paddle.signal.frame(paddle.to_tensor(x), 6, 6)
        back = paddle.signal.overlap_add(frames, hop_length=6)
        np.testing.assert_allclose(_np(back), x, rtol=1e-6, atol=1e-6)

    def test_overlap_add_sums_overlaps(self):
        x = np.ones((4, 3), dtype="float32")  # frame_length 4, 3 frames
        got = paddle.signal.overlap_add(paddle.to_tensor(x), hop_length=2)
        want = np.zeros(8, dtype="float32")
        for i in range(3):
            want[i * 2: i * 2 + 4] += 1
        np.testing.assert_allclose(_np(got), want)

    def test_overlap_add_axis0(self):
        x = self.rng.standard_normal((3, 4, 2)).astype("float32")  # (n_frames, frame_len, batch)
        got = paddle.signal.overlap_add(paddle.to_tensor(x), hop_length=2, axis=0)
        assert _np(got).shape == (8, 2)
        want = np.zeros((8, 2), dtype="float32")
        for i in range(3):
            want[i * 2: i * 2 + 4] += x[i]
        np.testing.assert_allclose(_np(got), want, rtol=1e-6, atol=1e-6)

    def test_stft_matches_manual(self):
        x = self.rng.standard_normal((2, 64)).astype("float32")
        n_fft, hop = 16, 4
        win = np.hanning(n_fft).astype("float32")
        got = paddle.signal.stft(paddle.to_tensor(x), n_fft, hop_length=hop,
                                 window=paddle.to_tensor(win), center=False)
        # manual: frame then rfft
        frames = self._np_frame(x, n_fft, hop) * win[:, None]
        want = np.fft.rfft(frames, axis=-2)
        np.testing.assert_allclose(_np(got), want, rtol=1e-4, atol=1e-4)
        assert _np(got).shape == (2, n_fft // 2 + 1, 1 + (64 - n_fft) // hop)

    def test_stft_istft_roundtrip(self):
        x = self.rng.standard_normal((3, 128)).astype("float32")
        n_fft, hop = 32, 8
        win = (np.hanning(n_fft) + 0.1).astype("float32")  # NOLA-safe
        spec = paddle.signal.stft(paddle.to_tensor(x), n_fft, hop_length=hop,
                                  window=paddle.to_tensor(win))
        back = paddle.signal.istft(spec, n_fft, hop_length=hop,
                                   window=paddle.to_tensor(win), length=128)
        np.testing.assert_allclose(_np(back), x, rtol=1e-3, atol=1e-3)

    def test_stft_normalized_twosided(self):
        x = self.rng.standard_normal(64).astype("float32")
        spec = paddle.signal.stft(paddle.to_tensor(x), 16, normalized=True,
                                  onesided=False, center=True)
        assert _np(spec).shape[0] == 16
        back = paddle.signal.istft(spec, 16, normalized=True, onesided=False,
                                   length=64)
        np.testing.assert_allclose(_np(back), x, rtol=1e-3, atol=1e-3)

    def test_frame_grad_flows(self):
        x = paddle.to_tensor(self.rng.standard_normal(16).astype("float32"))
        x.stop_gradient = False
        y = paddle.signal.frame(x, 4, 4)
        y.sum().backward()
        np.testing.assert_allclose(_np(x.grad), np.ones(16), rtol=1e-6, atol=1e-6)
