"""Native C++ core, profiler, flags, monitor tests.

Parity targets: reader/lod_tensor_blocking_queue.h (queue),
memory/allocation/auto_growth_best_fit_allocator.cc (pool),
memory/allocation/mmap_allocator.cc (shm ring), platform/profiler.h
(RecordEvent), platform/flags.cc + monitor.h (flags/stats).
"""
import multiprocessing as mp
import os
import threading
import time

import numpy as np
import pytest

from paddle_tpu import core
from paddle_tpu.framework import monitor
from paddle_tpu.framework.flags import flag, get_flags, set_flags


class TestBlockingQueue:
    def test_fifo_roundtrip(self):
        q = core.BlockingQueue(4)
        for i in range(4):
            assert q.push(bytes([i]) * (i + 1))
        assert q.size() == 4
        for i in range(4):
            assert q.pop() == bytes([i]) * (i + 1)

    def test_bounded_blocks_then_timeout(self):
        q = core.BlockingQueue(1)
        q.push(b"a")
        t0 = time.time()
        assert q.push(b"b", timeout_ms=80) is False
        assert time.time() - t0 >= 0.05

    def test_pop_timeout_returns_none(self):
        q = core.BlockingQueue(1)
        assert q.pop(timeout_ms=50) is None

    def test_close_drains_then_eof(self):
        q = core.BlockingQueue(4)
        q.push(b"x")
        q.close()
        assert q.pop() == b"x"
        with pytest.raises(EOFError):
            q.pop(timeout_ms=100)

    def test_producer_consumer_threads(self):
        q = core.BlockingQueue(2)
        got = []

        def consumer():
            while True:
                try:
                    item = q.pop(timeout_ms=2000)
                except EOFError:
                    return
                if item is not None:
                    got.append(item)

        t = threading.Thread(target=consumer)
        t.start()
        for i in range(50):
            q.push(str(i).encode())
        q.close()
        t.join(timeout=5)
        assert [int(x) for x in got] == list(range(50))


class TestPinnedPool:
    def test_alloc_reuse_and_coalesce(self):
        pool = core.PinnedPool(chunk_size=1 << 20)
        a = pool.alloc_array((256, 256), np.float32)
        b = pool.alloc_array((128,), np.int64)
        a[:] = 2.5
        b[:] = 7
        assert float(a.sum()) == 2.5 * 256 * 256
        assert int(b.sum()) == 7 * 128
        if core.native_available():
            s = pool.stats()
            assert s["in_use"] >= 256 * 256 * 4 + 128 * 8
            assert pool.free_array(a) and pool.free_array(b)
            # all freed blocks coalesce back into one chunk-sized block
            s2 = pool.stats()
            assert s2["in_use"] == 0
            assert s2["free_blocks"] == 1

    def test_auto_growth_beyond_chunk(self):
        pool = core.PinnedPool(chunk_size=4096)
        big = pool.alloc_array((1 << 16,), np.uint8)  # 64 KiB > chunk
        big[:] = 1
        assert int(big.sum()) == 1 << 16


@pytest.mark.skipif(not core.native_available(), reason="needs native core")
class TestShmRing:
    def test_same_process_roundtrip(self):
        r = core.ShmRing(f"/pt_t1_{os.getpid()}", slot_size=4096, nslots=2)
        r.write(b"abc")
        r.write(b"defg")
        assert r.count() == 2
        assert r.read() == b"abc"
        assert r.read() == b"defg"
        r.destroy()

    def test_cross_process(self):
        name = f"/pt_t2_{os.getpid()}"
        r = core.ShmRing(name, slot_size=1 << 16, nslots=4)

        def child(n):
            from paddle_tpu.core import ShmRing

            w = ShmRing(n, create=False)
            for i in range(20):
                w.write(np.full(100, i, np.int32).tobytes())
            w._h = None

        p = mp.get_context("fork").Process(target=child, args=(name,))
        p.start()
        vals = []
        for _ in range(20):
            data = r.read(timeout_ms=5000)
            assert data is not None
            vals.append(int(np.frombuffer(data, np.int32)[0]))
        p.join(timeout=5)
        r.destroy()
        assert vals == list(range(20))

    def test_oversize_rejected(self):
        r = core.ShmRing(f"/pt_t3_{os.getpid()}", slot_size=64, nslots=2)
        with pytest.raises(ValueError):
            r.write(b"z" * 100)
        r.destroy()


class TestProfiler:
    def test_record_and_summary(self):
        from paddle_tpu import profiler

        profiler.start_profiler("CPU")
        with profiler.RecordEvent("outer"):
            time.sleep(0.01)
            with profiler.RecordEvent("inner"):
                time.sleep(0.005)
        with profiler.RecordEvent("outer"):
            time.sleep(0.002)
        table = profiler.stop_profiler(print_table=False)
        rows = {r["name"]: r for r in table}
        assert rows["outer"]["calls"] == 2
        assert rows["inner"]["calls"] == 1
        assert rows["outer"]["total_ms"] >= 10.0
        assert rows["inner"]["total_ms"] >= 4.0

    def test_chrome_trace_export(self, tmp_path):
        import json

        from paddle_tpu import profiler

        profiler.start_profiler("CPU")
        with profiler.RecordEvent("step"):
            time.sleep(0.001)
        path = str(tmp_path / "trace.json")
        profiler.stop_profiler(profile_path=path, print_table=False)
        with open(path) as f:
            trace = json.load(f)
        names = [e["name"] for e in trace["traceEvents"]]
        assert "step" in names

    def test_disabled_is_noop(self):
        from paddle_tpu import profiler

        profiler.reset()
        with profiler.RecordEvent("ignored"):
            pass
        assert all(r["name"] != "ignored" for r in profiler.summary())

    def test_decorator(self):
        from paddle_tpu import profiler

        @profiler.record_event("fn_span")
        def f(x):
            return x + 1

        profiler.start_profiler("CPU")
        assert f(1) == 2
        table = profiler.stop_profiler(print_table=False)
        assert any(r["name"] == "fn_span" for r in table)


class TestFlagsMonitor:
    def test_set_get_roundtrip(self):
        set_flags({"FLAGS_benchmark": True})
        assert get_flags("FLAGS_benchmark")["FLAGS_benchmark"] is True
        set_flags({"FLAGS_benchmark": "false"})
        assert flag("FLAGS_benchmark") is False

    def test_unknown_flag_raises(self):
        with pytest.raises(ValueError):
            set_flags({"FLAGS_does_not_exist": 1})

    def test_get_all(self):
        allf = get_flags()
        assert "FLAGS_check_nan_inf" in allf
        assert "FLAGS_allocator_strategy" in allf

    def test_top_level_api(self):
        import paddle_tpu as paddle

        paddle.set_flags({"FLAGS_eager_delete_tensor_gb": 1.5})
        assert paddle.get_flags("FLAGS_eager_delete_tensor_gb")[
            "FLAGS_eager_delete_tensor_gb"] == 1.5

    def test_check_nan_inf_toggles_debug_nans(self):
        import jax

        set_flags({"FLAGS_check_nan_inf": True})
        assert jax.config.jax_debug_nans
        set_flags({"FLAGS_check_nan_inf": False})
        assert not jax.config.jax_debug_nans

    def test_monitor_stats(self):
        monitor.stat_reset()
        monitor.stat_add("STAT_host_batches", 3)
        monitor.stat_add("STAT_host_batches", 2)
        monitor.stat_set("STAT_steps", 10)
        assert monitor.stat_get("STAT_host_batches") == 5
        assert monitor.all_stats()["STAT_steps"] == 10


class TestDataLoaderShm:
    def test_multiprocess_ring_loader(self):
        from paddle_tpu.io import DataLoader
        from paddle_tpu.io.dataset import Dataset

        class DS(Dataset):
            def __len__(self):
                return 32

            def __getitem__(self, i):
                return np.full((8, 8), i, np.float32), np.int64(i)

        dl = DataLoader(DS(), batch_size=4, num_workers=2, shuffle=False,
                        device_prefetch=False, use_shared_memory=True)
        seen = []
        for x, y in dl:
            assert tuple(np.asarray(x.numpy()).shape) == (4, 8, 8)
            seen.extend(np.asarray(y.numpy()).tolist())
        assert seen == list(range(32))
